"""CDC ingestion: debezium/canal/maxwell parsing + schema-evolving sink.

reference: paimon-flink-cdc format parsers + CdcRecordStoreMultiWrite.
"""

import os

import pytest

from paimon_tpu.cdc import (
    CdcSinkWriter, parse_canal, parse_debezium, parse_maxwell,
)
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, RowKind


def test_parse_debezium():
    assert parse_debezium({"op": "c", "after": {"id": 1}}) == \
        [({"id": 1}, RowKind.INSERT)]
    assert parse_debezium({"op": "d", "before": {"id": 1}}) == \
        [({"id": 1}, RowKind.DELETE)]
    u = parse_debezium({"op": "u", "before": {"id": 1, "v": 1},
                        "after": {"id": 1, "v": 2}})
    assert u == [({"id": 1, "v": 1}, RowKind.UPDATE_BEFORE),
                 ({"id": 1, "v": 2}, RowKind.UPDATE_AFTER)]
    # payload envelope unwraps
    assert parse_debezium({"payload": {"op": "r",
                                       "after": {"id": 9}}}) == \
        [({"id": 9}, RowKind.INSERT)]


def test_parse_canal_and_maxwell():
    c = parse_canal({"type": "UPDATE", "data": [{"id": 1, "v": 2}],
                     "old": [{"v": 1}]})
    assert c == [({"id": 1, "v": 1}, RowKind.UPDATE_BEFORE),
                 ({"id": 1, "v": 2}, RowKind.UPDATE_AFTER)]
    m = parse_maxwell({"type": "update", "data": {"id": 1, "v": 2},
                       "old": {"v": 1}})
    assert m == [({"id": 1, "v": 1}, RowKind.UPDATE_BEFORE),
                 ({"id": 1, "v": 2}, RowKind.UPDATE_AFTER)]


def _make(tmp_warehouse):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true"})
              .build())
    return FileStoreTable.create(os.path.join(tmp_warehouse, "t"), schema)


def test_cdc_sink_end_to_end(tmp_warehouse):
    table = _make(tmp_warehouse)
    sink = CdcSinkWriter(table, format="debezium")
    sink.write_events([
        {"op": "c", "after": {"id": 1, "v": 1.0}},
        {"op": "c", "after": {"id": 2, "v": 2.0}},
    ])
    sink.commit(1)
    sink.write_events([
        {"op": "u", "before": {"id": 1, "v": 1.0},
         "after": {"id": 1, "v": 10.0}},
        {"op": "d", "before": {"id": 2, "v": 2.0}},
    ])
    sink.commit(2)
    sink.close()
    out = FileStoreTable.load(table.path).to_arrow().to_pylist()
    assert out == [{"id": 1, "v": 10.0}]


def test_cdc_schema_evolution(tmp_warehouse):
    table = _make(tmp_warehouse)
    sink = CdcSinkWriter(table, format="debezium")
    sink.write_events([{"op": "c", "after": {"id": 1, "v": 1.0}}])
    sink.commit(1)
    # upstream adds a column mid-stream
    sink.write_events([{"op": "c", "after": {"id": 2, "v": 2.0,
                                             "city": "berlin"}}])
    sink.commit(2)
    sink.close()
    t = FileStoreTable.load(table.path)
    rows = sorted(t.to_arrow().to_pylist(), key=lambda r: r["id"])
    assert rows == [{"id": 1, "v": 1.0, "city": None},
                    {"id": 2, "v": 2.0, "city": "berlin"}]
    assert [f.name for f in t.schema.fields][-1] == "city"


def test_cdc_exactly_once_replay(tmp_warehouse):
    table = _make(tmp_warehouse)
    sink = CdcSinkWriter(table, format="maxwell", commit_user="job-x")
    sink.write_events([{"type": "insert", "data": {"id": 1, "v": 1.0}}])
    assert sink.commit(7) is not None
    # replay of the same checkpoint id commits nothing
    sink2 = CdcSinkWriter(FileStoreTable.load(table.path),
                          format="maxwell", commit_user="job-x")
    sink2.write_events([{"type": "insert", "data": {"id": 1, "v": 1.0}}])
    assert sink2.commit(7) is None
    assert FileStoreTable.load(table.path).to_arrow().num_rows == 1


def test_cdc_schema_evolution_mid_checkpoint_keeps_buffered_rows(
        tmp_warehouse):
    """Rows written BEFORE an in-checkpoint schema evolution must commit
    (the evolved writer cannot drop the old writer's buffers)."""
    table = _make(tmp_warehouse)
    sink = CdcSinkWriter(table, format="debezium")
    sink.write_events([{"op": "c", "after": {"id": 1, "v": 1.0}}])
    # same checkpoint: new column arrives before any commit
    sink.write_events([{"op": "c", "after": {"id": 2, "v": 2.0,
                                             "extra": 7}}])
    sink.commit(1)
    sink.close()
    rows = sorted(FileStoreTable.load(table.path).to_arrow().to_pylist(),
                  key=lambda r: r["id"])
    assert [r["id"] for r in rows] == [1, 2]
    assert rows[1]["extra"] == 7
