"""CDC ingestion: debezium/canal/maxwell parsing + schema-evolving sink.

reference: paimon-flink-cdc format parsers + CdcRecordStoreMultiWrite.
"""

import os

import pytest

from paimon_tpu.cdc import (
    CdcSinkWriter, parse_canal, parse_debezium, parse_maxwell,
)
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, RowKind, VarCharType


def test_parse_debezium():
    assert parse_debezium({"op": "c", "after": {"id": 1}}) == \
        [({"id": 1}, RowKind.INSERT)]
    assert parse_debezium({"op": "d", "before": {"id": 1}}) == \
        [({"id": 1}, RowKind.DELETE)]
    u = parse_debezium({"op": "u", "before": {"id": 1, "v": 1},
                        "after": {"id": 1, "v": 2}})
    assert u == [({"id": 1, "v": 1}, RowKind.UPDATE_BEFORE),
                 ({"id": 1, "v": 2}, RowKind.UPDATE_AFTER)]
    # payload envelope unwraps
    assert parse_debezium({"payload": {"op": "r",
                                       "after": {"id": 9}}}) == \
        [({"id": 9}, RowKind.INSERT)]


def test_parse_canal_and_maxwell():
    c = parse_canal({"type": "UPDATE", "data": [{"id": 1, "v": 2}],
                     "old": [{"v": 1}]})
    assert c == [({"id": 1, "v": 1}, RowKind.UPDATE_BEFORE),
                 ({"id": 1, "v": 2}, RowKind.UPDATE_AFTER)]
    m = parse_maxwell({"type": "update", "data": {"id": 1, "v": 2},
                       "old": {"v": 1}})
    assert m == [({"id": 1, "v": 1}, RowKind.UPDATE_BEFORE),
                 ({"id": 1, "v": 2}, RowKind.UPDATE_AFTER)]


def _make(tmp_warehouse):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true"})
              .build())
    return FileStoreTable.create(os.path.join(tmp_warehouse, "t"), schema)


def test_cdc_sink_end_to_end(tmp_warehouse):
    table = _make(tmp_warehouse)
    sink = CdcSinkWriter(table, format="debezium")
    sink.write_events([
        {"op": "c", "after": {"id": 1, "v": 1.0}},
        {"op": "c", "after": {"id": 2, "v": 2.0}},
    ])
    sink.commit(1)
    sink.write_events([
        {"op": "u", "before": {"id": 1, "v": 1.0},
         "after": {"id": 1, "v": 10.0}},
        {"op": "d", "before": {"id": 2, "v": 2.0}},
    ])
    sink.commit(2)
    sink.close()
    out = FileStoreTable.load(table.path).to_arrow().to_pylist()
    assert out == [{"id": 1, "v": 10.0}]


def test_cdc_schema_evolution(tmp_warehouse):
    table = _make(tmp_warehouse)
    sink = CdcSinkWriter(table, format="debezium")
    sink.write_events([{"op": "c", "after": {"id": 1, "v": 1.0}}])
    sink.commit(1)
    # upstream adds a column mid-stream
    sink.write_events([{"op": "c", "after": {"id": 2, "v": 2.0,
                                             "city": "berlin"}}])
    sink.commit(2)
    sink.close()
    t = FileStoreTable.load(table.path)
    rows = sorted(t.to_arrow().to_pylist(), key=lambda r: r["id"])
    assert rows == [{"id": 1, "v": 1.0, "city": None},
                    {"id": 2, "v": 2.0, "city": "berlin"}]
    assert [f.name for f in t.schema.fields][-1] == "city"


def test_cdc_exactly_once_replay(tmp_warehouse):
    table = _make(tmp_warehouse)
    sink = CdcSinkWriter(table, format="maxwell", commit_user="job-x")
    sink.write_events([{"type": "insert", "data": {"id": 1, "v": 1.0}}])
    assert sink.commit(7) is not None
    # replay of the same checkpoint id commits nothing
    sink2 = CdcSinkWriter(FileStoreTable.load(table.path),
                          format="maxwell", commit_user="job-x")
    sink2.write_events([{"type": "insert", "data": {"id": 1, "v": 1.0}}])
    assert sink2.commit(7) is None
    assert FileStoreTable.load(table.path).to_arrow().num_rows == 1


def test_cdc_schema_evolution_mid_checkpoint_keeps_buffered_rows(
        tmp_warehouse):
    """Rows written BEFORE an in-checkpoint schema evolution must commit
    (the evolved writer cannot drop the old writer's buffers)."""
    table = _make(tmp_warehouse)
    sink = CdcSinkWriter(table, format="debezium")
    sink.write_events([{"op": "c", "after": {"id": 1, "v": 1.0}}])
    # same checkpoint: new column arrives before any commit
    sink.write_events([{"op": "c", "after": {"id": 2, "v": 2.0,
                                             "extra": 7}}])
    sink.commit(1)
    sink.close()
    rows = sorted(FileStoreTable.load(table.path).to_arrow().to_pylist(),
                  key=lambda r: r["id"])
    assert [r["id"] for r in rows] == [1, 2]
    assert rows[1]["extra"] == 7


def test_cdc_commit_crash_after_cas_does_not_redeliver(
        tmp_warehouse, monkeypatch):
    """Crash BETWEEN the snapshot CAS and the commit ack: the messages
    are restored keyed by the attempted identifier, and a later
    checkpoint must detect the identifier actually landed and DROP them
    instead of re-delivering the committed rows (stream-daemon replay
    keyed by the checkpointed offset rides exactly this)."""
    from paimon_tpu.table.table import TableCommit

    table = _make(tmp_warehouse)
    sink = CdcSinkWriter(table, format="debezium", commit_user="job-y")

    real_commit = TableCommit.commit
    state = {"bombs": 1}

    def exploding_commit(self, messages, commit_identifier=..., **kw):
        sid = real_commit(self, messages,
                          commit_identifier=commit_identifier, **kw)
        if state["bombs"] > 0:
            state["bombs"] -= 1
            raise RuntimeError("injected crash after CAS, before ack")
        return sid

    monkeypatch.setattr(TableCommit, "commit", exploding_commit)
    sink.write_events([{"op": "c", "after": {"id": 1, "v": 1.0}}])
    with pytest.raises(RuntimeError, match="after CAS"):
        sink.commit(1)
    # the snapshot DID land; the daemon replays with the next identifier
    sink.write_events([{"op": "c", "after": {"id": 2, "v": 2.0}}])
    sink.commit(2)
    sink.close()
    rows = sorted(FileStoreTable.load(table.path).to_arrow().to_pylist(),
                  key=lambda r: r["id"])
    assert rows == [{"id": 1, "v": 1.0}, {"id": 2, "v": 2.0}]
    # checkpoint 1's rows were committed exactly once: the two
    # snapshots' deltas hold one row each (no re-delivery of id=1)
    snaps = list(FileStoreTable.load(table.path)
                 .snapshot_manager.snapshots())
    assert [s.delta_record_count for s in snaps] == [1, 1]


def test_cdc_commit_failure_before_cas_retries_same_checkpoint(
        tmp_warehouse, monkeypatch):
    """Commit raises BEFORE the CAS lands: retrying the same identifier
    must deliver the restored messages exactly once."""
    from paimon_tpu.table.table import TableCommit

    table = _make(tmp_warehouse)
    sink = CdcSinkWriter(table, format="debezium", commit_user="job-z")

    real_commit = TableCommit.commit
    state = {"bombs": 1}

    def failing_commit(self, messages, commit_identifier=..., **kw):
        if state["bombs"] > 0:
            state["bombs"] -= 1
            raise RuntimeError("injected failure before CAS")
        return real_commit(self, messages,
                           commit_identifier=commit_identifier, **kw)

    monkeypatch.setattr(TableCommit, "commit", failing_commit)
    sink.write_events([{"op": "c", "after": {"id": 1, "v": 1.0}}])
    with pytest.raises(RuntimeError, match="before CAS"):
        sink.commit(1)
    assert sink.commit(1) is not None        # retry converges
    sink.close()
    assert FileStoreTable.load(table.path).to_arrow().to_pylist() == \
        [{"id": 1, "v": 1.0}]


def test_cdc_commit_properties_land_in_snapshot(tmp_warehouse):
    table = _make(tmp_warehouse)
    sink = CdcSinkWriter(table, format="debezium")
    sink.write_events([{"op": "c", "after": {"id": 1, "v": 1.0}}])
    sink.commit(3, properties={"stream.source.offset": "41"})
    sink.close()
    snap = FileStoreTable.load(table.path).latest_snapshot()
    assert snap.properties == {"stream.source.offset": "41"}
    assert snap.commit_identifier == 3


# -- computed columns / widening / database sync ------------------------------

def test_computed_columns_partition_from_timestamp(tmp_warehouse):
    from paimon_tpu.cdc import CdcSinkWriter
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("ts", VarCharType.string_type())
              .column("dt", VarCharType.string_type())
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "cc"), schema)
    w = CdcSinkWriter(table, format="debezium",
                      computed_columns=["dt=date_format(ts, yyyy-MM-dd)"])
    w.write_events([{"op": "c", "after": {"id": 1,
                                          "ts": "2024-03-05 10:00:00"}}])
    w.commit(1)
    row = w.table.to_arrow().to_pylist()[0]
    assert row["dt"] == "2024-03-05"


def test_null_first_column_defers_then_infers(tmp_warehouse):
    from paimon_tpu.cdc import CdcSinkWriter
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "nf"), schema)
    w = CdcSinkWriter(table, format="debezium")
    # first batch: new column arrives as all-null -> no ADD COLUMN yet
    w.write_events([{"op": "c", "after": {"id": 1, "extra": None}}])
    assert "extra" not in [f.name for f in w.table.schema.fields]
    # later batch: ints -> created as BIGINT, not STRING
    w.write_events([{"op": "c", "after": {"id": 2, "extra": 42}}])
    w.commit(1)
    f = [f for f in w.table.schema.fields if f.name == "extra"][0]
    assert f.type.root == "BIGINT"
    rows = sorted(w.table.to_arrow().to_pylist(), key=lambda r: r["id"])
    assert rows[1]["extra"] == 42 and rows[0]["extra"] is None


def test_type_widens_on_drift(tmp_warehouse):
    from paimon_tpu.cdc import CdcSinkWriter
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "wd"), schema)
    w = CdcSinkWriter(table, format="debezium")
    w.write_events([{"op": "c", "after": {"id": 1, "x": 10}}])     # BIGINT
    w.write_events([{"op": "c", "after": {"id": 2, "x": 1.5}}])    # widen
    w.commit(1)
    f = [f for f in w.table.schema.fields if f.name == "x"][0]
    assert f.type.root == "DOUBLE"
    rows = sorted(w.table.to_arrow().to_pylist(), key=lambda r: r["id"])
    assert rows == [{"id": 1, "x": 10.0}, {"id": 2, "x": 1.5}]


def test_database_sync_multi_table(tmp_warehouse):
    from paimon_tpu.catalog import create_catalog
    from paimon_tpu.cdc import CdcDatabaseSync

    catalog = create_catalog({"warehouse": os.path.join(tmp_warehouse,
                                                        "wh")})
    sync = CdcDatabaseSync(
        catalog, "appdb", format="maxwell",
        excluding_tables="tmp_.*",
        primary_keys={"users": ["uid"], "orders": ["oid"]})
    sync.write_events([
        {"database": "appdb", "table": "users", "type": "insert",
         "data": {"uid": 1, "name": "ada"},
         "primary_key_columns": ["uid"]},
        {"database": "appdb", "table": "orders", "type": "insert",
         "data": {"oid": 100, "uid": 1, "amt": 9.5},
         "primary_key_columns": ["oid"]},
        {"database": "appdb", "table": "tmp_scratch", "type": "insert",
         "data": {"k": 1}},
    ])
    sync.write_events([
        {"database": "appdb", "table": "users", "type": "update",
         "data": {"uid": 1, "name": "ada l."},
         "old": {"name": "ada"}},
    ])
    sync.commit(1)
    assert sync.tables() == ["orders", "users"]
    users = catalog.get_table("appdb.users").to_arrow().to_pylist()
    assert users == [{"uid": 1, "name": "ada l."}]
    orders = catalog.get_table("appdb.orders").to_arrow().to_pylist()
    assert orders == [{"oid": 100, "uid": 1, "amt": 9.5}]
    assert not catalog.table_exists("appdb.tmp_scratch")
    sync.close()


def test_widen_int_to_bigint_and_timestamp_conflict(tmp_warehouse):
    from paimon_tpu.cdc import CdcSinkWriter
    from paimon_tpu.types import IntType
    import datetime
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("x", IntType())
              .column("y", DoubleType())
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "wl"), schema)
    w = CdcSinkWriter(table, format="debezium")
    w.write_events([{"op": "c", "after": {"id": 1, "x": 1 << 40,
                                          "y": 0.5}}])
    # INT widens to BIGINT; DOUBLE meeting datetime falls back to STRING
    w.write_events([{"op": "c", "after": {
        "id": 2, "x": 1, "y": datetime.datetime(2024, 1, 1)}}])
    w.commit(1)
    by = {f.name: f.type.root for f in w.table.schema.fields}
    assert by["x"] == "BIGINT"
    assert by["y"] == "VARCHAR"
    rows = sorted(w.table.to_arrow().to_pylist(), key=lambda r: r["id"])
    assert rows[0]["x"] == 1 << 40


def test_database_sync_filters_foreign_database(tmp_warehouse):
    from paimon_tpu.catalog import create_catalog
    from paimon_tpu.cdc import CdcDatabaseSync
    catalog = create_catalog({"warehouse": os.path.join(tmp_warehouse,
                                                        "wh2")})
    sync = CdcDatabaseSync(catalog, "appdb", format="maxwell",
                           primary_keys={"users": ["uid"]})
    sync.write_events([
        {"database": "appdb", "table": "users", "type": "insert",
         "data": {"uid": 1, "name": "a"}},
        {"database": "otherdb", "table": "users", "type": "insert",
         "data": {"uid": 99, "name": "evil"}},
    ])
    sync.commit(1)
    users = catalog.get_table("appdb.users").to_arrow().to_pylist()
    assert users == [{"uid": 1, "name": "a"}]


class TestNewFormats:
    """ogg / dms / aliyun parsers (reference
    paimon-flink-cdc/.../format/{ogg,dms,aliyun})."""

    def test_ogg(self):
        from paimon_tpu.cdc.formats import parse_ogg
        from paimon_tpu.types import RowKind
        assert parse_ogg({"op_type": "I",
                          "after": {"id": 1}}) == \
            [({"id": 1}, RowKind.INSERT)]
        assert parse_ogg({"op_type": "U", "before": {"id": 1, "v": 1},
                          "after": {"id": 1, "v": 2}}) == [
            ({"id": 1, "v": 1}, RowKind.UPDATE_BEFORE),
            ({"id": 1, "v": 2}, RowKind.UPDATE_AFTER)]
        assert parse_ogg({"op_type": "D", "before": {"id": 1}}) == \
            [({"id": 1}, RowKind.DELETE)]

    def test_dms(self):
        from paimon_tpu.cdc.formats import parse_dms
        from paimon_tpu.types import RowKind
        meta = {"record-type": "data"}
        assert parse_dms({"data": {"id": 1},
                          "metadata": dict(meta, operation="load")}) == \
            [({"id": 1}, RowKind.INSERT)]
        # update: pre-image in BI_-prefixed columns
        got = parse_dms({
            "data": {"id": 1, "v": 2, "BI_v": 1},
            "metadata": dict(meta, operation="update")})
        assert got == [({"id": 1, "v": 1}, RowKind.UPDATE_BEFORE),
                       ({"id": 1, "v": 2}, RowKind.UPDATE_AFTER)]
        assert parse_dms({"data": {"id": 1}, "metadata": dict(
            meta, operation="delete")}) == \
            [({"id": 1}, RowKind.DELETE)]
        # control records are skipped
        assert parse_dms({"data": {}, "metadata": {
            "record-type": "control", "operation": "insert"}}) == []

    def test_aliyun(self):
        from paimon_tpu.cdc.formats import parse_aliyun
        from paimon_tpu.types import RowKind
        assert parse_aliyun({"op": "INSERT", "payload": {
            "after": {"dataColumn": {"id": 1}}}}) == \
            [({"id": 1}, RowKind.INSERT)]
        # updates arrive as separate -U/+U events
        assert parse_aliyun({"op": "UPDATE_BEFORE", "payload": {
            "before": {"dataColumn": {"id": 1, "v": 1}}}}) == \
            [({"id": 1, "v": 1}, RowKind.UPDATE_BEFORE)]
        assert parse_aliyun({"op": "UPDATE_AFTER", "payload": {
            "after": {"dataColumn": {"id": 1, "v": 2}}}}) == \
            [({"id": 1, "v": 2}, RowKind.UPDATE_AFTER)]
        assert parse_aliyun({"ddl": True, "op": "INSERT"}) == []

    def test_ogg_sink_end_to_end(self, tmp_path):
        from paimon_tpu.cdc.sink import CdcSinkWriter
        from paimon_tpu.schema import Schema
        from paimon_tpu.table import FileStoreTable
        from paimon_tpu.types import BigIntType, VarCharType
        schema = (Schema.builder()
                  .column("id", BigIntType(False))
                  .column("name", VarCharType.string_type())
                  .primary_key("id")
                  .options({"bucket": "1"})
                  .build())
        t = FileStoreTable.create(str(tmp_path / "t"), schema)
        w = CdcSinkWriter(t, format="ogg")
        w.write_events([
            {"op_type": "I", "after": {"id": 1, "name": "a"}},
            {"op_type": "U", "before": {"id": 1, "name": "a"},
             "after": {"id": 1, "name": "b"}},
            {"op_type": "I", "after": {"id": 2, "name": "c"}},
            {"op_type": "D", "before": {"id": 2}},
        ])
        w.commit(1)
        w.close()
        got = t.to_arrow().to_pylist()
        assert got == [{"id": 1, "name": "b"}]

    def test_aliyun_requires_data_column(self):
        from paimon_tpu.cdc.formats import parse_aliyun
        # metadata-only payload must NOT leak into the row
        assert parse_aliyun({"op": "INSERT", "payload": {
            "after": {"columnTypes": {"id": "bigint"}}}}) == []
        assert parse_aliyun({"op": "DELETE", "payload": {}}) == []
