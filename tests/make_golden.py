"""Generate the committed golden wire-format fixture.

Run ONCE (python -m tests.make_golden) to freeze a tiny warehouse —
snapshot JSON, schema JSON, manifest avro bytes, data files, deletion
vectors, Iceberg metadata — under tests/fixtures/golden_v1/.  The
fixture bytes are committed; tests/test_golden.py then asserts forever
that today's code still reads them and that re-serialization is stable,
so the on-disk format can never silently drift (role of reference
JavaPyE2ETest.java: cross-version/cross-impl read compatibility).
"""

import json
import os
import shutil

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "golden_v1")


def build(root: str) -> dict:
    """Create the fixture warehouse at `root`; returns expected
    contents for the sidecar JSON."""
    import pyarrow as pa

    from paimon_tpu import predicate as P
    from paimon_tpu.schema import Schema
    from paimon_tpu.table import FileStoreTable
    from paimon_tpu.types import (
        BigIntType, DoubleType, IntType, VarCharType,
    )

    path = os.path.join(root, "golden_pk")
    schema = (Schema.builder()
              .column("pt", IntType(False))
              .column("id", BigIntType(False))
              .column("name", VarCharType.string_type())
              .column("score", DoubleType())
              .partition_keys("pt")
              .primary_key("pt", "id")
              .options({"bucket": "2", "write-only": "true",
                        "file-index.bloom-filter.columns": "id",
                        "changelog-producer": "input"})
              .build())
    table = FileStoreTable.create(path, schema)

    def commit(rows, kinds=None):
        wb = table.new_batch_write_builder()
        w = wb.new_write()
        w.write_dicts(rows, row_kinds=kinds)
        sid = wb.new_commit().commit(w.prepare_commit())
        w.close()
        return sid

    commit([{"pt": p, "id": i, "name": f"n{p}-{i}",
             "score": p * 10.0 + i}
            for p in (0, 1) for i in range(5)])
    commit([{"pt": 0, "id": 2, "name": "updated", "score": -2.0}])
    from paimon_tpu.types import RowKind
    commit([{"pt": 1, "id": 4, "name": "x", "score": 0.0}],
           kinds=[RowKind.DELETE])
    table.compact(full=True)
    table.create_tag("golden-tag")
    table.sync_iceberg()

    expected_rows = sorted(table.to_arrow().to_pylist(),
                           key=lambda r: (r["pt"], r["id"]))

    # append table with row tracking + DVs for the append wire surface
    apath = os.path.join(root, "golden_append")
    aschema = (Schema.builder()
               .column("id", BigIntType(False))
               .column("v", DoubleType())
               .options({"bucket": "-1",
                         "row-tracking.enabled": "true"})
               .build())
    at = FileStoreTable.create(apath, aschema)
    wb = at.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts([{"id": i, "v": float(i)} for i in range(8)])
    wb.new_commit().commit(w.prepare_commit())
    w.close()
    at.delete_where(P.in_("id", [1, 6]))

    expected_append = sorted(at.to_arrow(with_row_ids=True).to_pylist(),
                             key=lambda r: r["id"])
    return {"pk_rows": expected_rows, "append_rows": expected_append}


def main():
    import tempfile

    if os.path.exists(FIXTURE):
        raise SystemExit(f"{FIXTURE} already exists; golden fixtures "
                         f"are append-only — create golden_v2 instead")
    with tempfile.TemporaryDirectory() as tmp:
        expected = build(tmp)
        os.makedirs(os.path.dirname(FIXTURE), exist_ok=True)
        shutil.copytree(tmp, FIXTURE)
    with open(os.path.join(FIXTURE, "expected.json"), "w") as f:
        json.dump(expected, f, indent=1, sort_keys=True)
    n = sum(len(fs) for _, _, fs in os.walk(FIXTURE))
    print(f"golden fixture written: {FIXTURE} ({n} files)")


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    main()
