"""Whole-program analysis plane: engine, model, and rule fixtures.

Each new rule gets known-BAD fixture packages that must produce
exactly the expected finding and known-GOOD ones that must produce
none; the suppression machinery (reasoned markers, stale markers,
reasonless markers) is exercised directly; `paimon lint --json`'s
output shape is pinned for external CI; and the production tree runs
the FULL catalog with zero unsuppressed findings — the tier-1
acceptance gate.

Regression notes for the violations the new rules surfaced (fixed in
the same PR that shipped the rules) live in
test_fixed_violations_stay_fixed below.
"""

import json
import textwrap

import pytest

from paimon_tpu.analysis import run_package


def make_pkg(tmp_path, files):
    """A throwaway package the model can parse: rule scoping matches
    on package-relative paths, so fixtures mirror the real layout
    (service/..., parallel/...)."""
    pkg = tmp_path / "fixturepkg"
    for rel, src in files.items():
        p = pkg / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    init = pkg / "__init__.py"
    if not init.exists():
        init.write_text("")
    return str(pkg)


def lint(tmp_path, files, rules):
    return run_package(make_pkg(tmp_path, files), rule_ids=rules)


# -- lock-order --------------------------------------------------------------

_LOCK_CYCLE = """
    import threading

    ALPHA_LOCK = threading.Lock()
    BETA_LOCK = threading.Lock()

    def forward():
        with ALPHA_LOCK:
            take_beta()

    def take_beta():
        with BETA_LOCK:
            pass

    def backward():
        with BETA_LOCK:
            take_alpha()

    def take_alpha():
        with ALPHA_LOCK:
            pass
"""


def test_lock_order_two_lock_cycle(tmp_path):
    """The classic inversion: forward() holds ALPHA and takes BETA
    through a callee, backward() holds BETA and takes ALPHA — a cycle
    only an inter-procedural view can see."""
    rep = lint(tmp_path, {"service/locks.py": _LOCK_CYCLE},
               ["lock-order"])
    findings = rep.unsuppressed_by_rule("lock-order")
    assert len(findings) == 1
    assert "cycle" in findings[0].message
    assert "ALPHA_LOCK" in findings[0].message
    assert "BETA_LOCK" in findings[0].message


def test_lock_order_consistent_order_is_clean(tmp_path):
    """Same locks, same nesting, but ONE global order — no cycle, no
    finding."""
    rep = lint(tmp_path, {"service/locks.py": """
        import threading

        ALPHA_LOCK = threading.Lock()
        BETA_LOCK = threading.Lock()

        def forward():
            with ALPHA_LOCK:
                take_beta()

        def take_beta():
            with BETA_LOCK:
                pass

        def also_forward():
            with ALPHA_LOCK:
                with BETA_LOCK:
                    pass
    """}, ["lock-order"])
    assert rep.unsuppressed_by_rule("lock-order") == []


def test_lock_order_self_call_reacquire(tmp_path):
    """`self.m()` runs on the SAME instance: re-acquiring the held
    non-reentrant lock one call away is a guaranteed self-deadlock."""
    rep = lint(tmp_path, {"service/cache.py": """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()

            def put(self, k, v):
                with self._lock:
                    self._evict()

            def _evict(self):
                with self._lock:
                    pass
    """}, ["lock-order"])
    findings = rep.unsuppressed_by_rule("lock-order")
    assert len(findings) == 1
    assert "self-deadlock" in findings[0].message


def test_lock_order_rlock_reacquire_is_clean(tmp_path):
    """The same shape over an RLock is reentrant by design."""
    rep = lint(tmp_path, {"service/cache.py": """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.RLock()

            def put(self, k, v):
                with self._lock:
                    self._evict()

            def _evict(self):
                with self._lock:
                    pass
    """}, ["lock-order"])
    assert rep.unsuppressed_by_rule("lock-order") == []


def test_lock_order_condition_aliases_to_its_lock(tmp_path):
    """Condition(self._lock) IS self._lock: with-ing the condition
    then with-ing the lock through a self-call must report the
    re-acquisition, not invent a second lock."""
    rep = lint(tmp_path, {"service/pipe.py": """
        import threading

        class Pipe:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition(self._lock)

            def push(self):
                with self._cond:
                    self._locked_len()

            def _locked_len(self):
                with self._lock:
                    return 0
    """}, ["lock-order"])
    findings = rep.unsuppressed_by_rule("lock-order")
    assert len(findings) == 1
    assert "self-deadlock" in findings[0].message


# -- loop-blocking -----------------------------------------------------------

def _server_fixture(helper_body):
    return {
        "parallel/executors.py": """
            def spawn_thread(fn, name=None):
                return fn
        """,
        "service/async_server.py": f"""
            import threading
            from fixturepkg.parallel.executors import spawn_thread

            class Server:
                def __init__(self):
                    self._lock = threading.Lock()

                def start(self):
                    spawn_thread(self._loop, name="srv-loop")

                def _loop(self):
                    while True:
                        self._tick()

                def _tick(self):
                    self._helper()

                def _helper(self):
{textwrap.indent(textwrap.dedent(helper_body), ' ' * 20)}
        """,
    }


def test_loop_blocking_two_hops_from_loop(tmp_path):
    """A lock acquisition TWO calls below the loop callback — the
    regression shape per-function lints can never see."""
    rep = lint(tmp_path, _server_fixture("""
        with self._lock:
            pass
    """), ["loop-blocking"])
    findings = rep.unsuppressed_by_rule("loop-blocking")
    assert len(findings) == 1
    f = findings[0]
    assert "lock" in f.message
    assert "_loop -> " in f.message and "_helper" in f.message


def test_loop_blocking_clean_loop(tmp_path):
    rep = lint(tmp_path, _server_fixture("""
        return 1
    """), ["loop-blocking"])
    assert rep.unsuppressed_by_rule("loop-blocking") == []


def test_loop_blocking_missing_root_is_a_finding(tmp_path):
    """Renaming the loop thread must not silently disable the rule."""
    rep = lint(tmp_path, {"service/async_server.py": """
        def serve():
            return None
    """}, ["loop-blocking"])
    findings = rep.unsuppressed_by_rule("loop-blocking")
    assert len(findings) == 1
    assert "cannot locate" in findings[0].message


# -- deadline-wait -----------------------------------------------------------

def test_deadline_wait_unbounded_forms(tmp_path):
    """Zero-arg Queue.get / Event.wait / Future.result are exactly
    the waits a spent deadline cannot escape."""
    rep = lint(tmp_path, {"work.py": """
        def consume(q):
            return q.get()

        def wait_event(ev):
            ev.wait()

        def collect(fut):
            return fut.result()
    """}, ["deadline-wait"])
    findings = rep.unsuppressed_by_rule("deadline-wait")
    assert [f.line for f in findings] == [3, 6, 9]
    kinds = "\n".join(f.message for f in findings)
    assert "queue-get" in kinds
    assert "unbounded wait" in kinds
    assert "future-result" in kinds


def test_deadline_wait_bounded_forms_are_clean(tmp_path):
    rep = lint(tmp_path, {"work.py": """
        def consume(q):
            return q.get(timeout=1.0)

        def wait_event(ev):
            while not ev.wait(0.05):
                check_deadline("work")

        def collect(fut):
            return fut.result(timeout=2.0)

        def lookup(d, k):
            return d.get(k)
    """}, ["deadline-wait"])
    assert rep.unsuppressed_by_rule("deadline-wait") == []


def test_deadline_wait_module_level_cf_wait(tmp_path):
    """concurrent.futures.wait(fs) takes futures positionally — only
    an explicit timeout= bounds it."""
    rep = lint(tmp_path, {"work.py": """
        import concurrent.futures as cf

        def gather(futs):
            cf.wait(futs)

        def gather_bounded(futs):
            cf.wait(futs, timeout=1.0)
    """}, ["deadline-wait"])
    findings = rep.unsuppressed_by_rule("deadline-wait")
    assert [f.line for f in findings] == [5]


# -- fault-taxonomy ----------------------------------------------------------

def test_fault_taxonomy_swallowed_transient(tmp_path):
    """A swallowed 503 outside the fault plane: the bug class where a
    storm of transient errors reads as silence."""
    rep = lint(tmp_path, {"client.py": """
        def fetch(store):
            try:
                return store.read()
            except TransientStoreError:
                return None
    """}, ["fault-taxonomy"])
    findings = rep.unsuppressed_by_rule("fault-taxonomy")
    assert len(findings) == 1
    assert "TransientStoreError" in findings[0].message


def test_fault_taxonomy_hand_rolled_retry(tmp_path):
    rep = lint(tmp_path, {"client.py": """
        def fetch(store):
            while True:
                try:
                    return store.read()
                except OSError:
                    continue
    """}, ["fault-taxonomy"])
    findings = rep.unsuppressed_by_rule("fault-taxonomy")
    assert len(findings) == 1
    assert "hand-rolled" in findings[0].message


def test_fault_taxonomy_skip_loop_and_ladder_are_clean(tmp_path):
    """for-over-collection skip loops are item-level fault isolation,
    not retries; a retry that consults the taxonomy is the sanctioned
    shape; the fault plane itself is whitelisted."""
    rep = lint(tmp_path, {
        "sweep.py": """
            import os

            def sweep(paths):
                for p in paths:
                    try:
                        os.remove(p)
                    except OSError:
                        continue

            def fetch(store):
                while True:
                    try:
                        return store.read()
                    except OSError as e:
                        if not is_transient_error(e):
                            raise
                        continue
        """,
        "parallel/fault.py": """
            def classify(store):
                try:
                    return store.read()
                except TransientStoreError:
                    return None
        """,
    }, ["fault-taxonomy"])
    assert rep.unsuppressed_by_rule("fault-taxonomy") == []


# -- ownership-history -------------------------------------------------------

def test_ownership_history_raw_prop_literal(tmp_path):
    """Hand-parsing an ownership-stamp property outside
    parallel/distributed.py is the fork the rule exists to catch."""
    rep = lint(tmp_path, {"service/daemon.py": """
        def resume(props):
            if "multihost.ownership.version" in props:
                return int(props["multihost.ownership.version"])
            return None

        def floors(props, pid):
            return props.get("multihost.rejoin.floor.p" + str(pid))
    """}, ["ownership-history"])
    found = rep.unsuppressed_by_rule("ownership-history")
    assert len(found) == 3, found
    assert all("stamp_from_properties" in f.message for f in found)


def test_ownership_history_forked_constant_import(tmp_path):
    """Importing the raw property-name constants is the same fork one
    step removed."""
    rep = lint(tmp_path, {
        "parallel/distributed.py": """
            OWNERSHIP_VERSION_PROP = "multihost.ownership.version"

            def stamp_from_properties(props):
                return props.get(OWNERSHIP_VERSION_PROP)
        """,
        "maintenance/sweep.py": """
            from fixturepkg.parallel.distributed import (
                OWNERSHIP_VERSION_PROP,
            )

            def check(props):
                return OWNERSHIP_VERSION_PROP in props
        """,
    }, ["ownership-history"])
    found = rep.unsuppressed_by_rule("ownership-history")
    assert len(found) == 1, found
    assert "OWNERSHIP_VERSION_PROP" in found[0].message
    assert found[0].file.endswith("maintenance/sweep.py")


def test_ownership_history_docstrings_and_owner_are_clean(tmp_path):
    """Prose may NAME the properties (docstrings exempt), the encoding
    owner may define them, and the sanctioned API is free to use."""
    rep = lint(tmp_path, {
        "parallel/distributed.py": """
            OWNERSHIP_VERSION_PROP = "multihost.ownership.version"
            REJOIN_FLOOR_PREFIX = "multihost.rejoin.floor.p"
        """,
        "service/daemon.py": '''
            """Replays the gap below the granted
            multihost.rejoin.floor.p<i> floor before resuming."""

            def resume(table, props):
                """Anchored at multihost.ownership.history."""
                from fixturepkg.parallel.distributed import (
                    stamp_from_properties,
                )
                return stamp_from_properties(props)
        ''',
    }, ["ownership-history"])
    assert rep.unsuppressed_by_rule("ownership-history") == []


# -- migrated hygiene rules (fixture spot checks) ----------------------------

def test_hygiene_rules_on_fixtures(tmp_path):
    rep = lint(tmp_path, {"util.py": """
        import socket
        import threading
        import time

        def nap():
            time.sleep(1)

        def spin():
            return threading.Thread(target=nap)

        def quiet():
            try:
                nap()
            except Exception:
                pass
    """}, ["sleeps", "threads", "sockets", "swallow"])
    assert len(rep.unsuppressed_by_rule("sleeps")) == 1
    assert len(rep.unsuppressed_by_rule("threads")) == 1
    assert len(rep.unsuppressed_by_rule("sockets")) == 1
    assert len(rep.unsuppressed_by_rule("swallow")) == 1


def test_hygiene_home_modules_are_exempt(tmp_path):
    rep = lint(tmp_path, {
        "utils/backoff.py": "import time\n\n\ndef zz():\n"
                            "    time.sleep(1)\n",
        "parallel/executors.py": "import threading\n\n\n"
                                 "def t():\n"
                                 "    return threading.Thread()\n",
        "service/async_server.py": "import socket\nimport selectors\n",
    }, ["sleeps", "threads", "sockets"])
    assert rep.unsuppressed == []


# -- suppression machinery ---------------------------------------------------

def test_suppression_reason_and_stale_and_reasonless(tmp_path):
    rep = lint(tmp_path, {"util.py": """
        import time

        def reviewed():
            time.sleep(1)  # lint-ok: sleeps fixture: reviewed wait

        def stale():
            return 1  # lint-ok: sleeps nothing sleeps here anymore

        def reasonless():
            time.sleep(2)  # lint-ok: sleeps

        def typo():
            return 2  # lint-ok: sleps missing rule
    """}, ["sleeps"])
    # the reviewed site is suppressed but still visible in the report
    sleeps = rep.by_rule("sleeps")
    assert len(sleeps) == 2
    suppressed = [f for f in sleeps if f.suppressed]
    assert len(suppressed) == 1
    assert suppressed[0].suppress_reason == "fixture: reviewed wait"
    # the reasonless marker does NOT suppress, and is itself flagged
    assert len(rep.unsuppressed_by_rule("sleeps")) == 1
    bad = rep.unsuppressed_by_rule("bad-suppression")
    assert len(bad) == 2              # reasonless + unknown rule id
    assert any("no reason" in f.message for f in bad)
    assert any("unknown rule" in f.message for f in bad)
    stale = rep.unsuppressed_by_rule("stale-suppression")
    assert len(stale) == 1
    assert stale[0].line == 8


def test_suppression_comment_above_covers_next_code_line(tmp_path):
    rep = lint(tmp_path, {"util.py": """
        import time

        def reviewed():
            # lint-ok: sleeps reviewed wait with a reason that
            # wraps over two comment lines
            time.sleep(1)
    """}, ["sleeps"])
    assert rep.unsuppressed == []
    assert len(rep.by_rule("sleeps")) == 1
    assert rep.by_rule("sleeps")[0].suppressed


def test_marker_inside_string_literal_is_inert(tmp_path):
    """Docstrings and fixture strings that MENTION lint-ok must not
    create live markers (they would all be stale)."""
    rep = lint(tmp_path, {"util.py": '''
        DOC = """use `# lint-ok: sleeps why` to exempt a wait"""

        def f():
            return DOC
    '''}, ["sleeps"])
    assert rep.unsuppressed == []


# -- CLI ---------------------------------------------------------------------

def test_cli_lint_json_shape(tmp_path, capsys):
    """The machine contract external CI consumes: findings with
    rule/file/line/message/suppressed, a summary, the rule list, and
    exit 1 on unsuppressed findings."""
    from paimon_tpu.cli import main

    pkg = make_pkg(tmp_path, {"util.py": """
        import time

        def nap():
            time.sleep(1)
    """})
    rc = main(["lint", "--json", "--package-dir", pkg,
               "--rule", "sleeps"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["package"] == "fixturepkg"
    assert out["files"] == 2              # __init__.py + util.py
    assert "sleeps" in out["rules"]
    assert "stale-suppression" in out["rules"]
    assert out["summary"]["unsuppressed"] == 1
    assert out["summary"]["total"] == 1
    (f,) = out["findings"]
    assert f["rule"] == "sleeps"
    assert f["file"].endswith("util.py")
    assert f["line"] == 5
    assert f["suppressed"] is False
    assert isinstance(f["message"], str) and f["message"]


def test_cli_lint_clean_exit_zero(tmp_path, capsys):
    from paimon_tpu.cli import main

    pkg = make_pkg(tmp_path, {"util.py": "def f():\n    return 1\n"})
    rc = main(["lint", "--package-dir", pkg, "--rule", "sleeps"])
    assert rc == 0
    assert "0 finding(s)" in capsys.readouterr().out


def test_cli_list_rules(capsys):
    from paimon_tpu.cli import main

    assert main(["lint", "--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("swallow", "threads", "sleeps", "sockets",
                "collectives", "distributed-init",
                "host-materialization", "metric-drift",
                "obs-drift", "options-drift", "lock-order",
                "loop-blocking", "deadline-wait", "fault-taxonomy",
                "ownership-history"):
        assert rid in out, f"rule {rid} missing from catalog"


# -- obs-drift ---------------------------------------------------------------

def test_obs_drift_orphaned_stage_constant(tmp_path):
    """A STAGE_* name nothing ever opens a span under is drift: the
    merged fleet trace documents a stage that never appears."""
    rep = lint(tmp_path, {
        "obs/trace.py": """
            STAGE_SERVE_REQUEST = "serve.request"
            STAGE_GHOST = "ghost.stage"

            def adopt():
                return STAGE_SERVE_REQUEST
        """,
    }, ["obs-drift"])
    assert len(rep.findings) == 1
    f = rep.findings[0]
    assert f.rule == "obs-drift"
    assert "STAGE_GHOST" in f.message
    assert f.file.endswith("obs/trace.py")


def test_obs_drift_orphaned_flight_event(tmp_path):
    rep = lint(tmp_path, {
        "obs/flight.py": """
            EV_RETRY = "retry"
            EV_NEVER_RECORDED = "never"
        """,
        "parallel/fault.py": """
            from fixturepkg.obs.flight import EV_RETRY

            def on_retry():
                return EV_RETRY
        """,
    }, ["obs-drift"])
    assert [1 for f in rep.findings] == [1]
    assert "EV_NEVER_RECORDED" in rep.findings[0].message


def test_obs_drift_clean_when_all_consumed(tmp_path):
    """Known-good: same-module use (trace.py's own adoption path)
    and cross-module use both count as producers."""
    rep = lint(tmp_path, {
        "obs/trace.py": """
            STAGE_SERVE_REQUEST = "serve.request"
            STAGE_CLIENT_REQUEST = "client.request"

            def adopt():
                return STAGE_SERVE_REQUEST
        """,
        "obs/flight.py": """
            EV_CRASH = "crash"

            def _hook():
                return EV_CRASH
        """,
        "service/query_service.py": """
            from fixturepkg.obs.trace import STAGE_CLIENT_REQUEST

            def post():
                return STAGE_CLIENT_REQUEST
        """,
    }, ["obs-drift"])
    assert rep.findings == []


def test_obs_drift_ignores_packages_without_obs(tmp_path):
    rep = lint(tmp_path, {"util.py": "def f():\n    return 1\n"},
               ["obs-drift"])
    assert rep.findings == []


# -- the production tree -----------------------------------------------------

def test_production_tree_zero_unsuppressed_findings(lint_report):
    """THE acceptance gate: the full 15-rule catalog over paimon_tpu/
    reports zero unsuppressed findings — every new finding is either a
    bug to fix or a deliberate pattern that needs a reviewed,
    reasoned `# lint-ok:` marker at the site."""
    assert lint_report.unsuppressed == [], (
        "unsuppressed findings:\n"
        + "\n".join(str(f) for f in lint_report.unsuppressed))


def test_production_rule_catalog_is_complete(lint_report):
    ids = {r.id for r in lint_report.rules}
    assert ids >= {"swallow", "threads", "sleeps", "sockets",
                   "collectives", "distributed-init",
                   "host-materialization", "metric-drift",
                   "obs-drift", "options-drift", "lock-order",
                   "loop-blocking", "deadline-wait", "fault-taxonomy",
                   "ownership-history"}
    assert len(ids) >= 15


def test_production_suppressions_all_carry_reasons(lint_report):
    """Every suppressed finding in the tree has a non-empty reason
    (the engine enforces this; this pins the contract)."""
    suppressed = [f for f in lint_report.findings if f.suppressed]
    assert suppressed, "expected reviewed suppressions in the tree"
    for f in suppressed:
        assert f.suppress_reason, f


def test_fixed_violations_stay_fixed(lint_report):
    """Regression notes for the genuine violations the four new rules
    surfaced (fixed in the PR that shipped the rules):

    * lookup/local_query.py `_get_or_build`: the in-flight-builder
      wait was a bare `ev.wait()` — a caller whose deadline was spent
      (or whose builder died) parked forever; now a bounded wait loop
      calling check_deadline().
    * table/topology.py `_Worker.prepare`: `done.wait()` trusted the
      writer thread unconditionally; a wedged writer held the
      checkpoint barrier forever; now bounded + deadline-checked.
    * compact/manager.py `_prefetch`: the consumer's `q.get()` could
      outlive a stalled pump; now a bounded poll that re-checks the
      deadline (and still releases the pump via the cancel flag).
    * compact/manager.py / core/write.py / core/commit.py: every
      blocking `.result()` on compaction/prep/manifest futures now
      rides utils.deadline.wait_future() — bounded polling under a
      request deadline, plain result() without one.
    * lookup/local_query.py `_probe`: the evicted-SST rebuild-once
      retried EVERY OSError; it now consults
      parallel/fault.is_transient_error so deterministic decode
      errors surface instead of re-running the build.

    The checks below pin each fix at source level so a revert
    resurfaces here (and as an engine finding)."""
    mods = lint_report.model.modules
    lq = mods["lookup/local_query.py"].source
    assert "while not ev.wait(" in lq
    assert "is_transient_error" in lq
    topo = mods["table/topology.py"].source
    assert "while not done.wait(" in topo
    mgr = mods["compact/manager.py"].source
    assert "q.get(timeout=" in mgr
    assert "wait_future(" in mgr
    assert "wait_future(" in mods["core/write.py"].source
    assert "wait_future(" in mods["core/commit.py"].source
    # and the rules that found them stay green
    for rid in ("deadline-wait", "fault-taxonomy", "lock-order",
                "loop-blocking"):
        assert lint_report.unsuppressed_by_rule(rid) == []


def test_wait_future_contract():
    """The sanctioned future wait: plain result() without a deadline,
    bounded polling + DeadlineExceededError with one."""
    from concurrent.futures import ThreadPoolExecutor

    from paimon_tpu.utils.deadline import (
        DeadlineExceededError, deadline_scope, wait_future,
    )

    with ThreadPoolExecutor(1) as pool:
        fut = pool.submit(lambda: 42)
        assert wait_future(fut) == 42
        fut = pool.submit(lambda: 43)
        with deadline_scope(10_000):
            assert wait_future(fut, poll_s=0.01) == 43

        import threading
        release = threading.Event()
        hung = pool.submit(release.wait, 30)
        with deadline_scope(50):
            with pytest.raises(DeadlineExceededError):
                wait_future(hung, poll_s=0.01)
        release.set()           # let the worker finish; pool joins


# -- model / engine regressions ----------------------------------------------

def test_defs_in_all_compound_bodies_are_visible(tmp_path):
    """A def can hide in ANY compound statement.  The model once
    indexed only if/try/with BODIES — functions defined in loop
    bodies, except handlers, else/finally branches were invisible to
    every rule, so an unbounded wait inside one kept the tree green."""
    rep = lint(tmp_path, {"hidden.py": """
        def in_loop(items):
            for it in items:
                def load(fut):
                    return fut.result()
                load(it)

        def in_handler(q):
            try:
                return None
            except ValueError:
                def drain():
                    return q.get()
                return drain()

        def in_else_finally(flag, q):
            try:
                pass
            finally:
                def tail(ev):
                    ev.wait()
                tail(flag)
    """}, ["deadline-wait"])
    findings = rep.unsuppressed_by_rule("deadline-wait")
    kinds = "\n".join(f.message for f in findings)
    assert len(findings) == 3, kinds
    assert "future-result" in kinds
    assert "queue-get" in kinds


def test_lock_order_cycle_through_recursive_chain(tmp_path):
    """Mutually recursive callees must not poison the transitive-
    acquire memo: a result computed while an ancestor is on the DFS
    stack is INCOMPLETE and memoizing it permanently dropped the
    cycle's lock contributions — the textbook inversion below went
    unreported."""
    rep = lint(tmp_path, {"service/recur.py": """
        import threading

        ALPHA_LOCK = threading.Lock()
        BETA_LOCK = threading.Lock()

        def thread_one():
            with ALPHA_LOCK:
                take_alpha()

        def thread_two():
            with BETA_LOCK:
                take_beta()

        def take_alpha():
            with ALPHA_LOCK:
                take_beta()

        def take_beta():
            with BETA_LOCK:
                take_alpha()
    """}, ["lock-order"])
    findings = rep.unsuppressed_by_rule("lock-order")
    assert len(findings) == 1
    assert "ALPHA_LOCK" in findings[0].message
    assert "BETA_LOCK" in findings[0].message


def test_nested_def_is_not_a_method(tmp_path):
    """A def nested inside a method is a closure, not a method:
    registering it let `self.<name>()` resolve to it, producing
    phantom call edges (here: a false 'guaranteed self-deadlock' on a
    call that is an AttributeError at runtime)."""
    rep = lint(tmp_path, {"service/cache.py": """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()

            def maker(self):
                def helper():
                    with self._lock:
                        pass
                return helper

            def other(self, obj):
                with self._lock:
                    obj.helper()
    """}, ["lock-order"])
    assert rep.unsuppressed_by_rule("lock-order") == []


def test_nested_def_does_not_shadow_real_method(tmp_path):
    """A later nested def sharing a real method's name must not
    overwrite it in the class's method table — self-call edges would
    silently redirect to the closure."""
    rep = lint(tmp_path, {"service/cache.py": """
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()

            def evict(self):
                with self._lock:
                    pass

            def later(self):
                def evict():
                    return None
                return evict()

            def put(self):
                with self._lock:
                    self.evict()
    """}, ["lock-order"])
    findings = rep.unsuppressed_by_rule("lock-order")
    # put() -> the REAL evict() re-acquires the held lock
    assert len(findings) == 1
    assert "self-deadlock" in findings[0].message


def test_wait_future_done_in_race_window():
    """Future.result(timeout=) can raise TimeoutError after the
    worker completed (the wait's lock is released before the raise).
    wait_future must answer with the WORKER's outcome, not re-raise
    the poll's timeout as if the worker failed."""
    import concurrent.futures as cf

    from paimon_tpu.utils.deadline import deadline_scope, wait_future

    class RacyFuture(cf.Future):
        """First timed result() raises TimeoutError even though the
        future is done — the race window, made deterministic."""

        def __init__(self):
            super().__init__()
            self._raced = False

        def result(self, timeout=None):
            if timeout is not None and not self._raced:
                self._raced = True
                raise cf.TimeoutError()
            return super().result(timeout)

    fut = RacyFuture()
    fut.set_result("the-value")
    with deadline_scope(5_000):
        assert wait_future(fut, poll_s=0.01) == "the-value"

    # a worker that genuinely raised TimeoutError still propagates it
    fut = RacyFuture()
    fut.set_exception(cf.TimeoutError("worker timed out"))
    with deadline_scope(5_000):
        with pytest.raises(cf.TimeoutError, match="worker timed out"):
            wait_future(fut, poll_s=0.01)


def test_meta_rule_ids_round_trip(tmp_path, capsys):
    """Every report's `rules` array advertises bad-suppression /
    stale-suppression — an id copied from the JSON back into --rule
    (or run()) must be accepted, and an unknown id must raise a
    usable error, not a bare KeyError."""
    from paimon_tpu.analysis import run_package
    from paimon_tpu.cli import main

    pkg = make_pkg(tmp_path, {"util.py": "def f():\n    return 1\n"})
    rep = run_package(pkg, rule_ids=["sleeps", "stale-suppression"])
    assert rep.unsuppressed == []
    assert main(["lint", "--package-dir", pkg,
                 "--rule", "bad-suppression"]) == 0
    capsys.readouterr()
    with pytest.raises(ValueError, match="unknown rule id 'typo'"):
        run_package(pkg, rule_ids=["typo"])
