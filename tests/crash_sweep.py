"""Reusable crash-point sweep harness.

Generalizes the commit atomicity sweep (test_fault_injection.py) to ANY
maintenance operation: for every mutating-op index i, build a fresh
table, arm FailingFileIO to kill the i-th mutating operation, run the
operation, and after the injected crash assert

  1. the table is still readable at its last snapshot (crashed state),
  2. a restart of the operation on a clean FileIO converges,
  3. fsck finds no violation in the converged table.

The sweep ends at the first index where the operation completes with no
injection — every mutating op of the operation has then been killed
exactly once.  FailingFileIO's op trace names the op killed at each
point, so failures report "crash point #7 (delete manifest/...)"
instead of a bare index.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional

from paimon_tpu.table import FileStoreTable
from tests.failing_fileio import FailingFileIO, InjectedIOError


@dataclass
class CrashPoint:
    index: int
    op: str
    path: str

    def __str__(self):
        return f"crash point #{self.index} ({self.op} {self.path})"


def crash_point_sweep(
        make_table: Callable[[str], FileStoreTable],
        operation: Callable[[FileStoreTable], object],
        *,
        name: str,
        verify_after_crash: Optional[Callable] = None,
        verify_converged: Optional[Callable] = None,
        restart: Optional[Callable[[FileStoreTable], object]] = None,
        fsck_converged: bool = True,
        max_points: int = 400) -> List[CrashPoint]:
    """Sweep an injected crash over every mutating-op index of
    `operation`.

    make_table(tag) -> a FRESH seeded table per crash point (unique
    directory per tag).  operation(table) runs the op under test
    against whatever file_io the given table carries.  restart
    defaults to `operation` re-run on a reloaded clean table.
    verify_after_crash(table, point) / verify_converged(table) hook
    extra invariants; the readability + fsck checks always run.

    Returns the list of crash points exercised (ops killed)."""
    points: List[CrashPoint] = []
    for idx in range(max_points):
        tag = f"{name}-{idx}"
        table = make_table(tag)
        fio = FailingFileIO(table.file_io, name)
        broken = FileStoreTable(fio, table.path,
                                table.schema_manager.latest(),
                                branch=table.branch)
        FailingFileIO.reset(name, idx)
        try:
            operation(broken)
            crashed = False
        except InjectedIOError:
            crashed = True
        finally:
            trace = FailingFileIO.ops(name)
            FailingFileIO.disarm(name)
        killed = [r for r in trace if r.killed]
        if not killed:
            # the operation completed with no injection fired: every
            # mutating op has been killed once — sweep done
            assert not crashed
            return points
        point = CrashPoint(idx, killed[0].op, killed[0].path)
        points.append(point)
        # an operation may legitimately SURVIVE a killed op (best-effort
        # paths like hint writes swallow IO errors); convergence checks
        # below still apply either way

        # 1. crashed state: readable at the last snapshot
        try:
            if verify_after_crash is not None:
                verify_after_crash(table, point)
            else:
                table.to_arrow()
        except AssertionError:
            raise
        except Exception as e:              # noqa: BLE001
            raise AssertionError(
                f"{point}: table unreadable in crashed state: "
                f"{type(e).__name__}: {e}") from e

        # 2. restart on a clean FileIO converges
        fresh = FileStoreTable.load(table.path,
                                    file_io=table.file_io)
        try:
            (restart or operation)(fresh)
        except Exception as e:              # noqa: BLE001
            raise AssertionError(
                f"{point}: restart did not converge: "
                f"{type(e).__name__}: {e}") from e
        if verify_converged is not None:
            verify_converged(fresh)

        # 3. the converged graph is internally consistent
        if fsck_converged:
            report = fresh.fsck()
            assert report.ok, \
                f"{point}: fsck after restart found violations: " \
                f"{[v.to_dict() for v in report.violations]}"
    raise AssertionError(
        f"sweep {name!r} did not terminate within {max_points} crash "
        f"points — operation never completed cleanly")
