"""Device merge kernel tests, checked against a pure-Python oracle that
mimics the reference semantics (latest-by-sequence wins, stable ties by
arrival order, deletes dropped)."""

import numpy as np
import pyarrow as pa
import pytest

from paimon_tpu.ops.merge import KIND_COL, SEQ_COL, merge_runs
from paimon_tpu.ops.normkey import NormalizedKeyEncoder
from paimon_tpu.types import RowKind


def make_run(keys, seqs, kinds=None, values=None, key_type=pa.int64()):
    n = len(keys)
    kinds = kinds if kinds is not None else [RowKind.INSERT] * n
    values = values if values is not None else list(range(n))
    return pa.table({
        "k": pa.array(keys, key_type),
        SEQ_COL: pa.array(seqs, pa.int64()),
        KIND_COL: pa.array(kinds, pa.int8()),
        "v": pa.array(values, pa.int64()),
    })


def oracle_dedup(runs, drop_deletes=True):
    """Reference model: per key keep record with max (seq, arrival)."""
    best = {}
    arrival = 0
    for run in runs:
        for row in run.to_pylist():
            key = row["k"]
            cand = (row[SEQ_COL], arrival, row)
            if key not in best or cand[:2] > best[key][:2]:
                best[key] = cand
            arrival += 1
    out = []
    for key in sorted(best, key=lambda x: (x is None, x)):
        row = best[key][2]
        if drop_deletes and row[KIND_COL] in (RowKind.DELETE,
                                              RowKind.UPDATE_BEFORE):
            continue
        out.append((row["k"], row["v"]))
    return out


def result_pairs(res):
    t = res.take()
    return list(zip(t.column("k").to_pylist(), t.column("v").to_pylist()))


def test_single_run_dedup():
    run = make_run([1, 2, 2, 3], [0, 1, 2, 3], values=[10, 20, 21, 30])
    res = merge_runs([run], ["k"])
    assert result_pairs(res) == [(1, 10), (2, 21), (3, 30)]


def test_multi_run_latest_wins():
    r1 = make_run([1, 2, 3], [0, 1, 2], values=[10, 20, 30])
    r2 = make_run([2, 3], [3, 4], values=[21, 31])
    res = merge_runs([r1, r2], ["k"])
    assert result_pairs(res) == [(1, 10), (2, 21), (3, 31)]


def test_delete_drops_key():
    r1 = make_run([1, 2], [0, 1], values=[10, 20])
    r2 = make_run([1], [2], kinds=[RowKind.DELETE], values=[0])
    res = merge_runs([r1, r2], ["k"])
    assert result_pairs(res) == [(2, 20)]
    res_keep = merge_runs([r1, r2], ["k"], drop_deletes=False)
    assert [k for k, _ in result_pairs(res_keep)] == [1, 2]


def test_equal_seq_later_run_wins():
    # user-defined sequence: ties broken by arrival order (later wins)
    r1 = make_run([1], [5], values=[100])
    r2 = make_run([1], [5], values=[200])
    res = merge_runs([r1, r2], ["k"])
    assert result_pairs(res) == [(1, 200)]


def test_first_row_engine():
    r1 = make_run([1, 2], [0, 1], values=[10, 20])
    r2 = make_run([1, 2], [2, 3], values=[11, 21])
    res = merge_runs([r1, r2], ["k"], merge_engine="first-row")
    assert result_pairs(res) == [(1, 10), (2, 20)]


def test_negative_and_extreme_int_keys():
    keys = [-(1 << 62), -1, 0, 1, (1 << 62)]
    run = make_run(keys, list(range(5)), values=list(range(5)))
    res = merge_runs([run], ["k"])
    assert [k for k, _ in result_pairs(res)] == sorted(keys)


def test_float_keys():
    keys = [3.5, -2.25, 0.0, -1e300, 1e300]
    run = pa.table({
        "k": pa.array(keys, pa.float64()),
        SEQ_COL: pa.array(range(5), pa.int64()),
        KIND_COL: pa.array([0] * 5, pa.int8()),
        "v": pa.array(range(5), pa.int64()),
    })
    res = merge_runs([run], ["k"])
    out = res.take().column("k").to_pylist()
    assert out == sorted(keys)


def test_string_keys_short():
    keys = ["banana", "apple", "cherry", "apple"]
    run = pa.table({
        "k": pa.array(keys, pa.string()),
        SEQ_COL: pa.array(range(4), pa.int64()),
        KIND_COL: pa.array([0] * 4, pa.int8()),
        "v": pa.array(range(4), pa.int64()),
    })
    res = merge_runs([run], ["k"])
    assert result_pairs(res) == [("apple", 3), ("banana", 0), ("cherry", 2)]


def test_string_keys_truncated_prefix():
    # keys share a 16-byte prefix and differ beyond it -> host refinement
    base = "x" * 20
    keys = [base + "bbb", base + "aaa", base + "bbb", "short"]
    run = pa.table({
        "k": pa.array(keys, pa.string()),
        SEQ_COL: pa.array(range(4), pa.int64()),
        KIND_COL: pa.array([0] * 4, pa.int8()),
        "v": pa.array(range(4), pa.int64()),
    })
    res = merge_runs([run], ["k"])
    assert result_pairs(res) == [
        ("short", 3), (base + "aaa", 1), (base + "bbb", 2)]


def test_composite_keys():
    run = pa.table({
        "a": pa.array([1, 1, 2, 2], pa.int32()),
        "b": pa.array(["x", "y", "x", "x"], pa.string()),
        SEQ_COL: pa.array(range(4), pa.int64()),
        KIND_COL: pa.array([0] * 4, pa.int8()),
        "v": pa.array(range(4), pa.int64()),
    })
    res = merge_runs([run], ["a", "b"])
    t = res.take()
    assert t.column("a").to_pylist() == [1, 1, 2]
    assert t.column("b").to_pylist() == ["x", "y", "x"]
    assert t.column("v").to_pylist() == [0, 1, 3]


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_randomized_vs_oracle(seed):
    rng = np.random.default_rng(seed)
    runs = []
    seq = 0
    for _ in range(rng.integers(2, 6)):
        n = int(rng.integers(1, 500))
        keys = rng.integers(-50, 50, n).tolist()
        # runs must be internally deduped on (key) like real sorted runs?
        # No -- L0 flush dedups, but merge must handle any seq layout.
        seqs = list(range(seq, seq + n))
        seq += n
        kinds = rng.choice(
            [RowKind.INSERT, RowKind.UPDATE_AFTER, RowKind.DELETE],
            n, p=[0.6, 0.25, 0.15]).tolist()
        values = rng.integers(0, 10**9, n).tolist()
        runs.append(make_run(keys, seqs, kinds, values))
    res = merge_runs(runs, ["k"])
    assert result_pairs(res) == oracle_dedup(runs)


def test_large_merge_correctness():
    rng = np.random.default_rng(42)
    n = 200_000
    keys = rng.integers(0, 50_000, n)
    r1 = make_run(keys.tolist(), list(range(n)),
                  values=rng.integers(0, 1 << 30, n).tolist())
    keys2 = rng.integers(0, 50_000, n)
    r2 = make_run(keys2.tolist(), list(range(n, 2 * n)),
                  values=rng.integers(0, 1 << 30, n).tolist())
    res = merge_runs([r1, r2], ["k"])
    t = res.take()
    ks = t.column("k").to_pylist()
    assert ks == sorted(set(keys.tolist()) | set(keys2.tolist()))


def test_int64_min_key_not_dropped_single_chip():
    """Regression: INT64_MIN encodes to all-zero lanes (same as padding);
    it must still win its segment (validity is part of segment identity)."""
    import pyarrow as pa
    from paimon_tpu.ops.merge import merge_runs
    from paimon_tpu.ops.normkey import NormalizedKeyEncoder

    t = pa.table({
        "_KEY_k": pa.array([-(1 << 63), 7, -(1 << 63)], pa.int64()),
        "_SEQUENCE_NUMBER": pa.array([0, 1, 2], pa.int64()),
        "_VALUE_KIND": pa.array([0, 0, 0], pa.int8()),
    })
    res = merge_runs([t], ["_KEY_k"])
    got = sorted(res.take().column("_KEY_k").to_pylist())
    assert got == [-(1 << 63), 7]
    assert 2 in res.indices  # max-seq row wins for the dup key


def test_nullable_key_distinct_from_int64_max():
    """ADVICE fix: a null key must get its own presence lane, never
    colliding with INT64_MAX, and must sort last."""
    import numpy as np
    import pyarrow as pa
    from paimon_tpu.ops.normkey import NormalizedKeyEncoder

    enc = NormalizedKeyEncoder([pa.int64()], nullable=[True])
    assert enc.num_lanes == 3
    t = pa.table({"k": pa.array([5, None, (1 << 63) - 1], pa.int64())})
    lanes, _ = enc.encode_table(t, ["k"])
    assert not np.array_equal(lanes[1], lanes[2])  # null != INT64_MAX
    order = sorted(range(3), key=lambda i: tuple(lanes[i]))
    assert order == [0, 2, 1]                      # nulls last


def test_nullable_string_key_distinct_from_ff_prefix():
    import numpy as np
    import pyarrow as pa
    from paimon_tpu.ops.normkey import NormalizedKeyEncoder

    enc = NormalizedKeyEncoder([pa.string()], nullable=[True])
    t = pa.table({"k": pa.array(["\xff" * 16, None])})
    lanes, _ = enc.encode_table(t, ["k"])
    assert not np.array_equal(lanes[0], lanes[1])
    assert tuple(lanes[1]) > tuple(lanes[0])       # null sorts last
