from datetime import date, datetime, time
from decimal import Decimal

import pytest

from paimon_tpu.data.binary_row import BINARY_ROW_EMPTY, BinaryRowCodec
from paimon_tpu.types import (
    BigIntType, BinaryType, BooleanType, DateType, DecimalType, DoubleType,
    FloatType, IntType, SmallIntType, TimeType, TimestampType, TinyIntType,
    VarBinaryType, VarCharType,
)


def roundtrip(types, values):
    codec = BinaryRowCodec(types)
    data = codec.to_bytes(values)
    return codec.from_bytes(data)


def test_fixed_width():
    types = [BooleanType(), TinyIntType(), SmallIntType(), IntType(),
             BigIntType(), FloatType(), DoubleType()]
    vals = (True, -5, 300, -70000, 1 << 40, 1.5, -2.25)
    assert roundtrip(types, vals) == vals


def test_nulls():
    types = [IntType(), VarCharType(100), DoubleType()]
    assert roundtrip(types, (None, None, None)) == (None, None, None)
    assert roundtrip(types, (1, None, 2.0)) == (1, None, 2.0)


def test_strings_inline_and_var():
    types = [VarCharType(100), VarCharType(100)]
    # <=7 bytes inline; >7 in var part
    vals = ("abc", "a-much-longer-string-than-seven-bytes")
    assert roundtrip(types, vals) == vals


def test_unicode():
    types = [VarCharType(100)]
    vals = ("héllo wörld ünïcode",)
    assert roundtrip(types, vals) == vals


def test_binary():
    types = [BinaryType(4), VarBinaryType(100)]
    vals = (b"\x00\x01", b"\xff" * 20)
    assert roundtrip(types, vals) == vals


def test_decimal_compact_and_wide():
    types = [DecimalType(10, 2), DecimalType(28, 4)]
    vals = (Decimal("123.45"), Decimal("-99999999999999999999.1234"))
    assert roundtrip(types, vals) == vals


def test_temporal():
    types = [DateType(), TimeType(3), TimestampType(3), TimestampType(6)]
    vals = (date(2024, 3, 1), time(12, 30, 45, 123000),
            datetime(2024, 3, 1, 12, 0, 0, 123000),
            datetime(2024, 3, 1, 12, 0, 0, 123456))
    out = roundtrip(types, vals)
    assert out == vals


def test_empty_row():
    assert BINARY_ROW_EMPTY == b"\x00\x00\x00\x00" + b"\x00" * 8
    codec = BinaryRowCodec([])
    assert codec.from_bytes(BINARY_ROW_EMPTY) == ()


def test_multi_var_offsets():
    # Several var-length fields interleaved with nulls and fixed fields
    types = [VarCharType(100), IntType(), VarCharType(100), VarCharType(100)]
    vals = ("first-long-string-here", 42, None, "second-long-string-there")
    assert roundtrip(types, vals) == vals
