"""Pipelined write/ingest engine (parallel/write_pipeline.py).

Row-identity of the pipelined flush pool against the serial write path
across every merge engine, the spillable buffer and append tables;
sequence-number safety under concurrent flush scheduling (reserved at
write() time, tier-1); transient-fault retry semantics (storms retry
and complete, exhausted storms RAISE at the prepare-commit barrier);
executor-thread hygiene + the in-flight byte budget (tier-1); LPT
flush scheduling; the write metric group; and the two-phase
upload-failure path-context regression.
"""

import threading
import time

import numpy as np
import pytest

from paimon_tpu.fs import get_file_io
from paimon_tpu.fs.object_store import TransientStoreError
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType
from tests.store_oracle import make_random_engine_table

ENGINES = ["deduplicate", "first-row", "partial-update", "aggregation"]

# small buffers force MANY flushes per commit so the pool actually
# pipelines; parallelism 4 on a 4-bucket table exercises real overlap
PIPED = {"write.flush.parallelism": "4", "write-buffer-size": "16 kb"}
SERIAL = {"write.flush.parallelism": "1", "write-buffer-size": "16 kb"}


def _rows(table):
    return sorted(table.to_arrow().to_pylist(),
                  key=lambda r: (r["pt"], r["id"]))


def _write_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("paimon-write")]


def _wait_no_write_threads(before=(), timeout=5.0):
    """Write-pipeline threads beyond `before` still alive after a GC
    pass.  gc.collect() first: dangling executors of OTHER tests'
    never-closed writers only release their workers when collected, and
    this check is about OUR writer's close() joining OUR pool."""
    import gc
    gc.collect()
    before = set(before)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        cur = [t for t in _write_threads() if t not in before]
        if not cur:
            return []
        time.sleep(0.01)
    return [t for t in _write_threads() if t not in before]


# -- row identity ------------------------------------------------------------

@pytest.mark.parametrize("engine", ENGINES)
def test_pipelined_equals_serial_all_engines(tmp_path, engine):
    """Same seed, serial vs pipelined writers: the tables' full
    merge-on-read scans must be row-identical (store_oracle tables are
    bit-deterministic per seed, so the two writes are twins)."""
    serial = make_random_engine_table(
        str(tmp_path / f"s_{engine}"), seed=77, engine=engine,
        extra_options=SERIAL)
    piped = make_random_engine_table(
        str(tmp_path / f"p_{engine}"), seed=77, engine=engine,
        extra_options=PIPED)
    a, b = _rows(serial), _rows(piped)
    assert a == b and len(a) > 0


def test_pipelined_equals_serial_spillable(tmp_path):
    """write-buffer-spillable: spill writes + folding + the final
    merge ride the same per-bucket actor, so the pipelined table must
    still match serial (changelog-producer=input rides along)."""
    common = {"write-buffer-spillable": "true",
              "sort-spill-buffer-size": "8 kb",
              "local-sort.max-num-file-handles": "3",
              "write-buffer-size": "64 kb",
              "changelog-producer": "input"}
    serial = make_random_engine_table(
        str(tmp_path / "s"), seed=9, engine="deduplicate",
        extra_options={**common, "write.flush.parallelism": "1"})
    piped = make_random_engine_table(
        str(tmp_path / "p"), seed=9, engine="deduplicate",
        extra_options={**common, "write.flush.parallelism": "4"})
    assert _rows(serial) == _rows(piped)
    # both produced a changelog stream of the same total length
    def changelog_rows(t):
        return sum(s.changelog_record_count or 0
                   for s in t.snapshot_manager.snapshots())
    assert changelog_rows(serial) == changelog_rows(piped) > 0


def test_pipelined_equals_serial_append(tmp_path):
    def build(tag, par):
        schema = (Schema.builder()
                  .column("id", BigIntType(False))
                  .column("v", DoubleType())
                  .options({"bucket": "-1",
                            "write.flush.parallelism": par,
                            "write-buffer-size": "8 kb"})
                  .build())
        table = FileStoreTable.create(str(tmp_path / tag), schema)
        wb = table.new_batch_write_builder()
        with wb.new_write() as w:
            for c in range(6):
                w.write_dicts([{"id": c * 1000 + i, "v": float(i)}
                               for i in range(300)])
            wb.new_commit().commit(w.prepare_commit())
        return table
    a = build("s", "1").to_arrow().sort_by("id")
    b = build("p", "4").to_arrow().sort_by("id")
    assert a.equals(b) and a.num_rows == 1800


# -- sequence-number safety (tier-1) -----------------------------------------

def _bucket_seqs(table):
    """{(partition, bucket): sorted seq list} over every data file."""
    from paimon_tpu.core.kv_file import read_kv_file
    scan = table.new_scan()
    out = {}
    for split in table.new_read_builder().new_scan().plan().splits:
        seqs = out.setdefault((split.partition, split.bucket), [])
        for meta in split.data_files:
            t = read_kv_file(table.file_io, scan.path_factory,
                             split.partition, split.bucket, meta,
                             None, None)
            seqs.extend(t.column("_SEQUENCE_NUMBER").to_pylist())
    return {k: sorted(v) for k, v in out.items()}


def test_no_duplicate_or_reordered_seq_across_pipelined_flushes(tmp_path):
    """Sequence ranges are reserved at write() time on the caller
    thread: many concurrent flushes must never duplicate or reorder a
    sequence number within a bucket, across commits included."""
    table = make_random_engine_table(
        str(tmp_path / "t"), seed=41, engine="deduplicate",
        deletes=False, extra_options=PIPED)
    per_bucket = _bucket_seqs(table)
    assert per_bucket
    for key, seqs in per_bucket.items():
        assert len(seqs) == len(set(seqs)), \
            f"duplicate sequence numbers in bucket {key}"
    # second commit continues the per-bucket sequence from the restore
    wb = table.new_batch_write_builder()
    with wb.new_write() as w:
        w.write_dicts([{"pt": 0, "id": i, "v1": 1, "v2": 1.0,
                        "name": "x"} for i in range(50)])
        wb.new_commit().commit(w.prepare_commit())
    again = _bucket_seqs(table)
    for key, seqs in again.items():
        assert len(seqs) == len(set(seqs)), \
            f"duplicate sequence numbers after restore in bucket {key}"


# -- fault semantics ---------------------------------------------------------

class WriteStormFileIO:
    """Duck-typed FileIO: the first `faults` data-file write_bytes
    calls fail with a 503 (a passing transient storm).  Global counter,
    not per-path — retried flushes write FRESH file names."""

    def __init__(self, inner, faults=3):
        self.inner = inner
        self.left = faults
        self.faults = 0
        self.lock = threading.Lock()

    def write_bytes(self, path, data, overwrite=True):
        if path.rsplit("/", 1)[-1].startswith("data-"):
            with self.lock:
                if self.left > 0:
                    self.left -= 1
                    self.faults += 1
                    raise TransientStoreError(f"503 on {path}")
        return self.inner.write_bytes(path, data, overwrite=overwrite)

    def __getattr__(self, name):
        return getattr(self.inner, name)


def _storm_table(tmp_path, storm, **opts):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "2", "write-only": "true"})
              .build())
    table = FileStoreTable.create(str(tmp_path / "t"), schema)
    return FileStoreTable.load(
        table.path, file_io=storm,
        dynamic_options={"write.flush.parallelism": "4",
                         "write-buffer-size": "8 kb",
                         "write.retry.backoff": "0", **opts})


def test_mid_write_503_storm_retries_and_completes(tmp_path):
    from paimon_tpu.metrics import WRITE_RETRIES, global_registry
    storm = WriteStormFileIO(get_file_io(str(tmp_path)), faults=3)
    table = _storm_table(tmp_path, storm)
    r0 = global_registry().write_metrics().counter(WRITE_RETRIES).count
    wb = table.new_batch_write_builder()
    with wb.new_write() as w:
        w.write_dicts([{"id": i, "v": float(i)} for i in range(2000)])
        wb.new_commit().commit(w.prepare_commit())
    assert storm.faults == 3
    assert global_registry().write_metrics() \
        .counter(WRITE_RETRIES).count >= r0 + 3
    got = table.to_arrow()
    assert got.num_rows == 2000


def test_exhausted_write_storm_raises_at_barrier(tmp_path):
    """A storm outliving write.retry.max-attempts must RAISE the
    original transient error at the prepare-commit barrier — a flush is
    never silently dropped — and close() must join the workers."""
    storm = WriteStormFileIO(get_file_io(str(tmp_path)), faults=10 ** 9)
    table = _storm_table(tmp_path, storm,
                         **{"write.retry.max-attempts": "2"})
    before = _write_threads()
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    try:
        with pytest.raises(TransientStoreError):
            w.write_dicts([{"id": i, "v": float(i)}
                           for i in range(2000)])
            w.prepare_commit()
    finally:
        w.close()
    assert not _wait_no_write_threads(before), "leaked write threads"
    # nothing was committed
    assert table.snapshot_manager.latest_snapshot() is None


def test_non_transient_error_propagates_without_retry(tmp_path):
    from paimon_tpu.parallel.write_pipeline import FlushPool
    pool = FlushPool(parallelism=4, max_bytes=1 << 20)
    calls = []

    def bad():
        calls.append(1)
        raise ValueError("schema bug")

    pool.submit(("p", 0), 10, bad)
    with pytest.raises(ValueError, match="schema bug"):
        pool.drain()
    pool.shutdown()
    assert len(calls) == 1, "non-transient errors must not retry"


def test_failed_drain_poisons_the_pool():
    """After a drain() raised, the cancelled tasks' payloads are gone
    (snapshots detached, seqs reserved): a retried prepare on the same
    writer would silently commit with rows missing, so every later
    submit/drain must RAISE instead of pretending to succeed."""
    from paimon_tpu.parallel.write_pipeline import FlushPool
    pool = FlushPool(parallelism=2, max_bytes=1 << 30)

    def boom():
        raise ValueError("flush died")

    pool.submit(("a", 0), 1, boom)
    with pytest.raises(ValueError, match="flush died"):
        pool.drain()
    with pytest.raises(RuntimeError, match="close this writer"):
        pool.drain()
    with pytest.raises(RuntimeError, match="close this writer"):
        pool.submit(("a", 0), 1, lambda: None)
    pool.shutdown()


def test_failed_prepare_commit_never_silently_commits(tmp_path):
    """End-to-end twin of the poison test: after a prepare_commit()
    raised (exhausted storm), a second prepare_commit() on the same
    writer raises too — it must not return a partial message set."""
    storm = WriteStormFileIO(get_file_io(str(tmp_path)), faults=10 ** 9)
    table = _storm_table(tmp_path, storm,
                         **{"write.retry.max-attempts": "2"})
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    try:
        with pytest.raises(TransientStoreError):
            w.write_dicts([{"id": i, "v": float(i)}
                           for i in range(2000)])
            w.prepare_commit()
        storm.left = 0                # the "storm" passes...
        with pytest.raises(RuntimeError, match="close this writer"):
            w.prepare_commit()        # ...but the writer is poisoned
    finally:
        w.close()
    assert table.snapshot_manager.latest_snapshot() is None


def test_error_cancels_queued_flushes():
    from paimon_tpu.parallel.write_pipeline import FlushPool
    pool = FlushPool(parallelism=2, max_bytes=1 << 30)
    ran = []
    gate = threading.Event()

    def slow_fail():
        gate.wait(5)
        raise ValueError("boom")

    pool.submit(("a", 0), 1, slow_fail)
    for i in range(5):
        pool.submit(("a", 0), 1, lambda i=i: ran.append(i))
    gate.set()
    with pytest.raises(ValueError, match="boom"):
        pool.drain()
    pool.shutdown()
    assert ran == [], "queued tasks after the failure must be cancelled"


# -- tier-1 hygiene: threads + byte budget -----------------------------------

def test_no_leaked_threads_after_write_close(tmp_path):
    before = _write_threads()
    table = make_random_engine_table(
        str(tmp_path / "t"), seed=1, engine="deduplicate",
        commits=1, extra_options=PIPED)
    assert not _wait_no_write_threads(before), \
        "leaked threads after close"
    assert _rows(table)


def test_flush_byte_budget_respected():
    from paimon_tpu.parallel.write_pipeline import FlushPool
    pool = FlushPool(parallelism=4, max_bytes=1)
    running = []

    def task():
        running.append(1)
        time.sleep(0.005)

    for i in range(8):
        pool.submit(("b", i), 1000, task)
    pool.drain()
    pool.shutdown()
    # a 1-byte budget degenerates to exactly one flush in flight
    assert pool.max_inflight_tasks == 1
    assert pool.peak_inflight_bytes <= 1000
    # an ample budget actually pipelines distinct buckets
    pool2 = FlushPool(parallelism=4, max_bytes=1 << 30)
    gate = threading.Event()
    for i in range(4):
        pool2.submit(("b", i), 1000, gate.wait)
    gate.set()
    pool2.drain()
    pool2.shutdown()
    assert pool2.max_inflight_tasks > 1


def test_same_bucket_flushes_never_overlap():
    """The per-key actor: two tasks of one bucket must run strictly in
    submission order, even with idle workers available."""
    from paimon_tpu.parallel.write_pipeline import FlushPool
    pool = FlushPool(parallelism=4, max_bytes=1 << 30)
    order = []
    lock = threading.Lock()

    def task(i):
        with lock:
            order.append(("start", i))
        time.sleep(0.002)
        with lock:
            order.append(("end", i))

    for i in range(6):
        pool.submit(("pt", 7), 1, lambda i=i: task(i))
    pool.drain()
    pool.shutdown()
    assert order == [(p, i) for i in range(6) for p in ("start", "end")]


@pytest.mark.parametrize("par", ["1", "4"])
def test_aggressive_spill_folding_exact_counts(tmp_path, par):
    """Regression: spill file names must be fold-proof.  With
    max-num-file-handles=2 every spill triggers a fold, and the old
    len(spills)/listdir-derived names could REPEAT after a fold shrank
    both — truncating a live run.  Counts must be exact on both the
    serial and pipelined paths (changelog-producer=input doubles as an
    exactly-once event counter)."""
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true",
                        "changelog-producer": "input",
                        "write-buffer-spillable": "true",
                        "sort-spill-buffer-size": "4 kb",
                        "local-sort.max-num-file-handles": "2",
                        "write-buffer-size": "64 kb",
                        "write.flush.parallelism": par,
                        "write.retry.backoff": "0"})
              .build())
    table = FileStoreTable.create(str(tmp_path / "t"), schema)
    wb = table.new_batch_write_builder()
    with wb.new_write() as w:
        for b in range(10):
            w.write_dicts([{"id": b * 1000 + i, "v": float(b)}
                           for i in range(200)])
        wb.new_commit().commit(w.prepare_commit())
    assert table.to_arrow().num_rows == 2000
    snap = table.snapshot_manager.latest_snapshot()
    assert snap.changelog_record_count == 2000


def test_spill_dirs_cleaned_on_pipelined_abort(tmp_path):
    """close() without prepare_commit joins the pool workers and then
    removes every spill temp dir the async spill tasks created."""
    import glob
    import os
    import tempfile as _tempfile

    def spill_dirs():
        return set(glob.glob(
            os.path.join(_tempfile.gettempdir(), "paimon-spill-*")))

    before = spill_dirs()
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true",
                        "write-buffer-size": "10kb",
                        "write-buffer-spillable": "true",
                        "write.flush.parallelism": "4"})
              .build())
    table = FileStoreTable.create(str(tmp_path / "t"), schema)
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    # well past the 4-batch prep lookahead so spills actually schedule
    for b in range(12):
        w.write_dicts([{"id": i, "v": float(b)} for i in range(400)])
    deadline = time.monotonic() + 5.0
    while not (spill_dirs() - before) and time.monotonic() < deadline:
        time.sleep(0.01)
    assert spill_dirs() - before, "no spill dir appeared mid-write"
    w.close()                     # abort: no prepare_commit
    assert spill_dirs() == before
    assert table.snapshot_manager.latest_snapshot() is None


# -- LPT scheduling ----------------------------------------------------------

def test_prepare_commit_schedules_largest_bucket_first(tmp_path, monkeypatch):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "4", "write-only": "true",
                        "write.flush.parallelism": "4"})
              .build())
    table = FileStoreTable.create(str(tmp_path / "t"), schema)
    wb = table.new_batch_write_builder()
    with wb.new_write() as w:
        # skew: bucket of id=0 gets 10x the rows of the others
        w.write_dicts([{"id": i % 4, "v": float(i)} for i in range(40)]
                      + [{"id": 0, "v": float(i)} for i in range(400)])
        store = w._write
        submitted = []
        pool = store.flush_pool()
        real_submit = pool.submit

        def recording(key, est, fn):
            submitted.append(est)
            return real_submit(key, est, fn)

        monkeypatch.setattr(pool, "submit", recording)
        wb.new_commit().commit(w.prepare_commit())
    assert len(submitted) >= 2
    assert submitted == sorted(submitted, reverse=True), \
        f"final flushes not scheduled largest-first: {submitted}"


# -- metrics -----------------------------------------------------------------

def test_write_metric_group_exposes_pipeline_counters(tmp_path):
    from paimon_tpu.metrics import (
        WRITE_FLUSHED_BYTES, WRITE_FLUSHES, global_registry,
    )
    group = global_registry().write_metrics()
    f0 = group.counter(WRITE_FLUSHES).count
    b0 = group.counter(WRITE_FLUSHED_BYTES).count
    make_random_engine_table(str(tmp_path / "t"), seed=3,
                             engine="deduplicate", commits=1,
                             extra_options=PIPED)
    assert group.counter(WRITE_FLUSHES).count > f0
    assert group.counter(WRITE_FLUSHED_BYTES).count > b0
    snap = global_registry().snapshot()
    assert "flushes" in snap.get("write", {})


# -- two-phase upload failures carry the path (satellite bugfix) -------------

def test_two_phase_upload_failure_names_the_file(tmp_path):
    """A failed part upload inside close_for_commit() must raise the
    SAME exception type with the destination path in the message — not
    the backend's generic error."""
    from paimon_tpu.fs.object_store import (
        LocalObjectStoreBackend, ObjectStoreFileIO,
    )

    class DiskFullBackend(LocalObjectStoreBackend):
        def put(self, key, data, if_none_match=False):
            raise RuntimeError("disk full")

    fio = ObjectStoreFileIO(DiskFullBackend(str(tmp_path / "bucket")))
    s = fio.new_two_phase_stream("objfs://tbl/bucket-0/data-123.parquet")
    s.write(b"payload")
    with pytest.raises(RuntimeError,
                       match=r"tbl/bucket-0/data-123\.parquet"):
        s.close_for_commit()


def test_two_phase_close_killable_via_failing_fileio(tmp_path):
    """FailingFileIO intercepts the close()-time upload as a mutating
    op, and the injected error names the destination path (crash
    sweeps kill mid-upload through this hook)."""
    from tests.failing_fileio import FailingFileIO, InjectedIOError
    fio = FailingFileIO(get_file_io(str(tmp_path)), "tp-close")
    FailingFileIO.reset("tp-close", 0)
    try:
        s = fio.new_two_phase_stream(str(tmp_path / "part-0.bin"))
        s.write(b"x")
        with pytest.raises(InjectedIOError, match=r"part-0\.bin"):
            s.close_for_commit()
    finally:
        FailingFileIO.disarm("tp-close")
    ops = [r.op for r in FailingFileIO.ops("tp-close")]
    assert "two_phase.close" in ops
