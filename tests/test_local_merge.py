"""Local merge: pre-shuffle hot-key dedup in the write path.

reference: mergetree/localmerge/HashMapLocalMerger.java (+ LocalMerger
SPI wired by MergeTreeWriter when local-merge-buffer-size is set).
"""

import numpy as np
import pyarrow as pa
import pytest

from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, RowKind


def lm_table(tmp_path, **opts):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "2", "write-only": "true",
                        "local-merge-buffer-size": "1mb", **opts})
              .build())
    return FileStoreTable.create(str(tmp_path / "t"), schema)


def test_hot_key_collapses_before_bucket_write(tmp_path):
    t = lm_table(tmp_path)
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    # 1000 updates of ONE hot key + some cold keys, many small writes
    for i in range(100):
        w.write_dicts([{"id": 7, "v": float(i)},
                       {"id": 1000 + i, "v": 1.0}])
    wb.new_commit().commit(w.prepare_commit())
    w.close()
    out = t.to_arrow().sort_by("id").to_pylist()
    assert [r for r in out if r["id"] == 7][0]["v"] == 99.0
    assert len(out) == 101
    # the hot key reached storage once: total stored rows == distinct
    files = [f for s in t.new_read_builder().new_scan().plan().splits
             for f in s.data_files]
    assert sum(f.row_count for f in files) == 101


def test_delete_wins_through_local_merge(tmp_path):
    t = lm_table(tmp_path)
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts([{"id": 1, "v": 1.0}, {"id": 2, "v": 2.0}])
    w.write_dicts([{"id": 1, "v": 1.0}], row_kinds=[RowKind.DELETE])
    wb.new_commit().commit(w.prepare_commit())
    w.close()
    out = t.to_arrow().to_pylist()
    assert [r["id"] for r in out] == [2]


def test_sequence_field_respected(tmp_path):
    t = lm_table(tmp_path, **{"sequence.field": "v"})
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts([{"id": 1, "v": 9.0}])
    w.write_dicts([{"id": 1, "v": 3.0}])     # lower sequence: loses
    wb.new_commit().commit(w.prepare_commit())
    w.close()
    assert t.to_arrow().to_pylist()[0]["v"] == 9.0


def test_buffer_flush_at_threshold(tmp_path):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true",
                        "local-merge-buffer-size": "4kb"})
              .build())
    t = FileStoreTable.create(str(tmp_path / "t"), schema)
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    for i in range(50):
        w.write_dicts([{"id": j, "v": float(i)} for j in range(64)])
    wb.new_commit().commit(w.prepare_commit())
    w.close()
    out = t.to_arrow()
    assert out.num_rows == 64
    assert set(out.column("v").to_pylist()) == {49.0}


def test_partitioned_rows_do_not_collapse(tmp_path):
    """The fold key must include partition columns: same id in two
    partitions is two rows (pk = (pt, id))."""
    from paimon_tpu.types import IntType
    schema = (Schema.builder()
              .column("pt", IntType(False))
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .partition_keys("pt")
              .primary_key("pt", "id")
              .options({"bucket": "1", "write-only": "true",
                        "local-merge-buffer-size": "1mb"})
              .build())
    t = FileStoreTable.create(str(tmp_path / "t"), schema)
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts([{"pt": 1, "id": 1, "v": 1.0},
                   {"pt": 2, "id": 1, "v": 2.0}])
    wb.new_commit().commit(w.prepare_commit())
    w.close()
    rows = sorted(t.to_arrow().to_pylist(), key=lambda r: r["pt"])
    assert [(r["pt"], r["v"]) for r in rows] == [(1, 1.0), (2, 2.0)]


def test_incompatible_configs_refuse(tmp_path):
    with pytest.raises(ValueError, match="local-merge"):
        t = lm_table(tmp_path, **{"merge-engine": "partial-update"})
        wb = t.new_batch_write_builder()
        wb.new_write()
    with pytest.raises(ValueError, match="changelog"):
        t = lm_table(tmp_path / "b", **{"changelog-producer": "input"})
        wb = t.new_batch_write_builder()
        wb.new_write()
