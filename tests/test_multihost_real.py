"""REAL multi-process multi-host execution: two OS processes bring up
jax's distributed runtime (Gloo-backed CPU collectives), form one
global 8-device mesh (4 local devices each), write disjoint partitions
of the SAME table (per-process commit users, CAS-serialized commits),
take deterministic split ownership, and reduce a globally-sharded
array with a cross-process collective.

This exercises the actual multi-host contract of
`parallel/multihost.py` — not the single-process degradation the other
multihost tests cover.  reference: SURVEY §5 "distributed
communication backend" (engine RPC/NCCL) -> jax distributed runtime +
XLA DCN collectives.

Root cause of the long-standing failure (triaged in the
tail-tolerance PR): jax 0.4.x ships the CPU backend with
cross-process collectives DISABLED — the distributed runtime, table
writes, CAS commits and split ownership all worked, but the final
jitted cross-process reduction died with "Multiprocess computations
aren't implemented on the CPU backend".  Fixed by opting into the
Gloo implementation (`jax_cpu_collectives_implementation=gloo`)
inside `multihost.initialize()` before the backend comes up.  For
jaxlib builds genuinely lacking Gloo the same error (or the flag's
absence) is detected in the worker output and the test SKIPS with the
recorded reason instead of failing tier-1.
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# capability marker: jaxlib builds without Gloo cross-process CPU
# collectives fail with exactly this (see module docstring) — an
# environment limit, not a paimon_tpu bug
_NO_CPU_COLLECTIVES = "Multiprocess computations aren't implemented"

WORKER = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
import jax
jax.config.update("jax_platforms", "cpu")

pid = int(sys.argv[1]); port = sys.argv[2]; table_path = sys.argv[3]
sys.path.insert(0, sys.argv[4])

from paimon_tpu.parallel import multihost as MH

idx, count = MH.initialize(f"127.0.0.1:{port}", 2, pid)
assert (idx, count) == (pid, 2)
assert jax.local_device_count() == 4 and jax.device_count() == 8

from paimon_tpu import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, IntType, VarCharType

ROWS = 128
schema = (Schema.builder()
          .column("part", VarCharType(nullable=False))
          .column("id", BigIntType(False))
          .column("v", IntType())
          .partition_keys("part")
          .primary_key("id", "part")
          .options({"bucket": "1"}).build())
if pid == 0:
    t = FileStoreTable.create(table_path, schema)
else:
    import time
    for _ in range(100):
        try:
            t = FileStoreTable.load(table_path)
            break
        except Exception:
            time.sleep(0.1)
    else:
        raise RuntimeError("table never appeared")

# each process commits its own partition; the snapshot CAS serializes
user = MH.distributed_write_commit_user()
assert user.endswith(f"p{pid}")
wb = t.new_batch_write_builder()
wb.commit_user = user
w = wb.new_write()
w.write_dicts([{"part": f"h{pid}", "id": i, "v": pid}
               for i in range(ROWS)])
wb.new_commit().commit(w.prepare_commit())
w.close()

# barrier: wait until BOTH commits are visible, then plan the same scan
import time
for _ in range(200):
    t = FileStoreTable.load(table_path)
    if (t.snapshot_manager.latest_snapshot() is not None
            and t.to_arrow().num_rows == 2 * ROWS):
        break
    time.sleep(0.1)
else:
    raise RuntimeError("second commit never became visible")

splits = sorted(t.new_read_builder().new_scan().plan().splits,
                key=lambda s: s.partition)
mine = MH.assign_splits(splits)
assert len(mine) == 1, "round-robin ownership must be disjoint"

import pyarrow as pa
read = t.new_read_builder().new_read()
local = pa.concat_tables([read.read_split(s) for s in mine],
                         promote_options="none")
assert local.num_rows == ROWS

# every process feeds ITS rows into one globally-sharded array; the
# jitted reductions run cross-process collectives over Gloo
import numpy as np
import jax.numpy as jnp
mesh = MH.global_mesh(("b",))
g = MH.process_local_batch(mesh, {
    "v": np.asarray(local.column("v").combine_chunks(), dtype=np.int32),
}, axis="b")
total = int(jax.jit(jnp.sum)(g["v"]))
n = int(np.prod(g["v"].shape))
assert n == 2 * ROWS, n
assert total == ROWS * 1, total        # pid-0 rows are 0, pid-1 rows are 1
print(f"proc {pid}: MULTIHOST-OK n={n} sum={total}", flush=True)
'''


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_multihost(tmp_path):
    port = _free_port()
    table_path = str(tmp_path / "t")
    worker_py = tmp_path / "worker.py"
    worker_py.write_text(WORKER)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)       # worker pins its own device count
    procs = [subprocess.Popen(
        [sys.executable, str(worker_py), str(pid), str(port),
         table_path, REPO],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in (0, 1)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=420)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    if any(_NO_CPU_COLLECTIVES in out for out in outs):
        pytest.skip(
            "jaxlib CPU backend lacks Gloo cross-process collectives "
            "(jax_cpu_collectives_implementation=gloo unavailable); "
            "multi-host CPU emulation cannot run here")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-4000:]}"
        assert f"proc {pid}: MULTIHOST-OK n=256 sum=128" in out, out[-2000:]
