"""Commit atomicity under injected IO failures + key-range conflicts.

reference test strategy (SURVEY §4): FailingFileIO drives
commit retry/abort atomicity; ConflictDetection covers concurrent
compactions writing the same level.
"""

import os

import pytest

from paimon_tpu.core.commit import CommitConflictError
from paimon_tpu.fs import get_file_io
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType
from tests.failing_fileio import FailingFileIO, InjectedIOError


def _schema(opts=None):
    return (Schema.builder()
            .column("id", BigIntType(False))
            .column("v", DoubleType())
            .primary_key("id")
            .options({"bucket": "1", "write-only": "true",
                      **(opts or {})})
            .build())


def _commit(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    sid = wb.new_commit().commit(w.prepare_commit())
    w.close()
    return sid


def test_commit_fails_atomically_then_succeeds(tmp_warehouse):
    """Every mutating step of a commit may die; the table must stay
    readable at its previous snapshot and a retry must succeed."""
    path = os.path.join(tmp_warehouse, "t")
    inner = get_file_io(path)
    table = FileStoreTable.create(path, _schema())
    _commit(table, [{"id": 1, "v": 1.0}])

    fio = FailingFileIO(inner, "commit-atomic")
    failing_table = FileStoreTable(fio, path, table.schema_manager.latest())

    # inject a failure at every successive mutating operation index until
    # one full commit succeeds
    for fail_after in range(0, 30):
        FailingFileIO.reset("commit-atomic", fail_after)
        wb = failing_table.new_batch_write_builder()
        w = wb.new_write()
        w.write_dicts([{"id": 2, "v": 2.0}])
        try:
            wb.new_commit().commit(w.prepare_commit())
            break
        except InjectedIOError:
            # aborted mid-commit: previous state must be intact
            assert table.to_arrow().num_rows in (1, 2)
            latest = table.snapshot_manager.latest_snapshot()
            assert latest is not None
        finally:
            FailingFileIO.disarm("commit-atomic")
    else:
        pytest.fail("commit never succeeded")

    rows = sorted(table.to_arrow().to_pylist(), key=lambda r: r["id"])
    assert rows == [{"id": 1, "v": 1.0}, {"id": 2, "v": 2.0}]


def test_snapshot_read_consistent_under_failures(tmp_warehouse):
    """A reader planning against an old snapshot keeps working while
    commits fail and retry around it."""
    path = os.path.join(tmp_warehouse, "t2")
    table = FileStoreTable.create(path, _schema())
    _commit(table, [{"id": i, "v": float(i)} for i in range(5)])
    plan = table.new_read_builder().new_scan().plan()

    fio = FailingFileIO(get_file_io(path), "reader-consistency")
    failing_table = FileStoreTable(fio, path, table.schema_manager.latest())
    FailingFileIO.reset("reader-consistency", 2)
    wb = failing_table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts([{"id": 99, "v": 99.0}])
    with pytest.raises(InjectedIOError):
        wb.new_commit().commit(w.prepare_commit())
    FailingFileIO.disarm("reader-consistency")

    out = table.new_read_builder().new_read().to_arrow(plan)
    assert out.num_rows == 5


def test_concurrent_compaction_key_overlap_conflict(tmp_warehouse):
    """Two compactions of the same bucket racing: the loser must get a
    CommitConflictError, not silently stack overlapping files at L>0."""
    from paimon_tpu.compact.manager import MergeTreeCompactManager
    from paimon_tpu.core.commit import FileStoreCommit
    from paimon_tpu.core.write import CommitMessage

    path = os.path.join(tmp_warehouse, "t3")
    table = FileStoreTable.create(path, _schema())
    _commit(table, [{"id": 1, "v": 1.0}])
    _commit(table, [{"id": 2, "v": 2.0}])

    scan = table.new_scan()
    snapshot = table.snapshot_manager.latest_snapshot()
    files = [e.file for e in scan.read_entries(snapshot)]

    def run_compaction():
        mgr = MergeTreeCompactManager(
            table.file_io, table.path, table.schema, table.options,
            (), 0, files, schema_manager=table.schema_manager)
        return mgr.compact(full=True)

    r1 = run_compaction()
    r2 = run_compaction()      # planned against the SAME snapshot

    commit = FileStoreCommit(table.file_io, table.path, table.schema,
                             table.options)
    commit.commit([CommitMessage((), 0, 1, compact_before=r1.before,
                                 compact_after=r1.after)])
    with pytest.raises(CommitConflictError):
        commit.commit([CommitMessage((), 0, 1, compact_before=r2.before,
                                     compact_after=r2.after)])
    # table unaffected by the failed commit
    assert table.to_arrow().num_rows == 2


def test_key_overlap_check_with_decoded_keys(tmp_warehouse):
    """Overlap detection must compare DECODED keys (BinaryRow bytes are
    not order-comparable): adds at L>0 with no delete conflicts."""
    import dataclasses

    from paimon_tpu.core.commit import FileStoreCommit
    from paimon_tpu.core.write import CommitMessage
    from paimon_tpu.data.binary_row import BinaryRowCodec
    from paimon_tpu.types import BigIntType

    path = os.path.join(tmp_warehouse, "t4")
    table = FileStoreTable.create(path, _schema())
    _commit(table, [{"id": 1, "v": 1.0}, {"id": 300, "v": 3.0}])
    table.compact(full=True)                    # live L-max file [1,300]

    scan = table.new_scan()
    snapshot = table.snapshot_manager.latest_snapshot()
    live = [e.file for e in scan.read_entries(snapshot)]
    top = max(live, key=lambda f: f.level)
    codec = BinaryRowCodec([BigIntType(False)])

    def fake_file(lo, hi):
        return dataclasses.replace(top,
                                   file_name="data-fake-" + str(lo)
                                   + ".parquet",
                                   min_key=codec.to_bytes((lo,)),
                                   max_key=codec.to_bytes((hi,)))

    commit = FileStoreCommit(table.file_io, table.path, table.schema,
                             table.options)
    # overlapping range [200, 400] x live [1, 300] -> conflict. NOTE
    # bytewise compare of 256 < 1 (little-endian) would MISS this.
    with pytest.raises(CommitConflictError):
        commit.commit([CommitMessage((), 0, 1,
                                     compact_after=[fake_file(200, 400)],
                                     compact_before=[])])
    # disjoint range [400, 500] commits fine
    sid = commit.commit([CommitMessage((), 0, 1,
                                       compact_after=[fake_file(400, 500)],
                                       compact_before=[])])
    assert sid is not None
