"""Row tracking + data evolution: row ids, column updates, row-id
deletes, sorted global index.

reference: operation/FileStoreCommitImpl.assignRowTracking (id
assignment), operation/DataEvolutionSplitRead.java (row-range column
merge), append/dataevolution/ (update path), globalindex/sorted/.
"""

import numpy as np
import pyarrow as pa
import pytest

from paimon_tpu import predicate as P
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, IntType, VarCharType


def tracked_table(tmp_path, **opts):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("name", VarCharType.string_type())
              .column("score", DoubleType())
              .options({"bucket": "-1", "row-tracking.enabled": "true",
                        **opts})
              .build())
    return FileStoreTable.create(str(tmp_path / "t"), schema)


def write(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    sid = wb.new_commit().commit(w.prepare_commit())
    w.close()
    return sid


def test_row_ids_assigned_densely_across_commits(tmp_path):
    t = tracked_table(tmp_path)
    write(t, [{"id": i, "name": f"n{i}", "score": float(i)}
              for i in range(10)])
    assert t.latest_snapshot().next_row_id == 10
    write(t, [{"id": 10 + i, "name": "x", "score": 0.0}
              for i in range(5)])
    assert t.latest_snapshot().next_row_id == 15
    out = t.to_arrow(with_row_ids=True).sort_by("_ROW_ID")
    assert out.column("_ROW_ID").to_pylist() == list(range(15))
    assert out.column("id").to_pylist() == list(range(15))


def test_file_meta_carries_first_row_id(tmp_path):
    t = tracked_table(tmp_path)
    write(t, [{"id": 1, "name": "a", "score": 1.0}])
    write(t, [{"id": 2, "name": "b", "score": 2.0}])
    files = sorted((f for s in t.new_read_builder().new_scan().plan()
                    .splits for f in s.data_files),
                   key=lambda f: f.first_row_id)
    assert [f.first_row_id for f in files] == [0, 1]


def test_update_columns_rewrites_only_touched_columns(tmp_path):
    t = tracked_table(tmp_path)
    write(t, [{"id": i, "name": f"n{i}", "score": float(i)}
              for i in range(20)])
    sid = t.update_columns(
        np.array([3, 7, 15]),
        pa.table({"score": pa.array([30.0, 70.0, 150.0])}))
    assert sid is not None
    out = t.to_arrow().sort_by("id").to_pylist()
    assert out[3]["score"] == 30.0 and out[7]["score"] == 70.0 \
        and out[15]["score"] == 150.0
    assert out[4]["score"] == 4.0
    # names untouched
    assert [r["name"] for r in out] == [f"n{i}" for i in range(20)]
    # the evolution file wrote only the score column
    files = [f for s in t.new_read_builder().new_scan().plan().splits
             for f in s.data_files]
    evo = [f for f in files if f.write_cols is not None]
    assert evo and all(f.write_cols == ["score"] for f in evo)
    base = [f for f in files if f.write_cols is None]
    assert all(f.first_row_id is not None for f in base)


def test_update_layering_newest_wins(tmp_path):
    t = tracked_table(tmp_path)
    write(t, [{"id": i, "name": "a", "score": 0.0} for i in range(8)])
    t.update_columns(np.array([2]), pa.table({"score": [20.0]}))
    t.update_columns(np.array([2, 3]),
                     pa.table({"score": [200.0, 30.0]}))
    out = t.to_arrow().sort_by("id").to_pylist()
    assert out[2]["score"] == 200.0 and out[3]["score"] == 30.0


def test_update_two_columns_and_row_ids_survive(tmp_path):
    t = tracked_table(tmp_path)
    write(t, [{"id": i, "name": "a", "score": 0.0} for i in range(6)])
    t.update_columns(
        np.array([1, 4]),
        pa.table({"name": ["u1", "u4"], "score": [1.0, 4.0]}))
    out = t.to_arrow(with_row_ids=True).sort_by("_ROW_ID").to_pylist()
    assert out[1]["name"] == "u1" and out[4]["score"] == 4.0
    assert [r["_ROW_ID"] for r in out] == list(range(6))


def test_update_unknown_row_id_raises(tmp_path):
    t = tracked_table(tmp_path)
    write(t, [{"id": 0, "name": "a", "score": 0.0}])
    with pytest.raises(ValueError, match="not found"):
        t.update_columns(np.array([99]), pa.table({"score": [1.0]}))


def test_delete_by_row_ids(tmp_path):
    t = tracked_table(tmp_path)
    write(t, [{"id": i, "name": "a", "score": float(i)}
              for i in range(10)])
    sid = t.delete_by_row_ids([2, 5, 9])
    assert sid is not None
    out = t.to_arrow(with_row_ids=True)
    assert sorted(out.column("_ROW_ID").to_pylist()) == \
        [0, 1, 3, 4, 6, 7, 8]


def test_delete_then_update_coexist(tmp_path):
    t = tracked_table(tmp_path)
    write(t, [{"id": i, "name": "a", "score": 0.0} for i in range(10)])
    t.delete_by_row_ids([0, 1])
    t.update_columns(np.array([5]), pa.table({"score": [55.0]}))
    out = t.to_arrow(with_row_ids=True).sort_by("_ROW_ID").to_pylist()
    assert [r["_ROW_ID"] for r in out] == list(range(2, 10))
    assert [r for r in out if r["_ROW_ID"] == 5][0]["score"] == 55.0


def test_delete_where_sees_updated_values(tmp_path):
    """Predicate deletes must evaluate the evolution-merged CURRENT
    values, not each physical file's stale columns."""
    t = tracked_table(tmp_path)
    write(t, [{"id": i, "name": "a", "score": float(i)}
              for i in range(10)])
    # row 3's score becomes 50; row 5 keeps score 5
    t.update_columns(np.array([3]), pa.table({"score": [50.0]}))
    t.delete_where(P.equal("score", 5.0))      # must delete row 5 only
    out = t.to_arrow(with_row_ids=True).sort_by("_ROW_ID").to_pylist()
    ids = [r["_ROW_ID"] for r in out]
    assert 5 not in ids and 3 in ids
    assert [r for r in out if r["_ROW_ID"] == 3][0]["score"] == 50.0
    # deleting by the NEW value must hit the updated row
    t.delete_where(P.equal("score", 50.0))
    ids = t.to_arrow(with_row_ids=True).column("_ROW_ID").to_pylist()
    assert 3 not in ids


def test_compact_folds_overlays_and_keeps_row_ids(tmp_path):
    """Data-evolution compaction: overlay groups fold into one full
    file per range, row ids stay put, DVs follow the rewritten file."""
    t = tracked_table(tmp_path)
    write(t, [{"id": i, "name": f"n{i}", "score": float(i)}
              for i in range(10)])
    t.update_columns(np.array([2, 7]),
                     pa.table({"score": [20.0, 70.0]}))
    t.update_columns(np.array([2]), pa.table({"name": ["u2"]}))
    t.delete_by_row_ids([5])
    before = t.to_arrow(with_row_ids=True).sort_by("_ROW_ID").to_pylist()
    files_before = sum(len(s.data_files) for s in
                      t.new_read_builder().new_scan().plan().splits)
    assert files_before == 3              # base + two overlays

    sid = t.compact(full=True)
    assert sid is not None
    assert t.latest_snapshot().commit_kind == "COMPACT"
    after = t.to_arrow(with_row_ids=True).sort_by("_ROW_ID").to_pylist()
    assert after == before                # same rows, same ids, no 5
    plan = t.new_read_builder().new_scan().plan()
    assert sum(len(s.data_files) for s in plan.splits) == 1
    f = plan.splits[0].data_files[0]
    assert f.first_row_id == 0 and f.write_cols is None

    # further updates keep working against the folded file
    t.update_columns(np.array([2]), pa.table({"score": [200.0]}))
    rows = t.to_arrow(with_row_ids=True).sort_by("_ROW_ID").to_pylist()
    assert [r for r in rows if r["_ROW_ID"] == 2][0]["score"] == 200.0

    # settled tables are a compaction no-op
    t.compact(full=True)
    assert t.compact(full=True) is None


def test_global_index_lookup_and_update_by_key(tmp_path):
    t = tracked_table(tmp_path)
    write(t, [{"id": 100 - i, "name": f"k{i}", "score": float(i)}
              for i in range(50)])
    gi = t.global_index("id")
    rids = gi.lookup([100, 51, 77, 9999])
    out = t.to_arrow(with_row_ids=True)
    by_rid = {r["_ROW_ID"]: r for r in out.to_pylist()}
    assert by_rid[rids[0]]["id"] == 100
    assert by_rid[rids[1]]["id"] == 51
    assert by_rid[rids[2]]["id"] == 77
    assert rids[3] == -1

    # update-by-key: index -> row ids -> column update
    targets = gi.lookup([80, 60])
    t.update_columns(targets, pa.table({"score": [800.0, 600.0]}))
    out = t.to_arrow(predicate=P.in_("id", [80, 60])).to_pylist()
    assert sorted(r["score"] for r in out) == [600.0, 800.0]


def test_global_index_rebuild_on_new_snapshot(tmp_path):
    t = tracked_table(tmp_path)
    write(t, [{"id": 1, "name": "a", "score": 0.0}])
    gi = t.global_index("id")
    assert gi.lookup([1])[0] == 0
    write(t, [{"id": 2, "name": "b", "score": 0.0}])
    gi2 = t.global_index("id")        # stale meta -> rebuild
    assert gi2.lookup([2])[0] == 1
    # cached load when snapshot unchanged
    gi3 = t.global_index("id")
    assert gi3.snapshot_id == gi2.snapshot_id


def test_row_ids_with_projection(tmp_path):
    t = tracked_table(tmp_path)
    write(t, [{"id": i, "name": "a", "score": 0.0} for i in range(3)])
    out = t.to_arrow(projection=["id"], with_row_ids=True)
    assert out.column_names == ["id", "_ROW_ID"]
