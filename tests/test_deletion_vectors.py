"""Deletion vectors: roaring wire format + DELETE FROM write path.

reference: deletionvectors/BitmapDeletionVector.java (MAGIC 1581511376,
RoaringBitmap32 portable serialization), DeletionVectorsIndexFile.java
(VERSION byte + [len][magic|bitmap][crc] entries).
"""

import os
import struct
import zlib

import numpy as np
import pytest

from paimon_tpu import predicate as P
from paimon_tpu.index.deletion_vector import (
    MAGIC_V1, DeletionVector, DeletionVectorsIndexFile,
)
from paimon_tpu.index.roaring import (
    deserialize_roaring32, serialize_roaring32,
)
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType


def test_roaring_roundtrip_array_container():
    pos = np.array([1, 5, 7, 65536, 65537, 1 << 20], dtype=np.uint32)
    data = serialize_roaring32(pos)
    # cookie 12346 little-endian
    assert struct.unpack_from("<I", data, 0)[0] == 12346
    out = deserialize_roaring32(data)
    assert np.array_equal(out, pos)


def test_roaring_roundtrip_bitmap_container():
    pos = np.arange(0, 10000, dtype=np.uint32)    # card > 4096 -> bitmap
    data = serialize_roaring32(pos)
    out = deserialize_roaring32(data)
    assert np.array_equal(out, pos)


def test_roaring_reads_run_container():
    """Hand-build a run-container payload (cookie 12347) and decode it."""
    n = 1
    cookie = 12347 | ((n - 1) << 16)
    run_flags = bytes([1])
    keycards = struct.pack("<HH", 0, 9)           # key 0, card 10
    body = struct.pack("<H", 1) + struct.pack("<HH", 3, 9)  # run 3..12
    data = struct.pack("<I", cookie) + run_flags + keycards + body
    out = deserialize_roaring32(data)
    assert np.array_equal(out, np.arange(3, 13, dtype=np.uint32))


def test_dv_wire_layout():
    dv = DeletionVector(np.array([2, 4, 9]))
    blob = dv.serialize()
    (length,) = struct.unpack_from(">i", blob, 0)
    (magic,) = struct.unpack_from(">i", blob, 4)
    assert magic == MAGIC_V1 == 1581511376
    body = blob[4:4 + length]
    (crc,) = struct.unpack_from(">I", blob, 4 + length)
    assert crc == (zlib.crc32(body) & 0xFFFFFFFF)
    back = DeletionVector.deserialize(blob)
    assert back.positions.tolist() == [2, 4, 9]


def test_dv_index_file_roundtrip(tmp_path):
    from paimon_tpu.fs import get_file_io

    fio = get_file_io(str(tmp_path))
    idx = DeletionVectorsIndexFile(fio, str(tmp_path))
    dvs = {"data-a.parquet": DeletionVector(np.array([0, 3])),
           "data-b.parquet": DeletionVector(np.array([7]))}
    name, size, ranges = idx.write(dvs)
    raw = open(os.path.join(str(tmp_path), name), "rb").read()
    assert raw[0] == 1                            # VERSION_ID_V1
    assert len(raw) == size
    back = idx.read(name, ranges)
    assert back["data-a.parquet"].positions.tolist() == [0, 3]
    assert back["data-b.parquet"].positions.tolist() == [7]
    assert ranges["data-a.parquet"][2] == 2       # cardinality


def _commit(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    wb.new_commit().commit(w.prepare_commit())
    w.close()


def test_delete_where_append_table_uses_dvs(tmp_warehouse):
    schema = (Schema.builder()
              .column("id", BigIntType())
              .column("v", DoubleType())
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "t"), schema)
    _commit(table, [{"id": i, "v": float(i)} for i in range(10)])
    _commit(table, [{"id": i, "v": float(i)} for i in range(10, 20)])

    sid = table.delete_where(P.less_than("id", 5))
    assert sid is not None
    out = sorted(table.to_arrow().column("id").to_pylist())
    assert out == list(range(5, 20))
    # data files untouched (positions masked, not rewritten)
    snap = table.snapshot_manager.latest_snapshot()
    assert snap.index_manifest

    # second delete merges with existing DVs
    table.delete_where(P.equal("id", 17))
    out = sorted(table.to_arrow().column("id").to_pylist())
    assert out == [i for i in range(5, 20) if i != 17]

    # no-op delete commits nothing
    before = table.snapshot_manager.latest_snapshot_id()
    assert table.delete_where(P.equal("id", 999)) is None
    assert table.snapshot_manager.latest_snapshot_id() == before


def test_delete_where_pk_table_writes_retractions(tmp_warehouse):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "1"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "p"), schema)
    _commit(table, [{"id": i, "v": float(i)} for i in range(6)])
    table.delete_where(P.greater_than("v", 3.5))
    assert sorted(table.to_arrow().column("id").to_pylist()) == \
        [0, 1, 2, 3]


def test_roaring_rejects_out_of_range():
    with pytest.raises(ValueError):
        serialize_roaring32(np.array([1 << 32], dtype=np.int64))


def test_dv_crc_validation():
    dv = DeletionVector(np.array([1, 2, 3]))
    blob = bytearray(dv.serialize())
    blob[10] ^= 0xFF                      # corrupt the bitmap body
    with pytest.raises(ValueError):
        DeletionVector.deserialize(bytes(blob))


def test_delete_where_conflict_replans(tmp_warehouse):
    """A concurrent commit between DV planning and publish forces a
    replan instead of silently dropping it."""
    schema = (Schema.builder()
              .column("id", BigIntType())
              .column("v", DoubleType())
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "c"), schema)
    _commit(table, [{"id": i, "v": float(i)} for i in range(10)])

    # interleave by committing between plan and commit: patch the commit
    # entry point used inside _delete_append_dv_once
    from paimon_tpu.core import commit as commit_mod
    real_commit = commit_mod.FileStoreCommit.commit
    calls = {"n": 0}

    def flaky_commit(self, *a, **k):
        if calls["n"] == 0 and k.get("expected_latest_id") is not None:
            calls["n"] += 1
            _commit(table, [{"id": 100, "v": 100.0}])
        return real_commit(self, *a, **k)

    commit_mod.FileStoreCommit.commit = flaky_commit
    try:
        sid = table.delete_where(P.less_than("id", 3))
    finally:
        commit_mod.FileStoreCommit.commit = real_commit
    assert sid is not None
    ids = sorted(table.to_arrow().column("id").to_pylist())
    assert ids == [3, 4, 5, 6, 7, 8, 9, 100]
