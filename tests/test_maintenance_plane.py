"""Mesh-sharded maintenance plane (parallel/maintenance_plane.py):
lease-based, takeover-capable bucket ownership for compaction, expiry
and changelog serving.

Fake-topology layer: planes with explicit (process_index,
process_count) over one table in ONE process drive the lease
protocol, the failure detector (injected clocks), deterministic
takeover, the scheduling filters, the stamped-commit recovery
regression and the fsck ownership check without a mesh.  The
in-process two-daemon takeover test at the bottom is the single-box
rehearsal of the real 2-process gloo soak
(tests/test_multihost_maintenance.py).
"""

import time

import pytest

from paimon_tpu.metrics import (
    MULTIHOST_LEASE_EXPIRED, MULTIHOST_LEASE_RENEWALS,
    MULTIHOST_MAINTENANCE_TAKEOVERS, MULTIHOST_OWNED_BUCKETS,
    global_registry,
)
from paimon_tpu.parallel.distributed import (
    OwnershipError, OwnershipMap, lease_props, merge_lease_view,
    owner_of, resume_ownership_map,
)
from paimon_tpu.parallel.maintenance_plane import MaintenancePlane
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, IntType


def _schema(buckets=4, extra=None):
    opts = {"bucket": str(buckets)}
    opts.update(extra or {})
    return (Schema.builder()
            .column("id", BigIntType(False))
            .column("v", IntType())
            .primary_key("id")
            .options(opts)
            .build())


def _table(tmp_path, name="t", buckets=4, extra=None):
    return FileStoreTable.create(str(tmp_path / name),
                                 _schema(buckets, extra))


def _write_commit(table, rows, user=None):
    wb = table.new_batch_write_builder()
    if user:
        wb.commit_user = user
    with wb.new_write() as w:
        w.write_dicts(rows)
        return wb.new_commit().commit(w.prepare_commit())


# -- ownership with a dead set ------------------------------------------------

class TestTakeoverOwnership:
    def test_dead_owner_reassigned_to_survivors_deterministically(self):
        n = 4
        dead = frozenset({2})
        owners = [owner_of((), b, n, dead) for b in range(64)]
        # twice the same map, nothing owned by the dead process,
        # survivors all participate
        assert owners == [owner_of((), b, n, dead) for b in range(64)]
        assert 2 not in owners
        assert set(owners) <= {0, 1, 3}
        # only groups the dead process owned move; everything else is
        # byte-stable across the takeover
        for b in range(64):
            if owner_of((), b, n) != 2:
                assert owner_of((), b, n, dead) == owner_of((), b, n)

    def test_every_survivor_computes_the_same_successor_map(self):
        # the whole point: N survivors adopt with NO communication
        a = OwnershipMap(3, 4, 32).with_dead({1})
        b = OwnershipMap(3, 4, 32).with_dead({1})
        assert a == b
        assert a.version == 4
        assert [a.owner_of((), x) for x in range(32)] == \
            [b.owner_of((), x) for x in range(32)]

    def test_with_dead_idempotent_and_monotone(self):
        m = OwnershipMap(1, 3, 8)
        m2 = m.with_dead({2})
        assert m2.version == 2 and m2.dead == frozenset({2})
        assert m2.with_dead({2}) is m2          # no spurious bump
        m3 = m2.with_dead({0})
        assert m3.version == 3
        assert m3.dead == frozenset({0, 2})
        assert m3.alive() == [1]

    def test_all_dead_raises(self):
        with pytest.raises(OwnershipError, match="dead"):
            owner_of((), 0, 2, frozenset({0, 1}))

    def test_dead_set_roundtrips_through_properties(self):
        from paimon_tpu.parallel.distributed import _map_from_properties
        m = OwnershipMap(5, 4, 16, frozenset({1, 3}))
        assert _map_from_properties(m.to_properties()) == m


# -- leases -------------------------------------------------------------------

class TestLeases:
    def test_lease_props_renew_self_and_carry_view(self):
        p = lease_props(1, 500, {0: 100, 1: 200})
        assert p == {"multihost.lease.p0": "100",
                     "multihost.lease.p1": "500"}
        # never regress own entry
        p = lease_props(1, 50, {1: 200})
        assert p["multihost.lease.p1"] == "200"

    def test_merge_lease_view_max_merges_recent_chain(self, tmp_path):
        t = _table(tmp_path)
        # two committers race: each stamps the view IT knew; the
        # reader folds the window with max()
        from paimon_tpu.core.commit import FileStoreCommit
        c = FileStoreCommit(t.file_io, t.path, t.schema, t.options,
                            commit_user="x")
        c.commit([], properties=lease_props(0, 1000, {1: 50}),
                 force_create=True)
        c.commit([], properties=lease_props(1, 800, {0: 900}),
                 force_create=True)
        view = merge_lease_view(FileStoreTable.load(t.path))
        assert view == {0: 1000, 1: 800}


# -- the plane ----------------------------------------------------------------

def _plane(table, pid, count, clock, base="maint"):
    return MaintenancePlane(table, base_user=base, process_index=pid,
                            process_count=count, clock=clock)


class TestMaintenancePlane:
    def test_detector_declares_stale_peer_once(self, tmp_path):
        t = _table(tmp_path, extra={"multihost.lease.timeout": "1000",
                                    "multihost.lease.interval": "100"})
        now = {"ms": 10_000}
        clock = lambda: now["ms"]                          # noqa: E731
        g = global_registry().multihost_metrics()
        expired0 = g.counter(MULTIHOST_LEASE_EXPIRED).count
        takeovers0 = g.counter(MULTIHOST_MAINTENANCE_TAKEOVERS).count

        p0 = _plane(t, 0, 2, clock)
        p0.ensure_lease()
        p1 = _plane(FileStoreTable.load(t.path), 1, 2, clock)
        p1.ensure_lease()
        p0.refresh_view()
        # both healthy: no verdicts
        assert p0.detect_expired() == frozenset()
        # p1 goes silent past the timeout
        now["ms"] += 5_000
        assert p0.detect_expired() == frozenset({1})
        # declared exactly once (the caller is acting on it)
        assert p0.detect_expired() == frozenset()
        assert g.counter(MULTIHOST_LEASE_EXPIRED).count == expired0 + 1
        # adoption bumps the generation and the owned gauge jumps
        owned_before = g.gauge(MULTIHOST_OWNED_BUCKETS).value
        v = p0.ownership.version
        p0.adopt({1})
        assert p0.ownership.version == v + 1
        assert p0.ownership.dead == frozenset({1})
        assert g.counter(MULTIHOST_MAINTENANCE_TAKEOVERS).count == \
            takeovers0 + 1
        assert g.gauge(MULTIHOST_OWNED_BUCKETS).value > owned_before
        assert g.gauge(MULTIHOST_OWNED_BUCKETS).value == 4

    def test_own_renewals_keep_self_alive(self, tmp_path):
        t = _table(tmp_path, extra={"multihost.lease.timeout": "1000"})
        now = {"ms": 0}
        p0 = _plane(t, 0, 2, lambda: now["ms"])
        p0.ensure_lease()
        now["ms"] += 10_000
        assert 0 not in p0.expired_processes()   # never self

    def test_heartbeat_renews_idle_lease_and_stamps(self, tmp_path):
        t = _table(tmp_path, extra={"multihost.lease.interval": "100",
                                    "multihost.lease.timeout": "1000"})
        now = {"ms": 1_000}
        p0 = _plane(t, 0, 2, lambda: now["ms"])
        g = global_registry().multihost_metrics()
        renewals0 = g.counter(MULTIHOST_LEASE_RENEWALS).count
        assert p0.ensure_lease() is not None
        assert not p0.heartbeat_due()
        assert p0.maybe_heartbeat() is None      # fresh: not due
        now["ms"] += 500
        sid = p0.maybe_heartbeat()
        assert sid is not None
        assert g.counter(MULTIHOST_LEASE_RENEWALS).count == \
            renewals0 + 2
        fresh = FileStoreTable.load(t.path)
        # the heartbeat snapshot carries ownership + lease stamps
        snap = fresh.latest_snapshot()
        assert snap.properties["multihost.ownership.version"] == "1"
        assert snap.properties["multihost.lease.p0"] == str(now["ms"])
        assert merge_lease_view(fresh)[0] == now["ms"]
        # heartbeats are disabled on single-process planes
        p_solo = _plane(_table(tmp_path, "solo"), 0, 1,
                        lambda: now["ms"])
        assert p_solo.maybe_heartbeat() is None

    def test_plane_recorded_dead_self_enters_rejoining(self, tmp_path):
        t = _table(tmp_path, extra={"multihost.lease.timeout": "500"})
        now = {"ms": 0}
        p0 = _plane(t, 0, 2, lambda: now["ms"])
        p0.ensure_lease()
        p0.adopt({1})
        p0.maybe_heartbeat() if p0.heartbeat_due() else \
            p0.ensure_lease()                    # publish the map
        # default: the resurrected host constructs in the rejoining
        # state — it owns nothing and waits to be readmitted
        p1 = _plane(FileStoreTable.load(t.path), 1, 2,
                    lambda: now["ms"])
        assert p1.rejoining
        assert not any(p1.owns((), b) for b in range(4))
        # opting out restores the refusal
        with pytest.raises(OwnershipError, match="DEAD"):
            _plane(FileStoreTable.load(
                t.path,
                dynamic_options={"multihost.rejoin.enabled": "false"}),
                1, 2, lambda: now["ms"])
        # survivors resume the recorded generation, dead set included
        p0b = _plane(FileStoreTable.load(t.path), 0, 2,
                     lambda: now["ms"])
        assert p0b.ownership.dead == frozenset({1})

    def test_rejoin_request_readmit_round_trip(self, tmp_path):
        t = _table(tmp_path, extra={"multihost.lease.timeout": "500"})
        now = {"ms": 0}
        p0 = _plane(t, 0, 2, lambda: now["ms"])
        p0.ensure_lease()
        p0.adopt({1})
        p0.ensure_lease()                        # publish the map
        p1 = _plane(FileStoreTable.load(t.path), 1, 2,
                    lambda: now["ms"])
        assert p1.rejoining
        assert p1.request_rejoin() is not None
        # every survivor computes the same pending set from the store;
        # the elected (lowest alive) one grants
        assert p0.pending_rejoin_requests() == frozenset({1})
        assert p0.owns_rejoin_grant()
        readmitted = p0.readmit(p0.pending_rejoin_requests())
        assert readmitted == frozenset({1})
        assert p0.ownership.dead == frozenset()
        assert p0.ownership.version == 3         # bring-up, death, rejoin
        # readmission is exactly-once: a retry is a no-op
        assert p0.readmit({1}) == frozenset()
        p0.ensure_lease()                        # publish the grant
        # the rejoiner observes the generation where it is alive again
        assert p1.refresh_ownership()
        assert not p1.rejoining
        assert p1.ownership.version == 3
        # warm rejoin: p1 got exactly its old primary groups back
        assert {b for b in range(4) if p1.owns((), b)} == \
            {b for b in range(4)
             if OwnershipMap(1, 2, 4).owner_of((), b) == 1}
        # the full generation history is persisted and exact
        fresh = FileStoreTable.load(t.path)
        from paimon_tpu.parallel.distributed import (
            resume_generation_history)
        hist = resume_generation_history(fresh)
        assert [m.version for m in hist.entries] == [1, 2, 3]
        assert hist.at(2).dead == frozenset({1})
        assert hist.at(3).dead == frozenset()
        # a stale request from a re-dead host ages out with its lease
        p0.adopt({1})
        now["ms"] += 10_000
        assert p0.pending_rejoin_requests() == frozenset()

    def test_expiry_election_fails_over(self, tmp_path):
        t = _table(tmp_path)
        now = {"ms": 0}
        p0 = _plane(t, 0, 2, lambda: now["ms"])
        p1 = _plane(FileStoreTable.load(t.path), 1, 2,
                    lambda: now["ms"])
        assert p0.owns_expiry() and not p1.owns_expiry()
        p1.adopt({0})
        assert p1.owns_expiry()

    def test_group_filters_partition_the_table(self, tmp_path):
        t = _table(tmp_path, buckets=8)
        p0 = _plane(t, 0, 2, lambda: 0)
        p1 = _plane(FileStoreTable.load(t.path), 1, 2, lambda: 0)
        owned0 = {b for b in range(8) if p0.owns((), b)}
        owned1 = {b for b in range(8) if p1.owns((), b)}
        assert owned0 | owned1 == set(range(8))
        assert owned0.isdisjoint(owned1)


# -- stamped-commit recovery (satellite regression) ---------------------------

class TestStampedRecovery:
    def test_resume_survives_long_foreign_maintenance_run(self,
                                                          tmp_path):
        """Satellite 1: a long run of maintenance-only commits under
        OTHER commit users used to push the last ownership-stamped
        snapshot past resume_ownership_map's 64-snapshot walk, and
        the plane restarted at a version that already meant something
        else.  The walk now continues to the earliest retained
        snapshot."""
        t = _table(tmp_path, extra={"snapshot.num-retained.min": "200",
                                    "snapshot.num-retained.max": "200"})
        plane = t.new_distributed_write(process_index=0,
                                        process_count=2)
        plane.write_dicts([{"id": i, "v": 0} for i in range(50)])
        plane.commit()
        plane.close()
        # 70 foreign snapshots (uuid commit users, no stamps)
        for k in range(70):
            _write_commit(FileStoreTable.load(t.path),
                          [{"id": 1000 + k, "v": k}])
        resumed = resume_ownership_map(FileStoreTable.load(t.path))
        assert resumed is not None and resumed.version == 1
        # and the plane resumes the SAME generation, no spurious bump
        again = FileStoreTable.load(t.path).new_distributed_write(
            process_index=0, process_count=2)
        assert again.ownership.version == 1
        again.close()

    def test_plane_issued_compaction_commits_are_stamped(self,
                                                         tmp_path):
        """The other half of the satellite: compaction issued BY the
        plane stamps lease + ownership, so plane-only traffic keeps
        the tip stamped (one-snapshot recovery walk)."""
        t = _table(tmp_path, extra={
            "num-sorted-run.compaction-trigger": "1"})
        now = {"ms": 5_000}
        plane = _plane(t, 0, 2, lambda: now["ms"])
        for k in range(3):
            _write_commit(
                FileStoreTable.load(
                    t.path, dynamic_options={"write-only": "true"}),
                [{"id": i, "v": k} for i in range(40)])
        props = dict(plane.stamp_properties())
        sid = FileStoreTable.load(t.path).compact(
            full=True, group_filter=plane.group_filter(),
            commit_user=plane.commit_user,
            properties_provider=plane.stamp_properties)
        assert sid is not None
        snap = FileStoreTable.load(t.path).snapshot_manager \
            .snapshot(sid)
        assert snap.commit_user == plane.commit_user
        assert snap.properties["multihost.ownership.version"] == \
            props["multihost.ownership.version"]
        assert "multihost.lease.p0" in snap.properties
        # the compaction touched ONLY owned groups
        fresh = FileStoreTable.load(t.path)
        scan = fresh.new_scan()
        for e in scan.read_entries(fresh.latest_snapshot()):
            part = tuple(scan._partition_codec.from_bytes(e.partition))
            if e.file.level and e.file.level > 0:
                assert plane.owns(part, e.bucket), \
                    f"compacted foreign bucket {e.bucket}"


# -- fsck ownership check -----------------------------------------------------

class TestFsckOwnership:
    def _stamped_commit(self, table, user, props, rows):
        from paimon_tpu.core.commit import FileStoreCommit
        c = FileStoreCommit(table.file_io, table.path, table.schema,
                            table.options, commit_user=user)
        return c.commit([], properties=props, force_create=True)

    def test_version_regression_flagged(self, tmp_path):
        t = _table(tmp_path)
        m1 = OwnershipMap(1, 2, 4)
        m2 = OwnershipMap(2, 2, 4, frozenset({1}))
        self._stamped_commit(t, "a", m1.to_properties(), [])
        self._stamped_commit(t, "a", m2.to_properties(), [])
        self._stamped_commit(t, "b", m1.to_properties(), [])  # stale!
        report = FileStoreTable.load(t.path).fsck()
        kinds = report.kinds()
        assert "ownership-inconsistency" in kinds
        assert any("regressed" in v.detail
                   for v in report.by_kind("ownership-inconsistency"))

    def test_one_version_two_maps_flagged(self, tmp_path):
        t = _table(tmp_path)
        self._stamped_commit(
            t, "a", OwnershipMap(3, 2, 4).to_properties(), [])
        self._stamped_commit(
            t, "b", OwnershipMap(3, 4, 4).to_properties(), [])
        report = FileStoreTable.load(t.path).fsck()
        viols = report.by_kind("ownership-inconsistency")
        assert viols and any("two different maps" in v.detail
                             for v in viols)

    def test_healthy_takeover_chain_is_clean(self, tmp_path):
        t = _table(tmp_path)
        m1 = OwnershipMap(1, 2, 4)
        self._stamped_commit(t, "a", m1.to_properties(), [])
        self._stamped_commit(t, "a", m1.to_properties(), [])
        m2 = m1.with_dead({1})
        self._stamped_commit(t, "a", m2.to_properties(), [])
        assert FileStoreTable.load(t.path).fsck().ok


# -- expire floor -------------------------------------------------------------

def test_expire_respects_min_retained_snapshot_floor(tmp_path):
    t = _table(tmp_path, extra={"snapshot.num-retained.min": "1",
                                "snapshot.num-retained.max": "2"})
    for k in range(8):
        _write_commit(FileStoreTable.load(t.path),
                      [{"id": k, "v": k}])
    fresh = FileStoreTable.load(t.path)
    # without the floor, retain_max=2 would expire everything < 7
    result = fresh.expire_snapshots(older_than_ms=2 ** 62,
                                    min_retained_snapshot_id=3)
    assert result.expired_snapshots == [1, 2]
    sm = FileStoreTable.load(t.path).snapshot_manager
    assert sm.earliest_snapshot_id() == 3


# -- review-fix regressions ---------------------------------------------------

class TestReviewFixes:
    def _daemon(self, t, pid, count, base="stream-daemon",
                source=None):
        from paimon_tpu.cdc.source import MemoryCdcSource
        from paimon_tpu.service.stream_daemon import StreamDaemon
        plane = MaintenancePlane(t, base_user=base, process_index=pid,
                                 process_count=count)
        return StreamDaemon(t, source or MemoryCdcSource(),
                            commit_user=base, plane=plane)

    def test_reconcile_queues_peer_published_takeovers(self, tmp_path):
        """A 3-host mesh where a faster survivor publishes the
        takeover first: this host's detector suppresses the peer
        (already in ownership.dead), but its OWN re-sharded share is
        still unbackfilled — the reconciliation must queue it from
        the global map minus the local ledger."""
        t = _table(tmp_path)
        d = self._daemon(t, 0, 3)
        d.plane.ownership = d.plane.ownership.with_dead({2})
        assert d.plane.detect_expired() == frozenset()  # suppressed
        d._reconcile_adoptions()
        assert d._pending_adoptions == [2]
        d._reconcile_adoptions()                        # idempotent
        assert d._pending_adoptions == [2]
        # durably adopted: nothing left to queue
        d._pending_adoptions.clear()
        d._ingest_dead = frozenset({2})
        d._reconcile_adoptions()
        assert d._pending_adoptions == []

    def test_takeover_disabled_freezes_ownership(self, tmp_path):
        t = _table(tmp_path, extra={
            "multihost.maintenance.takeover": "false"})
        d = self._daemon(t, 0, 2)
        d.plane.ownership = d.plane.ownership.with_dead({1})
        assert not d.plane.takeover_enabled
        d._reconcile_adoptions({1})
        assert d._pending_adoptions == []
        # the standalone path also freezes
        assert d.plane.detect_and_take_over() == frozenset()

    def test_stamp_refreshes_generation_from_store(self, tmp_path):
        """A commit losing its CAS race to a peer's takeover
        re-evaluates the provider per attempt; the stamp must carry
        the NEW generation read back from the store, not the stale
        in-memory one (which would land an ownership regression at
        the tip)."""
        t = _table(tmp_path)
        now = {"ms": 0}
        p0 = _plane(t, 0, 3, lambda: now["ms"])
        p1 = _plane(FileStoreTable.load(t.path), 1, 3,
                    lambda: now["ms"])
        p0.adopt({2})
        p0.ensure_lease()          # publishes v2 dead={2}
        stamped = p1.stamp_properties()
        assert stamped["multihost.ownership.version"] == "2"
        assert stamped["multihost.ownership.dead"] == "2"
        assert p1.ownership.version == 2

    def test_expiry_floor_protects_pending_adoption(self, tmp_path):
        """A dead peer's newest offset checkpoint stays protected
        until EVERY alive process's ledger covers it — one survivor's
        published takeover must not let expiry drop the offset the
        other survivor's pending backfill still needs."""
        from paimon_tpu.core.commit import FileStoreCommit

        t = _table(tmp_path)

        def stamp(user, props):
            c = FileStoreCommit(t.file_io, t.path, t.schema,
                                t.options, commit_user=user)
            c.commit([], properties=props, force_create=True)

        m1 = OwnershipMap(1, 3, 4)
        base = {"stream.source.offset": "10",
                "stream.ingest.ts-ms": "1"}
        for p in (0, 1, 2):
            stamp(f"stream-daemon-p{p}",
                  {**base, **m1.to_properties()})
        dead_ckpt = FileStoreTable.load(t.path) \
            .snapshot_manager.latest_snapshot_id()   # p2's checkpoint
        # p0 publishes ITS takeover of p2 (ledger covers 2)...
        m2 = m1.with_dead({2})
        stamp("stream-daemon-p0",
              {**m2.to_properties(), "stream.adopted": "2",
               "stream.source.offset": "11",
               "stream.ingest.ts-ms": "2"})
        # ...but p1's ledger does NOT cover 2 yet
        fresh = FileStoreTable.load(t.path)
        d1 = self._daemon(fresh, 1, 3)
        floor = d1._expiry_floor(fresh)
        assert floor is not None and floor <= dead_ckpt, \
            (floor, dead_ckpt)
        # once p1's ledger covers 2, the dead checkpoint is released
        stamp("stream-daemon-p1",
              {**m2.to_properties(), "stream.adopted": "2",
               "stream.source.offset": "11",
               "stream.ingest.ts-ms": "3"})
        fresh2 = FileStoreTable.load(fresh.path)
        d1b = self._daemon(fresh2, 1, 3)
        floor2 = d1b._expiry_floor(fresh2)
        assert floor2 is not None and floor2 > dead_ckpt

    def test_adoption_backfills_through_poll_position(self, tmp_path):
        """The backfill upper bound is the survivor's POLL position,
        not its committed offset: events polled-but-uncheckpointed
        had their adopted-group share filtered out while the dead
        peer still owned it, and forward ingest resumes past them —
        stopping the backfill at the committed offset would lose them
        forever.  Reproduced by giving the survivor a checkpoint
        interval longer than the soak, so its committed offset stays
        far behind its poll position at adoption time."""
        import pyarrow  # noqa: F401  (environment guard)

        from paimon_tpu.cdc.source import MemoryCdcSource
        from paimon_tpu.service.stream_daemon import StreamDaemon

        opts = {
            "stream.compaction.interval": "80",
            "stream.ingest.poll-interval": "10",
            "stream.serve.poll-interval": "15",
            "multihost.lease.interval": "120",
            "multihost.lease.timeout": "900",
            "snapshot.num-retained.min": "100000",
            "snapshot.num-retained.max": "100000",
        }
        schema = (Schema.builder()
                  .column("id", BigIntType(False))
                  .column("v", BigIntType())
                  .primary_key("id")
                  .options({"bucket": "4", **opts})
                  .build())
        t = FileStoreTable.create(str(tmp_path / "gap"), schema)
        source = MemoryCdcSource()
        expected = {}

        def emit(n0, n1):
            evs = []
            for n in range(n0, n1):
                key = n % 23
                evs.append({"op": "c", "after": {"id": key, "v": n}})
                expected[key] = n
            source.append(*evs)

        planes = [MaintenancePlane(FileStoreTable.load(t.path),
                                   base_user="stream-daemon",
                                   process_index=i, process_count=2)
                  for i in range(2)]
        # survivor checkpoint interval >> test duration: its
        # committed offset lags its poll position at adoption
        d0 = StreamDaemon(
            FileStoreTable.load(t.path), source,
            commit_user="stream-daemon", plane=planes[0],
            dynamic_options={"stream.checkpoint.interval": "60000"}
        ).start()
        d1 = StreamDaemon(
            FileStoreTable.load(t.path), source,
            commit_user="stream-daemon", plane=planes[1],
            dynamic_options={"stream.checkpoint.interval": "50"}
        ).start()
        try:
            emit(0, 120)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and \
                    d1.status()["offset_committed"] < 119:
                d0.poll_changelog(timeout=0.0)
                d1.poll_changelog(timeout=0.0)
                time.sleep(0.02)
            assert d1.status()["offset_committed"] >= 119
            d1.kill()
            # events keep flowing while d0 has still never
            # checkpointed (offset_committed == -1, poll far ahead)
            emit(120, 240)
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                d0.poll_changelog(timeout=0.0)
                st = d0.status()
                if st["distributed"]["adopted"] == [1] and \
                        st["offset_committed"] >= 239:
                    break
                time.sleep(0.03)
            st = d0.status()
            assert st["distributed"]["adopted"] == [1], st
            d0.stop(drain=True)
        finally:
            d0.kill(), d1.kill()

        final = FileStoreTable.load(t.path)
        state = {r["id"]: r["v"]
                 for r in final.to_arrow().to_pylist()}
        assert state == expected, \
            "adopted-group events polled past the survivor's " \
            "committed offset were lost"
        assert final.fsck().ok


# -- in-process two-daemon takeover (single-box rehearsal) --------------------

def test_two_daemon_takeover_in_process(tmp_path):
    """Two distributed stream daemons (fake 2-process topology) over
    one table and one replayable source; daemon 1 is killed mid-run
    and daemon 0 adopts its buckets: no event lost or duplicated, the
    final table is byte-identical to the single-process oracle,
    per-user offsets stay strictly increasing, the takeover is
    visible in maintenance_takeovers, and fsck (ownership check
    included) is clean."""
    import pyarrow as pa

    from paimon_tpu.cdc.source import MemoryCdcSource
    from paimon_tpu.core.read import ROW_KIND_COL
    from paimon_tpu.service.stream_daemon import StreamDaemon

    def big_schema(extra=None):
        o = {"bucket": "4"}
        o.update(extra or {})
        # v is BigInt: the CDC sink infers python ints as BigInt and
        # would widen an Int column, diverging from the oracle schema
        return (Schema.builder()
                .column("id", BigIntType(False))
                .column("v", BigIntType())
                .primary_key("id")
                .options(o)
                .build())

    opts = {
        "stream.checkpoint.interval": "60",
        "stream.compaction.interval": "80",
        "stream.ingest.poll-interval": "10",
        "stream.serve.poll-interval": "15",
        "num-sorted-run.compaction-trigger": "3",
        "multihost.lease.interval": "150",
        "multihost.lease.timeout": "1200",
        "snapshot.num-retained.min": "100000",
        "snapshot.num-retained.max": "100000",
    }
    t = FileStoreTable.create(str(tmp_path / "dist"),
                              big_schema(opts))

    # one deterministic global event stream, replayable by offset;
    # each daemon gets its own source HANDLE over the same events
    # (poll is read-only)
    source = MemoryCdcSource()
    expected = {}

    def emit(n0, n1):
        events = []
        for n in range(n0, n1):
            key = n % 37
            events.append({"op": "c", "after": {"id": key, "v": n}})
            expected[key] = n
        source.append(*events)

    g = global_registry().multihost_metrics()
    takeovers0 = g.counter(MULTIHOST_MAINTENANCE_TAKEOVERS).count

    planes = [
        MaintenancePlane(FileStoreTable.load(t.path),
                         base_user="stream-daemon",
                         process_index=i, process_count=2)
        for i in range(2)]
    daemons = [
        StreamDaemon(FileStoreTable.load(t.path), source,
                     commit_user="stream-daemon",
                     plane=planes[i]).start()
        for i in range(2)]

    consumed = [[], []]

    def drain(i):
        while True:
            rows = daemons[i].poll_changelog(timeout=0.0)
            if not rows:
                return
            consumed[i].extend(rows)

    total = 0
    try:
        # phase 1: both alive
        for _ in range(6):
            emit(total, total + 30)
            total += 30
            time.sleep(0.12)
            drain(0), drain(1)
        # both must have checkpointed before the kill so the takeover
        # has a real offset to adopt
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and (
                daemons[0].status()["offset_committed"] < 0
                or daemons[1].status()["offset_committed"] < 0):
            drain(0), drain(1)
            time.sleep(0.05)
        assert daemons[1].status()["offset_committed"] >= 0

        # phase 2: host 1 dies abruptly (no drain, no final
        # checkpoint — everything past its last checkpoint is lost
        # and must be re-ingested by the survivor)
        daemons[1].kill()
        drain(1)
        # keep emitting through the outage
        for _ in range(6):
            emit(total, total + 30)
            total += 30
            time.sleep(0.1)
            drain(0)

        # phase 3: the survivor converges on EVERYTHING
        last = source.latest_offset()
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            drain(0)
            st = daemons[0].status()
            if st["offset_committed"] >= last and \
                    st["distributed"]["adopted"] == [1]:
                break
            time.sleep(0.05)
        st = daemons[0].status()
        assert st["distributed"]["adopted"] == [1], st
        assert st["offset_committed"] >= last, st
        daemons[0].stop(drain=True)
        drain(0)
    finally:
        for d in daemons:
            d.kill()

    assert g.counter(MULTIHOST_MAINTENANCE_TAKEOVERS).count > takeovers0

    # table state == oracle (byte identity)
    final = FileStoreTable.load(t.path)
    oracle = FileStoreTable.create(
        str(tmp_path / "oracle"), big_schema())
    wb = oracle.new_batch_write_builder()
    with wb.new_write() as w:
        w.write_dicts([{"id": k, "v": v}
                       for k, v in sorted(expected.items())])
        wb.new_commit().commit(w.prepare_commit())
    assert final.to_arrow().sort_by("id").equals(
        oracle.to_arrow().sort_by("id"))

    # changelog exactly-once: dead host's stream first (all its rows
    # predate the takeover), then the survivor's (which replays the
    # unserved suffix per adopted bucket before continuing) — the
    # merged materialization must equal the expected state
    materialized = {}
    for stream in (consumed[1], consumed[0]):
        for r in stream:
            if r[ROW_KIND_COL] in (0, 2):
                materialized[r["id"]] = r["v"]
            elif r[ROW_KIND_COL] == 3:
                materialized.pop(r["id"], None)
    assert materialized == expected

    # offsets strictly increasing per commit user; both users present
    offsets = {0: [], 1: []}
    for snap in final.snapshot_manager.snapshots():
        for p in (0, 1):
            if snap.commit_user == f"stream-daemon-p{p}" and \
                    snap.properties and \
                    "stream.source.offset" in snap.properties:
                offsets[p].append(
                    int(snap.properties["stream.source.offset"]))
    assert offsets[0] and offsets[1]
    for p in (0, 1):
        assert offsets[p] == sorted(set(offsets[p])), offsets[p]
    assert offsets[0][-1] >= source.latest_offset()

    # the takeover generation is stamped and the graph is clean —
    # ownership consistency included
    resumed = resume_ownership_map(final)
    assert resumed is not None and resumed.dead == frozenset({1})
    report = final.fsck()
    assert report.ok, [v.to_dict() for v in report.violations]


# -- batched SPMD event routing (ISSUE 12 satellite) --------------------------

def test_batched_event_routing_matches_per_row_oracle(tmp_path):
    """The poll-batch router (_event_groups: ONE vectorized bucket
    hash per batch) must agree event-for-event with the per-row oracle
    (one-row table through the same FixedBucketAssigner) — including
    no-change events, deletes, and the ownership+floor filter."""
    import pyarrow as pa

    from paimon_tpu.cdc.source import MemoryCdcSource
    from paimon_tpu.service.stream_daemon import StreamDaemon

    t = _table(tmp_path, buckets=8)
    plane = MaintenancePlane(t, base_user="stream-daemon",
                             process_index=0, process_count=2)
    d = StreamDaemon(t, MemoryCdcSource(), commit_user="stream-daemon",
                     plane=plane)
    d._init_event_router()

    rng = __import__("random").Random(7)
    events = []
    for i in range(500):
        key = rng.randrange(1000)
        if i % 97 == 0:
            events.append({"op": "c"})             # parses to nothing
        elif i % 5 == 0:
            events.append({"op": "d",
                           "before": {"id": key, "v": i}})
        else:
            events.append({"op": "c",
                           "after": {"id": key, "v": i}})

    def oracle_group(event):
        changes = d._parse_event(event)
        if not changes:
            return None
        row = changes[0][0]
        sub = pa.Table.from_pylist(
            [{k: row.get(k) for k in d._bucket_key_names}],
            schema=d._key_schema)
        bucket = int(d._assigner.assign(sub)[0])
        part = tuple(row.get(k) for k in d._partition_key_names)
        return part, bucket

    batched = d._event_groups(events)
    assert len(batched) == len(events)
    assert d._key_schema is not None
    expected = [oracle_group(e) for e in events]
    assert batched == expected
    assert any(g is None for g in batched)
    assert len({g[1] for g in batched if g}) > 1   # hash spread

    # the ownership/floor filter composes identically on both paths
    fm = d._forward_map()
    mine_batched = [e for (off, e), g in
                    zip(enumerate(events), batched)
                    if d._owns_forward_group(off, g, fm)]
    mine_per_row = [e for off, e in enumerate(events)
                    if d._owns_forward_event(off, e, fm)]
    assert mine_batched == mine_per_row
    assert 0 < len(mine_batched) < sum(g is not None for g in batched)
