"""Iceberg REST catalog committer + DLF-style HMAC signed auth.

reference: paimon-iceberg/.../IcebergRestMetadataCommitter.java (+ its
Test), paimon-api/.../rest/auth/DLFAuthProvider.java +
DLFDefaultSigner.java + DLFAuthSignatureTest.java.
"""

import json
import os

import pytest

from paimon_tpu.catalog.auth import (
    BearerAuthProvider, DLFAuthProvider, verify_dlf_request,
)
from paimon_tpu.iceberg.reader import IcebergTable
from paimon_tpu.iceberg.rest import (
    IcebergCommitConflictError, IcebergRESTCatalogServer,
    IcebergRestClient, IcebergRestCommitter,
)
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType


def _commit(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    wb.new_commit().commit(w.prepare_commit())
    w.close()


def _make_table(root):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "1"})
              .build())
    return FileStoreTable.create(os.path.join(root, "t"), schema)


@pytest.fixture()
def server(tmp_path):
    s = IcebergRESTCatalogServer(str(tmp_path / "rest-wh")).start()
    yield s
    s.stop()


class TestRestCommitter:
    def test_round_trip_create_then_read(self, tmp_path, server):
        """export -> REST commit -> independent reader consumes the
        metadata the REST response points at."""
        table = _make_table(str(tmp_path))
        _commit(table, [{"id": 1, "v": 1.0}, {"id": 2, "v": 2.0}])
        table.compact(full=True)

        client = IcebergRestClient(server.uri)
        committer = IcebergRestCommitter(client, "db", "t")
        table.sync_iceberg(committer=committer)

        loaded = client.load_table("db", "t")
        assert loaded is not None
        # the response's metadata-location is durable JSON on disk
        meta = json.loads(open(loaded["metadata-location"]).read())
        assert meta["current-snapshot-id"] == \
            loaded["metadata"]["current-snapshot-id"]
        # independent spec-walking reader consumes it
        it = IcebergTable(meta, table.file_io)
        got = it.to_arrow().sort_by("id")
        assert got.column("id").to_pylist() == [1, 2]
        assert got.column("v").to_pylist() == [1.0, 2.0]

    def test_incremental_commit_cas(self, tmp_path, server):
        """Second sync commits with assert-ref-snapshot-id on the first
        export's snapshot — the happy CAS path."""
        table = _make_table(str(tmp_path))
        _commit(table, [{"id": 1, "v": 1.0}])
        table.compact(full=True)
        client = IcebergRestClient(server.uri)
        committer = IcebergRestCommitter(client, "db", "t")
        table.sync_iceberg(committer=committer)
        first = client.load_table("db", "t")

        _commit(table, [{"id": 2, "v": 2.0}])
        table.compact(full=True)
        table.sync_iceberg(committer=committer)
        second = client.load_table("db", "t")
        assert second["metadata"]["current-snapshot-id"] > \
            first["metadata"]["current-snapshot-id"]
        it = IcebergTable(
            json.loads(open(second["metadata-location"]).read()),
            table.file_io)
        assert sorted(it.to_arrow().column("id").to_pylist()) == [1, 2]

    def test_cas_conflict_raises(self, server):
        """A commit whose required base no longer matches is refused
        with 409 -> IcebergCommitConflictError (reference
        CommitFailedException path)."""
        client = IcebergRestClient(server.uri)
        client.create_namespace("db")
        meta = {"format-version": 2, "table-uuid": "u-1",
                "location": "/x", "current-snapshot-id": 10,
                "schemas": [{"schema-id": 0, "fields": []}],
                "last-column-id": 0, "snapshots": [
                    {"snapshot-id": 10, "sequence-number": 1}]}
        client.create_table("db", "t", meta)
        with pytest.raises(IcebergCommitConflictError):
            client.commit_table("db", "t", [
                {"type": "assert-ref-snapshot-id", "ref": "main",
                 "snapshot-id": 999},
            ], [{"action": "add-snapshot",
                 "snapshot": {"snapshot-id": 11,
                              "sequence-number": 2}}])

    def test_diverged_base_recreates(self, tmp_path, server):
        """If the catalog diverged from our last export (reference's
        'incorrect base' branch), the committer drops and recreates."""
        table = _make_table(str(tmp_path))
        _commit(table, [{"id": 1, "v": 1.0}])
        table.compact(full=True)
        client = IcebergRestClient(server.uri)
        committer = IcebergRestCommitter(client, "db", "t")
        table.sync_iceberg(committer=committer)

        # a foreign writer moves main somewhere else
        client.commit_table("db", "t", [], [
            {"action": "add-snapshot",
             "snapshot": {"snapshot-id": 777, "sequence-number": 50}},
            {"action": "set-snapshot-ref", "ref-name": "main",
             "type": "branch", "snapshot-id": 777}])

        _commit(table, [{"id": 2, "v": 2.0}])
        table.compact(full=True)
        table.sync_iceberg(committer=committer)
        cur = client.load_table("db", "t")["metadata"]
        assert cur["current-snapshot-id"] != 777
        snap_ids = {s["snapshot-id"] for s in cur["snapshots"]}
        assert 777 not in snap_ids


class TestDLFAuth:
    KEYS = {"akid-1": "secret-1"}

    def test_signature_stable_and_verifies(self):
        prov = DLFAuthProvider("akid-1", "secret-1", region="r-1",
                               now_fn=lambda: 1_700_000_000.0)
        h = prov.auth_headers("POST", "/v1/ns/tables", {"a": "1"},
                              '{"x":1}')
        assert h["Authorization"].startswith("DLF4-HMAC-SHA256 ")
        assert h["x-dlf-content-sha256"] == "UNSIGNED-PAYLOAD"
        assert "content-md5" in h
        # deterministic for fixed time + inputs
        h2 = prov.auth_headers("POST", "/v1/ns/tables", {"a": "1"},
                               '{"x":1}')
        assert h == h2
        assert verify_dlf_request(
            h, "POST", "/v1/ns/tables", {"a": "1"}, '{"x":1}',
            self.KEYS, region="r-1",
            now_fn=lambda: 1_700_000_000.0)

    def test_verify_rejects_tampering(self):
        now = lambda: 1_700_000_000.0    # noqa: E731
        prov = DLFAuthProvider("akid-1", "secret-1", region="r-1",
                               now_fn=now)
        h = prov.auth_headers("GET", "/v1/t", None, None)
        ok = dict(kw=1)
        assert verify_dlf_request(h, "GET", "/v1/t", None, None,
                                  self.KEYS, region="r-1", now_fn=now)
        # wrong path
        assert not verify_dlf_request(h, "GET", "/v1/other", None, None,
                                      self.KEYS, region="r-1",
                                      now_fn=now)
        # wrong method
        assert not verify_dlf_request(h, "POST", "/v1/t", None, None,
                                      self.KEYS, region="r-1",
                                      now_fn=now)
        # unknown key
        assert not verify_dlf_request(h, "GET", "/v1/t", None, None,
                                      {"other": "s"}, region="r-1",
                                      now_fn=now)
        # wrong secret
        assert not verify_dlf_request(h, "GET", "/v1/t", None, None,
                                      {"akid-1": "bad"}, region="r-1",
                                      now_fn=now)
        # stale timestamp (> 15 min skew)
        assert not verify_dlf_request(h, "GET", "/v1/t", None, None,
                                      self.KEYS, region="r-1",
                                      now_fn=lambda: now() + 3600)

    def test_token_loader_rotation(self):
        tokens = [("akid-1", "secret-1", None),
                  ("akid-2", "secret-2", "sts-token")]
        prov = DLFAuthProvider(token_loader=lambda: tokens[0],
                               region="r-1",
                               now_fn=lambda: 1_700_000_000.0)
        h1 = prov.auth_headers("GET", "/v1/t", None, None)
        assert "Credential=akid-1/" in h1["Authorization"]
        tokens[0] = tokens[1]
        h2 = prov.auth_headers("GET", "/v1/t", None, None)
        assert "Credential=akid-2/" in h2["Authorization"]
        assert h2["x-dlf-security-token"] == "sts-token"
        assert verify_dlf_request(
            h2, "GET", "/v1/t", None, None, {"akid-2": "secret-2"},
            region="r-1", now_fn=lambda: 1_700_000_000.0)

    def test_signed_rest_server_round_trip(self, tmp_path):
        """The loopback Iceberg REST server enforces DLF signatures:
        signed requests pass, unsigned/bearer are 401."""
        keys = {"akid-1": "secret-1"}

        def check(headers, method, path, body):
            return verify_dlf_request(headers, method, path, None, body,
                                      keys, region="r-1")

        s = IcebergRESTCatalogServer(str(tmp_path / "wh"),
                                     auth_check=check).start()
        try:
            signed = IcebergRestClient(
                s.uri, auth_provider=DLFAuthProvider(
                    "akid-1", "secret-1", region="r-1"))
            signed.create_namespace("db")
            meta = {"format-version": 2, "location": "/x",
                    "schemas": [{"schema-id": 0, "fields": []}],
                    "last-column-id": 0, "snapshots": [],
                    "current-snapshot-id": None}
            signed.create_table("db", "t", meta)
            assert signed.load_table("db", "t") is not None

            unsigned = IcebergRestClient(s.uri)
            with pytest.raises(RuntimeError, match="401"):
                unsigned.load_table("db", "t")
            bearer = IcebergRestClient(
                s.uri, auth_provider=BearerAuthProvider("tok"))
            with pytest.raises(RuntimeError, match="401"):
                bearer.load_table("db", "t")
        finally:
            s.stop()


class TestDLFGoldenVector:
    def test_signature_pinned(self):
        """Golden vector freezing the DLF4-HMAC-SHA256 wire algorithm
        (canonical request -> string-to-sign -> derived key chain);
        any refactor changing these bytes breaks interop with servers
        validating the same spec."""
        p = DLFAuthProvider("AKID", "SECRET", security_token="STS",
                            region="cn-hangzhou",
                            now_fn=lambda: 1_700_000_000.0)
        h = p.auth_headers("POST", "/v1/cat/databases",
                           {"maxResults": "10"}, '{"name":"db"}')
        assert h["Authorization"] == (
            "DLF4-HMAC-SHA256 Credential=AKID/20231114/cn-hangzhou/"
            "DlfNext/aliyun_v4_request,Signature=7787f3efff0f52eeab47"
            "d1f65fa25fe7ff6b11060eaa7ab00d9901e1a14d5ee8")
        assert h["content-md5"] == "6ZF45M/6TJ2FOC248EOPDg=="
        assert h["x-dlf-date"] == "20231114T221320Z"
