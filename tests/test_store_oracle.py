"""Randomized store-level oracle sweep.

Mirrors the reference's TestFileStore/TestKeyValueGenerator randomized
harness (paimon-core/src/test/java/org/apache/paimon/TestFileStore.java):
random workload interleavings, replayed against an in-memory model.

Fast CI sweep: every (merge engine x changelog producer) cell at a few
seeds, plus a wider seed sweep on the deduplicate engine.  Long mode:
ORACLE_SEEDS / ORACLE_STEPS env vars scale the sweep up (e.g.
ORACLE_SEEDS=50 ORACLE_STEPS=60 python -m pytest tests/test_store_oracle.py).
"""

import os

import pytest

from tests.store_oracle import StoreOracle

SEEDS = int(os.environ.get("ORACLE_SEEDS", "0"))
STEPS = int(os.environ.get("ORACLE_STEPS", "18"))


@pytest.mark.parametrize("engine,producer", [
    ("deduplicate", "none"),
    ("deduplicate", "input"),
    ("deduplicate", "lookup"),
    ("deduplicate", "full-compaction"),
    ("partial-update", "none"),
    ("partial-update", "lookup"),
    ("aggregation", "none"),
    ("aggregation", "full-compaction"),
    ("first-row", "none"),
    ("first-row", "lookup"),
])
@pytest.mark.parametrize("seed", [11, 42])
def test_oracle_engine_producer_matrix(tmp_path, engine, producer, seed):
    oracle = StoreOracle(str(tmp_path / "t"), seed=seed, engine=engine,
                         changelog_producer=producer)
    oracle.run(steps=STEPS)


@pytest.mark.parametrize("seed", list(range(100, 100 + max(SEEDS, 20))))
def test_oracle_dedup_seed_sweep(tmp_path, seed):
    oracle = StoreOracle(str(tmp_path / "t"), seed=seed,
                         engine="deduplicate", changelog_producer="none")
    oracle.run(steps=int(os.environ.get("ORACLE_STEPS", "12")))


@pytest.mark.parametrize("seed", [7, 23])
def test_oracle_dynamic_bucket(tmp_path, seed):
    oracle = StoreOracle(str(tmp_path / "t"), seed=seed,
                         engine="deduplicate", bucket="-1",
                         partitioned=False, allow_schema_add=False)
    oracle.run(steps=12)


@pytest.mark.parametrize("seed", [3, 19, 57])
def test_oracle_with_rollbacks(tmp_path, seed):
    oracle = StoreOracle(str(tmp_path / "t"), seed=seed,
                         engine="deduplicate", allow_rollback=True,
                         allow_expire=False)
    oracle.run(steps=25)


@pytest.mark.parametrize("seed", [5])
def test_oracle_single_bucket_unpartitioned(tmp_path, seed):
    oracle = StoreOracle(str(tmp_path / "t"), seed=seed,
                         engine="deduplicate", bucket="1",
                         partitioned=False)
    oracle.run(steps=15)
