"""Streaming mesh compaction engine (parallel/mesh_engine.py) on the
virtual 8-device CPU mesh: per-engine equivalence against the
single-chip compaction path, bounded-window streaming, skew-aware
packing, and the hard UnsupportedMergeEngineError contract.
"""

import json
import os

import numpy as np
import pytest

import jax

from paimon_tpu.parallel import (
    UnsupportedMergeEngineError, bucket_mesh, compact_table_mesh,
    compact_table_sharded, pack_buckets, packing_skew,
)
from paimon_tpu.table import FileStoreTable
from tests.store_oracle import make_random_engine_table

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ENGINES = ["deduplicate", "partial-update", "aggregation", "first-row"]


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8, "conftest should give 8 CPU devices"
    return bucket_mesh(8)


def _rows(table):
    return sorted(table.to_arrow().to_pylist(),
                  key=lambda r: (r["pt"], r["id"]))


def _bucket_kv(table):
    """{bucket: KV rows in key order} of the stored files — the
    file-level (not merge-on-read) contents, incl. seq + kind."""
    from paimon_tpu.core.kv_file import read_kv_file
    from paimon_tpu.core.read import MergeFileSplitRead, assemble_runs
    import pyarrow as pa

    reader = MergeFileSplitRead(table.file_io, table.path, table.schema,
                                table.options)
    out = {}
    for s in table.new_read_builder().new_scan().plan().splits:
        tables = []
        for run in assemble_runs(s.data_files):
            for f in run:
                tables.append(read_kv_file(
                    table.file_io, reader.path_factory, s.partition,
                    s.bucket, f, schema=table.schema,
                    schema_manager=table.schema_manager))
        t = pa.concat_tables(tables, promote_options="none")
        out[(tuple(s.partition), s.bucket)] = t.to_pylist()
    return out


def _twins(tmp_path, engine, seed=11, **kw):
    a = make_random_engine_table(str(tmp_path / "single"), seed, engine,
                                 **kw)
    b = make_random_engine_table(str(tmp_path / "mesh"), seed, engine,
                                 **kw)
    return a, b


def _assert_equivalent(single, meshed, stats):
    assert stats.snapshot_id is not None
    assert meshed.latest_snapshot().commit_kind == "COMPACT"
    # merge-on-read state identical
    assert _rows(meshed) == _rows(single)
    # stored file contents identical per bucket (keys, seq, kind,
    # values) — row-identical, not merely state-identical
    assert _bucket_kv(meshed) == _bucket_kv(single)
    # mesh output is fully compacted: single max-level run per bucket
    max_level = meshed.options.num_levels - 1
    for s in meshed.new_read_builder().new_scan().plan().splits:
        assert all(f.level == max_level for f in s.data_files)


@pytest.mark.parametrize("engine", ENGINES)
def test_mesh_matches_single_chip(tmp_path, mesh, engine):
    single, meshed = _twins(tmp_path, engine, seed=7 + len(engine))
    assert single.compact(full=True) is not None
    stats = compact_table_mesh(meshed, mesh)
    assert stats.buckets > 0 and stats.windows > 0
    assert stats.output_rows == sum(
        len(v) for v in _bucket_kv(meshed).values())
    _assert_equivalent(single, meshed, stats)


def test_mesh_partial_update_sequence_groups(tmp_path, mesh):
    single, meshed = _twins(tmp_path, "partial-update", seed=23,
                            sequence_group=True)
    assert single.compact(full=True) is not None
    stats = compact_table_mesh(meshed, mesh)
    _assert_equivalent(single, meshed, stats)


def test_mesh_dedup_user_sequence_field(tmp_path, mesh):
    opts = {"sequence.field": "v1"}
    single, meshed = _twins(tmp_path, "deduplicate", seed=31,
                            deletes=False, extra_options=opts)
    assert single.compact(full=True) is not None
    stats = compact_table_mesh(meshed, mesh)
    _assert_equivalent(single, meshed, stats)


def test_mesh_idempotent(tmp_path, mesh):
    _, meshed = _twins(tmp_path, "deduplicate", seed=3)
    stats = compact_table_mesh(meshed, mesh)
    assert stats.snapshot_id is not None
    again = compact_table_mesh(meshed, mesh)
    assert again.snapshot_id is None
    assert again.buckets == 0


def test_mesh_unsupported_engine_raises(tmp_path, mesh):
    t = make_random_engine_table(str(tmp_path / "t"), 1, "deduplicate",
                                 commits=1, rows_per_commit=20)
    bogus = t.copy({"merge-engine": "shiny-new-engine"})
    with pytest.raises(UnsupportedMergeEngineError):
        compact_table_mesh(bogus, mesh)


def test_legacy_sharded_guard_raises(tmp_path, mesh):
    """The legacy pad-everything path silently deduplicated every
    engine; now any non-deduplicate table gets the typed error."""
    t = make_random_engine_table(str(tmp_path / "t"), 2, "aggregation",
                                 commits=1, rows_per_commit=20)
    with pytest.raises(UnsupportedMergeEngineError):
        compact_table_sharded(t, mesh)


def test_mesh_rejects_changelog_producers(tmp_path, mesh):
    t = make_random_engine_table(str(tmp_path / "t"), 4, "deduplicate",
                                 commits=1, rows_per_commit=20)
    with pytest.raises(ValueError, match="changelog"):
        compact_table_mesh(t.copy({"changelog-producer": "input"}), mesh)


def test_mesh_streams_bounded_windows(tmp_path, mesh):
    """A bucket far larger than the window budget streams through the
    mesh without being materialized: the per-bucket run buffers stay
    under runs x window-rows (+ refill slack), while the bucket itself
    is ~30x the window."""
    window = 4096
    t = make_random_engine_table(
        str(tmp_path / "t"), 42, "deduplicate", buckets=1, commits=3,
        rows_per_commit=40_000, key_space=1_000_000, deletes=False,
        extra_options={"tpu.mesh.window-rows": str(window)})
    before = _rows(t)                      # merge-on-read ground truth
    stats = compact_table_mesh(t, mesh)
    assert stats.snapshot_id is not None
    # slightly under 3 x 40k: the write buffer pre-merges duplicate
    # keys within each commit batch
    assert stats.input_rows > 110_000
    assert stats.windows > 5               # genuinely windowed
    budget = 4 * 3 * window                # runs x window + refill slack
    assert 0 < stats.peak_buffered_rows <= budget
    assert 0 < stats.peak_window_rows <= budget
    assert budget < stats.input_rows // 2  # budget << bucket size
    assert _rows(t) == before


def test_compact_option_routes_through_mesh(tmp_path, mesh):
    """tpu.mesh.compact=true routes table.compact(full=True) through
    the mesh engine (compact/ manager routing); output matches the
    single-chip twin."""
    single, meshed = _twins(tmp_path, "aggregation", seed=13)
    assert single.compact(full=True) is not None
    routed = meshed.copy({"tpu.mesh.compact": "true"})
    sid = routed.compact(full=True)
    assert sid is not None
    assert routed.latest_snapshot().commit_kind == "COMPACT"
    assert _rows(routed) == _rows(single)
    assert _bucket_kv(routed) == _bucket_kv(single)


def test_compact_option_falls_back_single_chip(tmp_path):
    """Engines / configs the mesh engine cannot run route back to the
    single-chip manager instead of raising — per-engine routing, not a
    hard switch."""
    t = make_random_engine_table(
        str(tmp_path / "t"), 5, "deduplicate", commits=2,
        rows_per_commit=40,
        extra_options={"tpu.mesh.compact": "true",
                       "changelog-producer": "input"})
    sid = t.compact(full=True)
    assert sid is not None
    assert t.latest_snapshot().commit_kind == "COMPACT"


# -- packing -----------------------------------------------------------------


def test_pack_buckets_skew_aware():
    counts = [1000, 10, 10, 10, 10, 10, 10, 10]
    lanes = pack_buckets(counts, 4)
    loads = [sum(counts[i] for i in lane) for lane in lanes]
    # the hot bucket owns a lane alone; every bucket assigned once
    assert sorted(i for lane in lanes for i in lane) == list(range(8))
    assert max(loads) == 1000
    assert [0] in lanes
    assert packing_skew(counts, lanes) == pytest.approx(
        1000 / (sum(counts) / 4))


def test_pack_buckets_balances_uniform():
    counts = [100] * 16
    lanes = pack_buckets(counts, 8)
    assert all(len(lane) == 2 for lane in lanes)


def test_pack_buckets_fewer_buckets_than_lanes():
    lanes = pack_buckets([5, 7], 8)
    assert sorted(i for lane in lanes for i in lane) == [0, 1]
    assert sum(1 for lane in lanes if lane) == 2


def test_pack_buckets_deterministic():
    counts = [3, 9, 1, 9, 3, 7]
    assert pack_buckets(counts, 3) == pack_buckets(list(counts), 3)


# -- multichip dryrun (CI-recorded) ------------------------------------------


@pytest.mark.slow
def test_dryrun_multichip_engines(mesh):
    """Aggregation + deduplicate through the mesh engine at >= 10M
    rows on the CPU mesh backend; rows/s recorded to MULTICHIP_r06.json
    (the round-6 multichip artifact)."""
    from paimon_tpu.parallel.dryrun import run_engines

    rows = int(os.environ.get("DRYRUN_ROWS", "10000000"))
    record = run_engines(8, rows=rows, mesh=mesh,
                         out_path=os.path.join(REPO,
                                               "MULTICHIP_r06.json"))
    for engine in ("deduplicate", "aggregation"):
        r = record["engines"][engine]
        assert r["input_rows"] >= rows
        assert r["output_rows"] > 0
        assert r["rows_per_sec"] > 0
