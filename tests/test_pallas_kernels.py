"""Pallas winner-select kernel: interpret-mode parity with the plain
XLA segmented mask (the compiled path runs the identical program on
real TPUs)."""

import os

import numpy as np
import pytest

from paimon_tpu.ops.merge import device_sorted_winners


def _winner_set(lanes, seq, keep):
    perm, winner, prev = device_sorted_winners(lanes, seq, keep)
    perm, winner = np.asarray(perm), np.asarray(winner, bool)
    n = len(seq)
    real = perm < n
    return (set(perm[winner & real].tolist()),
            {int(perm[i]): int(np.asarray(prev)[i])
             for i in np.flatnonzero(winner & real)})


@pytest.mark.parametrize("keep", ["last", "first"])
@pytest.mark.parametrize("seed", [0, 5, 11])
def test_pallas_matches_xla_mask(keep, seed):
    os.environ["PAIMON_FORCE_DEVICE_SORT"] = "1"
    try:
        rng = np.random.default_rng(seed)
        n = int(rng.integers(100, 6000))
        lanes = rng.integers(0, 12, (n, 3), dtype=np.uint64) \
            .astype(np.uint32)
        seq = rng.permutation(n).astype(np.int64)

        os.environ.pop("PAIMON_DISABLE_PALLAS", None)
        with_pallas = _winner_set(lanes, seq, keep)

        os.environ["PAIMON_DISABLE_PALLAS"] = "1"
        # kill switch is part of the jit cache key: takes effect on
        # the very next call, no cache clearing needed
        without = _winner_set(lanes, seq, keep)

        assert with_pallas == without
    finally:
        os.environ.pop("PAIMON_FORCE_DEVICE_SORT", None)
        os.environ.pop("PAIMON_DISABLE_PALLAS", None)


def test_padding_never_joins_segments():
    """All-zero real keys must not merge with the all-zero padding
    rows (validity is part of segment identity in the kernel too)."""
    os.environ["PAIMON_FORCE_DEVICE_SORT"] = "1"
    try:
        lanes = np.zeros((5, 2), dtype=np.uint32)
        seq = np.arange(5, dtype=np.int64)
        perm, winner, _ = device_sorted_winners(lanes, seq, "last")
        perm, winner = np.asarray(perm), np.asarray(winner, bool)
        win = perm[winner & (perm < 5)]
        assert win.tolist() == [4]       # one segment, max-seq row
    finally:
        os.environ.pop("PAIMON_FORCE_DEVICE_SORT", None)
