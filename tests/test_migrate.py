"""In-place migration of plain file tables into paimon append tables.

reference: flink/procedure/MigrateTableProcedure +
migrate/FileMigrationUtils (metadata-only: files are moved, never
rewritten).
"""

import glob
import os

import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from paimon_tpu.catalog import create_catalog
from paimon_tpu.maintenance.migrate import migrate_table


def _hive_dir(root, partitioned=True):
    """Build a hive-style parquet directory: dt=a/, dt=b/."""
    n = 0
    for dt in (["a", "b"] if partitioned else [None]):
        d = os.path.join(root, f"dt={dt}") if dt else root
        os.makedirs(d, exist_ok=True)
        for i in range(2):
            t = pa.table({
                "id": pa.array(range(n, n + 5), pa.int64()),
                "v": pa.array([float(x) for x in range(5)],
                              pa.float64()),
            })
            pq.write_table(t, os.path.join(d, f"part-{i}.parquet"))
            n += 5
    return n


class TestMigrate:
    def test_partitioned_move(self, tmp_path):
        src = str(tmp_path / "hive_t")
        total = _hive_dir(src)
        cat = create_catalog({"warehouse": str(tmp_path / "wh")})
        cat.create_database("db", ignore_if_exists=True)
        t = migrate_table(cat, src, "db.m", move=True)
        # all rows visible, partition column materialized
        got = t.to_arrow()
        assert got.num_rows == total
        assert sorted(set(got.column("dt").to_pylist())) == ["a", "b"]
        assert sorted(got.column("id").to_pylist()) == list(range(total))
        # files were MOVED (source drained), never rewritten
        assert not glob.glob(f"{src}/**/*.parquet", recursive=True)
        # partition pruning works on the migrated layout
        pruned = t.copy({}).new_read_builder() \
            .with_partition_filter({"dt": "a"}).new_scan().plan()
        assert {tuple(s.partition) for s in pruned.splits} == {("a",)}
        # and the table behaves like any append table afterwards
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        w.write_dicts([{"id": 999, "v": 9.0, "dt": "a"}])
        wb.new_commit().commit(w.prepare_commit())
        w.close()
        assert t.to_arrow().num_rows == total + 1

    def test_unpartitioned_copy_keeps_source(self, tmp_path):
        src = str(tmp_path / "flat_t")
        total = _hive_dir(src, partitioned=False)
        cat = create_catalog({"warehouse": str(tmp_path / "wh")})
        cat.create_database("db", ignore_if_exists=True)
        t = migrate_table(cat, src, "db.m2", move=False)
        assert t.to_arrow().num_rows == total
        assert len(glob.glob(f"{src}/*.parquet")) == 2   # source intact

    def test_sql_procedure(self, tmp_path):
        from paimon_tpu.sql import SQLContext
        src = str(tmp_path / "h")
        total = _hive_dir(src)
        cat = create_catalog({"warehouse": str(tmp_path / "wh")})
        ctx = SQLContext(cat)
        ctx.sql("CREATE DATABASE db")
        out = ctx.sql(f"CALL sys.migrate_table('{src}', 'db.mt')")
        assert f"migrated {total} rows" in str(out.to_pylist())
        got = ctx.sql("SELECT count(*) AS n FROM db.mt "
                      "WHERE dt = 'a'").to_pylist()
        assert got == [{"n": total // 2}]

    def test_row_id_read_path_fills_partitions(self, tmp_path):
        """Row-range read branch (with_row_ids) must fill partition
        columns absent from migrated files too."""
        src = str(tmp_path / "h2")
        total = _hive_dir(src)
        cat = create_catalog({"warehouse": str(tmp_path / "wh")})
        cat.create_database("db", ignore_if_exists=True)
        t = migrate_table(cat, src, "db.rr", move=True)
        rb = t.new_read_builder()
        if hasattr(rb, "with_row_ids"):
            rb = rb.with_row_ids(True)
        got = rb.new_read().to_arrow(rb.new_scan().plan().splits)
        assert got.num_rows == total
        assert sorted(set(got.column("dt").to_pylist())) == ["a", "b"]
