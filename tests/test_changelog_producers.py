"""Changelog producers full-compaction / lookup + point lookups.

Oracle: reference FullChangelogMergeTreeCompactRewriter,
LookupChangelogMergeFunctionWrapper.java:54 semantics — changelog rows
emitted at compaction describe the transition of the visible state.
"""

import os

import pytest

from paimon_tpu.core.read import ROW_KIND_COL
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, RowKind


def _make(tmp_warehouse, producer):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true",
                        "changelog-producer": producer})
              .build())
    return FileStoreTable.create(os.path.join(tmp_warehouse, "t"), schema)


def _commit(table, rows, kinds=None):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows, row_kinds=kinds)
    sid = wb.new_commit().commit(w.prepare_commit())
    w.close()
    return sid


def _drain_changelog(table, scan):
    rows = []
    while True:
        p = scan.plan()
        if p is None:
            break
        t = table.new_read_builder().new_read().to_arrow(p)
        rows.extend(t.to_pylist())
    return rows


@pytest.mark.parametrize("producer", ["full-compaction", "lookup"])
def test_compaction_changelog_insert_update_delete(tmp_warehouse,
                                                   producer):
    table = _make(tmp_warehouse, producer)
    _commit(table, [{"id": 1, "v": 1.0}, {"id": 2, "v": 2.0}])
    table.compact(full=True)

    scan = table.copy({"scan.mode": "latest"}) \
        .new_read_builder().new_stream_scan()
    scan.plan()

    # upsert 1, insert 3, delete 2 -> compact -> changelog
    _commit(table, [{"id": 1, "v": 10.0}, {"id": 3, "v": 3.0}])
    _commit(table, [{"id": 2, "v": 0.0}], kinds=[RowKind.DELETE])
    table.compact(full=True)

    rows = _drain_changelog(table, scan)
    by_kind = {}
    for r in rows:
        by_kind.setdefault(r[ROW_KIND_COL], []).append(r)
    assert [r["id"] for r in by_kind.get(RowKind.INSERT, [])] == [3]
    assert [r["id"] for r in by_kind.get(RowKind.DELETE, [])] == [2]
    ub = by_kind.get(RowKind.UPDATE_BEFORE, [])
    ua = by_kind.get(RowKind.UPDATE_AFTER, [])
    assert [(r["id"], r["v"]) for r in ub] == [(1, 1.0)]
    assert [(r["id"], r["v"]) for r in ua] == [(1, 10.0)]
    # -U comes immediately before its +U in the emitted order
    kinds_seq = [r[ROW_KIND_COL] for r in rows]
    i = kinds_seq.index(RowKind.UPDATE_BEFORE)
    assert kinds_seq[i + 1] == RowKind.UPDATE_AFTER


def test_full_compaction_no_change_no_changelog(tmp_warehouse):
    table = _make(tmp_warehouse, "full-compaction")
    _commit(table, [{"id": 1, "v": 1.0}])
    table.compact(full=True)
    scan = table.copy({"scan.mode": "latest"}) \
        .new_read_builder().new_stream_scan()
    scan.plan()
    # full compaction with no new data -> no changelog rows
    table.compact(full=True)
    assert _drain_changelog(table, scan) == []


def test_lookup_producer_emits_old_values_from_higher_levels(
        tmp_warehouse):
    """The defining lookup case: the compaction unit only contains L0,
    the old value lives in a higher level and must be looked up."""
    table = _make(tmp_warehouse, "lookup")
    _commit(table, [{"id": 7, "v": 1.0}])
    table.compact(full=True)               # id=7 now at max level

    scan = table.copy({"scan.mode": "latest"}) \
        .new_read_builder().new_stream_scan()
    scan.plan()

    _commit(table, [{"id": 7, "v": 2.0}])  # L0 only
    table.compact(full=True)
    rows = _drain_changelog(table, scan)
    assert [(r["id"], r["v"], r[ROW_KIND_COL]) for r in rows] == \
        [(7, 1.0, RowKind.UPDATE_BEFORE), (7, 2.0, RowKind.UPDATE_AFTER)]


def test_local_table_query(tmp_warehouse):
    from paimon_tpu.lookup import LocalTableQuery

    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "4", "write-only": "true"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "q"), schema)
    _commit(table, [{"id": i, "v": float(i)} for i in range(100)])
    _commit(table, [{"id": 5, "v": 55.0}])

    q = LocalTableQuery(table)
    res = q.lookup([{"id": 5}, {"id": 42}, {"id": 1000}])
    assert res[0] == {"id": 5, "v": 55.0}
    assert res[1] == {"id": 42, "v": 42.0}
    assert res[2] is None

    # cache invalidates on new snapshot
    _commit(table, [{"id": 42, "v": -1.0}])
    assert q.lookup_row({"id": 42}) == {"id": 42, "v": -1.0}


def test_full_compaction_first_data_emits_inserts(tmp_warehouse):
    """Regression: a single-file upgrade into the top level must still
    produce +I changelog (no silent metadata-only promotion)."""
    table = _make(tmp_warehouse, "full-compaction")
    scan = table.copy({"scan.mode": "latest"}) \
        .new_read_builder().new_stream_scan()
    scan.plan()
    _commit(table, [{"id": 1, "v": 1.0}])   # ONE L0 file
    table.compact(full=True)
    rows = _drain_changelog(table, scan)
    assert [(r["id"], r[ROW_KIND_COL]) for r in rows] == \
        [(1, RowKind.INSERT)]
