"""ANALYZE stats, caching FileIO, FormatTable, privileges."""

import os

import pyarrow as pa
import pytest

import paimon_tpu
from paimon_tpu.catalog.privilege import (
    Privilege, PrivilegedCatalog, PrivilegeError, PrivilegeManager,
)
from paimon_tpu.fs import get_file_io
from paimon_tpu.fs.caching import CachingFileIO
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.table.format_table import FormatTable
from paimon_tpu.types import BigIntType, DoubleType, VarCharType


def _make(tmp_warehouse):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("name", VarCharType())
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "1"})
              .build())
    return FileStoreTable.create(os.path.join(tmp_warehouse, "t"), schema)


def _commit(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    wb.new_commit().commit(w.prepare_commit())
    w.close()


def test_analyze_statistics(tmp_warehouse):
    table = _make(tmp_warehouse)
    _commit(table, [{"id": i, "name": f"n{i}", "v": float(i)}
                    for i in range(10)])
    sid = table.analyze()
    assert sid is not None
    snap = table.snapshot_manager.latest_snapshot()
    assert snap.commit_kind == "ANALYZE"
    assert snap.statistics

    stats = table.statistics()
    assert stats["mergedRecordCount"] == 10
    assert stats["colStats"]["id"]["distinctCount"] == 10
    assert stats["colStats"]["v"]["min"] == "0.0"
    assert stats["colStats"]["name"]["maxLen"] >= 2

    # later data commits keep the stats reachable (walk back)
    _commit(table, [{"id": 99, "name": "z", "v": 9.0}])
    assert table.statistics()["mergedRecordCount"] == 10


def test_caching_fileio(tmp_warehouse):
    table = _make(tmp_warehouse)
    _commit(table, [{"id": 1, "name": "a", "v": 1.0}])

    cached = CachingFileIO(get_file_io(table.path))
    ct = FileStoreTable(cached, table.path, table.schema_manager.latest())
    assert ct.to_arrow().num_rows == 1
    misses_first = cached.misses
    assert ct.to_arrow().num_rows == 1
    assert cached.hits > 0
    assert cached.misses == misses_first      # second read fully cached

    # mutable hint files are never cached: new commits become visible
    _commit(table, [{"id": 2, "name": "b", "v": 2.0}])
    assert ct.to_arrow().num_rows == 2


def test_format_table_roundtrip(tmp_path):
    ft = FormatTable(str(tmp_path / "ft"), "csv")
    ft.write(pa.table({"a": pa.array([1, 2], pa.int64())}))
    ft.write(pa.table({"a": pa.array([3], pa.int64())}))
    out = ft.to_arrow()
    assert sorted(out.column("a").to_pylist()) == [1, 2, 3]

    # hive-style partitions
    ft2 = FormatTable(str(tmp_path / "ftp"), "parquet")
    ft2.write(pa.table({"v": pa.array([1])}), partition={"dt": "d1"})
    ft2.write(pa.table({"v": pa.array([2])}), partition={"dt": "d2"})
    assert ft2.to_arrow().num_rows == 2
    assert ft2.to_arrow(partition={"dt": "d1"}).column("v").to_pylist() \
        == [1]


def test_privileges(tmp_path):
    wh = str(tmp_path / "wh")
    cat = paimon_tpu.create_catalog({"warehouse": wh})
    cat.create_database("db")
    cat.create_table("db.t", Schema.builder()
                     .column("id", BigIntType(False))
                     .primary_key("id").options({"bucket": "1"}).build())

    pm = PrivilegeManager(cat.file_io, wh)
    assert not pm.enabled()
    pm.init("rootpw")
    pm.create_user("alice", "pw1")
    pm.grant("alice", Privilege.SELECT, "db.t")

    root = PrivilegedCatalog(cat, "root", "rootpw")
    root.get_table("db.t")                       # admin: everything

    alice = PrivilegedCatalog(cat, "alice", "pw1")
    alice.get_table("db.t")                      # granted
    with pytest.raises(PrivilegeError):
        alice.drop_table("db.t")
    with pytest.raises(PrivilegeError):
        alice.create_database("db2")
    pm.grant("alice", Privilege.CREATE_DATABASE)
    alice.create_database("db2")

    with pytest.raises(PrivilegeError):
        PrivilegedCatalog(cat, "alice", "wrong")

    pm.revoke("alice", Privilege.SELECT, "db.t")
    with pytest.raises(PrivilegeError):
        alice.get_table("db.t")


def test_privileged_table_blocks_writes(tmp_path):
    wh = str(tmp_path / "wh2")
    cat = paimon_tpu.create_catalog({"warehouse": wh})
    cat.create_database("db")
    cat.create_table("db.t", Schema.builder()
                     .column("id", BigIntType(False))
                     .primary_key("id").options({"bucket": "1"}).build())
    pm = PrivilegeManager(cat.file_io, wh)
    pm.init("rootpw")
    pm.create_user("bob", "pw")
    pm.grant("bob", Privilege.SELECT, "db.t")

    bob_t = PrivilegedCatalog(cat, "bob", "pw").get_table("db.t")
    assert bob_t.to_arrow().num_rows == 0       # read allowed
    with pytest.raises(PrivilegeError):
        bob_t.new_batch_write_builder()
    with pytest.raises(PrivilegeError):
        bob_t.create_tag("x")
    pm.grant("bob", Privilege.INSERT, "db.t")
    wb = bob_t.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts([{"id": 1}])
    wb.new_commit().commit(w.prepare_commit())
    assert bob_t.to_arrow().num_rows == 1


def test_format_table_partition_columns(tmp_path):
    ft = FormatTable(str(tmp_path / "fp"), "parquet")
    ft.write(pa.table({"v": pa.array([1])}), partition={"dt": "d1"})
    ft.write(pa.table({"v": pa.array([2])}), partition={"dt": "d2"})
    out = ft.to_arrow()
    assert sorted(zip(out.column("dt").to_pylist(),
                      out.column("v").to_pylist())) == \
        [("d1", 1), ("d2", 2)]


def test_expire_cleans_stats_files(tmp_warehouse):
    import time

    table = _make(tmp_warehouse)
    _commit(table, [{"id": 1, "name": "a", "v": 1.0}])
    table.analyze()
    old_stats = table.snapshot_manager.latest_snapshot().statistics
    for i in range(3):
        _commit(table, [{"id": 2 + i, "name": "b", "v": 2.0}])
    table.analyze()
    table.expire_snapshots(retain_max=1, retain_min=1,
                           older_than_ms=int(time.time() * 1000) + 1)
    assert not os.path.exists(
        os.path.join(table.path, "statistics", old_stats))
    # the surviving ANALYZE snapshot's stats remain readable
    assert table.statistics() is not None


def test_compact_timer_window():
    """reference compact/CompactTimer.java busy-window semantics."""
    from paimon_tpu.metrics import CompactTimer
    now = [100_000]
    t = CompactTimer(window_ms=1000, clock=lambda: now[0])
    t.start()
    now[0] += 300
    t.stop()
    assert t.busy_millis() == 300
    now[0] += 500
    assert t.busy_millis() == 300
    now[0] += 600                       # interval slides out of window
    assert t.busy_millis() < 300
    t.start()
    now[0] += 200
    assert t.busy_millis() >= 200       # unfinished interval counts
    t.stop()


def test_metrics_wired_into_commit_scan_compact(tmp_path):
    from paimon_tpu.metrics import global_registry
    from paimon_tpu.schema import Schema
    from paimon_tpu.table import FileStoreTable
    from paimon_tpu.types import BigIntType

    schema = (Schema.builder().column("id", BigIntType(False))
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true"}).build())
    t = FileStoreTable.create(str(tmp_path / "m"), schema)
    before = global_registry().group("commit").counter("commits").count
    for i in range(2):
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        w.write_dicts([{"id": i}])
        wb.new_commit().commit(w.prepare_commit())
        w.close()
    t.compact(full=True)
    t.to_arrow()
    reg = global_registry()
    assert reg.group("commit").counter("commits").count >= before + 2
    assert reg.group("compaction").counter("tasks").count >= 1
    assert reg.group("scan").counter("plans").count >= 1
    assert reg.group("compaction").histogram("duration_ms").count >= 1
