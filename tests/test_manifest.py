import pytest

from paimon_tpu.data.binary_row import BINARY_ROW_EMPTY, BinaryRowCodec
from paimon_tpu.fs import LocalFileIO
from paimon_tpu.manifest import (
    DataFileMeta, FileKind, IndexFileMeta, IndexManifestEntry,
    IndexManifestFile, ManifestEntry, ManifestFile, ManifestList,
    SimpleStats, merge_manifest_entries,
)
from paimon_tpu.types import BigIntType, IntType, VarCharType


def make_file(name, level=0, min_key=1, max_key=9):
    key_codec = BinaryRowCodec([BigIntType()])
    return DataFileMeta(
        file_name=name, file_size=1024, row_count=100,
        min_key=key_codec.to_bytes((min_key,)),
        max_key=key_codec.to_bytes((max_key,)),
        key_stats=SimpleStats.from_values([BigIntType()], (min_key,),
                                          (max_key,), [0]),
        value_stats=SimpleStats.EMPTY,
        min_sequence_number=0, max_sequence_number=99,
        schema_id=0, level=level)


def entry(kind, name, bucket=0, level=0):
    return ManifestEntry(kind, BINARY_ROW_EMPTY, bucket, 2,
                         make_file(name, level))


@pytest.fixture
def mdir(tmp_path):
    return str(tmp_path / "manifest")


def test_manifest_roundtrip(mdir):
    mf = ManifestFile(LocalFileIO(), mdir)
    entries = [entry(FileKind.ADD, f"data-{i}.parquet") for i in range(10)]
    meta = mf.write(entries, schema_id=3)
    assert meta.num_added_files == 10
    assert meta.num_deleted_files == 0
    assert meta.schema_id == 3
    out = mf.read(meta.file_name)
    assert len(out) == 10
    assert out[0].file.file_name == "data-0.parquet"
    assert out[0].file.min_key == entries[0].file.min_key
    assert out[0].file.key_stats == entries[0].file.key_stats


def test_manifest_list_roundtrip(mdir):
    fio = LocalFileIO()
    mf = ManifestFile(fio, mdir)
    ml = ManifestList(fio, mdir)
    metas = [mf.write([entry(FileKind.ADD, f"f{i}.parquet")]) for i in
             range(3)]
    name, size = ml.write(metas)
    assert size > 0
    out = ml.read(name)
    assert [m.file_name for m in out] == [m.file_name for m in metas]


def test_merge_entries():
    e1 = entry(FileKind.ADD, "a.parquet")
    e2 = entry(FileKind.ADD, "b.parquet")
    e3 = entry(FileKind.DELETE, "a.parquet")
    live = merge_manifest_entries([e1, e2, e3])
    live_adds = [e for e in live if e.kind == FileKind.ADD]
    assert [e.file.file_name for e in live_adds] == ["b.parquet"]


def test_merge_respects_level():
    # same file name at different level = different identity (upgrade)
    e_add0 = entry(FileKind.ADD, "a.parquet", level=0)
    e_del0 = entry(FileKind.DELETE, "a.parquet", level=0)
    e_add1 = entry(FileKind.ADD, "a.parquet", level=1)
    live = merge_manifest_entries([e_add0, e_del0, e_add1])
    adds = [e for e in live if e.kind == FileKind.ADD]
    assert len(adds) == 1
    assert adds[0].file.level == 1


def test_partition_stats(mdir):
    part_codec = BinaryRowCodec([VarCharType(10)])
    mf = ManifestFile(LocalFileIO(), mdir,
                      partition_types=[VarCharType(10)])
    entries = []
    for dt in ["2024-01-02", "2024-01-01", "2024-01-03"]:
        e = entry(FileKind.ADD, f"{dt}.parquet")
        e.partition = part_codec.to_bytes((dt,))
        entries.append(e)
    meta = mf.write(entries)
    mins, maxs = meta.partition_stats.decode([VarCharType(10)])
    assert mins == ("2024-01-01",)
    assert maxs == ("2024-01-03",)


def test_index_manifest(mdir):
    imf = IndexManifestFile(LocalFileIO(), mdir)
    e1 = IndexManifestEntry(
        FileKind.ADD, BINARY_ROW_EMPTY, 0,
        IndexFileMeta("HASH", "index-abc-0", 400, 100))
    e2 = IndexManifestEntry(
        FileKind.ADD, BINARY_ROW_EMPTY, 1,
        IndexFileMeta("DELETION_VECTORS", "index-dv-0", 64, 10,
                      dv_ranges={"data-1.parquet": (0, 32, 5)}))
    name = imf.write([e1, e2])
    out = imf.read(name)
    assert len(out) == 2
    assert out[1].index_file.dv_ranges == {"data-1.parquet": (0, 32, 5)}
    # combine: delete the hash index
    e3 = IndexManifestEntry(
        FileKind.DELETE, BINARY_ROW_EMPTY, 0,
        IndexFileMeta("HASH", "index-abc-0", 400, 100))
    name2 = imf.combine(name, [e3])
    out2 = imf.read(name2)
    assert len(out2) == 1
    assert out2[0].index_file.index_type == "DELETION_VECTORS"
