"""Fault-injected soak harness for the streaming daemon.

Runs ingest + compaction + changelog-serving (service/stream_daemon.py)
over one primary-key table for N wall-clock seconds while a scheduled
fault plan hits the store:

- **503 storms** — FailingFileIO armed with a bounded `fail_times`, so
  a burst of mutating ops fails transiently (write retries, supervised
  loop restarts) and then heals;
- **torn two-phase uploads** — storms started with a small `fail_after`
  land on whatever mutating op comes next, including `two_phase.close`
  (the staged-bytes upload) and `two_phase.commit`;
- **kill/restart mid-checkpoint** — the store is armed to fail
  EVERYTHING, the in-flight checkpoint dies, the daemon is killed
  without drain, and a NEW daemon instance recovers from the
  checkpointed offset and replays.

The harness is also the exactly-once auditor.  It tracks the expected
materialized state (id -> v, with deletes) as it emits events, and at
the end asserts:

1. the table's final state equals the expected state (no lost events);
2. the changelog stream, materialized in consumption order across all
   daemon incarnations, equals the expected state (no lost/duplicated
   deliveries — a duplicate replayed checkpoint would re-deliver rows
   and a stale delete would corrupt the materialization);
3. committed source offsets read back from snapshot properties are
   strictly increasing and end at the last emitted offset (checkpoint
   atomicity: an offset is committed exactly when its data is);
4. commit identifiers of ingest checkpoints are strictly increasing
   (no identifier reuse across kill/restart cycles);
5. `fsck` is clean;
6. freshness (event pulled -> visible in a changelog scan) was
   measured through the obs plane; p95 is reported.

`run_soak` returns a report dict; tests assert on it.  The tier-1
smoke runs a short deterministic schedule; the `slow` variant runs
>= 60 s with more cycles (tests/test_stream_daemon.py).
"""

from __future__ import annotations

import random
import time
import uuid
from typing import Dict, List, Optional

from paimon_tpu.cdc.source import MemoryCdcSource
from paimon_tpu.core.read import ROW_KIND_COL
from paimon_tpu.metrics import (
    STREAM_CHECKPOINTS, STREAM_COMPACTIONS, STREAM_EVENTS_INGESTED,
    STREAM_FRESHNESS_MS, STREAM_LOOP_RESTARTS, global_registry,
)
from paimon_tpu.schema import Schema
from paimon_tpu.service.stream_daemon import (
    PROP_OFFSET, StreamDaemon, recover_checkpoint,
)
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType
from tests.failing_fileio import FailingFileIO

__all__ = ["run_soak"]


DEFAULT_TABLE_OPTIONS = {
    "bucket": "2",
    # small checkpoints + small trigger so a short soak exercises many
    # checkpoint commits and real compactions
    "stream.checkpoint.interval": "80",
    "stream.compaction.interval": "200",
    "num-sorted-run.compaction-trigger": "3",
    "stream.serve.poll-interval": "20",
    "stream.ingest.poll-interval": "10",
    "stream.restart.backoff": "20",
    "stream.restart.backoff.cap": "150",
    "write.retry.backoff": "5",
    # keep every snapshot: the end-of-run offset audit walks all of
    # them, and the serving loop must never lose a delta to expiry
    "snapshot.num-retained.min": "100000",
    "snapshot.num-retained.max": "100000",
}


class _Auditor:
    """Expected state + changelog materialization, upsert semantics."""

    def __init__(self):
        self.expected: Dict[int, int] = {}
        self.materialized: Dict[int, int] = {}

    def emit(self, key: int, value: Optional[int]):
        if value is None:
            self.expected.pop(key, None)
        else:
            self.expected[key] = value

    def apply(self, rows: List[dict]):
        for r in rows:
            kind = r[ROW_KIND_COL]
            if kind in (0, 2):                     # +I / +U
                self.materialized[r["id"]] = r["v"]
            elif kind == 3:                        # -D
                self.materialized.pop(r["id"], None)


def _drain(daemon: StreamDaemon, auditor: _Auditor,
           timeout: float = 0.05):
    while True:
        rows = daemon.poll_changelog(timeout=timeout)
        if not rows:
            return
        auditor.apply(rows)


def run_soak(base_dir: str, *,
             duration_s: float = 6.0,
             seed: int = 7,
             keys: int = 29,
             emit_batch: int = 4,
             emit_interval_s: float = 0.004,
             kills: int = 3,
             storms: int = 3,
             storm_fail_times: int = 5,
             mesh: bool = False,
             delete_ratio: float = 0.08,
             table_options: Optional[Dict[str, str]] = None) -> Dict:
    """Run the soak; returns the report dict (asserting internally on
    every exactly-once / convergence invariant)."""
    rng = random.Random(seed)
    fault_name = f"soak-{uuid.uuid4().hex[:8]}"

    opts = dict(DEFAULT_TABLE_OPTIONS)
    if mesh:
        opts["tpu.mesh.compact"] = "true"
    opts.update(table_options or {})
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", BigIntType())
              .primary_key("id")
              .options(opts)
              .build())
    base = FileStoreTable.create(f"{base_dir}/soak", schema)
    fio = FailingFileIO(base.file_io, fault_name)
    table = FileStoreTable(fio, base.path,
                           base.schema_manager.latest())

    source = MemoryCdcSource()
    auditor = _Auditor()
    counter = {"n": 0}

    def emit_some(k: int):
        events = []
        for _ in range(k):
            n = counter["n"]
            counter["n"] = n + 1
            key = n % keys
            if auditor.expected.get(key) is not None and \
                    rng.random() < delete_ratio:
                events.append({"op": "d", "before": {"id": key,
                                                     "v": n}})
                auditor.emit(key, None)
            else:
                events.append({"op": "c", "after": {"id": key,
                                                    "v": n}})
                auditor.emit(key, n)
        source.append(*events)

    # fault schedule: kills evenly spaced in the middle 70% of the run,
    # storms offset between them
    t_start = time.monotonic()
    t_end = t_start + duration_s
    emit_until = t_start + duration_s * 0.8
    kill_at = [t_start + duration_s * (0.15 + 0.7 * (i + 1)
                                       / (kills + 1))
               for i in range(kills)]
    storm_at = [t_start + duration_s * (0.1 + 0.7 * (i + 0.5)
                                        / (storms + 1))
                for i in range(storms)]
    storms_done = kills_done = 0

    g = global_registry().stream_metrics()
    base_counts = {name: g.counter(name).count
                   for name in (STREAM_EVENTS_INGESTED,
                                STREAM_CHECKPOINTS,
                                STREAM_LOOP_RESTARTS,
                                STREAM_COMPACTIONS)}

    daemon = StreamDaemon(table, source).start()
    incarnations = 1
    last_emit = 0.0
    try:
        while time.monotonic() < t_end:
            now = time.monotonic()
            if now < emit_until and now - last_emit >= emit_interval_s:
                emit_some(emit_batch)
                last_emit = now
            _drain(daemon, auditor, timeout=0.0)
            if storms_done < storms and now >= storm_at[storms_done]:
                # transient 503 storm; small fail_after tears whatever
                # comes next (incl. two-phase closes/commits)
                FailingFileIO.reset(fault_name,
                                    rng.randrange(0, 4),
                                    fail_times=storm_fail_times)
                storms_done += 1
            if kills_done < kills and now >= kill_at[kills_done]:
                # kill mid-checkpoint: everything fails, the in-flight
                # checkpoint dies, then the process "dies"
                FailingFileIO.reset(fault_name, 0)
                time.sleep(0.05)
                daemon.kill()
                FailingFileIO.disarm(fault_name)
                _drain(daemon, auditor)        # old incarnation's tail
                daemon = StreamDaemon(table, source).start()
                incarnations += 1
                kills_done += 1
            time.sleep(0.002)

        FailingFileIO.disarm(fault_name)
        # convergence: wait until the last emitted offset is committed
        last_offset = source.latest_offset()
        deadline = time.monotonic() + max(30.0, duration_s)
        while time.monotonic() < deadline:
            _drain(daemon, auditor, timeout=0.0)
            if daemon.status()["offset_committed"] >= last_offset:
                break
            time.sleep(0.05)
        status = daemon.stop(drain=True)
        _drain(daemon, auditor)
    finally:
        FailingFileIO.disarm(fault_name)
        daemon.kill()

    assert status["offset_committed"] == last_offset, \
        f"daemon never converged: committed " \
        f"{status['offset_committed']} < emitted {last_offset}"

    # -- audits (all on a clean FileIO) --------------------------------------
    final = FileStoreTable.load(base.path)
    table_state = {r["id"]: r["v"]
                   for r in final.to_arrow().to_pylist()}
    assert table_state == auditor.expected, \
        "table state diverged from emitted events (lost/dup writes)"
    assert auditor.materialized == auditor.expected, \
        "changelog materialization diverged (lost/dup deliveries)"

    offsets, idents = [], []
    for snap in final.snapshot_manager.snapshots():
        if snap.commit_user == "stream-daemon" and snap.properties \
                and PROP_OFFSET in snap.properties:
            offsets.append(int(snap.properties[PROP_OFFSET]))
            idents.append(snap.commit_identifier)
    assert offsets == sorted(set(offsets)), \
        f"committed offsets not strictly increasing: {offsets}"
    assert offsets and offsets[-1] == last_offset
    assert idents == sorted(set(idents)), \
        f"commit identifiers not strictly increasing: {idents}"
    assert recover_checkpoint(final, "stream-daemon")[0] == last_offset

    report = final.fsck()
    assert report.ok, [v.to_dict() for v in report.violations]

    freshness = g.histogram(STREAM_FRESHNESS_MS)
    assert freshness.total_count > 0, \
        "no freshness samples: the serving loop never measured " \
        "event -> changelog-visible latency"

    return {
        "duration_s": round(time.monotonic() - t_start, 2),
        "events_emitted": counter["n"],
        "events_ingested": g.counter(STREAM_EVENTS_INGESTED).count
        - base_counts[STREAM_EVENTS_INGESTED],
        "checkpoints": g.counter(STREAM_CHECKPOINTS).count
        - base_counts[STREAM_CHECKPOINTS],
        "loop_restarts": g.counter(STREAM_LOOP_RESTARTS).count
        - base_counts[STREAM_LOOP_RESTARTS],
        "compactions": g.counter(STREAM_COMPACTIONS).count
        - base_counts[STREAM_COMPACTIONS],
        "kill_restart_cycles": kills_done,
        "storms": storms_done,
        "daemon_incarnations": incarnations,
        "keys_final": len(auditor.expected),
        "freshness_p95_ms": freshness.percentile(95),
        "freshness_samples": freshness.total_count,
        "fsck_ok": True,
        "final_offset": last_offset,
        "snapshots": final.snapshot_manager.snapshot_count(),
    }
