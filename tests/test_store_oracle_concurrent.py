"""Seeded multi-writer randomized sweeps (VERDICT r3 next #7).

reference: operation/commit/ConflictDetection.java,
FileStoreCommitImpl.java:756 retry loop; TestFileStore.java (the
single-writer oracle this extends with real thread interleavings).

Env knobs for long mode: ORACLE_CONCURRENT_SEEDS=20 runs more seeds.
"""

import os

import pytest

from tests.store_oracle import ConcurrentOracle

_SEEDS = int(os.environ.get("ORACLE_CONCURRENT_SEEDS", "3"))


@pytest.mark.parametrize("seed", range(_SEEDS))
class TestConcurrentOracle:
    def test_disjoint_writers_exact(self, tmp_path, seed):
        """3 writers on disjoint partitions + racing compactor: exact
        model equality regardless of interleaving."""
        ConcurrentOracle(str(tmp_path / "t"), seed=seed,
                         mode="disjoint-dedup", writers=3).run()

    def test_overlapping_aggregation_exact(self, tmp_path, seed):
        """3 writers on ONE shared key space with commutative
        aggregates (sum/max): final state is interleaving-independent,
        exact equality must hold."""
        ConcurrentOracle(str(tmp_path / "t"), seed=seed + 100,
                         mode="overlap-agg", writers=3).run()

    def test_overlapping_dedup_invariants(self, tmp_path, seed):
        """2 writers + compactor racing on shared keys: winners are
        timing-dependent, but no torn rows, no phantom keys, and a
        quiescent full compaction is a no-op on state."""
        ConcurrentOracle(str(tmp_path / "t"), seed=seed + 200,
                         mode="overlap-dedup", writers=2).run()
