"""Clone: independent copy of a table's current state.

reference: flink/procedure/CloneProcedure + clone/ actions.
"""

import os

import pytest

from paimon_tpu.catalog import create_catalog
from paimon_tpu.maintenance.clone import clone_table
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, RowKind


def _commit(table, rows, kinds=None):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows, row_kinds=kinds)
    sid = wb.new_commit().commit(w.prepare_commit())
    w.close()
    return sid


def _cat(tmp_path):
    cat = create_catalog({"warehouse": str(tmp_path / "wh")})
    cat.create_database("db", ignore_if_exists=True)
    return cat


class TestClone:
    def test_pk_table_levels_and_independence(self, tmp_path):
        cat = _cat(tmp_path)
        src = cat.create_table("db.src", (
            Schema.builder()
            .column("id", BigIntType(False))
            .column("v", DoubleType())
            .primary_key("id")
            .options({"bucket": "1"})
            .build()))
        _commit(src, [{"id": i, "v": float(i)} for i in range(10)])
        src.compact(full=True)
        _commit(src, [{"id": 1, "v": 111.0}])     # L0 over compacted L5

        dst = clone_table(cat, "db.src", "db.dst")
        got = dst.to_arrow().sort_by("id")
        assert got.num_rows == 10
        assert got.column("v").to_pylist()[1] == 111.0   # merge preserved

        # the clone is INDEPENDENT: writes diverge both ways
        _commit(dst, [{"id": 99, "v": 9.0}])
        _commit(src, [{"id": 50, "v": 5.0}])
        assert dst.to_arrow().num_rows == 11
        assert FileStoreTable.load(src.path).to_arrow().num_rows == 11
        assert 99 not in FileStoreTable.load(src.path) \
            .to_arrow().column("id").to_pylist()

    def test_clone_carries_deletion_vectors(self, tmp_path):
        from paimon_tpu import predicate as P
        cat = _cat(tmp_path)
        src = cat.create_table("db.s2", (
            Schema.builder()
            .column("id", BigIntType(False))
            .column("v", DoubleType())
            .options({"bucket": "-1"})
            .build()))
        _commit(src, [{"id": i, "v": float(i)} for i in range(8)])
        src.delete_where(P.less_than("id", 3))
        assert src.to_arrow().num_rows == 5
        dst = clone_table(cat, "db.s2", "db.d2")
        assert sorted(dst.to_arrow().column("id").to_pylist()) == \
            [3, 4, 5, 6, 7]

    def test_sql_procedure(self, tmp_path):
        from paimon_tpu.sql import SQLContext
        cat = _cat(tmp_path)
        ctx = SQLContext(cat)
        ctx.sql("CREATE TABLE db.a (id BIGINT NOT NULL, "
                "PRIMARY KEY (id)) WITH ('bucket'='1')")
        ctx.sql("INSERT INTO db.a VALUES (1), (2)")
        out = ctx.sql("CALL sys.clone('db.a', 'db.b')")
        assert "cloned" in str(out.to_pylist())
        assert ctx.sql("SELECT count(*) AS n FROM db.b").to_pylist() \
            == [{"n": 2}]

    def test_clone_schema_evolved_table(self, tmp_path):
        from paimon_tpu.schema import SchemaChange, SchemaManager
        from paimon_tpu.types import IntType
        cat = _cat(tmp_path)
        src = cat.create_table("db.ev", (
            Schema.builder()
            .column("id", BigIntType(False))
            .column("v", DoubleType())
            .primary_key("id")
            .options({"bucket": "1"})
            .build()))
        _commit(src, [{"id": 1, "v": 1.0}])
        sm = SchemaManager(src.file_io, src.path)
        sm.commit_changes(SchemaChange.add_column("extra", IntType()))
        src = FileStoreTable.load(src.path)
        _commit(src, [{"id": 2, "v": 2.0, "extra": 7}])

        dst = clone_table(cat, "db.ev", "db.ev2")
        got = dst.to_arrow().sort_by("id").to_pylist()
        assert got == [{"id": 1, "v": 1.0, "extra": None},
                       {"id": 2, "v": 2.0, "extra": 7}]

    def test_clone_unqualified_names_via_use(self, tmp_path):
        from paimon_tpu.sql import SQLContext
        cat = _cat(tmp_path)
        ctx = SQLContext(cat)
        ctx.sql("USE db")
        ctx.sql("CREATE TABLE s3 (id BIGINT NOT NULL, "
                "PRIMARY KEY (id)) WITH ('bucket'='1')")
        ctx.sql("INSERT INTO s3 VALUES (1)")
        out = ctx.sql("CALL sys.clone('s3', 'd3')")
        assert "cloned 1 rows" in str(out.to_pylist())
        assert ctx.sql("SELECT count(*) AS n FROM d3").to_pylist() == \
            [{"n": 1}]
