"""Mesh-sharded maintenance plane over a REAL 2-process gloo mesh:
host-death-tolerant streaming daemons and the distributed rescale.

ISSUE acceptance layer (the in-process rehearsal lives in
tests/test_maintenance_plane.py):

- `test_multihost_soak_host_kill_two_process` — two gloo processes
  each run a distributed StreamDaemon (sharded ingest/compaction/
  serving, per-host commit users + consumers) over ONE table and the
  identical deterministic CDC stream; process 1 is killed abruptly
  (`os._exit`) mid-soak.  The survivor's lease detector declares it
  dead, adopts its buckets (backfill exactly-once, serve catch-up
  from the dead consumer's position) and keeps compacting.  The
  parent audits: final table byte-identical to the single-process
  oracle, merged changelog materialization equals the expected state
  (no lost or duplicated deliveries), per-user committed offsets
  strictly increasing, `maintenance_takeovers` > 0 with every bucket
  re-leased to the survivor, compaction progressed AFTER the kill,
  and fsck — ownership-consistency check included — is clean.

- `test_distributed_rescale_two_process_owned_buckets_only` — the
  rescale REWRITE is sharded: each host writes only the new buckets
  it will own under the bumped map (asserted in-worker and
  cross-checked over the mesh), the elected committer publishes ONE
  overwrite, and the result is byte-identical to the oracle.

- `test_multihost_soak_full` (slow) — longer stream, a 503 storm on
  the survivor riding the write-retry ladder, later kill.
"""

import json
import os
import socket
import subprocess
import sys
import time

import pytest

from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType

from tests.multihost_soak import expected_state, materialize

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_NO_CPU_COLLECTIVES = "Multiprocess computations aren't implemented"


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_workers(worker_src, tmp_path, n_procs, args=None,
                 expected_rc=None, timeout=420):
    port = _free_port()
    table_path = str(tmp_path / "t")
    worker_py = tmp_path / "worker.py"
    worker_py.write_text(worker_src)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    procs = [subprocess.Popen(
        [sys.executable, str(worker_py), str(pid), str(port),
         table_path, REPO, str(n_procs)] + [str(a) for a in (args or [])],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(n_procs)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    if any(_NO_CPU_COLLECTIVES in out for out in outs):
        pytest.skip("jaxlib CPU backend lacks Gloo cross-process "
                    "collectives; multi-host CPU emulation cannot run")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        want = (expected_rc or {}).get(pid, 0)
        assert p.returncode == want, \
            f"proc {pid} rc={p.returncode} (want {want}):\n{out[-6000:]}"
    return table_path, outs


_PROLOG = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")

pid = int(sys.argv[1]); port = sys.argv[2]; table_path = sys.argv[3]
REPO = sys.argv[4]
sys.path.insert(0, REPO); n_procs = int(sys.argv[5])
sys.path.insert(0, os.path.join(REPO, "tests"))

from paimon_tpu.parallel import multihost as MH

# peer death is the EVENT UNDER TEST: widen the coordination
# service's missed-heartbeat budget so the survivor is governed by
# its leases (and the parent's timeout), not aborted by XLA ~100s
# after the victim's os._exit
idx, count = MH.initialize(f"127.0.0.1:{port}", n_procs, pid,
                           max_missing_heartbeats=360)
assert (idx, count) == (pid, n_procs)

from paimon_tpu import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType

def make_schema(extra):
    return (Schema.builder()
            .column("id", BigIntType(False))
            .column("v", BigIntType())
            .primary_key("id")
            .options(extra)
            .build())

def shared_table(extra):
    if pid == 0:
        FileStoreTable.create(table_path, make_schema(extra))
    MH.barrier("table-created")
    return FileStoreTable.load(table_path)
'''


_SOAK_WORKER = _PROLOG + r'''
import json, time
from multihost_soak import (
    SOAK_TABLE_OPTIONS, gen_events,
)
from paimon_tpu.cdc.source import MemoryCdcSource
from paimon_tpu.metrics import (
    MULTIHOST_MAINTENANCE_TAKEOVERS, MULTIHOST_OWNED_BUCKETS,
    STREAM_COMPACTIONS, global_registry,
)
from paimon_tpu.parallel.maintenance_plane import MaintenancePlane
from paimon_tpu.service.stream_daemon import StreamDaemon

N_TOTAL = int(sys.argv[6])
KILL_AFTER = int(sys.argv[7])        # victim dies past this offset
STORM = int(sys.argv[8])             # survivor 503 storms (slow soak)
TICK_S = 0.025
PER_TICK = 6

t = shared_table(dict(SOAK_TABLE_OPTIONS))
fio = t.file_io
if STORM and pid == 0:
    sys.path.insert(0, os.path.join(REPO, "tests"))
    from failing_fileio import FailingFileIO
    fio = FailingFileIO(t.file_io, f"mh-soak-p{pid}")
    t = FileStoreTable(fio, t.path, t.schema_manager.latest())

plane = MaintenancePlane(t, base_user="stream-daemon")
source = MemoryCdcSource()
daemon = StreamDaemon(t, source, commit_user="stream-daemon",
                      plane=plane).start()

rows_path = table_path + f".rows-p{pid}.jsonl"
rows_f = open(rows_path, "a")

def drain():
    while True:
        rows = daemon.poll_changelog(timeout=0.0)
        if not rows:
            rows_f.flush()
            return
        for r in rows:
            rows_f.write(json.dumps(r) + "\n")

g = global_registry()
emitted = 0
storms_done = 0
compactions_at_kill = None
marker = table_path + ".victim-dead"
while emitted < N_TOTAL:
    source.append(*gen_events(emitted, emitted + PER_TICK))
    emitted += PER_TICK
    drain()
    # sample the compaction counter the moment the victim's death is
    # visible: "compaction progressed AFTER the kill" must count the
    # work done on the post-kill two-thirds of the stream.  Sampling
    # after the emit loop raced — a compactor that caught up exactly
    # at stream end had nothing left to do, and the worker burned its
    # whole 120s progress window on an already-converged table
    if compactions_at_kill is None and os.path.exists(marker):
        compactions_at_kill = g.stream_metrics().counter(
            STREAM_COMPACTIONS).count
    if pid == n_procs - 1 and emitted >= KILL_AFTER:
        # HOST DEATH: no drain, no final checkpoint, no goodbye —
        # everything past the last committed checkpoint is lost and
        # must be re-ingested exactly-once by the survivor
        drain()
        rows_f.flush(); rows_f.close()
        open(marker, "w").close()
        os._exit(42)
    if STORM and pid == 0 and storms_done < STORM and \
            emitted >= (storms_done + 1) * N_TOTAL // (STORM + 2):
        # bounded 503 storm on the survivor: the write-retry ladder +
        # supervised loop restarts must absorb it
        FailingFileIO.reset(f"mh-soak-p{pid}", 0, fail_times=4)
        storms_done += 1
    time.sleep(TICK_S)

# survivor: converge on EVERYTHING (own share + adopted share)
deadline = time.time() + 240
while time.time() < deadline:
    drain()
    st = daemon.status()
    if compactions_at_kill is None and os.path.exists(marker):
        compactions_at_kill = g.stream_metrics().counter(
            STREAM_COMPACTIONS).count
    if st["offset_committed"] >= N_TOTAL - 1 and \
            st["distributed"]["adopted"] == [n_procs - 1]:
        break
    time.sleep(0.05)

st = daemon.status()
assert st["distributed"]["adopted"] == [n_procs - 1], st
assert st["offset_committed"] >= N_TOTAL - 1, st

# compaction must PROGRESS after the kill (the dead host's buckets
# are the survivor's problem now) — wait for at least one more run
deadline = time.time() + 120
while time.time() < deadline:
    if g.stream_metrics().counter(STREAM_COMPACTIONS).count > \
            (compactions_at_kill or 0):
        break
    time.sleep(0.1)
post_kill_compactions = g.stream_metrics().counter(
    STREAM_COMPACTIONS).count - (compactions_at_kill or 0)

daemon.stop(drain=True)
drain()
rows_f.close()

mh = g.multihost_metrics()
summary = {
    "takeovers": mh.counter(MULTIHOST_MAINTENANCE_TAKEOVERS).count,
    "owned_buckets": mh.gauge(MULTIHOST_OWNED_BUCKETS).value,
    "post_kill_compactions": post_kill_compactions,
    "offset_committed": daemon.status()["offset_committed"],
    "ownership_version": plane.ownership.version,
    "dead": sorted(plane.ownership.dead),
}
with open(table_path + ".summary.json", "w") as f:
    json.dump(summary, f)
print(f"proc {pid}: MH-SOAK-OK {json.dumps(summary)}", flush=True)
sys.stdout.flush()
os._exit(0)
'''


def _audit_soak(table_path, outs, n_total, n_procs=2):
    victim = n_procs - 1
    assert "MH-SOAK-OK" in outs[0], outs[0][-6000:]

    expected = expected_state(n_total)
    final = FileStoreTable.load(table_path)

    # byte-identity to the single-process oracle
    oracle_path = table_path + "-oracle"
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", BigIntType())
              .primary_key("id")
              .options({"bucket": "4"})
              .build())
    oracle = FileStoreTable.create(oracle_path, schema)
    wb = oracle.new_batch_write_builder()
    with wb.new_write() as w:
        w.write_dicts([{"id": k, "v": v}
                       for k, v in sorted(expected.items())])
        wb.new_commit().commit(w.prepare_commit())
    assert final.to_arrow().sort_by("id").equals(
        oracle.to_arrow().sort_by("id")), \
        "distributed daemon state != single-process oracle"

    # merged changelog materialization: the victim's stream first
    # (all its deliveries predate the takeover), then the survivor's
    streams = []
    for p in (victim, 0):
        rows = []
        with open(f"{table_path}.rows-p{p}.jsonl") as f:
            for line in f:
                rows.append(json.loads(line))
        streams.append(rows)
    assert materialize(streams) == expected, \
        "changelog deliveries lost or duplicated across the takeover"

    # per-user committed offsets strictly increasing; the survivor's
    # chain ends at the final offset
    offsets = {p: [] for p in range(n_procs)}
    for snap in final.snapshot_manager.snapshots():
        for p in range(n_procs):
            if snap.commit_user == f"stream-daemon-p{p}" and \
                    snap.properties and \
                    "stream.source.offset" in snap.properties:
                offsets[p].append(
                    int(snap.properties["stream.source.offset"]))
    for p in range(n_procs):
        assert offsets[p], f"user p{p} never checkpointed"
        assert offsets[p] == sorted(set(offsets[p])), \
            f"p{p} offsets not strictly increasing: {offsets[p]}"
    assert offsets[0][-1] == n_total - 1

    # the takeover is visible: buckets re-leased, compaction resumed
    with open(table_path + ".summary.json") as f:
        summary = json.load(f)
    assert summary["takeovers"] > 0
    assert summary["owned_buckets"] == 4          # every bucket mine
    assert summary["dead"] == [victim]
    assert summary["post_kill_compactions"] > 0, \
        "compaction stalled after the host kill"

    # ownership generation recorded, graph clean (ownership check on)
    from paimon_tpu.parallel.distributed import resume_ownership_map
    resumed = resume_ownership_map(final)
    assert resumed is not None and resumed.dead == frozenset({victim})
    report = final.fsck()
    assert report.ok, [v.to_dict() for v in report.violations]


def test_multihost_soak_host_kill_two_process(tmp_path):
    """ISSUE acceptance: a mid-soak host kill on a real 2-process
    gloo mesh loses no events, stalls no compaction, converges
    byte-identical to the single-process oracle, re-leases the dead
    host's buckets (maintenance_takeovers > 0) and stays
    fsck-clean."""
    n_total = 1080
    table_path, outs = _run_workers(
        _SOAK_WORKER, tmp_path, 2,
        args=[n_total, n_total // 3, 0],
        expected_rc={1: 42}, timeout=420)
    _audit_soak(table_path, outs, n_total)


@pytest.mark.slow
def test_multihost_soak_full(tmp_path):
    """Slow variant: longer stream, two bounded 503 storms on the
    survivor riding the write-retry ladder, a later kill."""
    n_total = 4200
    table_path, outs = _run_workers(
        _SOAK_WORKER, tmp_path, 2,
        args=[n_total, n_total // 2, 2],
        expected_rc={1: 42}, timeout=560)
    _audit_soak(table_path, outs, n_total)


# -- kill-two-then-rejoin chaos soak (ISSUE 17 tentpole) ----------------------

_REJOIN_SOAK_WORKER = _PROLOG + r'''
import json, time
from multihost_soak import SOAK_TABLE_OPTIONS, gen_events
from paimon_tpu.cdc.source import MemoryCdcSource
from paimon_tpu.metrics import (
    FLEET_GENERATIONS, FLEET_REJOINS,
    MULTIHOST_MAINTENANCE_TAKEOVERS, global_registry,
)
from paimon_tpu.parallel.maintenance_plane import MaintenancePlane
from paimon_tpu.service.stream_daemon import StreamDaemon

N_TOTAL = int(sys.argv[6])
KILL = int(sys.argv[7])       # pid 2 dies past this offset (abrupt)
KILL2 = int(sys.argv[8])      # pid 1 dies past this one, AT the CAS
STORM = int(sys.argv[9])      # survivor 503 storms (slow soak)
TICK_S = 0.025
PER_TICK = 6

t = shared_table(dict(SOAK_TABLE_OPTIONS))
if pid == 1 or (pid == 0 and STORM):
    from failing_fileio import FailingFileIO
    fio = FailingFileIO(t.file_io, f"mh-rejoin-p{pid}")
    t = FileStoreTable(fio, t.path, t.schema_manager.latest())

plane = MaintenancePlane(t, base_user="stream-daemon")
source = MemoryCdcSource()
daemon = StreamDaemon(t, source, commit_user="stream-daemon",
                      plane=plane).start()

rows_f = open(table_path + f".rows-p{pid}.jsonl", "a")
def drain():
    while True:
        rows = daemon.poll_changelog(timeout=0.0)
        if not rows:
            rows_f.flush(); return
        for r in rows:
            rows_f.write(json.dumps(r) + "\n")

g = global_registry()
adopted_marker = table_path + ".adopted-all"
emitted = 0
while emitted < N_TOTAL:
    source.append(*gen_events(emitted, emitted + PER_TICK))
    emitted += PER_TICK
    drain()
    if pid == 2 and emitted >= KILL:
        # abrupt host death mid-traffic: no drain, no goodbye
        rows_f.flush(); rows_f.close()
        os._exit(42)
    if pid == 1 and emitted >= KILL2:
        # die AT the snapshot CAS: every store op now fails
        # (InjectedIOError mid-upload), so the in-flight checkpoint
        # tears partway — then the host is gone.  Cascading: pid 2 is
        # already dead, so this victim's takeover floor must come
        # from the generation history, not the current dead set
        FailingFileIO.reset("mh-rejoin-p1", 0, fail_times=10000)
        time.sleep(0.4)
        rows_f.flush(); rows_f.close()
        os._exit(42)
    if pid == 0:
        if STORM and emitted in (KILL, KILL2):
            # 503 storm on the survivor exactly while it is trying
            # to adopt a victim: rides the commit retry ladder
            FailingFileIO.reset("mh-rejoin-p0", 0, fail_times=STORM)
        if not os.path.exists(adopted_marker):
            d = daemon.status()["distributed"]
            if sorted(d["adopted"]) == [1, 2]:
                open(adopted_marker, "w").close()  # parent: rejoins
    time.sleep(TICK_S)

# survivor: finish adopting both victims if the emission loop ended
# first, then publish the marker that lets the parent resurrect them
deadline = time.time() + 240
while not os.path.exists(adopted_marker):
    assert time.time() < deadline, daemon.status()
    drain()
    d = daemon.status()["distributed"]
    if sorted(d["adopted"]) == [1, 2]:
        open(adopted_marker, "w").close()
        break
    time.sleep(0.05)

# carry the fleet through both rejoins to convergence
deadline = time.time() + 240
done = False
while time.time() < deadline:
    drain()
    st = daemon.status()
    if st["offset_committed"] >= N_TOTAL - 1 and \
            not plane.ownership.dead and \
            os.path.exists(table_path + ".rejoined-p1") and \
            os.path.exists(table_path + ".rejoined-p2"):
        done = True
        break
    time.sleep(0.05)
assert done, daemon.status()

# release the rejoiners: they hold their daemons (and leases) alive
# until this marker so the all-alive observation above cannot race
# their teardown — an exited rejoiner's lease expires in ~1.5s and
# the detector would (correctly) declare it dead AGAIN
open(table_path + ".fleet-converged", "w").close()

daemon.stop(drain=True)
drain()
rows_f.close()

fleet = g.fleet_metrics()
summary = {
    "takeovers": g.multihost_metrics().counter(
        MULTIHOST_MAINTENANCE_TAKEOVERS).count,
    "rejoins": fleet.counter(FLEET_REJOINS).count,
    "generations": fleet.gauge(FLEET_GENERATIONS).value,
    "offset_committed": daemon.status()["offset_committed"],
    "ownership_version": plane.ownership.version,
    "dead": sorted(plane.ownership.dead),
}
with open(table_path + ".summary.json", "w") as f:
    json.dump(summary, f)
print(f"proc {pid}: MH-SOAK-OK {json.dumps(summary)}", flush=True)
sys.stdout.flush()
os._exit(0)
'''


# second incarnation of a killed host: NO mesh bring-up — rejoin is a
# store-only protocol, so the resurrected process needs nothing but
# the table path and its old process index
_REJOIN_WORKER = r'''
import os, sys, json, time
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

pid = int(sys.argv[1]); table_path = sys.argv[3]
REPO = sys.argv[4]; n_procs = int(sys.argv[5])
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))
N_TOTAL = int(sys.argv[6])

from multihost_soak import gen_events
from paimon_tpu.cdc.source import MemoryCdcSource
from paimon_tpu.parallel.maintenance_plane import MaintenancePlane
from paimon_tpu.service.stream_daemon import StreamDaemon
from paimon_tpu.table import FileStoreTable

t = FileStoreTable.load(table_path)
plane = MaintenancePlane(t, base_user="stream-daemon",
                         process_index=pid, process_count=n_procs)
assert plane.rejoining, \
    "restart of a dead-recorded host must enter the rejoining state"
source = MemoryCdcSource()
source.append(*gen_events(0, N_TOTAL))   # full replayable history
daemon = StreamDaemon(t, source, commit_user="stream-daemon",
                      plane=plane).start()

rows_f = open(table_path + f".rows-p{pid}.jsonl", "a")
def drain():
    while True:
        rows = daemon.poll_changelog(timeout=0.0)
        if not rows:
            rows_f.flush(); return
        for r in rows:
            rows_f.write(json.dumps(r) + "\n")

deadline = time.time() + 240
ok = False
while time.time() < deadline:
    drain()
    st = daemon.status()
    if not st["distributed"]["rejoining"] and \
            st["offset_committed"] >= N_TOTAL - 1:
        ok = True
        break
    time.sleep(0.05)
st = daemon.status()
assert ok, st
open(table_path + f".rejoined-p{pid}", "w").close()
summary = {"rejoin_replayed": st["distributed"]["rejoin_replayed"],
           "offset_committed": st["offset_committed"],
           "ownership_version": st["distributed"]["ownership_version"]}
with open(table_path + f".rejoin-summary-p{pid}.json", "w") as f:
    json.dump(summary, f)
# stay ALIVE (daemon heartbeating, lease fresh) until the survivor
# has observed the all-alive fleet — exiting now would expire this
# host's lease mid-observation and the detector would re-declare it
# dead, which the survivor's convergence wait could never recover
# from (a correct re-death, but not the lifecycle under test)
release = time.time() + 240
while not os.path.exists(table_path + ".fleet-converged") and \
        time.time() < release:
    drain()
    time.sleep(0.05)
daemon.stop(drain=True)
drain()
rows_f.close()
print(f"proc {pid}: MH-REJOIN-OK {json.dumps(summary)}", flush=True)
sys.stdout.flush()
os._exit(0)
'''


def _run_rejoin_soak(tmp_path, n_total, kill, kill2, storm=0,
                     timeout=420):
    port = _free_port()
    table_path = str(tmp_path / "t")
    worker_py = tmp_path / "worker.py"
    worker_py.write_text(_REJOIN_SOAK_WORKER)
    rejoin_py = tmp_path / "rejoin.py"
    rejoin_py.write_text(_REJOIN_WORKER)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)

    def spawn(py, pid, extra):
        return subprocess.Popen(
            [sys.executable, str(py), str(pid), str(port), table_path,
             REPO, "3"] + [str(a) for a in extra],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env)

    procs = {p: spawn(worker_py, p, [n_total, kill, kill2, storm])
             for p in range(3)}
    outs = {}
    try:
        for p in (2, 1):            # victims die first, in order
            outs[p], _ = procs[p].communicate(timeout=timeout)
            if _NO_CPU_COLLECTIVES in outs[p]:
                pytest.skip("jaxlib CPU backend lacks Gloo "
                            "cross-process collectives")
            assert procs[p].returncode == 42, \
                f"victim {p} rc={procs[p].returncode}:\n" \
                f"{outs[p][-6000:]}"
        # survivor adopts both; fsck mid-chaos (two hosts down)
        deadline = time.time() + timeout
        while not os.path.exists(table_path + ".adopted-all"):
            assert procs[0].poll() is None, \
                procs[0].communicate()[0][-6000:]
            assert time.time() < deadline, \
                "survivor never adopted both victims"
            time.sleep(0.1)
        mid = FileStoreTable.load(table_path).fsck()
        assert mid.ok, [v.to_dict() for v in mid.violations]
        # resurrect both victims — store-only rejoin, no mesh
        rejoiners = {p: spawn(rejoin_py, p, [n_total])
                     for p in (1, 2)}
        for p in (1, 2):
            out, _ = rejoiners[p].communicate(timeout=timeout)
            outs[f"rejoin{p}"] = out
            assert rejoiners[p].returncode == 0, \
                f"rejoiner {p}:\n{out[-6000:]}"
        outs[0], _ = procs[0].communicate(timeout=timeout)
        assert procs[0].returncode == 0, outs[0][-6000:]
    finally:
        for pr in procs.values():
            if pr.poll() is None:
                pr.kill()
    return table_path, outs


def _audit_rejoin_soak(table_path, outs, n_total):
    assert "MH-SOAK-OK" in outs[0], outs[0][-6000:]
    for p in (1, 2):
        assert "MH-REJOIN-OK" in outs[f"rejoin{p}"], \
            outs[f"rejoin{p}"][-6000:]

    expected = expected_state(n_total)
    final = FileStoreTable.load(table_path)

    # byte-identity to the single-process oracle
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", BigIntType())
              .primary_key("id")
              .options({"bucket": "4"})
              .build())
    oracle = FileStoreTable.create(table_path + "-oracle", schema)
    wb = oracle.new_batch_write_builder()
    with wb.new_write() as w:
        w.write_dicts([{"id": k, "v": v}
                       for k, v in sorted(expected.items())])
        wb.new_commit().commit(w.prepare_commit())
    assert final.to_arrow().sort_by("id").equals(
        oracle.to_arrow().sort_by("id")), \
        "post-rejoin fleet state != single-process oracle"

    # per-user committed offsets strictly increasing ACROSS both
    # incarnations of each victim, and every host drained to the end
    offsets = {p: [] for p in range(3)}
    for snap in final.snapshot_manager.snapshots():
        for p in range(3):
            if snap.commit_user == f"stream-daemon-p{p}" and \
                    snap.properties and \
                    "stream.source.offset" in snap.properties:
                offsets[p].append(
                    int(snap.properties["stream.source.offset"]))
    for p in range(3):
        assert offsets[p], f"user p{p} never checkpointed"
        assert offsets[p] == sorted(set(offsets[p])), \
            f"p{p} offsets not strictly increasing: {offsets[p]}"
        assert offsets[p][-1] == n_total - 1, \
            f"p{p} did not converge: {offsets[p][-1]}"

    # exactly-once cascading takeover + both rejoins, on /metrics
    with open(table_path + ".summary.json") as f:
        summary = json.load(f)
    assert summary["takeovers"] >= 2, summary
    assert summary["rejoins"] >= 2, summary
    assert summary["dead"] == [], summary
    assert summary["generations"] == summary["ownership_version"]
    for p in (1, 2):
        with open(f"{table_path}.rejoin-summary-p{p}.json") as f:
            rs = json.load(f)
        assert rs["rejoin_replayed"] > 0, \
            f"rejoiner {p} replayed no gap rows: {rs}"

    # the persisted generation history is exact: bring-up, both
    # deaths, both readmissions — versions strictly increasing,
    # the double-death generation present, nobody dead at the tip
    from paimon_tpu.parallel.distributed import (
        resume_generation_history,
    )
    hist = resume_generation_history(final)
    assert hist is not None
    versions = [m.version for m in hist.entries]
    assert versions == sorted(set(versions)), versions
    assert any(m.dead == frozenset({1, 2}) for m in hist.entries), \
        [(m.version, sorted(m.dead)) for m in hist.entries]
    assert hist.current().dead == frozenset()

    report = final.fsck()
    assert report.ok, [v.to_dict() for v in report.violations]


def test_multihost_soak_kill_two_then_rejoin(tmp_path):
    """ISSUE 17 acceptance (smoke scale): real 3-process gloo mesh,
    two hosts killed mid-traffic — one abruptly, one at the snapshot
    CAS under an injected IO storm (torn uploads) — cascading
    exactly-once takeover computed from the persisted generation
    history, then BOTH victims rejoin with no operator: readmitted by
    the elected survivor, offset gaps replayed, final table
    byte-identical to the single-process oracle, per-user offsets
    strictly increasing, fsck clean mid-chaos and after,
    `rejoins >= 2` and `maintenance_takeovers >= 2`."""
    n_total = 1080
    table_path, outs = _run_rejoin_soak(
        tmp_path, n_total, kill=360, kill2=480)
    _audit_rejoin_soak(table_path, outs, n_total)


@pytest.mark.slow
def test_multihost_soak_kill_two_then_rejoin_storm(tmp_path):
    """Storm variant: longer stream and a 503 storm armed on the
    SURVIVOR at both kill offsets, so each cascading adoption commit
    has to climb the write-retry ladder while the dying host's torn
    uploads are still on disk."""
    n_total = 2400
    table_path, outs = _run_rejoin_soak(
        tmp_path, n_total, kill=798, kill2=948, storm=4, timeout=560)
    _audit_rejoin_soak(table_path, outs, n_total)


_RESCALE_WORKER = _PROLOG + r'''
import json

t = shared_table({"bucket": "4",
                  "multihost.write.routing": "spmd",
                  "multihost.commit.arbitration": "coordinator"})
plane = t.new_distributed_write()

rows = [{"id": i, "v": i} for i in range(600)]
plane.write_dicts(rows)            # identical global batch (spmd)
plane.commit(commit_identifier=1)

plane.rescale_buckets(8)
assert plane.table.options.bucket == 8
assert plane.ownership.version == 2

# THE acceptance: this host wrote only the new buckets it will OWN
mine = plane.last_rescale_written_buckets
owned = {b for b in range(8)
         if plane.ownership.owner_of((), b) == pid}
assert mine, "host rewrote nothing — the rescale was not sharded"
assert set(mine) <= owned, (mine, sorted(owned))

# cross-check over the mesh: shares are disjoint and cover every
# routed bucket
payloads = MH.allgather_bytes(json.dumps(mine).encode())
shares = [json.loads(p) for p in payloads]
flat = [b for share in shares for b in share]
assert len(flat) == len(set(flat)), f"overlapping shares: {shares}"
assert sorted(flat) == list(range(8)), shares

plane.write_dicts([{"id": 1000 + i, "v": 1} for i in range(100)])
plane.commit(commit_identifier=2)
plane.close()
print(f"proc {pid}: MH-RESCALE-OK mine={sorted(mine)}", flush=True)
'''


def test_distributed_rescale_two_process_owned_buckets_only(tmp_path):
    """Each host of a real 2-process mesh rewrites only the buckets
    it will own under the bumped ownership version; the elected
    committer publishes ONE overwrite; the result is byte-identical
    to the oracle."""
    table_path, outs = _run_workers(_RESCALE_WORKER, tmp_path, 2)
    for pid, out in enumerate(outs):
        assert f"proc {pid}: MH-RESCALE-OK" in out, out[-4000:]

    t = FileStoreTable.load(table_path)
    assert t.options.bucket == 8
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", BigIntType())
              .primary_key("id")
              .options({"bucket": "8"})
              .build())
    oracle = FileStoreTable.create(str(tmp_path / "oracle"), schema)
    wb = oracle.new_batch_write_builder()
    with wb.new_write() as w:
        w.write_dicts([{"id": i, "v": i} for i in range(600)]
                      + [{"id": 1000 + i, "v": 1} for i in range(100)])
        wb.new_commit().commit(w.prepare_commit())
    assert t.to_arrow().sort_by("id").equals(
        oracle.to_arrow().sort_by("id"))
    report = t.fsck()
    assert report.ok, [v.to_dict() for v in report.violations]
