"""Multi-host write plane (parallel/distributed.py): sharded bucket
ownership, commit arbitration, snapshot-consistent cross-host scans,
online rescale.

Three layers:

1. Fake-topology unit tests — two `DistributedWritePlane`s with
   explicit (process_index, process_count) over ONE table in ONE
   process exercise the ownership split, routing modes, property
   stamping, version resume, rescale handoff and conflict accounting
   without a mesh (the agreement primitives degrade to no-ops at
   jax.process_count()==1).

2. REAL 2-process harnesses (tier-1) — subprocess workers bring up
   jax's distributed runtime (Gloo CPU collectives, the
   test_multihost_real recipe), form one 8-device mesh and drive the
   actual cross-host contract: disjoint input streams rerouted to
   owners over the mesh ('exchange'), concurrent CAS-arbitrated
   commits, coordinator (single-committer) arbitration, pinned
   cross-host scans, rescale under live traffic.  The parent then
   audits the ISSUE's acceptance: final table byte-identical to the
   single-process oracle, linear snapshot history, fsck-clean, and
   the multihost metric group live on the Prometheus /metrics
   endpoint.

3. A slow 4-process soak — bounded 503 storms (FailingFileIO) riding
   the write-retry ladder, plus one process killed MID-COMMIT (after
   its manifests uploaded, before the snapshot CAS): survivors
   converge, the dead process's staged files never reach the table,
   and maintenance sweeps them (remove_orphan_files + fsck clean).
"""

import os
import socket
import subprocess
import sys

import pyarrow as pa
import pytest

from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, IntType

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_NO_CPU_COLLECTIVES = "Multiprocess computations aren't implemented"


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _schema(buckets: int = 4, extra=None):
    opts = {"bucket": str(buckets)}
    opts.update(extra or {})
    return (Schema.builder()
            .column("id", BigIntType(False))
            .column("v", IntType())
            .primary_key("id")
            .options(opts)
            .build())


def _oracle(tmp_path, rows, buckets: int = 4) -> pa.Table:
    """Single-process reference ingest of the same global rows."""
    t = FileStoreTable.create(str(tmp_path / "oracle"), _schema(buckets))
    wb = t.new_batch_write_builder()
    with wb.new_write() as w:
        w.write_dicts(rows)
        wb.new_commit().commit(w.prepare_commit())
    return t.to_arrow().sort_by("id")


def _assert_linear_snapshots(table, allowed_users):
    """Snapshot history is linear: ids contiguous from earliest to
    latest, every snapshot present and committed by an expected
    user."""
    sm = table.snapshot_manager
    earliest, latest = sm.earliest_snapshot_id(), sm.latest_snapshot_id()
    assert earliest == 1
    users = set()
    for sid in range(earliest, latest + 1):
        assert sm.snapshot_exists(sid), f"gap at snapshot {sid}"
        users.add(sm.snapshot(sid).commit_user)
    assert users <= set(allowed_users), users


def _run_workers(worker_src, tmp_path, n_procs, args=None,
                 expected_rc=None, timeout=420):
    port = _free_port()
    table_path = str(tmp_path / "t")
    worker_py = tmp_path / "worker.py"
    worker_py.write_text(worker_src)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)       # workers pin their own devices
    procs = [subprocess.Popen(
        [sys.executable, str(worker_py), str(pid), str(port),
         table_path, REPO, str(n_procs)] + [str(a) for a in (args or [])],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for pid in range(n_procs)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    if any(_NO_CPU_COLLECTIVES in out for out in outs):
        pytest.skip("jaxlib CPU backend lacks Gloo cross-process "
                    "collectives; multi-host CPU emulation cannot run")
    for pid, (p, out) in enumerate(zip(procs, outs)):
        want = (expected_rc or {}).get(pid, 0)
        assert p.returncode == want, \
            f"proc {pid} rc={p.returncode} (want {want}):\n{out[-4000:]}"
    return table_path, outs


# -- 1. fake-topology unit tests ---------------------------------------------

class TestOwnership:
    def test_owner_deterministic_and_covering(self):
        from paimon_tpu.parallel.distributed import owner_of
        owners = [owner_of((), b, 4) for b in range(64)]
        assert owners == [owner_of((), b, 4) for b in range(64)]
        assert set(owners) == {0, 1, 2, 3}        # everyone owns some
        assert all(0 <= o < 4 for o in owners)
        # partitions shard too, and differently from bare buckets
        assert owner_of(("2024-01-01",), 0, 4) in range(4)
        assert owner_of((), 0, 1) == 0

    def test_handoffs_counts_moved_and_new_buckets(self):
        from paimon_tpu.parallel.distributed import OwnershipMap
        a = OwnershipMap(1, 2, 4)
        b = OwnershipMap(2, 2, 8)
        moved = a.handoffs_to(b)
        expect = 4        # 4 brand-new buckets; owners of 0..3 keep
        expect += sum(1 for i in range(4)
                      if a.owner_of((), i) != b.owner_of((), i))
        assert moved == expect

    def test_properties_roundtrip(self):
        from paimon_tpu.parallel.distributed import (
            OWNERSHIP_BUCKETS_PROP, OWNERSHIP_PROCESSES_PROP,
            OWNERSHIP_VERSION_PROP, OwnershipMap,
        )
        p = OwnershipMap(3, 2, 8).to_properties()
        assert p[OWNERSHIP_VERSION_PROP] == "3"
        assert p[OWNERSHIP_PROCESSES_PROP] == "2"
        assert p[OWNERSHIP_BUCKETS_PROP] == "8"


class TestFakeTopologyPlane:
    """Two planes with explicit (pid, count) over one table — the
    ownership/arbitration logic minus the mesh collectives."""

    def _planes(self, tmp_path, routing="spmd", extra=None):
        opts = {"multihost.write.routing": routing}
        opts.update(extra or {})
        t = FileStoreTable.create(str(tmp_path / "t"),
                                  _schema(4, opts))
        p0 = t.new_distributed_write(process_index=0, process_count=2)
        # second plane is process 1 of the same 2-process topology,
        # over its own table handle (separate writers, one store)
        p1 = FileStoreTable.load(str(tmp_path / "t")) \
            .new_distributed_write(process_index=1, process_count=2)
        return t, p0, p1

    def test_spmd_split_covers_and_commits_converge(self, tmp_path):
        t, p0, p1 = self._planes(tmp_path)
        rows = [{"id": i, "v": i} for i in range(200)]
        for p in (p0, p1):                 # identical global batch
            p.write_dicts(rows)
            assert p.commit() is not None
        final = FileStoreTable.load(t.path).to_arrow().sort_by("id")
        assert final.num_rows == 200       # zero lost, zero dup
        assert final.column("id").to_pylist() == list(range(200))
        assert FileStoreTable.load(t.path).fsck().ok
        p0.close(), p1.close()

    def test_ownership_split_is_disjoint(self, tmp_path):
        t, p0, p1 = self._planes(tmp_path)
        data = pa.table({"id": pa.array(range(500), pa.int64()),
                         "v": pa.array([0] * 500, pa.int32())})
        l0, f0, _ = p0._split_local_foreign(data)
        l1, f1, _ = p1._split_local_foreign(data)
        assert sorted(set(l0) | set(l1)) == list(range(500))
        assert set(l0).isdisjoint(set(l1))
        assert sorted(set(l0) | set(f0)) == list(range(500))
        p0.close(), p1.close()

    def test_local_only_raises_on_foreign_rows(self, tmp_path):
        from paimon_tpu.parallel.distributed import OwnershipError
        t, p0, p1 = self._planes(tmp_path, routing="local-only")
        with pytest.raises(OwnershipError, match="local-only"):
            p0.write_dicts([{"id": i, "v": 0} for i in range(100)])
        p0.close(), p1.close()

    def test_commit_stamps_ownership_properties(self, tmp_path):
        from paimon_tpu.parallel.distributed import (
            OWNERSHIP_VERSION_PROP, resume_ownership_version,
        )
        t, p0, p1 = self._planes(tmp_path)
        p0.write_dicts([{"id": i, "v": 0} for i in range(50)])
        p0.commit()
        snap = FileStoreTable.load(t.path).latest_snapshot()
        assert snap.properties[OWNERSHIP_VERSION_PROP] == "1"
        assert resume_ownership_version(FileStoreTable.load(t.path)) == 1
        p0.close(), p1.close()

    def test_rescale_drain_handoff(self, tmp_path):
        from paimon_tpu.metrics import (
            MULTIHOST_OWNERSHIP_HANDOFFS, global_registry,
        )
        t, p0, p1 = self._planes(tmp_path)
        rows1 = [{"id": i, "v": 1} for i in range(100)]
        for p in (p0, p1):
            p.write_dicts(rows1)
        # fake topology runs the two planes SEQUENTIALLY, so the
        # drains must land before the first rescale call like the
        # real-mesh barrier orders them — p1 draining after p0's
        # rewrite would stamp the old ownership generation past the
        # new one, which fsck now flags as ownership-inconsistency
        # (the REAL 2-process coordinator test covers true
        # buffered-rows-during-rescale traffic)
        p0.commit()
        p1.commit()
        handoffs = global_registry().multihost_metrics().counter(
            MULTIHOST_OWNERSHIP_HANDOFFS)
        before = handoffs.count
        p0.rescale_buckets(8)              # elected rewriter
        p1.rescale_buckets(8)              # peer: drain + reopen only
        assert p0.table.options.bucket == 8
        assert p1.table.options.bucket == 8
        assert p0.ownership.version == 2 == p1.ownership.version
        assert handoffs.count > before
        rows2 = [{"id": 100 + i, "v": 2} for i in range(60)]
        for p in (p0, p1):
            p.write_dicts(rows2)
            p.commit()
        final = FileStoreTable.load(t.path)
        assert final.to_arrow().num_rows == 160
        assert final.options.bucket == 8
        assert final.fsck().ok
        p0.close(), p1.close()

    def test_rescale_preserves_dynamic_options_and_stamps_version(
            self, tmp_path):
        """Review fixes: (1) the handoff reload must re-apply
        load-time dynamic options (copy() REPLACES them — losing
        write-only / retry tuning mid-run changed behavior after a
        rescale); (2) the rescale overwrite snapshot itself carries
        the bumped ownership version, so a process restarting before
        the first post-rescale commit cannot resume a regressed
        generation."""
        from paimon_tpu.options import CoreOptions
        from paimon_tpu.parallel.distributed import (
            OWNERSHIP_VERSION_PROP, resume_ownership_version,
        )
        FileStoreTable.create(str(tmp_path / "t"), _schema(4))
        t = FileStoreTable.load(
            str(tmp_path / "t"),
            dynamic_options={"write-only": "true",
                             "write.retry.max-attempts": "8"})
        plane = t.new_distributed_write(process_index=0,
                                        process_count=1)
        plane.write_dicts([{"id": i, "v": 1} for i in range(60)])
        plane.rescale_buckets(8)
        assert plane.table.options.write_only is True
        assert plane.table.options.get(
            CoreOptions.WRITE_RETRY_MAX_ATTEMPTS) == 8
        fresh = FileStoreTable.load(str(tmp_path / "t"))
        assert fresh.latest_snapshot().properties[
            OWNERSHIP_VERSION_PROP] == "2"
        assert resume_ownership_version(fresh) == 2
        plane.close()

    def test_cas_conflict_counted(self, tmp_path, monkeypatch):
        from paimon_tpu.metrics import (
            MULTIHOST_COMMIT_CONFLICTS, MULTIHOST_COMMIT_RETRIES,
            global_registry,
        )
        from paimon_tpu.snapshot import SnapshotManager
        t, p0, p1 = self._planes(
            tmp_path, extra={"commit.min-retry-wait": "1",
                             "commit.max-retry-wait": "2"})
        g = global_registry().multihost_metrics()
        conflicts = g.counter(MULTIHOST_COMMIT_CONFLICTS)
        retries = g.counter(MULTIHOST_COMMIT_RETRIES)
        c0, r0 = conflicts.count, retries.count
        real = SnapshotManager.try_commit
        lost = {"n": 0}

        def race_once(self, snap):
            if lost["n"] == 0:
                # an honest race: a concurrent peer lands the
                # contested id first, so THIS CAS genuinely loses and
                # the commit re-resolves against the new latest
                lost["n"] = 1
                wb = FileStoreTable.load(t.path) \
                    .new_batch_write_builder()
                wb.commit_user = "peer"
                with wb.new_write() as w:
                    w.write_dicts([{"id": 9999, "v": 9}])
                    wb.new_commit().commit(w.prepare_commit())
            return real(self, snap)

        monkeypatch.setattr(SnapshotManager, "try_commit", race_once)
        p0.write_dicts([{"id": i, "v": 0} for i in range(40)])
        assert p0.commit() is not None
        assert conflicts.count == c0 + 1
        assert retries.count == r0 + 1
        p0.close(), p1.close()

    def test_rejects_dynamic_bucket_tables(self, tmp_path):
        from paimon_tpu.parallel.distributed import OwnershipError
        t = FileStoreTable.create(str(tmp_path / "dyn"), _schema(4))
        t = t.copy({"bucket": "-1"})
        with pytest.raises(OwnershipError, match="fixed-bucket"):
            t.new_distributed_write(process_index=0, process_count=2)

    def test_rejects_append_only_tables(self, tmp_path):
        # the append writer has no buckets= route; accepting the
        # table would crash with TypeError on the FIRST write
        from paimon_tpu.parallel.distributed import OwnershipError
        schema = (Schema.builder()
                  .column("id", BigIntType(False))
                  .column("v", IntType())
                  .options({"bucket": "4", "bucket-key": "id"})
                  .build())
        t = FileStoreTable.create(str(tmp_path / "ao"), schema)
        with pytest.raises(OwnershipError, match="primary-key"):
            t.new_distributed_write(process_index=0, process_count=2)

    def test_rescale_empty_table_is_schema_change(self, tmp_path):
        # an empty drained table has nothing to rewrite: the rescale
        # is just the bucket schema change + handoff (previously a
        # misleading OwnershipError with the writer already closed) —
        # and the bumped generation is still STAMPED (forced empty
        # snapshot), so a restart resumes version 2, not 0/1
        from paimon_tpu.parallel.distributed import (
            resume_ownership_version,
        )
        t = FileStoreTable.create(str(tmp_path / "t"), _schema(4))
        plane = t.new_distributed_write(process_index=0,
                                        process_count=1)
        plane.rescale_buckets(8)
        assert plane.table.options.bucket == 8
        assert plane.ownership.version == 2
        assert resume_ownership_version(
            FileStoreTable.load(t.path)) == 2
        plane.write_dicts([{"id": 1, "v": 1}])
        plane.commit()
        plane.close()
        assert FileStoreTable.load(t.path).to_arrow().num_rows == 1

    def test_resume_bumps_version_on_topology_change(self, tmp_path):
        # a tip written by a 2-process map resumed by a 3-process
        # plane is a NEW ownership function: the version must bump,
        # never let one number denote two different maps
        t = FileStoreTable.create(str(tmp_path / "t"), _schema(4))
        p = t.new_distributed_write(process_index=0, process_count=2)
        p.write_dicts([{"id": i, "v": 0} for i in range(40)])
        p.commit()
        p.close()
        same = FileStoreTable.load(t.path).new_distributed_write(
            process_index=0, process_count=2)
        assert same.ownership.version == 1
        same.close()
        resized = FileStoreTable.load(t.path).new_distributed_write(
            process_index=0, process_count=3)
        assert resized.ownership.version == 2
        resized.close()

    def test_defaults_fill_before_ownership_hash(self, tmp_path):
        # fields.*.default-value on a nullable bucket-key column:
        # the plane must hash the DEFAULTED value like the
        # single-process path, or the row lands in (and is owned
        # via) a different bucket than the oracle's
        schema = (Schema.builder()
                  .column("id", BigIntType())
                  .column("v", IntType())
                  .primary_key("id")
                  .options({"bucket": "4",
                            "fields.id.default-value": "7"})
                  .build())
        FileStoreTable.create(str(tmp_path / "t"), schema)
        rows = [{"id": None, "v": 1}, {"id": 3, "v": 2}]
        # spmd routing: identical input on both fake processes
        planes = [FileStoreTable.load(
            str(tmp_path / "t"),
            dynamic_options={"multihost.write.routing": "spmd"})
            .new_distributed_write(process_index=i, process_count=2)
            for i in range(2)]
        for p in planes:
            p.write_dicts(rows)
            p.commit()
            p.close()
        # oracle
        ot = FileStoreTable.create(str(tmp_path / "oracle"), schema)
        wb = ot.new_batch_write_builder()
        with wb.new_write() as w:
            w.write_dicts(rows)
            wb.new_commit().commit(w.prepare_commit())
        final = FileStoreTable.load(
            str(tmp_path / "t")).to_arrow().sort_by("id")
        assert final.equals(ot.to_arrow().sort_by("id"))

    def test_rescale_partitioned_raises_before_any_barrier(
            self, tmp_path):
        # validation must raise identically on EVERY process before
        # the drain/barrier — a committer-only NotImplementedError
        # would strand the peers inside sync_global_devices
        from paimon_tpu.parallel.distributed import OwnershipError
        from paimon_tpu.types import VarCharType
        schema = (Schema.builder()
                  .column("part", VarCharType(nullable=False))
                  .column("id", BigIntType(False))
                  .column("v", IntType())
                  .partition_keys("part")
                  .primary_key("id", "part")
                  .options({"bucket": "2"}).build())
        t = FileStoreTable.create(str(tmp_path / "p"), schema)
        plane = t.new_distributed_write(process_index=1,
                                        process_count=2)
        plane.write_dicts([{"part": "a", "id": 1, "v": 1}])
        with pytest.raises(OwnershipError, match="partitioned"):
            plane.rescale_buckets(4)
        # the plane is still usable after the validation error
        plane.commit()
        plane.close()

    def test_rejects_unknown_modes(self, tmp_path):
        t = FileStoreTable.create(
            str(tmp_path / "t"),
            _schema(4, {"multihost.write.routing": "bogus"}))
        with pytest.raises(ValueError, match="routing"):
            t.new_distributed_write(process_index=0, process_count=2)
        t2 = FileStoreTable.load(
            t.path, dynamic_options={
                "multihost.write.routing": "spmd",
                "multihost.commit.arbitration": "bogus"})
        with pytest.raises(ValueError, match="arbitration"):
            t2.new_distributed_write(process_index=0, process_count=2)


# -- 2. real 2-process harnesses (tier-1) ------------------------------------

_PROLOG = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=%(dev)d"
import jax
jax.config.update("jax_platforms", "cpu")

pid = int(sys.argv[1]); port = sys.argv[2]; table_path = sys.argv[3]
sys.path.insert(0, sys.argv[4]); n_procs = int(sys.argv[5])

from paimon_tpu.parallel import multihost as MH

idx, count = MH.initialize(f"127.0.0.1:{port}", n_procs, pid)
assert (idx, count) == (pid, n_procs)

from paimon_tpu import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, IntType

def make_schema(buckets, extra):
    opts = {"bucket": str(buckets)}
    opts.update(extra)
    return (Schema.builder()
            .column("id", BigIntType(False))
            .column("v", IntType())
            .primary_key("id")
            .options(opts)
            .build())

def shared_table(buckets, extra):
    if pid == 0:
        t = FileStoreTable.create(table_path, make_schema(buckets, extra))
    MH.barrier("table-created")
    return FileStoreTable.load(table_path)
'''

_CAS_WORKER = _PROLOG % {"dev": 4} + r'''
ROWS = 400                      # global rows per checkpoint

t = shared_table(4, {"commit.min-retry-wait": "1",
                     "commit.max-retry-wait": "10"})
plane = t.new_distributed_write()
assert plane.routing == "exchange"
assert plane.commit_user == f"writer-p{pid}"

# disjoint input streams: process p ingests the ids of its parity;
# 'exchange' reroutes the share that hashes to the OTHER process's
# buckets over the mesh
for ckpt in (1, 2):
    base = (ckpt - 1) * ROWS
    mine = [{"id": base + i, "v": pid} for i in range(ROWS)
            if i % 2 == pid]
    plane.write_dicts(mine)
    sid = plane.commit(commit_identifier=ckpt)
    assert sid is not None

# snapshot-consistent cross-host scan: one pinned id, split shares
# disjoint-cover the table
sid, splits = plane.pinned_scan()
local = plane.scan_to_arrow()
counts = MH.allgather_bytes(f"{sid}:{local.num_rows}".encode())
sids = {c.decode().split(":")[0] for c in counts}
assert len(sids) == 1, f"pinned snapshot disagreement: {sids}"
total = sum(int(c.decode().split(":")[1]) for c in counts)
assert total == 2 * ROWS, total

# the multihost metric group must be live on the Prometheus endpoint
if pid == 0:
    from paimon_tpu.service.query_service import KvQueryServer
    srv = KvQueryServer(t).start()
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=30)
    conn.request("GET", "/metrics")
    body = conn.getresponse().read().decode()
    srv.stop()
    for name in ("paimon_multihost_commit_conflicts",
                 "paimon_multihost_commit_retries",
                 "paimon_multihost_foreign_rows_routed",
                 "paimon_multihost_barrier_wait_ms"):
        assert name in body, f"missing {name} on /metrics"

plane.close()
print(f"proc {pid}: DIST-CAS-OK rows={local.num_rows} sid={sid}",
      flush=True)
'''

_COORD_WORKER = _PROLOG % {"dev": 4} + r'''
ROWS = 300

t = shared_table(4, {"multihost.write.routing": "spmd",
                     "multihost.commit.arbitration": "coordinator",
                     "write-only": "true"})
plane = t.new_distributed_write()
assert plane.commit_user == "writer-committer"

def batch(k):
    return [{"id": (k - 1) * ROWS + i, "v": k} for i in range(ROWS)]

# identical global batches on every process (SPMD); the elected
# committer gathers commit messages over the mesh and publishes ONE
# snapshot per checkpoint
for ckpt in (1, 2):
    plane.write_dicts(batch(ckpt))
    sid = plane.commit(commit_identifier=ckpt)
    assert sid == ckpt, (sid, ckpt)

# online rescale under live traffic: checkpoint 3's rows are still
# buffered when the rescale arrives — drain-and-handoff
plane.write_dicts(batch(3))
plane.rescale_buckets(8)
assert plane.table.options.bucket == 8
assert plane.ownership.version == 2

plane.write_dicts(batch(4))
plane.commit(commit_identifier=4)

sid, splits = plane.pinned_scan()
local = plane.scan_to_arrow()
counts = MH.allgather_bytes(str(local.num_rows).encode())
total = sum(int(c) for c in counts)
assert total == 4 * ROWS, total
plane.close()
print(f"proc {pid}: DIST-COORD-OK rows={local.num_rows}", flush=True)
'''


def test_distributed_cas_two_process(tmp_path):
    """ISSUE acceptance: both hosts write concurrently to disjoint
    owned buckets over a REAL 2-process gloo mesh, commit through CAS
    arbitration, and the result is byte-identical to the
    single-process oracle with a linear fsck-clean history."""
    table_path, outs = _run_workers(_CAS_WORKER, tmp_path, 2)
    for pid, out in enumerate(outs):
        assert f"proc {pid}: DIST-CAS-OK" in out, out[-2000:]

    t = FileStoreTable.load(table_path)
    rows = [{"id": i, "v": i % 2} for i in range(800)]
    oracle = _oracle(tmp_path, rows)
    final = t.to_arrow().sort_by("id")
    assert final.equals(oracle), "distributed result != oracle"
    _assert_linear_snapshots(t, {"writer-p0", "writer-p1"})
    report = t.fsck()
    assert report.ok, report.violations


def test_distributed_coordinator_and_rescale_two_process(tmp_path):
    """Coordinator arbitration publishes ONE snapshot per global
    checkpoint under the shared committer user, and an online rescale
    mid-traffic (drain-and-handoff) preserves every row."""
    table_path, outs = _run_workers(_COORD_WORKER, tmp_path, 2)
    for pid, out in enumerate(outs):
        assert f"proc {pid}: DIST-COORD-OK" in out, out[-2000:]

    t = FileStoreTable.load(table_path)
    assert t.options.bucket == 8
    rows = [{"id": (k - 1) * 300 + i, "v": k}
            for k in (1, 2, 3, 4) for i in range(300)]
    oracle = _oracle(tmp_path, rows, buckets=8)
    final = t.to_arrow().sort_by("id")
    assert final.equals(oracle), "distributed result != oracle"
    sm = t.snapshot_manager
    # ckpt1, ckpt2, rescale drain (ckpt3 rows), rescale overwrite,
    # ckpt4 — exactly one snapshot each, no CAS retries burned
    users = [sm.snapshot(s).commit_user
             for s in range(1, sm.latest_snapshot_id() + 1)]
    assert sm.latest_snapshot_id() == 5, users
    assert users.count("writer-committer") == 4
    report = t.fsck()
    assert report.ok, report.violations


# -- 3. slow 4-process soak --------------------------------------------------

_SOAK_WORKER = _PROLOG % {"dev": 2} + r'''
from paimon_tpu.fs import LocalFileIO
sys.path.insert(0, os.path.join(sys.argv[4], "tests"))
from failing_fileio import FailingFileIO

ROWS = 1200                     # global rows per checkpoint
CKPTS = 2

fio = FailingFileIO(LocalFileIO(), f"soak-p{pid}")
if pid == 0:
    FileStoreTable.create(
        table_path,
        make_schema(8, {"commit.min-retry-wait": "1",
                        "commit.max-retry-wait": "20",
                        "write.retry.max-attempts": "8",
                        "write.retry.backoff": "5"}))
MH.barrier("table-created")
t = FileStoreTable.load(table_path, file_io=fio)
plane = t.new_distributed_write()

for ckpt in (1, 2):
    base = (ckpt - 1) * ROWS
    mine = [{"id": base + i, "v": pid} for i in range(ROWS)
            if i % n_procs == pid]
    plane.write_dicts(mine)
    # bounded 503 storm right before the flush-heavy commit: the
    # write-retry ladder must absorb it (auto-disarms after 2 ops)
    FailingFileIO.reset(f"soak-p{pid}", fail_after=0, fail_times=2)
    sid = plane.commit(commit_identifier=ckpt)
    FailingFileIO.disarm(f"soak-p{pid}")
    assert sid is not None

# the pinned scan is the LAST collective: every process (victim
# included) participates, then the plane is done with the mesh
local = plane.scan_to_arrow()
plane.close()

dead_marker = table_path + ".victim-dead"
if pid == n_procs - 1:
    # victim: die MID-COMMIT — after prepare_commit uploaded data
    # files and the commit wrote its manifests, right AT the snapshot
    # CAS.  Everything staged must stay invisible and sweepable.
    from paimon_tpu.snapshot import SnapshotManager
    wb = t.new_batch_write_builder()
    wb.commit_user = "doomed"
    w = wb.new_write()
    w.write_dicts([{"id": 10_000 + i, "v": 99} for i in range(200)])
    msgs = w.prepare_commit()

    def die(self, snap):
        open(dead_marker, "w").close()
        os._exit(42)
    SnapshotManager.try_commit = die
    wb.new_commit().commit(msgs)
    raise AssertionError("unreachable: try_commit must have exited")

# survivors: wait for the victim's death (its doomed commit needs the
# coordination-service leader alive), then exit WITHOUT jax's
# distributed shutdown barrier — a dead peer makes that barrier abort
# the whole process (SIGABRT) even though all table work succeeded
import time
deadline = time.time() + 120
while not os.path.exists(dead_marker) and time.time() < deadline:
    time.sleep(0.1)
assert os.path.exists(dead_marker), "victim never reached its CAS"
print(f"proc {pid}: DIST-SOAK-OK rows={local.num_rows}", flush=True)
sys.stdout.flush()
os._exit(0)
'''


@pytest.mark.slow
def test_distributed_soak_four_process_kill_mid_commit(tmp_path):
    """4-process mesh under bounded 503 storms; the last process is
    killed mid-commit (manifests written, CAS never executed).
    Survivors' rows all land exactly once; the dead process's staged
    files never become visible and maintenance sweeps them."""
    n = 4
    table_path, outs = _run_workers(_SOAK_WORKER, tmp_path, n,
                                    expected_rc={n - 1: 42},
                                    timeout=540)
    for pid in range(n - 1):
        assert f"proc {pid}: DIST-SOAK-OK" in outs[pid], \
            outs[pid][-2000:]

    t = FileStoreTable.load(table_path)
    final = t.to_arrow().sort_by("id")
    # zero lost, zero dup from the surviving checkpoints; none of the
    # victim's doomed rows (ids >= 10_000) leaked in
    assert final.num_rows == 2 * 1200
    assert final.column("id").to_pylist() == list(range(2400))
    _assert_linear_snapshots(t, {f"writer-p{p}" for p in range(n)})
    assert t.fsck().ok

    # the kill left orphans (uploaded data files + manifests with no
    # snapshot): maintenance must SWEEP them without touching live
    # data (older_than_ms is the absolute cutoff — a far-future one
    # waives the in-flight-writer grace period for the test)
    future_ms = 2 ** 60
    swept = t.remove_orphan_files(older_than_ms=future_ms)
    assert swept, "expected the dead process's staged files as orphans"
    after = FileStoreTable.load(table_path)
    assert after.to_arrow().sort_by("id").equals(final)
    assert after.fsck().ok
