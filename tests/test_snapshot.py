import json
import time

import pytest

from paimon_tpu.fs import LocalFileIO
from paimon_tpu.snapshot import (
    BranchManager, CommitKind, ConsumerManager, Snapshot, SnapshotManager,
    TagManager,
)


def make_snapshot(sid, time_millis=None, kind=CommitKind.APPEND):
    return Snapshot(
        id=sid, schema_id=0,
        base_manifest_list=f"manifest-list-base-{sid}",
        delta_manifest_list=f"manifest-list-delta-{sid}",
        commit_user="test-user", commit_identifier=sid,
        commit_kind=kind,
        time_millis=time_millis or int(time.time() * 1000),
        total_record_count=sid * 100, delta_record_count=100)


@pytest.fixture
def sm(tmp_path):
    return SnapshotManager(LocalFileIO(), str(tmp_path / "t"))


def test_json_wire_format():
    s = make_snapshot(7)
    d = json.loads(s.to_json())
    assert d["version"] == 3
    assert d["schemaId"] == 0
    assert d["commitKind"] == "APPEND"
    assert "changelogManifestList" not in d  # nulls omitted
    back = Snapshot.from_json(s.to_json())
    assert back == s


def test_commit_and_read(sm):
    assert sm.latest_snapshot_id() is None
    assert sm.try_commit(make_snapshot(1))
    assert sm.try_commit(make_snapshot(2))
    assert not sm.try_commit(make_snapshot(2))  # CAS conflict
    assert sm.latest_snapshot_id() == 2
    assert sm.earliest_snapshot_id() == 1
    assert [s.id for s in sm.snapshots()] == [1, 2]


def test_stale_latest_hint(sm):
    for i in range(1, 5):
        assert sm.try_commit(make_snapshot(i))
    # corrupt the hint downward; manager must walk forward
    sm._write_hint("LATEST", 2)
    assert sm.latest_snapshot_id() == 4


def test_time_travel(sm):
    for i in range(1, 6):
        assert sm.try_commit(make_snapshot(i, time_millis=i * 1000))
    assert sm.earlier_or_equal_time_mills(3500).id == 3
    assert sm.earlier_or_equal_time_mills(500) is None
    assert sm.earlier_or_equal_time_mills(99999).id == 5


def test_tags(tmp_path, sm):
    for i in range(1, 4):
        sm.try_commit(make_snapshot(i))
    tm = TagManager(LocalFileIO(), sm.table_path)
    tm.create_tag(sm.snapshot(2), "v1.0")
    assert tm.tag_exists("v1.0")
    assert tm.get_tag("v1.0").id == 2
    with pytest.raises(ValueError):
        tm.create_tag(sm.snapshot(3), "v1.0")
    tm.create_tag(sm.snapshot(3), "v1.1")
    assert list(tm.tags().keys()) == ["v1.0", "v1.1"]
    tm.delete_tag("v1.0")
    assert not tm.tag_exists("v1.0")


def test_consumers(tmp_path, sm):
    cm = ConsumerManager(LocalFileIO(), sm.table_path)
    assert cm.consumer("job1") is None
    cm.record_consumer("job1", 5)
    cm.record_consumer("job2", 3)
    assert cm.consumer("job1") == 5
    assert cm.min_next_snapshot() == 3
    cm.delete_consumer("job2")
    assert cm.min_next_snapshot() == 5


def test_branches(tmp_path):
    fio = LocalFileIO()
    table_path = str(tmp_path / "t")
    # need a schema to branch from
    from paimon_tpu.schema import Schema, SchemaManager
    from paimon_tpu.types import IntType
    SchemaManager(fio, table_path).create_table(
        Schema.builder().column("id", IntType(False)).build())
    sm = SnapshotManager(fio, table_path)
    for i in range(1, 3):
        sm.try_commit(make_snapshot(i))

    bm = BranchManager(fio, table_path)
    bm.create_branch("dev", from_snapshot=sm.snapshot(2))
    assert bm.branch_exists("dev")
    assert bm.branches() == ["dev"]

    branch_sm = SnapshotManager(fio, table_path, branch="dev")
    assert branch_sm.latest_snapshot_id() == 2
    branch_sm.try_commit(make_snapshot(3))

    bm.fast_forward("dev")
    assert sm.latest_snapshot_id() == 3
    bm.drop_branch("dev")
    assert not bm.branch_exists("dev")
