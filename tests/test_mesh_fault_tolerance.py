"""Per-bucket fault tolerance of the streaming mesh compaction engine
(parallel/mesh_engine.py §4 + parallel/fault.py): transient faults in
one bucket's window stream retry with backoff, degrade to the
single-chip path when retries exhaust, and the committed output stays
file-level identical to a fault-free run.  Non-transient errors
propagate immediately.
"""

import os

import pytest

import jax

from paimon_tpu.metrics import (
    COMPACTION_BUCKET_FAILURES, COMPACTION_BUCKET_FALLBACKS,
    COMPACTION_BUCKET_RETRIES, global_registry,
)
from paimon_tpu.parallel import (
    BucketRetryPolicy, bucket_mesh, compact_table_mesh,
    is_transient_error,
)
from paimon_tpu.parallel import mesh_engine as me
from paimon_tpu.table import FileStoreTable
from tests.failing_fileio import FailingFileIO, InjectedIOError
from tests.store_oracle import make_random_engine_table
from tests.test_mesh_engine import _bucket_kv, _rows

# jax surfaces device loss as jaxlib's XlaRuntimeError; tests model it
# with a same-named class so is_transient_error's name check fires
XlaRuntimeError = type("XlaRuntimeError", (RuntimeError,), {})


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) >= 8
    return bucket_mesh(8)


def _twins(tmp_path, engine, seed, **kw):
    clean = make_random_engine_table(str(tmp_path / "clean"), seed,
                                     engine, **kw)
    faulty = make_random_engine_table(str(tmp_path / "faulty"), seed,
                                      engine, **kw)
    return clean, faulty


def _broken(table, name):
    fio = FailingFileIO(table.file_io, name)
    return FileStoreTable(fio, table.path,
                          table.schema_manager.latest(),
                          branch=table.branch)


def _counter(name):
    return global_registry().compaction_metrics().counter(name).count


def _policy(**kw):
    kw.setdefault("max_attempts", 3)
    kw.setdefault("backoff_base_ms", 0.0)
    return BucketRetryPolicy(**kw)


def test_transient_fault_retries_to_identical_output(tmp_path, mesh):
    clean, faulty = _twins(tmp_path, "deduplicate", seed=101, buckets=1)
    assert compact_table_mesh(clean, mesh).snapshot_id is not None

    name = "mesh-retry"
    broken = _broken(faulty, name)
    retries0 = _counter(COMPACTION_BUCKET_RETRIES)
    FailingFileIO.reset(name, 0, fail_times=1)   # one transient kill
    try:
        stats = compact_table_mesh(broken, mesh,
                                   retry_policy=_policy())
    finally:
        FailingFileIO.disarm(name)
    assert stats.snapshot_id is not None
    assert stats.retries >= 1 and stats.fallbacks == 0
    assert _counter(COMPACTION_BUCKET_RETRIES) == retries0 + stats.retries
    assert [r for r in FailingFileIO.ops(name) if r.killed]

    reread = FileStoreTable.load(faulty.path)
    assert reread.latest_snapshot().commit_kind == "COMPACT"
    # file-level identical to the fault-free twin, not merely
    # state-identical: same keys, seqs, kinds, values per bucket
    assert _bucket_kv(reread) == _bucket_kv(clean)
    assert _rows(reread) == _rows(clean)


def test_storm_exhausts_retries_then_single_chip_fallback(tmp_path,
                                                          mesh):
    clean, faulty = _twins(tmp_path, "aggregation", seed=55, buckets=1)
    assert compact_table_mesh(clean, mesh).snapshot_id is not None

    name = "mesh-fallback"
    broken = _broken(faulty, name)
    fallbacks0 = _counter(COMPACTION_BUCKET_FALLBACKS)
    # the storm outlives the mesh retries (2 kills, max_attempts=2)
    # but has passed by the time the single-chip fallback runs
    FailingFileIO.reset(name, 0, fail_times=2)
    try:
        stats = compact_table_mesh(broken, mesh,
                                   retry_policy=_policy(max_attempts=2))
    finally:
        FailingFileIO.disarm(name)
    assert stats.snapshot_id is not None
    assert stats.retries == 1 and stats.fallbacks == 1
    assert _counter(COMPACTION_BUCKET_FALLBACKS) == fallbacks0 + 1

    reread = FileStoreTable.load(faulty.path)
    assert _bucket_kv(reread) == _bucket_kv(clean)
    assert _rows(reread) == _rows(clean)


def test_device_loss_degrades_every_bucket(tmp_path, mesh, monkeypatch):
    """A dead kernel (device/lane loss) fails every in-flight bucket;
    each rides its own ladder down to the single-chip path and the job
    still commits the fault-free result."""
    clean, faulty = _twins(tmp_path, "deduplicate", seed=77, buckets=3)
    assert compact_table_mesh(clean, mesh).snapshot_id is not None

    monkeypatch.setattr(
        me._MeshWindowKernel, "__call__",
        lambda self, *a: (_ for _ in ()).throw(
            XlaRuntimeError("device lost")))
    stats = compact_table_mesh(faulty, mesh,
                               retry_policy=_policy(max_attempts=2))
    assert stats.snapshot_id is not None
    assert stats.fallbacks >= 1
    reread = FileStoreTable.load(faulty.path)
    assert _bucket_kv(reread) == _bucket_kv(clean)
    assert _rows(reread) == _rows(clean)


def test_fallback_disabled_raises_after_retries(tmp_path, mesh):
    table = make_random_engine_table(str(tmp_path / "t"), 9,
                                     "deduplicate", buckets=1)
    name = "mesh-no-fallback"
    broken = _broken(table, name)
    failures0 = _counter(COMPACTION_BUCKET_FAILURES)
    FailingFileIO.reset(name, 0)               # hard fault: never clears
    try:
        with pytest.raises(InjectedIOError):
            compact_table_mesh(
                broken, mesh,
                retry_policy=_policy(max_attempts=2, fallback=False))
    finally:
        FailingFileIO.disarm(name)
    assert _counter(COMPACTION_BUCKET_FAILURES) == failures0 + 1
    # nothing committed; the table still reads at its last snapshot
    reread = FileStoreTable.load(table.path)
    assert reread.latest_snapshot().commit_kind != "COMPACT"
    reread.to_arrow()


def test_non_transient_error_propagates_immediately(tmp_path, mesh,
                                                    monkeypatch):
    """Programming errors must not ride the retry ladder — they would
    loop deterministically and degrade silently."""
    table = make_random_engine_table(str(tmp_path / "t"), 13,
                                     "deduplicate", buckets=1)
    calls = {"n": 0}

    def boom(self, *a, **kw):
        calls["n"] += 1
        raise ValueError("schema bug")

    monkeypatch.setattr(me._EngineContext, "merge_window_device", boom)
    monkeypatch.setattr(me._EngineContext, "merge_window_host", boom)
    with pytest.raises(ValueError, match="schema bug"):
        compact_table_mesh(table, mesh, retry_policy=_policy())
    assert calls["n"] == 1                     # no retry attempts


def test_is_transient_error_taxonomy():
    from paimon_tpu.fs.object_store import TransientStoreError

    assert is_transient_error(TransientStoreError("503"))
    assert is_transient_error(InjectedIOError("killed"))
    assert is_transient_error(OSError("io"))
    assert is_transient_error(FileNotFoundError("raced"))
    assert is_transient_error(XlaRuntimeError("device lost"))
    assert not is_transient_error(ValueError("bug"))
    assert not is_transient_error(KeyError("bug"))
    assert not is_transient_error(RuntimeError("generic"))


def test_retry_policy_from_options(tmp_path):
    table = make_random_engine_table(
        str(tmp_path / "t"), 3, "deduplicate", commits=1,
        rows_per_commit=10,
        extra_options={"compaction.retry.max-attempts": "7",
                       "compaction.retry.backoff": "250 ms",
                       "compaction.mesh.fallback": "false"})
    policy = BucketRetryPolicy.from_options(table.options)
    assert policy.max_attempts == 7
    assert policy.backoff_base_ms == 250
    assert policy.fallback is False


def test_retry_policy_retry_call():
    attempts = {"n": 0}

    def flaky():
        attempts["n"] += 1
        if attempts["n"] < 3:
            raise OSError("transient")
        return "ok"

    seen = []
    policy = BucketRetryPolicy(max_attempts=3, backoff_base_ms=0)
    assert policy.retry_call(
        flaky, on_retry=lambda n, e: seen.append(n)) == "ok"
    assert attempts["n"] == 3 and seen == [1, 2]

    attempts["n"] = 0
    with pytest.raises(OSError):
        BucketRetryPolicy(max_attempts=2,
                          backoff_base_ms=0).retry_call(flaky)
    assert attempts["n"] == 2                  # capped

    def bug():
        raise ValueError("no retry")

    with pytest.raises(ValueError):
        policy.retry_call(bug)
