"""SQL layer tests: parser, executor, pushdown, DDL/DML, procedures.

Mirrors the statement surface the reference drives through its SQL
entry points (pypaimon/sql SQLContext, cli/cli_sql.py) and the Flink
SQL examples in the reference docs.
"""

import pyarrow as pa
import pytest

from paimon_tpu.sql import SQLContext
from paimon_tpu.sql.parser import SQLError, parse
from paimon_tpu.catalog.catalog import create_catalog


@pytest.fixture()
def ctx(tmp_path):
    cat = create_catalog(warehouse=str(tmp_path / "wh"))
    cat.create_database("default", ignore_if_exists=True)
    return SQLContext(cat)


def _setup_orders(ctx):
    ctx.sql("""
        CREATE TABLE orders (
            id BIGINT NOT NULL,
            customer STRING,
            amount DOUBLE,
            qty INT,
            PRIMARY KEY (id) NOT ENFORCED
        ) WITH ('bucket' = '2')
    """)
    ctx.sql("""
        INSERT INTO orders VALUES
            (1, 'alice', 10.0, 2),
            (2, 'bob', 20.5, 1),
            (3, 'alice', 5.25, 4),
            (4, 'carol', 40.0, 3),
            (5, 'bob', 15.0, 2)
    """)


# ---------------------------------------------------------------------------
# parser
# ---------------------------------------------------------------------------

class TestParser:
    def test_select_roundtrip(self):
        s = parse("SELECT a, b AS x FROM t WHERE a > 1 "
                  "GROUP BY a HAVING count(*) > 2 "
                  "ORDER BY a DESC LIMIT 10 OFFSET 2")
        assert len(s.items) == 2
        assert s.items[1].alias == "x"
        assert s.limit == 10 and s.offset == 2
        assert not s.order_by[0][1]          # DESC

    def test_string_escapes_and_comments(self):
        s = parse("SELECT 'it''s' -- trailing\nFROM t /* block */")
        assert s.items[0].expr.value == "it's"

    def test_time_travel(self):
        s = parse("SELECT * FROM t VERSION AS OF 3")
        assert s.from_.snapshot_id == 3
        s = parse("SELECT * FROM t VERSION AS OF 'my-tag'")
        assert s.from_.tag == "my-tag"
        s = parse("SELECT * FROM t FOR SYSTEM_TIME AS OF 1700000000000")
        assert s.from_.timestamp_ms == 1700000000000

    def test_create_table(self):
        c = parse("CREATE TABLE IF NOT EXISTS db.t ("
                  "  id BIGINT NOT NULL COMMENT 'pk',"
                  "  v DECIMAL(10, 2),"
                  "  PRIMARY KEY (id) NOT ENFORCED"
                  ") PARTITIONED BY (dt) WITH ('bucket' = '4')")
        assert c.if_not_exists
        assert c.columns[0].type_str == "BIGINT NOT NULL"
        assert c.columns[1].type_str == "DECIMAL(10, 2)"
        assert c.primary_key == ["id"]
        assert c.partitioned_by == ["dt"]
        assert c.options == {"bucket": "4"}

    def test_errors(self):
        with pytest.raises(SQLError):
            parse("SELECT FROM t")
        with pytest.raises(SQLError):
            parse("SELECT * FROM t WHERE")
        with pytest.raises(SQLError):
            parse("FLUSH TABLES")


# ---------------------------------------------------------------------------
# queries
# ---------------------------------------------------------------------------

class TestQueries:
    def test_select_star_order(self, ctx):
        _setup_orders(ctx)
        out = ctx.sql("SELECT * FROM orders ORDER BY id")
        assert out.column_names == ["id", "customer", "amount", "qty"]
        assert out.column("id").to_pylist() == [1, 2, 3, 4, 5]

    def test_projection_expressions(self, ctx):
        _setup_orders(ctx)
        out = ctx.sql("SELECT id, amount * qty AS total, "
                      "upper(customer) AS cust "
                      "FROM orders WHERE id = 3")
        assert out.to_pylist() == [{"id": 3, "total": 21.0,
                                    "cust": "ALICE"}]

    def test_where_variants(self, ctx):
        _setup_orders(ctx)
        q = "SELECT id FROM orders WHERE {} ORDER BY id"
        cases = {
            "amount > 10 AND qty >= 2": [4, 5],
            "customer IN ('alice', 'bob')": [1, 2, 3, 5],
            "customer NOT IN ('alice')": [2, 4, 5],
            "amount BETWEEN 10 AND 21": [1, 2, 5],
            "customer LIKE 'a%'": [1, 3],
            "customer LIKE '%aro%'": [4],
            "NOT (qty = 2)": [2, 3, 4],
            "id % 2 = 0": [2, 4],
        }
        for cond, expect in cases.items():
            assert ctx.sql(q.format(cond)).column("id").to_pylist() == \
                expect, cond

    def test_aggregation(self, ctx):
        _setup_orders(ctx)
        out = ctx.sql("SELECT customer, count(*) AS n, sum(amount) AS s, "
                      "avg(qty) AS a, min(amount) AS lo, max(amount) AS hi "
                      "FROM orders GROUP BY customer ORDER BY customer")
        rows = out.to_pylist()
        assert rows[0] == {"customer": "alice", "n": 2, "s": 15.25,
                           "a": 3.0, "lo": 5.25, "hi": 10.0}
        assert [r["customer"] for r in rows] == ["alice", "bob", "carol"]

    def test_global_aggregate(self, ctx):
        _setup_orders(ctx)
        out = ctx.sql("SELECT count(*) AS n, sum(amount) AS total "
                      "FROM orders")
        assert out.to_pylist() == [{"n": 5, "total": 90.75}]

    def test_global_aggregate_empty(self, ctx):
        _setup_orders(ctx)
        out = ctx.sql("SELECT count(*) AS n, max(amount) AS m "
                      "FROM orders WHERE id > 100")
        assert out.to_pylist() == [{"n": 0, "m": None}]

    def test_having_and_count_distinct(self, ctx):
        _setup_orders(ctx)
        out = ctx.sql("SELECT customer, count(DISTINCT qty) AS dq "
                      "FROM orders GROUP BY customer "
                      "HAVING count(*) > 1 ORDER BY customer")
        assert out.to_pylist() == [{"customer": "alice", "dq": 2},
                                   {"customer": "bob", "dq": 2}]

    def test_group_by_expression(self, ctx):
        _setup_orders(ctx)
        out = ctx.sql("SELECT qty % 2 AS parity, count(*) AS n "
                      "FROM orders GROUP BY qty % 2 ORDER BY parity")
        assert out.to_pylist() == [{"parity": 0, "n": 3},
                                   {"parity": 1, "n": 2}]

    def test_case_cast_functions(self, ctx):
        _setup_orders(ctx)
        out = ctx.sql(
            "SELECT id, CASE WHEN amount >= 20 THEN 'big' "
            "ELSE 'small' END AS size_, "
            "CAST(amount AS INT) AS ai, "
            "coalesce(NULL, customer) AS c, "
            "substr(customer, 1, 3) AS pre "
            "FROM orders WHERE id <= 2 ORDER BY id")
        assert out.to_pylist() == [
            {"id": 1, "size_": "small", "ai": 10, "c": "alice",
             "pre": "ali"},
            # CAST truncates toward zero (Java (int) semantics,
            # data/casting.py numeric narrowing rule)
            {"id": 2, "size_": "big", "ai": 20, "c": "bob", "pre": "bob"},
        ]

    def test_distinct_union_all(self, ctx):
        _setup_orders(ctx)
        out = ctx.sql("SELECT DISTINCT customer FROM orders")
        assert sorted(out.column("customer").to_pylist()) == \
            ["alice", "bob", "carol"]
        out = ctx.sql("SELECT id FROM orders WHERE id = 1 "
                      "UNION ALL SELECT id FROM orders WHERE id = 2")
        assert sorted(out.column("id").to_pylist()) == [1, 2]

    def test_subquery(self, ctx):
        _setup_orders(ctx)
        out = ctx.sql("SELECT cust, total FROM ("
                      "  SELECT customer AS cust, sum(amount) AS total"
                      "  FROM orders GROUP BY customer) t "
                      "WHERE total > 16 ORDER BY total DESC")
        assert out.to_pylist() == [{"cust": "carol", "total": 40.0},
                                   {"cust": "bob", "total": 35.5}]

    def test_select_without_from(self, ctx):
        out = ctx.sql("SELECT 1 + 2 AS three, 'x' AS s")
        assert out.to_pylist() == [{"three": 3, "s": "x"}]

    def test_order_nulls_and_position(self, ctx):
        ctx.sql("CREATE TABLE tn (id INT, v INT)")
        ctx.sql("INSERT INTO tn VALUES (1, NULL), (2, 5), (3, 1)")
        out = ctx.sql("SELECT id, v FROM tn ORDER BY v ASC NULLS FIRST")
        assert out.column("id").to_pylist() == [1, 3, 2]
        out = ctx.sql("SELECT id, v FROM tn ORDER BY 2 DESC")
        assert out.column("id").to_pylist()[:2] == [2, 3]

    def test_registered_view(self, ctx):
        ctx.register("v", pa.table({"a": [1, 2, 3]}))
        out = ctx.sql("SELECT sum(a) AS s FROM v")
        assert out.to_pylist() == [{"s": 6}]

    def test_union_order_limit_bind_whole_union(self, ctx):
        _setup_orders(ctx)
        out = ctx.sql("SELECT id FROM orders WHERE id >= 4 "
                      "UNION ALL SELECT id FROM orders WHERE id <= 2 "
                      "ORDER BY id")
        assert out.column("id").to_pylist() == [1, 2, 4, 5]
        out = ctx.sql("SELECT id FROM orders WHERE id >= 4 "
                      "UNION ALL SELECT id FROM orders WHERE id <= 2 "
                      "ORDER BY id DESC LIMIT 2")
        assert out.column("id").to_pylist() == [5, 4]

    def test_having_without_aggregate_rejected(self, ctx):
        from paimon_tpu.sql.parser import SQLError
        _setup_orders(ctx)
        with pytest.raises(SQLError, match="HAVING"):
            ctx.sql("SELECT id FROM orders HAVING id > 2")

    def test_order_by_ordinal_validation(self, ctx):
        from paimon_tpu.sql.parser import SQLError
        _setup_orders(ctx)
        with pytest.raises(SQLError, match="positional"):
            ctx.sql("SELECT id FROM orders ORDER BY 0")
        with pytest.raises(SQLError, match="positional"):
            ctx.sql("SELECT id FROM orders ORDER BY 2")


class TestJoins:
    def _setup(self, ctx):
        _setup_orders(ctx)
        ctx.sql("CREATE TABLE customers (name STRING NOT NULL, "
                "tier STRING, PRIMARY KEY (name) NOT ENFORCED) "
                "WITH ('bucket' = '1')")
        ctx.sql("INSERT INTO customers VALUES ('alice', 'gold'), "
                "('bob', 'silver'), ('dave', 'bronze')")

    def test_inner_join(self, ctx):
        self._setup(ctx)
        out = ctx.sql(
            "SELECT o.id, c.tier FROM orders o "
            "JOIN customers c ON o.customer = c.name ORDER BY o.id")
        assert out.to_pylist() == [
            {"id": 1, "tier": "gold"}, {"id": 2, "tier": "silver"},
            {"id": 3, "tier": "gold"}, {"id": 5, "tier": "silver"}]

    def test_left_join(self, ctx):
        self._setup(ctx)
        out = ctx.sql(
            "SELECT o.id, c.tier FROM orders o "
            "LEFT JOIN customers c ON o.customer = c.name ORDER BY o.id")
        assert out.column("tier").to_pylist() == \
            ["gold", "silver", "gold", None, "silver"]

    def test_join_residual_condition(self, ctx):
        self._setup(ctx)
        out = ctx.sql(
            "SELECT o.id FROM orders o JOIN customers c "
            "ON o.customer = c.name AND o.amount > 12 ORDER BY o.id")
        assert out.column("id").to_pylist() == [2, 5]

    def test_cross_join(self, ctx):
        self._setup(ctx)
        out = ctx.sql("SELECT count(*) AS n FROM orders CROSS JOIN "
                      "customers")
        assert out.to_pylist() == [{"n": 15}]

    def test_left_join_residual_keeps_outer_rows(self, ctx):
        # residual ON conditions participate in the match; LEFT JOIN
        # still emits every left row
        self._setup(ctx)
        out = ctx.sql(
            "SELECT o.id, c.tier FROM orders o LEFT JOIN customers c "
            "ON o.customer = c.name AND o.amount > 12 ORDER BY o.id")
        assert out.column("tier").to_pylist() == \
            [None, "silver", None, None, "silver"]
        assert out.column("id").to_pylist() == [1, 2, 3, 4, 5]

    def test_join_aggregate(self, ctx):
        self._setup(ctx)
        out = ctx.sql(
            "SELECT c.tier, sum(o.amount) AS s FROM orders o "
            "JOIN customers c ON o.customer = c.name "
            "GROUP BY c.tier ORDER BY c.tier")
        assert out.to_pylist() == [{"tier": "gold", "s": 15.25},
                                   {"tier": "silver", "s": 35.5}]


# ---------------------------------------------------------------------------
# pushdown
# ---------------------------------------------------------------------------

class TestPushdown:
    def test_explain_shows_pushdown(self, ctx):
        _setup_orders(ctx)
        plan = ctx.sql("EXPLAIN SELECT id FROM orders WHERE id > 3 "
                       "AND upper(customer) = 'BOB'")
        text = "\n".join(plan.column("plan").to_pylist())
        assert "pushed predicate" in text
        assert "id" in text and "gt" in text.lower() or ">" in text

    def test_pushdown_correctness_vs_residual(self, ctx):
        _setup_orders(ctx)
        # mixed pushable + non-pushable conjuncts must both apply
        out = ctx.sql("SELECT id FROM orders "
                      "WHERE id >= 2 AND length(customer) = 3 ORDER BY id")
        assert out.column("id").to_pylist() == [2, 5]

    def test_or_not_pushed_still_correct(self, ctx):
        _setup_orders(ctx)
        out = ctx.sql("SELECT id FROM orders "
                      "WHERE id = 1 OR length(customer) = 5 ORDER BY id")
        assert out.column("id").to_pylist() == [1, 3, 4]

    def test_not_over_partially_convertible_and(self, ctx):
        # NOT(a AND f(b)): the AND converts partially, so pushing
        # NOT(partial) would over-prune — must not be pushed
        _setup_orders(ctx)
        out = ctx.sql("SELECT id FROM orders WHERE NOT "
                      "(customer = 'alice' AND length(customer) = 9) "
                      "ORDER BY id")
        assert out.column("id").to_pylist() == [1, 2, 3, 4, 5]


# ---------------------------------------------------------------------------
# DDL / DML / procedures
# ---------------------------------------------------------------------------

class TestDdlDml:
    def test_show_describe(self, ctx):
        _setup_orders(ctx)
        assert ctx.sql("SHOW TABLES").column("table_name").to_pylist() \
            == ["orders"]
        assert "default" in ctx.sql("SHOW DATABASES") \
            .column("database_name").to_pylist()
        d = ctx.sql("DESCRIBE orders")
        assert d.column("name").to_pylist() == \
            ["id", "customer", "amount", "qty"]
        assert d.column("key").to_pylist()[0] == "PRI"
        ddl = ctx.sql("SHOW CREATE TABLE orders") \
            .column("create_table")[0].as_py()
        assert "PRIMARY KEY (`id`)" in ddl and "'bucket' = '2'" in ddl

    def test_use_and_qualified_names(self, ctx):
        ctx.sql("CREATE DATABASE db2")
        ctx.sql("CREATE TABLE db2.t2 (a INT)")
        ctx.sql("INSERT INTO db2.t2 VALUES (7)")
        assert ctx.sql("SELECT * FROM db2.t2").to_pylist() == [{"a": 7}]
        ctx.sql("USE db2")
        assert ctx.sql("SELECT * FROM t2").to_pylist() == [{"a": 7}]

    def test_insert_select_and_overwrite(self, ctx):
        _setup_orders(ctx)
        ctx.sql("CREATE TABLE summary (customer STRING NOT NULL, "
                "total DOUBLE, PRIMARY KEY (customer) NOT ENFORCED) "
                "WITH ('bucket' = '1')")
        ctx.sql("INSERT INTO summary SELECT customer, sum(amount) "
                "FROM orders GROUP BY customer")
        out = ctx.sql("SELECT * FROM summary ORDER BY customer")
        assert out.column("total").to_pylist() == [15.25, 35.5, 40.0]
        ctx.sql("INSERT OVERWRITE summary VALUES ('zed', 1.0)")
        assert ctx.sql("SELECT * FROM summary").to_pylist() == \
            [{"customer": "zed", "total": 1.0}]

    def test_insert_partial_columns(self, ctx):
        ctx.sql("CREATE TABLE p (a INT, b STRING, c DOUBLE)")
        ctx.sql("INSERT INTO p (a, c) VALUES (1, 2.5)")
        assert ctx.sql("SELECT * FROM p").to_pylist() == \
            [{"a": 1, "b": None, "c": 2.5}]

    def test_pk_upsert_via_insert(self, ctx):
        _setup_orders(ctx)
        ctx.sql("INSERT INTO orders VALUES (1, 'alice', 99.0, 9)")
        out = ctx.sql("SELECT amount FROM orders WHERE id = 1")
        assert out.column("amount").to_pylist() == [99.0]

    def test_delete(self, ctx):
        _setup_orders(ctx)
        r = ctx.sql("DELETE FROM orders WHERE customer = 'bob'")
        assert "2 rows deleted" in r.column("result")[0].as_py()
        assert ctx.sql("SELECT count(*) AS n FROM orders") \
            .to_pylist() == [{"n": 3}]

    def test_delete_rejects_partial_where(self, ctx):
        # a WHERE whose AND only partially converts must error, not
        # delete the superset matched by the convertible conjunct
        from paimon_tpu.sql.parser import SQLError
        _setup_orders(ctx)
        with pytest.raises(SQLError, match="DELETE WHERE"):
            ctx.sql("DELETE FROM orders WHERE customer = 'bob' "
                    "AND length(customer) = 99")
        assert ctx.sql("SELECT count(*) AS n FROM orders") \
            .to_pylist() == [{"n": 5}]

    def test_insert_paren_select(self, ctx):
        _setup_orders(ctx)
        ctx.sql("CREATE TABLE t2 (id BIGINT)")
        ctx.sql("INSERT INTO t2 (SELECT id FROM orders WHERE id <= 2)")
        assert sorted(ctx.sql("SELECT * FROM t2")
                      .column("id").to_pylist()) == [1, 2]

    def test_explain_reads_no_data(self, ctx, monkeypatch):
        _setup_orders(ctx)
        from paimon_tpu.table.table import FileStoreTable

        def boom(self, *a, **k):
            raise AssertionError("EXPLAIN must not read data")

        monkeypatch.setattr(FileStoreTable, "to_arrow", boom)
        plan = ctx.sql("EXPLAIN SELECT id FROM orders WHERE id > 3")
        assert "pushed predicate" in \
            "\n".join(plan.column("plan").to_pylist())

    def test_update(self, ctx):
        _setup_orders(ctx)
        r = ctx.sql("UPDATE orders SET amount = amount + 1, qty = 0 "
                    "WHERE customer = 'alice'")
        assert "2 rows updated" in r.column("result")[0].as_py()
        out = ctx.sql("SELECT id, amount, qty FROM orders "
                      "WHERE customer = 'alice' ORDER BY id")
        assert out.to_pylist() == [{"id": 1, "amount": 11.0, "qty": 0},
                                   {"id": 3, "amount": 6.25, "qty": 0}]

    def test_alter(self, ctx):
        _setup_orders(ctx)
        ctx.sql("ALTER TABLE orders SET ('snapshot.num-retained.max' = "
                "'10')")
        t = ctx.catalog.get_table(ctx._ident("orders"))
        assert t.schema.options["snapshot.num-retained.max"] == "10"
        ctx.sql("ALTER TABLE orders ADD COLUMN note STRING")
        out = ctx.sql("SELECT note FROM orders WHERE id = 1")
        assert out.column("note").to_pylist() == [None]

    def test_drop(self, ctx):
        _setup_orders(ctx)
        ctx.sql("DROP TABLE orders")
        assert ctx.sql("SHOW TABLES").num_rows == 0
        ctx.sql("DROP TABLE IF EXISTS orders")   # no error


class TestProceduresAndTravel:
    def test_call_compact_and_tags(self, ctx):
        _setup_orders(ctx)
        r = ctx.sql("CALL sys.compact('orders', TRUE)")
        assert "snapshot" in r.column("result")[0].as_py()
        ctx.sql("CALL sys.create_tag('orders', 'v1')")
        ctx.sql("INSERT INTO orders VALUES (9, 'zed', 1.0, 1)")
        out = ctx.sql("SELECT count(*) AS n FROM orders "
                      "VERSION AS OF 'v1'")
        assert out.to_pylist() == [{"n": 5}]
        assert ctx.sql("SELECT count(*) AS n FROM orders") \
            .to_pylist() == [{"n": 6}]

    def test_snapshot_travel_and_system_table(self, ctx):
        _setup_orders(ctx)
        ctx.sql("INSERT INTO orders VALUES (10, 'x', 1.0, 1)")
        snaps = ctx.sql("SELECT * FROM orders$snapshots")
        assert snaps.num_rows >= 2
        out = ctx.sql("SELECT count(*) AS n FROM orders VERSION AS OF 1")
        assert out.to_pylist() == [{"n": 5}]

    def test_call_expire(self, ctx):
        _setup_orders(ctx)
        ctx.sql("INSERT INTO orders VALUES (11, 'y', 2.0, 1)")
        r = ctx.sql("CALL sys.expire_snapshots('orders', 1)")
        assert "expired" in r.column("result")[0].as_py()

    def test_call_mark_partition_done(self, ctx):
        import os
        ctx.sql("CREATE TABLE pt (id BIGINT NOT NULL, v DOUBLE, "
                "dt STRING NOT NULL, PRIMARY KEY (id, dt)) "
                "PARTITIONED BY (dt) WITH ('bucket' = '1')")
        ctx.sql("INSERT INTO pt VALUES (1, 1.0, '2026-07-01')")
        r = ctx.sql(
            "CALL sys.mark_partition_done('pt', 'dt=2026-07-01')")
        assert "1 partitions marked done" in r.column("result")[0].as_py()
        t = ctx.catalog.get_table(ctx._ident("pt"))
        assert os.path.exists(
            os.path.join(t.path, "dt=2026-07-01", "_SUCCESS"))


class TestGlobalSystemTables:
    def test_sys_database_tables(self, ctx):
        _setup_orders(ctx)
        ctx.sql("CREATE DATABASE db2")
        ctx.sql("CREATE TABLE db2.t2 (a INT) WITH ('bucket' = '-1')")
        out = ctx.sql("SELECT * FROM sys.all_tables ORDER BY "
                      "database_name, table_name")
        rows = out.to_pylist()
        assert [(r["database_name"], r["table_name"]) for r in rows] == \
            [("db2", "t2"), ("default", "orders")]
        assert rows[1]["record_count"] == 5

        opts = ctx.sql("SELECT value FROM sys.all_table_options "
                       "WHERE table_name = 'orders' AND key = 'bucket'")
        assert opts.column("value").to_pylist() == ["2"]

        cat = ctx.sql("SELECT * FROM sys.catalog_options")
        keys = cat.column("key").to_pylist()
        assert "warehouse" in keys

    def test_sys_all_partitions(self, ctx):
        ctx.sql("CREATE TABLE pt (p STRING NOT NULL, v INT) "
                "PARTITIONED BY (p) WITH ('bucket' = '-1')")
        ctx.sql("INSERT INTO pt VALUES ('x', 1), ('y', 2), ('x', 3)")
        out = ctx.sql("SELECT * FROM sys.all_partitions "
                      "WHERE table_name = 'pt' ORDER BY partition")
        assert out.num_rows == 2
        assert out.column("record_count").to_pylist() == [2, 1]


class TestWindowFunctions:
    def test_row_number(self, ctx):
        _setup_orders(ctx)
        out = ctx.sql(
            "SELECT id, row_number() OVER (PARTITION BY customer "
            "ORDER BY amount DESC) AS rn FROM orders ORDER BY id")
        assert out.to_pylist() == [
            {"id": 1, "rn": 1},   # alice: 10.0 > 5.25
            {"id": 2, "rn": 1},   # bob: 20.5 > 15.0
            {"id": 3, "rn": 2},
            {"id": 4, "rn": 1},   # carol alone
            {"id": 5, "rn": 2}]

    def test_rank_dense_rank_ties(self, ctx):
        ctx.sql("CREATE TABLE r (g STRING, v INT)")
        ctx.sql("INSERT INTO r VALUES ('a',1),('a',1),('a',2),('a',3),"
                "('b',5)")
        out = ctx.sql(
            "SELECT g, v, rank() OVER (PARTITION BY g ORDER BY v) AS r,"
            " dense_rank() OVER (PARTITION BY g ORDER BY v) AS dr "
            "FROM r ORDER BY g, v")
        rows = out.to_pylist()
        assert [(x["r"], x["dr"]) for x in rows] == \
            [(1, 1), (1, 1), (3, 2), (4, 3), (1, 1)]

    def test_partition_aggregates(self, ctx):
        _setup_orders(ctx)
        out = ctx.sql(
            "SELECT id, sum(amount) OVER (PARTITION BY customer) AS s, "
            "count(*) OVER (PARTITION BY customer) AS n, "
            "max(amount) OVER (PARTITION BY customer) AS m "
            "FROM orders ORDER BY id")
        rows = out.to_pylist()
        assert rows[0] == {"id": 1, "s": 15.25, "n": 2, "m": 10.0}
        assert rows[3] == {"id": 4, "s": 40.0, "n": 1, "m": 40.0}

    def test_lag_lead(self, ctx):
        _setup_orders(ctx)
        out = ctx.sql(
            "SELECT id, lag(amount) OVER (PARTITION BY customer "
            "ORDER BY id) AS prev, lead(amount) OVER (PARTITION BY "
            "customer ORDER BY id) AS nxt FROM orders ORDER BY id")
        rows = out.to_pylist()
        assert rows[0] == {"id": 1, "prev": None, "nxt": 5.25}
        assert rows[2] == {"id": 3, "prev": 10.0, "nxt": None}
        assert rows[4] == {"id": 5, "prev": 20.5, "nxt": None}

    def test_first_last_value_strings(self, ctx):
        _setup_orders(ctx)
        out = ctx.sql(
            "SELECT id, first_value(customer) OVER (ORDER BY amount) "
            "AS cheapest FROM orders ORDER BY id")
        # global window (no partition): first by amount = alice (5.25)
        assert set(out.column("cheapest").to_pylist()) == {"alice"}

    def test_window_without_partition(self, ctx):
        _setup_orders(ctx)
        out = ctx.sql("SELECT id, row_number() OVER (ORDER BY amount) "
                      "AS rn FROM orders ORDER BY rn")
        assert out.column("id").to_pylist() == [3, 1, 5, 2, 4]

    def test_window_over_subquery_and_mix_rejected(self, ctx):
        from paimon_tpu.sql.parser import SQLError
        _setup_orders(ctx)
        out = ctx.sql(
            "SELECT cust, rank() OVER (ORDER BY total DESC) AS r FROM "
            "(SELECT customer AS cust, sum(amount) AS total FROM orders"
            " GROUP BY customer) t ORDER BY r")
        assert out.to_pylist()[0] == {"cust": "carol", "r": 1}
        with pytest.raises(SQLError, match="window"):
            ctx.sql("SELECT customer, sum(amount), row_number() OVER "
                    "(ORDER BY customer) FROM orders GROUP BY customer")


class TestWindowEdgeCases:
    def test_rank_without_order_all_peers(self, ctx):
        ctx.sql("CREATE TABLE wr (g STRING, v INT)")
        ctx.sql("INSERT INTO wr VALUES ('a',1),('a',1),('b',2),('b',3)")
        out = ctx.sql("SELECT g, rank() OVER (PARTITION BY g) AS r, "
                      "dense_rank() OVER (PARTITION BY g) AS dr "
                      "FROM wr ORDER BY g, v")
        assert out.column("r").to_pylist() == [1, 1, 1, 1]
        assert out.column("dr").to_pylist() == [1, 1, 1, 1]

    def test_count_strings_and_int_types(self, ctx):
        ctx.sql("CREATE TABLE wc (g STRING, s STRING, v BIGINT)")
        ctx.sql("INSERT INTO wc VALUES ('a','x',1),('a',NULL,2),"
                "('b','y',3)")
        out = ctx.sql("SELECT g, count(s) OVER (PARTITION BY g) AS c, "
                      "sum(v) OVER (PARTITION BY g) AS sv "
                      "FROM wc ORDER BY g, v")
        assert out.column("c").to_pylist() == [1, 1, 1]
        assert out.column("sv").to_pylist() == [3, 3, 3]
        import pyarrow as pa
        assert out.schema.field("sv").type == pa.int64()

    def test_all_null_partition_aggregates(self, ctx):
        ctx.sql("CREATE TABLE wn (g STRING, v DOUBLE)")
        ctx.sql("INSERT INTO wn VALUES ('a',NULL),('a',NULL),('b',1.5)")
        out = ctx.sql(
            "SELECT g, min(v) OVER (PARTITION BY g) AS mn, "
            "sum(v) OVER (PARTITION BY g) AS sm, "
            "avg(v) OVER (PARTITION BY g) AS av FROM wn ORDER BY g")
        rows = out.to_pylist()
        assert rows[0] == {"g": "a", "mn": None, "sm": None, "av": None}
        assert rows[2] == {"g": "b", "mn": 1.5, "sm": 1.5, "av": 1.5}

    def test_lag_default_value(self, ctx):
        ctx.sql("CREATE TABLE wl (v INT)")
        ctx.sql("INSERT INTO wl VALUES (1),(2),(3)")
        out = ctx.sql("SELECT v, lag(v, 1, 0) OVER (ORDER BY v) AS p "
                      "FROM wl ORDER BY v")
        assert out.column("p").to_pylist() == [0, 1, 2]

    def test_running_sum_with_order(self, ctx):
        ctx.sql("CREATE TABLE ws (g STRING, v INT)")
        ctx.sql("INSERT INTO ws VALUES ('a',1),('a',2),('a',2),('a',4),"
                "('b',10)")
        out = ctx.sql("SELECT g, v, sum(v) OVER (PARTITION BY g "
                      "ORDER BY v) AS rs, count(*) OVER (PARTITION BY "
                      "g ORDER BY v) AS rc FROM ws ORDER BY g, v")
        # RANGE frame: peers (the two v=2 rows) share the value
        assert out.column("rs").to_pylist() == [1, 5, 5, 9, 10]
        assert out.column("rc").to_pylist() == [1, 3, 3, 4, 1]

    def test_min_with_order_rejected(self, ctx):
        from paimon_tpu.sql.parser import SQLError
        _setup_orders(ctx)
        with pytest.raises(SQLError, match="running"):
            ctx.sql("SELECT min(amount) OVER (ORDER BY id) FROM orders")

    def test_sys_time_travel_rejected(self, ctx):
        from paimon_tpu.sql.parser import SQLError
        _setup_orders(ctx)
        with pytest.raises(SQLError, match="time"):
            ctx.sql("SELECT * FROM sys.all_tables VERSION AS OF 9")


class TestViews:
    def test_create_select_drop(self, ctx):
        _setup_orders(ctx)
        ctx.sql("CREATE VIEW big_orders AS SELECT id, amount FROM "
                "orders WHERE amount > 12")
        out = ctx.sql("SELECT * FROM big_orders ORDER BY id")
        assert out.column("id").to_pylist() == [2, 4, 5]
        # views compose: query a view with aggregation
        agg = ctx.sql("SELECT count(*) AS n, sum(amount) AS s "
                      "FROM big_orders")
        assert agg.to_pylist() == [{"n": 3, "s": 75.5}]
        assert ctx.sql("SHOW VIEWS").column("view_name").to_pylist() \
            == ["big_orders"]
        ctx.sql("DROP VIEW big_orders")
        assert ctx.sql("SHOW VIEWS").num_rows == 0
        ctx.sql("DROP VIEW IF EXISTS big_orders")     # no error

    def test_or_replace_and_persistence(self, ctx):
        _setup_orders(ctx)
        ctx.sql("CREATE VIEW v1 AS SELECT id FROM orders WHERE id = 1")
        ctx.sql("CREATE OR REPLACE VIEW v1 AS "
                "SELECT id FROM orders WHERE id >= 4")
        assert ctx.sql("SELECT * FROM v1 ORDER BY id") \
            .column("id").to_pylist() == [4, 5]
        # a NEW context over the same catalog sees the view (persisted)
        from paimon_tpu.sql import SQLContext
        ctx2 = SQLContext(ctx.catalog)
        assert ctx2.sql("SELECT count(*) AS n FROM v1") \
            .to_pylist() == [{"n": 2}]

    def test_view_follows_base_table_updates(self, ctx):
        _setup_orders(ctx)
        ctx.sql("CREATE VIEW all_o AS SELECT id FROM orders")
        assert ctx.sql("SELECT count(*) AS n FROM all_o") \
            .to_pylist() == [{"n": 5}]
        ctx.sql("INSERT INTO orders VALUES (9, 'z', 1.0, 1)")
        assert ctx.sql("SELECT count(*) AS n FROM all_o") \
            .to_pylist() == [{"n": 6}]

    def test_view_time_travel_rejected(self, ctx):
        from paimon_tpu.sql.parser import SQLError
        _setup_orders(ctx)
        ctx.sql("CREATE VIEW v2 AS SELECT id FROM orders")
        with pytest.raises(SQLError, match="time travel"):
            ctx.sql("SELECT * FROM v2 VERSION AS OF 1")

    def test_view_name_conflicts_with_table(self, ctx):
        _setup_orders(ctx)
        with pytest.raises(Exception, match="table named"):
            ctx.sql("CREATE VIEW orders AS SELECT 1")


class TestVariantSql:
    def test_variant_get_in_sql(self, ctx, tmp_path):
        from paimon_tpu.data.variant import column_from_objects
        import pyarrow as _pa
        ctx.register("ev", _pa.table({
            "id": _pa.array([1, 2], _pa.int64()),
            "payload": column_from_objects(
                [{"user": {"name": "ann"}, "n": 3},
                 {"user": {"name": "bo"}, "n": 7}]),
        }))
        out = ctx.sql("SELECT id, variant_get(payload, '$.user.name') "
                      "AS name, variant_get(payload, '$.n') AS n "
                      "FROM ev ORDER BY id")
        assert out.to_pylist() == [
            {"id": 1, "name": "ann", "n": 3},
            {"id": 2, "name": "bo", "n": 7}]


class TestViewEdgeCases:
    def test_replace_function_still_works(self, ctx):
        _setup_orders(ctx)
        out = ctx.sql("SELECT replace(customer, 'a', 'o') AS c "
                      "FROM orders WHERE id = 1")
        assert out.to_pylist() == [{"c": "olice"}]

    def test_cyclic_view_rejected(self, ctx):
        from paimon_tpu.sql.parser import SQLError
        _setup_orders(ctx)
        ctx.sql("CREATE VIEW va AS SELECT id FROM orders")
        ctx.sql("CREATE OR REPLACE VIEW va AS SELECT id FROM va")
        with pytest.raises(SQLError, match="cyclic"):
            ctx.sql("SELECT * FROM va")

    def test_view_resolves_in_defining_database(self, ctx):
        _setup_orders(ctx)
        ctx.sql("CREATE VIEW dv AS SELECT id FROM orders")
        ctx.sql("CREATE DATABASE other")
        ctx.sql("USE other")
        out = ctx.sql("SELECT count(*) AS n FROM default.dv")
        assert out.to_pylist() == [{"n": 5}]

    def test_table_cannot_shadow_view(self, ctx):
        _setup_orders(ctx)
        ctx.sql("CREATE VIEW sv AS SELECT id FROM orders")
        with pytest.raises(Exception, match="view named"):
            ctx.sql("CREATE TABLE sv (x BIGINT)")


class TestCatalogFunctions:
    def test_create_call_drop(self, ctx):
        _setup_orders(ctx)
        ctx.sql("CREATE FUNCTION total (price DOUBLE, n INT) "
                "RETURNS DOUBLE AS 'price * n'")
        out = ctx.sql("SELECT id, total(amount, qty) AS t FROM orders "
                      "WHERE id <= 2 ORDER BY id")
        assert out.to_pylist() == [{"id": 1, "t": 20.0},
                                   {"id": 2, "t": 20.5}]
        assert ctx.sql("SHOW FUNCTIONS") \
            .column("function_name").to_pylist() == ["total"]
        ctx.sql("DROP FUNCTION total")
        assert ctx.sql("SHOW FUNCTIONS").num_rows == 0

    def test_udf_in_where_gets_pushdown_semantics(self, ctx):
        _setup_orders(ctx)
        ctx.sql("CREATE FUNCTION at_least (v DOUBLE, bound DOUBLE) "
                "RETURNS BOOLEAN AS 'v >= bound'")
        out = ctx.sql("SELECT id FROM orders "
                      "WHERE at_least(amount, 15.0) ORDER BY id")
        assert out.column("id").to_pylist() == [2, 4, 5]

    def test_udf_composition_and_nesting(self, ctx):
        _setup_orders(ctx)
        ctx.sql("CREATE FUNCTION twice (x DOUBLE) RETURNS DOUBLE "
                "AS 'x * 2'")
        ctx.sql("CREATE FUNCTION quad (x DOUBLE) RETURNS DOUBLE "
                "AS 'twice(twice(x))'")
        out = ctx.sql("SELECT quad(amount) AS q FROM orders "
                      "WHERE id = 1")
        assert out.to_pylist() == [{"q": 40.0}]

    def test_arity_and_or_replace(self, ctx):
        from paimon_tpu.sql.parser import SQLError
        _setup_orders(ctx)
        ctx.sql("CREATE FUNCTION one (x INT) AS 'x'")
        with pytest.raises(SQLError, match="argument"):
            ctx.sql("SELECT one(1, 2) FROM orders")
        ctx.sql("CREATE OR REPLACE FUNCTION one (x INT, y INT) "
                "AS 'x + y'")
        assert ctx.sql("SELECT one(1, 2) AS v").to_pylist() == \
            [{"v": 3}]

    def test_builtins_not_shadowed(self, ctx):
        _setup_orders(ctx)
        # a catalog function named like a builtin never shadows it
        from paimon_tpu.catalog.function import (Function,
                                                 FunctionDefinition)
        ctx.catalog.create_function(
            ctx._ident("upper"),
            Function([("x", "STRING")],
                     definitions={"sql": FunctionDefinition(
                         "sql", definition="'shadowed'")}))
        out = ctx.sql("SELECT upper(customer) AS c FROM orders "
                      "WHERE id = 1")
        assert out.to_pylist() == [{"c": "ALICE"}]

    def test_persistence_across_contexts(self, ctx):
        _setup_orders(ctx)
        ctx.sql("CREATE FUNCTION t2x (x INT) AS 'x * 2'")
        from paimon_tpu.sql import SQLContext
        ctx2 = SQLContext(ctx.catalog)
        assert ctx2.sql("SELECT t2x(21) AS v").to_pylist() == \
            [{"v": 42}]

    def test_trailing_garbage_in_body_rejected(self, ctx):
        from paimon_tpu.sql.parser import SQLError
        with pytest.raises(SQLError, match="trailing"):
            ctx.sql("CREATE FUNCTION bad (x INT) AS 'x + 1 zzz 42'")

    def test_builtin_name_rejected_at_create(self, ctx):
        from paimon_tpu.sql.parser import SQLError
        with pytest.raises(SQLError, match="shadow"):
            ctx.sql("CREATE FUNCTION upper (x STRING) AS 'x'")


class TestSearchProcedures:
    def test_call_search_procedures(self, ctx):
        ctx.sql("CREATE TABLE docs (id BIGINT NOT NULL, title STRING, "
                "emb ARRAY<FLOAT>, PRIMARY KEY (id)) "
                "WITH ('bucket' = '1')")
        ctx.sql("INSERT INTO docs VALUES "
                "(1, 'tpu lakehouse guide', ARRAY[1.0, 0.0]), "
                "(2, 'cooking pasta', ARRAY[0.0, 1.0]), "
                "(3, 'tpu kernels', ARRAY[0.9, 0.1])")
        r = ctx.sql("CALL sys.full_text_search('docs', 'title', "
                    "'tpu', 2)")
        assert set(r.column("id").to_pylist()) == {1, 3}
        assert "_score" in r.column_names
        r = ctx.sql("CALL sys.vector_search('docs', 'emb', "
                    "'1.0,0.05', 1)")
        assert r.column("id").to_pylist() == [1]
        r = ctx.sql("CALL sys.hybrid_search('docs', 'emb', '0.9,0.1', "
                    "'title', 'tpu kernels', 2)")
        assert r.column("id").to_pylist()[0] == 3


class TestDdlTypeMatrix:
    """Parameterized / nested types in DDL — reference
    paimon-api types/DataTypes.java surface."""

    def test_create_with_nested_types(self, ctx):
        ctx.sql(
            "CREATE TABLE typed ("
            " id BIGINT NOT NULL,"
            " tags ARRAY<STRING>,"
            " nested ARRAY<ARRAY<INT>>,"
            " attrs MAP<STRING, INT>,"
            " price DECIMAL(10, 2),"
            " pt ROW<x DOUBLE, y DOUBLE>,"
            " ms MULTISET<STRING>,"
            " ts3 TIMESTAMP(3),"
            " PRIMARY KEY (id)) WITH ('bucket' = '1')")
        out = ctx.sql("DESCRIBE typed")
        types = dict(zip(out.column("name").to_pylist(),
                         out.column("type").to_pylist()))
        assert types["tags"].startswith("ARRAY<")
        assert types["attrs"].startswith("MAP<")
        assert "DECIMAL(10, 2)" in types["price"]
        assert types["pt"].startswith("ROW<")

    def test_array_literal_roundtrip(self, ctx):
        ctx.sql("CREATE TABLE arr_t (id BIGINT NOT NULL, v ARRAY<DOUBLE>, "
                "PRIMARY KEY (id)) WITH ('bucket' = '1')")
        ctx.sql("INSERT INTO arr_t VALUES (1, ARRAY[1.5, 2.5]), "
                "(2, ARRAY[]), (3, NULL)")
        rows = {r["id"]: r["v"]
                for r in ctx.sql("SELECT id, v FROM arr_t").to_pylist()}
        assert rows[1] == [1.5, 2.5]
        assert rows[2] == []
        assert rows[3] is None

    def test_map_literal_roundtrip(self, ctx):
        ctx.sql("CREATE TABLE map_t (id BIGINT NOT NULL, "
                "m MAP<STRING, BIGINT>, PRIMARY KEY (id)) "
                "WITH ('bucket' = '1')")
        ctx.sql("INSERT INTO map_t VALUES (1, MAP['a', 1, 'b', 2])")
        got = ctx.sql("SELECT m FROM map_t").to_pylist()[0]["m"]
        assert dict(got) == {"a": 1, "b": 2}

    def test_cast_to_parameterized_type(self, ctx):
        out = ctx.sql("SELECT CAST(1.5 AS DECIMAL(8, 3)) AS d")
        import decimal
        assert out.to_pylist()[0]["d"] == decimal.Decimal("1.500")

    def test_bad_generic_rejected(self, ctx):
        with pytest.raises(SQLError):
            ctx.sql("CREATE TABLE b1 (id INT, v ARRAY<)")
        with pytest.raises((SQLError, ValueError)):
            ctx.sql("CREATE TABLE b2 (id INT, v MAP<INT>)")


class TestCTE:
    """WITH common table expressions (desugared to named subqueries at
    parse time — the reference gets CTEs from its DataFusion SQL
    layer)."""

    def _ctx(self, tmp_path):
        from paimon_tpu.catalog import create_catalog
        from paimon_tpu.sql import SQLContext
        cat = create_catalog({"warehouse": str(tmp_path / "wh")})
        ctx = SQLContext(cat)
        ctx.sql("CREATE DATABASE db")
        ctx.sql("CREATE TABLE db.t (id BIGINT NOT NULL, v DOUBLE, "
                "PRIMARY KEY (id)) WITH ('bucket'='1')")
        ctx.sql("INSERT INTO db.t VALUES (1, 1.5), (2, 2.5), (3, 3.5)")
        return ctx

    def test_basic(self, tmp_path):
        ctx = self._ctx(tmp_path)
        r = ctx.sql("WITH big AS (SELECT * FROM db.t WHERE v > 2) "
                    "SELECT count(*) AS n FROM big")
        assert r.to_pylist() == [{"n": 2}]

    def test_chained_ctes_and_join(self, tmp_path):
        ctx = self._ctx(tmp_path)
        r = ctx.sql(
            "WITH big AS (SELECT * FROM db.t WHERE v > 2), "
            "tiny AS (SELECT * FROM big WHERE id = 3) "
            "SELECT t.id, tiny.v FROM db.t t "
            "JOIN tiny ON t.id = tiny.id")
        assert r.to_pylist() == [{"id": 3, "v": 3.5}]

    def test_cte_with_alias_and_union(self, tmp_path):
        ctx = self._ctx(tmp_path)
        r = ctx.sql(
            "WITH w AS (SELECT id FROM db.t WHERE id = 1) "
            "SELECT a.id FROM w a UNION ALL SELECT id FROM w")
        assert sorted(x["id"] for x in r.to_pylist()) == [1, 1]

    def test_explain_with(self, tmp_path):
        ctx = self._ctx(tmp_path)
        ctx.sql("EXPLAIN WITH b AS (SELECT * FROM db.t) "
                "SELECT * FROM b")   # no error

    def test_duplicate_cte_name_rejected(self, tmp_path):
        from paimon_tpu.sql.executor import SQLError
        ctx = self._ctx(tmp_path)
        with pytest.raises(SQLError, match="more than once"):
            ctx.sql("WITH a AS (SELECT 1 AS x), a AS (SELECT 2 AS x) "
                    "SELECT * FROM a")

    def test_cte_in_insert_and_view(self, tmp_path):
        ctx = self._ctx(tmp_path)
        ctx.sql("CREATE TABLE db.t2 (id BIGINT NOT NULL, "
                "PRIMARY KEY (id)) WITH ('bucket'='1')")
        ctx.sql("INSERT INTO db.t2 WITH big AS "
                "(SELECT id FROM db.t WHERE v > 2) SELECT id FROM big")
        assert sorted(r["id"] for r in
                      ctx.sql("SELECT id FROM db.t2").to_pylist()) ==             [2, 3]
        ctx.sql("CREATE VIEW db.v AS WITH big AS "
                "(SELECT id FROM db.t WHERE v > 2) "
                "SELECT count(*) AS n FROM big")
        assert ctx.sql("SELECT n FROM db.v").to_pylist() == [{"n": 2}]

    def test_in_subquery(self, tmp_path):
        ctx = self._ctx(tmp_path)
        ctx.sql("CREATE TABLE db.s (id BIGINT NOT NULL, "
                "PRIMARY KEY (id)) WITH ('bucket'='1')")
        ctx.sql("INSERT INTO db.s VALUES (2), (3)")
        got = ctx.sql("SELECT id FROM db.t WHERE id IN "
                      "(SELECT id FROM db.s) ORDER BY id").to_pylist()
        assert [r["id"] for r in got] == [2, 3]
        got = ctx.sql("SELECT id FROM db.t WHERE id NOT IN "
                      "(SELECT id FROM db.s)").to_pylist()
        assert [r["id"] for r in got] == [1]
        # CTE visible inside the IN subquery
        got = ctx.sql(
            "WITH w AS (SELECT id FROM db.s) SELECT id FROM db.t "
            "WHERE id IN (SELECT id FROM w) ORDER BY id").to_pylist()
        assert [r["id"] for r in got] == [2, 3]
        from paimon_tpu.sql.executor import SQLError
        with pytest.raises(SQLError, match="one column"):
            ctx.sql("SELECT id FROM db.t WHERE id IN "
                    "(SELECT id, id FROM db.s)")

    def test_in_subquery_null_three_valued_logic(self, tmp_path):
        ctx = self._ctx(tmp_path)
        ctx.sql("CREATE TABLE db.s (id BIGINT NOT NULL, r BIGINT, "
                "PRIMARY KEY (id)) WITH ('bucket'='1')")
        ctx.sql("INSERT INTO db.s VALUES (10, 2), (11, NULL)")
        # IN against a set containing NULL: only the real match
        got = ctx.sql("SELECT id FROM db.t WHERE id IN "
                      "(SELECT r FROM db.s)").to_pylist()
        assert [r["id"] for r in got] == [2]
        # NOT IN against a set containing NULL: NEVER true
        assert ctx.sql("SELECT id FROM db.t WHERE id NOT IN "
                       "(SELECT r FROM db.s)").to_pylist() == []

    def test_delete_with_in_subquery(self, tmp_path):
        ctx = self._ctx(tmp_path)
        ctx.sql("CREATE TABLE db.s (id BIGINT NOT NULL, "
                "PRIMARY KEY (id)) WITH ('bucket'='1')")
        ctx.sql("INSERT INTO db.s VALUES (2)")
        ctx.sql("DELETE FROM db.t WHERE id IN (SELECT id FROM db.s)")
        got = ctx.sql("SELECT id FROM db.t ORDER BY id").to_pylist()
        assert [r["id"] for r in got] == [1, 3]


class TestSetOps:
    """UNION [DISTINCT] / INTERSECT / EXCEPT (UNION ALL predates)."""

    def _ctx(self, tmp_path):
        from paimon_tpu.catalog import create_catalog
        from paimon_tpu.sql import SQLContext
        cat = create_catalog({"warehouse": str(tmp_path / "wh")})
        ctx = SQLContext(cat)
        ctx.sql("CREATE DATABASE db")
        ctx.sql("CREATE TABLE db.a (id BIGINT NOT NULL, "
                "PRIMARY KEY (id)) WITH ('bucket'='1')")
        ctx.sql("CREATE TABLE db.b (id BIGINT NOT NULL, "
                "PRIMARY KEY (id)) WITH ('bucket'='1')")
        ctx.sql("INSERT INTO db.a VALUES (1), (2), (3)")
        ctx.sql("INSERT INTO db.b VALUES (2), (3), (4)")
        return ctx

    def test_union_distinct(self, tmp_path):
        ctx = self._ctx(tmp_path)
        got = ctx.sql("SELECT id FROM db.a UNION SELECT id FROM db.b "
                      "ORDER BY id").to_pylist()
        assert [r["id"] for r in got] == [1, 2, 3, 4]

    def test_intersect(self, tmp_path):
        ctx = self._ctx(tmp_path)
        got = ctx.sql("SELECT id FROM db.a INTERSECT "
                      "SELECT id FROM db.b ORDER BY id").to_pylist()
        assert [r["id"] for r in got] == [2, 3]

    def test_except(self, tmp_path):
        ctx = self._ctx(tmp_path)
        got = ctx.sql("SELECT id FROM db.a EXCEPT "
                      "SELECT id FROM db.b").to_pylist()
        assert [r["id"] for r in got] == [1]

    def test_union_all_unchanged(self, tmp_path):
        ctx = self._ctx(tmp_path)
        got = ctx.sql("SELECT id FROM db.a UNION ALL "
                      "SELECT id FROM db.b").to_pylist()
        assert sorted(r["id"] for r in got) == [1, 2, 2, 3, 3, 4]

    def test_same_op_chains_allowed(self, tmp_path):
        ctx = self._ctx(tmp_path)
        got = ctx.sql("SELECT id FROM db.a UNION ALL SELECT id FROM "
                      "db.b UNION ALL SELECT id FROM db.a").to_pylist()
        assert len(got) == 9
        got = ctx.sql("SELECT id FROM db.a UNION SELECT id FROM db.b "
                      "UNION SELECT id FROM db.a ORDER BY id").to_pylist()
        assert [r["id"] for r in got] == [1, 2, 3, 4]

    def test_mixed_or_except_chain_rejected(self, tmp_path):
        from paimon_tpu.sql.executor import SQLError
        ctx = self._ctx(tmp_path)
        with pytest.raises(SQLError, match="parenthesize"):
            ctx.sql("SELECT id FROM db.a EXCEPT SELECT id FROM db.b "
                    "EXCEPT SELECT id FROM db.a")
        with pytest.raises(SQLError, match="parenthesize"):
            ctx.sql("SELECT id FROM db.a UNION ALL SELECT id FROM db.b "
                    "UNION SELECT id FROM db.a")
        # the documented workaround
        got = ctx.sql(
            "SELECT * FROM (SELECT id FROM db.a EXCEPT "
            "SELECT id FROM db.b) t EXCEPT SELECT id FROM db.a")
        assert got.to_pylist() == []

    def test_intersect_duplicate_output_names(self, tmp_path):
        ctx = self._ctx(tmp_path)
        # both output columns named 'id': keys must stay positional
        got = ctx.sql(
            "SELECT a.id, b.id FROM db.a a JOIN db.b b ON a.id = b.id "
            "INTERSECT SELECT a.id, b.id FROM db.a a "
            "JOIN db.b b ON a.id = b.id ORDER BY 1").to_pylist()
        assert len(got) == 2

    def test_intersect_array_values(self, tmp_path):
        ctx = self._ctx(tmp_path)
        got = ctx.sql("SELECT ARRAY[1, 2] AS arr FROM db.a INTERSECT "
                      "SELECT ARRAY[1, 2] AS arr FROM db.b").to_pylist()
        assert got == [{"arr": [1, 2]}]


class TestScalarSubquery:
    def _ctx(self, tmp_path):
        from paimon_tpu.catalog import create_catalog
        from paimon_tpu.sql import SQLContext
        cat = create_catalog({"warehouse": str(tmp_path / "wh")})
        ctx = SQLContext(cat)
        ctx.sql("CREATE DATABASE db")
        ctx.sql("CREATE TABLE db.t (id BIGINT NOT NULL, v DOUBLE, "
                "PRIMARY KEY (id)) WITH ('bucket'='1')")
        ctx.sql("INSERT INTO db.t VALUES (1, 1.5), (2, 2.5), (3, 3.5)")
        return ctx

    def test_in_where(self, tmp_path):
        ctx = self._ctx(tmp_path)
        got = ctx.sql("SELECT id FROM db.t WHERE v = "
                      "(SELECT max(v) FROM db.t)").to_pylist()
        assert got == [{"id": 3}]

    def test_in_projection(self, tmp_path):
        ctx = self._ctx(tmp_path)
        got = ctx.sql("SELECT id, v - (SELECT avg(v) FROM db.t) AS d "
                      "FROM db.t ORDER BY id").to_pylist()
        assert [round(r["d"], 6) for r in got] == [-1.0, 0.0, 1.0]

    def test_empty_is_null_and_multirow_errors(self, tmp_path):
        from paimon_tpu.sql.executor import SQLError
        ctx = self._ctx(tmp_path)
        got = ctx.sql("SELECT id FROM db.t WHERE v < "
                      "(SELECT v FROM db.t WHERE id = 99)").to_pylist()
        assert got == []          # NULL comparison filters all
        with pytest.raises(SQLError, match="more than one row"):
            ctx.sql("SELECT (SELECT v FROM db.t) FROM db.t")

    def test_in_update_and_insert(self, tmp_path):
        ctx = self._ctx(tmp_path)
        ctx.sql("UPDATE db.t SET v = (SELECT max(v) FROM db.t) "
                "WHERE id = 1")
        got = ctx.sql("SELECT v FROM db.t WHERE id = 1").to_pylist()
        assert got == [{"v": 3.5}]
        ctx.sql("INSERT INTO db.t VALUES "
                "(4, (SELECT min(v) FROM db.t))")
        got = ctx.sql("SELECT v FROM db.t WHERE id = 4").to_pylist()
        assert got == [{"v": 2.5}]


class TestMaintenanceProcedures:
    """CALL sys.* parity with the reference's procedure set
    (flink/procedure/*Procedure.java)."""

    def _ctx(self, tmp_path):
        from paimon_tpu.catalog import create_catalog
        from paimon_tpu.sql import SQLContext
        cat = create_catalog({"warehouse": str(tmp_path / "wh")})
        ctx = SQLContext(cat)
        ctx.sql("CREATE DATABASE db")
        ctx.sql("CREATE TABLE db.t (id BIGINT NOT NULL, v DOUBLE, "
                "PRIMARY KEY (id)) WITH ('bucket'='1')")
        for i in range(3):
            ctx.sql(f"INSERT INTO db.t VALUES ({i}, {float(i)})")
        return ctx

    def test_rename_tag(self, tmp_path):
        ctx = self._ctx(tmp_path)
        ctx.sql("CALL sys.create_tag('db.t', 'old')")
        ctx.sql("CALL sys.rename_tag('db.t', 'old', 'new')")
        tags = ctx.sql("SELECT tag_name FROM db.`t$tags`").to_pylist()
        assert [t["tag_name"] for t in tags] == ["new"]

    def test_rollback_and_tag_from_timestamp(self, tmp_path):
        ctx = self._ctx(tmp_path)
        cat = ctx.catalog
        t = cat.get_table("db.t")
        snap2 = t.snapshot_manager.snapshot(2)
        ctx.sql(f"CALL sys.create_tag_from_timestamp('db.t', 'at2', "
                f"{snap2.time_millis})")
        got = ctx.sql("SELECT count(*) AS n FROM db.t "
                      "VERSION AS OF 'at2'").to_pylist()
        assert got == [{"n": 2}]
        ctx.sql(f"CALL sys.rollback_to_timestamp('db.t', "
                f"{snap2.time_millis})")
        assert ctx.sql("SELECT count(*) AS n FROM db.t").to_pylist() \
            == [{"n": 2}]

    def test_clear_consumers(self, tmp_path):
        ctx = self._ctx(tmp_path)
        t = ctx.catalog.get_table("db.t")
        t.consumer_manager.record_consumer("job-a", 2)
        t.consumer_manager.record_consumer("other", 2)
        ctx.sql("CALL sys.clear_consumers('db.t', 'job-.*')")
        assert list(t.consumer_manager.consumers()) == ["other"]
        ctx.sql("CALL sys.clear_consumers('db.t')")
        assert not t.consumer_manager.consumers()

    def test_expire_tags_and_trigger_auto(self, tmp_path):
        ctx = self._ctx(tmp_path)
        out = ctx.sql("CALL sys.expire_tags('db.t')")
        assert "0 tags expired" in str(out.to_pylist())
        # the procedure rides the table options: set via ALTER
        ctx.sql("ALTER TABLE db.t SET "
                "('tag.automatic-creation'='process-time', "
                "'tag.creation-period'='daily')")
        out = ctx.sql("CALL sys.trigger_tag_automatic_creation('db.t')")
        assert "tags created" in str(out.to_pylist())

    def test_expire_changelogs_procedure(self, tmp_path):
        ctx = self._ctx(tmp_path)
        out = ctx.sql("CALL sys.expire_changelogs('db.t', 1)")
        assert "expired" in str(out.to_pylist())

    def test_rename_tag_preserves_retention(self, tmp_path):
        ctx = self._ctx(tmp_path)
        t = ctx.catalog.get_table("db.t")
        t.tag_manager.create_tag(t.latest_snapshot(), "tmp",
                                 time_retained_ms=60_000)
        ctx.sql("CALL sys.rename_tag('db.t', 'tmp', 'kept')")
        import json
        raw = json.loads(t.file_io.read_utf8(
            t.tag_manager.tag_path("kept")))
        assert raw.get("tagTimeRetained") == 60_000

    def test_tag_from_timestamp_arity(self, tmp_path):
        from paimon_tpu.sql.executor import SQLError
        ctx = self._ctx(tmp_path)
        with pytest.raises(SQLError, match="tag, millis"):
            ctx.sql("CALL sys.create_tag_from_timestamp('db.t', "
                    "1690000000000)")

    def test_repair_procedures(self, tmp_path):
        ctx = self._ctx(tmp_path)
        out = ctx.sql("CALL sys.remove_unexisting_files('db.t')")
        assert "0 files removed" in str(out.to_pylist())
        out = ctx.sql("CALL sys.compact_manifest('db.t')")
        assert "manifests compacted" in str(out.to_pylist())
        assert ctx.sql("SELECT count(*) AS n FROM db.t").to_pylist() \
            == [{"n": 3}]


class TestExists:
    def _ctx(self, tmp_path):
        from paimon_tpu.catalog import create_catalog
        from paimon_tpu.sql import SQLContext
        cat = create_catalog({"warehouse": str(tmp_path / "wh")})
        ctx = SQLContext(cat)
        ctx.sql("CREATE DATABASE db")
        ctx.sql("CREATE TABLE db.t (id BIGINT NOT NULL, v DOUBLE, "
                "PRIMARY KEY (id)) WITH ('bucket'='1')")
        ctx.sql("CREATE TABLE db.s (sid BIGINT NOT NULL, r BIGINT, "
                "PRIMARY KEY (sid)) WITH ('bucket'='1')")
        ctx.sql("INSERT INTO db.t VALUES (1, 1.5), (2, 2.5), (3, 3.5)")
        ctx.sql("INSERT INTO db.s VALUES (10, 2), (11, 3), (12, NULL)")
        return ctx

    def test_correlated_exists(self, tmp_path):
        ctx = self._ctx(tmp_path)
        got = ctx.sql("SELECT id FROM db.t WHERE EXISTS "
                      "(SELECT 1 FROM db.s WHERE r = id) "
                      "ORDER BY id").to_pylist()
        assert [x["id"] for x in got] == [2, 3]

    def test_correlated_not_exists_with_inner_nulls(self, tmp_path):
        ctx = self._ctx(tmp_path)
        # inner NULL r must NOT poison NOT EXISTS (unlike raw NOT IN)
        got = ctx.sql("SELECT id FROM db.t WHERE NOT EXISTS "
                      "(SELECT 1 FROM db.s WHERE r = id)").to_pylist()
        assert [x["id"] for x in got] == [1]

    def test_correlated_with_extra_inner_filter(self, tmp_path):
        ctx = self._ctx(tmp_path)
        got = ctx.sql("SELECT id FROM db.t WHERE EXISTS "
                      "(SELECT 1 FROM db.s WHERE r = id AND sid > 10)"
                      ).to_pylist()
        assert [x["id"] for x in got] == [3]

    def test_uncorrelated_exists(self, tmp_path):
        ctx = self._ctx(tmp_path)
        assert len(ctx.sql("SELECT id FROM db.t WHERE EXISTS "
                           "(SELECT 1 FROM db.s WHERE sid > 11)")
                   .to_pylist()) == 3
        assert ctx.sql("SELECT id FROM db.t WHERE EXISTS "
                       "(SELECT 1 FROM db.s WHERE sid > 99)") \
            .to_pylist() == []
        assert len(ctx.sql("SELECT id FROM db.t WHERE NOT EXISTS "
                           "(SELECT 1 FROM db.s WHERE sid > 99)")
                   .to_pylist()) == 3

    def test_qualified_correlation(self, tmp_path):
        ctx = self._ctx(tmp_path)
        got = ctx.sql(
            "SELECT t.id FROM db.t t WHERE EXISTS "
            "(SELECT 1 FROM db.s x WHERE x.r = t.id) ORDER BY t.id"
        ).to_pylist()
        assert [x["id"] for x in got] == [2, 3]

    def test_outer_null_correlation(self, tmp_path):
        ctx = self._ctx(tmp_path)
        ctx.sql("CREATE TABLE db.u (id BIGINT NOT NULL, w BIGINT, "
                "PRIMARY KEY (id)) WITH ('bucket'='1')")
        ctx.sql("INSERT INTO db.u VALUES (1, 2), (2, NULL)")
        # NULL w: r = w can never hold -> NOT EXISTS is TRUE
        got = ctx.sql("SELECT id FROM db.u WHERE NOT EXISTS "
                      "(SELECT 1 FROM db.s WHERE r = w)").to_pylist()
        assert [x["id"] for x in got] == [2]
        got = ctx.sql("SELECT id FROM db.u WHERE EXISTS "
                      "(SELECT 1 FROM db.s WHERE r = w)").to_pylist()
        assert [x["id"] for x in got] == [1]

    def test_uncorrelated_union_and_limit_shapes(self, tmp_path):
        ctx = self._ctx(tmp_path)
        # non-empty second UNION branch must count
        got = ctx.sql(
            "SELECT id FROM db.t WHERE EXISTS (SELECT sid FROM db.s "
            "WHERE sid > 99 UNION ALL SELECT id FROM db.t)")
        assert len(got.to_pylist()) == 3
        # OFFSET past the end -> empty -> EXISTS false
        got = ctx.sql("SELECT id FROM db.t WHERE EXISTS "
                      "(SELECT sid FROM db.s LIMIT 10 OFFSET 5)")
        assert got.to_pylist() == []

    def test_correlated_unsupported_shapes_raise(self, tmp_path):
        from paimon_tpu.sql.executor import SQLError
        ctx = self._ctx(tmp_path)
        with pytest.raises(SQLError, match="aggregates"):
            ctx.sql("SELECT id FROM db.t WHERE EXISTS "
                    "(SELECT count(*) FROM db.s WHERE r = id)")
        with pytest.raises(SQLError, match="LIMIT"):
            ctx.sql("SELECT id FROM db.t WHERE EXISTS "
                    "(SELECT 1 FROM db.s WHERE r = id LIMIT 0)")


class TestTruncate:
    def test_truncate_and_purge(self, tmp_path):
        from paimon_tpu.catalog import create_catalog
        from paimon_tpu.sql import SQLContext
        cat = create_catalog({"warehouse": str(tmp_path / "wh")})
        ctx = SQLContext(cat)
        ctx.sql("CREATE DATABASE db")
        ctx.sql("CREATE TABLE db.t (id BIGINT NOT NULL, "
                "PRIMARY KEY (id)) WITH ('bucket'='1')")
        ctx.sql("INSERT INTO db.t VALUES (1), (2)")
        ctx.sql("TRUNCATE TABLE db.t")
        assert ctx.sql("SELECT count(*) AS n FROM db.t").to_pylist() \
            == [{"n": 0}]
        # time travel still sees the pre-truncate state
        assert ctx.sql("SELECT count(*) AS n FROM db.t "
                       "VERSION AS OF 1").to_pylist() == [{"n": 2}]
        ctx.sql("INSERT INTO db.t VALUES (3)")
        ctx.sql("CALL sys.purge_files('db.t')")
        assert ctx.sql("SELECT count(*) AS n FROM db.t").to_pylist() \
            == [{"n": 0}]

    def test_truncate_not_reserved_as_identifier(self, tmp_path):
        from paimon_tpu.catalog import create_catalog
        from paimon_tpu.sql import SQLContext
        cat = create_catalog({"warehouse": str(tmp_path / "wh2")})
        ctx = SQLContext(cat)
        ctx.sql("CREATE DATABASE db")
        ctx.sql("CREATE TABLE db.k (id BIGINT NOT NULL, "
                "truncate BIGINT, PRIMARY KEY (id)) "
                "WITH ('bucket'='1')")
        ctx.sql("INSERT INTO db.k VALUES (1, 7)")
        got = ctx.sql("SELECT truncate FROM db.k").to_pylist()
        assert got == [{"truncate": 7}]


class TestMergeInto:
    def _ctx(self, tmp_path):
        from paimon_tpu.catalog import create_catalog
        from paimon_tpu.sql import SQLContext
        cat = create_catalog({"warehouse": str(tmp_path / "wh")})
        ctx = SQLContext(cat)
        ctx.sql("CREATE DATABASE db")
        ctx.sql("CREATE TABLE db.t (id BIGINT NOT NULL, v DOUBLE, "
                "tag STRING, PRIMARY KEY (id)) WITH ('bucket'='1')")
        ctx.sql("CREATE TABLE db.s (id BIGINT NOT NULL, nv DOUBLE, "
                "op STRING, PRIMARY KEY (id)) WITH ('bucket'='1')")
        ctx.sql("INSERT INTO db.t VALUES (1, 1.0, 'old'), "
                "(2, 2.0, 'old'), (3, 3.0, 'old')")
        ctx.sql("INSERT INTO db.s VALUES (2, 20.0, 'upd'), "
                "(3, 0.0, 'del'), (4, 40.0, 'new')")
        return ctx

    def test_update_delete_insert(self, tmp_path):
        ctx = self._ctx(tmp_path)
        out = ctx.sql(
            "MERGE INTO db.t AS t USING db.s AS s ON t.id = s.id "
            "WHEN MATCHED AND s.op = 'del' THEN DELETE "
            "WHEN MATCHED THEN UPDATE SET v = s.nv, tag = 'merged' "
            "WHEN NOT MATCHED THEN INSERT (id, v, tag) "
            "VALUES (s.id, s.nv, 'inserted')")
        assert "rows merged" in str(out.to_pylist())
        rows = ctx.sql("SELECT * FROM db.t ORDER BY id").to_pylist()
        assert rows == [
            {"id": 1, "v": 1.0, "tag": "old"},
            {"id": 2, "v": 20.0, "tag": "merged"},
            {"id": 4, "v": 40.0, "tag": "inserted"},
        ]

    def test_first_matching_clause_wins(self, tmp_path):
        ctx = self._ctx(tmp_path)
        ctx.sql(
            "MERGE INTO db.t AS t USING db.s AS s ON t.id = s.id "
            "WHEN MATCHED THEN UPDATE SET tag = 'first' "
            "WHEN MATCHED AND s.op = 'del' THEN DELETE")
        rows = ctx.sql("SELECT id, tag FROM db.t ORDER BY id") \
            .to_pylist()
        # the unconditional first clause claimed ALL matches: no delete
        assert rows == [{"id": 1, "tag": "old"},
                        {"id": 2, "tag": "first"},
                        {"id": 3, "tag": "first"}]

    def test_subquery_source_and_missing_insert_cols(self, tmp_path):
        ctx = self._ctx(tmp_path)
        ctx.sql(
            "MERGE INTO db.t t USING "
            "(SELECT id, nv FROM db.s WHERE op <> 'del') s "
            "ON t.id = s.id "
            "WHEN NOT MATCHED THEN INSERT (id, v) VALUES (s.id, s.nv)")
        rows = ctx.sql("SELECT id, v, tag FROM db.t WHERE id = 4") \
            .to_pylist()
        assert rows == [{"id": 4, "v": 40.0, "tag": None}]

    def test_key_update_rejected(self, tmp_path):
        from paimon_tpu.sql.executor import SQLError
        ctx = self._ctx(tmp_path)
        with pytest.raises(SQLError, match="key column"):
            ctx.sql("MERGE INTO db.t t USING db.s s ON t.id = s.id "
                    "WHEN MATCHED THEN UPDATE SET id = s.id")

    def test_append_target_rejected(self, tmp_path):
        from paimon_tpu.sql.executor import SQLError
        ctx = self._ctx(tmp_path)
        ctx.sql("CREATE TABLE db.ap (id BIGINT NOT NULL)")
        with pytest.raises(SQLError, match="primary-key"):
            ctx.sql("MERGE INTO db.ap a USING db.s s ON a.id = s.id "
                    "WHEN MATCHED THEN DELETE")

    def test_duplicate_source_keys_rejected(self, tmp_path):
        from paimon_tpu.sql.executor import SQLError
        ctx = self._ctx(tmp_path)
        with pytest.raises(SQLError, match="more than once"):
            ctx.sql(
                "MERGE INTO db.t t USING "
                "(SELECT id, nv FROM db.s UNION ALL "
                " SELECT id, nv FROM db.s) s ON t.id = s.id "
                "WHEN MATCHED THEN UPDATE SET v = s.nv")

    def test_key_update_rejected_even_with_no_matches(self, tmp_path):
        from paimon_tpu.sql.executor import SQLError
        ctx = self._ctx(tmp_path)
        with pytest.raises(SQLError, match="key column"):
            ctx.sql("MERGE INTO db.t t USING "
                    "(SELECT id, nv FROM db.s WHERE id > 999) s "
                    "ON t.id = s.id "
                    "WHEN MATCHED THEN UPDATE SET id = s.id")

    def test_merge_words_stay_identifiers(self, tmp_path):
        ctx = self._ctx(tmp_path)
        ctx.sql("CREATE TABLE db.w (id BIGINT NOT NULL, "
                "matched BIGINT, merge BIGINT, using BIGINT, "
                "PRIMARY KEY (id)) WITH ('bucket'='1')")
        ctx.sql("INSERT INTO db.w VALUES (1, 2, 3, 4)")
        got = ctx.sql("SELECT matched, merge, using FROM db.w") \
            .to_pylist()
        assert got == [{"matched": 2, "merge": 3, "using": 4}]


class TestTagFromWatermark:
    def test_create_tag_from_watermark(self, tmp_path):
        from paimon_tpu.catalog import create_catalog
        from paimon_tpu.sql import SQLContext
        cat = create_catalog({"warehouse": str(tmp_path / "wh")})
        ctx = SQLContext(cat)
        ctx.sql("CREATE DATABASE db")
        ctx.sql("CREATE TABLE db.t (id BIGINT NOT NULL, "
                "PRIMARY KEY (id)) WITH ('bucket'='1')")
        t = cat.get_table("db.t")
        for i, wm in enumerate([100, 200, 300]):
            wb = t.new_batch_write_builder()
            w = wb.new_write()
            w.write_dicts([{"id": i}])
            wb.new_commit().commit(w.prepare_commit(), watermark=wm)
            w.close()
        out = ctx.sql(
            "CALL sys.create_tag_from_watermark('db.t', 'wm', 150)")
        assert "snapshot 2" in str(out.to_pylist())
        got = ctx.sql("SELECT count(*) AS n FROM db.t "
                      "VERSION AS OF 'wm'").to_pylist()
        assert got == [{"n": 2}]
        from paimon_tpu.sql.executor import SQLError
        import pytest as _pt
        with _pt.raises(SQLError, match="watermark"):
            ctx.sql("CALL sys.create_tag_from_watermark('db.t', 'x', "
                    "99999)")
