"""Native serving hot path (PR 18).

* BUILD SMOKE: `native/*.c` compiles fresh in a temp dir and the
  resulting `.so` exports EVERY symbol python binds — the guard
  against a probe symbol silently missing (a stale cached lib would
  serve the slow path forever).
* PROBE PARITY: the batched C probe (`sst_probe_batch`) against the
  python bloom+searchsorted oracle — identical hits and rows across
  tombstones, empty SSTs, equal-key runs spanning blocks, partitioned
  batches, and misses.
* FALLBACK: a lib without the probe symbols degrades per-call to the
  python path, counted by `lookup.native_fallbacks`, answers
  unchanged.
* CONCURRENT SERVING: /lookup batches through the native probe under
  live commits and full compaction — no torn batches, SSTs for
  compacted-away files dropped and rebuilt once.
* WARM BOOT: persisted serving state restores with reader_builds == 0.
* REMOTE REPLICAS: POST /register joins the ring, the health loop
  suspends an unreachable replica after two failures and re-admits on
  the first success, /deregister leaves cleanly.
"""

import ctypes
import json
import os
import threading
import time
import urllib.request

import numpy as np
import pyarrow as pa
import pytest

from paimon_tpu import native
from paimon_tpu.lookup.sst import (
    BlockCache, SstReader, SstWriter, force_python_probe, pack_lanes,
)
from paimon_tpu.metrics import (
    LOOKUP_NATIVE_FALLBACKS, LOOKUP_NATIVE_PROBES, LOOKUP_READER_BUILDS,
    global_registry,
)
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, IntType, VarCharType

_HAS_NATIVE = native.load() is not None
_HAS_PROBE = _HAS_NATIVE and hasattr(native.load(), "sst_probe_batch")

needs_probe = pytest.mark.skipif(
    not _HAS_PROBE, reason="native sst_probe_batch unavailable")


def _counter(name):
    return global_registry().lookup_metrics().counter(name)


def _pk_table(path, buckets=2, extra_opts=None, partition=False):
    opts = {"bucket": str(buckets), "write-only": "true"}
    opts.update(extra_opts or {})
    b = (Schema.builder()
         .column("id", BigIntType(False))
         .column("name", VarCharType.string_type()))
    if partition:
        b = b.column("p", IntType(False)).partition_keys("p") \
             .primary_key("p", "id")
    else:
        b = b.primary_key("id")
    return FileStoreTable.create(path, b.options(opts).build())


def _commit(table, rows, kinds=None):
    wb = table.new_batch_write_builder()
    with wb.new_write() as w:
        w.write_dicts(rows, row_kinds=kinds)
        wb.new_commit().commit(w.prepare_commit())


# -- build smoke -------------------------------------------------------------


@pytest.mark.skipif(not _HAS_NATIVE, reason="no C compiler available")
class TestNativeBuildSmoke:
    def test_fresh_build_exports_every_bound_symbol(self, tmp_path):
        """Compile native/*.c from scratch; the .so must export every
        symbol the python side binds (REQUIRED + OPTIONAL) — the
        build-level guard that a new symbol generation actually made
        it into the artifact."""
        so = native.build_fresh(str(tmp_path))
        lib = ctypes.CDLL(so)
        for sym in native.EXPORTED_SYMBOLS:
            assert hasattr(lib, sym), f"fresh .so missing {sym}"

    def test_loaded_lib_exports_every_bound_symbol(self):
        """The CACHED lib the process actually serves with has the full
        symbol set too — a stale .so from before a new symbol was
        added loads fine but would silently pin the fallback path."""
        lib = native.load()
        missing = [s for s in native.EXPORTED_SYMBOLS
                   if not hasattr(lib, s)]
        assert not missing, \
            f"cached .so is stale, missing {missing} — " \
            f"remove it and rebuild"


# -- probe parity ------------------------------------------------------------


def _probe_both(reader, queries):
    """(native hits/rows, python hits/rows) for one query batch, as
    comparable (sorted hit list, sorted row tuples)."""
    def norm(res):
        hit, rows = res
        if rows is None:
            return sorted(hit.tolist()), []
        keep = [c for c in rows.column_names]
        body = list(zip(hit.tolist(),
                        *[rows.column(c).to_pylist() for c in keep]))
        return sorted(hit.tolist()), sorted(body)
    n = norm(reader.probe(queries))
    with force_python_probe():
        p = norm(reader.probe(queries))
    return n, p


@needs_probe
class TestProbeParity:
    def _sorted(self, n, num_lanes=2, seed=0, dupes=None):
        rng = np.random.default_rng(seed)
        hi = max((n // dupes) if dupes else 1 << 32, 1)
        lanes = rng.integers(0, hi, (n, num_lanes),
                             dtype=np.uint64).astype(np.uint32)
        order = np.argsort(pack_lanes(lanes), kind="stable")
        t = pa.table({"v": pa.array(np.arange(n), pa.int64())})
        return lanes[order], t.take(order)

    @pytest.mark.parametrize("block_rows", [64, 512])
    def test_random_hits_and_misses(self, tmp_path, block_rows):
        lanes, t = self._sorted(5_000, seed=1)
        path = str(tmp_path / "f.sst")
        SstWriter(block_rows=block_rows).write(path, lanes, t)
        r = SstReader(path, BlockCache())
        rng = np.random.default_rng(2)
        queries = np.concatenate([
            lanes[rng.integers(0, len(lanes), 300)],
            rng.integers(0, 1 << 32, (300, 2),
                         dtype=np.uint64).astype(np.uint32)])
        n, p = _probe_both(r, queries)
        assert n == p

    def test_equal_key_runs_spanning_blocks(self, tmp_path):
        """A run of equal packed keys crossing block boundaries (lanes
        prefix-truncate long string keys) must yield EVERY row of the
        run on both paths."""
        lanes, t = self._sorted(4_000, seed=3, dupes=40)  # ~100 each
        path = str(tmp_path / "f.sst")
        SstWriter(block_rows=64).write(path, lanes, t)
        r = SstReader(path, BlockCache())
        queries = lanes[::97]
        n, p = _probe_both(r, queries)
        assert n == p
        assert len(n[1]) > len(queries)      # runs actually probed

    def test_empty_sst(self, tmp_path):
        lanes = np.zeros((0, 2), np.uint32)
        t = pa.table({"v": pa.array([], pa.int64())})
        path = str(tmp_path / "e.sst")
        SstWriter().write(path, lanes, t)
        r = SstReader(path, BlockCache())
        hit, rows = r.probe(np.zeros((3, 2), np.uint32))
        assert len(hit) == 0 and rows is None

    def test_lookup_oracle_with_tombstones(self, tmp_path):
        """End to end through LocalTableQuery: updates + deletes, the
        native answers identical to python AND to the merged scan."""
        from paimon_tpu.lookup import LocalTableQuery
        t = _pk_table(str(tmp_path / "t"), buckets=2)
        _commit(t, [{"id": i, "name": f"a{i}"} for i in range(300)])
        _commit(t, [{"id": i, "name": f"b{i}"}
                    for i in range(0, 300, 3)])
        from paimon_tpu.types import RowKind
        _commit(t, [{"id": i, "name": "x"} for i in range(0, 300, 5)],
                kinds=[RowKind.DELETE] * len(range(0, 300, 5)))
        oracle = {r["id"]: r["name"]
                  for r in t.to_arrow().to_pylist()}
        q = LocalTableQuery(t, cache_dir=str(tmp_path / "c"))
        keys = [{"id": i} for i in range(-5, 310)]
        native_probes0 = _counter(LOOKUP_NATIVE_PROBES).count
        got_native = q.lookup(keys)
        assert _counter(LOOKUP_NATIVE_PROBES).count > native_probes0
        with force_python_probe():
            got_python = q.lookup(keys)
        assert got_native == got_python
        for k, row in zip(keys, got_native):
            exp = oracle.get(k["id"])
            if exp is None:
                assert row is None, (k, row)
            else:
                assert row == {"id": k["id"], "name": exp}

    def test_lookup_partitioned_batches(self, tmp_path):
        """Per-partition batches against a partitioned pk table (and
        multiple buckets inside each): the native probe resolves each
        partition's sub-batches identically to python, including a
        partition that does not exist."""
        from paimon_tpu.lookup import LocalTableQuery
        t = _pk_table(str(tmp_path / "t"), buckets=2, partition=True)
        rows = [{"p": p, "id": i, "name": f"p{p}-{i}"}
                for p in range(3) for i in range(100)]
        _commit(t, rows)
        q = LocalTableQuery(t, cache_dir=str(tmp_path / "c"))
        for p in range(4):                    # p=3 does not exist
            keys = [{"p": p, "id": i} for i in range(0, 110, 7)]
            got = q.lookup(keys, partition=(p,))
            with force_python_probe():
                exp = q.lookup(keys, partition=(p,))
            assert got == exp
            for k, row in zip(keys, got):
                if p < 3 and k["id"] < 100:
                    assert row["name"] == f"p{p}-{k['id']}"
                else:
                    assert row is None


# -- fallback ----------------------------------------------------------------


@needs_probe
class TestNativeFallback:
    def test_missing_symbol_degrades_per_call(self, tmp_path,
                                              monkeypatch):
        """native.sst_probe returning None (no lib / stale .so without
        the symbol) must fall back to python per call, count
        `lookup.native_fallbacks`, and answer identically.  The raw
        pointer prepared path is disabled up front (a stale .so never
        resolves a prep context), so every probe routes through
        sst_probe — the per-call degradation gate under test."""
        from paimon_tpu.lookup import LocalTableQuery
        monkeypatch.setattr(native, "sst_probe_prepare",
                            lambda *a, **k: None)
        t = _pk_table(str(tmp_path / "t"), buckets=1)
        _commit(t, [{"id": i, "name": f"n{i}"} for i in range(100)])
        q = LocalTableQuery(t, cache_dir=str(tmp_path / "c"))
        keys = [{"id": i} for i in range(0, 100, 3)] + [{"id": 999}]
        expected = q.lookup(keys)
        fallbacks0 = _counter(LOOKUP_NATIVE_FALLBACKS).count
        native0 = _counter(LOOKUP_NATIVE_PROBES).count
        monkeypatch.setattr(native, "sst_probe",
                            lambda *a, **k: None)
        assert q.lookup(keys) == expected
        assert _counter(LOOKUP_NATIVE_FALLBACKS).count > fallbacks0
        assert _counter(LOOKUP_NATIVE_PROBES).count == native0
        monkeypatch.undo()
        fallbacks1 = _counter(LOOKUP_NATIVE_FALLBACKS).count
        assert q.lookup(keys) == expected      # healed: native again
        assert _counter(LOOKUP_NATIVE_FALLBACKS).count == fallbacks1


# -- concurrent serving through the native probe -----------------------------


@needs_probe
class TestConcurrentNativeServing:
    def test_lookups_under_live_commits_and_compaction(self, tmp_path):
        """Concurrent /lookup batches through the native probe while
        commits land and a full compaction rewrites the files: every
        batch is torn-free (all rows from ONE snapshot's state: the
        old name generation or the new, never a mix), zero fallbacks,
        and the compacted-away files' SSTs are dropped then rebuilt
        exactly once per new file."""
        from paimon_tpu.service import KvQueryClient, KvQueryServer
        t = _pk_table(str(tmp_path / "t"), buckets=2, extra_opts={
            "service.lookup.refresh-interval": "20"})
        n = 200
        _commit(t, [{"id": i, "name": f"g0-{i}"} for i in range(n)])
        server = KvQueryServer(t).start()
        fallbacks0 = _counter(LOOKUP_NATIVE_FALLBACKS).count
        stop = threading.Event()
        errors = []

        def reader(seed):
            rng = np.random.default_rng(seed)
            try:
                with KvQueryClient(t, tenant=f"t{seed}") as c:
                    while not stop.is_set():
                        ids = sorted(
                            int(k) for k in rng.integers(0, n, 8))
                        rows = c.lookup([{"id": i} for i in ids])
                        gens = set()
                        for i, row in zip(ids, rows):
                            assert row is not None, (i, "missing row")
                            gen, rest = row["name"].split("-", 1)
                            assert int(rest) == i, row
                            gens.add(gen)
                        # batch coherence: one generation per batch
                        assert len(gens) == 1, f"torn batch: {gens}"
            except Exception as e:      # noqa: BLE001
                errors.append(repr(e))

        threads = [threading.Thread(target=reader, args=(i,))
                   for i in range(4)]
        try:
            [x.start() for x in threads]
            for g in range(1, 4):
                time.sleep(0.15)
                _commit(t, [{"id": i, "name": f"g{g}-{i}"}
                            for i in range(n)])
            t.copy({"write-only": "false"}).compact(full=True)
            time.sleep(0.3)
            stop.set()
            [x.join() for x in threads]
            # post-compaction: the query's live SSTs reference only
            # files that still exist (dropped generations evicted,
            # rebuilt against the compacted files)
            q = server._query
            assert q is not None
            for key in q.store.keys():
                r = q.store.get(key)
                assert r is None or os.path.exists(r.path), key
        finally:
            stop.set()
            [x.join() for x in threads]
            server.stop()
        assert errors == []
        assert _counter(LOOKUP_NATIVE_FALLBACKS).count == fallbacks0


# -- warm boot ---------------------------------------------------------------


@needs_probe
class TestWarmBoot:
    def test_restore_serves_with_zero_reader_builds(self, tmp_path):
        """The r12 warm-boot proof: persist a warm query's state, then
        a FRESH query restores it and serves correct answers without
        building a single SST (reader_builds delta == 0)."""
        from paimon_tpu.core.plan_cache import reset_plan_caches
        from paimon_tpu.lookup import LocalTableQuery
        from paimon_tpu.service import warmboot
        t = _pk_table(str(tmp_path / "t"), buckets=2)
        _commit(t, [{"id": i, "name": f"n{i}"} for i in range(200)])
        q1 = LocalTableQuery(t, cache_dir=str(tmp_path / "c1"))
        keys = [{"id": i} for i in range(200)]
        expected = q1.lookup(keys)
        dest = str(tmp_path / "warm")
        meta = warmboot.persist_serving_state(q1, dest)
        assert meta["ssts"] >= 2 and meta["plan"]
        q1.close()
        reset_plan_caches()
        q2 = LocalTableQuery(t, cache_dir=str(tmp_path / "c2"))
        restored = warmboot.restore_serving_state(q2, dest)
        assert restored["ssts"] == meta["ssts"] and restored["plan"]
        builds0 = _counter(LOOKUP_READER_BUILDS).count
        assert q2.lookup(keys) == expected
        assert _counter(LOOKUP_READER_BUILDS).count == builds0, \
            "warm boot rebuilt SSTs it should have adopted"
        q2.close()

    def test_server_persists_on_shutdown_and_restores(self, tmp_path):
        """KvQueryServer wiring: with service.warmboot.enabled a
        server persists on shutdown and the next server (same SSD
        tier) boots from it — reader_builds frozen across the second
        server's first lookups."""
        from paimon_tpu.core.plan_cache import reset_plan_caches
        from paimon_tpu.service import KvQueryClient, KvQueryServer
        t = _pk_table(str(tmp_path / "t"), buckets=2, extra_opts={
            "cache.disk.dir": str(tmp_path / "ssd"),
            "service.warmboot.enabled": "true"})
        _commit(t, [{"id": i, "name": f"n{i}"} for i in range(100)])
        keys = [{"id": i} for i in range(100)]
        s1 = KvQueryServer(t)
        s1.server.start()
        with KvQueryClient(address=s1.address) as c:
            expected = c.lookup(keys)
        s1.shutdown()                       # persists the warm state
        reset_plan_caches()
        s2 = KvQueryServer(t)
        s2.server.start()
        try:
            builds0 = _counter(LOOKUP_READER_BUILDS).count
            with KvQueryClient(address=s2.address) as c:
                assert c.lookup(keys) == expected
            assert _counter(LOOKUP_READER_BUILDS).count == builds0
            assert s2.last_warm_restore["ssts"] >= 2
        finally:
            s2.shutdown()

    def test_missing_state_degrades_to_cold(self, tmp_path):
        from paimon_tpu.lookup import LocalTableQuery
        from paimon_tpu.service import warmboot
        t = _pk_table(str(tmp_path / "t"), buckets=1)
        _commit(t, [{"id": 1, "name": "a"}])
        q = LocalTableQuery(t, cache_dir=str(tmp_path / "c"))
        out = warmboot.restore_serving_state(
            q, str(tmp_path / "nowhere"))
        assert out == {"ssts": 0, "plan": False}
        assert q.lookup_row({"id": 1})["name"] == "a"


# -- remote replica registration ---------------------------------------------


class TestRouterRegistration:
    def _serving_table(self, tmp_path, interval="100 ms"):
        t = _pk_table(str(tmp_path / "t"), buckets=2, extra_opts={
            "service.replicas.health-interval": interval})
        _commit(t, [{"id": i, "name": f"n{i}"} for i in range(50)])
        return t

    def _get(self, address, path):
        with urllib.request.urlopen(address + path, timeout=10) as r:
            return json.loads(r.read())

    def _lookup_via(self, address, tenant, key):
        from paimon_tpu.service import KvQueryClient
        with KvQueryClient(address=address, tenant=tenant,
                           follow_topology=False) as c:
            row = c.lookup([{"id": key}])[0]
            return row, c.last_replica

    def test_register_joins_ring_and_serves(self, tmp_path):
        from paimon_tpu.service import KvQueryServer
        from paimon_tpu.service.router import ReplicaRouter
        t = self._serving_table(tmp_path)
        s0 = KvQueryServer(t, replica_id=0)
        s0.server.start()
        s1 = KvQueryServer(t, replica_id=1)
        s1.server.start()
        router = ReplicaRouter(servers=[s0]).start()
        try:
            out = s1.register_with_router(router.address)
            assert out == {"registered": 1, "replica_count": 2}
            top = self._get(router.address, "/topology")
            assert [e["id"] for e in top["replicas"]] == [0, 1]
            seen = set()
            for ten in range(16):
                row, rep = self._lookup_via(router.address,
                                            f"t{ten}", 3)
                assert row == {"id": 3, "name": "n3"}
                seen.add(rep)
            assert seen == {"0", "1"}, \
                "registered replica never served"
            # re-register with a new address wins (restart case)
            s1.register_with_router(router.address)
            assert len(self._get(router.address,
                                 "/topology")["replicas"]) == 2
        finally:
            router.stop()
            s1.shutdown()
            s0.shutdown()

    def test_health_loop_suspends_and_readmits(self, tmp_path):
        from paimon_tpu.service import KvQueryServer
        from paimon_tpu.service.router import ReplicaRouter, _UpstreamPool
        t = self._serving_table(tmp_path)
        s0 = KvQueryServer(t, replica_id=0)
        s0.server.start()
        s1 = KvQueryServer(t, replica_id=1)
        s1.server.start()
        router = ReplicaRouter(servers=[s0]).start()
        try:
            s1.register_with_router(router.address)

            def wait_for(pred, timeout=5.0):
                deadline = time.monotonic() + timeout
                while time.monotonic() < deadline:
                    if pred():
                        return True
                    time.sleep(0.02)
                return False

            # black-hole the replica's pool: two consecutive failed
            # probes must suspend it out of the ring
            pool = router._remote[1]
            real_request = _UpstreamPool.request

            def dead(self, *a, **k):
                if self is pool:
                    raise ConnectionError("injected outage")
                return real_request(self, *a, **k)

            _UpstreamPool.request = dead
            try:
                assert wait_for(lambda: self._get(
                    router.address, "/topology")["suspended"] == [1])
                h = self._get(router.address, "/healthz")
                assert h["status"] == "degraded"
                assert h["replicas"]["1"] == {"suspended": True}
                # every tenant still answered by the survivor
                for ten in range(12):
                    row, rep = self._lookup_via(router.address,
                                                f"t{ten}", 7)
                    assert row == {"id": 7, "name": "n7"}
                    assert rep == "0"
            finally:
                _UpstreamPool.request = real_request
            # first healthy probe re-admits
            assert wait_for(lambda: self._get(
                router.address, "/topology")["suspended"] == [])
            seen = {self._lookup_via(router.address, f"t{i}", 3)[1]
                    for i in range(16)}
            assert seen == {"0", "1"}
        finally:
            router.stop()
            s1.shutdown()
            s0.shutdown()

    def test_deregister_leaves_cleanly(self, tmp_path):
        from paimon_tpu.service import KvQueryServer
        from paimon_tpu.service.router import ReplicaRouter
        t = self._serving_table(tmp_path)
        s0 = KvQueryServer(t, replica_id=0)
        s0.server.start()
        s1 = KvQueryServer(t, replica_id=1)
        s1.server.start()
        router = ReplicaRouter(servers=[s0]).start()
        try:
            s1.register_with_router(router.address)
            req = urllib.request.Request(
                router.address + "/deregister",
                data=json.dumps({"id": 1}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as r:
                assert json.loads(r.read()) == {
                    "deregistered": 1, "replica_count": 1}
            for ten in range(12):
                row, rep = self._lookup_via(router.address,
                                            f"t{ten}", 3)
                assert row == {"id": 3, "name": "n3"}
                assert rep == "0"
            # unknown / in-process ids refused
            for rid, code in ((1, 404), (0, 404)):
                req = urllib.request.Request(
                    router.address + "/deregister",
                    data=json.dumps({"id": rid}).encode(),
                    headers={"Content-Type": "application/json"})
                with pytest.raises(urllib.error.HTTPError) as ei:
                    urllib.request.urlopen(req, timeout=10)
                assert ei.value.code == code
        finally:
            router.stop()
            s1.shutdown()
            s0.shutdown()
