"""Docs generator drift check (paimon-docs analog)."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_options_doc_up_to_date():
    """docs/options.md regenerates cleanly from paimon_tpu/options.py."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "docs",
                                      "generate_options.py"), "--check"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert proc.returncode == 0, proc.stderr
