"""Docs generator drift check (paimon-docs analog).

The tier-1 drift assertion now rides the analysis engine's
options-drift rule (one shared pass, structured findings); the
generator's own behaviors (CLI --check exit code, duplicate-key
detection) keep their direct tests.
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_options_doc_up_to_date(lint_report):
    """docs/options.md regenerates cleanly from paimon_tpu/options.py
    — the engine's options-drift rule, wrapped for tier-1."""
    offenders = lint_report.unsuppressed_by_rule("options-drift")
    assert offenders == [], [f.message for f in offenders]


def test_generate_options_check_exit_code():
    """The CLI contract external tooling uses: --check exits 0 when
    docs/options.md is current."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "docs",
                                      "generate_options.py"), "--check"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert proc.returncode == 0, proc.stderr


def test_no_duplicated_option_keys():
    """Every CoreOptions key is declared exactly once.  Duplicates with
    the same attribute name collapse in the class dict (the second
    silently wins), so this scans the source — the bug class behind the
    doubled `manifest.target-file-size` declaration."""
    import inspect

    sys.path.insert(0, REPO)
    from docs.generate_options import duplicate_option_keys
    from paimon_tpu.options import CoreOptions

    assert duplicate_option_keys(inspect.getsource(CoreOptions)) == []


def test_duplicate_option_key_detection():
    """The drift checker actually flags a duplicated key (and so
    generate_options.py --check exits non-zero on one)."""
    sys.path.insert(0, REPO)
    from docs.generate_options import duplicate_option_keys

    src = '''
    A = ConfigOption("some.key", str, "x", "")
    B = ConfigOption(
        "other.key", int, 1, "")
    A = ConfigOption("some.key", str, "y", "")
    '''
    assert duplicate_option_keys(src) == ["some.key"]
