"""Docs generator drift check (paimon-docs analog)."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_options_doc_up_to_date():
    """docs/options.md regenerates cleanly from paimon_tpu/options.py."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "docs",
                                      "generate_options.py"), "--check"],
        capture_output=True, text=True, cwd=REPO, timeout=120)
    assert proc.returncode == 0, proc.stderr


def test_no_duplicated_option_keys():
    """Every CoreOptions key is declared exactly once.  Duplicates with
    the same attribute name collapse in the class dict (the second
    silently wins), so this scans the source — the bug class behind the
    doubled `manifest.target-file-size` declaration."""
    import inspect

    sys.path.insert(0, REPO)
    from docs.generate_options import duplicate_option_keys
    from paimon_tpu.options import CoreOptions

    assert duplicate_option_keys(inspect.getsource(CoreOptions)) == []


def test_duplicate_option_key_detection():
    """The drift checker actually flags a duplicated key (and so
    generate_options.py --check exits non-zero on one)."""
    sys.path.insert(0, REPO)
    from docs.generate_options import duplicate_option_keys

    src = '''
    A = ConfigOption("some.key", str, "x", "")
    B = ConfigOption(
        "other.key", int, 1, "")
    A = ConfigOption("some.key", str, "y", "")
    '''
    assert duplicate_option_keys(src) == ["some.key"]
