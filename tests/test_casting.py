"""CastExecutor rule matrix coverage.

reference: paimon-common casting/CastExecutors.java + rule classes;
Java semantics (narrowing truncation, float saturation, token booleans,
trimmed parses) asserted per rule.
"""

import datetime

import pyarrow as pa
import pytest

from paimon_tpu.data.casting import CastError, can_cast, cast_array
from paimon_tpu.types import (
    ArrayType, BigIntType, BinaryType, BooleanType, CharType, DateType,
    DecimalType, DoubleType, FloatType, IntType, LocalZonedTimestampType,
    MapType, SmallIntType, TimeType, TimestampType, TinyIntType,
    VarBinaryType, VarCharType,
)

S = VarCharType.string_type()


def cast(vals, src, dst, arrow_src=None):
    from paimon_tpu.types import data_type_to_arrow
    arr = pa.array(vals, arrow_src or data_type_to_arrow(src))
    return cast_array(arr, src, dst).to_pylist()


# -- numeric -----------------------------------------------------------------

def test_int_widen():
    assert cast([1, -2, None], TinyIntType(), BigIntType()) == \
        [1, -2, None]


def test_int_narrow_truncates_twos_complement():
    # Java (byte)(int) semantics
    assert cast([300, -300, 127, None], IntType(), TinyIntType()) == \
        [44, -44, 127, None]
    assert cast([1 << 40], BigIntType(), IntType()) == [0]


def test_float_to_int_truncates_and_saturates():
    assert cast([3.9, -3.9, None], DoubleType(), IntType()) == \
        [3, -3, None]
    assert cast([1e12, -1e12], DoubleType(), IntType()) == \
        [2147483647, -2147483648]
    assert cast([float("nan")], DoubleType(), IntType()) == [0]
    # JLS: (byte)300.0f == (byte)(int)300.0f == 44, not a saturated 127
    assert cast([300.0, 1e12], DoubleType(), TinyIntType()) == [44, -1]


def test_decimal_to_int_exact_above_2_53():
    import decimal
    d = DecimalType(38, 0)
    big = 9007199254740993            # 2^53 + 1: float64 cannot hold it
    out = cast([decimal.Decimal(big)], d, BigIntType())
    assert out == [big]
    out = cast([decimal.Decimal("5.99"), decimal.Decimal("-5.99")],
               DecimalType(10, 2), IntType())
    assert out == [5, -5]             # truncation toward zero


def test_int_to_float():
    assert cast([2, None], IntType(), DoubleType()) == [2.0, None]


def test_numeric_to_boolean_and_back():
    assert cast([0, 2, None], IntType(), BooleanType()) == \
        [False, True, None]
    assert cast([True, False, None], BooleanType(), IntType()) == \
        [1, 0, None]


def test_decimal_rules():
    d = DecimalType(10, 2)
    assert cast([1, None], IntType(), d) == \
        [__import__("decimal").Decimal("1.00"), None]
    out = cast(["3.14", "  2.50 "], S, d)
    assert [str(v) for v in out] == ["3.14", "2.50"]
    assert cast(out, d, IntType()) == [3, 2]
    assert cast(out, d, DoubleType()) == [3.14, 2.5]
    wider = cast(out, d, DecimalType(12, 4))
    assert str(wider[0]) == "3.1400"


# -- strings -----------------------------------------------------------------

def test_string_to_numeric_trims_and_raises():
    assert cast([" 42 ", None], S, IntType()) == [42, None]
    assert cast(["1.5"], S, DoubleType()) == [1.5]
    with pytest.raises(CastError):
        cast(["abc"], S, IntType())
    with pytest.raises(CastError):
        cast([str(1 << 40)], S, IntType())   # range-checked like Java


def test_string_to_boolean_token_set():
    assert cast(["true", "F", " YES ", "0", None], S, BooleanType()) == \
        [True, False, True, False, None]
    with pytest.raises(CastError):
        cast(["maybe"], S, BooleanType())


def test_string_temporal_parses():
    assert cast(["2024-03-01", None], S, DateType()) == \
        [datetime.date(2024, 3, 1), None]
    out = cast(["12:34:56"], S, TimeType())
    assert out == [datetime.time(12, 34, 56)]
    out = cast(["2024-03-01 10:20:30"], S, TimestampType(3))
    assert out == [datetime.datetime(2024, 3, 1, 10, 20, 30)]
    with pytest.raises(CastError):
        cast(["not a date"], S, DateType())


def test_char_varchar_length_semantics():
    assert cast(["abcdef", "ab", None], S, VarCharType(3)) == \
        ["abc", "ab", None]
    assert cast(["abcdef", "ab"], S, CharType(4)) == ["abcd", "ab  "]


def test_string_binary_round_trip():
    assert cast(["hi", None], S, VarBinaryType.bytes_type()) == \
        [b"hi", None]
    assert cast([b"hi", None], VarBinaryType.bytes_type(), S) == \
        ["hi", None]
    assert cast([b"abc"], VarBinaryType.bytes_type(),
                BinaryType(5)) == [b"abc\x00\x00"]


# -- to-string ---------------------------------------------------------------

def test_everything_to_string():
    assert cast([True, False, None], BooleanType(), S) == \
        ["true", "false", None]
    assert cast([42], IntType(), S) == ["42"]
    assert cast([datetime.date(2024, 1, 2)], DateType(), S) == \
        ["2024-01-02"]
    out = cast([[1, 2], None], ArrayType(IntType()), S)
    assert out == ["[1,2]", None]


# -- temporal conversions ----------------------------------------------------

def test_date_timestamp_conversions():
    ts = cast([datetime.date(2024, 1, 2)], DateType(), TimestampType(3))
    assert ts == [datetime.datetime(2024, 1, 2, 0, 0)]
    d = cast(ts, TimestampType(3), DateType())
    assert d == [datetime.date(2024, 1, 2)]
    t = cast([datetime.datetime(2024, 1, 2, 3, 4, 5)],
             TimestampType(3), TimeType())
    assert t == [datetime.time(3, 4, 5)]


def test_numeric_to_timestamp_epoch_seconds():
    out = cast([86400], BigIntType(), TimestampType(3))
    assert out == [datetime.datetime(1970, 1, 2)]


# -- rule coverage table -----------------------------------------------------

def test_rule_coverage_matrix():
    """Every (src, dst) family pair the reference CastExecutors resolves
    must resolve here too."""
    pairs = [
        (TinyIntType(), BigIntType()), (BigIntType(), TinyIntType()),
        (IntType(), DoubleType()), (DoubleType(), IntType()),
        (FloatType(), DoubleType()), (DoubleType(), FloatType()),
        (IntType(), BooleanType()), (BooleanType(), IntType()),
        (IntType(), DecimalType(10, 2)), (DecimalType(10, 2), IntType()),
        (DecimalType(10, 2), DecimalType(12, 4)),
        (DecimalType(10, 2), DoubleType()),
        (S, IntType()), (S, DoubleType()), (S, BooleanType()),
        (S, DecimalType(10, 2)), (S, DateType()), (S, TimeType()),
        (S, TimestampType(3)), (S, VarBinaryType.bytes_type()),
        (S, CharType(3)), (CharType(3), S),
        (IntType(), S), (DoubleType(), S), (BooleanType(), S),
        (DateType(), S), (TimestampType(3), S),
        (DecimalType(10, 2), S),
        (ArrayType(IntType()), S), (MapType(S, IntType()), S),
        (VarBinaryType.bytes_type(), S),
        (VarBinaryType.bytes_type(), BinaryType(4)),
        (DateType(), TimestampType(3)),
        (TimestampType(3), DateType()), (TimestampType(3), TimeType()),
        (TimestampType(3), LocalZonedTimestampType(3)),
        (BigIntType(), TimestampType(3)),
        (SmallIntType(), IntType()),
    ]
    missing = [(str(s), str(d)) for s, d in pairs if not can_cast(s, d)]
    assert not missing, missing


def test_unsupported_pairs_refuse():
    assert not can_cast(DateType(), IntType())
    with pytest.raises(CastError):
        cast_array(pa.array([1], pa.int32()), DateType(), IntType())


def test_double_to_bigint_saturates_not_wraps():
    out = cast([1e19, -1e19, float(2**63)], DoubleType(), BigIntType())
    assert out == [2**63 - 1, -(2**63), 2**63 - 1]


def test_float_to_string_java_rendering():
    assert cast([1.0, 2.5, None], DoubleType(), S) == \
        ["1.0", "2.5", None]


def test_string_to_time_rounds_millis():
    out = cast(["0:05:00.570"], S, TimeType())
    assert out == [datetime.time(0, 5, 0, 570000)]
