"""ANN vector search (MXU matmul top-k) vs exact numpy oracle."""

import os

import numpy as np
import pyarrow as pa
import pytest

from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import ArrayType, BigIntType, FloatType
from paimon_tpu.vector import BruteForceIndex, IVFFlatIndex, vector_search


def _exact_topk(vectors, q, k, metric):
    if metric == "cosine":
        sims = (vectors @ q) / (np.linalg.norm(vectors, axis=1)
                                * np.linalg.norm(q) + 1e-12)
    elif metric == "dot":
        sims = vectors @ q
    else:
        sims = -np.sum((vectors - q) ** 2, axis=1)
    return np.argsort(-sims)[:k]


@pytest.mark.parametrize("metric", ["cosine", "dot", "l2"])
def test_brute_force_matches_exact(metric):
    rng = np.random.default_rng(0)
    vectors = rng.standard_normal((500, 32)).astype(np.float32)
    q = rng.standard_normal(32).astype(np.float32)
    idx = BruteForceIndex(vectors, metric)
    _, got = idx.search(q, 10)
    expect = _exact_topk(vectors, q, 10, metric)
    assert set(got[0].tolist()) == set(expect.tolist())


def test_brute_force_batch_queries():
    rng = np.random.default_rng(1)
    vectors = rng.standard_normal((300, 16)).astype(np.float32)
    qs = rng.standard_normal((5, 16)).astype(np.float32)
    scores, ids = BruteForceIndex(vectors, "cosine").search(qs, 3)
    assert scores.shape == (5, 3) and ids.shape == (5, 3)
    for qi in range(5):
        assert ids[qi, 0] == _exact_topk(vectors, qs[qi], 1, "cosine")[0]


def test_ivf_flat_recall():
    rng = np.random.default_rng(2)
    vectors = rng.standard_normal((2000, 24)).astype(np.float32)
    queries = rng.standard_normal((20, 24)).astype(np.float32)
    idx = IVFFlatIndex(vectors, n_clusters=16, metric="cosine")
    hits = 0
    for q in queries:
        _, got = idx.search(q, 10, nprobe=6)
        expect = _exact_topk(vectors, q, 10, "cosine")
        hits += len(set(got[0].tolist()) & set(expect.tolist()))
    recall = hits / (len(queries) * 10)
    assert recall > 0.7, recall


def test_table_vector_search(tmp_warehouse):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("emb", ArrayType(FloatType()))
              .primary_key("id")
              .options({"bucket": "1"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "t"), schema)
    rng = np.random.default_rng(3)
    embs = rng.standard_normal((50, 8)).astype(np.float32)
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts([{"id": i, "emb": embs[i].tolist()} for i in range(50)])
    wb.new_commit().commit(w.prepare_commit())
    w.close()

    out = vector_search(table, "emb", embs[7], k=3)
    assert out.num_rows == 3
    assert out.column("id").to_pylist()[0] == 7     # itself first
    assert "_score" in out.column_names


def test_full_text_search(tmp_warehouse):
    from paimon_tpu.index.fulltext import FullTextIndex, full_text_search
    from paimon_tpu.types import VarCharType

    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("body", VarCharType())
              .primary_key("id")
              .options({"bucket": "1"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "ft"),
                                  schema)
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts([
        {"id": 1, "body": "the quick brown fox jumps over the lazy dog"},
        {"id": 2, "body": "a fast auburn fox"},
        {"id": 3, "body": "completely unrelated text about databases"},
        {"id": 4, "body": None},
    ])
    wb.new_commit().commit(w.prepare_commit())

    out = full_text_search(table, "body", "brown fox", k=3)
    ids = out.column("id").to_pylist()
    assert ids[0] == 1                     # both terms match
    assert set(ids) == {1, 2}              # doc 3/4 never match
    assert full_text_search(table, "body", "zebra").num_rows == 0

    idx = FullTextIndex(["alpha beta", "beta beta gamma"])
    rows, scores = idx.search("beta")
    assert rows.tolist()[0] == 1           # higher tf ranks first
