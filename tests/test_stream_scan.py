"""Streaming plane: startup modes, follow-up scanners, row-kind
preservation, consumer progress, exactly-once stream commits.

reference semantics: table/source/DataTableStreamScan.java,
source/snapshot/DeltaFollowUpScanner.java, ChangelogFollowUpScanner.java.
"""

import os

import pytest

from paimon_tpu.core.read import ROW_KIND_COL
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, RowKind


def _make_table(tmp_warehouse, opts=None):
    options = {"bucket": "1", "write-only": "true"}
    options.update(opts or {})
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options(options)
              .build())
    return FileStoreTable.create(os.path.join(tmp_warehouse, "t"), schema)


def _commit(table, rows, kinds=None):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows, row_kinds=kinds)
    sid = wb.new_commit().commit(w.prepare_commit())
    w.close()
    return sid


def _read_plan(table, plan):
    rb = table.new_read_builder()
    return rb.new_read().to_arrow(plan)


def test_latest_full_then_deltas(tmp_warehouse):
    table = _make_table(tmp_warehouse)
    _commit(table, [{"id": 1, "v": 1.0}, {"id": 2, "v": 2.0}])
    _commit(table, [{"id": 2, "v": 22.0}])

    scan = table.new_read_builder().new_stream_scan()
    first = scan.plan()
    rows = sorted(_read_plan(table, first).to_pylist(),
                  key=lambda r: r["id"])
    assert all(r.pop(ROW_KIND_COL) == RowKind.INSERT for r in rows)
    assert rows == [{"id": 1, "v": 1.0}, {"id": 2, "v": 22.0}]
    assert scan.plan() is None              # caught up

    _commit(table, [{"id": 3, "v": 3.0}])
    nxt = scan.plan()
    out = _read_plan(table, nxt).to_pylist()
    assert {r["id"] for r in out} == {3}
    assert all(r[ROW_KIND_COL] == RowKind.INSERT for r in out)
    assert scan.plan() is None


def test_delta_follow_up_preserves_row_kinds(tmp_warehouse):
    table = _make_table(tmp_warehouse)
    _commit(table, [{"id": 1, "v": 1.0}])

    scan = table.new_read_builder().new_stream_scan()
    scan.plan()                             # initial full

    _commit(table, [{"id": 1, "v": 0.0}], kinds=[RowKind.DELETE])
    out = _read_plan(table, scan.plan()).to_pylist()
    assert len(out) == 1
    assert out[0][ROW_KIND_COL] == RowKind.DELETE   # -D survives


def test_delta_follow_up_skips_compact_snapshots(tmp_warehouse):
    table = _make_table(tmp_warehouse)
    _commit(table, [{"id": 1, "v": 1.0}])
    scan = table.new_read_builder().new_stream_scan()
    scan.plan()
    _commit(table, [{"id": 1, "v": 2.0}])
    table.compact(full=True)                # COMPACT snapshot
    plans = []
    while True:
        p = scan.plan()
        if p is None:
            break
        plans.append(p)
    rows = [r for p in plans for r in _read_plan(table, p).to_pylist()]
    # only the delta of the APPEND commit; compaction rewrite is not new
    assert [r["v"] for r in rows] == [2.0]


def test_startup_latest_sees_only_new(tmp_warehouse):
    table = _make_table(tmp_warehouse)
    _commit(table, [{"id": 1, "v": 1.0}])
    scan = table.copy({"scan.mode": "latest"}) \
        .new_read_builder().new_stream_scan()
    first = scan.plan()
    assert first.splits == []
    _commit(table, [{"id": 2, "v": 2.0}])
    out = _read_plan(table, scan.plan()).to_pylist()
    assert {r["id"] for r in out} == {2}


def test_startup_from_snapshot(tmp_warehouse):
    table = _make_table(tmp_warehouse)
    _commit(table, [{"id": 1, "v": 1.0}])   # snapshot 1
    _commit(table, [{"id": 2, "v": 2.0}])   # snapshot 2
    _commit(table, [{"id": 3, "v": 3.0}])   # snapshot 3
    scan = table.copy({"scan.mode": "from-snapshot",
                       "scan.snapshot-id": "2"}) \
        .new_read_builder().new_stream_scan()
    assert scan.plan().splits == []         # no initial full scan
    ids = []
    while True:
        p = scan.plan()
        if p is None:
            break
        ids.extend(r["id"] for r in _read_plan(table, p).to_pylist())
    assert ids == [2, 3]


def test_startup_from_snapshot_full(tmp_warehouse):
    table = _make_table(tmp_warehouse)
    _commit(table, [{"id": 1, "v": 1.0}])
    _commit(table, [{"id": 1, "v": 9.0}])   # snapshot 2
    _commit(table, [{"id": 3, "v": 3.0}])   # snapshot 3
    scan = table.copy({"scan.mode": "from-snapshot-full",
                       "scan.snapshot-id": "2"}) \
        .new_read_builder().new_stream_scan()
    first = _read_plan(table, scan.plan()).to_pylist()
    assert sorted(r["v"] for r in first) == [9.0]    # merged state @2
    nxt = _read_plan(table, scan.plan()).to_pylist()
    assert [r["id"] for r in nxt] == [3]


def test_startup_from_timestamp(tmp_warehouse):
    table = _make_table(tmp_warehouse)
    _commit(table, [{"id": 1, "v": 1.0}])
    snap1 = table.snapshot_manager.snapshot(1)
    _commit(table, [{"id": 2, "v": 2.0}])
    scan = table.copy({"scan.mode": "from-timestamp",
                       "scan.timestamp-millis":
                           str(snap1.time_millis)}) \
        .new_read_builder().new_stream_scan()
    assert scan.plan().splits == []
    ids = []
    while True:
        p = scan.plan()
        if p is None:
            break
        ids.extend(r["id"] for r in _read_plan(table, p).to_pylist())
    assert ids == [2]


def test_changelog_producer_input_follow_up(tmp_warehouse):
    table = _make_table(tmp_warehouse,
                        {"changelog-producer": "input"})
    _commit(table, [{"id": 1, "v": 1.0}])
    scan = table.new_read_builder().new_stream_scan()
    scan.plan()
    _commit(table, [{"id": 1, "v": 2.0}])
    _commit(table, [{"id": 1, "v": 0.0}], kinds=[RowKind.DELETE])
    rows = []
    while True:
        p = scan.plan()
        if p is None:
            break
        rows.extend(_read_plan(table, p).to_pylist())
    assert [(r["v"], r[ROW_KIND_COL]) for r in rows] == \
        [(2.0, RowKind.INSERT), (0.0, RowKind.DELETE)]


def test_consumer_progress_and_resume(tmp_warehouse):
    table = _make_table(tmp_warehouse)
    _commit(table, [{"id": 1, "v": 1.0}])
    t2 = table.copy({"consumer-id": "job-a"})
    scan = t2.new_read_builder().new_stream_scan()
    scan.plan()
    # progress is only persisted once the caller confirms processing
    assert table.consumer_manager.consumer("job-a") is None
    scan.notify_checkpoint_complete(scan.checkpoint())
    assert table.consumer_manager.consumer("job-a") == 2

    _commit(table, [{"id": 2, "v": 2.0}])
    # a NEW scan with the same consumer-id resumes from the recorded
    # progress: no initial full scan, only the un-consumed delta
    scan2 = t2.new_read_builder().new_stream_scan()
    out = _read_plan(table, scan2.plan()).to_pylist()
    assert {r["id"] for r in out} == {2}


def test_checkpoint_restore(tmp_warehouse):
    table = _make_table(tmp_warehouse)
    _commit(table, [{"id": 1, "v": 1.0}])
    scan = table.new_read_builder().new_stream_scan()
    scan.plan()
    cp = scan.checkpoint()
    _commit(table, [{"id": 2, "v": 2.0}])
    # simulate failover: new scan restored at the checkpoint
    scan2 = table.new_read_builder().new_stream_scan()
    scan2.restore(cp)
    out = _read_plan(table, scan2.plan()).to_pylist()
    assert {r["id"] for r in out} == {2}


def test_stream_write_exactly_once(tmp_warehouse):
    table = _make_table(tmp_warehouse)
    wb = table.new_stream_write_builder().with_commit_user("job-1")
    w = wb.new_write()
    c = wb.new_commit()
    w.write_dicts([{"id": 1, "v": 1.0}])
    msgs = w.prepare_commit()
    c.commit(msgs, commit_identifier=7)

    # recovery replays checkpoint 7: filter_committed drops it
    wb2 = table.new_stream_write_builder().with_commit_user("job-1")
    c2 = wb2.new_commit()
    assert c2.filter_committed([7, 8]) == [8]


def test_compacted_full_does_not_skip_later_appends(tmp_warehouse):
    table = _make_table(tmp_warehouse)
    _commit(table, [{"id": 1, "v": 1.0}])   # snapshot 1 APPEND
    table.compact(full=True)                # snapshot 2 COMPACT
    _commit(table, [{"id": 2, "v": 2.0}])   # snapshot 3 APPEND
    scan = table.copy({"scan.mode": "compacted-full"}) \
        .new_read_builder().new_stream_scan()
    first = _read_plan(table, scan.plan()).to_pylist()
    assert {r["id"] for r in first} == {1}
    rest = []
    while True:
        p = scan.plan()
        if p is None:
            break
        rest.extend(_read_plan(table, p).to_pylist())
    assert {r["id"] for r in rest} == {2}   # snapshot 3 not skipped


def test_empty_streaming_poll_has_stable_schema(tmp_warehouse):
    import pyarrow as pa
    from paimon_tpu import predicate as P

    table = _make_table(tmp_warehouse)
    _commit(table, [{"id": 1, "v": 1.0}])
    rb = (table.new_read_builder()
          .with_filter(P.equal("id", 999)))
    scan = rb.new_stream_scan()
    scan.plan()
    _commit(table, [{"id": 2, "v": 2.0}])
    p = scan.plan()
    t = rb.new_read().to_arrow(p)
    assert t.num_rows == 0
    assert ROW_KIND_COL in t.column_names   # schema stable across polls


def test_incremental_between_batch_scan(tmp_warehouse):
    table = _make_table(tmp_warehouse)
    _commit(table, [{"id": 1, "v": 1.0}])            # snapshot 1
    _commit(table, [{"id": 2, "v": 2.0}])            # snapshot 2
    table.create_tag("t2", 2)
    _commit(table, [{"id": 3, "v": 3.0}])            # snapshot 3
    t = table.copy({"incremental-between": "1,3"})
    out = t.to_arrow()
    assert sorted(out.column("id").to_pylist()) == [2, 3]
    # tag names resolve too
    t2 = table.copy({"incremental-between": "t2,3"})
    assert t2.to_arrow().column("id").to_pylist() == [3]


def test_incremental_between_merges_across_snapshots(tmp_warehouse):
    """A key updated twice in the range emits ONCE with the final value
    (reference IncrementalStartingScanner groups per bucket)."""
    table = _make_table(tmp_warehouse)
    _commit(table, [{"id": 1, "v": 1.0}])            # snapshot 1
    _commit(table, [{"id": 1, "v": 2.0}])            # snapshot 2
    _commit(table, [{"id": 1, "v": 3.0}])            # snapshot 3
    t = table.copy({"incremental-between": "0,3"})
    out = t.to_arrow().to_pylist()
    assert out == [{"id": 1, "v": 3.0}]

    with pytest.raises(ValueError):
        table.copy({"incremental-between": "0,99"}).to_arrow()
