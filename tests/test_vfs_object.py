"""ObjectTable + VFS view over a warehouse."""

import os

import pytest

import paimon_tpu
from paimon_tpu.schema import Schema
from paimon_tpu.table.object_table import ObjectTable
from paimon_tpu.types import BigIntType
from paimon_tpu.vfs import Vfs


def test_object_table(tmp_path):
    ot = ObjectTable(str(tmp_path / "objs"))
    ot.put("images/a.png", b"PNG1")
    ot.put("images/b.png", b"PNG22")
    ot.put("readme.txt", b"hello")
    t = ot.to_arrow()
    assert t.num_rows == 3
    rows = {r["path"]: r for r in t.to_pylist()}
    assert rows["images/a.png"]["length"] == 4
    assert rows["readme.txt"]["name"] == "readme.txt"
    assert ot.read("images/b.png") == b"PNG22"
    ot.delete("readme.txt")
    assert ot.refresh() == 2


def test_vfs_browses_warehouse(tmp_path):
    cat = paimon_tpu.create_catalog({"warehouse": str(tmp_path / "wh")})
    cat.create_database("db")
    t = cat.create_table("db.t", Schema.builder()
                         .column("id", BigIntType(False))
                         .primary_key("id").options({"bucket": "1"})
                         .build())
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts([{"id": 1}])
    wb.new_commit().commit(w.prepare_commit())

    vfs = Vfs(cat)
    assert [s.path for s in vfs.listdir("/")] == ["/db"]
    assert [s.path for s in vfs.listdir("/db")] == ["/db/t"]
    entries = {s.path.rsplit("/", 1)[-1] for s in vfs.listdir("/db/t")}
    assert {"snapshot", "schema", "manifest"} <= entries
    snap = vfs.open("/db/t/snapshot/snapshot-1")
    assert b'"commitKind"' in snap
    assert vfs.exists("/db/t/snapshot/LATEST")
    assert not vfs.exists("/db/nope")
    assert vfs.size("/db/t/snapshot/LATEST") > 0


def test_path_traversal_rejected(tmp_path):
    cat = paimon_tpu.create_catalog({"warehouse": str(tmp_path / "wh2")})
    cat.create_database("db")
    cat.create_table("db.t", Schema.builder()
                     .column("id", BigIntType(False))
                     .primary_key("id").options({"bucket": "1"}).build())
    vfs = Vfs(cat)
    with pytest.raises(ValueError):
        vfs.open("/db/t/../../../etc/passwd")
    ot = ObjectTable(str(tmp_path / "objs2"))
    with pytest.raises(ValueError):
        ot.put("../evil", b"x")
    with pytest.raises(ValueError):
        ot.read("../../etc/passwd")
    with pytest.raises(IsADirectoryError):
        vfs.size("/db/t")


def test_vfs_over_rest_catalog(tmp_path):
    from paimon_tpu.catalog.rest import RESTCatalogServer

    backing = paimon_tpu.create_catalog(
        {"warehouse": str(tmp_path / "wh3")})
    backing.create_database("db")
    backing.create_table("db.t", Schema.builder()
                         .column("id", BigIntType(False))
                         .primary_key("id").options({"bucket": "1"})
                         .build())
    server = RESTCatalogServer(backing).start()
    try:
        rest = paimon_tpu.create_catalog(
            {"metastore": "rest", "uri": server.uri})
        vfs = Vfs(rest)
        names = {s.path.rsplit("/", 1)[-1]
                 for s in vfs.listdir("/db/t")}
        assert "schema" in names
    finally:
        server.stop()
