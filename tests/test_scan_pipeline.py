"""Pipelined merge-on-read scan (parallel/scan_pipeline.py).

Row-identity of the pipelined executor against the serial path across
every merge engine, deletion vectors, schema evolution, projections and
streaming reads; transient-fault retry semantics (503 storms retry and
complete, exhausted storms RAISE instead of riding the corrupt-file
skip); executor-thread hygiene + the prefetch byte budget (tier-1);
footer/range cache behavior; the injectable expire clock.
"""

import collections
import os
import threading
import time

import pytest

from paimon_tpu import predicate as P
from paimon_tpu.fs import get_file_io
from paimon_tpu.fs.object_store import TransientStoreError
from paimon_tpu.schema import Schema, SchemaChange, SchemaManager
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, IntType
from tests.store_oracle import make_random_engine_table

ENGINES = ["deduplicate", "first-row", "partial-update", "aggregation"]


def _rows(table, **dyn):
    t = table.copy(dyn) if dyn else table
    return sorted(t.to_arrow().to_pylist(),
                  key=lambda r: (r["pt"], r["id"]))


def _scan_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith("paimon-scan")]


def _wait_no_scan_threads(timeout=5.0):
    deadline = time.monotonic() + timeout
    while _scan_threads() and time.monotonic() < deadline:
        time.sleep(0.01)
    return _scan_threads()


class StormFileIO:
    """Duck-typed FileIO: every data file's read_bytes 503s `per_path`
    times before succeeding (a passing transient storm)."""

    def __init__(self, inner, per_path=2):
        self.inner = inner
        self.per_path = per_path
        self.counts = collections.Counter()
        self.lock = threading.Lock()
        self.faults = 0

    def read_bytes(self, path):
        if path.rsplit("/", 1)[-1].startswith("data-"):
            with self.lock:
                if self.counts[path] < self.per_path:
                    self.counts[path] += 1
                    self.faults += 1
                    raise TransientStoreError(f"503 on {path}")
        return self.inner.read_bytes(path)

    def __getattr__(self, name):
        return getattr(self.inner, name)


# -- row identity ------------------------------------------------------------

@pytest.mark.parametrize("engine", ENGINES)
def test_pipelined_equals_serial_all_engines(tmp_path, engine):
    table = make_random_engine_table(
        str(tmp_path / engine), seed=77, engine=engine)
    serial = _rows(table, **{"scan.split.parallelism": "1"})
    piped = _rows(table, **{"scan.split.parallelism": "4",
                            "read.prefetch.splits": "3"})
    assert piped == serial and len(serial) > 0


def test_pipelined_equals_serial_projection_and_predicate(tmp_path):
    table = make_random_engine_table(str(tmp_path / "t"), seed=5,
                                     engine="deduplicate")

    def read(par):
        rb = table.copy({"scan.split.parallelism": par}) \
            .new_read_builder() \
            .with_projection(["pt", "id", "name"]) \
            .with_filter(P.greater_than("id", 30))
        t = rb.new_read().to_arrow(rb.new_scan().plan())
        assert t.column_names == ["pt", "id", "name"]
        return sorted(t.to_pylist(), key=lambda r: (r["pt"], r["id"]))

    assert read("4") == read("1")


def test_pipelined_equals_serial_schema_evolution(tmp_path):
    table = make_random_engine_table(str(tmp_path / "t"), seed=9,
                                     engine="deduplicate", commits=2)
    sm = SchemaManager(table.file_io, table.path)
    sm.commit_changes(SchemaChange.add_column("extra", IntType()))
    table = FileStoreTable.load(table.path, table.file_io)
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts([{"pt": 0, "id": i, "v1": i, "v2": 1.0,
                    "name": "n", "extra": i * 2} for i in range(40)])
    wb.new_commit().commit(w.prepare_commit())
    w.close()
    serial = _rows(table, **{"scan.split.parallelism": "1"})
    piped = _rows(table, **{"scan.split.parallelism": "4"})
    assert piped == serial
    assert any(r["extra"] is not None for r in serial)
    assert any(r["extra"] is None for r in serial)   # evolved old files


def test_pipelined_equals_serial_deletion_vectors(tmp_path):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .options({"bucket": "-1"})
              .build())
    table = FileStoreTable.create(str(tmp_path / "t"), schema)
    for c in range(4):
        wb = table.new_batch_write_builder()
        w = wb.new_write()
        w.write_dicts([{"id": c * 100 + i, "v": float(i)}
                       for i in range(50)])
        wb.new_commit().commit(w.prepare_commit())
        w.close()
    assert table.delete_where(P.less_than("id", 120)) is not None
    serial = table.copy({"scan.split.parallelism": "1"}).to_arrow()
    piped = table.copy({"scan.split.parallelism": "4"}).to_arrow()
    assert piped.sort_by("id").equals(serial.sort_by("id"))
    assert serial.num_rows == 200 - 70   # 50 + 20 rows DV-deleted


def test_pipelined_equals_serial_streaming(tmp_path):
    table = make_random_engine_table(str(tmp_path / "t"), seed=21,
                                     engine="deduplicate", commits=4)
    serial_rb = table.copy({"scan.split.parallelism": "1",
                            "scan.mode": "from-snapshot-full",
                            "scan.snapshot-id": "1"}).new_read_builder()
    piped_rb = table.copy({"scan.split.parallelism": "4",
                           "scan.mode": "from-snapshot-full",
                           "scan.snapshot-id": "1"}).new_read_builder()
    scan = serial_rb.new_stream_scan()
    plans = 0
    while True:
        plan = scan.plan()
        if plan is None:
            break
        plans += 1
        a = serial_rb.new_read().to_arrow(plan)
        b = piped_rb.new_read().to_arrow(plan)
        assert "_ROW_KIND" in a.column_names
        assert b.sort_by([("pt", "ascending"), ("id", "ascending")]) \
            .equals(a.sort_by([("pt", "ascending"), ("id", "ascending")]))
    assert plans >= 2


def test_iter_splits_unordered_covers_all_splits(tmp_path):
    table = make_random_engine_table(str(tmp_path / "t"), seed=31,
                                     engine="deduplicate")
    rb = table.copy({"scan.split.parallelism": "4"}).new_read_builder()
    plan = rb.new_scan().plan()
    seen = sorted(i for i, _, _ in
                  rb.new_read().iter_splits(plan, ordered=False))
    assert seen == list(range(len(plan.splits)))


def test_limit_early_exit(tmp_path):
    table = make_random_engine_table(str(tmp_path / "t"), seed=41,
                                     engine="deduplicate")
    full = table.to_arrow()
    limited = table.to_arrow(limit=7)
    assert limited.num_rows == 7
    assert limited.column_names == full.column_names


# -- fault semantics ---------------------------------------------------------

def test_mid_scan_503_storm_retries_and_completes(tmp_path):
    table = make_random_engine_table(str(tmp_path / "t"), seed=3,
                                     engine="deduplicate")
    expect = _rows(table)
    storm = StormFileIO(get_file_io(table.path), per_path=2)
    stormy = FileStoreTable.load(
        table.path, file_io=storm,
        dynamic_options={"read.retry.backoff": "0",
                         "scan.split.parallelism": "4"})
    assert _rows(stormy) == expect
    assert storm.faults > 0


def test_exhausted_transient_storm_raises_not_skipped(tmp_path):
    """A transient fault that outlives read.retry.max-attempts must
    RAISE even under scan.ignore-corrupt-files — mislabeling a 503 as
    corruption would silently drop rows."""
    table = make_random_engine_table(str(tmp_path / "t"), seed=3,
                                     engine="deduplicate")
    storm = StormFileIO(get_file_io(table.path), per_path=10 ** 9)
    stormy = FileStoreTable.load(
        table.path, file_io=storm,
        dynamic_options={"read.retry.backoff": "0",
                         "read.retry.max-attempts": "2",
                         "scan.ignore-corrupt-files": "true",
                         "scan.split.parallelism": "4"})
    with pytest.raises(TransientStoreError):
        stormy.to_arrow()
    assert not _wait_no_scan_threads(), "leaked scan threads after raise"


def test_decode_errors_are_not_transient():
    """Modern pyarrow raises plain OSError for corrupt compressed
    pages; the format readers re-tag decode-phase failures as
    CorruptDataError so the taxonomy keeps them in the corrupt-file
    class (skippable), never the retry class."""
    import pyarrow as pa

    from paimon_tpu.format.format import CorruptDataError
    from paimon_tpu.parallel.fault import is_transient_error
    assert not is_transient_error(CorruptDataError("corrupt page"))
    assert not is_transient_error(pa.ArrowInvalid("bad magic"))
    assert is_transient_error(OSError("io fault"))
    assert is_transient_error(TransientStoreError("503"))


def test_corrupt_page_with_valid_footer_skipped_when_opted_in(tmp_path):
    """The OSError corruption flavor end-to-end: valid parquet footer,
    garbled page bytes (zstd decode fails deterministically) — must
    take the ignore-corrupt-files skip, not the transient retry."""
    table = make_random_engine_table(str(tmp_path / "t"), seed=17,
                                     engine="deduplicate", buckets=1)
    split = table.new_read_builder().new_scan().plan().splits[0]
    io_ = get_file_io(table.path)
    path = f"{table.path}/bucket-0/{split.data_files[0].file_name}"
    raw = bytearray(io_.read_bytes(path))
    mid = len(raw) // 3
    for i in range(mid, min(mid + 400, len(raw) - 100)):
        raw[i] ^= 0xA5
    io_.delete(path)
    io_.write_bytes(path, bytes(raw))
    from paimon_tpu.fs.caching import global_footer_cache
    global_footer_cache().clear()    # footer was cached pre-corruption
    with pytest.raises(Exception):
        table.copy({"scan.split.parallelism": "4"}).to_arrow()
    lenient = table.copy({"scan.split.parallelism": "4",
                          "read.retry.backoff": "0",
                          "scan.ignore-corrupt-files": "true"})
    with pytest.warns(RuntimeWarning, match="corrupt"):
        lenient.to_arrow()


def test_corrupt_file_still_skipped_when_opted_in(tmp_path):
    table = make_random_engine_table(str(tmp_path / "t"), seed=13,
                                     engine="deduplicate", buckets=1)
    split = table.new_read_builder().new_scan().plan().splits[0]
    path = f"{table.path}/bucket-0/{split.data_files[0].file_name}"
    get_file_io(table.path).write_bytes(path, b"not parquet at all")
    with pytest.raises(Exception):
        table.copy({"scan.split.parallelism": "4"}).to_arrow()
    lenient = table.copy({"scan.split.parallelism": "4",
                          "scan.ignore-corrupt-files": "true"})
    with pytest.warns(RuntimeWarning, match="corrupt"):
        out = lenient.to_arrow()
    assert out.num_rows > 0


def test_missing_file_not_retried_and_skippable(tmp_path):
    """A planned-then-deleted file (racing expiry/orphan clean) cannot
    reappear: it must NOT burn retry backoff, and it stays in the
    skip-eligible class like before the pipeline."""
    from paimon_tpu.metrics import (
        SCAN_READ_RETRIES, global_registry,
    )
    table = make_random_engine_table(str(tmp_path / "t"), seed=19,
                                     engine="deduplicate", buckets=1)
    split = table.new_read_builder().new_scan().plan().splits[0]
    path = f"{table.path}/bucket-0/{split.data_files[0].file_name}"
    get_file_io(table.path).delete(path)
    retries0 = global_registry().scan_metrics() \
        .counter(SCAN_READ_RETRIES).count
    with pytest.raises(FileNotFoundError):
        table.copy({"scan.split.parallelism": "4"}).to_arrow()
    lenient = table.copy({"scan.split.parallelism": "4",
                          "scan.ignore-corrupt-files": "true"})
    with pytest.warns(RuntimeWarning, match="corrupt"):
        lenient.to_arrow()
    assert global_registry().scan_metrics() \
        .counter(SCAN_READ_RETRIES).count == retries0


def test_fsck_deep_bypasses_footer_cache(tmp_path):
    """--deep verification must reparse the ON-DISK footer: a footer
    torn after a scan warmed the process footer cache is still
    reported corrupt."""
    from paimon_tpu.maintenance.fsck import ViolationKind
    table = make_random_engine_table(str(tmp_path / "t"), seed=23,
                                     engine="deduplicate", buckets=1,
                                     commits=1)
    assert table.fsck(deep=True).ok
    table.to_arrow()                          # warm the footer cache
    split = table.new_read_builder().new_scan().plan().splits[0]
    io_ = get_file_io(table.path)
    path = f"{table.path}/bucket-0/{split.data_files[0].file_name}"
    raw = io_.read_bytes(path)
    io_.delete(path)
    io_.write_bytes(path, raw[: len(raw) // 2] + raw[-4:])   # torn
    report = table.fsck(deep=True)
    assert ViolationKind.CORRUPT_DATA_FILE in report.kinds()


# -- tier-1 hygiene: threads + byte budget -----------------------------------

def test_no_leaked_threads_after_read_and_after_abandon(tmp_path):
    table = make_random_engine_table(str(tmp_path / "t"), seed=1,
                                     engine="deduplicate")
    piped = table.copy({"scan.split.parallelism": "4"})
    piped.to_arrow()
    assert not _wait_no_scan_threads(), "leaked threads after read"
    rb = piped.new_read_builder()
    plan = rb.new_scan().plan()
    gen = rb.new_read().iter_splits(plan)
    next(gen)
    gen.close()                       # consumer abandons mid-scan
    assert not _wait_no_scan_threads(), "leaked threads after abandon"


def test_prefetch_byte_budget_respected(tmp_path):
    from paimon_tpu.parallel.scan_pipeline import iter_split_tables
    table = make_random_engine_table(str(tmp_path / "t"), seed=7,
                                     engine="deduplicate")
    rb = table.new_read_builder()
    splits = rb.new_scan().plan().splits
    assert len(splits) >= 2
    biggest = max(sum(f.file_size for f in s.data_files)
                  for s in splits)
    opts = table.copy({"scan.split.parallelism": "4",
                       "read.prefetch.max-bytes": "1"}).options
    stats = {}
    read = rb.new_read()._read
    out = list(iter_split_tables(read, splits, opts, stats=stats))
    assert len(out) == len(splits)
    # a 1-byte budget degenerates to exactly one split in flight
    assert stats["max_inflight_splits"] == 1
    assert stats["peak_inflight_bytes"] <= biggest
    # an ample budget actually pipelines
    stats2 = {}
    ample = table.copy({"scan.split.parallelism": "4"}).options
    list(iter_split_tables(read, splits, ample, stats=stats2))
    assert stats2["max_inflight_splits"] > 1


# -- caches ------------------------------------------------------------------

def test_footer_cache_hits_on_rescan_and_option_gates(tmp_path):
    from paimon_tpu.fs.caching import global_footer_cache
    cache = global_footer_cache()
    table = make_random_engine_table(str(tmp_path / "t"), seed=2,
                                     engine="deduplicate", buckets=2)
    cache.clear()
    h0 = cache.hits
    table.to_arrow()
    assert cache.hits == h0          # cold scan: misses only
    assert len(cache) > 0
    table.to_arrow()
    assert cache.hits > h0           # re-scan served from the cache
    # read.cache.footer=false neither reads nor populates
    cache.clear()
    off = table.copy({"read.cache.footer": "false"})
    h1, m1 = cache.hits, cache.misses
    off.to_arrow()
    assert len(cache) == 0 and (cache.hits, cache.misses) == (h1, m1)


def test_range_cache_serves_repeats_and_evicts_on_write(tmp_path):
    from paimon_tpu.fs.caching import CachingFileIO
    inner = get_file_io(str(tmp_path))
    path = str(tmp_path / "data-abc.bin")
    inner.write_bytes(path, bytes(range(200)))
    cached = CachingFileIO(inner, capacity_bytes=0,
                           range_cache_bytes=1 << 20)
    assert cached.read_range(path, 10, 5) == bytes(range(10, 15))
    assert cached.range_hits == 0
    assert cached.read_range(path, 10, 5) == bytes(range(10, 15))
    assert cached.range_hits == 1
    a, b = cached.read_ranges(path, [(10, 5), (50, 3)])
    assert (a, b) == (bytes(range(10, 15)), bytes(range(50, 53)))
    assert cached.range_hits == 2    # first range from cache
    cached.write_bytes(path, b"xx")  # mutation evicts
    assert cached.read_range(path, 0, 2) == b"xx"
    assert cached.range_hits == 2


def test_read_cache_range_option_wraps_table_fileio(tmp_path):
    from paimon_tpu.fs.caching import CachingFileIO
    table = make_random_engine_table(str(tmp_path / "t"), seed=2,
                                     engine="deduplicate", commits=1)
    wrapped = table.copy({"read.cache.range": "true"})
    assert isinstance(wrapped.file_io, CachingFileIO)
    assert wrapped.file_io.range_capacity > 0
    assert _rows(wrapped) == _rows(table)
    # already-wrapped FileIO is not double-wrapped
    again = FileStoreTable(wrapped.file_io, wrapped.path, wrapped.schema,
                           {"read.cache.range": "true"})
    assert again.file_io is wrapped.file_io


# -- query service /scan -----------------------------------------------------

def test_query_service_scan_endpoint(tmp_path):
    from paimon_tpu.service.query_service import (
        KvQueryClient, KvQueryServer,
    )
    table = make_random_engine_table(str(tmp_path / "t"), seed=11,
                                     engine="deduplicate", buckets=2)
    server = KvQueryServer(table).start()
    try:
        client = KvQueryClient(table)
        rows = client.scan(limit=9)
        assert len(rows) == 9
        rows = client.scan(projection=["pt", "id"], limit=5)
        assert len(rows) == 5 and set(rows[0]) == {"pt", "id"}
        assert client.scan(limit=0) == []
        # server-side errors carry the server's message, not a bare 500
        with pytest.raises(RuntimeError, match="scan failed"):
            client.scan(projection=123)
    finally:
        server.stop()


# -- injectable expire clock -------------------------------------------------

def test_record_level_expire_filter_now_ms_injectable():
    import pyarrow as pa

    from paimon_tpu.core.read import record_level_expire_filter
    from paimon_tpu.options import CoreOptions, Options
    opts = CoreOptions(Options({"record-level.expire-time": "1 s",
                                "record-level.time-field": "ts"}))
    table = pa.table({"id": pa.array([1, 2, 3], pa.int64()),
                      "ts": pa.array([100, 200, None], pa.int32())})
    # ts is seconds; now=201s -> cutoff 200s: row 1 expired, row 2
    # kept (>= cutoff), null always kept
    out = record_level_expire_filter(opts, table, now_ms=201_000)
    assert out.column("id").to_pylist() == [2, 3]
    # same call, clock pinned earlier -> nothing expired yet
    out2 = record_level_expire_filter(opts, table, now_ms=100_500)
    assert out2.column("id").to_pylist() == [1, 2, 3]
