"""Web-scale serving plane (PR 13): event-loop engine, read replicas,
hot delta tier.

Async engine (service/async_server.py): pipelined HTTP/1.1 requests on
one socket answered in order, malformed input -> 400, connection cap ->
503, /healthz engine vitals (replica id, snapshot pin, delta size,
event-loop lag), thread hygiene after stop.

Delta tier (service/delta.py): a key written through the serving
writer is readable via /lookup BEFORE any flush or commit, tombstones
answer None, newest write wins, post-flush answers are byte-identical,
abandoned writers un-publish their uncommitted rows, generations retire
only once EVERY attached reader's plan covers them (min-floor), and
ineligible configurations are refused with a reason.

Replicas + router (service/router.py): shared_cache_state coherence
under concurrent replicas (live commits + compaction: snapshot advance
on one replica evicts dropped files process-wide before the new plan
serves; no torn batches anywhere), consistent-hash stability across
fleet resizes, aggregated /healthz and federated /metrics, the
/topology-following client vs the dumb proxy path, and the
X-Replica-Id debug header end to end.
"""

import json
import socket
import threading
import time

import pytest

from paimon_tpu.schema import Schema
from paimon_tpu.service import (
    KvQueryClient, KvQueryServer, ReplicaRouter, ReplicaSet,
)
from paimon_tpu.service.delta import (
    DeltaTier, ServingWriter, delta_ineligible_reason,
    reset_delta_tiers, shared_delta_tier,
)
from paimon_tpu.service.router import HashRing, _relabel_prometheus
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, RowKind, VarCharType


@pytest.fixture(autouse=True)
def _fresh_delta_tiers():
    reset_delta_tiers()
    yield
    reset_delta_tiers()


def _pk_table(path, buckets=2, extra_opts=None):
    opts = {"bucket": str(buckets), "write-only": "true",
            "service.lookup.refresh-interval": "0"}
    opts.update(extra_opts or {})
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .column("name", VarCharType.string_type())
              .primary_key("id")
              .options(opts)
              .build())
    return FileStoreTable.create(path, schema)


def _commit(table, rows, kinds=None):
    wb = table.new_batch_write_builder()
    with wb.new_write() as w:
        w.write_dicts(rows, row_kinds=kinds)
        wb.new_commit().commit(w.prepare_commit())


def _rows(n, name="seed", lo=0):
    return [{"id": i, "v": float(i), "name": f"{name}{i}"}
            for i in range(lo, lo + n)]


def _serving_threads():
    return [t for t in threading.enumerate()
            if t.name.startswith(("paimon-serve", "paimon-router"))]


# -- async engine ------------------------------------------------------------


class TestAsyncEngine:
    def test_pipelined_requests_answered_in_order(self, tmp_path):
        t = _pk_table(str(tmp_path / "t"))
        _commit(t, _rows(50))
        server = KvQueryServer(t).start()
        try:
            reqs = []
            for i in range(8):
                body = json.dumps(
                    {"keys": [{"id": i}]}).encode()
                reqs.append(
                    (f"POST /lookup HTTP/1.1\r\nHost: x\r\n"
                     f"Content-Type: application/json\r\n"
                     f"Content-Length: {len(body)}\r\n\r\n"
                     ).encode() + body)
            sk = socket.create_connection(
                ("127.0.0.1", server.port), timeout=10)
            sk.sendall(b"".join(reqs))        # all 8 back-to-back
            buf = b""
            deadline = time.time() + 20
            while buf.count(b"HTTP/1.1 200") < 8 and \
                    time.time() < deadline:
                buf += sk.recv(1 << 20)
            sk.close()
            assert buf.count(b"HTTP/1.1 200") == 8
            # responses carry the payloads IN REQUEST ORDER
            offs = [buf.find(f'"name": "seed{i}"'.encode())
                    for i in range(8)]
            assert all(o >= 0 for o in offs), offs
            assert offs == sorted(offs), offs
        finally:
            server.stop()

    def test_malformed_request_answers_400(self, tmp_path):
        t = _pk_table(str(tmp_path / "t"))
        _commit(t, _rows(5))
        server = KvQueryServer(t).start()
        try:
            sk = socket.create_connection(
                ("127.0.0.1", server.port), timeout=5)
            sk.sendall(b"NOT-HTTP\r\n\r\n")
            assert b"400" in sk.recv(65536)
            sk.close()
        finally:
            server.stop()

    def test_connection_cap_answers_503(self, tmp_path):
        t = _pk_table(str(tmp_path / "t"), extra_opts={
            "service.max-connections": "2"})
        _commit(t, _rows(5))
        server = KvQueryServer(t).start()
        socks = []
        try:
            for _ in range(2):
                sk = socket.create_connection(
                    ("127.0.0.1", server.port), timeout=5)
                # a round trip proves the connection is accepted
                body = b'{"keys": [{"id": 1}]}'
                sk.sendall((f"POST /lookup HTTP/1.1\r\nHost: x\r\n"
                            f"Content-Length: {len(body)}\r\n\r\n"
                            ).encode() + body)
                assert b"200" in sk.recv(1 << 20)
                socks.append(sk)
            extra = socket.create_connection(
                ("127.0.0.1", server.port), timeout=5)
            got = extra.recv(65536)
            assert b"503" in got or got == b""   # refused over the cap
            extra.close()
        finally:
            for sk in socks:
                sk.close()
            server.stop()

    def test_healthz_reports_engine_vitals(self, tmp_path):
        t = _pk_table(str(tmp_path / "t"))
        _commit(t, _rows(20))
        server = KvQueryServer(t, replica_id=7).start()
        try:
            with KvQueryClient(address=server.address) as c:
                c.lookup_row({"id": 3})
                hz = c.healthz()
            assert hz["replica_id"] == 7
            assert hz["snapshot_id"] == 1           # pinned plan
            assert hz["delta"] is not None          # tier attached
            assert hz["delta"]["rows"] == 0
            assert "recent_lag_ms" in hz["event_loop"]
            assert hz["event_loop"]["connections"] >= 0
        finally:
            server.stop()

    def test_loop_lag_histogram_is_fed(self, tmp_path):
        from paimon_tpu.metrics import SERVICE_LOOP_LAG_MS, global_registry
        t = _pk_table(str(tmp_path / "t"))
        _commit(t, _rows(10))
        server = KvQueryServer(t).start()
        try:
            with KvQueryClient(address=server.address) as c:
                for i in range(5):
                    c.lookup_row({"id": i})
            h = global_registry().service_metrics(t.name) \
                .histogram(SERVICE_LOOP_LAG_MS)
            assert h.total_count >= 5
        finally:
            server.stop()

    def test_stop_leaves_no_threads(self, tmp_path):
        t = _pk_table(str(tmp_path / "t"))
        _commit(t, _rows(5))
        server = KvQueryServer(t).start()
        with KvQueryClient(address=server.address) as c:
            c.lookup_row({"id": 1})
        server.stop()
        deadline = time.monotonic() + 5
        while _serving_threads() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not _serving_threads()


# -- hot delta tier ----------------------------------------------------------


class TestDeltaTier:
    def test_written_key_readable_before_any_flush_or_commit(
            self, tmp_path):
        """THE acceptance property: a serving-writer row answers
        /lookup with zero snapshots committed for it, and the
        post-flush answer is identical."""
        t = _pk_table(str(tmp_path / "t"))
        _commit(t, _rows(50))
        server = KvQueryServer(t).start()
        try:
            sw = server.new_serving_writer()
            with KvQueryClient(address=server.address) as c:
                sw.write_dicts([
                    {"id": 1000, "v": 7.5, "name": "fresh"},
                    {"id": 3, "v": 99.0, "name": "updated"}])
                snap_before = t.snapshot_manager.latest_snapshot_id()
                pre_new = c.lookup_row({"id": 1000})
                pre_upd = c.lookup_row({"id": 3})
                assert pre_new == {"id": 1000, "v": 7.5,
                                   "name": "fresh"}
                assert pre_upd == {"id": 3, "v": 99.0,
                                   "name": "updated"}
                # genuinely pre-commit: no snapshot advanced
                assert t.snapshot_manager.latest_snapshot_id() \
                    == snap_before
                sid = sw.commit()
                assert sid == snap_before + 1
                server.query().refresh()
                post_new = c.lookup_row({"id": 1000})
                post_upd = c.lookup_row({"id": 3})
            assert post_new == pre_new        # identical post-flush
            assert post_upd == pre_upd
            # the LSM now owns the rows; the delta drained
            assert server._delta.stats()["rows"] == 0
            sw.close()
        finally:
            server.stop()

    def test_delete_tombstone_visible_before_commit(self, tmp_path):
        t = _pk_table(str(tmp_path / "t"))
        _commit(t, _rows(20))
        server = KvQueryServer(t).start()
        try:
            sw = server.new_serving_writer()
            with KvQueryClient(address=server.address) as c:
                assert c.lookup_row({"id": 5}) is not None
                sw.write_dicts([{"id": 5, "v": 0.0, "name": "x"}],
                               row_kinds=[RowKind.DELETE])
                assert c.lookup_row({"id": 5}) is None   # pre-commit
                sw.commit()
                server.query().refresh()
                assert c.lookup_row({"id": 5}) is None   # post-commit
            sw.close()
        finally:
            server.stop()

    def test_newest_write_wins_within_delta(self, tmp_path):
        t = _pk_table(str(tmp_path / "t"))
        _commit(t, _rows(5))
        tier = shared_delta_tier(t)
        with ServingWriter(t, tier) as sw:
            from paimon_tpu.lookup import LocalTableQuery
            q = LocalTableQuery(t, delta=tier)
            sw.write_dicts([{"id": 9, "v": 1.0, "name": "first"}])
            sw.write_dicts([{"id": 9, "v": 2.0, "name": "second"}])
            assert q.lookup([{"id": 9}])[0]["name"] == "second"
            # delete then re-insert: the re-insert wins
            sw.write_dicts([{"id": 9, "v": 0.0, "name": "x"}],
                           row_kinds=[RowKind.DELETE])
            assert q.lookup([{"id": 9}])[0] is None
            sw.write_dicts([{"id": 9, "v": 3.0, "name": "third"}])
            assert q.lookup([{"id": 9}])[0]["name"] == "third"
            q.close()

    def test_abandoned_writer_unpublishes_uncommitted_rows(
            self, tmp_path):
        t = _pk_table(str(tmp_path / "t"))
        _commit(t, _rows(5))
        server = KvQueryServer(t).start()
        try:
            sw = server.new_serving_writer()
            with KvQueryClient(address=server.address) as c:
                sw.write_dicts([{"id": 77, "v": 1.0, "name": "u"}])
                assert c.lookup_row({"id": 77}) is not None
                sw.close()        # never committed
                assert c.lookup_row({"id": 77}) is None
        finally:
            server.stop()

    def test_generation_retires_only_after_every_reader_advances(
            self, tmp_path):
        """Min-floor pruning: replica A refreshing must not un-publish
        rows replica B still serves from an older plan."""
        from paimon_tpu.lookup import LocalTableQuery
        t = _pk_table(str(tmp_path / "t"))
        _commit(t, _rows(10))
        tier = shared_delta_tier(t)
        a = LocalTableQuery(t, delta=tier)
        b = LocalTableQuery(t, delta=tier)
        a.lookup([{"id": 1}])
        b.lookup([{"id": 1}])                 # both pinned at snap 1
        with ServingWriter(t, tier) as sw:
            sw.write_dicts([{"id": 500, "v": 1.0, "name": "d"}])
            sw.commit()                       # sealed at snapshot 2
            assert tier.stats()["sealed_generations"] == 1
            a.refresh()
            a.lookup([{"id": 1}])             # A advanced to snap 2
            # B still pins snap 1: the generation must survive
            assert tier.stats()["sealed_generations"] == 1
            assert b.lookup([{"id": 500}])[0]["name"] == "d"
            b.refresh()
            b.lookup([{"id": 1}])             # B advanced too
            assert tier.stats()["sealed_generations"] == 0
            # every reader now answers from the LSM
            assert a.lookup([{"id": 500}])[0]["name"] == "d"
            assert b.lookup([{"id": 500}])[0]["name"] == "d"
        a.close()
        b.close()

    def test_unloaded_reader_blocks_pruning(self, tmp_path):
        """A registered reader that has not loaded a plan (or is
        mid-first-load having sampled an older snapshot) has an
        UNKNOWN floor: sealing must keep the generation until it
        reports in — pruning would un-publish rows its about-to-
        install plan may not cover."""
        from paimon_tpu.lookup import LocalTableQuery
        t = _pk_table(str(tmp_path / "t"))
        _commit(t, _rows(5))
        tier = shared_delta_tier(t)
        pending = LocalTableQuery(t, delta=tier)   # registered, no plan
        with ServingWriter(t, tier) as sw:
            sw.write_dicts([{"id": 800, "v": 1.0, "name": "k"}])
            sw.commit()
            assert tier.stats()["sealed_generations"] == 1
            pending.lookup([{"id": 800}])          # first load -> floor
            assert tier.stats()["sealed_generations"] == 0
        pending.close()

    def test_closing_a_reader_releases_its_floor(self, tmp_path):
        from paimon_tpu.lookup import LocalTableQuery
        t = _pk_table(str(tmp_path / "t"))
        _commit(t, _rows(5))
        tier = shared_delta_tier(t)
        stale = LocalTableQuery(t, delta=tier)
        stale.lookup([{"id": 1}])             # pins snapshot 1
        live = LocalTableQuery(t, delta=tier)
        live.lookup([{"id": 1}])
        with ServingWriter(t, tier) as sw:
            sw.write_dicts([{"id": 600, "v": 1.0, "name": "z"}])
            sw.commit()
            live.refresh()
            live.lookup([{"id": 1}])
            assert tier.stats()["sealed_generations"] == 1  # stale pins
            stale.close()                     # unregister -> re-prune
            assert tier.stats()["sealed_generations"] == 0
        live.close()

    def test_ineligible_configurations_are_refused_with_reason(
            self, tmp_path):
        t = _pk_table(str(tmp_path / "seq"), extra_opts={
            "sequence.field": "v",
            "service.delta.enabled": "true"})
        assert "sequence.field" in delta_ineligible_reason(t)
        server = KvQueryServer(t)
        assert server._delta is None          # silently not attached
        with pytest.raises(ValueError, match="sequence.field"):
            server.new_serving_writer()
        server.server.stop()
        t2 = _pk_table(str(tmp_path / "off"), extra_opts={
            "service.delta.enabled": "false"})
        server2 = KvQueryServer(t2)
        assert server2._delta is None
        with pytest.raises(ValueError, match="delta tier unavailable"):
            server2.new_serving_writer()
        server2.server.stop()

    def test_overflow_counter_past_max_bytes(self, tmp_path):
        from paimon_tpu.metrics import (
            SERVICE_DELTA_OVERFLOWS, global_registry,
        )
        t = _pk_table(str(tmp_path / "t"), extra_opts={
            "service.delta.max-bytes": "1"})
        _commit(t, _rows(2))
        tier = shared_delta_tier(t)
        before = global_registry().service_metrics(t.name) \
            .counter(SERVICE_DELTA_OVERFLOWS).count
        with ServingWriter(t, tier) as sw:
            sw.write_dicts(_rows(50, name="big", lo=1000))
            after = global_registry().service_metrics(t.name) \
                .counter(SERVICE_DELTA_OVERFLOWS).count
            assert after > before
            # overflow never drops uncommitted rows
            assert tier.stats()["rows"] == 50

    def test_partitioned_table_delta_visibility(self, tmp_path):
        """The delta key includes the partition: a pre-commit row is
        visible under ITS partition only, with the same write-side and
        probe-side composite key encoding."""
        from paimon_tpu.lookup import LocalTableQuery
        schema = (Schema.builder()
                  .column("p", BigIntType(False))
                  .column("id", BigIntType(False))
                  .column("name", VarCharType.string_type())
                  .partition_keys("p")
                  .primary_key("p", "id")
                  .options({"bucket": "2", "write-only": "true"})
                  .build())
        t = FileStoreTable.create(str(tmp_path / "t"), schema)
        _commit(t, [{"p": 1, "id": i, "name": f"a{i}"}
                    for i in range(10)])
        tier = shared_delta_tier(t)
        q = LocalTableQuery(t, delta=tier)
        with ServingWriter(t, tier) as sw:
            sw.write_dicts([{"p": 1, "id": 77, "name": "fresh"},
                            {"p": 2, "id": 78, "name": "other"}])
            hit = q.lookup([{"p": 1, "id": 77}], partition=(1,))[0]
            assert hit == {"p": 1, "id": 77, "name": "fresh"}
            # the other partition's key is not visible under p=1
            assert q.lookup([{"p": 1, "id": 78}],
                            partition=(1,))[0] is None
            assert q.lookup([{"p": 2, "id": 78}],
                            partition=(2,))[0]["name"] == "other"
            sw.commit()
            q.refresh()
            assert q.lookup([{"p": 1, "id": 77}],
                            partition=(1,))[0]["name"] == "fresh"
        q.close()

    def test_view_capture_survives_concurrent_seal_and_prune(
            self, tmp_path):
        """A captured view keeps serving generations that seal+prune
        swap out underneath it (lists are replaced, never mutated)."""
        from paimon_tpu.lookup import LocalTableQuery
        t = _pk_table(str(tmp_path / "t"))
        _commit(t, _rows(5))
        tier = shared_delta_tier(t)
        with ServingWriter(t, tier) as sw:
            sw.write_dicts([{"id": 300, "v": 1.0, "name": "cap"}])
            view = tier.view()                # captured pre-seal
            sw.commit()
            q = LocalTableQuery(t, delta=tier)
            q.lookup([{"id": 1}])             # advance -> prune
            q.close()
            assert tier.stats()["sealed_generations"] == 0
            kt = (300,)
            hit = view.probe(tier._pkey(()), _bucket_of(t, 300), kt)
            assert not view.is_miss(hit) and hit["name"] == "cap"


def _bucket_of(table, key_id: int) -> int:
    import pyarrow as pa

    from paimon_tpu.core.bucket import FixedBucketAssigner
    rt = table.schema.logical_row_type()
    from paimon_tpu.types import data_type_to_arrow
    bucket_keys = table.schema.bucket_keys()
    assigner = FixedBucketAssigner(
        bucket_keys, [rt.get_field(k).type for k in bucket_keys],
        max(1, table.options.bucket))
    q = pa.table({"id": pa.array([key_id], pa.int64())})
    return int(assigner.assign(q)[0])


# -- replicas + router -------------------------------------------------------


class TestReplicas:
    def test_replica_set_serves_all_tenants_with_debug_header(
            self, tmp_path):
        t = _pk_table(str(tmp_path / "t"))
        _commit(t, _rows(100))
        with ReplicaSet(t, replicas=3) as rs:
            rs.start()
            seen = set()
            for i in range(16):
                with KvQueryClient(address=rs.address,
                                   tenant=f"tenant-{i}") as c:
                    assert c.lookup_row({"id": i})["name"] == f"seed{i}"
                    assert c.last_replica is not None
                    seen.add(int(c.last_replica))
            # consistent hashing spreads 16 tenants over >1 replica
            assert len(seen) > 1, seen

    def test_proxy_path_forwards_and_reports_replica(self, tmp_path):
        """A dumb client (follow_topology=False) rides the router
        proxy; the X-Replica-Id header still reports who answered."""
        t = _pk_table(str(tmp_path / "t"))
        _commit(t, _rows(20))
        with ReplicaSet(t, replicas=2) as rs:
            rs.start()
            with KvQueryClient(address=rs.address, tenant="bob",
                               follow_topology=False) as c:
                assert c.lookup_row({"id": 3})["name"] == "seed3"
                assert c._ring is None
                assert list(c._conns) == [rs.address]   # proxied
                proxied = int(c.last_replica)
            expected = rs.router.ring.pick("bob")["id"]
            assert proxied == expected

    def test_torn_batch_and_cache_coherence_under_live_commits(
            self, tmp_path):
        """ISSUE satellite: snapshot advance on one replica must evict
        dropped files everywhere (shared_cache_state is process-wide)
        before the new plan serves — concurrent lookups on EVERY
        replica across live commits + compaction always see a
        consistent version, never a torn batch or stale bytes."""
        t = _pk_table(str(tmp_path / "t"), buckets=2, extra_opts={
            "write-only": "false",            # compaction drops files
            "read.cache.range": "true"})
        _commit(t, _rows(200, name="v0-"))
        with ReplicaSet(t, replicas=3) as rs:
            rs.start()
            stop = threading.Event()
            errors = []

            def committer():
                try:
                    for gen in range(1, 6):
                        _commit(t, _rows(200, name=f"v{gen}-"))
                        time.sleep(0.05)
                except Exception as e:      # noqa: BLE001
                    errors.append(f"commit: {e!r}")
                finally:
                    stop.set()

            def prober(tenant):
                try:
                    with KvQueryClient(address=rs.address,
                                       tenant=tenant) as c:
                        while not stop.is_set():
                            rows = c.lookup(
                                [{"id": k} for k in range(0, 40, 7)])
                            vers = {r["name"].split("-")[0]
                                    for r in rows if r}
                            # one BATCH never spans two versions
                            assert len(vers) <= 1, \
                                f"torn batch: {vers}"
                except Exception as e:      # noqa: BLE001
                    errors.append(f"probe[{tenant}]: {e!r}")

            probers = [threading.Thread(target=prober,
                                        args=(f"tenant-{i}",))
                       for i in range(6)]
            cth = threading.Thread(target=committer)
            [p.start() for p in probers]
            cth.start()
            cth.join()
            [p.join() for p in probers]
            assert not errors, errors[:3]
            # after everything lands, every replica serves v5 bytes
            for i in range(8):
                with KvQueryClient(address=rs.address,
                                   tenant=f"late-{i}") as c:
                    row = c.lookup_row({"id": 11})
                    assert row["name"] == "v5-11", row

    def test_shared_tier_evicts_dropped_files_across_replicas(
            self, tmp_path):
        """Compaction on a refresh of ONE replica's plan invalidates
        the dropped files' bytes in the PROCESS-wide tier: no replica
        can serve stale cached bytes for vanished files."""
        from paimon_tpu.fs.caching import shared_cache_state
        t = _pk_table(str(tmp_path / "t"), buckets=1, extra_opts={
            "service.lookup.refresh-interval": "100000"})
        _commit(t, _rows(50, name="a"))
        _commit(t, _rows(50, name="b"))
        with ReplicaSet(t, replicas=2) as rs:
            rs.start()
            # warm BOTH replicas' plans + the shared byte tier (each
            # replica must hold the pre-compaction plan for the test
            # to mean anything)
            for s in rs.servers:
                assert s.query().lookup([{"id": 7}])[0]["name"] == "b7"
            old_files = {f.file_name
                         for s in t.new_read_builder().new_scan()
                         .plan().splits for f in s.data_files}
            t.compact(full=True)              # rewrites -> drops files
            # ONE replica refreshes; eviction is process-wide
            rs.servers[0].query().refresh()
            rs.servers[0].query().lookup([{"id": 7}])
            state = shared_cache_state()
            with state.lock:
                cached_paths = set(state.cache.keys()) | \
                    {p for (p, _o, _l) in state.ranges.keys()}
            for path in cached_paths:
                assert not any(path.endswith(f) for f in old_files), \
                    f"stale bytes for dropped file: {path}"
            # the OTHER replica (plan still old is fine — its files
            # may be gone) re-reads fresh bytes on refresh
            rs.servers[1].query().refresh()
            row = rs.servers[1].query().lookup([{"id": 7}])[0]
            assert row["name"] == "b7"

    def test_router_healthz_aggregates_and_metrics_federate(
            self, tmp_path):
        t = _pk_table(str(tmp_path / "t"))
        _commit(t, _rows(10))
        with ReplicaSet(t, replicas=2) as rs:
            rs.start()
            with KvQueryClient(address=rs.address) as c:
                c.lookup_row({"id": 1})
                hz = c.healthz()
            assert hz["router"] is True
            assert hz["replica_count"] == 2
            assert set(hz["replicas"]) == {"0", "1"}
            assert hz["replicas"]["0"]["replica_id"] == 0
            # in-process fleet: /metrics renders the shared registry
            import urllib.request
            text = urllib.request.urlopen(
                rs.address + "/metrics", timeout=10).read().decode()
            assert "paimon_service_requests" in text

    def test_router_federation_survives_dead_remote(self, tmp_path):
        """A remote replica that died does not poison the router's
        aggregation surfaces: /metrics federates the live replica's
        series (replica label intact) and skips the dead one, /slo
        rolls up the live replica and lists the dead one as
        unreachable — partial answers, never a 5xx."""
        t = _pk_table(str(tmp_path / "t"))
        _commit(t, _rows(10))
        live = KvQueryServer(FileStoreTable.load(t.path),
                             replica_id=1).start()
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead_addr = "http://127.0.0.1:%d" % s.getsockname()[1]
        s.close()                         # nobody listens here anymore
        router = ReplicaRouter(
            addresses={1: live.address, 2: dead_addr}, table_name="t")
        router.server.start()
        try:
            # prime the live replica's serving series
            with KvQueryClient(address=live.address,
                               follow_topology=False) as c:
                c.lookup_row({"id": 1})
            import urllib.request
            text = urllib.request.urlopen(
                router.address + "/metrics",
                timeout=10).read().decode()
            with KvQueryClient(address=router.address,
                               follow_topology=False) as c:
                slo = c.slo()
            live_lines = [ln for ln in text.splitlines()
                          if ln.startswith("paimon_service_requests{")]
            assert any('replica="1"' in ln for ln in live_lines), \
                text[:2000]
            assert not any('replica="2"' in ln
                           for ln in text.splitlines())
            assert slo["replicas"] == 1
            assert "1" in slo["per_replica"]
            assert "2" in slo["unreachable"]
            assert slo["alert"] is False
        finally:
            router.server.stop()
            for pool in router._remote.values():
                pool.close()
            live.stop()

    def test_hash_ring_stability_on_resize(self):
        nodes3 = [{"id": i, "address": f"http://h:{8000 + i}"}
                  for i in range(3)]
        nodes4 = nodes3 + [{"id": 3, "address": "http://h:8003"}]
        r3, r4 = HashRing(nodes3, 64), HashRing(nodes4, 64)
        tenants = [f"tenant-{i}" for i in range(1000)]
        moved = sum(r3.pick(x)["id"] != r4.pick(x)["id"]
                    for x in tenants)
        # consistent hashing: ~1/4 of tenants move, never a reshuffle
        assert moved < 500, moved
        # and the mapping is deterministic across ring rebuilds
        r3b = HashRing(nodes3, 64)
        assert all(r3.pick(x)["id"] == r3b.pick(x)["id"]
                   for x in tenants)

    def test_relabel_prometheus_injects_replica_label(self):
        text = ("# HELP paimon_service_requests x\n"
                "# TYPE paimon_service_requests counter\n"
                "paimon_service_requests 5\n"
                'paimon_service_lookup_ms{table="t",quantile="p95"}'
                " 1.5\n")
        out = _relabel_prometheus(text, 2)
        assert 'paimon_service_requests{replica="2"} 5' in out
        assert ('paimon_service_lookup_ms{replica="2",table="t",'
                'quantile="p95"} 1.5') in out
        assert out.splitlines()[0].startswith("# HELP")

    def test_delta_visible_on_every_replica(self, tmp_path):
        """The tier is shared by table path: one serving writer, N
        replicas, zero commits — all replicas answer the fresh key."""
        t = _pk_table(str(tmp_path / "t"))
        _commit(t, _rows(10))
        with ReplicaSet(t, replicas=3) as rs:
            rs.start()
            sw = rs.new_serving_writer()
            sw.write_dicts([{"id": 900, "v": 1.0, "name": "hot"}])
            answered = set()
            for i in range(12):
                with KvQueryClient(address=rs.address,
                                   tenant=f"tn-{i}") as c:
                    assert c.lookup_row({"id": 900})["name"] == "hot"
                    answered.add(int(c.last_replica))
            assert len(answered) > 1          # not all one replica
            sw.close()

    def test_stop_leaves_no_threads(self, tmp_path):
        t = _pk_table(str(tmp_path / "t"))
        _commit(t, _rows(5))
        rs = ReplicaSet(t, replicas=2).start()
        with KvQueryClient(address=rs.address) as c:
            c.lookup_row({"id": 1})
        rs.stop()
        deadline = time.monotonic() + 5
        while _serving_threads() and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not _serving_threads()


def test_replicated_bench_rig_smoke():
    """benchmarks/serve_bench --replica-serve/--client-load rig end to
    end at toy scale: replica subprocesses come up, the router routes,
    client processes follow /topology, the labeled latency series and
    the oracle identity check all land in the record."""
    from benchmarks.serve_bench import measure_replicated
    out = measure_replicated(rows=5000, clients=8, seconds=1.0,
                             replicas=2, client_procs=2, emit=None)
    assert out["qps"] > 0
    assert out["oracle_rows_checked"] > 0
    assert set(out["per_replica"]) == {"0", "1"}
    for series in ("client_ok_p95_ms", "client_all_p95_ms",
                   "obs_lookup_p95_ms", "obs_lookup_p95_ms_max"):
        assert series in out, series
    assert "client_ok" in out["latency_series"]
