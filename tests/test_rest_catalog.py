"""REST catalog protocol: client <-> server over HTTP with bearer auth.

reference: paimon-api/.../rest/RESTApi + rest/RESTCatalog.java.
"""

import pytest

import paimon_tpu
from paimon_tpu.catalog import (
    DatabaseNotFoundError, TableAlreadyExistsError, TableNotFoundError,
)
from paimon_tpu.catalog.rest import RESTCatalogClient, RESTCatalogServer
from paimon_tpu.schema import Schema
from paimon_tpu.types import BigIntType, DoubleType


@pytest.fixture
def served(tmp_path):
    backing = paimon_tpu.create_catalog(
        {"warehouse": str(tmp_path / "wh")})
    server = RESTCatalogServer(backing, token="s3cr3t").start()
    yield server
    server.stop()


def _schema():
    return (Schema.builder()
            .column("id", BigIntType(False))
            .column("v", DoubleType())
            .primary_key("id")
            .options({"bucket": "1"})
            .build())


def test_rest_catalog_end_to_end(served):
    cat = paimon_tpu.create_catalog(
        {"metastore": "rest", "uri": served.uri, "token": "s3cr3t"})
    assert cat.list_databases() == []
    cat.create_database("db", properties={"owner": "x"})
    assert cat.list_databases() == ["db"]
    assert cat.load_database_properties("db") == {"owner": "x"}

    t = cat.create_table("db.t", _schema())
    assert cat.list_tables("db") == ["t"]

    # full write/read through the table the REST catalog resolved
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts([{"id": 1, "v": 1.0}])
    wb.new_commit().commit(w.prepare_commit())
    t2 = cat.get_table("db.t")
    assert t2.to_arrow().to_pylist() == [{"id": 1, "v": 1.0}]

    with pytest.raises(TableAlreadyExistsError):
        cat.create_table("db.t", _schema())
    cat.rename_table("db.t", "db.u")
    assert cat.list_tables("db") == ["u"]
    cat.drop_table("db.u")
    with pytest.raises(TableNotFoundError):
        cat.get_table("db.u")
    with pytest.raises(DatabaseNotFoundError):
        cat.list_tables("nope")


def test_rest_catalog_auth(served):
    bad = RESTCatalogClient(served.uri, token="wrong")
    with pytest.raises(RuntimeError):
        bad.list_databases()
    anon = RESTCatalogClient(served.uri)
    with pytest.raises(RuntimeError):
        anon.list_databases()


def test_kv_query_service(tmp_path):
    from paimon_tpu.service import KvQueryClient, KvQueryServer
    from paimon_tpu.table import FileStoreTable

    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "2"})
              .build())
    table = FileStoreTable.create(str(tmp_path / "q"), schema)
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts([{"id": i, "v": float(i)} for i in range(50)])
    wb.new_commit().commit(w.prepare_commit())

    server = KvQueryServer(table).start()
    try:
        # discovery via the table's service registry
        client = KvQueryClient(table)
        rows = client.lookup([{"id": 7}, {"id": 999}])
        assert rows[0] == {"id": 7, "v": 7.0}
        assert rows[1] is None
        assert client.lookup_row({"id": 49}) == {"id": 49, "v": 49.0}
    finally:
        server.stop()
    # address unregistered on stop
    with pytest.raises(RuntimeError):
        KvQueryClient(table)


def test_rest_drop_database_cascade_guard(served):
    cat = paimon_tpu.create_catalog(
        {"metastore": "rest", "uri": served.uri, "token": "s3cr3t"})
    cat.create_database("db")
    cat.create_table("db.t", _schema())
    with pytest.raises(RuntimeError):
        cat.drop_database("db")          # non-empty, cascade=False
    cat.drop_database("db", cascade=True)
    assert cat.list_databases() == []


def test_jdbc_catalog(tmp_path):
    cat = paimon_tpu.create_catalog({
        "metastore": "jdbc",
        "uri": str(tmp_path / "catalog.db"),
        "warehouse": str(tmp_path / "wh"),
    })
    cat.create_database("db", properties={"owner": "x"})
    assert cat.list_databases() == ["db"]
    assert cat.load_database_properties("db") == {"owner": "x"}
    t = cat.create_table("db.t", _schema())
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts([{"id": 1, "v": 1.0}])
    wb.new_commit().commit(w.prepare_commit())

    # a SECOND catalog instance over the same DB sees everything
    cat2 = paimon_tpu.create_catalog({
        "metastore": "jdbc",
        "uri": str(tmp_path / "catalog.db"),
        "warehouse": str(tmp_path / "wh"),
    })
    assert cat2.list_tables("db") == ["t"]
    assert cat2.get_table("db.t").to_arrow().num_rows == 1
    with pytest.raises(TableAlreadyExistsError):
        cat2.create_table("db.t", _schema())
    cat2.rename_table("db.t", "db.u")
    assert cat.list_tables("db") == ["u"]
    with pytest.raises(ValueError):
        cat.drop_database("db")
    cat.drop_database("db", cascade=True)
    assert cat2.list_databases() == []
    cat.close(); cat2.close()


def test_jdbc_rename_into_missing_database_rejected(tmp_path):
    cat = paimon_tpu.create_catalog({
        "metastore": "jdbc",
        "uri": str(tmp_path / "c2.db"),
        "warehouse": str(tmp_path / "wh2"),
    })
    cat.create_database("db")
    cat.create_table("db.t", _schema())
    with pytest.raises(DatabaseNotFoundError):
        cat.rename_table("db.t", "nope.u")
    assert cat.list_tables("db") == ["t"]
    cat.close()


def test_pagination_and_token_file(tmp_path):
    """maxResults/pageToken paging (reference RESTApi.MAX_RESULTS /
    PAGE_TOKEN) and rotating bearer-token files."""
    from paimon_tpu.catalog import create_catalog
    from paimon_tpu.catalog.rest import RESTCatalogClient, RESTCatalogServer

    inner = create_catalog({"warehouse": str(tmp_path / "wh")})
    for i in range(7):
        inner.create_database(f"db{i}")
    token_file = tmp_path / "token"
    token_file.write_text("secret-1\n")
    server = RESTCatalogServer(inner, token="secret-1")
    server.start()
    try:
        client = RESTCatalogClient(server.uri,
                                   token_file=str(token_file))
        # raw page walk
        page1, tok = client.list_databases_paged(max_results=3)
        assert len(page1) == 3 and tok == page1[-1]
        page2, tok2 = client.list_databases_paged(max_results=3,
                                                  page_token=tok)
        assert len(page2) == 3 and page2[0] > page1[-1]
        # auto-paged listing sees everything exactly once
        names = client.list_databases(page_size=2)
        assert sorted(n for n in names if n.startswith("db")) == \
            [f"db{i}" for i in range(7)]

        # token rotation: server now requires a new secret
        server.token = "secret-2"
        token_file.write_text("secret-2\n")
        assert "db0" in client.list_databases()

        # tables paging
        from paimon_tpu.schema import Schema
        from paimon_tpu.types import IntType
        for i in range(5):
            inner.create_table(
                f"db0.t{i}",
                Schema.builder().column("a", IntType())
                .options({"bucket": "-1"}).build())
        ts, tok = client.list_tables_paged("db0", max_results=2)
        assert ts == ["t0", "t1"] and tok == "t1"
        assert client.list_tables("db0", page_size=2) == \
            [f"t{i}" for i in range(5)]
    finally:
        server.stop()
