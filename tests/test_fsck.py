"""Table fsck (maintenance/fsck.py): every seeded corruption class is
detected with a typed violation, fix_violations repairs the fixable
classes, and the CLI surface (`paimon table fsck`) wires both.
"""

import json
import os

import pyarrow.parquet as pq
import pytest

from paimon_tpu.cli import main as cli_main
from paimon_tpu.maintenance import (
    ViolationKind, expire_snapshots, fix_violations, fsck,
)
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType

FAR_FUTURE_MS = 10 ** 18


def _schema(opts=None):
    return (Schema.builder()
            .column("id", BigIntType(False))
            .column("v", DoubleType())
            .primary_key("id")
            .options({"bucket": "1", "write-only": "true",
                      **(opts or {})})
            .build())


def _commit(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    sid = wb.new_commit().commit(w.prepare_commit())
    w.close()
    return sid


@pytest.fixture()
def table(tmp_path):
    t = FileStoreTable.create(str(tmp_path / "t"), _schema())
    for i in range(3):
        _commit(t, [{"id": j, "v": float(i)} for j in range(i, i + 4)])
    return t


def _live_data_paths(table):
    scan = table.new_scan()
    out = []
    for s in table.new_read_builder().new_scan().plan().splits:
        for f in s.data_files:
            out.append(scan.path_factory.data_file_path(
                s.partition, s.bucket, f.file_name))
    return out


def _latest_manifest_paths(table):
    """Paths of the manifest FILES referenced by the latest snapshot."""
    scan = table.new_scan()
    snap = table.latest_snapshot()
    names = []
    for list_name in (snap.base_manifest_list,
                      snap.delta_manifest_list):
        if list_name:
            names.extend(m.file_name
                         for m in scan.manifest_list.read(list_name))
    return [scan.manifest_file.path(n) for n in names]


def test_healthy_table_is_clean(table):
    report = fsck(table)
    assert report.ok
    assert report.snapshots_checked == 3
    assert report.manifests_checked > 0
    assert report.data_files_checked > 0
    assert table.fsck().ok                 # table-level convenience


def test_detects_dangling_data_file(table):
    os.remove(_live_data_paths(table)[0])
    report = fsck(table)
    assert ViolationKind.DANGLING_DATA_FILE in report.kinds()
    v = report.by_kind(ViolationKind.DANGLING_DATA_FILE)[0]
    assert v.snapshot_id is not None and v.obj


def test_detects_truncated_manifest(table):
    path = _latest_manifest_paths(table)[0]
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[:len(blob) // 2])
    report = fsck(table)
    assert ViolationKind.CORRUPT_MANIFEST in report.kinds()


def test_detects_missing_manifest(table):
    os.remove(_latest_manifest_paths(table)[0])
    report = fsck(table)
    assert ViolationKind.MISSING_MANIFEST in report.kinds()


def test_detects_missing_manifest_list(table):
    scan = table.new_scan()
    os.remove(scan.manifest_list.path(
        table.latest_snapshot().base_manifest_list))
    report = fsck(table)
    assert ViolationKind.MISSING_MANIFEST_LIST in report.kinds()


def test_detects_snapshot_chain_gap(table):
    os.remove(f"{table.path}/snapshot/snapshot-2")
    report = fsck(table)
    assert ViolationKind.SNAPSHOT_GAP in report.kinds()
    gap = report.by_kind(ViolationKind.SNAPSHOT_GAP)[0]
    assert gap.snapshot_id == 2


def test_detects_bad_hints(table):
    open(f"{table.path}/snapshot/EARLIEST", "w").write("99")
    report = fsck(table)
    assert ViolationKind.BAD_HINT in report.kinds()


def test_detects_corrupt_snapshot(table):
    open(f"{table.path}/snapshot/snapshot-2", "w").write("{not json")
    report = fsck(table)
    assert ViolationKind.CORRUPT_SNAPSHOT in report.kinds()
    # a corrupt snapshot file is NOT a data manifest: --fix must not
    # route it through the manifest-drop path (it is unfixable)
    assert fix_violations(table, report) == []


def test_corrupt_index_manifest_not_deleted_by_fix(table):
    """Index manifests share manifest/ with data manifests but have
    their own violation kinds — fix_violations must never drop one (it
    cannot rewrite the index chain, so deleting would turn a corrupt-
    but-present file into a permanently missing one)."""
    from paimon_tpu.core.commit import FileStoreCommit
    from paimon_tpu.manifest import FileKind
    from paimon_tpu.manifest.index_manifest import (
        IndexFileMeta, IndexManifestEntry,
    )

    # commit a snapshot carrying an index manifest
    commit = FileStoreCommit(table.file_io, table.path, table.schema,
                             table.options)
    ix = IndexFileMeta("HASH", "index-test-0", 8, 2)
    table.file_io.write_bytes(
        table.new_scan().path_factory.index_file_path(ix.file_name),
        b"\x00" * 8)
    commit.commit([], index_entries=[
        IndexManifestEntry(FileKind.ADD, b"", 0, ix)])
    name = table.latest_snapshot().index_manifest
    path = table.new_scan().index_manifest_file.path(name)
    open(path, "wb").write(b"garbage")

    report = fsck(table, all_snapshots=False)
    assert ViolationKind.CORRUPT_INDEX_MANIFEST in report.kinds()
    assert ViolationKind.CORRUPT_MANIFEST not in report.kinds()
    assert fix_violations(table, report) == []
    assert table.file_io.exists(path)      # never deleted

    os.remove(path)
    report = fsck(table, all_snapshots=False)
    assert ViolationKind.MISSING_INDEX_MANIFEST in report.kinds()
    assert ViolationKind.MISSING_MANIFEST not in report.kinds()
    assert fix_violations(table, report) == []


def test_detects_row_count_mismatch(table):
    path = f"{table.path}/snapshot/snapshot-3"
    snap = json.loads(open(path).read())
    snap["totalRecordCount"] += 5
    open(path, "w").write(json.dumps(snap))
    report = fsck(table, snapshot_id=3)
    assert ViolationKind.ROW_COUNT_MISMATCH in report.kinds()


def test_deep_detects_stats_mismatch(table):
    # rewrite one live data file with a row sliced off: still readable,
    # but actual rows no longer match the manifest's recorded stats
    path = _live_data_paths(table)[0]
    t = pq.read_table(path)
    pq.write_table(t.slice(0, t.num_rows - 1), path)
    assert fsck(table, deep=False).kinds() <= \
        {ViolationKind.FILE_SIZE_MISMATCH}   # shallow can't see rows
    report = fsck(table, deep=True)
    assert ViolationKind.STATS_MISMATCH in report.kinds()


def test_deep_detects_corrupt_data_file(table):
    path = _live_data_paths(table)[0]
    size = os.path.getsize(path)
    open(path, "wb").write(b"\x00" * size)   # same size, unreadable
    report = fsck(table, deep=True)
    assert ViolationKind.CORRUPT_DATA_FILE in report.kinds()


def test_fsck_counts_violations_metric(table):
    from paimon_tpu.metrics import FSCK_VIOLATIONS, global_registry
    group = global_registry().maintenance_metrics()
    before = group.counter(FSCK_VIOLATIONS).count
    os.remove(_live_data_paths(table)[0])
    report = fsck(table)
    assert group.counter(FSCK_VIOLATIONS).count == \
        before + len(report.violations)


def test_fix_dangling_data_file(table):
    os.remove(_live_data_paths(table)[0])
    report = fsck(table)
    actions = fix_violations(table, report)
    assert "remove-unexisting-files" in actions
    # the repaired LATEST snapshot is clean; older snapshots still pin
    # the lost file and heal by expiry
    assert fsck(table, all_snapshots=False).ok
    expire_snapshots(table, retain_max=1, retain_min=1,
                     older_than_ms=FAR_FUTURE_MS)
    assert fsck(table).ok
    table.to_arrow()                       # and the table still reads


def test_fix_corrupt_manifest(table):
    path = _latest_manifest_paths(table)[0]
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[:len(blob) // 2])
    report = fsck(table, all_snapshots=False)
    actions = fix_violations(table, report)
    assert "drop-corrupt-manifests" in actions
    assert "remove-unexisting-manifests" in actions
    assert fsck(table, all_snapshots=False).ok


def test_fix_bad_hints(table):
    open(f"{table.path}/snapshot/EARLIEST", "w").write("99")
    open(f"{table.path}/snapshot/LATEST", "w").write("77")
    actions = fix_violations(table, fsck(table))
    assert actions == ["rewrite-hints"]
    assert fsck(table).ok
    sm = table.snapshot_manager
    assert sm.earliest_snapshot_id() == 1
    assert sm.latest_snapshot_id() == 3


# -- CLI surface -------------------------------------------------------------


def _cli(capsys, *argv):
    rc = cli_main(list(argv))
    out = capsys.readouterr()
    return rc, out.out


def _cli_table(capsys, wh):
    assert _cli(capsys, "-w", wh, "db", "create", "d1")[0] == 0
    rc, _ = _cli(capsys, "-w", wh, "table", "create", "d1.t",
                 "--column", "id:BIGINT NOT NULL",
                 "--column", "v:DOUBLE", "--primary-key", "id",
                 "--option", "bucket=1")
    assert rc == 0
    rc, _ = _cli(capsys, "-w", wh, "sql",
                 "INSERT INTO d1.t VALUES (1, 1.5), (2, 2.5)")
    assert rc == 0
    return os.path.join(wh, "d1.db", "t")


def test_cli_fsck_clean_and_violations(capsys, tmp_path):
    wh = str(tmp_path / "wh")
    tpath = _cli_table(capsys, wh)
    rc, out = _cli(capsys, "-w", wh, "table", "fsck", "d1.t")
    assert rc == 0
    assert json.loads(out)["ok"] is True

    open(os.path.join(tpath, "snapshot", "EARLIEST"), "w").write("99")
    rc, out = _cli(capsys, "-w", wh, "table", "fsck", "d1.t")
    assert rc == 1
    report = json.loads(out)
    assert report["ok"] is False
    assert report["violations"][0]["kind"] == ViolationKind.BAD_HINT


def test_cli_fsck_fix(capsys, tmp_path):
    wh = str(tmp_path / "wh")
    tpath = _cli_table(capsys, wh)
    open(os.path.join(tpath, "snapshot", "EARLIEST"), "w").write("99")
    rc, out = _cli(capsys, "-w", wh, "table", "fsck", "d1.t", "--fix")
    assert rc == 0
    report = json.loads(out)
    assert report["ok"] is True
    assert report["fix_actions"] == ["rewrite-hints"]


# -- incremental fsck (sweep watermark) --------------------------------------

def _forge_total(table, sid, delta=7):
    path = f"{table.path}/snapshot/snapshot-{sid}"
    d = json.loads(open(path).read())
    d["totalRecordCount"] = d["totalRecordCount"] + delta
    open(path, "w").write(json.dumps(d))


def _new_path(after, before):
    fresh = [p for p in after if p not in before]
    assert fresh, "expected post-watermark objects"
    return fresh[0]


def test_incremental_is_o_delta(table):
    """The witness for the whole mode: a stamped-clean chain costs
    ZERO manifest decodes to re-verify, and new commits cost only
    their own delta."""
    full = fsck(table, stamp_watermark=True)
    assert full.ok and not full.incremental
    assert full.manifest_entries_decoded > 0

    rep = fsck(table, incremental=True)
    assert rep.ok and rep.incremental
    assert rep.manifest_entries_decoded == 0

    _commit(table, [{"id": 50, "v": 1.0}])
    _commit(table, [{"id": 51, "v": 1.0}])
    rep2 = fsck(table, incremental=True)
    assert rep2.ok and rep2.incremental
    assert 0 < rep2.manifest_entries_decoded < \
        full.manifest_entries_decoded


def test_incremental_absent_watermark_runs_full(table):
    rep = fsck(table, incremental=True)
    assert rep.ok and not rep.incremental
    assert rep.manifest_entries_decoded > 0


def test_incremental_rollback_demotes_to_full(table):
    """rollback_to rewrites history past the stamp: the next
    incremental run must silently fall back to a full pass (and a
    clean stamped one re-arms it)."""
    assert fsck(table, stamp_watermark=True).ok
    _commit(table, [{"id": 60, "v": 6.0}])
    table.rollback_to(2)
    rep = fsck(table, incremental=True)
    assert rep.ok and not rep.incremental
    assert fsck(table, incremental=True, stamp_watermark=True).ok
    rep2 = fsck(table, incremental=True)
    assert rep2.ok and rep2.incremental


def test_validate_watermark_mirrors_matches_tip(table):
    """Identity = (id, base list, delta list): UUID list names make a
    recreated id distinguishable, exactly like the plan cache."""
    from paimon_tpu.maintenance import SweepWatermark, validate_watermark

    snap = table.latest_snapshot()
    good = SweepWatermark(snap.id, snap.base_manifest_list or "",
                          snap.delta_manifest_list or "", 123)
    assert validate_watermark(table, good)
    assert not validate_watermark(
        table, SweepWatermark(snap.id, "manifest-list-recreated",
                              good.delta_list, 123))
    assert not validate_watermark(
        table, SweepWatermark(snap.id + 99, good.base_list,
                              good.delta_list, 123))


_AGREEMENT_SEEDS = [
    (ViolationKind.DANGLING_DATA_FILE,
     lambda t, pd, pm: os.remove(_new_path(_live_data_paths(t), pd))),
    (ViolationKind.CORRUPT_MANIFEST,
     lambda t, pd, pm: open(_new_path(_latest_manifest_paths(t), pm),
                            "wb").write(b"xx")),
    (ViolationKind.MISSING_MANIFEST,
     lambda t, pd, pm: os.remove(
         _new_path(_latest_manifest_paths(t), pm))),
    (ViolationKind.MISSING_MANIFEST_LIST,
     lambda t, pd, pm: os.remove(t.new_scan().manifest_list.path(
         t.latest_snapshot().delta_manifest_list))),
    (ViolationKind.SNAPSHOT_GAP,
     lambda t, pd, pm: os.remove(
         f"{t.path}/snapshot/snapshot-{t.latest_snapshot().id - 1}")),
    (ViolationKind.CORRUPT_SNAPSHOT,
     lambda t, pd, pm: open(
         f"{t.path}/snapshot/snapshot-{t.latest_snapshot().id - 1}",
         "w").write("{not json")),
    (ViolationKind.ROW_COUNT_MISMATCH,
     lambda t, pd, pm: _forge_total(t, t.latest_snapshot().id)),
]


@pytest.mark.parametrize(
    "kind,seed", _AGREEMENT_SEEDS,
    ids=[k for k, _ in _AGREEMENT_SEEDS])
def test_incremental_full_agreement(table, kind, seed):
    """The agreement oracle: every violation producible in the
    post-watermark delta is found by BOTH modes — the periodic full
    pass can only ever ADD coverage (absolute recounts,
    level-overlap), never disagree on the delta."""
    assert fsck(table, stamp_watermark=True).ok
    pre_data = set(_live_data_paths(table))
    pre_manifests = set(_latest_manifest_paths(table))
    _commit(table, [{"id": 100, "v": 9.0}])
    _commit(table, [{"id": 101, "v": 9.0}])
    seed(table, pre_data, pre_manifests)

    inc = fsck(table, incremental=True)
    assert inc.incremental
    assert kind in inc.kinds(), \
        f"incremental missed {kind}: {inc.to_dict()}"
    full = fsck(table)
    assert kind in full.kinds(), f"full missed {kind}"


def test_stamp_requires_clean_chain(table):
    """A dirty chain must never arm the incremental mode: the stamp
    would launder the violation out of every future delta."""
    os.remove(_live_data_paths(table)[0])
    rep = fsck(table, stamp_watermark=True)
    assert not rep.ok
    after = fsck(table, incremental=True)
    assert not after.incremental           # nothing was stamped


def test_cli_fsck_incremental_flags(capsys, tmp_path):
    wh = str(tmp_path / "wh")
    _cli_table(capsys, wh)
    rc, out = _cli(capsys, "-w", wh, "table", "fsck", "d1.t",
                   "--stamp-watermark")
    assert rc == 0 and json.loads(out)["ok"] is True
    rc, out = _cli(capsys, "-w", wh, "table", "fsck", "d1.t",
                   "--incremental")
    assert rc == 0
    report = json.loads(out)
    assert report["ok"] is True
    assert report["incremental"] is True
    assert report["manifest_entries_decoded"] == 0
