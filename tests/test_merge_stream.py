"""Streamed k-way merge: window semantics must be bit-identical to the
one-shot whole-bucket merge."""

import os

import numpy as np
import pyarrow as pa
import pytest

from paimon_tpu.ops.merge import merge_runs
from paimon_tpu.ops.merge_stream import merge_runs_streamed
from paimon_tpu.ops.normkey import NormalizedKeyEncoder
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType


def _kv(keys, seqs):
    return pa.table({
        "_KEY_k": pa.array(keys, pa.int64()),
        "_SEQUENCE_NUMBER": pa.array(seqs, pa.int64()),
        "_VALUE_KIND": pa.array(np.zeros(len(keys), np.int8), pa.int8()),
        "v": pa.array([float(s) for s in seqs], pa.float64()),
    })


def _chunks(table, n):
    for start in range(0, table.num_rows, n):
        yield table.slice(start, n)


def _run_streamed(runs, chunk_rows):
    enc = NormalizedKeyEncoder([pa.int64()], nullable=[False])
    out = []

    def merge_window(tables):
        return merge_runs(tables, ["_KEY_k"], key_encoder=enc).take()

    merge_runs_streamed([_chunks(r, chunk_rows) for r in runs],
                        ["_KEY_k"], enc, out.append, merge_window)
    return pa.concat_tables(out) if out else _kv([], [])


@pytest.mark.parametrize("chunk_rows", [3, 7, 64, 1000])
def test_streamed_equals_oneshot(chunk_rows):
    rng = np.random.default_rng(5)
    runs = []
    seq = 0
    for _ in range(4):
        keys = np.sort(rng.choice(500, size=200, replace=False))
        seqs = np.arange(seq, seq + len(keys))
        seq += len(keys)
        runs.append(_kv(keys, seqs))

    enc = NormalizedKeyEncoder([pa.int64()], nullable=[False])
    expect = merge_runs(runs, ["_KEY_k"], key_encoder=enc).take()
    got = _run_streamed(runs, chunk_rows)
    assert got.num_rows == expect.num_rows
    assert got.column("_KEY_k").to_pylist() == \
        expect.column("_KEY_k").to_pylist()
    assert got.column("v").to_pylist() == expect.column("v").to_pylist()


def test_streamed_duplicate_key_spanning_chunks():
    """A key group larger than the chunk size must stay in one window."""
    keys = [5] * 50 + [9]
    seqs = list(range(51))
    run = _kv(keys, seqs)
    got = _run_streamed([run], chunk_rows=4)
    assert got.column("_KEY_k").to_pylist() == [5, 9]
    assert got.column("v").to_pylist() == [49.0, 50.0]   # max-seq wins


def test_streamed_uneven_runs():
    r1 = _kv([1, 2, 3], [0, 1, 2])
    r2 = _kv([100, 200], [3, 4])
    r3 = _kv([2, 150], [5, 6])
    got = _run_streamed([r1, r2, r3], chunk_rows=2)
    assert got.column("_KEY_k").to_pylist() == [1, 2, 3, 100, 150, 200]
    assert got.column("v").to_pylist()[1] == 5.0   # r3's later write wins


def test_streamed_compaction_e2e(tmp_warehouse):
    """Compaction over the stream threshold produces identical results to
    the in-memory path."""
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true",
                        "tpu.merge.stream-threshold-rows": "100",
                        "tpu.merge.chunk-rows": "64"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "t"), schema)
    rng = np.random.default_rng(0)
    for r in range(4):
        ids = rng.integers(0, 300, 200)
        wb = table.new_batch_write_builder()
        w = wb.new_write()
        w.write_arrow(pa.table({
            "id": pa.array(ids, pa.int64()),
            "v": pa.array(np.full(len(ids), float(r)), pa.float64()),
        }))
        wb.new_commit().commit(w.prepare_commit())
        w.close()

    expect = table.to_arrow()          # merge-on-read truth
    assert table.compact(full=True) is not None
    got = table.to_arrow()
    e = sorted(expect.to_pylist(), key=lambda r: r["id"])
    g = sorted(got.to_pylist(), key=lambda r: r["id"])
    assert g == e
    # and the files rolled at target size are key-sorted overall
    splits = table.new_read_builder().new_scan().plan().splits
    assert all(f.level > 0 for s in splits for f in s.data_files)
