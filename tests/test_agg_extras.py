"""New aggregators: roaring bitmaps, HLL/theta sketches, nested
update, primary_key, ignore-retract.

reference: mergetree/compact/aggregate/FieldRoaringBitmap32Agg.java,
FieldRoaringBitmap64Agg.java, FieldHllSketchAgg.java,
FieldThetaSketchAgg.java, FieldNestedUpdateAgg.java,
FieldPrimaryKeyAgg.java, FieldIgnoreRetractAgg.java.
"""

import os

import numpy as np
import pyarrow as pa
import pytest

from paimon_tpu.index.roaring import (
    deserialize_roaring32, deserialize_roaring64, serialize_roaring32,
    serialize_roaring64,
)
from paimon_tpu.ops.sketch import (
    hll_build, hll_estimate, theta_build, theta_estimate,
)
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import (
    ArrayType, BigIntType, IntType, RowType, VarBinaryType, VarCharType,
)


def agg_table(tmp_warehouse, columns, field_opts):
    b = Schema.builder().column("k", BigIntType(False))
    for name, typ in columns:
        b = b.column(name, typ)
    opts = {"bucket": "1", "write-only": "true",
            "merge-engine": "aggregation"}
    opts.update(field_opts)
    return FileStoreTable.create(os.path.join(tmp_warehouse, "t"),
                                 b.primary_key("k").options(opts).build())


def commit(table, rows, kinds=None):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows, row_kinds=kinds)
    wb.new_commit().commit(w.prepare_commit())
    w.close()


def test_rbm32_union(tmp_warehouse):
    t = agg_table(tmp_warehouse,
                  [("bits", VarBinaryType.bytes_type())],
                  {"fields.bits.aggregate-function": "rbm32"})
    commit(t, [{"k": 1, "bits": bytes(serialize_roaring32(
        np.array([1, 5, 9], np.uint32)))}])
    commit(t, [{"k": 1, "bits": bytes(serialize_roaring32(
        np.array([5, 100], np.uint32)))}])
    out = t.to_arrow().to_pylist()[0]
    assert deserialize_roaring32(out["bits"]).tolist() == [1, 5, 9, 100]


def test_rbm64_union(tmp_warehouse):
    t = agg_table(tmp_warehouse,
                  [("bits", VarBinaryType.bytes_type())],
                  {"fields.bits.aggregate-function": "rbm64"})
    big = 1 << 40
    commit(t, [{"k": 1, "bits": bytes(serialize_roaring64(
        np.array([3, big], np.uint64)))}])
    commit(t, [{"k": 1, "bits": bytes(serialize_roaring64(
        np.array([big, big + 7], np.uint64)))}])
    out = t.to_arrow().to_pylist()[0]
    assert deserialize_roaring64(out["bits"]).tolist() == \
        [3, big, big + 7]


def test_hll_sketch_merge_estimates(tmp_warehouse):
    t = agg_table(tmp_warehouse,
                  [("sk", VarBinaryType.bytes_type())],
                  {"fields.sk.aggregate-function": "hll_sketch"})
    a = hll_build(pa.array(range(0, 6000), pa.int64()))
    b = hll_build(pa.array(range(4000, 10000), pa.int64()))
    commit(t, [{"k": 1, "sk": a}])
    commit(t, [{"k": 1, "sk": b}])
    merged = t.to_arrow().to_pylist()[0]["sk"]
    est = hll_estimate(merged)
    assert abs(est - 10000) / 10000 < 0.05    # ~1.6% expected at p=12


def test_theta_sketch_merge_estimates(tmp_warehouse):
    t = agg_table(tmp_warehouse,
                  [("sk", VarBinaryType.bytes_type())],
                  {"fields.sk.aggregate-function": "theta_sketch"})
    a = theta_build(pa.array(range(0, 6000), pa.int64()))
    b = theta_build(pa.array(range(4000, 10000), pa.int64()))
    commit(t, [{"k": 1, "sk": a}])
    commit(t, [{"k": 1, "sk": b}])
    est = theta_estimate(t.to_arrow().to_pylist()[0]["sk"])
    assert abs(est - 10000) / 10000 < 0.08


def test_nested_update_append_and_keyed(tmp_warehouse):
    from paimon_tpu.types import DataField
    row_t = RowType([DataField(100, "oid", BigIntType()),
                     DataField(101, "st", VarCharType.string_type())])
    t = agg_table(
        tmp_warehouse, [("orders", ArrayType(row_t))],
        {"fields.orders.aggregate-function": "nested_update",
         "fields.orders.nested-key": "oid"})
    commit(t, [{"k": 1, "orders": [{"oid": 1, "st": "new"},
                                   {"oid": 2, "st": "new"}]}])
    commit(t, [{"k": 1, "orders": [{"oid": 1, "st": "paid"}]}])
    out = t.to_arrow().to_pylist()[0]["orders"]
    assert out == [{"oid": 1, "st": "paid"}, {"oid": 2, "st": "new"}]


def test_nested_update_unkeyed_concats(tmp_warehouse):
    from paimon_tpu.types import DataField
    row_t = RowType([DataField(100, "x", IntType())])
    t = agg_table(
        tmp_warehouse, [("vs", ArrayType(row_t))],
        {"fields.vs.aggregate-function": "nested_update"})
    commit(t, [{"k": 1, "vs": [{"x": 1}]}])
    commit(t, [{"k": 1, "vs": [{"x": 1}, {"x": 2}]}])
    assert t.to_arrow().to_pylist()[0]["vs"] == \
        [{"x": 1}, {"x": 1}, {"x": 2}]


def test_primary_key_agg_keeps_first(tmp_warehouse):
    t = agg_table(tmp_warehouse, [("v", IntType())],
                  {"fields.v.aggregate-function": "primary_key"})
    commit(t, [{"k": 1, "v": 10}])
    commit(t, [{"k": 1, "v": 99}])
    assert t.to_arrow().to_pylist()[0]["v"] == 10


def test_ignore_retract_sum(tmp_warehouse):
    from paimon_tpu.types import RowKind
    t = agg_table(tmp_warehouse, [("a", IntType()), ("b", IntType())],
                  {"fields.a.aggregate-function": "sum",
                   "fields.b.aggregate-function": "sum",
                   "fields.b.ignore-retract": "true"})
    commit(t, [{"k": 1, "a": 10, "b": 10}])
    commit(t, [{"k": 1, "a": 3, "b": 3}],
           kinds=[RowKind.UPDATE_BEFORE])
    commit(t, [{"k": 1, "a": 1, "b": 1}])
    row = t.to_arrow().to_pylist()[0]
    assert row["a"] == 8          # 10 - 3 + 1
    assert row["b"] == 11         # retract ignored: 10 + 1


def test_ignore_retract_all_retract_is_null(tmp_warehouse):
    from paimon_tpu.types import RowKind
    t = agg_table(tmp_warehouse, [("b", IntType())],
                  {"fields.b.aggregate-function": "sum",
                   "fields.b.ignore-retract": "true"})
    commit(t, [{"k": 1, "b": 5}], kinds=[RowKind.UPDATE_BEFORE])
    rows = t.to_arrow().to_pylist()
    assert rows == [] or rows[0]["b"] is None


def test_nested_update_bad_key_raises(tmp_warehouse):
    from paimon_tpu.types import DataField
    row_t = RowType([DataField(100, "x", IntType())])
    t = agg_table(
        tmp_warehouse, [("vs", ArrayType(row_t))],
        {"fields.vs.aggregate-function": "nested_update",
         "fields.vs.nested-key": "xx"})
    commit(t, [{"k": 1, "vs": [{"x": 1}]}])
    commit(t, [{"k": 1, "vs": [{"x": 2}]}])
    with pytest.raises(ValueError, match="nested-key"):
        t.to_arrow()
