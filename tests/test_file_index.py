"""Per-file bloom filter indexes: build, serialize, scan skip.

reference: fileindex/bloomfilter/, io/DataFileIndexWriter.java,
io/FileIndexEvaluator.java.
"""

import os

import numpy as np
import pyarrow as pa
import pytest

from paimon_tpu import predicate as P
from paimon_tpu.index.bloom import (
    BloomFilter, build_file_index, hash_column, hash_value,
    read_file_index,
)
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, VarCharType


def test_bloom_roundtrip_and_fpp():
    rng = np.random.default_rng(3)
    vals = rng.integers(0, 1 << 40, 10_000)
    col = pa.chunked_array([pa.array(vals, pa.int64())])
    hashes = hash_column(col)
    bf = BloomFilter.build(hashes, fpp=0.01)
    bf2 = BloomFilter.deserialize(bf.serialize())
    # no false negatives
    for h in hashes[:200]:
        assert bf2.might_contain(int(h))
    # false-positive rate near target
    probe = hash_column(pa.chunked_array(
        [pa.array(rng.integers(1 << 41, 1 << 42, 2000), pa.int64())]))
    fp = sum(bf2.might_contain(int(h)) for h in probe)
    assert fp < 2000 * 0.05


def test_bloom_string_column():
    col = pa.chunked_array([pa.array(["alpha", "beta", None, "gamma"])])
    bf = BloomFilter.build(hash_column(col))
    assert bf.might_contain(hash_value("beta", pa.string()))
    assert not bf.might_contain(hash_value("nope-nope-nope", pa.string()))


def test_file_index_blob_roundtrip():
    t = pa.table({"a": pa.array([1, 2, 3], pa.int64()),
                  "b": pa.array(["x", "y", "z"])})
    blob = build_file_index(t, ["a", "b"])
    idx = read_file_index(blob)
    assert set(idx) == {"a", "b"}
    assert idx["a"].might_contain(hash_value(2, pa.int64()))
    assert not idx["a"].might_contain(hash_value(99, pa.int64()))


def _commit(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    wb.new_commit().commit(w.prepare_commit())
    w.close()


def test_scan_skips_files_via_bloom(tmp_warehouse):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("name", VarCharType())
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true",
                        "file-index.bloom-filter.columns": "id,name"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "t"), schema)
    _commit(table, [{"id": i, "name": f"n{i}", "v": float(i)}
                    for i in range(0, 100)])
    _commit(table, [{"id": i, "name": f"n{i}", "v": float(i)}
                    for i in range(1000, 1100)])

    # embedded index present in the manifests
    snap = table.snapshot_manager.latest_snapshot()
    entries = table.new_scan().read_entries(snap)
    assert all(e.file.embedded_index for e in entries)

    # equality on a value absent from file 1 -> only file 2 planned
    rb = table.new_read_builder().with_filter(P.equal("id", 1050))
    plan = rb.new_scan().plan()
    assert sum(len(s.data_files) for s in plan.splits) == 1
    assert rb.new_read().to_arrow(plan).to_pylist() == \
        [{"id": 1050, "name": "n1050", "v": 1050.0}]

    # value-column equality on a PK table: per-file pruning would be
    # merge-unsafe, so the whole bucket reads (both files) but the bloom
    # still prunes the bucket entirely when NO file can match
    rb2 = table.new_read_builder().with_filter(P.equal("name", "n42"))
    plan2 = rb2.new_scan().plan()
    assert sum(len(s.data_files) for s in plan2.splits) == 2
    assert rb2.new_read().to_arrow(plan2).column("id").to_pylist() == [42]
    rb2b = table.new_read_builder().with_filter(P.equal("name", "absent"))
    assert rb2b.new_scan().plan().splits == []

    # no-match key equality prunes everything
    rb3 = table.new_read_builder().with_filter(P.equal("id", 555))
    assert rb3.new_scan().plan().splits == []


def test_bloom_survives_compaction(tmp_warehouse):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true",
                        "file-index.bloom-filter.columns": "id"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "c"), schema)
    _commit(table, [{"id": 1, "v": 1.0}])
    _commit(table, [{"id": 2, "v": 2.0}])
    table.compact(full=True)
    snap = table.snapshot_manager.latest_snapshot()
    entries = table.new_scan().read_entries(snap)
    assert all(e.file.embedded_index for e in entries)
    rb = table.new_read_builder().with_filter(P.equal("id", 2))
    assert rb.new_read().to_arrow(rb.new_scan().plan()) \
        .column("v").to_pylist() == [2.0]


def test_value_filter_never_drops_newer_versions(tmp_warehouse):
    """Merge-safety regression: a value filter matching only an OLD
    version of a key must not resurrect it by pruning the newer file."""
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("name", VarCharType())
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true",
                        "file-index.bloom-filter.columns": "name"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "v"), schema)
    _commit(table, [{"id": 1, "name": "old"}])
    _commit(table, [{"id": 1, "name": "new"}])
    rb = table.new_read_builder().with_filter(P.equal("name", "old"))
    out = rb.new_read().to_arrow(rb.new_scan().plan())
    assert out.num_rows == 0        # id=1 is now 'new'; 'old' must NOT appear


def test_bloom_sidecar_above_threshold(tmp_warehouse):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true",
                        "file-index.bloom-filter.columns": "id",
                        "file-index.in-manifest-threshold": "64"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "s"), schema)
    _commit(table, [{"id": i, "v": float(i)} for i in range(5000)])
    snap = table.snapshot_manager.latest_snapshot()
    entries = table.new_scan().read_entries(snap)
    assert all(e.file.embedded_index is None for e in entries)
    assert all(any(x.endswith(".index") for x in e.file.extra_files)
               for e in entries)
    rb = table.new_read_builder().with_filter(P.equal("id", 99999))
    assert rb.new_scan().plan().splits == []     # sidecar consulted
