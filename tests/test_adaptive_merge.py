"""Link-adaptive merge path selection + packed winners-only output."""

import numpy as np
import pytest

from paimon_tpu.ops import merge as M


def _mk(n, dupes=2, seed=0):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, max(n // dupes, 1), n).astype(np.uint32)
    lanes = np.stack([keys, np.zeros(n, np.uint32)], axis=1)
    seq = np.arange(n, dtype=np.int64)
    return lanes, seq


class TestCostModel:
    def test_wide_link_prefers_device(self, monkeypatch):
        monkeypatch.setattr(M, "_LINK_BW", (8e9, 8e9))   # PCIe-ish
        assert M._device_path_pays(4_000_000, 2, True, True)

    def test_tunnel_link_prefers_host(self, monkeypatch):
        # 5M rows pad to 8M: the padded transfer over a slow d2h link
        # loses to the host fast path
        monkeypatch.setattr(M, "_LINK_BW", (900e6, 8e6))  # the tunnel
        assert not M._device_path_pays(5_000_000, 2, True, True)
        # the full 9-byte/row output on the tunnel loses even unpadded
        assert not M._device_path_pays(4_000_000, 2, False, True)

    def test_tunnel_full_path_vs_slow_host_is_marginal_device(self,
                                                              monkeypatch):
        # the 9-byte/row full path on the tunnel against the SLOW
        # general host sort: modeled device 4.7s vs host 5.7s at 4M
        # rows — device by a hair; pins the crossover direction
        monkeypatch.setattr(M, "_LINK_BW", (900e6, 8e6))
        assert M._device_path_pays(4_000_000, 6, False, False)


class TestPackedDevicePath:
    def test_packed_matches_host(self, monkeypatch):
        monkeypatch.setenv("PAIMON_FORCE_DEVICE_SORT", "1")
        lanes, seq = _mk(5000)
        perm_d, win_d, prev_d = M.device_sorted_winners(
            lanes, seq, "last", winners_only=True)
        monkeypatch.setenv("PAIMON_FORCE_HOST_SORT", "1")
        monkeypatch.delenv("PAIMON_FORCE_DEVICE_SORT")
        perm_h, win_h, _ = M.device_sorted_winners(
            lanes, seq, "last", winners_only=True)
        # same winner sets (device is padded, host unpadded)
        dw = set(perm_d[win_d[: len(perm_d)]].tolist())
        hw = set(perm_h[win_h].tolist())
        assert dw == hw
        assert (prev_d == -1).all()            # winners_only contract

    def test_packed_first_row(self, monkeypatch):
        monkeypatch.setenv("PAIMON_FORCE_DEVICE_SORT", "1")
        lanes, seq = _mk(3000, seed=3)
        perm, win, _ = M.device_sorted_winners(
            lanes, seq, "first", winners_only=True)
        winners = perm[win[: len(perm)]]
        keys = lanes[:, 0]
        # each winner is the FIRST arrival of its key
        for w in winners[:100]:
            k = keys[w]
            assert w == np.flatnonzero(keys == k).min()


class TestForceHost:
    def test_force_host_on_any_backend(self, monkeypatch):
        monkeypatch.setenv("PAIMON_FORCE_HOST_SORT", "1")
        lanes, seq = _mk(2000)
        perm, win, prev = M.device_sorted_winners(lanes, seq, "last")
        assert len(perm) == 2000               # unpadded => host path
        assert win.sum() == len(np.unique(lanes[:, 0]))
