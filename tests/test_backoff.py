"""The shared capped decorrelated-jitter backoff (utils/backoff.py) and
its three consumers: RetryingObjectStoreBackend's max-elapsed budget,
FileStoreCommit's CAS retry wait, and the mesh bucket ladder (covered
end-to-end in test_mesh_fault_tolerance.py).
"""

import random

import pytest

from paimon_tpu.utils.backoff import Backoff


class FakeClock:
    def __init__(self):
        self.t = 0.0
        self.sleeps = []

    def clock(self):
        return self.t

    def sleep(self, s):
        self.sleeps.append(s)
        self.t += s


def test_decorrelated_jitter_bounds():
    b = Backoff(10.0, cap_ms=10_000.0, rng=random.Random(42))
    prev = b.next_ms()
    assert prev == 10.0                       # first wait = base
    for _ in range(50):
        nxt = b.next_ms()
        assert 10.0 <= nxt <= max(10.0, 3.0 * prev)
        assert nxt <= 10_000.0
        prev = nxt


def test_jitter_spreads_waits():
    """Two concurrent retriers draw different schedules — the whole
    point of decorrelated jitter vs exponential lockstep."""
    a = Backoff(10.0, rng=random.Random(1))
    b = Backoff(10.0, rng=random.Random(2))
    sched_a = [a.next_ms() for _ in range(8)]
    sched_b = [b.next_ms() for _ in range(8)]
    assert sched_a != sched_b


def test_cap_bounds_tail():
    b = Backoff(100.0, cap_ms=150.0, rng=random.Random(7))
    waits = [b.next_ms() for _ in range(20)]
    assert max(waits) <= 150.0
    # default cap = 32x base
    assert Backoff(10.0).cap_ms == 320.0
    # cap below base is clamped up, not inverted
    assert Backoff(100.0, cap_ms=1.0).cap_ms == 100.0


def test_zero_base_never_sleeps():
    fc = FakeClock()
    b = Backoff(0.0, sleep=fc.sleep, clock=fc.clock)
    for _ in range(5):
        assert b.pause() is True
    assert fc.sleeps == []
    assert b.attempts == 5


def test_max_elapsed_budget_stops():
    fc = FakeClock()
    b = Backoff(1000.0, cap_ms=1000.0, max_elapsed_ms=2500.0,
                rng=random.Random(3), sleep=fc.sleep, clock=fc.clock)
    pauses = 0
    while b.pause():
        pauses += 1
        assert pauses < 100
    assert b.budget_exhausted()
    # never slept past the budget's end
    assert fc.t * 1000.0 <= 2500.0 + 1e-6
    assert pauses >= 2


def test_budget_without_start_is_fresh():
    b = Backoff(10.0, max_elapsed_ms=100.0)
    assert b.elapsed_ms() == 0.0
    assert not b.budget_exhausted()


# -- RetryingObjectStoreBackend budget ---------------------------------------


def _flaky_stack(tmp_path, fail_rate, seed=0, **retry_kw):
    from paimon_tpu.fs.object_store import (
        FlakyObjectStoreBackend, LocalObjectStoreBackend,
        RetryingObjectStoreBackend,
    )
    inner = LocalObjectStoreBackend(str(tmp_path / "store"))
    flaky = FlakyObjectStoreBackend(inner, seed=seed,
                                    fail_rate=fail_rate)
    return RetryingObjectStoreBackend(flaky, **retry_kw), flaky


def test_object_store_retries_through_storm(tmp_path):
    retry, flaky = _flaky_stack(tmp_path, fail_rate=0.5, seed=11,
                                max_attempts=20, backoff_s=0.0)
    for i in range(20):
        retry.put(f"k{i}", b"v")
        assert retry.get(f"k{i}") == b"v"
    assert flaky.stats["injected"] > 0


def test_object_store_max_elapsed_budget(tmp_path):
    from paimon_tpu.fs.object_store import TransientStoreError
    retry, _ = _flaky_stack(tmp_path, fail_rate=1.0, seed=5,
                            max_attempts=10 ** 6, backoff_s=0.0,
                            max_elapsed_s=0.0)
    with pytest.raises(TransientStoreError, match="retry budget"):
        retry.get("missing")
    with pytest.raises(TransientStoreError, match="retry budget"):
        retry.put("k", b"v")


def test_object_store_attempts_cap_still_applies(tmp_path):
    from paimon_tpu.fs.object_store import TransientStoreError
    retry, flaky = _flaky_stack(tmp_path, fail_rate=1.0, seed=5,
                                max_attempts=3, backoff_s=0.0)
    with pytest.raises(TransientStoreError, match="attempts exhausted"):
        retry.get("missing")
    assert flaky.stats["injected"] == 3


def test_object_store_jittered_backoff_deterministic_rng(tmp_path,
                                                         monkeypatch):
    import paimon_tpu.utils.backoff as bo

    slept = []

    class RecordingBackoff(Backoff):
        def __init__(self, *a, **kw):
            kw["sleep"] = slept.append
            super().__init__(*a, **kw)

    monkeypatch.setattr(bo, "Backoff", RecordingBackoff)
    retry, _ = _flaky_stack(tmp_path, fail_rate=1.0, seed=5,
                            max_attempts=4, backoff_s=0.005,
                            backoff_cap_s=0.01,
                            rng=random.Random(9))
    from paimon_tpu.fs.object_store import TransientStoreError
    with pytest.raises(TransientStoreError):
        retry.get("missing")
    # 4 attempts -> 3 waits: the terminal failure raises immediately
    # instead of sleeping a wait no retry will ever use
    assert len(slept) == 3
    assert all(0.005 <= s <= 0.01 + 1e-9 for s in slept)


# -- FileStoreCommit's retry wait uses the shared budget ---------------------


def test_commit_retry_bounded_by_timeout(tmp_path):
    """commit.timeout caps total CAS-retry stall even when
    commit.max-retries would allow (effectively) unbounded attempts."""
    import time

    from paimon_tpu.core.commit import (
        CommitConflictError, FileStoreCommit,
    )
    from paimon_tpu.schema import Schema
    from paimon_tpu.table import FileStoreTable
    from paimon_tpu.types import BigIntType

    schema = (Schema.builder().column("id", BigIntType(False))
              .primary_key("id")
              .options({"bucket": "1",
                        "commit.max-retries": "1000000",
                        "commit.min-retry-wait": "5 ms",
                        "commit.max-retry-wait": "10 ms",
                        "commit.timeout": "80 ms"}).build())
    table = FileStoreTable.create(str(tmp_path / "t"), schema)

    commit = FileStoreCommit(table.file_io, table.path, table.schema,
                             table.options)
    # a racer that always wins: every CAS attempt loses
    commit.snapshot_manager.try_commit = lambda snapshot: False
    t0 = time.monotonic()
    with pytest.raises(CommitConflictError, match="commit.timeout"):
        commit._try_commit([], [], 0, "APPEND")
    assert time.monotonic() - t0 < 5.0         # budget, not max-retries
