"""IVF-SQ8 scalar quantization + host HNSW graph.

reference: paimon-vector IvfHnswSqVectorGlobalIndexerFactory.java /
IvfHnswFlatVectorGlobalIndexerFactory.java (the SQ + HNSW halves of
the native vector index plane).
"""

import numpy as np
import pytest

from paimon_tpu.vector.ann import (
    BruteForceIndex, HNSWIndex, IVFSQIndex, PersistedVectorIndex,
)
from tests.test_ivfpq import clustered, recall_at_k


class TestIVFSQ:
    def test_recall(self):
        v, rng = clustered(20_000, 64)
        q = v[rng.integers(0, len(v), 32)] \
            + 0.01 * rng.normal(size=(32, 64)).astype(np.float32)
        exact = BruteForceIndex(v, metric="l2").search(q, 10)[1]
        idx = IVFSQIndex(v, metric="l2", keep_vectors=False)
        got = idx.search(q, 10, nprobe=12)[1]
        r = recall_at_k(got, exact, 10)
        # SQ8 residuals lose far less than PQ: high recall without
        # refine
        assert r >= 0.9, f"recall@10 = {r}"

    def test_compression_4x(self):
        v, _ = clustered(8_000, 64)
        idx = IVFSQIndex(v, keep_vectors=False)
        assert idx.memory_bytes() < v.nbytes / 3.5

    def test_refine_rerank(self):
        v, rng = clustered(10_000, 32)
        q = v[rng.integers(0, len(v), 16)]
        exact = BruteForceIndex(v, metric="l2").search(q, 5)[1]
        idx = IVFSQIndex(v, metric="l2")
        got = idx.search(q, 5, nprobe=10, refine=50)[1]
        assert recall_at_k(got, exact, 5) >= 0.95

    def test_cosine(self):
        v, rng = clustered(5_000, 32)
        q = v[rng.integers(0, len(v), 8)]
        exact = BruteForceIndex(v, metric="cosine").search(q, 5)[1]
        idx = IVFSQIndex(v, metric="cosine")
        got = idx.search(q, 5, nprobe=10)[1]
        assert recall_at_k(got, exact, 5) >= 0.85

    def test_state_round_trip(self):
        v, rng = clustered(3_000, 32)
        idx = IVFSQIndex(v, keep_vectors=False)
        meta, arrays = idx.state()
        assert meta["kind"] == "ivfsq"
        back = IVFSQIndex.from_state(meta, arrays)
        q = v[:4]
        a = idx.search(q, 5, nprobe=6)
        b = back.search(q, 5, nprobe=6)
        assert np.array_equal(a[1], b[1])
        assert np.allclose(a[0], b[0])


class TestHNSW:
    def test_recall(self):
        v, rng = clustered(5_000, 32)
        q = v[rng.integers(0, len(v), 20)] \
            + 0.01 * rng.normal(size=(20, 32)).astype(np.float32)
        exact = BruteForceIndex(v, metric="l2").search(q, 10)[1]
        idx = HNSWIndex(v, m=16, ef_construction=80, metric="l2")
        got = idx.search(q, 10, ef=80)[1]
        r = recall_at_k(got, exact, 10)
        assert r >= 0.9, f"recall@10 = {r}"

    def test_exact_hit_on_members(self):
        # well-separated corpus (clustered() can contain near-duplicate
        # points where the top-1 is a legitimate tie)
        rng = np.random.default_rng(3)
        v = rng.normal(size=(2_000, 16)).astype(np.float32)
        idx = HNSWIndex(v, metric="l2")
        scores, ids = idx.search(v[:8], 1, ef=40)
        assert (ids[:, 0] == np.arange(8)).all(), (ids[:, 0], scores)

    def test_state_round_trip(self):
        v, rng = clustered(1_500, 16)
        idx = HNSWIndex(v, metric="l2")
        meta, arrays = idx.state()
        back = HNSWIndex.from_state(meta, arrays)
        q = v[rng.integers(0, len(v), 8)]
        a = idx.search(q, 5, ef=50)
        b = back.search(q, 5, ef=50)
        assert np.array_equal(a[1], b[1])


class TestPersistedKinds:
    @pytest.mark.parametrize("kind", ["ivfsq", "hnsw"])
    def test_build_persist_load(self, tmp_path, kind):
        from tests.test_ivfpq import TestPersistedVectorIndex
        t, v = TestPersistedVectorIndex()._table(tmp_path, n=1_500,
                                                 d=16)
        p = PersistedVectorIndex(t, "emb")
        built = p.build(kind=kind, metric="l2")
        loaded = p.load()
        assert loaded is not None
        assert type(loaded) is type(built)
        q = v[:4]
        kw = {"nprobe": 8} if kind == "ivfsq" else {"ef": 50}
        a = built.search(q, 5, **kw)
        b = loaded.search(q, 5, **kw)
        assert np.array_equal(a[1], b[1])


class TestMetricEdges:
    def test_hnsw_rejects_dot(self):
        v, _ = clustered(100, 8)
        with pytest.raises(ValueError, match="l2/cosine"):
            HNSWIndex(v, metric="dot")

    def test_ivfsq_dot_refine_ranks_by_dot(self):
        rng = np.random.default_rng(9)
        # varying norms make dot != l2 ordering
        v = (rng.normal(size=(4_000, 16))
             * rng.uniform(0.1, 5.0, size=(4_000, 1))) \
            .astype(np.float32)
        q = rng.normal(size=(8, 16)).astype(np.float32)
        exact = BruteForceIndex(v, metric="dot").search(q, 5)[1]
        idx = IVFSQIndex(v, metric="dot")
        got = idx.search(q, 5, nprobe=20, refine=400)[1]
        assert recall_at_k(got, exact, 5) >= 0.8
