"""Partition mark-done actions + streaming trigger.

reference: partition/actions/* (SuccessFileMarkDoneAction writes a
key-compatible `_SUCCESS` JSON, AddDonePartitionAction registers
'<partition>.done'), flink/sink/listener/PartitionMarkDoneTrigger.java
(idle-time + partition-time-interval + end-input semantics),
flink/procedure/MarkPartitionDoneProcedure.java.
"""

import json
import os

import pytest

from paimon_tpu.maintenance.mark_done import (
    AddDonePartitionAction, PartitionMarkDoneTrigger, SuccessFile,
)
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, VarCharType


def _make(tmp_warehouse, opts=None):
    options = {"bucket": "1", "write-only": "true"}
    options.update(opts or {})
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .column("dt", VarCharType(nullable=False))
              .partition_keys("dt")
              .primary_key("id", "dt")
              .options(options).build())
    return FileStoreTable.create(os.path.join(tmp_warehouse, "t"), schema)


def _commit(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    wb.new_commit().commit(w.prepare_commit())
    w.close()


def test_success_file_marker(tmp_warehouse):
    t = _make(tmp_warehouse)
    _commit(t, [{"id": 1, "v": 1.0, "dt": "2026-07-01"}])
    marked = t.mark_partitions_done(["dt=2026-07-01"])
    assert marked == ["dt=2026-07-01"]
    path = os.path.join(t.path, "dt=2026-07-01", "_SUCCESS")
    assert os.path.exists(path)
    sf = SuccessFile.from_json(open(path).read())
    assert sf.creation_time == sf.modification_time > 0
    # re-mark: creationTime survives, modificationTime advances
    first_created = sf.creation_time
    t.mark_partitions_done([("2026-07-01",)])   # tuple form
    sf2 = SuccessFile.from_json(open(path).read())
    assert sf2.creation_time == first_created
    assert sf2.modification_time >= sf.modification_time
    # wire shape: reference SuccessFile.java JSON keys
    d = json.loads(open(path).read())
    assert set(d) == {"creationTime", "modificationTime"}


def test_done_partition_and_event_actions(tmp_warehouse):
    t = _make(tmp_warehouse, {
        "partition.mark-done-action":
            "success-file,done-partition,mark-event"})
    _commit(t, [{"id": 1, "v": 1.0, "dt": "2026-07-01"},
                {"id": 2, "v": 2.0, "dt": "2026-07-02"}])
    t.mark_partitions_done([{"dt": "2026-07-01"}, "dt=2026-07-02"])
    reg = AddDonePartitionAction(t.file_io, t.path)
    assert reg.done_partitions() == ["dt=2026-07-01.done",
                                     "dt=2026-07-02.done"]
    # idempotent registration
    t.mark_partitions_done(["dt=2026-07-01"])
    assert reg.done_partitions().count("dt=2026-07-01.done") == 1
    from paimon_tpu.maintenance.mark_done import MarkPartitionDoneEventAction
    events = MarkPartitionDoneEventAction(t.file_io, t.path).events()
    assert sorted(e["partition"] for e in events) == [
        "dt=2026-07-01", "dt=2026-07-01", "dt=2026-07-02"]
    assert all(e["event"] == "partition.done" for e in events)


def test_unpartitioned_rejected(tmp_warehouse):
    schema = (Schema.builder().column("id", BigIntType(False))
              .column("v", DoubleType()).primary_key("id")
              .options({"bucket": "1"}).build())
    t = FileStoreTable.create(os.path.join(tmp_warehouse, "u"), schema)
    with pytest.raises(ValueError, match="not partitioned"):
        t.mark_partitions_done(["dt=x"])


def test_trigger_idle_time_semantics(tmp_warehouse):
    t = _make(tmp_warehouse, {
        "partition.idle-time-to-done": "15 min",
        "partition.time-interval": "1 d"})
    trig = PartitionMarkDoneTrigger(t)
    day = 24 * 3600 * 1000
    import datetime
    start = int(datetime.datetime(2026, 7, 1).timestamp() * 1000)
    trig.notify("dt=2026-07-01", now_ms=start + day // 2)
    # partition day not over: effective time = start + interval
    assert trig.done_partitions(now_ms=start + day) == []
    # 10 min past the day boundary: still inside idle window
    assert trig.done_partitions(now_ms=start + day + 10 * 60000) == []
    # 16 min past: done, and removed from pending
    assert trig.done_partitions(
        now_ms=start + day + 16 * 60000) == ["dt=2026-07-01"]
    assert trig.done_partitions(now_ms=start + 2 * day) == []
    # late write AFTER the day: idle clock runs from last update
    trig.notify("dt=2026-07-01", now_ms=start + 2 * day)
    assert trig.done_partitions(now_ms=start + 2 * day + 14 * 60000) == []
    assert trig.done_partitions(
        now_ms=start + 2 * day + 16 * 60000) == ["dt=2026-07-01"]


def test_trigger_end_input_and_state(tmp_warehouse):
    t = _make(tmp_warehouse, {
        "partition.mark-done-when-end-input": "true"})
    trig = PartitionMarkDoneTrigger(t)
    trig.notify(("2026-07-01",))
    trig.notify("dt=2026-07-02")
    # checkpoint/restore round-trip
    state = trig.snapshot()
    trig2 = PartitionMarkDoneTrigger(t)
    trig2.restore(state)
    done = trig2.mark(end_input=True)
    assert sorted(done) == ["dt=2026-07-01", "dt=2026-07-02"]
    assert os.path.exists(os.path.join(t.path, "dt=2026-07-01", "_SUCCESS"))
    assert trig2.done_partitions(end_input=True) == []


def test_traversal_rejected(tmp_warehouse):
    """SQL-reachable partition strings must not escape the table dir."""
    t = _make(tmp_warehouse)
    with pytest.raises(ValueError, match="escapes"):
        t.mark_partitions_done(["../../evil"])


def test_trigger_misconfig_rejected(tmp_warehouse):
    """idle-time without time-interval would silently never mark."""
    t = _make(tmp_warehouse, {"partition.idle-time-to-done": "15 min"})
    with pytest.raises(ValueError, match="must be set together"):
        PartitionMarkDoneTrigger(t)


def test_trigger_skips_unparseable_partition(tmp_warehouse):
    t = _make(tmp_warehouse, {
        "partition.idle-time-to-done": "1 s",
        "partition.time-interval": "1 s"})
    trig = PartitionMarkDoneTrigger(t)
    trig.notify("dt=not-a-date", now_ms=0)
    assert trig.done_partitions(now_ms=10 ** 12) == []
    assert trig.snapshot() == []
