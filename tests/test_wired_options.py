"""Behavior tests for the round-3 wired CoreOptions: commit retry
bounds, empty-commit handling, sequence sort order, plan partition
sorting, partition expiration cap."""

import numpy as np
import pyarrow as pa
import pytest

from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, IntType, VarCharType


def _pk_table(path, extra_opts=None):
    opts = {"bucket": "1"}
    opts.update(extra_opts or {})
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("seq", IntType())
              .column("v", DoubleType())
              .primary_key("id")
              .options(opts)
              .build())
    return FileStoreTable.create(str(path), schema)


def _write(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    sid = wb.new_commit().commit(w.prepare_commit())
    w.close()
    return sid


class TestSequenceSortOrder:
    def test_descending_smaller_sequence_wins(self, tmp_path):
        t = _pk_table(tmp_path / "t", {
            "sequence.field": "seq",
            "sequence.field.sort-order": "descending"})
        _write(t, [{"id": 1, "seq": 5, "v": 5.0}])
        _write(t, [{"id": 1, "seq": 3, "v": 3.0}])   # smaller -> wins
        _write(t, [{"id": 1, "seq": 9, "v": 9.0}])   # larger -> loses
        assert t.to_arrow().to_pylist() == \
            [{"id": 1, "seq": 3, "v": 3.0}]
        # survives compaction too
        t.compact(full=True)
        assert t.to_arrow().to_pylist() == \
            [{"id": 1, "seq": 3, "v": 3.0}]

    def test_ascending_default_unchanged(self, tmp_path):
        t = _pk_table(tmp_path / "t", {"sequence.field": "seq"})
        _write(t, [{"id": 1, "seq": 5, "v": 5.0}])
        _write(t, [{"id": 1, "seq": 3, "v": 3.0}])
        assert t.to_arrow().to_pylist() == \
            [{"id": 1, "seq": 5, "v": 5.0}]

    def test_descending_null_still_loses(self, tmp_path):
        t = _pk_table(tmp_path / "t", {
            "sequence.field": "seq",
            "sequence.field.sort-order": "descending"})
        _write(t, [{"id": 1, "seq": 7, "v": 7.0}])
        _write(t, [{"id": 1, "seq": None, "v": 0.0}])
        assert t.to_arrow().to_pylist() == \
            [{"id": 1, "seq": 7, "v": 7.0}]


class TestEmptyCommit:
    def test_empty_batch_commit_skipped(self, tmp_path):
        t = _pk_table(tmp_path / "t")
        _write(t, [{"id": 1, "seq": 1, "v": 1.0}])
        before = t.latest_snapshot().id
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        sid = wb.new_commit().commit(w.prepare_commit())
        assert sid is None
        assert t.latest_snapshot().id == before

    def test_forced_empty_commit(self, tmp_path):
        t = _pk_table(tmp_path / "t",
                      {"snapshot.ignore-empty-commit": "false"})
        _write(t, [{"id": 1, "seq": 1, "v": 1.0}])
        before = t.latest_snapshot().id
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        sid = wb.new_commit().commit(w.prepare_commit())
        assert sid == before + 1


class TestCommitRetries:
    def test_max_retries_bounds_cas_race(self, tmp_path, monkeypatch):
        from paimon_tpu.core.commit import CommitConflictError
        t = _pk_table(tmp_path / "t", {"commit.max-retries": "2",
                                       "commit.min-retry-wait": "1",
                                       "commit.max-retry-wait": "2"})
        _write(t, [{"id": 1, "seq": 1, "v": 1.0}])
        # a snapshot manager that always loses the CAS
        from paimon_tpu.snapshot import SnapshotManager
        monkeypatch.setattr(SnapshotManager, "try_commit",
                            lambda self, snap: False)
        with pytest.raises(CommitConflictError, match="max-retries"):
            _write(t, [{"id": 2, "seq": 1, "v": 2.0}])


class TestPlanSortPartition:
    def test_splits_sorted_by_partition(self, tmp_path):
        schema = (Schema.builder()
                  .column("p", VarCharType(10, False))
                  .column("v", BigIntType())
                  .partition_keys("p")
                  .options({"bucket": "1", "bucket-key": "v",
                            "scan.plan-sort-partition": "true"})
                  .build())
        t = FileStoreTable.create(str(tmp_path / "t"), schema)
        for part in ["zz", "aa", "mm"]:
            wb = t.new_batch_write_builder()
            w = wb.new_write()
            w.write_dicts([{"p": part, "v": 1}])
            wb.new_commit().commit(w.prepare_commit())
            w.close()
        splits = t.new_read_builder().new_scan().plan().splits
        parts = [s.partition[0] for s in splits]
        assert parts == sorted(parts)


class TestStreamingWiredOptions:
    def test_consumer_ignore_progress(self, tmp_path):
        t = _pk_table(tmp_path / "t", {"consumer-id": "c1"})
        _write(t, [{"id": 1, "seq": 1, "v": 1.0}])
        scan = t.new_read_builder().new_stream_scan()
        p1 = scan.plan()
        scan.notify_checkpoint_complete(scan.checkpoint())
        _write(t, [{"id": 2, "seq": 1, "v": 2.0}])
        # a fresh scan resumes past snapshot 1...
        scan2 = t.new_read_builder().new_stream_scan()
        p2 = scan2.plan()
        assert p2.snapshot_id == 2 and not p2.splits == p1.splits
        # ...unless consumer.ignore-progress starts it fresh
        t3 = t.copy({"consumer.ignore-progress": "true"})
        scan3 = t3.new_read_builder().new_stream_scan()
        p3 = scan3.plan()
        assert p3.snapshot_id == 2 and len(p3.splits) > 0
        read = t3.new_read_builder().new_read()
        import pyarrow as pa
        full = pa.concat_tables([read.read_split(s) for s in p3.splits],
                                promote_options="none")
        assert full.num_rows == 2          # full load, not just delta

    def test_bounded_watermark_ends_stream(self, tmp_path):
        t = _pk_table(tmp_path / "t",
                      {"scan.bounded.watermark": "1000"})
        wb = t.new_stream_write_builder()
        w = wb.new_write()
        w.write_dicts([{"id": 1, "seq": 1, "v": 1.0}])
        wb.new_commit().commit(w.prepare_commit(), commit_identifier=1,
                               watermark=500)
        scan = t.new_read_builder().new_stream_scan()
        assert scan.plan() is not None          # initial full load
        w2 = wb.new_write()
        w2.write_dicts([{"id": 2, "seq": 1, "v": 2.0}])
        wb.new_commit().commit(w2.prepare_commit(), commit_identifier=2,
                               watermark=2000)       # past the bound
        assert scan.plan() is None              # stream ended
        assert scan.plan() is None

    def test_streaming_read_overwrite(self, tmp_path):
        t = _pk_table(tmp_path / "t")
        _write(t, [{"id": 1, "seq": 1, "v": 1.0}])
        scan = t.new_read_builder().new_stream_scan()
        scan.plan()
        wb = t.new_batch_write_builder().with_overwrite()
        w = wb.new_write()
        w.write_dicts([{"id": 9, "seq": 1, "v": 9.0}])
        wb.new_commit().commit(w.prepare_commit())
        # default: overwrite snapshots are skipped
        plan = scan.plan()
        assert plan is not None and plan.splits == []
        # with the flag: the overwrite's delta is read
        t2 = t.copy({"streaming-read-overwrite": "true"})
        scan2 = t2.new_read_builder().new_stream_scan()
        scan2.plan()
        scan2.restore(2)
        plan2 = scan2.plan()
        assert plan2 is not None and len(plan2.splits) > 0


class TestSplitBinning:
    def test_append_bucket_bins_by_target_size(self, tmp_path):
        schema = (Schema.builder()
                  .column("v", BigIntType())
                  .options({"bucket": "-1",
                            "source.split.target-size": "1kb",
                            "source.split.open-file-cost": "16b"})
                  .build())
        t = FileStoreTable.create(str(tmp_path / "t"), schema)
        for _ in range(6):          # six small files in one bucket
            wb = t.new_batch_write_builder()
            w = wb.new_write()
            w.write_dicts([{"v": i} for i in range(50)])
            wb.new_commit().commit(w.prepare_commit())
            w.close()
        splits = t.new_read_builder().new_scan().plan().splits
        assert len(splits) > 1          # binned, not one giant split
        total = sum(sum(f.row_count for f in s.data_files)
                    for s in splits)
        assert total == 300
        assert t.to_arrow().num_rows == 300

    def test_pk_bucket_never_bins(self, tmp_path):
        t = _pk_table(tmp_path / "t",
                      {"source.split.target-size": "1kb",
                       "source.split.open-file-cost": "16b",
                       "write-only": "true"})
        for i in range(4):
            _write(t, [{"id": i, "seq": 1, "v": 1.0}])
        splits = t.new_read_builder().new_scan().plan().splits
        assert len(splits) == 1          # merge needs the whole bucket


class TestCompactionWiredOptions:
    def test_total_size_threshold_full_compacts(self, tmp_path):
        t = _pk_table(tmp_path / "t",
                      {"write-only": "true",
                       "compaction.total-size-threshold": "10mb"})
        for i in range(2):          # only 2 runs: below run trigger
            _write(t, [{"id": i, "seq": 1, "v": 1.0}])
        sid = t.compact()           # not full — strategy picks anyway
        assert sid is not None
        splits = t.new_read_builder().new_scan().plan().splits
        assert len(splits[0].data_files) == 1

    def test_file_num_limit_forces_pick(self, tmp_path):
        t = _pk_table(tmp_path / "t",
                      {"write-only": "true",
                       "compaction.total-size-threshold": "0",
                       "compaction.file-num-limit": "3"})
        for i in range(3):
            _write(t, [{"id": i, "seq": 1, "v": 1.0}])
        assert t.compact() is not None


class TestChangelogFileOptions:
    def test_changelog_format_and_prefix(self, tmp_path):
        t = _pk_table(tmp_path / "t",
                      {"changelog-producer": "input",
                       "changelog-file.format": "avro",
                       "changelog-file.prefix": "cl-"})
        wb = t.new_stream_write_builder()
        w = wb.new_write()
        w.write_dicts([{"id": 1, "seq": 1, "v": 1.0}])
        wb.new_commit().commit(w.prepare_commit(), commit_identifier=1)
        import os
        found = []
        for root, _, names in os.walk(str(tmp_path / "t")):
            found += [n for n in names if n.startswith("cl-")]
        assert found and all(n.endswith(".avro") for n in found)
        # changelog stream decodes the avro files
        t2 = t.copy({"scan.mode": "from-snapshot-full",
                     "scan.snapshot-id": "1"})
        scan = t2.new_read_builder().new_stream_scan()
        plan = scan.plan()
        assert plan is not None


class TestPartitionExpireCap:
    def test_expiration_max_num(self, tmp_path):
        schema = (Schema.builder()
                  .column("dt", VarCharType(10, False))
                  .column("v", BigIntType())
                  .partition_keys("dt")
                  .options({"bucket": "1", "bucket-key": "v",
                            "partition.expiration-time": "1 d",
                            "partition.expiration-max-num": "2"})
                  .build())
        t = FileStoreTable.create(str(tmp_path / "t"), schema)
        for day in ["2000-01-01", "2000-01-02", "2000-01-03",
                    "2000-01-04"]:
            wb = t.new_batch_write_builder()
            w = wb.new_write()
            w.write_dicts([{"dt": day, "v": 1}])
            wb.new_commit().commit(w.prepare_commit())
            w.close()
        expired = t.expire_partitions()
        assert len(expired) == 2                     # capped
        # oldest two went first
        assert sorted(e[0] for e in expired) == \
            ["2000-01-01", "2000-01-02"]
        remaining = set(
            np.asarray(t.to_arrow().column("dt")).tolist())
        assert remaining == {"2000-01-03", "2000-01-04"}


class TestParquetFormatOptions:
    def test_enable_dictionary_off(self, tmp_path):
        """parquet.enable.dictionary=false reaches the parquet writer
        (reference: format options forwarded to FileFormat factories)."""
        import pyarrow.parquet as pq

        t = _pk_table(tmp_path / "t", {
            "parquet.enable.dictionary": "false"})
        _write(t, [{"id": i, "seq": 1, "v": 1.0} for i in range(10)])
        t2 = _pk_table(tmp_path / "t2")
        _write(t2, [{"id": i, "seq": 1, "v": 1.0} for i in range(10)])

        def dict_encoded(table):
            split = table.new_read_builder().new_scan().plan().splits[0]
            f = split.data_files[0]
            path = (f"{table.path}/bucket-0/{f.file_name}")
            md = pq.ParquetFile(path).metadata
            col = md.row_group(0).column(0)
            return "PLAIN_DICTIONARY" in str(col.encodings) or \
                "RLE_DICTIONARY" in str(col.encodings)

        assert not dict_encoded(t)
        assert dict_encoded(t2)       # default stays dictionary-on


class TestCompressionCodecs:
    @pytest.mark.parametrize("fmt,codec", [
        ("parquet", "lz4"), ("parquet", "snappy"), ("parquet", "zstd"),
        ("orc", "lz4"), ("orc", "snappy")])
    def test_file_compression_codecs(self, tmp_path, fmt, codec):
        """file.compression codecs beyond zstd round-trip per format
        (reference compression/: lz4, zstd, aircompressor snappy)."""
        t = _pk_table(tmp_path / f"{fmt}_{codec}", {
            "file.format": fmt, "file.compression": codec})
        _write(t, [{"id": i, "seq": 1, "v": float(i)} for i in range(50)])
        out = t.to_arrow()
        assert out.num_rows == 50
        if fmt == "parquet":
            import pyarrow.parquet as pq
            f = (t.new_read_builder().new_scan().plan()
                 .splits[0].data_files[0])
            md = pq.ParquetFile(
                f"{t.path}/bucket-0/{f.file_name}").metadata
            assert md.row_group(0).column(0).compression == codec.upper()


class TestMaintenanceOptions:
    def test_clean_empty_directories(self, tmp_path):
        """snapshot.clean-empty-directories removes emptied partition
        dirs after expire (reference SnapshotDeletion)."""
        from paimon_tpu.schema import Schema
        schema = (Schema.builder()
                  .column("dt", VarCharType(nullable=False))
                  .column("v", IntType())
                  .partition_keys("dt")
                  .options({"bucket": "1", "bucket-key": "v",
                            "snapshot.num-retained.min": "1",
                            "snapshot.num-retained.max": "1",
                            "snapshot.clean-empty-directories": "true"})
                  .build())
        t = FileStoreTable.create(str(tmp_path / "t"), schema)
        _write(t, [{"dt": "a", "v": 1}])
        # overwrite the partition away, then expire the old snapshot
        wb = t.new_batch_write_builder().with_overwrite({"dt": "a"})
        w = wb.new_write()
        wb.new_commit().commit(w.prepare_commit())
        w.close()
        _write(t, [{"dt": "b", "v": 2}])
        t.expire_snapshots()
        import os
        assert not os.path.exists(os.path.join(str(t.path), "dt=a"))
        assert os.path.exists(os.path.join(str(t.path), "dt=b"))

    def test_delete_file_threads_and_manifest_parallelism(self, tmp_path):
        """delete-file.thread-num + scan.manifest.parallelism produce
        the same results as the serial paths."""
        t = _pk_table(tmp_path / "t", {
            "delete-file.thread-num": "4",
            "scan.manifest.parallelism": "4",
            "snapshot.num-retained.min": "1",
            "snapshot.num-retained.max": "1"})
        for i in range(4):
            _write(t, [{"id": j, "seq": i, "v": float(i)}
                       for j in range(20)])
        t.compact(full=True)
        res = t.expire_snapshots()
        assert res.deleted_data_files > 0
        rows = {r["id"]: r["v"] for r in t.to_arrow().to_pylist()}
        assert len(rows) == 20 and rows[0] == 3.0


def _spill_dirs():
    import glob
    import os
    import tempfile
    return set(glob.glob(os.path.join(tempfile.gettempdir(),
                                      "paimon-spill-*")))


class TestSpillableWriteBuffer:
    @pytest.fixture(autouse=True)
    def _snapshot_tmp(self):
        self._before = _spill_dirs()

    def _write_many(self, t, batches=6, per=500):
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        for b in range(batches):
            w.write_dicts([{"id": (b * per + i) % 1500, "seq": b,
                            "v": float(b)} for i in range(per)])
        wb.new_commit().commit(w.prepare_commit())
        w.close()

    def test_spillable_merges_to_fewer_l0_files(self, tmp_path):
        """write-buffer-spillable: spilled runs merge into one L0 write
        at prepare-commit instead of one file per buffer-full
        (reference SortBufferWriteBuffer spill + MergeSorter)."""
        common = {"write-buffer-size": "40kb", "write-only": "true"}
        t_plain = _pk_table(tmp_path / "plain", common)
        t_spill = _pk_table(tmp_path / "spill", {
            **common, "write-buffer-spillable": "true"})
        for t in (t_plain, t_spill):
            self._write_many(t)

        def l0_files(t):
            split = t.new_read_builder().new_scan().plan().splits[0]
            return [f for f in split.data_files if f.level == 0]

        plain, spill = l0_files(t_plain), l0_files(t_spill)
        assert len(plain) > 1              # small buffer => many flushes
        assert len(spill) < len(plain)     # merged at prepare-commit
        # bit-identical read-back between the two paths
        a = {r["id"]: (r["seq"], r["v"])
             for r in t_plain.to_arrow().to_pylist()}
        b = {r["id"]: (r["seq"], r["v"])
             for r in t_spill.to_arrow().to_pylist()}
        assert a == b and len(a) == 1500
        # no NEW spill temp dirs survive (delta-based: other runs may
        # have left stale dirs in the shared tmp)
        assert _spill_dirs() == self._before

    def test_spillable_aggregation_engine(self, tmp_path):
        """Deferred-merge engines keep every row through the spill."""
        from paimon_tpu.schema import Schema
        schema = (Schema.builder()
                  .column("id", BigIntType(False))
                  .column("total", BigIntType())
                  .primary_key("id")
                  .options({"bucket": "1", "write-only": "true",
                            "write-buffer-size": "10kb",
                            "write-buffer-spillable": "true",
                            "merge-engine": "aggregation",
                            "fields.total.aggregate-function": "sum"})
                  .build())
        t = FileStoreTable.create(str(tmp_path / "agg"), schema)
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        for b in range(5):
            w.write_dicts([{"id": i, "total": 1} for i in range(300)])
        wb.new_commit().commit(w.prepare_commit())
        w.close()
        rows = {r["id"]: r["total"] for r in t.to_arrow().to_pylist()}
        assert len(rows) == 300 and all(v == 5 for v in rows.values())

    def test_spillable_with_input_changelog(self, tmp_path):
        """changelog-producer=input still records EVERY arrival through
        the spill path (one changelog row per written row)."""
        t = _pk_table(tmp_path / "cl", {
            "write-buffer-size": "10kb",
            "write-buffer-spillable": "true",
            "changelog-producer": "input"})
        self._write_many(t, batches=3, per=400)
        snap = t.snapshot_manager.latest_snapshot()
        plan = t.new_scan().plan_changelog(snap)
        total = sum(f.row_count for s in plan.splits
                    for f in s.data_files)
        assert total == 3 * 400

    def test_spill_dirs_cleaned_on_abort(self, tmp_path):
        """close() without prepare_commit removes spill temp dirs.
        Serial flush path: the mid-write spill-exists precondition is
        deterministic only inline — the pipelined abort-cleanup twin
        lives in test_write_pipeline.py."""
        t = _pk_table(tmp_path / "abort", {
            "write-buffer-size": "10kb",
            "write-buffer-spillable": "true",
            "write.flush.parallelism": "1"})
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        for b in range(4):
            w.write_dicts([{"id": i, "seq": b, "v": 1.0}
                           for i in range(400)])
        assert _spill_dirs() - self._before   # spills exist mid-write
        w.close()                     # abort: no prepare_commit
        assert _spill_dirs() == self._before
