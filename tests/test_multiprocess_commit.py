"""Cross-process commit contention: real OS processes race the
rename-CAS snapshot publish.

reference intent: FileStoreCommitImpl's optimistic retry under
concurrent committers (tryCommit loop :756) — here exercised by
actual concurrent processes, not injected races.
"""

import os
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax; jax.config.update("jax_platforms", "cpu")
from paimon_tpu.table import FileStoreTable

path, worker_id, n_commits = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
t = FileStoreTable.load(path)
for i in range(n_commits):
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts([{"id": worker_id * 1000 + i,
                    "v": float(worker_id)}])
    sid = wb.new_commit().commit(w.prepare_commit())
    assert sid is not None
    w.close()
print("worker", worker_id, "done")
"""


@pytest.mark.parametrize("workers,commits", [(4, 5)])
def test_concurrent_processes_commit(tmp_path, workers, commits):
    from paimon_tpu.schema import Schema
    from paimon_tpu.table import FileStoreTable
    from paimon_tpu.types import BigIntType, DoubleType

    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "2", "write-only": "true"})
              .build())
    path = str(tmp_path / "t")
    FileStoreTable.create(path, schema)

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WORKER, path, str(w), str(commits)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        for w in range(workers)]
    for p in procs:
        out, err = p.communicate(timeout=300)
        assert p.returncode == 0, err.decode()[-2000:]

    t = FileStoreTable.load(path)
    # every commit won a distinct snapshot; no write was lost
    assert t.latest_snapshot().id == workers * commits
    rows = t.to_arrow().to_pylist()
    assert len(rows) == workers * commits
    expected = {w * 1000 + i for w in range(workers)
                for i in range(commits)}
    assert {r["id"] for r in rows} == expected
