"""Z-order clustering + metrics + csv/json formats."""

import os

import numpy as np
import pyarrow as pa
import pytest

from paimon_tpu import predicate as P
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, IntType


def _commit(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    wb.new_commit().commit(w.prepare_commit())
    w.close()


def test_z_index_locality():
    from paimon_tpu.ops.zorder import z_index

    t = pa.table({"x": pa.array([0, 0, 7, 7], pa.int64()),
                  "y": pa.array([0, 7, 0, 7], pa.int64())})
    z = z_index(t, ["x", "y"])
    # (0,0) must be smallest; (7,7) largest
    assert int(np.argmin(z)) == 0
    assert int(np.argmax(z)) == 3


def test_sort_compact_zorder_improves_pruning(tmp_warehouse):
    schema = (Schema.builder()
              .column("x", BigIntType())
              .column("y", BigIntType())
              .column("v", DoubleType())
              .options({"target-file-size": "4kb"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "t"), schema)
    rng = np.random.default_rng(0)
    rows = [{"x": int(a), "y": int(b), "v": 1.0}
            for a, b in rng.integers(0, 1000, (12000, 2))]
    _commit(table, rows)
    before = table.to_arrow()
    sid = table.sort_compact(["x", "y"])
    assert sid is not None
    after = table.to_arrow()
    assert after.num_rows == before.num_rows
    # same multiset of rows
    key = lambda r: (r["x"], r["y"], r["v"])
    assert sorted(map(key, before.to_pylist())) == \
        sorted(map(key, after.to_pylist()))
    # stats-based pruning on x now skips most files
    splits = table.new_read_builder() \
        .with_filter(P.less_than("x", 50)).new_scan().plan().splits
    files_hit = sum(len(s.data_files) for s in splits)
    total = sum(len(s.data_files)
                for s in table.new_read_builder().new_scan().plan().splits)
    assert total > 3
    assert files_hit < total


def test_sort_compact_rejected_on_pk_table(tmp_warehouse):
    schema = (Schema.builder().column("id", BigIntType(False))
              .column("v", DoubleType()).primary_key("id")
              .options({"bucket": "1"}).build())
    t = FileStoreTable.create(os.path.join(tmp_warehouse, "p"), schema)
    with pytest.raises(ValueError):
        t.sort_compact(["v"])


def test_metrics_registry():
    from paimon_tpu.metrics import MetricRegistry

    reg = MetricRegistry()
    g = reg.commit_metrics("t1")
    g.counter("commits").inc()
    g.counter("commits").inc(2)
    with g.timer("commit_duration_ms"):
        pass
    snap = reg.snapshot()
    assert snap["commit:t1"]["commits"] == 3
    assert snap["commit:t1"]["commit_duration_ms"]["count"] == 1


def test_csv_json_formats(tmp_path):
    from paimon_tpu.format import get_format
    from paimon_tpu.fs import get_file_io

    fio = get_file_io(str(tmp_path))
    t = pa.table({"a": pa.array([1, 2], pa.int64()),
                  "b": pa.array(["x", "y"])})
    for fmt_name in ("csv", "json"):
        fmt = get_format(fmt_name)
        path = os.path.join(str(tmp_path), f"f.{fmt_name}")
        fmt.create_writer().write(fio, path, t)
        back = fmt.create_reader().read(fio, path)
        assert back.column("a").to_pylist() == [1, 2]
        assert back.column("b").to_pylist() == ["x", "y"]


def test_sort_compact_preserves_deletes(tmp_warehouse):
    """DV rows must stay deleted through a sort-compact rewrite."""
    schema = (Schema.builder().column("x", BigIntType())
              .column("y", BigIntType()).build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "dv"),
                                  schema)
    _commit(table, [{"x": i, "y": i} for i in range(20)])
    table.delete_where(P.less_than("x", 5))
    assert table.to_arrow().num_rows == 15
    table.sort_compact(["x"])
    out = sorted(table.to_arrow().column("x").to_pylist())
    assert out == list(range(5, 20))


def test_append_compact_preserves_deletes(tmp_warehouse):
    schema = (Schema.builder().column("x", BigIntType()).build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "dc"),
                                  schema)
    for i in range(6):
        _commit(table, [{"x": i}])
    table.delete_where(P.equal("x", 2))
    table.compact(full=True)
    assert sorted(table.to_arrow().column("x").to_pylist()) == \
        [0, 1, 3, 4, 5]
    # DV index rewritten away (rows physically dropped)
    snap = table.snapshot_manager.latest_snapshot()
    if snap.index_manifest:
        entries = table.new_scan().index_manifest_file.read(
            snap.index_manifest)
        assert not [e for e in entries
                    if e.index_file.index_type == "DELETION_VECTORS"]


def test_vector_search_batch_queries(tmp_warehouse):
    from paimon_tpu.types import ArrayType, FloatType
    from paimon_tpu.vector import vector_search

    schema = (Schema.builder().column("id", BigIntType(False))
              .column("emb", ArrayType(FloatType()))
              .primary_key("id").options({"bucket": "1"}).build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "vb"),
                                  schema)
    embs = np.random.default_rng(5).standard_normal((30, 8)) \
        .astype(np.float32)
    _commit(table, [{"id": i, "emb": embs[i].tolist()}
                    for i in range(30)])
    out = vector_search(table, "emb", embs[[3, 9]], k=2)
    assert out.num_rows == 4
    by_q = {q: [] for q in (0, 1)}
    for r in out.to_pylist():
        by_q[r["_query"]].append(r["id"])
    assert by_q[0][0] == 3 and by_q[1][0] == 9


def test_hilbert_curve_properties():
    """Adjacent Hilbert indexes must be adjacent points (unit steps) —
    the property that makes it cluster better than z-order."""
    from paimon_tpu.ops.zorder import hilbert_index

    n = 16
    pts = [(x, y) for x in range(n) for y in range(n)]
    t = pa.table({"x": pa.array([p[0] for p in pts], pa.int64()),
                  "y": pa.array([p[1] for p in pts], pa.int64())})
    h = hilbert_index(t, ["x", "y"])
    order = np.argsort(h)
    walked = [pts[i] for i in order]
    # every consecutive pair of curve points is one grid step apart
    steps = [abs(a[0] - b[0]) + abs(a[1] - b[1])
             for a, b in zip(walked, walked[1:])]
    assert all(s == 1 for s in steps)
    assert len(set(h.tolist())) == n * n     # bijective on the grid


def test_sort_compact_hilbert(tmp_warehouse):
    schema = (Schema.builder()
              .column("x", BigIntType())
              .column("y", BigIntType())
              .options({"target-file-size": "4kb"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "h"),
                                  schema)
    rng = np.random.default_rng(1)
    _commit(table, [{"x": int(a), "y": int(b)}
                    for a, b in rng.integers(0, 500, (8000, 2))])
    before = sorted(map(lambda r: (r["x"], r["y"]),
                        table.to_arrow().to_pylist()))
    assert table.sort_compact(["x", "y"], strategy="hilbert") is not None
    after = sorted(map(lambda r: (r["x"], r["y"]),
                       table.to_arrow().to_pylist()))
    assert after == before


def test_hilbert_single_column(tmp_warehouse):
    from paimon_tpu.ops.zorder import hilbert_index

    t = pa.table({"x": pa.array([5, 1, 9], pa.int64())})
    h = hilbert_index(t, ["x"])
    assert np.argsort(h).tolist() == [1, 0, 2]   # order-preserving in 1D
