"""SST lookup files + bounded LookupStore + SST-backed
LocalTableQuery.

reference: sst/SstFileReader.java, lookup/sort/
SortLookupStoreFactory.java, mergetree/LookupLevels.java (disk-size
eviction), table/query/LocalTableQuery.java.
"""

import os

import numpy as np
import pyarrow as pa
import pytest

from paimon_tpu.lookup.sst import (
    BlockCache, LookupStore, SstReader, SstWriter, pack_lanes,
)


def make_sorted(n, num_lanes=2, seed=0):
    rng = np.random.default_rng(seed)
    lanes = rng.integers(0, 1 << 32, (n, num_lanes), dtype=np.uint64) \
        .astype(np.uint32)
    order = np.argsort(pack_lanes(lanes), kind="stable")
    lanes = lanes[order]
    t = pa.table({"v": pa.array(np.arange(n), pa.int64())})
    return lanes, t


class TestSstFile:
    def test_write_probe_round_trip(self, tmp_path):
        lanes, t = make_sorted(10_000)
        path = str(tmp_path / "f.sst")
        SstWriter(block_rows=512).write(path, lanes, t)
        r = SstReader(path, BlockCache())
        # probe every 97th key + some misses
        q_idx = np.arange(0, 10_000, 97)
        queries = lanes[q_idx]
        miss = np.full((5, lanes.shape[1]), 0xFFFFFFFF, np.uint32)
        q = np.concatenate([queries, miss])
        hit_pos, rows = r.probe(q)
        assert set(hit_pos.tolist()) == set(range(len(q_idx)))
        got = dict(zip(hit_pos.tolist(),
                       rows.column("v").to_pylist()))
        for i, qi in enumerate(q_idx):
            assert got[i] == int(t.column("v")[qi].as_py())

    def test_probe_only_touches_needed_blocks(self, tmp_path):
        lanes, t = make_sorted(8192)
        path = str(tmp_path / "f.sst")
        SstWriter(block_rows=256).write(path, lanes, t)
        cache = BlockCache()
        r = SstReader(path, cache)
        r.probe(lanes[:1])
        assert len(cache._lru) <= 2      # one block (plus none extra)

    def test_block_cache_bounded(self, tmp_path):
        lanes, t = make_sorted(50_000)
        path = str(tmp_path / "f.sst")
        SstWriter(block_rows=256).write(path, lanes, t)
        cache = BlockCache(max_bytes=64 << 10)
        r = SstReader(path, cache)
        r.probe(lanes[::37])             # touch many blocks
        assert cache._bytes <= 2 * (64 << 10)

    def test_empty_table(self, tmp_path):
        lanes = np.zeros((0, 2), np.uint32)
        t = pa.table({"v": pa.array([], pa.int64())})
        path = str(tmp_path / "e.sst")
        SstWriter().write(path, lanes, t)
        r = SstReader(path, BlockCache())
        hit, rows = r.probe(np.zeros((3, 2), np.uint32))
        assert len(hit) == 0 and rows is None


class TestLookupStore:
    def test_disk_budget_evicts_lru(self, tmp_path):
        store = LookupStore(str(tmp_path / "cache"),
                            max_disk_bytes=200_000,
                            block_cache=BlockCache())
        for i in range(6):
            lanes, t = make_sorted(5000, seed=i)
            store.put(f"b{i}", lanes, t)
        on_disk = os.listdir(str(tmp_path / "cache"))
        total = sum(os.path.getsize(os.path.join(
            str(tmp_path / "cache"), f)) for f in on_disk)
        assert total <= 300_000          # within ~1 file of budget
        assert store.get("b5") is not None   # newest survives
        assert store.get("b0") is None       # oldest evicted

    def test_replace_same_key_drops_old(self, tmp_path):
        store = LookupStore(str(tmp_path / "c"),
                            block_cache=BlockCache())
        lanes, t = make_sorted(100)
        store.put("k", lanes, t)
        store.put("k", lanes, t)
        assert len(store._readers) == 1


class TestLocalQuerySstBacked:
    def _table(self, tmp_path, n=500, buckets=2):
        from paimon_tpu.schema import Schema
        from paimon_tpu.table import FileStoreTable
        from paimon_tpu.types import BigIntType, VarCharType

        schema = (Schema.builder()
                  .column("id", BigIntType(False))
                  .column("name", VarCharType.string_type())
                  .primary_key("id")
                  .options({"bucket": str(buckets),
                            "write-only": "true"})
                  .build())
        t = FileStoreTable.create(str(tmp_path / "t"), schema)
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        w.write_dicts([{"id": i, "name": f"n{i}"} for i in range(n)])
        wb.new_commit().commit(w.prepare_commit())
        w.close()
        return t

    def test_lookup_hits_and_misses(self, tmp_path):
        from paimon_tpu.lookup import LocalTableQuery
        t = self._table(tmp_path)
        q = LocalTableQuery(t, cache_dir=str(tmp_path / "cache"))
        out = q.lookup([{"id": 3}, {"id": 499}, {"id": 10_000}])
        assert out[0] == {"id": 3, "name": "n3"}
        assert out[1] == {"id": 499, "name": "n499"}
        assert out[2] is None
        # state actually spilled to disk
        assert any(f.endswith(".sst")
                   for f in os.listdir(str(tmp_path / "cache")))

    def test_snapshot_change_invalidates(self, tmp_path):
        from paimon_tpu.lookup import LocalTableQuery
        t = self._table(tmp_path, n=50)
        q = LocalTableQuery(t, cache_dir=str(tmp_path / "cache"))
        assert q.lookup_row({"id": 7})["name"] == "n7"
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        w.write_dicts([{"id": 7, "name": "updated"}])
        wb.new_commit().commit(w.prepare_commit())
        w.close()
        assert q.lookup_row({"id": 7})["name"] == "updated"

    def test_string_pk_long_keys(self, tmp_path):
        from paimon_tpu.lookup import LocalTableQuery
        from paimon_tpu.schema import Schema
        from paimon_tpu.table import FileStoreTable
        from paimon_tpu.types import IntType, VarCharType

        schema = (Schema.builder()
                  .column("k", VarCharType.string_type(False))
                  .column("v", IntType())
                  .primary_key("k")
                  .options({"bucket": "1", "write-only": "true"})
                  .build())
        t = FileStoreTable.create(str(tmp_path / "t"), schema)
        prefix = "x" * 40                # beyond the lane prefix
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        w.write_dicts([{"k": prefix + "a", "v": 1},
                       {"k": prefix + "b", "v": 2}])
        wb.new_commit().commit(w.prepare_commit())
        w.close()
        q = LocalTableQuery(t, cache_dir=str(tmp_path / "cache"))
        assert q.lookup_row({"k": prefix + "b"})["v"] == 2
        assert q.lookup_row({"k": prefix + "zzz"}) is None
