"""CAS-retry correctness: overwrite delete-set recomputation and
per-attempt manifest cleanup (ADVICE round-1 fixes)."""

import os

import pytest

from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType


def _make_table(tmp_warehouse):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true"})
              .build())
    return FileStoreTable.create(os.path.join(tmp_warehouse, "t"), schema)


def _commit_rows(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    sid = wb.new_commit().commit(w.prepare_commit())
    w.close()
    return sid


def test_overwrite_recomputes_deletes_on_retry(tmp_warehouse):
    """A file committed concurrently between overwrite planning and CAS
    publish must still be deleted by the overwrite."""
    table = _make_table(tmp_warehouse)
    _commit_rows(table, [{"id": 1, "v": 1.0}])

    wb = table.new_batch_write_builder().with_overwrite()
    w = wb.new_write()
    w.write_dicts([{"id": 100, "v": 100.0}])
    messages = w.prepare_commit()
    commit = wb.new_commit()

    # interleave: another committer lands a row, and the overwrite's first
    # CAS attempt loses
    sm = commit._commit.snapshot_manager
    real_try = sm.try_commit
    state = {"interfered": False}

    def flaky_try(snapshot):
        if not state["interfered"]:
            state["interfered"] = True
            _commit_rows(table, [{"id": 2, "v": 2.0}])
            return False
        return real_try(snapshot)

    sm.try_commit = flaky_try
    commit.commit(messages)
    sm.try_commit = real_try

    rows = sorted(table.to_arrow().to_pylist(), key=lambda r: r["id"])
    assert rows == [{"id": 100, "v": 100.0}], rows


def test_retry_cleans_up_attempt_manifests(tmp_warehouse):
    """A lost CAS attempt must not leak its per-attempt manifest lists."""
    table = _make_table(tmp_warehouse)
    _commit_rows(table, [{"id": 1, "v": 1.0}])

    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts([{"id": 3, "v": 3.0}])
    messages = w.prepare_commit()
    commit = wb.new_commit()

    sm = commit._commit.snapshot_manager
    real_try = sm.try_commit
    state = {"n": 0}

    def flaky_try(snapshot):
        state["n"] += 1
        if state["n"] == 1:
            _commit_rows(table, [{"id": 2, "v": 2.0}])
            return False
        return real_try(snapshot)

    sm.try_commit = flaky_try
    commit.commit(messages)
    sm.try_commit = real_try

    # every manifest list on disk must be referenced by some snapshot
    mdir = os.path.join(table.path, "manifest")
    referenced = set()
    for snap in table.snapshot_manager.snapshots():
        referenced.add(snap.base_manifest_list)
        referenced.add(snap.delta_manifest_list)
        if snap.changelog_manifest_list:
            referenced.add(snap.changelog_manifest_list)
    on_disk = {f for f in os.listdir(mdir)
               if f.startswith("manifest-list-")}
    orphans = on_disk - referenced
    assert not orphans, orphans
