"""Fault-injection FileIO (reference test utility
utils/FailingFileIO.java:44: throws on the Nth operation per named
counter) + open-stream tracking in the spirit of TraceableFileIO.

Extensions over the reference:
- every mutating op is recorded in a per-name OP TRACE
  (`FailingFileIO.ops(name)` -> [OpRecord(op, path, index, killed)])
  so crash-point sweeps can report exactly which operation was killed;
- `copy`, `delete_quietly` and two-phase commit/discard are
  intercepted too (they bypass write_bytes/delete in the base FileIO);
- `reset(name, fail_after, fail_times=None)` can limit how many ops
  fail before the counter auto-disarms (models a transient 503 storm
  that passes, for retry/fallback testing) — the default None fails
  every op until `disarm`, modeling a hard crash.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

from paimon_tpu.fs.fileio import (
    FileIO, TwoPhaseCommitter, TwoPhaseOutputStream,
)


class InjectedIOError(IOError):
    pass


@dataclass
class OpRecord:
    op: str
    path: str
    index: int
    killed: bool = False


class FailingFileIO(FileIO):
    """Delegates to an inner FileIO, failing the Nth write/delete/rename
    per named counter and tracing every mutating op."""

    _counters: Dict[str, int] = {}
    _fail_left: Dict[str, Optional[int]] = {}
    _traces: Dict[str, List[OpRecord]] = {}
    _lock = threading.Lock()

    def __init__(self, inner: FileIO, name: str):
        self.inner = inner
        self.name = name

    @classmethod
    def reset(cls, name: str, fail_after: int,
              fail_times: Optional[int] = None):
        """Fail every mutating op once `fail_after` of them succeeded.
        `fail_times` bounds how many ops fail before auto-disarm
        (None = fail forever until `disarm`)."""
        with cls._lock:
            cls._counters[name] = fail_after
            cls._fail_left[name] = fail_times
            cls._traces[name] = []

    @classmethod
    def disarm(cls, name: str):
        with cls._lock:
            cls._counters.pop(name, None)
            cls._fail_left.pop(name, None)

    @classmethod
    def ops(cls, name: str) -> List[OpRecord]:
        """The mutating-op trace since the last reset()."""
        with cls._lock:
            return list(cls._traces.get(name, []))

    def _tick(self, op: str, path: str):
        with self._lock:
            trace = self._traces.setdefault(self.name, [])
            remaining = self._counters.get(self.name)
            kill = remaining is not None and remaining <= 0
            rec = OpRecord(op, path, len(trace), killed=kill)
            trace.append(rec)
            if remaining is None:
                return
            if kill:
                left = self._fail_left.get(self.name)
                if left is not None:
                    left -= 1
                    if left <= 0:
                        self._counters.pop(self.name, None)
                        self._fail_left.pop(self.name, None)
                    else:
                        self._fail_left[self.name] = left
                raise InjectedIOError(
                    f"injected failure ({self.name}) at op "
                    f"#{rec.index}: {op} {path}")
            self._counters[self.name] = remaining - 1

    # -- mutating ops fail by counter ---------------------------------------

    def write_bytes(self, path, data, overwrite=True):
        self._tick("write_bytes", path)
        return self.inner.write_bytes(path, data, overwrite=overwrite)

    def try_to_write_atomic(self, path, data):
        self._tick("try_to_write_atomic", path)
        return self.inner.try_to_write_atomic(path, data)

    def delete(self, path, recursive=False):
        self._tick("delete", path)
        return self.inner.delete(path, recursive=recursive)

    def delete_quietly(self, path):
        # NOT quiet under injection: a kill here models the process
        # dying mid-delete, which swallowing would hide from the sweep
        self._tick("delete_quietly", path)
        return self.inner.delete_quietly(path)

    def rename(self, src, dst):
        self._tick("rename", src)
        return self.inner.rename(src, dst)

    def copy(self, src, dst, overwrite=True):
        self._tick("copy", dst)
        return self.inner.copy(src, dst, overwrite=overwrite)

    def new_two_phase_stream(self, path) -> TwoPhaseOutputStream:
        outer = self
        stream = self.inner.new_two_phase_stream(path)

        class S(TwoPhaseOutputStream):
            def write(self, data):
                stream.write(data)

            def close_for_commit(self) -> TwoPhaseCommitter:
                # close() is where the staged bytes upload: killable so
                # crash sweeps can die mid-upload, and the injected
                # error carries the destination path like the fs layer
                outer._tick("two_phase.close", path)
                committer = stream.close_for_commit()

                class C(TwoPhaseCommitter):
                    def commit(self_c):
                        outer._tick("two_phase.commit", path)
                        committer.commit()

                    def discard(self_c):
                        outer._tick("two_phase.discard", path)
                        committer.discard()

                return C()

        return S()

    def mkdirs(self, path):
        return self.inner.mkdirs(path)

    # -- reads delegate ------------------------------------------------------

    def read_bytes(self, path):
        return self.inner.read_bytes(path)

    def read_range(self, path, offset, length):
        return self.inner.read_range(path, offset, length)

    def exists(self, path):
        return self.inner.exists(path)

    def get_file_size(self, path):
        return self.inner.get_file_size(path)

    def list_status(self, path):
        return self.inner.list_status(path)

    def is_object_store(self):
        return self.inner.is_object_store()
