"""Fault-injection FileIO (reference test utility
utils/FailingFileIO.java:44: throws on the Nth operation per named
counter) + open-stream tracking in the spirit of TraceableFileIO."""

from __future__ import annotations

import threading
from typing import Dict

from paimon_tpu.fs.fileio import FileIO


class InjectedIOError(IOError):
    pass


class FailingFileIO(FileIO):
    """Delegates to an inner FileIO, failing the Nth write/delete/rename
    per named counter."""

    _counters: Dict[str, int] = {}
    _lock = threading.Lock()

    def __init__(self, inner: FileIO, name: str):
        self.inner = inner
        self.name = name

    @classmethod
    def reset(cls, name: str, fail_after: int):
        """Fail every mutating op once `fail_after` of them succeeded."""
        with cls._lock:
            cls._counters[name] = fail_after

    @classmethod
    def disarm(cls, name: str):
        with cls._lock:
            cls._counters.pop(name, None)

    def _tick(self):
        with self._lock:
            remaining = self._counters.get(self.name)
            if remaining is None:
                return
            if remaining <= 0:
                raise InjectedIOError(
                    f"injected failure ({self.name})")
            self._counters[self.name] = remaining - 1

    # -- mutating ops fail by counter ---------------------------------------

    def write_bytes(self, path, data, overwrite=True):
        self._tick()
        return self.inner.write_bytes(path, data, overwrite=overwrite)

    def try_to_write_atomic(self, path, data):
        self._tick()
        return self.inner.try_to_write_atomic(path, data)

    def delete(self, path, recursive=False):
        self._tick()
        return self.inner.delete(path, recursive=recursive)

    def rename(self, src, dst):
        self._tick()
        return self.inner.rename(src, dst)

    def mkdirs(self, path):
        return self.inner.mkdirs(path)

    # -- reads delegate ------------------------------------------------------

    def read_bytes(self, path):
        return self.inner.read_bytes(path)

    def read_range(self, path, offset, length):
        return self.inner.read_range(path, offset, length)

    def exists(self, path):
        return self.inner.exists(path)

    def get_file_size(self, path):
        return self.inner.get_file_size(path)

    def list_status(self, path):
        return self.inner.list_status(path)

    def is_object_store(self):
        return self.inner.is_object_store()
