"""Fleet observability end to end (ISSUE 20 acceptance layer).

Cross-process trace propagation: a REAL 2-process gloo maintenance
soak and a router + 2-subprocess-replica serving rig each spool their
spans to a shared `trace.export.dir`; the parent stitches ONE Perfetto
file with obs/merge.py and PARSES it — per-process tracks, spans, and
flow arrows across every process boundary (store-carried
`trace.context` links for the soak, X-Parent-Span serving hops for the
rig).

Black-box flight recorder: an injected stream-daemon loop crash dumps
the ring (triggering event + the operational events recorded BEFORE
it), and `paimon table debug-bundle` round-trips the same ring through
the CLI.  A SIGTERM'd daemon subprocess leaves both its trace spool
and a flight dump behind (the signal handler flushes BEFORE draining).

SLO plane: an injected 504 storm flips the multi-window burn-rate
alert — visible at the replica's /slo, the router's fleet aggregate,
and the `slo` Prometheus group — and a healthy loadgen run recovers
it.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from paimon_tpu.obs import flight
from paimon_tpu.obs.merge import export_merged, read_spools
from paimon_tpu.obs.trace import (
    disable_tracing, enable_tracing, reset_spool, set_export_dir,
    set_replica_id, spool_flush, take_spans,
)
from paimon_tpu.schema import Schema
from paimon_tpu.service import KvQueryClient, KvQueryServer, ReplicaRouter
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType

from tests.test_multihost_maintenance import _PROLOG, _run_workers

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _obs_reset():
    yield
    disable_tracing()
    set_export_dir(None)
    set_replica_id(None)
    take_spans(clear=True)
    reset_spool()
    rec = flight.recorder()
    rec.clear()
    rec.dump_dir = None
    rec.enabled = True


def _child_env():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    return env


# -- merged-trace parsing (the acceptance bar: a test that PARSES the
# export, not one that trusts the stats dict) --------------------------------

def _load_merged(path):
    """(procs, spans, flows): procs maps chrome pid -> process label;
    spans are the "X" events; flows are resolved (s_event, f_event)
    pairs joined on the flow id."""
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"]
    procs = {e["pid"]: e["args"]["name"] for e in events
             if e.get("ph") == "M" and e.get("name") == "process_name"}
    spans = [e for e in events if e.get("ph") == "X"]
    starts, ends = {}, {}
    for e in events:
        if e.get("cat") != "flow":
            continue
        (starts if e["ph"] == "s" else ends)[e["id"]] = e
    flows = [(starts[i], ends[i]) for i in sorted(starts) if i in ends]
    return procs, spans, flows


def _os_pid_of(procs):
    """chrome pid -> OS pid parsed from the 'host/pid [replica]'
    process_name label."""
    return {p: int(name.split("/", 1)[1].split(" ")[0])
            for p, name in procs.items()}


# -- leg 1a: gloo soak, store-carried context --------------------------------

_OBS_SOAK_WORKER = _PROLOG + r'''
import time
from multihost_soak import SOAK_TABLE_OPTIONS, gen_events
from paimon_tpu.cdc.source import MemoryCdcSource
from paimon_tpu.obs.trace import spool_flush
from paimon_tpu.parallel.maintenance_plane import MaintenancePlane
from paimon_tpu.service.stream_daemon import StreamDaemon

N_TOTAL = int(sys.argv[6])
KILL_AFTER = int(sys.argv[7])        # victim dies past this offset
SPOOL = sys.argv[8]
TICK_S = 0.02
PER_TICK = 6

opts = dict(SOAK_TABLE_OPTIONS)
opts["trace.enabled"] = "true"
opts["trace.export.dir"] = SPOOL
t = shared_table(opts)

plane = MaintenancePlane(t, base_user="stream-daemon")
source = MemoryCdcSource()
daemon = StreamDaemon(t, source, commit_user="stream-daemon",
                      plane=plane).start()

def drain():
    while daemon.poll_changelog(timeout=0.0):
        pass

emitted = 0
while emitted < N_TOTAL:
    source.append(*gen_events(emitted, emitted + PER_TICK))
    emitted += PER_TICK
    drain()
    if pid == n_procs - 1 and emitted >= KILL_AFTER:
        # HOST DEATH — but the black box made it to disk first: the
        # spool holds every checkpoint span recorded so far, so the
        # parent can stitch the dead host's track into the fleet trace
        spool_flush()
        os._exit(42)
    time.sleep(TICK_S)

# survivor: converge on everything (own share + adopted share)
deadline = time.time() + 240
while time.time() < deadline:
    drain()
    st = daemon.status()
    if st["offset_committed"] >= N_TOTAL - 1 and \
            st["distributed"]["adopted"] == [n_procs - 1]:
        break
    time.sleep(0.05)

st = daemon.status()
assert st["distributed"]["adopted"] == [n_procs - 1], st
assert st["offset_committed"] >= N_TOTAL - 1, st
daemon.stop(drain=True)
drain()
spool_flush()
print(f"proc {pid}: OBS-SOAK-OK", flush=True)
os._exit(0)
'''


def test_fleet_trace_merge_gloo_maintenance_soak(tmp_path):
    """Two gloo daemon processes + the auditing parent = three
    processes in ONE merged Perfetto file, tied together by
    store-carried trace.context flow arrows across BOTH worker
    boundaries, with the survivor's takeover span on its track."""
    spool = tmp_path / "spool"
    spool.mkdir()
    n_total, kill_after = 300, 120
    table_path, outs = _run_workers(
        _OBS_SOAK_WORKER, tmp_path, 2,
        args=[n_total, kill_after, str(spool)],
        expected_rc={1: 42}, timeout=300)
    assert "OBS-SOAK-OK" in outs[0], outs[0][-6000:]

    # every checkpoint/takeover commit carried its committer's context
    final = FileStoreTable.load(table_path)
    by_tag = {}
    for snap in final.snapshot_manager.snapshots():
        ctx = (snap.properties or {}).get("trace.context")
        if ctx:
            by_tag.setdefault(ctx.rsplit(":", 1)[0], []).append(snap)
    assert len(by_tag) >= 2, \
        f"want traced snapshots from both workers, got {list(by_tag)}"

    # the parent consumes one EARLY snapshot per worker (early = its
    # committer span was certainly spooled before any kill) — plan()
    # emits the plan.link boundary span that the merge resolves into a
    # worker-track -> parent-track flow arrow
    enable_tracing()
    set_export_dir(str(spool))
    scan = final.new_read_builder().new_scan()
    for _tag, snaps in sorted(by_tag.items()):
        scan.plan(snapshot_id=min(s.id for s in snaps))
    spool_flush()
    disable_tracing()

    out = str(tmp_path / "fleet-trace.json")
    stats = export_merged(str(spool), out)
    assert stats["processes"] == 3, stats
    assert stats["flows"] >= 2, stats
    assert stats["out"] == out

    procs, spans, flows = _load_merged(out)
    assert len(procs) == 3
    me = [p for p, o in _os_pid_of(procs).items()
          if o == os.getpid()]
    assert len(me) == 1, procs
    me = me[0]
    worker_pids = set(procs) - {me}
    # every process contributed spans to its own track
    assert worker_pids <= {s["pid"] for s in spans}
    # both worker boundaries have a RESOLVED store-carried arrow into
    # the parent's plan.link span
    link_srcs = {s_ev["pid"] for s_ev, f_ev in flows
                 if f_ev["pid"] == me and s_ev["name"] == "link"}
    assert worker_pids <= link_srcs, (link_srcs, worker_pids)
    by_pid_names = {}
    for s in spans:
        by_pid_names.setdefault(s["pid"], set()).add(s["name"])
    # the arrows land on checkpoint commits, and the survivor's
    # takeover of the dead host is on the merged timeline
    assert any("stream.checkpoint" in by_pid_names[p]
               for p in worker_pids), by_pid_names
    assert any("stream.takeover" in by_pid_names.get(p, set())
               for p in worker_pids), by_pid_names
    assert any(s["name"] == "plan.link" and s["pid"] == me
               for s in spans)


# -- leg 1b: serving rig, header-carried context -----------------------------

_REPLICA_CHILD = r'''
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
rid = int(sys.argv[1]); table_path = sys.argv[2]; spool = sys.argv[3]
sys.path.insert(0, sys.argv[4])
import pyarrow as pa
pa.set_cpu_count(2); pa.set_io_thread_count(2)
from paimon_tpu.table import FileStoreTable
from paimon_tpu.service import KvQueryServer

table = FileStoreTable.load(table_path, dynamic_options={
    "trace.enabled": "true",
    "trace.export.dir": spool,
    "service.lookup.refresh-interval": "1000"})
server = KvQueryServer(table, replica_id=rid)
server.server.start()           # no registry write: parent routes
print("ADDR %d %s" % (rid, server.address), flush=True)
sys.stdin.read()                # parent closes the pipe to stop us
server.server.stop()
from paimon_tpu.obs.trace import spool_flush
spool_flush()
os._exit(0)
'''


def _serving_table(path, rows=64):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", BigIntType())
              .primary_key("id")
              .options({"bucket": "2", "write-only": "true"})
              .build())
    t = FileStoreTable.create(path, schema)
    wb = t.new_batch_write_builder()
    with wb.new_write() as w:
        w.write_dicts([{"id": i, "v": i} for i in range(rows)])
        wb.new_commit().commit(w.prepare_commit())
    return t


def test_fleet_trace_merge_serving_rig(tmp_path):
    """Client -> router -> 2 replica PROCESSES: the X-Parent-Span hop
    headers become remote_parent flow arrows from the router's track
    into EACH replica's serve.request span in the merged trace."""
    t = _serving_table(str(tmp_path / "t"))
    spool = tmp_path / "spool"
    spool.mkdir()
    child = tmp_path / "replica_child.py"
    child.write_text(_REPLICA_CHILD)
    procs, addrs = [], {}
    try:
        for rid in range(2):
            procs.append(subprocess.Popen(
                [sys.executable, str(child), str(rid), t.path,
                 str(spool), REPO],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                text=True, env=_child_env()))
        for p in procs:
            line = p.stdout.readline().strip()
            assert line.startswith("ADDR "), line
            _tag, rid, addr = line.split(" ", 2)
            addrs[int(rid)] = addr

        enable_tracing()
        set_export_dir(str(spool))
        router = ReplicaRouter(addresses=addrs, table_name="t")
        router.server.start()
        try:
            # distinct tenants spread the consistent-hash ring over
            # both replicas; every request runs client.request ->
            # router serve.request -> replica serve.request
            for i in range(24):
                with KvQueryClient(address=router.address,
                                   tenant=f"tn-{i}",
                                   follow_topology=False) as c:
                    assert c.lookup_row({"id": i % 16})["v"] == i % 16
        finally:
            router.server.stop()
            for pool in router._remote.values():
                pool.close()
    finally:
        for p in procs:
            if p.stdin:
                p.stdin.close()
        for p in procs:
            p.wait(timeout=60)
    spool_flush()
    disable_tracing()

    out = str(tmp_path / "serve-trace.json")
    stats = export_merged(str(spool), out)
    assert stats["processes"] == 3, stats

    procs_map, spans, flows = _load_merged(out)
    pid_map = _os_pid_of(procs_map)
    me = [p for p, o in pid_map.items() if o == os.getpid()]
    assert len(me) == 1, procs_map
    me = me[0]
    replica_pids = set(procs_map) - {me}
    assert {pid_map[p] for p in replica_pids} == \
        {p.pid for p in procs}
    # replica tracks carry the replica id in their labels
    assert {procs_map[p].split("[")[-1].rstrip("]")
            for p in replica_pids} == {"r0", "r1"}
    # parent track: the originating client spans
    assert any(s["name"] == "client.request" and s["pid"] == me
               for s in spans)
    # EACH replica process serves with an adopted remote parent, and
    # the hop resolves to an arrow leaving the parent's track
    for rp in sorted(replica_pids):
        served = [s for s in spans
                  if s["pid"] == rp and s["name"] == "serve.request"]
        assert served, (rp, procs_map)
        assert all(s["args"].get("remote_parent") for s in served)
        arrows = [(s_ev, f_ev) for s_ev, f_ev in flows
                  if f_ev["pid"] == rp
                  and s_ev["name"] == "remote_parent"]
        assert arrows, f"no flow arrow into replica track {rp}"
        assert all(s_ev["pid"] == me for s_ev, _f in arrows)


# -- leg 2: flight recorder + debug bundle -----------------------------------

def _wait(cond, timeout=30.0, step=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(step)
    return False


def test_daemon_crash_dumps_flight_ring_and_debug_bundle(
        tmp_path, capsys):
    """An ingest loop that dies past its restart budget dumps the
    flight ring: the terminal loop.crash WITH the operational events
    recorded before it (here: a retried transient fault), and
    `paimon table debug-bundle` round-trips the same ring."""
    from paimon_tpu.cdc.source import MemoryCdcSource
    from paimon_tpu.parallel.fault import BucketRetryPolicy
    from paimon_tpu.service.stream_daemon import StreamDaemon

    dumps = tmp_path / "flight"

    # organic preceding context: a transient fault rides the retry
    # ladder, which records EV_RETRY into the always-on ring
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise ConnectionError("injected blip")
        return "ok"

    assert BucketRetryPolicy(max_attempts=3).retry_call(flaky) == "ok"

    class BoomSource(MemoryCdcSource):
        def poll(self, after_offset, max_events):
            raise RuntimeError("boom: injected source failure")

    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", BigIntType())
              .primary_key("id")
              .options({"bucket": "2",
                        "stream.ingest.poll-interval": "10",
                        "stream.restart.backoff": "10",
                        "stream.restart.backoff.cap": "40",
                        "stream.restart.max-restarts": "1",
                        "obs.flight.dump.dir": str(dumps)})
              .build())
    table = FileStoreTable.create(str(tmp_path / "t"), schema)
    daemon = StreamDaemon(table, BoomSource(), compact=False,
                          serve=False).start()
    try:
        assert _wait(
            lambda: daemon.status()["loops"]["ingest"]["failed"])
    finally:
        daemon.kill()

    dump_files = sorted(dumps.glob("flight-*.json"))
    assert dump_files, "terminal loop failure left no flight dump"
    docs = [json.loads(p.read_text()) for p in dump_files]
    doc = next(d for d in docs
               if any(e["kind"] == "loop.crash" for e in d["events"]))
    assert doc["pid"] == os.getpid()
    kinds = [e["kind"] for e in doc["events"]]
    crash = [e for e in doc["events"] if e["kind"] == "loop.crash"][-1]
    assert crash["loop"] == "ingest"
    assert crash["why"] == "max_restarts"
    assert "boom" in str(crash["error"])
    # the ring kept what came BEFORE the trigger
    assert "retry" in kinds
    assert kinds.index("retry") < kinds.index("loop.crash")

    # CLI round trip: the bundle carries the same ring + table context
    from paimon_tpu.cli import main
    wh = str(tmp_path / "wh")
    assert main(["-w", wh, "db", "create", "d1"]) == 0
    assert main(["-w", wh, "table", "create", "d1.t",
                 "--column", "id:BIGINT NOT NULL",
                 "--column", "v:DOUBLE",
                 "--primary-key", "id",
                 "--option", "bucket=2"]) == 0
    assert main(["-w", wh, "sql",
                 "INSERT INTO d1.t VALUES (1, 1.5), (2, 2.5)"]) == 0
    out_path = str(tmp_path / "bundle.json")
    capsys.readouterr()
    assert main(["-w", wh, "table", "debug-bundle", "d1.t",
                 "--out", out_path]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["out"] == out_path
    assert summary["flight_events"] >= 2
    with open(out_path) as f:
        bundle = json.load(f)
    assert bundle["table"]
    assert str(os.getpid()) in bundle["process"]
    bundle_kinds = [e["kind"] for e in bundle["flight"]["events"]]
    assert "loop.crash" in bundle_kinds and "retry" in bundle_kinds
    assert bundle["options"]["bucket"]["value"] == "2"
    assert any(r["group"] == "commit" for r in bundle["metrics"])


# -- leg 2b (satellite): SIGTERM'd daemon leaves the black box ---------------

_SIGTERM_DAEMON_CHILD = r'''
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
table_path = sys.argv[1]; spool = sys.argv[2]; dumps = sys.argv[3]
sys.path.insert(0, sys.argv[4])
from paimon_tpu.cdc.source import MemoryCdcSource
from paimon_tpu.schema import Schema
from paimon_tpu.service.stream_daemon import StreamDaemon
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType

schema = (Schema.builder()
          .column("id", BigIntType(False))
          .column("v", BigIntType())
          .primary_key("id")
          .options({"bucket": "2",
                    "stream.checkpoint.interval": "50",
                    "stream.ingest.poll-interval": "10",
                    "trace.enabled": "true",
                    "trace.export.dir": spool,
                    "obs.flight.dump.dir": dumps})
          .build())
table = FileStoreTable.create(table_path, schema)
src = MemoryCdcSource([{"op": "c", "after": {"id": i, "v": i}}
                       for i in range(40)])
daemon = StreamDaemon(table, src, compact=False, serve=False)
daemon.install_signal_handlers()
daemon.start()
while daemon.status()["offset_committed"] < 39:
    time.sleep(0.02)
print("READY", flush=True)
status = daemon.run_forever()
assert not any(l["failed"] for l in status["loops"].values()), status
print("STOPPED", flush=True)
'''


def test_sigtermed_daemon_leaves_spool_and_flight_dump(tmp_path):
    """Satellite regression: the daemon's signal handler flushes the
    trace spool AND dumps the flight ring BEFORE starting the drain —
    a killed daemon still contributes its track to the fleet trace."""
    spool = tmp_path / "spool"
    dumps = tmp_path / "flight"
    spool.mkdir()
    child = tmp_path / "daemon_child.py"
    child.write_text(_SIGTERM_DAEMON_CHILD)
    p = subprocess.Popen(
        [sys.executable, str(child), str(tmp_path / "t"), str(spool),
         str(dumps), REPO],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=_child_env())
    try:
        line = p.stdout.readline().strip()
        assert line == "READY", line
        os.kill(p.pid, signal.SIGTERM)
        out, _ = p.communicate(timeout=120)
    except Exception:
        p.kill()
        raise
    assert p.returncode == 0, out[-4000:]
    assert "STOPPED" in out, out[-4000:]

    spools = read_spools(str(spool))
    assert len(spools) == 1
    assert spools[0]["meta"]["pid"] == p.pid
    names = {s["name"] for s in spools[0]["spans"]}
    assert "stream.checkpoint" in names, names

    dump_files = sorted(dumps.glob("flight-*.json"))
    assert dump_files, "signal handler left no flight dump"
    docs = [json.loads(f.read_text()) for f in dump_files]
    doc = next(d for d in docs
               if any(e["kind"] == "sigterm" for e in d["events"]))
    assert doc["pid"] == p.pid
    ev = next(e for e in doc["events"] if e["kind"] == "sigterm")
    assert ev["signum"] == signal.SIGTERM


# -- leg 3: SLO burn-rate plane ----------------------------------------------

def _prom_value(text, name):
    """Last sample value of `name` in a Prometheus exposition."""
    vals = [float(line.rsplit(" ", 1)[1])
            for line in text.splitlines()
            if line.startswith(name) and not line.startswith("#")
            and (line[len(name)] in ("{", " "))]
    assert vals, f"{name} not in exposition"
    return vals[-1]


def test_slo_storm_flips_alert_and_recovers(tmp_path):
    """An injected 504 storm burns the availability budget above the
    threshold in BOTH windows -> alert on, visible at /slo, the router
    aggregate, and the `slo` Prometheus group; after the bad events
    age out of the fast window, a healthy loadgen run shows it clear."""
    from benchmarks.loadgen import run_loadgen
    from paimon_tpu.obs.export import render_prometheus

    t = _serving_table(str(tmp_path / "t"), rows=64)
    t = FileStoreTable.load(t.path, dynamic_options={
        "service.slo.fast-window-s": "1.0",
        "service.slo.slow-window-s": "5.0",
        "service.slo.burn-threshold": "2.0"})
    server = KvQueryServer(t).start()
    router = ReplicaRouter(servers=[server])
    router.server.start()
    try:
        with KvQueryClient(address=server.address,
                           follow_topology=False) as c:
            for i in range(5):
                assert c.lookup_row({"id": i})["v"] == i
            baseline = c.slo()
        assert baseline["enabled"] and not baseline["alert"]

        # storm: a zero-budget deadline turns every request into a
        # deterministic 504 — each one feeds the evaluator as a bad
        # availability event
        with KvQueryClient(address=server.address, timeout_ms=0,
                           follow_topology=False) as bad:
            for i in range(40):
                try:
                    bad.lookup_row({"id": i % 16})
                except Exception:
                    pass
        with KvQueryClient(address=server.address,
                           follow_topology=False) as c:
            stormed = c.slo()
        av = stormed["objectives"]["availability"]
        assert stormed["alert"] is True
        assert av["alert"] is True
        assert av["burn_fast"] >= stormed["burn_threshold"]
        assert av["burn_slow"] >= stormed["burn_threshold"]
        assert stormed["bad_events"] >= 40

        # the same state through the router's fleet rollup ...
        with KvQueryClient(address=router.address,
                           follow_topology=False) as rc:
            agg = rc.slo()
        assert agg["alert"] is True
        assert "0" in agg["per_replica"]
        assert agg["objectives"]["availability"]["burn_fast"] >= 2.0
        assert agg["unreachable"] == []

        # ... and through the `slo` Prometheus group (the /slo render
        # above refreshed the gauges)
        text = render_prometheus()
        assert _prom_value(text, "paimon_slo_alert") == 1.0
        assert _prom_value(
            text, "paimon_slo_availability_burn_fast") >= 2.0

        # recovery: let the storm age past the fast window, then
        # serve a healthy loadgen run — the fast leg cools and the
        # multi-window AND clears the alert
        time.sleep(1.1)
        res = run_loadgen(server.address, rows=64, seconds=1.0,
                          procs=1, threads=4)
        assert res["qps"] > 0
        with KvQueryClient(address=server.address,
                           follow_topology=False) as c:
            healed = c.slo()
        assert healed["alert"] is False
        assert healed["objectives"]["availability"]["burn_fast"] < 2.0
        assert healed["good_events"] > stormed["good_events"]
        text = render_prometheus()
        assert _prom_value(text, "paimon_slo_alert") == 0.0
    finally:
        router.server.stop()
        server.stop()
