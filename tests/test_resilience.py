"""Tail-tolerance plane tests: deadlines, hedged reads, circuit
breakers, brownout, snapshot-hint cache.

State machines run on injectable clocks; chaos regressions assert the
two invariants that make hedging safe to ship: NO duplicate side
effects (mutations are never hedged) and byte-identical results under
heavy-tailed / stuck-store injection.
"""

import os
import threading
import time

import pytest

from paimon_tpu import Schema
from paimon_tpu.fs.object_store import (
    CircuitOpenError, LatencyInjectingObjectStoreBackend,
    LocalObjectStoreBackend, ObjectStoreBackend, ObjectStoreFileIO,
    RetryingObjectStoreBackend, TransientStoreError,
)
from paimon_tpu.fs.resilience import (
    CircuitBreaker, LatencyTracker, ResilientObjectStoreBackend,
    maybe_wrap_resilience, set_degraded,
)
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType
from paimon_tpu.utils.backoff import Backoff, wait_for
from paimon_tpu.utils.deadline import (
    Deadline, DeadlineExceededError, check_deadline, current_deadline,
    deadline_scope,
)


class CountingBackend(ObjectStoreBackend):
    """Counts every op per kind; optionally fails reads on demand."""

    def __init__(self, inner):
        self.inner = inner
        self.counts = {"put": 0, "get": 0, "head": 0, "list": 0,
                       "delete": 0}
        self.fail_reads = False
        self._lock = threading.Lock()

    def _tick(self, op):
        with self._lock:
            self.counts[op] += 1

    def put(self, key, data, if_none_match=False):
        self._tick("put")
        return self.inner.put(key, data, if_none_match=if_none_match)

    def get(self, key, offset=0, length=None):
        self._tick("get")
        if self.fail_reads:
            raise TransientStoreError("injected 503")
        return self.inner.get(key, offset, length)

    def head(self, key):
        self._tick("head")
        if self.fail_reads:
            raise TransientStoreError("injected 503")
        return self.inner.head(key)

    def list(self, prefix):
        self._tick("list")
        if self.fail_reads:
            raise TransientStoreError("injected 503")
        return self.inner.list(prefix)

    def delete(self, key):
        self._tick("delete")
        return self.inner.delete(key)


def _schema(**extra):
    opts = {"bucket": "2"}
    opts.update({k: str(v) for k, v in extra.items()})
    return (Schema.builder()
            .column("id", BigIntType(False))
            .column("v", DoubleType())
            .primary_key("id")
            .options(opts).build())


def _fill(table, n=400, start=0):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts([{"id": start + i, "v": float(start + i)}
                   for i in range(n)])
    wb.new_commit().commit(w.prepare_commit())
    w.close()


# -- deadlines ---------------------------------------------------------------

def test_deadline_scope_and_check():
    clk = [0.0]
    with deadline_scope(100, clock=lambda: clk[0]) as dl:
        assert current_deadline() is dl
        assert 99 < dl.remaining_ms() <= 100
        check_deadline("t")                  # not exceeded: no raise
        clk[0] = 0.2
        assert dl.exceeded()
        with pytest.raises(DeadlineExceededError):
            check_deadline("t")
    assert current_deadline() is None
    check_deadline("no scope: never raises")


def test_deadline_entry_scope_outer_wins():
    with deadline_scope(50_000) as outer:
        # a table-level request.timeout must NOT shorten or extend an
        # active service deadline
        with deadline_scope(1, entry=True) as inner:
            assert inner is outer
            assert current_deadline() is outer


def test_deadline_none_is_noop():
    with deadline_scope(None) as dl:
        assert dl is None
        assert current_deadline() is None


def test_deadline_counts_metric_once():
    from paimon_tpu.metrics import (
        RESILIENCE_DEADLINE_EXCEEDED, global_registry,
    )
    c = global_registry().resilience_metrics().counter(
        RESILIENCE_DEADLINE_EXCEEDED)
    before = c.count
    clk = [0.0]
    with pytest.raises(DeadlineExceededError):
        with deadline_scope(10, clock=lambda: clk[0]):
            clk[0] = 1.0
            check_deadline("x")
    assert c.count == before + 1


def test_deadline_propagates_into_thread_pool():
    from paimon_tpu.parallel.executors import new_thread_pool
    pool = new_thread_pool(1, "dl-test")
    try:
        with deadline_scope(60_000) as dl:
            seen = pool.submit(current_deadline).result()
            assert seen is dl
        assert pool.submit(current_deadline).result() is None
    finally:
        pool.shutdown()


def test_backoff_pause_honors_deadline():
    clk = [0.0]
    sleeps = []
    with deadline_scope(100, clock=lambda: clk[0]):
        b = Backoff(1000.0, sleep=sleeps.append, clock=lambda: clk[0])
        b.pause()
        # the 1000ms base wait was capped to the 100ms budget
        assert sleeps and sleeps[0] <= 0.1001
        clk[0] = 0.2
        with pytest.raises(DeadlineExceededError):
            b.pause()


def test_wait_for_honors_deadline():
    clk = [0.0]
    sleeps = []
    with deadline_scope(50, clock=lambda: clk[0]):
        wait_for(10.0, sleep=sleeps.append)
        assert sleeps and sleeps[0] <= 0.0501
        clk[0] = 1.0
        with pytest.raises(DeadlineExceededError):
            wait_for(0.001, sleep=sleeps.append)


def test_deadline_not_transient_not_corrupt_skippable():
    from paimon_tpu.options import CoreOptions, Options
    from paimon_tpu.parallel.fault import is_transient_error
    from paimon_tpu.parallel.scan_pipeline import read_or_skip_corrupt
    assert not is_transient_error(DeadlineExceededError("x"))
    opts = CoreOptions(Options({"scan.ignore-corrupt-files": "true"}))

    def boom():
        raise DeadlineExceededError("spent")

    with pytest.raises(DeadlineExceededError):
        read_or_skip_corrupt(boom, opts, "f")


# -- latency tracker ---------------------------------------------------------

def test_latency_tracker_quantiles_and_cold_model():
    t = LatencyTracker(window=100, min_samples=10)
    assert t.percentile_ms("get", 95) is None       # cold: no hedging
    for i in range(100):
        t.record("get", float(i))
    p95 = t.percentile_ms("get", 95)
    assert 90 <= p95 <= 99
    assert t.percentile_ms("head", 95) is None      # per-op-class


# -- circuit breaker ---------------------------------------------------------

def test_breaker_consecutive_failures_trip_and_recover():
    clk = [0.0]
    b = CircuitBreaker("t1", failure_threshold=3, open_ms=1000,
                       clock=lambda: clk[0])
    assert b.state == "closed"
    for _ in range(2):
        b.record_failure()
    assert b.state == "closed"
    b.record_failure()
    assert b.state == "open"
    assert not b.allow()                    # fail fast
    clk[0] = 0.9
    assert not b.allow()                    # still open
    clk[0] = 1.01
    assert b.allow()                        # half-open probe admitted
    assert b.state == "half_open"
    assert not b.allow()                    # only 1 probe slot
    b.record_success()
    assert b.state == "closed"
    assert b.allow()


def test_breaker_half_open_failure_reopens():
    clk = [0.0]
    b = CircuitBreaker("t2", failure_threshold=1, open_ms=1000,
                       clock=lambda: clk[0])
    b.record_failure()
    assert b.state == "open"
    clk[0] = 1.1
    assert b.allow()
    b.record_failure()                      # probe failed
    assert b.state == "open"
    clk[0] = 2.0
    assert not b.allow()                    # timer re-armed at 1.1
    clk[0] = 2.2
    assert b.allow()


def test_breaker_error_rate_trips_without_consecutive_run():
    clk = [0.0]
    b = CircuitBreaker("t3", failure_threshold=100, error_rate=0.5,
                       window=8, open_ms=1000, clock=lambda: clk[0])
    # alternate success/failure: never 2 consecutive, rate = 50%
    for _ in range(5):
        b.record_failure()
        if b.state == "open":
            break
        b.record_success()
    assert b.state == "open"


def test_breaker_half_open_lost_probe_heals():
    """Regression (review): a probe whose outcome is never recorded
    (hung store call, or an exception outside the recorded taxonomy)
    must not wedge the breaker in HALF_OPEN with zero slots forever —
    after another open-ms of silence, fresh probes are granted."""
    clk = [0.0]
    b = CircuitBreaker("t-wedge", failure_threshold=1, open_ms=1000,
                       clock=lambda: clk[0])
    b.record_failure()
    clk[0] = 1.1
    assert b.allow()                        # probe slot consumed ...
    # ... and its outcome never recorded (probe hung)
    clk[0] = 1.5
    assert not b.allow()                    # still waiting on the probe
    clk[0] = 2.2                            # open-ms past half-open entry
    assert b.allow()                        # healed: fresh probe slot
    b.record_success()
    assert b.state == "closed"


def test_breaker_probe_lost_cas_counts_success(tmp_path):
    """Regression (review): PreconditionFailed (a LOST CAS) is an
    authoritative store answer — breaker success, never an
    outcome-less consumed probe slot."""
    from paimon_tpu.fs.object_store import PreconditionFailed
    inner = LocalObjectStoreBackend(str(tmp_path / "b"))
    inner.put("k", b"theirs")
    clk = [0.0]
    b = CircuitBreaker("t-cas", failure_threshold=1, open_ms=1000,
                       clock=lambda: clk[0])
    res = ResilientObjectStoreBackend(inner, breaker=b)
    b.record_failure()
    clk[0] = 1.1                            # half-open
    with pytest.raises(PreconditionFailed):
        res.put("k", b"ours", if_none_match=True)   # the probe: lost CAS
    assert b.state == "closed"              # authoritative answer healed it


def test_breaker_success_resets_consecutive_count():
    b = CircuitBreaker("t4", failure_threshold=3, error_rate=1.0,
                       window=1000)
    for _ in range(10):
        b.record_failure()
        b.record_failure()
        b.record_success()
    assert b.state == "closed"


def test_breaker_open_fails_fast_through_retry_ladder(tmp_path):
    """Acceptance: breaker-open calls fail in <10ms instead of riding
    the retry ladder's backoff sleeps."""
    counting = CountingBackend(
        LocalObjectStoreBackend(str(tmp_path / "b")))
    breaker = CircuitBreaker("t5", failure_threshold=2, open_ms=60_000)
    res = ResilientObjectStoreBackend(counting, name="t5",
                                      breaker=breaker)
    retry = RetryingObjectStoreBackend(res, max_attempts=6,
                                       backoff_s=1.0)
    counting.fail_reads = True
    with pytest.raises(TransientStoreError):
        retry.get("k")                      # trips the breaker inside
    assert breaker.state == "open"
    before = counting.counts["get"]
    t0 = time.perf_counter()
    with pytest.raises(CircuitOpenError):
        retry.get("k")
    elapsed_ms = (time.perf_counter() - t0) * 1000
    assert elapsed_ms < 10, f"breaker-open call took {elapsed_ms:.1f}ms"
    assert counting.counts["get"] == before     # zero store traffic


# -- hedged reads ------------------------------------------------------------

def _warm_resilient(counting, **kw):
    res = ResilientObjectStoreBackend(counting, hedge_enabled=True,
                                      **kw)
    res.tracker = LatencyTracker(min_samples=5)
    return res


def test_hedge_fires_and_first_success_wins(tmp_path):
    inner = LocalObjectStoreBackend(str(tmp_path / "b"))
    inner.put("k", b"payload")
    lat = LatencyInjectingObjectStoreBackend(inner, base_ms=0.5,
                                             seed=3)
    counting = CountingBackend(lat)
    res = _warm_resilient(counting, hedge_min_delay_ms=1.0,
                          hedge_max_ratio=0.5)
    for _ in range(30):
        assert res.get("k") == b"payload"
    # one stuck request: the hedge must answer long before 2s
    lat.stuck_rate, lat.stuck_ms = 1.0, 2000.0
    issued_before = res._hedges

    stuck_once = [True]
    orig_delay = lat._delay

    def delay_once(op):
        if stuck_once[0]:
            stuck_once[0] = False
            orig_delay(op)                   # pays the 2s stall
        else:
            lat.stuck_rate = 0.0
            orig_delay(op)

    lat._delay = delay_once
    t0 = time.perf_counter()
    assert res.get("k") == b"payload"
    elapsed = time.perf_counter() - t0
    assert elapsed < 1.0, f"hedge did not rescue: {elapsed:.2f}s"
    assert res._hedges == issued_before + 1
    from paimon_tpu.metrics import (
        RESILIENCE_HEDGES_WON, global_registry,
    )
    assert global_registry().resilience_metrics().counter(
        RESILIENCE_HEDGES_WON).count >= 1


def test_hedge_rate_cap(tmp_path):
    inner = LocalObjectStoreBackend(str(tmp_path / "b"))
    inner.put("k", b"x")
    slow = LatencyInjectingObjectStoreBackend(inner, base_ms=3.0,
                                              seed=1)
    counting = CountingBackend(slow)
    res = _warm_resilient(counting, hedge_min_delay_ms=0.1,
                          hedge_max_ratio=0.05)
    # constant-latency ops: EVERY op exceeds its p95-of-equal-values
    # delay, so only the cap can hold hedges down
    for _ in range(100):
        res.get("k")
    assert res._hedges <= 0.05 * res._ops + 1
    assert counting.counts["get"] <= 106    # <=5% duplicated + slack


def test_hedge_never_on_mutations(tmp_path):
    inner = LocalObjectStoreBackend(str(tmp_path / "b"))
    lat = LatencyInjectingObjectStoreBackend(inner, base_ms=0.2, seed=2)
    counting = CountingBackend(lat)
    res = _warm_resilient(counting, hedge_min_delay_ms=0.1,
                          hedge_max_ratio=1.0)
    inner.put("warm", b"w")
    for _ in range(20):
        res.get("warm")
    # slow EVERY op: if mutations could hedge, these would duplicate
    lat.base_ms = 50.0
    res.put("k1", b"v1")
    res.delete("k1")
    assert counting.counts["put"] == 1      # exactly one store PUT
    assert counting.counts["delete"] == 1   # exactly one store DELETE


def test_hedge_disabled_under_brownout(tmp_path):
    inner = LocalObjectStoreBackend(str(tmp_path / "b"))
    inner.put("k", b"x")
    counting = CountingBackend(
        LatencyInjectingObjectStoreBackend(inner, base_ms=2.0, seed=1))
    res = _warm_resilient(counting, hedge_min_delay_ms=0.1,
                          hedge_max_ratio=1.0)
    for _ in range(10):
        res.get("k")
    set_degraded(True)
    try:
        before = res._hedges
        for _ in range(10):
            res.get("k")
        assert res._hedges == before        # no hedges while degraded
    finally:
        set_degraded(False)


def test_hedged_missing_key_raises_immediately(tmp_path):
    """Regression (review): FileNotFoundError is an authoritative
    answer — the hedged wait raises it at once instead of waiting out
    the straggling loser (whose later error must not overwrite it)."""
    inner = LocalObjectStoreBackend(str(tmp_path / "b"))
    inner.put("warm", b"w")
    lat = LatencyInjectingObjectStoreBackend(inner, base_ms=0.5, seed=3)
    res = _warm_resilient(CountingBackend(lat), hedge_min_delay_ms=0.5,
                          hedge_max_ratio=1.0)
    for _ in range(20):
        res.get("warm")
    # ONLY the primary stalls 2s; the hedge fires and its FNF must
    # win immediately instead of waiting out the stuck loser
    lat.stuck_rate, lat.stuck_ms = 1.0, 2000.0
    calls = [0]
    orig_delay = lat._delay

    def delay_first_only(op):
        calls[0] += 1
        if calls[0] > 1:
            lat.stuck_rate = 0.0
        orig_delay(op)

    lat._delay = delay_first_only
    t0 = time.perf_counter()
    with pytest.raises(FileNotFoundError):
        res.get("absent-key")
    assert time.perf_counter() - t0 < 1.5
    res.close()


def test_spent_deadline_does_not_eat_half_open_probe(tmp_path):
    """Regression (review): the deadline check runs BEFORE the
    breaker gate, so a spent deadline cannot consume the only
    half-open probe slot outcome-less."""
    clk = [0.0]
    b = CircuitBreaker("t-slot", failure_threshold=1, open_ms=1000,
                       clock=lambda: clk[0])
    inner = LocalObjectStoreBackend(str(tmp_path / "b"))
    inner.put("k", b"x")
    res = ResilientObjectStoreBackend(inner, breaker=b)
    b.record_failure()
    clk[0] = 1.1                            # half-open window reached
    dclk = [0.0]
    with deadline_scope(10, clock=lambda: dclk[0]):
        dclk[0] = 1.0                       # spent
        with pytest.raises(DeadlineExceededError):
            res.get("k")
    # the spent-deadline call raised BEFORE the breaker gate, so the
    # probe slot is still available to a healthy caller right now —
    # no outcome-less consumption, no open_ms re-wait
    assert res.get("k") == b"x"
    assert b.state == "closed"


def test_degraded_switch_aggregates_across_sources():
    """Regression (review): two serving planes in one process — one
    recovering must not clear the other's active brownout."""
    from paimon_tpu.fs.resilience import is_degraded, set_degraded_for
    a, b = object(), object()
    set_degraded_for(a, True)
    set_degraded_for(b, True)
    set_degraded_for(b, False)
    assert is_degraded()                    # a still browned out
    set_degraded_for(a, False)
    assert not is_degraded()


def test_service_invalid_timeout_is_400(tmp_path):
    """Regression (review): a malformed timeout_ms is the client's
    error (400), not a server 500."""
    from paimon_tpu.service.query_service import KvQueryClient, KvQueryServer
    t = FileStoreTable.create(str(tmp_path / "t"), _schema())
    _fill(t, 10)
    srv = KvQueryServer(t).start()
    try:
        c = KvQueryClient(address=srv.address)
        with pytest.raises(RuntimeError, match="invalid timeout_ms"):
            c._post("scan", {"limit": 5, "timeout_ms": "1s"},
                    timeout=30)
    finally:
        srv.stop()


def test_deadline_abandons_stuck_read(tmp_path):
    """A HUNG store GET (stall, not error) cannot outlive the
    deadline: with hedging enabled the resilient wrapper abandons
    the in-flight call mid-flight — even on a COLD latency model
    (no hedge fires yet, but the pooled wait still bounds it)."""
    inner = LocalObjectStoreBackend(str(tmp_path / "b"))
    inner.put("k", b"x")
    lat = LatencyInjectingObjectStoreBackend(inner, base_ms=0.2, seed=1)
    res = ResilientObjectStoreBackend(lat, hedge_enabled=True)
    lat.stuck_rate, lat.stuck_ms = 1.0, 5000.0
    t0 = time.perf_counter()
    with pytest.raises(DeadlineExceededError):
        with deadline_scope(100):
            res.get("k")
    assert time.perf_counter() - t0 < 2.0   # did NOT wait out the hang
    res.close()


def test_breaker_only_reads_stay_inline_under_deadline(tmp_path):
    """Hedging off: a deadline in scope must NOT funnel reads through
    the hedge pool (no pool is ever built) — breaker-only configs pay
    zero dispatch overhead and are bounded cooperatively."""
    inner = LocalObjectStoreBackend(str(tmp_path / "b"))
    inner.put("k", b"x")
    res = ResilientObjectStoreBackend(inner, hedge_enabled=False,
                                      breaker=CircuitBreaker("inl"))
    with deadline_scope(60_000):
        assert res.get("k") == b"x"
    assert res._pool is None


def test_mutations_proceed_with_spent_deadline(tmp_path):
    """Regression (review): the commit's deadline-abort cleanup runs
    exactly when the deadline is already spent — its deletes must
    still reach the store through the resilient wrapper, or every
    504'd commit orphans its manifests."""
    inner = LocalObjectStoreBackend(str(tmp_path / "b"))
    res = ResilientObjectStoreBackend(inner, hedge_enabled=True)
    clk = [0.0]
    with deadline_scope(10, clock=lambda: clk[0]):
        clk[0] = 1.0                       # spent
        res.put("k", b"x")                 # no raise: CAS gate owns it
        assert inner.head("k") is not None
        assert res.delete("k")
        assert inner.head("k") is None
    res.close()


@pytest.mark.parametrize("slow_shape", ["all-ops", "puts-only"])
def test_commit_deadline_abort_cleans_manifests(tmp_path, slow_shape):
    """End-to-end: a commit that trips its request.timeout before the
    CAS publishes NOTHING — no new snapshot, and every manifest/list
    written for the aborted attempt is deleted (through the resilient
    wrapper: the cleanup deletes are SHIELDED from the spent
    deadline).  'puts-only' makes the deadline trip AFTER the
    manifests are written (reads stay fast, the budget burns on the
    manifest PUTs), exercising the real cleanup-delete path."""
    store = LocalObjectStoreBackend(str(tmp_path / "b"))
    lat = LatencyInjectingObjectStoreBackend(store, base_ms=0.0, seed=1)
    fio = ObjectStoreFileIO(lat, scheme=f"dlc{slow_shape[0]}://")
    t = FileStoreTable.create(
        f"dlc{slow_shape[0]}://t",
        _schema(**{"store.breaker.enabled": "true"}),
        file_io=fio)
    _fill(t, 100)
    manifests_before = {k for k, _ in store.list("t/manifest/")}
    t2 = t.copy({"request.timeout": "40"})
    wb = t2.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts([{"id": i, "v": 1.0} for i in range(1000, 1100)])
    msgs = w.prepare_commit()              # data uploads: still fast
    lat.base_ms = 15.0 if slow_shape == "all-ops" else {"put": 30.0}
    with pytest.raises(DeadlineExceededError):
        wb.new_commit().commit(msgs)
    lat.base_ms = 0.0
    w.close()
    assert t.snapshot_manager.latest_snapshot_id() == 1   # nothing published
    manifests_after = {k for k, _ in store.list("t/manifest/")}
    assert manifests_after == manifests_before, \
        manifests_after - manifests_before


def test_delete_quietly_shielded_from_spent_deadline(tmp_path):
    """Regression (review): best-effort cleanup deletes run exactly
    when the deadline is spent — the shield keeps the store op from
    raising-and-being-swallowed into an orphaning no-op, even through
    a hedge-enabled resilient wrapper whose delete() probes head()."""
    from paimon_tpu.options import CoreOptions, Options
    inner = LocalObjectStoreBackend(str(tmp_path / "b"))
    fio = ObjectStoreFileIO(inner, scheme="shield://")
    opts = CoreOptions(Options({"read.hedge.enabled": "true"}))
    wrapped = maybe_wrap_resilience(fio, opts)
    wrapped.write_bytes("shield://k", b"x")
    clk = [0.0]
    with deadline_scope(10, clock=lambda: clk[0]):
        clk[0] = 1.0                       # spent
        wrapped.delete_quietly("shield://k")
    assert inner.head("k") is None, "cleanup delete was a no-op"


def test_copy_enables_resilience_under_cache_wrap(tmp_path):
    """Regression (review): enabling breaker/hedge via
    table.copy() on a cache-wrapped table (read.cache.range) must
    thread resilience UNDER the cache, not silently no-op."""
    from paimon_tpu.fs.caching import CachingFileIO
    store = LocalObjectStoreBackend(str(tmp_path / "b"))
    fio = ObjectStoreFileIO(store, scheme="cw://")
    t = FileStoreTable.create(
        "cw://t", _schema(**{"read.cache.range": "true"}),
        file_io=fio)
    _fill(t, 50)
    assert isinstance(t.file_io, CachingFileIO)
    t2 = t.copy({"store.breaker.enabled": "true"})
    assert isinstance(t2.file_io, CachingFileIO)
    assert isinstance(t2.file_io.inner, ObjectStoreFileIO)
    assert isinstance(t2.file_io.inner.backend,
                      ResilientObjectStoreBackend)
    # same shared cache state, rows intact
    assert t2.file_io.state is t.file_io.state
    assert t2.to_arrow().num_rows == 50


def test_service_timeout_zero_is_a_real_deadline(tmp_path):
    """Regression (review): timeout_ms=0 means 'already expired'
    (immediate 504), not 'no deadline'."""
    from paimon_tpu.service.query_service import KvQueryClient, KvQueryServer
    t = FileStoreTable.create(str(tmp_path / "t"), _schema())
    _fill(t, 20)
    srv = KvQueryServer(t).start()
    try:
        c = KvQueryClient(address=srv.address, timeout_ms=0)
        with pytest.raises(DeadlineExceededError):
            c.scan(limit=10)
    finally:
        srv.stop()


# -- chaos regression: identical rows, no duplicate side effects -------------

def test_chaos_hedged_scan_byte_identical(tmp_path):
    """Under a 10%-of-GETs-50x tail plus hedging, scans return exactly
    the rows an unhedged table returns, and the chaos run issues ZERO
    extra mutations (fsck-grade safety for reads)."""
    plain_store = LocalObjectStoreBackend(str(tmp_path / "b"))
    fio_plain = ObjectStoreFileIO(plain_store, scheme="objfs://")
    t_plain = FileStoreTable.create("objfs://t", _schema(),
                                    file_io=fio_plain)
    _fill(t_plain, 600)
    expected = t_plain.to_arrow().sort_by("id")

    lat = LatencyInjectingObjectStoreBackend(
        plain_store, base_ms=0.5, seed=7, tail_rate=0.1,
        tail_multiplier=50.0)
    counting = CountingBackend(lat)
    fio_chaos = ObjectStoreFileIO(counting, scheme="objfs://")
    t_chaos = FileStoreTable.load(
        "objfs://t", file_io=fio_chaos,
        dynamic_options={"read.hedge.enabled": "true",
                         "read.hedge.min-delay": "1",
                         "read.hedge.max-ratio": "0.3",
                         "store.breaker.enabled": "true"})
    res = t_chaos.file_io.backend
    assert isinstance(res, ResilientObjectStoreBackend)
    res.tracker = LatencyTracker(min_samples=5)
    mutations_before = counting.counts["put"] + counting.counts["delete"]
    for _ in range(4):
        got = t_chaos.to_arrow().sort_by("id")
        assert got.equals(expected)
    assert counting.counts["put"] + counting.counts["delete"] == \
        mutations_before, "hedged READS caused store mutations"


def test_chaos_hedged_ingest_no_duplicates(tmp_path):
    """Writes through a resilient+hedged table under pareto tail:
    row counts exact (no duplicate flushes/commits), fsck clean."""
    store = LocalObjectStoreBackend(str(tmp_path / "b"))
    lat = LatencyInjectingObjectStoreBackend(
        store, base_ms=0.3, seed=11, tail_rate=0.05,
        pareto_alpha=1.2)
    fio = ObjectStoreFileIO(RetryingObjectStoreBackend(lat),
                            scheme="objfs://")
    t = FileStoreTable.create(
        "objfs://t", _schema(**{"read.hedge.enabled": "true",
                                "store.breaker.enabled": "true"}),
        file_io=fio)
    _fill(t, 300, start=0)
    _fill(t, 300, start=300)
    got = t.to_arrow()
    assert got.num_rows == 600
    assert sorted(set(got.column("id").to_pylist())) == list(range(600))
    from paimon_tpu.maintenance.fsck import fsck
    report = fsck(t)
    assert not report.violations, report.violations


# -- admission + brownout ----------------------------------------------------

def test_admission_deadline_bounds_queue_wait():
    from paimon_tpu.service.admission import AdmissionController
    ctrl = AdmissionController(max_bytes=100, queue_depth=8,
                               queue_timeout_ms=30_000,
                               table="dl-q")
    big = ctrl.acquire("a", 100)            # budget fully consumed
    t0 = time.perf_counter()
    with pytest.raises(DeadlineExceededError):
        with deadline_scope(50):
            ctrl.acquire("b", 100)
    # bounded by the 50ms deadline, NOT the 30s queue timeout
    assert time.perf_counter() - t0 < 5.0
    big.release()


def test_admission_brownout_shed_by_priority():
    from paimon_tpu.metrics import (
        RESILIENCE_BROWNOUT_SHEDS, global_registry,
    )
    from paimon_tpu.service.admission import (
        AdmissionController, AdmissionRejected,
    )
    ctrl = AdmissionController(max_bytes=1 << 20, table="shed")
    sheds = global_registry().resilience_metrics().counter(
        RESILIENCE_BROWNOUT_SHEDS)
    before = sheds.count
    ctrl.set_shed_below(100)
    with pytest.raises(AdmissionRejected):
        ctrl.acquire("low", 10, priority=1)
    assert sheds.count == before + 1
    ctrl.acquire("hi", 10, priority=100).release()   # default passes
    ctrl.set_shed_below(0)
    ctrl.acquire("low", 10, priority=1).release()    # restored


def test_brownout_ladder_and_hysteresis(tmp_path):
    from paimon_tpu.options import CoreOptions, Options
    from paimon_tpu.service.admission import AdmissionController
    from paimon_tpu.service.brownout import BrownoutController
    clk = [0.0]
    ctrl = AdmissionController(max_bytes=1 << 20, queue_depth=10,
                               table="bo")
    opts = CoreOptions(Options({"service.brownout.hold-ms": "1000"}))
    bo = BrownoutController(ctrl, opts, clock=lambda: clk[0])
    assert bo.observe() == 0
    # signal 1: failure rate (10 events in the 10s window = 1/s)
    for _ in range(10):
        bo.timeouts.record()
    assert bo.observe() == 1
    from paimon_tpu.fs.resilience import hedging_allowed
    assert not hedging_allowed()
    # signal 2: an open breaker -> rung 2, low priority sheds
    b = CircuitBreaker("bo-store", failure_threshold=1, open_ms=60_000,
                       clock=lambda: clk[0])
    res = ResilientObjectStoreBackend(
        LocalObjectStoreBackend(str(tmp_path / "b")),
        name="bo-store", breaker=b)
    b.record_failure()
    assert bo.observe() == 2
    assert ctrl._shed_below == 100
    hz = bo.healthz()
    assert hz["status"] == "brownout"
    assert hz["brownout_level"] == 2
    assert hz["breakers"].get("bo-store") == "open"
    assert hz["shedding_below_priority"] == 100
    # failure-rate signal clears, breaker stays open -> target rung 1,
    # but the hold (entered at t=0, 1000ms) keeps rung 2 (hysteresis)
    bo.timeouts._events.clear()
    clk[0] = 0.5
    assert bo.observe() == 2
    clk[0] = 1.5                            # past hold-ms
    assert bo.observe() == 1                # steps DOWN
    bo.reset()
    assert bo.level == 0
    assert hedging_allowed()
    assert ctrl._shed_below == 0
    res.close()


# -- serving plane 504 + healthz --------------------------------------------

@pytest.mark.parametrize("via", ["body", "option"])
def test_service_deadline_504(tmp_path, via):
    from paimon_tpu.service.query_service import KvQueryClient, KvQueryServer
    store = LocalObjectStoreBackend(str(tmp_path / "b"))
    lat = LatencyInjectingObjectStoreBackend(store, base_ms=0.0, seed=1)
    fio = ObjectStoreFileIO(lat, scheme="objfs://")
    t = FileStoreTable.create("objfs://t", _schema(), file_io=fio)
    _fill(t, 200)
    opts = {"service.cache.shared": "false"}
    if via == "option":
        opts["service.request.timeout"] = "80"
    srv = KvQueryServer(t.copy(opts)).start()
    try:
        ok = KvQueryClient(address=srv.address)
        assert ok.lookup([{"id": 3}])[0]["v"] == 3.0
        # every GET now stalls 300ms: the request cannot finish in 80ms
        lat.stuck_rate, lat.stuck_ms = 1.0, 300.0
        kw = {"timeout_ms": 80} if via == "body" else {}
        slow = KvQueryClient(address=srv.address, **kw)
        t0 = time.perf_counter()
        with pytest.raises(DeadlineExceededError):
            slow.scan(limit=100)
        # 504 within deadline + small grace (one stalled op may have
        # to finish before the next check runs)
        assert (time.perf_counter() - t0) * 1000 < 80 + 1500
        lat.stuck_rate = 0.0
        hz = ok.healthz()
        assert hz["recent_504_per_s"] > 0
    finally:
        srv.stop()


def test_resilience_group_on_prometheus(tmp_path):
    from paimon_tpu.metrics import global_registry
    from paimon_tpu.obs.export import render_prometheus
    # ensure the group exists with at least one of each kind
    CircuitBreaker("prom-backend")
    global_registry().resilience_metrics().counter("deadline_exceeded")
    text = render_prometheus()
    assert "# TYPE paimon_resilience_breaker_state gauge" in text
    assert 'paimon_resilience_breaker_state{table="prom-backend"} 0' \
        in text
    assert "paimon_resilience_deadline_exceeded" in text
    for line in text.splitlines():
        if line.startswith("paimon_resilience"):
            # line-validated: name{labels} value
            parts = line.rsplit(" ", 1)
            assert len(parts) == 2 and parts[1] is not None
            float(parts[1])


# -- snapshot-hint cache -----------------------------------------------------

def test_latest_snapshot_cache_cuts_store_roundtrips(tmp_path):
    counting = CountingBackend(
        LocalObjectStoreBackend(str(tmp_path / "b")))
    fio = ObjectStoreFileIO(counting, scheme="objfs://")
    t = FileStoreTable.create("objfs://t", _schema(), file_io=fio)
    _fill(t, 50)
    sm = t.snapshot_manager
    sm.latest_snapshot()                     # prime the cache
    before = dict(counting.counts)
    for _ in range(5):
        assert sm.latest_snapshot_id() == 1
    probes = sum(counting.counts.values()) - sum(before.values())
    # warm walks are pure exists probes (head+list per exists), never
    # hint reads: <= 4 ops per walk vs ~8+ for the hint path
    assert probes <= 5 * 4, probes
    assert counting.counts["get"] == before["get"]   # no hint/json reads


def test_latest_snapshot_cache_sees_external_commit(tmp_path):
    fio = ObjectStoreFileIO(
        LocalObjectStoreBackend(str(tmp_path / "b")), scheme="objfs://")
    t = FileStoreTable.create("objfs://t", _schema(), file_io=fio)
    _fill(t, 10)
    assert t.snapshot_manager.latest_snapshot_id() == 1
    # an EXTERNAL writer commits snapshot 2 (fresh table handle =
    # fresh SnapshotManager; the first handle's cache must walk
    # forward, not answer stale)
    t2 = FileStoreTable.load("objfs://t", file_io=fio)
    _fill(t2, 10, start=10)
    assert t2.snapshot_manager.latest_snapshot_id() == 2
    assert t.snapshot_manager.latest_snapshot_id() == 2


def test_latest_snapshot_cache_survives_rollback(tmp_path):
    fio = ObjectStoreFileIO(
        LocalObjectStoreBackend(str(tmp_path / "b")), scheme="objfs://")
    t = FileStoreTable.create("objfs://t", _schema(), file_io=fio)
    _fill(t, 10)
    _fill(t, 10, start=10)
    _fill(t, 10, start=20)
    assert t.snapshot_manager.latest_snapshot_id() == 3
    t.rollback_to(1)
    assert t.snapshot_manager.latest_snapshot_id() == 1
    assert t.to_arrow().num_rows == 10
    # recommit after rollback re-uses id 2 with NEW content
    _fill(t, 5, start=100)
    assert t.snapshot_manager.latest_snapshot_id() == 2
    assert t.to_arrow().num_rows == 15


def test_latest_snapshot_cache_cas_bump_on_loss(tmp_path):
    from paimon_tpu.snapshot.snapshot_manager import SnapshotManager
    fio = ObjectStoreFileIO(
        LocalObjectStoreBackend(str(tmp_path / "b")), scheme="objfs://")
    t = FileStoreTable.create("objfs://t", _schema(), file_io=fio)
    _fill(t, 10)
    sm = SnapshotManager(fio, "objfs://t")
    snap = sm.snapshot(1)
    # losing a CAS on id 1 proves it exists: the cache bumps there
    lost = sm.try_commit(snap)
    assert not lost
    assert sm._cached_latest_id == 1
    assert sm.latest_snapshot_id() == 1


# -- wiring ------------------------------------------------------------------

def test_maybe_wrap_resilience_idempotent_and_shared(tmp_path):
    from paimon_tpu.options import CoreOptions, Options
    store = LocalObjectStoreBackend(str(tmp_path / "b"))
    fio = ObjectStoreFileIO(store, scheme="objfs://")
    opts = CoreOptions(Options({"store.breaker.enabled": "true"}))
    w1 = maybe_wrap_resilience(fio, opts)
    w2 = maybe_wrap_resilience(
        ObjectStoreFileIO(store, scheme="objfs://"), opts)
    assert isinstance(w1.backend, ResilientObjectStoreBackend)
    # one breaker per physical store, shared across table handles
    assert w1.backend is w2.backend
    # wrapping the already-wrapped FileIO is a no-op
    assert maybe_wrap_resilience(w1, opts) is w1
    # disabled options: untouched
    off = CoreOptions(Options({}))
    assert maybe_wrap_resilience(fio, off) is fio


def test_scan_pipeline_prefetch_shrinks_when_degraded(tmp_path):
    t = FileStoreTable.create(
        str(tmp_path / "t"),
        _schema(**{"scan.split.parallelism": "2",
                   "read.prefetch.splits": "4"}))
    _fill(t, 400)
    _fill(t, 400, start=400)
    from paimon_tpu.parallel.scan_pipeline import iter_split_tables
    rb = t.new_read_builder()
    plan = rb.new_scan().plan()
    read = rb.new_read()
    set_degraded(True)
    try:
        stats = {}
        rows = sum(tb.num_rows for _, _, tb in iter_split_tables(
            read._read, plan.splits, t.options, stats=stats))
        assert rows == 800
        # window = parallelism only, no prefetch extra
        assert stats["max_inflight_splits"] <= 2
    finally:
        set_degraded(False)
