"""Tiered host-SSD storage: disk cache tier + staged uploads.

Covers the ISSUE 8 safety matrix: a wiped/truncated/bit-flipped cache
dir mid-scan and mid-ingest must degrade to the object store with
results identical to an uncached run (and fsck clean); the disk tier
must never exceed cache.disk.max-bytes even under concurrent load;
staged uploads must retry from the staged bytes (never re-encode),
surface failures at the prepare_commit barrier, keep the commit
durability contract, and seed the read tier.
"""

import glob
import os
import threading

import numpy as np
import pyarrow as pa
import pytest

from paimon_tpu.fs.caching import (
    ByteCacheState, CachingFileIO, DiskCacheTier, evict_dropped_file,
    reset_disk_tiers, shared_cache_state,
)
from paimon_tpu.fs.fileio import LocalFileIO
from paimon_tpu.fs.object_store import (
    FlakyObjectStoreBackend, LatencyInjectingObjectStoreBackend,
    LocalObjectStoreBackend, ObjectStoreBackend, ObjectStoreFileIO,
    TransientStoreError,
)
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType

ROWS = 50_000


@pytest.fixture(autouse=True)
def _reset_tiers():
    """Shared disk tiers point at per-test tmpdirs: they must never
    outlive the test (a later table joining the shared state would
    resurrect a deleted directory)."""
    yield
    reset_disk_tiers()


def _data(rows=ROWS, seed=7):
    rng = np.random.default_rng(seed)
    return pa.table({
        "id": pa.array(rng.permutation(rows), pa.int64()),
        "v": pa.array(rng.random(rows), pa.float64()),
    })


def _schema(extra=None):
    opts = {"bucket": "2", "write-only": "true",
            "write-buffer-size": "256 kb"}
    opts.update(extra or {})
    return (Schema.builder()
            .column("id", BigIntType(False))
            .column("v", DoubleType())
            .primary_key("id")
            .options(opts)
            .build())


def _ingest(table, data=None, chunks=5):
    data = data if data is not None else _data()
    wb = table.new_batch_write_builder()
    per = data.num_rows // chunks
    with wb.new_write() as w:
        for i in range(chunks):
            w.write_arrow(data.slice(i * per, per))
        wb.new_commit().commit(w.prepare_commit())
    return data


class CountingBackend(ObjectStoreBackend):
    """Counts per-op calls, keyed coarsely by object class."""

    def __init__(self, inner):
        self.inner = inner
        self.counts = {}
        self._lock = threading.Lock()

    def _note(self, op, key):
        name = key.rsplit("/", 1)[-1]
        kind = "data" if name.startswith("data-") else "other"
        with self._lock:
            self.counts[(op, kind)] = self.counts.get((op, kind), 0) + 1

    def put(self, key, data, if_none_match=False):
        self._note("put", key)
        return self.inner.put(key, data, if_none_match=if_none_match)

    def get(self, key, offset=0, length=None):
        self._note("get", key)
        return self.inner.get(key, offset, length)

    def head(self, key):
        return self.inner.head(key)

    def list(self, prefix):
        return self.inner.list(prefix)

    def delete(self, key):
        return self.inner.delete(key)

    def data_gets(self):
        with self._lock:
            return self.counts.get(("get", "data"), 0)


def _obj_table(tmp, name, extra=None, backend_wrap=None):
    backend = LocalObjectStoreBackend(os.path.join(tmp, f"bucket_{name}"))
    if backend_wrap is not None:
        backend = backend_wrap(backend)
    fio = ObjectStoreFileIO(backend, scheme=f"{name}://")
    table = FileStoreTable.create(f"{name}://t", _schema(extra),
                                  file_io=fio)
    return table, backend, fio


# -- DiskCacheTier unit behavior ---------------------------------------------

def test_disk_tier_roundtrip_and_validation(tmp_path):
    t = DiskCacheTier(str(tmp_path / "c"), 1 << 20)
    key = t.file_key("data-abc")
    assert t.put(key, b"payload" * 100)
    assert t.get(key) == b"payload" * 100
    assert t.get(t.file_key("data-missing")) is None

    # truncate -> validation miss, entry dropped
    entry = glob.glob(str(tmp_path / "c" / "*.pce"))[0]
    blob = open(entry, "rb").read()
    open(entry, "wb").write(blob[:len(blob) // 2])
    assert t.get(key) is None
    assert len(t) == 0

    # bit-flip -> crc miss
    assert t.put(key, b"payload" * 100)
    entry = glob.glob(str(tmp_path / "c" / "*.pce"))[0]
    blob = bytearray(open(entry, "rb").read())
    blob[-1] ^= 0xFF
    open(entry, "wb").write(bytes(blob))
    assert t.get(key) is None

    # wrong-key content (a renamed/aliased entry file) never serves
    assert t.put(t.file_key("data-x"), b"X" * 50)
    src = glob.glob(str(tmp_path / "c" / "*.pce"))[0]
    t2 = DiskCacheTier(str(tmp_path / "c2"), 1 << 20)
    alias = t2._entry_file(t2.file_key("data-y"))
    os.makedirs(os.path.dirname(alias), exist_ok=True)
    open(alias, "wb").write(open(src, "rb").read())
    t2._index[t2.file_key("data-y")] = (alias, os.path.getsize(alias))
    assert t2.get(t2.file_key("data-y")) is None


def test_disk_tier_adoption_across_restart(tmp_path):
    d = str(tmp_path / "c")
    t = DiskCacheTier(d, 1 << 20)
    t.put(t.file_key("data-a"), b"A" * 100)
    t.put(t.range_key("data-b", 10, 20), b"B" * 20)
    # a fresh tier over the same dir adopts (and still validates) the
    # surviving entries — staged-upload seeding survives restarts
    t2 = DiskCacheTier(d, 1 << 20)
    assert len(t2) == 2
    assert t2.get(t2.file_key("data-a")) == b"A" * 100
    assert t2.get(t2.range_key("data-b", 10, 20)) == b"B" * 20
    # junk and crash-orphaned put() tmps are removed, not adopted (an
    # uncounted tmp would breach the max-bytes bound across restarts)
    open(os.path.join(d, "junk.pce"), "wb").write(b"not an entry")
    open(os.path.join(d, ".deadbeef.tmp"), "wb").write(b"x" * 1000)
    t3 = DiskCacheTier(d, 1 << 20)
    assert len(t3) == 2
    assert not os.path.exists(os.path.join(d, "junk.pce"))
    assert not os.path.exists(os.path.join(d, ".deadbeef.tmp"))


def test_promote_on_repeated_hits_and_demote_on_pressure(tmp_path):
    inner = LocalFileIO()
    big = tmp_path / "data-big.parquet"
    small = tmp_path / "data-small.parquet"
    big.write_bytes(b"B" * 600)
    small.write_bytes(b"s" * 300)
    st = ByteCacheState(capacity_bytes=700, range_cache_bytes=0)
    st.attach_disk(DiskCacheTier(str(tmp_path / "c"), 1 << 20),
                   promote_hits=2)
    fio = CachingFileIO(inner, capacity_bytes=700, state=st)
    disk = st.disk

    assert fio.read_bytes(str(big)) == b"B" * 600      # miss -> memory
    assert disk.get(disk.file_key(str(big))) is None   # 0 hits: not yet
    fio.read_bytes(str(big))                           # hit 1
    assert disk.get(disk.file_key(str(big))) is None
    fio.read_bytes(str(big))                           # hit 2 -> promote
    assert disk.get(disk.file_key(str(big))) == b"B" * 600

    # inserting `small` overflows the 700-byte memory LRU -> `big` is
    # demoted (already on disk) and `small`'s later eviction demotes it
    fio.read_bytes(str(small))
    assert str(big) not in st.cache
    fio.read_bytes(str(big))      # comes back via the DISK tier, no
    os.unlink(small)              # inner read; and small demoted when
    assert disk.get(disk.file_key(str(small))) == b"s" * 300
    assert fio.read_bytes(str(small)) == b"s" * 300    # store gone: SSD


def test_wipe_cache_dir_mid_run_degrades(tmp_path):
    inner = LocalFileIO()
    f = tmp_path / "data-f.parquet"
    f.write_bytes(b"x" * 1000)
    st = ByteCacheState(capacity_bytes=0)
    st.attach_disk(DiskCacheTier(str(tmp_path / "c"), 1 << 20))
    fio = CachingFileIO(inner, capacity_bytes=0, state=st)
    assert fio.read_bytes(str(f)) == b"x" * 1000
    assert st.disk.get(st.disk.file_key(str(f))) is not None
    import shutil
    shutil.rmtree(tmp_path / "c")           # wipe mid-run
    assert fio.read_bytes(str(f)) == b"x" * 1000   # degraded to store
    # and the tier heals: the dir is recreated for later entries
    assert fio.read_bytes(str(f)) == b"x" * 1000


# -- scan path end-to-end ----------------------------------------------------

def test_scan_rides_ssd_tier_and_matches_uncached(tmp_path):
    reference, _, _ = _obj_table(str(tmp_path), "ref")
    expected = _ingest(reference)

    table, backend, fio = _obj_table(
        str(tmp_path), "tier",
        extra={"cache.disk.dir": str(tmp_path / "ssd")},
        backend_wrap=CountingBackend)
    _ingest(table)

    cold = table.to_arrow().sort_by("id")
    gets_after_cold = backend.data_gets()
    assert gets_after_cold > 0
    warm = table.to_arrow().sort_by("id")
    # warm re-scan: every data file served from the SSD tier
    assert backend.data_gets() == gets_after_cold
    ref_rows = reference.to_arrow().sort_by("id")
    assert cold.equals(ref_rows) and warm.equals(ref_rows)
    assert expected.num_rows == cold.num_rows


def _purge_memory_tier(table):
    """Drop the shared state's MEMORY entries only (the whole-file
    capacity may have been grown by earlier tests in the process —
    e.g. the serving plane's 256MB — which would otherwise serve reads
    before the disk tier this test exercises)."""
    st = table.file_io.state
    with st.lock:
        st.cache.clear()
        st.ranges.clear()
        st.size = st.range_size = 0


def test_corrupt_ssd_entries_mid_scan_identical_and_fsck_clean(tmp_path):
    table, backend, fio = _obj_table(
        str(tmp_path), "corr",
        extra={"cache.disk.dir": str(tmp_path / "ssd")})
    _ingest(table)
    baseline = table.to_arrow().sort_by("id")
    # two more scans earn hit-based promotion (miss, hit 1, hit 2 ->
    # promote) even when a grown shared MEMORY tier absorbed the first
    # read; then purge memory so the corrupted re-scan must go
    # disk -> store
    table.to_arrow()
    table.to_arrow()
    _purge_memory_tier(table)

    entries = sorted(glob.glob(str(tmp_path / "ssd" / "*.pce")))
    assert entries, "scan did not populate the SSD tier"
    # truncate one, bit-flip another, delete a third
    blob = open(entries[0], "rb").read()
    open(entries[0], "wb").write(blob[:max(1, len(blob) // 3)])
    if len(entries) > 1:
        blob = bytearray(open(entries[1], "rb").read())
        blob[len(blob) // 2] ^= 0xFF
        open(entries[1], "wb").write(bytes(blob))
    if len(entries) > 2:
        os.unlink(entries[2])

    again = table.to_arrow().sort_by("id")
    assert again.equals(baseline)
    assert table.fsck().ok
    # a full wipe mid-run degrades too
    import shutil
    shutil.rmtree(tmp_path / "ssd")
    _purge_memory_tier(table)
    assert table.to_arrow().sort_by("id").equals(baseline)


def test_evict_dropped_file_evicts_both_tiers(tmp_path):
    inner = LocalFileIO()
    f = tmp_path / "data-g.parquet"
    f.write_bytes(b"g" * 500)
    st = shared_cache_state(0, 0)
    from paimon_tpu.fs.caching import shared_disk_tier
    # promote_hits=1: the entry reaches disk on its first memory HIT
    # even when an earlier test grew the shared memory capacity (with
    # capacity 0 the first MISS already demotes it to disk)
    st.attach_disk(shared_disk_tier(str(tmp_path / "c"), 1 << 20),
                   promote_hits=1)
    fio = CachingFileIO(inner, capacity_bytes=0, state=st)
    fio.read_bytes(str(f))
    fio.read_bytes(str(f))
    assert st.disk.get(st.disk.file_key(str(f))) is not None
    evict_dropped_file(str(f))
    # miss (the get above re-warmed LRU order only; eviction dropped it)
    assert st.disk.get(st.disk.file_key(str(f))) is None


# -- max-bytes hygiene under concurrency -------------------------------------

def test_disk_tier_never_exceeds_max_bytes_concurrent(tmp_path):
    """8 threads hammer a 64KB tier with ~200 distinct 2KB files; a
    sampler asserts the on-disk entry bytes never exceed the bound at
    any observed instant."""
    inner = LocalFileIO()
    files = []
    for i in range(200):
        p = tmp_path / f"data-{i:03d}.bin"
        p.write_bytes(os.urandom(2048))
        files.append(str(p))
    max_bytes = 64 << 10
    st = ByteCacheState(capacity_bytes=0)
    st.attach_disk(DiskCacheTier(str(tmp_path / "c"), max_bytes))
    fio = CachingFileIO(inner, capacity_bytes=0, state=st)

    stop = threading.Event()
    errors = []
    peaks = [0]

    def reader(seed):
        rng = np.random.default_rng(seed)
        try:
            while not stop.is_set():
                fio.read_bytes(files[int(rng.integers(len(files)))])
        except Exception as e:              # noqa: BLE001
            errors.append(e)
            stop.set()

    def sampler():
        while not stop.is_set():
            total = 0
            for p in glob.glob(str(tmp_path / "c" / "*.pce")):
                try:
                    total += os.path.getsize(p)
                except OSError:
                    pass
            peaks[0] = max(peaks[0], total)
            if total > max_bytes:
                errors.append(AssertionError(
                    f"disk tier exceeded its bound: {total} > "
                    f"{max_bytes}"))
                stop.set()

    threads = [threading.Thread(target=reader, args=(i,),
                                name=f"tier-r{i}") for i in range(8)]
    threads.append(threading.Thread(target=sampler, name="tier-sampler"))
    for t in threads:
        t.start()
    import time
    time.sleep(1.5)
    stop.set()
    for t in threads:
        t.join(10)
    assert not errors, errors
    assert st.disk.total_bytes <= max_bytes
    assert peaks[0] > 0, "sampler never saw a populated tier"


# -- staged uploads ----------------------------------------------------------

def test_staged_ingest_identical_and_durable(tmp_path):
    reference, _, _ = _obj_table(str(tmp_path), "sref")
    _ingest(reference)

    table, backend, fio = _obj_table(
        str(tmp_path), "stag",
        extra={"write.stage.dir": str(tmp_path / "stage")})
    _ingest(table)
    # durability: every committed data file is IN THE STORE (readable
    # through a fresh FileIO with no stager attached)
    fresh = FileStoreTable.load("stag://t", file_io=fio)
    assert fresh.to_arrow().sort_by("id").equals(
        reference.to_arrow().sort_by("id"))
    assert fresh.fsck().ok
    # no staged leftovers once writers closed
    assert glob.glob(str(tmp_path / "stage" / "*" / "*")) == []


def test_staged_upload_retries_reread_staged_bytes(tmp_path):
    # every data-file PUT 503s twice before landing: uploads retry
    # (from the staged bytes) until acked; each data file is staged
    # EXACTLY once — a re-encode would stage again
    class StormyPuts(ObjectStoreBackend):
        def __init__(self, inner):
            self.inner = inner
            self.attempts = {}
            self.injected = 0
            self._lock = threading.Lock()

        def put(self, key, data, if_none_match=False):
            if key.rsplit("/", 1)[-1].startswith("data-"):
                with self._lock:
                    n = self.attempts.get(key, 0) + 1
                    self.attempts[key] = n
                    if n <= 2:
                        self.injected += 1
                        raise TransientStoreError(f"503 on put {key}")
            return self.inner.put(key, data,
                                  if_none_match=if_none_match)

        def get(self, key, offset=0, length=None):
            return self.inner.get(key, offset, length)

        def head(self, key):
            return self.inner.head(key)

        def list(self, prefix):
            return self.inner.list(prefix)

        def delete(self, key):
            return self.inner.delete(key)

    table, backend, fio = _obj_table(
        str(tmp_path), "flaky",
        extra={"write.stage.dir": str(tmp_path / "stage"),
               "write.retry.max-attempts": "5",
               "write.retry.backoff": "1 ms"},
        backend_wrap=StormyPuts)
    wb = table.new_batch_write_builder()
    data = _data(20_000)
    with wb.new_write() as w:
        w.write_arrow(data)
        msgs = w.prepare_commit()
        stager = w._write._stager
        n_files = sum(len(m.new_files) for m in msgs)
        assert n_files > 0
        assert stager.staged == n_files      # one stage per file, ever
        wb.new_commit().commit(msgs)
    assert backend.injected >= 2 * n_files, "storm never fired"
    assert table.to_arrow().sort_by("id").equals(data.sort_by("id"))


def test_staged_upload_failure_surfaces_at_barrier(tmp_path):
    class DeadPuts(ObjectStoreBackend):
        def __init__(self, inner):
            self.inner = inner

        def put(self, key, data, if_none_match=False):
            if "data-" in key.rsplit("/", 1)[-1]:
                raise TransientStoreError("503 forever")
            return self.inner.put(key, data,
                                  if_none_match=if_none_match)

        def get(self, key, offset=0, length=None):
            return self.inner.get(key, offset, length)

        def head(self, key):
            return self.inner.head(key)

        def list(self, prefix):
            return self.inner.list(prefix)

        def delete(self, key):
            return self.inner.delete(key)

    table, backend, fio = _obj_table(
        str(tmp_path), "dead",
        extra={"write.stage.dir": str(tmp_path / "stage"),
               "write.retry.max-attempts": "2",
               "write.retry.backoff": "1 ms"},
        backend_wrap=DeadPuts)
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    try:
        with pytest.raises(TransientStoreError):
            # fail-fast stage() may re-raise the dead upload on a
            # LATER flush inside write_arrow (timing-dependent);
            # otherwise it surfaces at the drain barrier — never later
            w.write_arrow(_data(20_000))
            w.prepare_commit()
        # poisoning latches no later than the first drain (the early-
        # surface ordering pays one more barrier raise to get there)
        with pytest.raises((RuntimeError, TransientStoreError)):
            w.prepare_commit()
        # the stager is poisoned: a retried prepare on the same writer
        # must refuse instead of committing with files missing
        with pytest.raises(RuntimeError, match="close this writer"):
            w.prepare_commit()
    finally:
        w.close()
    # nothing was committed
    assert table.snapshot_manager.latest_snapshot() is None


def test_staged_upload_seeds_read_tier(tmp_path):
    table, backend, fio = _obj_table(
        str(tmp_path), "seed",
        extra={"write.stage.dir": str(tmp_path / "stage"),
               "cache.disk.dir": str(tmp_path / "ssd")},
        backend_wrap=CountingBackend)
    data = _ingest(table)
    # the upload seeded the SSD tier: the first scan after ingest needs
    # ZERO object-store GETs for data files
    assert backend.data_gets() == 0
    rows = table.to_arrow().sort_by("id")
    assert backend.data_gets() == 0
    assert rows.equals(data.sort_by("id"))


def test_staged_upload_seeds_private_state_tier(tmp_path):
    """A table riding a PRIVATE ByteCacheState (explicitly wrapped
    FileIO) must seed ITS tier, not the process-shared one."""
    backend = LocalObjectStoreBackend(str(tmp_path / "bucket"))
    inner = ObjectStoreFileIO(backend, scheme="priv://")
    st = ByteCacheState(capacity_bytes=0)
    st.attach_disk(DiskCacheTier(str(tmp_path / "ssd"), 1 << 20))
    wrapped = CachingFileIO(inner, capacity_bytes=0, state=st)
    table = FileStoreTable.create(
        "priv://t",
        _schema({"write.stage.dir": str(tmp_path / "stage")}),
        file_io=wrapped)
    data = _ingest(table, _data(20_000))
    keys = [k for k in st.disk._index if k.startswith("F|")]
    assert keys, "upload did not seed the private state's disk tier"
    assert table.to_arrow().sort_by("id").equals(data.sort_by("id"))


def test_range_reads_reach_whole_file_seeds(tmp_path):
    """With the range-only memory shape (whole-file capacity 0), a
    ranged read must still be served from a whole-file SSD entry —
    sliced, with the slice cached as a range entry so the full entry
    is not re-read for the same range."""
    inner = LocalFileIO()
    f = tmp_path / "data-r.bin"
    f.write_bytes(bytes(range(256)) * 100)
    st = ByteCacheState(capacity_bytes=0, range_cache_bytes=0)
    st.attach_disk(DiskCacheTier(str(tmp_path / "c"), 1 << 20))
    fio = CachingFileIO(inner, capacity_bytes=0, state=st)
    # seed the whole file (what a staged upload does)
    st.disk.put(st.disk.file_key(str(f)), f.read_bytes())
    os.unlink(f)                       # store gone: only SSD can serve
    got = fio.read_range(str(f), 256, 256)
    assert got == bytes(range(256))
    # the slice is now its own range entry
    assert st.disk.get(st.disk.range_key(str(f), 256, 256)) == got
    # vectored path too
    out = fio.read_ranges(str(f), [(0, 16), (512, 16)])
    assert out[0] == bytes(range(16)) and out[1] == bytes(range(16))


def test_mid_ingest_wipes_degrade_and_stay_exact(tmp_path):
    table, backend, fio = _obj_table(
        str(tmp_path), "wipe",
        extra={"write.stage.dir": str(tmp_path / "stage"),
               "cache.disk.dir": str(tmp_path / "ssd")})
    data = _data()
    wb = table.new_batch_write_builder()
    per = data.num_rows // 5
    import shutil
    with wb.new_write() as w:
        for i in range(5):
            w.write_arrow(data.slice(i * per, per))
            if i == 2:
                # wipe BOTH local tiers mid-ingest: the cache degrades,
                # staged uploads that already acked are unaffected, and
                # in-flight staging recreates its dir
                shutil.rmtree(tmp_path / "ssd", ignore_errors=True)
        wb.new_commit().commit(w.prepare_commit())
    assert table.to_arrow().sort_by("id").equals(data.sort_by("id"))
    assert table.fsck().ok


# -- latency injection -------------------------------------------------------

def test_latency_injecting_backend():
    import time

    class Instant(ObjectStoreBackend):
        def put(self, key, data, if_none_match=False):
            pass

        def get(self, key, offset=0, length=None):
            return b"x"

        def head(self, key):
            return 1

        def list(self, prefix):
            return []

        def delete(self, key):
            return True

    be = LatencyInjectingObjectStoreBackend(
        Instant(), base_ms={"get": 30.0}, jitter_ms=0.0, seed=1)
    t0 = time.perf_counter()
    be.get("k")
    assert time.perf_counter() - t0 >= 0.028
    t0 = time.perf_counter()
    be.put("k", b"")                       # not in the dict -> 0 delay
    assert time.perf_counter() - t0 < 0.02
    assert be.stats["delayed_calls"] == 2
    assert be.stats["delay_ms_total"] == 30.0

    # composable with the fault injector: the round trip is charged
    # before the 503 fires
    flaky = FlakyObjectStoreBackend(
        LatencyInjectingObjectStoreBackend(
            Instant(), base_ms=5.0, seed=2),
        seed=2, fail_rate=1.0)
    with pytest.raises(TransientStoreError):
        flaky.get("k")
