"""Repair actions: remove_unexisting_files + compact_manifest.

reference: flink/action/RemoveUnexistingFilesAction,
flink/procedure/CompactManifestProcedure.
"""

import os

import pytest

from paimon_tpu.maintenance.repair import (
    compact_manifests, remove_unexisting_files,
)
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType


def _make(tmp, opts=None):
    o = {"bucket": "1", "write-only": "true"}
    o.update(opts or {})
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options(o)
              .build())
    return FileStoreTable.create(os.path.join(tmp, "t"), schema)


def _commit(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    sid = wb.new_commit().commit(w.prepare_commit())
    w.close()
    return sid


class TestRemoveUnexistingFiles:
    def test_reconciles_after_manual_deletion(self, tmp_path):
        t = _make(str(tmp_path))
        _commit(t, [{"id": 1, "v": 1.0}])
        _commit(t, [{"id": 2, "v": 2.0}])
        # a human deletes a data file out of band
        split = t.new_read_builder().new_scan().plan().splits[0]
        victim = max(split.data_files,
                     key=lambda f: f.min_sequence_number)
        path = t.new_scan().path_factory.data_file_path(
            (), 0, victim.file_name)
        os.remove(path)
        with pytest.raises(Exception):
            t.to_arrow()
        # dry run reports without committing
        missing = remove_unexisting_files(t, dry_run=True)
        assert missing == [path]
        with pytest.raises(Exception):
            t.to_arrow()
        # repair commits DELETE entries; table is readable again
        gone = remove_unexisting_files(t)
        assert gone == [path]
        t2 = FileStoreTable.load(t.path)
        assert t2.to_arrow().column("id").to_pylist() == [1]

    def test_noop_when_all_present(self, tmp_path):
        t = _make(str(tmp_path))
        _commit(t, [{"id": 1, "v": 1.0}])
        before = t.latest_snapshot().id
        assert remove_unexisting_files(t) == []
        assert t.latest_snapshot().id == before


class TestCompactManifests:
    def test_merges_to_one_manifest(self, tmp_path):
        # high merge-min so commits accumulate many small manifests
        t = _make(str(tmp_path), {"manifest.merge-min-count": "1000"})
        for i in range(6):
            _commit(t, [{"id": i, "v": float(i)}])
        snap = t.latest_snapshot()
        scan = t.new_scan()
        base = scan.manifest_list.read_all(snap.base_manifest_list,
                                           snap.delta_manifest_list)
        assert len(base) > 1
        sid = compact_manifests(t)
        assert sid == snap.id + 1
        t2 = FileStoreTable.load(t.path)
        snap2 = t2.latest_snapshot()
        assert snap2.commit_kind == "COMPACT"
        scan2 = t2.new_scan()
        base2 = scan2.manifest_list.read_all(snap2.base_manifest_list,
                                             snap2.delta_manifest_list)
        assert len(base2) == 1
        assert sorted(t2.to_arrow().column("id").to_pylist()) == \
            list(range(6))
        # row accounting survives the rewrite
        assert snap2.total_record_count == snap.total_record_count

    def test_empty_table_noop(self, tmp_path):
        t = _make(str(tmp_path))
        assert compact_manifests(t) is None
