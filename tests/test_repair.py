"""Repair actions: remove_unexisting_files + compact_manifest.

reference: flink/action/RemoveUnexistingFilesAction,
flink/procedure/CompactManifestProcedure.
"""

import os

import pytest

from paimon_tpu.maintenance.repair import (
    compact_manifests, remove_unexisting_files,
)
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType


def _make(tmp, opts=None):
    o = {"bucket": "1", "write-only": "true"}
    o.update(opts or {})
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options(o)
              .build())
    return FileStoreTable.create(os.path.join(tmp, "t"), schema)


def _commit(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    sid = wb.new_commit().commit(w.prepare_commit())
    w.close()
    return sid


class TestRemoveUnexistingFiles:
    def test_reconciles_after_manual_deletion(self, tmp_path):
        t = _make(str(tmp_path))
        _commit(t, [{"id": 1, "v": 1.0}])
        _commit(t, [{"id": 2, "v": 2.0}])
        # a human deletes a data file out of band
        split = t.new_read_builder().new_scan().plan().splits[0]
        victim = max(split.data_files,
                     key=lambda f: f.min_sequence_number)
        path = t.new_scan().path_factory.data_file_path(
            (), 0, victim.file_name)
        os.remove(path)
        with pytest.raises(Exception):
            t.to_arrow()
        # dry run reports without committing
        missing = remove_unexisting_files(t, dry_run=True)
        assert missing == [path]
        with pytest.raises(Exception):
            t.to_arrow()
        # repair commits DELETE entries; table is readable again
        gone = remove_unexisting_files(t)
        assert gone == [path]
        t2 = FileStoreTable.load(t.path)
        assert t2.to_arrow().column("id").to_pylist() == [1]

    def test_noop_when_all_present(self, tmp_path):
        t = _make(str(tmp_path))
        _commit(t, [{"id": 1, "v": 1.0}])
        before = t.latest_snapshot().id
        assert remove_unexisting_files(t) == []
        assert t.latest_snapshot().id == before


class TestCompactManifests:
    def test_merges_to_one_manifest(self, tmp_path):
        # high merge-min so commits accumulate many small manifests
        t = _make(str(tmp_path), {"manifest.merge-min-count": "1000"})
        for i in range(6):
            _commit(t, [{"id": i, "v": float(i)}])
        snap = t.latest_snapshot()
        scan = t.new_scan()
        base = scan.manifest_list.read_all(snap.base_manifest_list,
                                           snap.delta_manifest_list)
        assert len(base) > 1
        sid = compact_manifests(t)
        assert sid == snap.id + 1
        t2 = FileStoreTable.load(t.path)
        snap2 = t2.latest_snapshot()
        assert snap2.commit_kind == "COMPACT"
        scan2 = t2.new_scan()
        base2 = scan2.manifest_list.read_all(snap2.base_manifest_list,
                                             snap2.delta_manifest_list)
        assert len(base2) == 1
        assert sorted(t2.to_arrow().column("id").to_pylist()) == \
            list(range(6))
        # row accounting survives the rewrite
        assert snap2.total_record_count == snap.total_record_count

    def test_empty_table_noop(self, tmp_path):
        t = _make(str(tmp_path))
        assert compact_manifests(t) is None


class TestRewriteFileIndex:
    def test_retrofit_bloom_index(self, tmp_path):
        # table written WITHOUT index options
        t = _make(str(tmp_path))
        _commit(t, [{"id": i, "v": float(i)} for i in range(100)])
        split = t.new_read_builder().new_scan().plan().splits[0]
        assert all(f.embedded_index is None and not f.extra_files
                   for f in split.data_files)
        # enable the option, retrofit
        t2 = t.copy({"file-index.bloom-filter.columns": "id"})
        from paimon_tpu.maintenance.repair import rewrite_file_index
        n = rewrite_file_index(t2)
        assert n == 1
        t3 = FileStoreTable.load(t.path).copy(
            {"file-index.bloom-filter.columns": "id"})
        split = t3.new_read_builder().new_scan().plan().splits[0]
        assert any(f.embedded_index is not None or f.extra_files
                   for f in split.data_files)
        # data intact; idempotent second run
        assert t3.to_arrow().num_rows == 100
        assert rewrite_file_index(t2) == 0
        # index actually prunes: equality miss skips the file
        from paimon_tpu import predicate as P
        plan = t3.new_read_builder() \
            .with_filter(P.equal("id", 10_000)).new_scan().plan()
        assert not plan.splits or all(
            not s.data_files for s in plan.splits)

    def test_force_rebuild_after_spec_change(self, tmp_path):
        from paimon_tpu.maintenance.repair import rewrite_file_index
        t = _make(str(tmp_path),
                  {"file-index.bloom-filter.columns": "id"})
        _commit(t, [{"id": i, "v": float(i)} for i in range(50)])
        # spec changes: default run skips indexed files, force rebuilds
        t2 = FileStoreTable.load(t.path).copy(
            {"file-index.bloom-filter.columns": "id",
             "file-index.bitmap.columns": "id"})
        assert rewrite_file_index(t2) == 0
        assert rewrite_file_index(t2, force=True) == 1
        # force is re-runnable (sidecar name owned by the rewrite)
        assert rewrite_file_index(t2, force=True) == 1
        assert FileStoreTable.load(t.path).to_arrow().num_rows == 50


class TestRemoveUnexistingManifests:
    def test_repair_after_manifest_deletion(self, tmp_path):
        import glob
        from paimon_tpu.maintenance.repair import (
            remove_unexisting_manifests,
        )
        t = _make(str(tmp_path), {"manifest.merge-min-count": "1000"})
        for i in range(4):
            _commit(t, [{"id": i, "v": float(i)}])
        # a human deletes one manifest file out of band
        manifests = sorted(glob.glob(
            os.path.join(t.path, "manifest", "manifest-*")))
        data_manifests = [m for m in manifests
                          if "list" not in m.rsplit("/", 1)[-1]]
        os.remove(data_manifests[1])
        # a warm delta-apply plan cache (populated by the commits
        # above) legitimately masks the out-of-band deletion in this
        # process; the corruption bites a COLD planner — any fresh
        # process — which is who this repair exists for
        from paimon_tpu.core.plan_cache import reset_plan_caches
        reset_plan_caches()
        with pytest.raises(Exception):
            t.to_arrow()
        sid = remove_unexisting_manifests(t)
        assert sid is not None
        t2 = FileStoreTable.load(t.path)
        got = sorted(t2.to_arrow().column("id").to_pylist())
        # the deleted manifest's entries are gone; the rest survive
        assert len(got) == 3 and set(got) <= {0, 1, 2, 3}


class TestBranchAndDatabaseProcedures:
    def test_rename_branch_and_compact_database(self, tmp_path):
        from paimon_tpu.catalog import create_catalog
        from paimon_tpu.sql import SQLContext
        cat = create_catalog({"warehouse": str(tmp_path / "wh")})
        ctx = SQLContext(cat)
        ctx.sql("CREATE DATABASE db")
        for name in ("x", "y"):
            ctx.sql(f"CREATE TABLE db.{name} (id BIGINT NOT NULL, "
                    "PRIMARY KEY (id)) WITH ('bucket'='1')")
            ctx.sql(f"INSERT INTO db.{name} VALUES (1), (2)")
        out = ctx.sql("CALL sys.compact_database('db', 'full')")
        assert "2 tables compacted" in str(out.to_pylist())

        ctx.sql("CALL sys.create_branch('db.x', 'dev')")
        ctx.sql("CALL sys.rename_branch('db.x', 'dev', 'feat')")
        t = cat.get_table("db.x")
        assert t.branch_manager.branch_exists("feat")
        assert not t.branch_manager.branch_exists("dev")

    def test_sql_rewrite_file_index_actually_builds(self, tmp_path):
        """Regression: the procedure must NOT be shadowed by the
        analyze alias."""
        from paimon_tpu.catalog import create_catalog
        from paimon_tpu.sql import SQLContext
        cat = create_catalog({"warehouse": str(tmp_path / "wh3")})
        ctx = SQLContext(cat)
        ctx.sql("CREATE DATABASE db")
        ctx.sql("CREATE TABLE db.t (id BIGINT NOT NULL, "
                "PRIMARY KEY (id)) WITH ('bucket'='1')")
        ctx.sql("INSERT INTO db.t VALUES (1), (2)")
        ctx.sql("ALTER TABLE db.t SET "
                "('file-index.bloom-filter.columns'='id')")
        out = ctx.sql("CALL sys.rewrite_file_index('db.t')")
        assert "files indexed" in str(out.to_pylist())
        t = cat.get_table("db.t")
        split = t.new_read_builder().new_scan().plan().splits[0]
        assert any(f.embedded_index is not None or f.extra_files
                   for f in split.data_files)

    def test_repair_fixes_total_record_count(self, tmp_path):
        import glob
        from paimon_tpu.maintenance.repair import (
            remove_unexisting_manifests,
        )
        t = _make(str(tmp_path), {"manifest.merge-min-count": "1000"})
        for i in range(4):
            _commit(t, [{"id": i, "v": float(i)}])
        data_manifests = [m for m in sorted(glob.glob(
            os.path.join(t.path, "manifest", "manifest-*")))
            if "list" not in m.rsplit("/", 1)[-1]]
        os.remove(data_manifests[1])
        remove_unexisting_manifests(t)
        t2 = FileStoreTable.load(t.path)
        snap = t2.latest_snapshot()
        # the snapshot's accounting matches what is actually readable
        assert snap.total_record_count == t2.to_arrow().num_rows == 3
