"""Crash-point sweeps over every maintenance path (tests/crash_sweep.py
harness): compaction, snapshot expire, orphan clean, rescale and tag
creation each get every one of their mutating IO ops killed once; after
each injected crash the table must stay readable at its last snapshot,
a restart must converge, and fsck must find the converged graph clean.
"""

import os
import time

import pytest

from paimon_tpu.maintenance import expire_snapshots, remove_orphan_files
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType
from tests.crash_sweep import crash_point_sweep

FAR_FUTURE_MS = 10 ** 18


def _schema(opts=None):
    return (Schema.builder()
            .column("id", BigIntType(False))
            .column("v", DoubleType())
            .primary_key("id")
            .options({"bucket": "1", "write-only": "true",
                      **(opts or {})})
            .build())


def _commit(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    sid = wb.new_commit().commit(w.prepare_commit())
    w.close()
    return sid


def _make_factory(tmp_path, opts=None, commits=3):
    def make(tag):
        table = FileStoreTable.create(str(tmp_path / tag),
                                      _schema(opts))
        for i in range(commits):
            _commit(table, [{"id": j, "v": float(i)}
                            for j in range(i, i + 4)])
        return table
    return make


def _final_rows(commits=3):
    """Merged expectation of _make_factory's writes (last write wins)."""
    out = {}
    for i in range(commits):
        for j in range(i, i + 4):
            out[j] = float(i)
    return [{"id": k, "v": v} for k, v in sorted(out.items())]


def _rows(table):
    return sorted(table.to_arrow().to_pylist(), key=lambda r: r["id"])


def _assert_chain_intact(table):
    """Snapshot chain contiguous, hints resolvable (satellite:
    earliest/latest hints consistent or recoverable)."""
    sm = table.snapshot_manager
    ids = sm._all_ids()
    assert ids, "no snapshots left"
    assert ids == list(range(ids[0], ids[-1] + 1)), \
        f"snapshot chain has a gap: {ids}"
    earliest = sm.earliest_snapshot_id()
    latest = sm.latest_snapshot_id()
    assert earliest == ids[0] and latest == ids[-1]
    assert sm.latest_snapshot() is not None


def test_compaction_sweep(tmp_path):
    expected = _final_rows()

    def converged(table):
        assert _rows(table) == expected
        # fully compacted: one top-level run
        for s in table.new_read_builder().new_scan().plan().splits:
            assert len(s.data_files) == 1

    pts = crash_point_sweep(
        _make_factory(tmp_path),
        lambda t: t.compact(full=True),
        name="sweep-compact", verify_converged=converged)
    assert len(pts) >= 3
    assert {"write_bytes", "try_to_write_atomic"} <= \
        {p.op for p in pts}


def test_pipelined_write_sweep(tmp_path):
    """The async flush path (parallel/write_pipeline.py): kill every
    mutating op of a pipelined write+commit once — including uploads
    running on pool workers.  The injected error must reach the
    prepare-commit barrier (after write.retry exhausts), the crashed
    table must stay readable at its last snapshot, a restart must
    converge to the same rows, and fsck must be clean."""
    rows = [{"id": j, "v": float(j % 7)} for j in range(120)]
    expected = sorted(({"id": r["id"], "v": r["v"]}
                       for r in {r["id"]: r for r in rows}.values()),
                      key=lambda r: r["id"])

    def make(tag):
        # bucket=2 + a tiny buffer: several pooled flushes per bucket
        return FileStoreTable.create(
            str(tmp_path / tag),
            _schema({"bucket": "2",
                     "write.flush.parallelism": "4",
                     "write.retry.max-attempts": "2",
                     "write.retry.backoff": "0",
                     "write-buffer-size": "2 kb"}))

    def op(table):
        wb = table.new_batch_write_builder()
        w = wb.new_write()
        try:
            w.write_dicts(rows)
            wb.new_commit().commit(w.prepare_commit())
        finally:
            w.close()

    def converged(table):
        assert _rows(table) == expected

    pts = crash_point_sweep(
        make, op, name="sweep-pipelined-write",
        verify_converged=converged,
        verify_after_crash=lambda table, point: table.to_arrow())
    assert len(pts) >= 3
    # data-file uploads (worker threads) and the snapshot CAS were both
    # among the killed ops
    assert {"write_bytes", "try_to_write_atomic"} <= {p.op for p in pts}


def test_expire_sweep(tmp_path):
    def op(table):
        expire_snapshots(table, retain_max=1, retain_min=1,
                         older_than_ms=FAR_FUTURE_MS)

    def after_crash(table, point):
        # the latest snapshot never expires; it must stay readable and
        # the chain must be a contiguous suffix with recoverable hints
        assert _rows(table) == _final_rows()
        _assert_chain_intact(table)

    def converged(table):
        assert table.snapshot_manager.snapshot_count() == 1
        assert _rows(table) == _final_rows()
        _assert_chain_intact(table)

    pts = crash_point_sweep(
        _make_factory(tmp_path), op, name="sweep-expire",
        verify_after_crash=after_crash, verify_converged=converged)
    assert any(p.op == "delete_quietly" for p in pts), \
        "expire sweep never killed a file deletion"


def test_orphan_clean_sweep(tmp_path):
    def make(tag):
        table = _make_factory(tmp_path)(tag)
        # seed orphans in the data and manifest planes
        fio = table.file_io
        bucket_dir = f"{table.path}/bucket-0"
        for i in range(3):
            fio.write_bytes(f"{bucket_dir}/data-orphan-{i}.parquet",
                            b"junk" * 10)
        fio.write_bytes(f"{table.path}/manifest/manifest-orphan-0",
                        b"junk")
        return table

    def op(table):
        remove_orphan_files(table, older_than_ms=FAR_FUTURE_MS)

    def converged(table):
        assert _rows(table) == _final_rows()
        leftovers = [s.path for s in
                     table.file_io.list_status(f"{table.path}/bucket-0")
                     if "orphan" in os.path.basename(s.path)]
        assert leftovers == []

    pts = crash_point_sweep(make, op, name="sweep-orphan",
                            verify_converged=converged)
    assert len(pts) >= 4          # 4 orphans -> >= 4 delete points
    assert all(p.op == "delete_quietly" for p in pts)


def test_rescale_sweep(tmp_path):
    expected = _final_rows()

    def op(table):
        table.rescale_buckets(2)

    def converged(table):
        # rescale commits a new schema; the in-memory instance that ran
        # the restart predates it — reload to see the converged state
        reloaded = FileStoreTable.load(table.path,
                                       file_io=table.file_io)
        assert _rows(reloaded) == expected
        assert reloaded.options.bucket == 2

    pts = crash_point_sweep(
        _make_factory(tmp_path), op, name="sweep-rescale",
        verify_converged=converged)
    assert len(pts) >= 4


def test_tag_creation_sweep(tmp_path):
    def op(table):
        if not table.tag_manager.tag_exists("nightly"):
            table.create_tag("nightly", 3)

    def converged(table):
        assert table.tag_manager.tag_exists("nightly")
        assert table.tag_manager.get_tag("nightly").id == 3
        _assert_chain_intact(table)

    pts = crash_point_sweep(
        _make_factory(tmp_path), op, name="sweep-tag",
        verify_converged=converged)
    assert len(pts) >= 1


def test_expire_then_tag_interplay(tmp_path):
    """Tag creation pins its snapshot against a later expire even when
    both maintenance ops crash and restart around each other."""
    make = _make_factory(tmp_path, commits=4)

    def op(table):
        if not table.tag_manager.tag_exists("pin"):
            table.create_tag("pin", 2)
        expire_snapshots(table, retain_max=1, retain_min=1,
                         older_than_ms=FAR_FUTURE_MS)

    def converged(table):
        assert table.tag_manager.tag_exists("pin")
        # the tagged snapshot's files survive: reading the tag works
        tagged = table.tag_manager.get_tag("pin")
        scan = table.new_scan()
        for e in scan.read_entries(tagged):
            partition = scan._partition_codec.from_bytes(e.partition)
            path = e.file.external_path or \
                scan.path_factory.data_file_path(
                    partition, e.bucket, e.file.file_name)
            assert table.file_io.exists(path)

    pts = crash_point_sweep(make, op, name="sweep-tag-expire",
                            verify_converged=converged)
    assert len(pts) >= 3


def test_sweep_reports_killed_op(tmp_path):
    """The harness names the exact op killed (satellite: op traces)."""
    pts = crash_point_sweep(
        _make_factory(tmp_path, commits=1),
        lambda t: t.compact(full=True), name="sweep-trace")
    for p in pts:
        assert p.op and p.path
        assert str(p).startswith(f"crash point #{p.index} ")


def test_stream_checkpoint_sweep(tmp_path):
    """Kill EVERY mutating op in the daemon's offset-commit path
    (ingest writes -> flush uploads -> manifest encodes -> snapshot CAS
    -> hint writes) and assert the exactly-once contract holds at each
    crash point: readable after crash, a restarted checkpoint replays
    from the recovered offset and converges to exactly one copy of
    every event, offsets land atomically with the data, fsck clean."""
    from paimon_tpu.cdc.source import MemoryCdcSource
    from paimon_tpu.service.stream_daemon import (
        checkpoint_once, recover_checkpoint,
    )

    events = [{"op": "c", "after": {"id": i % 3, "v": float(i)}}
              for i in range(6)]
    expected = [{"id": 0, "v": 3.0}, {"id": 1, "v": 4.0},
                {"id": 2, "v": 5.0}]

    def op(table):
        checkpoint_once(table, MemoryCdcSource(events))

    def converged(table):
        assert _rows(table) == expected
        off, ckpt = recover_checkpoint(table, "stream-daemon")
        assert off == len(events) - 1
        assert ckpt >= 1
        # the offset is atomic with the data: every daemon snapshot
        # carries one, and they never regress
        offs = [int(s.properties["stream.source.offset"])
                for s in table.snapshot_manager.snapshots()
                if s.commit_user == "stream-daemon" and s.properties]
        assert offs == sorted(set(offs))

    pts = crash_point_sweep(_make_factory(tmp_path, commits=0), op,
                            name="sweep-stream-ckpt",
                            verify_converged=converged)
    assert len(pts) >= 5
