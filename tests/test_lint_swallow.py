"""Tier-1 lint: no NEW silent broad-exception swallowing in
paimon_tpu/, no bare thread construction outside parallel/, no bare
`time.sleep(` outside utils/backoff.py, and no raw `socket` /
`selectors` usage outside service/async_server.py.

An `except Exception: pass` (or bare except / continue body) hides
every error class — including the transient faults the maintenance
plane must now retry or propagate (parallel/fault.py).

Every handler that catches Exception/BaseException/bare and does
nothing must appear in the reviewed allowlist below; the comparison is
exact both ways, so removing one must also prune the list.  Narrow
typed catches (OSError, ValueError, ...) are out of scope — they are
deliberate, local decisions.

`threading.Thread(` outside paimon_tpu/parallel/ is banned: all
threads and pools go through parallel/executors.py (spawn_thread /
new_thread_pool) so every worker carries an attributable name and the
no-leaked-thread tier-1 tests can key on it.

`time.sleep(` outside paimon_tpu/utils/backoff.py is banned: every
wait in library code must be deadline-aware and injectable — either a
`Backoff.pause()` (retry ladders) or `wait_for()` (one-shot waits),
both of which cap to the current request deadline
(utils/deadline.py) and raise once it is spent.  A bare sleep is an
un-interruptible stall a timed-out request cannot escape.  Injectable
sleeps stored as attributes (`self._sleep(...)`) are fine — only
direct `time.sleep` / `from time import sleep` CALLS are flagged.
"""

import ast
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "paimon_tpu")

# reviewed silent broad handlers: "<relpath>::<function>" — each is a
# genuine best-effort path whose failure must not fail the caller
ALLOWED_SILENT_BROAD = {
    # quiet delete is the two-phase-commit cleanup contract
    "paimon_tpu/fs/fileio.py::delete_quietly",
    # privilege mutation on a catalog without the privilege meta table
    "paimon_tpu/catalog/privilege.py::_mutate",
    # warehouse-wide iteration skips tables that fail to load
    "paimon_tpu/catalog/system.py::_each_table",
    # EXISTS rewrite falls back to the unoptimized plan
    "paimon_tpu/sql/executor.py::_rewrite_exists",
}

_BROAD = {"Exception", "BaseException"}


def _broad_names(type_node):
    """Exception class names in an except clause that are broad."""
    if type_node is None:
        return ["<bare>"]                      # bare except
    nodes = type_node.elts if isinstance(type_node, ast.Tuple) \
        else [type_node]
    out = []
    for n in nodes:
        name = n.id if isinstance(n, ast.Name) else \
            n.attr if isinstance(n, ast.Attribute) else None
        if name in _BROAD:
            out.append(name)
    return out


def _silent_broad_handlers():
    found = set()
    for root, dirs, files in os.walk(PKG):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            path = os.path.join(root, f)
            rel = os.path.relpath(path, REPO)
            with open(path, encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), rel)
            funcs = [n for n in ast.walk(tree)
                     if isinstance(n, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))]
            for node in ast.walk(tree):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                if len(node.body) != 1 or not isinstance(
                        node.body[0], (ast.Pass, ast.Continue)):
                    continue
                if not _broad_names(node.type):
                    continue
                enc = "<module>"
                for fn in funcs:
                    if fn.lineno <= node.lineno <= fn.end_lineno:
                        enc = fn.name
                found.add(f"{rel}::{enc}")
    return found


def _bare_thread_constructions():
    """`threading.Thread(...)` / `Thread(...)` call sites outside
    paimon_tpu/parallel/, as '<relpath>:<line>' strings."""
    found = []
    for root, dirs, files in os.walk(PKG):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            path = os.path.join(root, f)
            rel = os.path.relpath(path, REPO).replace(os.sep, "/")
            if rel.startswith("paimon_tpu/parallel/"):
                continue               # the one reviewed home of threads
            with open(path, encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), rel)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                name = fn.attr if isinstance(fn, ast.Attribute) else \
                    fn.id if isinstance(fn, ast.Name) else None
                if name == "Thread":
                    found.append(f"{rel}:{node.lineno}")
    return found


def _bare_sleep_calls():
    """Direct `time.sleep(...)` / `sleep(...)`-imported-from-time call
    sites outside paimon_tpu/utils/backoff.py, as '<relpath>:<line>'
    strings."""
    found = []
    for root, dirs, files in os.walk(PKG):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            path = os.path.join(root, f)
            rel = os.path.relpath(path, REPO).replace(os.sep, "/")
            if rel == "paimon_tpu/utils/backoff.py":
                continue       # the one reviewed home of real sleeps
            with open(path, encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), rel)
            # names bound by `from time import sleep` (any alias)
            time_sleep_names = set()
            for node in ast.walk(tree):
                if isinstance(node, ast.ImportFrom) and \
                        node.module == "time":
                    for alias in node.names:
                        if alias.name == "sleep":
                            time_sleep_names.add(
                                alias.asname or alias.name)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                hit = (isinstance(fn, ast.Attribute) and
                       fn.attr == "sleep" and
                       isinstance(fn.value, ast.Name) and
                       fn.value.id in ("time", "_time")) or \
                      (isinstance(fn, ast.Name) and
                       fn.id in time_sleep_names)
                if hit:
                    found.append(f"{rel}:{node.lineno}")
    return found


def _distributed_initialize_calls():
    """`jax.distributed.initialize(...)` bring-up sites outside
    paimon_tpu/parallel/multihost.py, as '<relpath>:<line>' strings —
    in every spelling: the attribute chain `<x>.distributed
    .initialize(...)`, the import form `from jax.distributed import
    initialize`, and `from jax import distributed as d` followed by
    `d.initialize(...)`.  multihost.initialize is the ONE reviewed
    bring-up: it opts the CPU backend into Gloo cross-process
    collectives BEFORE the backend initializes (multihost.py:57); a
    direct call elsewhere bypasses that and resurrects the
    'Multiprocess computations aren't implemented' failure mode."""
    found = []
    for root, dirs, files in os.walk(PKG):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            path = os.path.join(root, f)
            rel = os.path.relpath(path, REPO).replace(os.sep, "/")
            if rel == "paimon_tpu/parallel/multihost.py":
                continue       # the one reviewed bring-up path
            with open(path, encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), rel)
            # names bound by `from jax.distributed import initialize`
            # (any alias) and module aliases from
            # `from jax import distributed [as d]`
            init_names = set()
            dist_aliases = set()
            for node in ast.walk(tree):
                if not isinstance(node, ast.ImportFrom):
                    continue
                if node.module == "jax.distributed":
                    for alias in node.names:
                        if alias.name == "initialize":
                            init_names.add(alias.asname or alias.name)
                            found.append(f"{rel}:{node.lineno}")
                elif node.module == "jax":
                    for alias in node.names:
                        if alias.name == "distributed":
                            dist_aliases.add(alias.asname or alias.name)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                hit = (isinstance(fn, ast.Attribute) and
                       fn.attr == "initialize" and
                       ((isinstance(fn.value, ast.Attribute) and
                         fn.value.attr == "distributed") or
                        (isinstance(fn.value, ast.Name) and
                         fn.value.id in dist_aliases))) or \
                      (isinstance(fn, ast.Name) and
                       fn.id in init_names)
                if hit:
                    found.append(f"{rel}:{node.lineno}")
    return found


_COLLECTIVES = {"sync_global_devices", "broadcast_one_to_all",
                "process_allgather"}


def _raw_collective_calls():
    """`sync_global_devices` / `broadcast_one_to_all` /
    `process_allgather` call sites (and their `from ... import`
    bindings) outside paimon_tpu/parallel/multihost.py, as
    '<relpath>:<line>' strings.  multihost.py's barrier() /
    broadcast_value() / allgather_bytes() are the ONE reviewed wrap:
    they are deadline-bounded (a spent request budget never enters a
    collective it may not leave), record barrier_wait_ms, and degrade
    to single-process no-ops.  A raw jax.experimental.multihost_utils
    call elsewhere gets none of that — and a hung collective with a
    dead peer is exactly the failure the lease-based maintenance
    plane exists to tolerate."""
    found = []
    for root, dirs, files in os.walk(PKG):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            path = os.path.join(root, f)
            rel = os.path.relpath(path, REPO).replace(os.sep, "/")
            if rel == "paimon_tpu/parallel/multihost.py":
                continue       # the one reviewed home of collectives
            with open(path, encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), rel)
            # names bound by `from jax.experimental.multihost_utils
            # import sync_global_devices [as x]` (any alias)
            bound = set()
            for node in ast.walk(tree):
                if isinstance(node, ast.ImportFrom) and node.module \
                        and node.module.endswith("multihost_utils"):
                    for alias in node.names:
                        if alias.name in _COLLECTIVES:
                            bound.add(alias.asname or alias.name)
                            found.append(f"{rel}:{node.lineno}")
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                hit = (isinstance(fn, ast.Attribute) and
                       fn.attr in _COLLECTIVES) or \
                      (isinstance(fn, ast.Name) and fn.id in bound)
                if hit:
                    found.append(f"{rel}:{node.lineno}")
    return found


_NET_MODULES = {"socket", "selectors"}


def _raw_network_imports():
    """`import socket` / `import selectors` (and their from-import
    forms, any alias) outside paimon_tpu/service/async_server.py, as
    '<relpath>:<line>' strings.  The event-loop request engine is the
    ONE reviewed home of non-blocking socket code: its loop owns
    every fd, bounds connections and pipelining, measures loop lag
    and shuts down cleanly — an ad-hoc `socket`/`selectors` loop
    elsewhere gets none of that (and the no-leaked-thread/fd tier-1
    hygiene cannot see it).  HTTP clients use http.client, servers
    use service/async_server.AsyncHttpServer."""
    found = []
    for root, dirs, files in os.walk(PKG):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for f in sorted(files):
            if not f.endswith(".py"):
                continue
            path = os.path.join(root, f)
            rel = os.path.relpath(path, REPO).replace(os.sep, "/")
            if rel == "paimon_tpu/service/async_server.py":
                continue       # the one reviewed home of raw sockets
            with open(path, encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), rel)
            for node in ast.walk(tree):
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        if alias.name.split(".")[0] in _NET_MODULES:
                            found.append(f"{rel}:{node.lineno}")
                elif isinstance(node, ast.ImportFrom):
                    if node.module and \
                            node.module.split(".")[0] in _NET_MODULES:
                        found.append(f"{rel}:{node.lineno}")
    return found


def test_no_raw_sockets_outside_async_server():
    offenders = _raw_network_imports()
    assert not offenders, (
        f"raw socket/selectors import outside "
        f"service/async_server.py — ad-hoc network loops are banned: "
        f"serve through AsyncHttpServer (bounded, observable, "
        f"shutdown-clean) and talk HTTP through http.client: "
        f"{sorted(offenders)}")


# device-kernel modules whose bodies must stay traceable end to end:
# a host materialization here silently reintroduces the round-trip the
# device decode plane exists to remove (the host boundary lives in
# format/rawpage.py, which orchestrates these kernels)
_KERNEL_MODULES = (
    "paimon_tpu/ops/decode.py",
    "paimon_tpu/ops/pallas_kernels.py",
)


def _host_materialization_calls():
    """`np.asarray(...)` / `<x>.tolist()` / `jax.device_get(...)` call
    sites inside the device-kernel modules, as '<relpath>:<line>'
    strings.  A line carrying an explicit `# host-ok:` marker (with a
    reason) is a reviewed exemption — same spirit as the time.sleep /
    threading.Thread allowlists."""
    found = []
    for rel in _KERNEL_MODULES:
        path = os.path.join(REPO, rel)
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        lines = src.splitlines()
        tree = ast.parse(src, rel)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not isinstance(fn, ast.Attribute):
                continue
            hit = (fn.attr == "asarray"
                   and isinstance(fn.value, ast.Name)
                   and fn.value.id in ("np", "numpy")) \
                or fn.attr == "tolist" \
                or (fn.attr == "device_get"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id == "jax")
            if not hit:
                continue
            if "# host-ok:" in lines[node.lineno - 1]:
                continue
            found.append(f"{rel}:{node.lineno}")
    return found


def test_no_host_materialization_in_kernel_modules():
    offenders = _host_materialization_calls()
    assert not offenders, (
        f"host materialization (np.asarray / .tolist() / "
        f"jax.device_get) inside a device-kernel module — keep the "
        f"kernel traceable and materialize at the format/rawpage.py "
        f"boundary instead, or mark a reviewed exception with "
        f"`# host-ok: <reason>`: {sorted(offenders)}")


def test_no_raw_collectives_outside_multihost():
    offenders = _raw_collective_calls()
    assert not offenders, (
        f"raw sync_global_devices / broadcast_one_to_all / "
        f"process_allgather outside parallel/multihost.py — use "
        f"multihost.barrier() / broadcast_value() / allgather_bytes(), "
        f"the deadline-bounded, metric-instrumented agreement "
        f"primitives: {sorted(offenders)}")


def test_no_distributed_initialize_outside_multihost():
    offenders = _distributed_initialize_calls()
    assert not offenders, (
        f"direct jax.distributed.initialize( outside "
        f"parallel/multihost.py — use multihost.initialize(), which "
        f"opts the CPU backend into Gloo collectives before the "
        f"backend comes up (skipping it breaks multi-process CPU "
        f"meshes): {sorted(offenders)}")


def test_no_bare_sleeps_outside_backoff():
    offenders = _bare_sleep_calls()
    assert not offenders, (
        f"bare time.sleep( outside utils/backoff.py — every wait must "
        f"be deadline-aware/injectable: use Backoff.pause() for retry "
        f"ladders or utils.backoff.wait_for() for one-shot waits: "
        f"{sorted(offenders)}")


def test_no_bare_threads_outside_parallel():
    offenders = _bare_thread_constructions()
    assert not offenders, (
        f"bare threading.Thread( outside parallel/ — use "
        f"parallel/executors.py spawn_thread/new_thread_pool so the "
        f"thread is named and reviewable: {sorted(offenders)}")


def test_no_unreviewed_silent_exception_swallowing():
    found = _silent_broad_handlers()
    new = found - ALLOWED_SILENT_BROAD
    assert not new, (
        f"new silent except-Exception swallowing (handle the error, "
        f"propagate it, or add to the reviewed allowlist): "
        f"{sorted(new)}")
    stale = ALLOWED_SILENT_BROAD - found
    assert not stale, (
        f"allowlist entries no longer present — prune them: "
        f"{sorted(stale)}")
