"""Tier-1 hygiene lints, as thin wrappers over the analysis engine.

These seven checks each used to be a standalone AST walk re-parsing
every file under paimon_tpu/ (seven full-tree parses per run).  They
are now RULES in paimon_tpu/analysis/ running over one shared program
model — the session-scoped `lint_report` fixture performs the single
parse+run, and each test here just asserts its rule is clean.  The
reviewed exemptions moved from in-test allowlists to uniform
`# lint-ok: <rule> <reason>` markers at the exempted sites (the
engine flags stale and reasonless markers itself).

Rule semantics (full catalog in docs/static_analysis.md):

* swallow — no NEW silent broad-exception swallowing: an
  `except Exception: pass` hides every error class, including the
  transient faults the maintenance plane must retry or propagate;
* threads — `threading.Thread(` outside parallel/ is banned: all
  threads go through parallel/executors.py so every worker carries an
  attributable name;
* sleeps — `time.sleep(` outside utils/backoff.py is banned: every
  wait must be deadline-aware and injectable (Backoff.pause /
  wait_for);
* sockets — raw `socket`/`selectors` imports outside
  service/async_server.py are banned: the event-loop engine is the
  one reviewed home of non-blocking socket code;
* collectives — raw multihost_utils collectives outside
  parallel/multihost.py are banned: the wrapped primitives are
  deadline-bounded and metric-instrumented;
* distributed-init — `jax.distributed.initialize(` outside
  parallel/multihost.py resurrects the no-Gloo-collectives failure
  mode;
* host-materialization — np.asarray / .tolist() / jax.device_get
  inside the device-kernel modules silently reintroduces the host
  round-trip the decode plane removed.
"""


def _clean(lint_report, rule_id):
    offenders = [f"{f.file}:{f.line}" for f in
                 lint_report.unsuppressed_by_rule(rule_id)]
    assert not offenders, (
        f"rule '{rule_id}' findings (fix the code or add a reviewed "
        f"`# lint-ok: {rule_id} <reason>` marker): {offenders}\n"
        + "\n".join(str(f) for f in
                    lint_report.unsuppressed_by_rule(rule_id)))


def test_no_unreviewed_silent_exception_swallowing(lint_report):
    _clean(lint_report, "swallow")


def test_no_bare_threads_outside_parallel(lint_report):
    _clean(lint_report, "threads")


def test_no_bare_sleeps_outside_backoff(lint_report):
    _clean(lint_report, "sleeps")


def test_no_raw_sockets_outside_async_server(lint_report):
    _clean(lint_report, "sockets")


def test_no_raw_collectives_outside_multihost(lint_report):
    _clean(lint_report, "collectives")


def test_no_distributed_initialize_outside_multihost(lint_report):
    _clean(lint_report, "distributed-init")


def test_no_host_materialization_in_kernel_modules(lint_report):
    _clean(lint_report, "host-materialization")


def test_this_file_does_not_parse_the_tree_itself():
    """The migration's point: tier-1 lint tests consume the shared
    engine run instead of re-walking the package with their own AST
    parses and tree walks — neither may ever creep back in here."""
    with open(__file__, encoding="utf-8") as fh:
        src = fh.read()
    # concatenation keeps this test's own source from matching itself
    assert ("import " + "ast") not in src
    assert ("os." + "walk") not in src
