"""FileSystemCatalog warehouse layout + public API smoke.

reference: catalog/FileSystemCatalog.java, catalog/Identifier.java.
"""

import os

import pytest

import paimon_tpu
from paimon_tpu import Schema
from paimon_tpu.catalog import (
    DatabaseAlreadyExistsError, DatabaseNotFoundError, Identifier,
    TableAlreadyExistsError, TableNotFoundError,
)
from paimon_tpu.types import BigIntType, DoubleType


@pytest.fixture
def catalog(tmp_path):
    return paimon_tpu.create_catalog(
        {"warehouse": str(tmp_path / "wh")})


def _schema(opts=None):
    return (Schema.builder()
            .column("id", BigIntType(False))
            .column("v", DoubleType())
            .primary_key("id")
            .options({"bucket": "1", **(opts or {})})
            .build())


def test_database_lifecycle(catalog):
    assert catalog.list_databases() == []
    catalog.create_database("db1", properties={"owner": "x"})
    assert catalog.list_databases() == ["db1"]
    assert catalog.load_database_properties("db1") == {"owner": "x"}
    with pytest.raises(DatabaseAlreadyExistsError):
        catalog.create_database("db1")
    catalog.create_database("db1", ignore_if_exists=True)
    catalog.drop_database("db1")
    assert catalog.list_databases() == []
    with pytest.raises(DatabaseNotFoundError):
        catalog.drop_database("db1")


def test_table_lifecycle(catalog):
    catalog.create_database("db")
    t = catalog.create_table("db.t1", _schema())
    assert catalog.list_tables("db") == ["t1"]
    # warehouse layout: <wh>/db.db/t1
    assert t.path.endswith("db.db/t1")

    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts([{"id": 1, "v": 1.0}])
    wb.new_commit().commit(w.prepare_commit())

    t2 = catalog.get_table(Identifier("db", "t1"))
    assert t2.to_arrow().num_rows == 1

    with pytest.raises(TableAlreadyExistsError):
        catalog.create_table("db.t1", _schema())
    catalog.rename_table("db.t1", "db.t2")
    assert catalog.list_tables("db") == ["t2"]
    with pytest.raises(TableNotFoundError):
        catalog.get_table("db.t1")
    catalog.drop_table("db.t2")
    assert catalog.list_tables("db") == []


def test_drop_database_cascade(catalog):
    catalog.create_database("db")
    catalog.create_table("db.t", _schema())
    with pytest.raises(ValueError):
        catalog.drop_database("db")
    catalog.drop_database("db", cascade=True)
    assert catalog.list_databases() == []


def test_identifier_parse():
    i = Identifier.parse("db.t")
    assert (i.database, i.table, i.branch) == ("db", "t", None)
    i2 = Identifier.parse("db.t$branch_b1")
    assert (i2.database, i2.table, i2.branch) == ("db", "t", "b1")
    with pytest.raises(ValueError):
        Identifier.parse("nodot")


def test_public_surface_importable():
    """Every advertised entry point must import and be callable
    (VERDICT round 1: dangling references are forbidden)."""
    import paimon_tpu
    from paimon_tpu.table import (
        FileStoreTable, BatchWriteBuilder, StreamWriteBuilder, ReadBuilder,
        DataTableStreamScan,
    )
    from paimon_tpu.catalog import FileSystemCatalog
    from paimon_tpu.parallel import merge_buckets_sharded
    assert callable(paimon_tpu.create_catalog)


def test_branch_identifier_rejected_for_ddl(catalog):
    catalog.create_database("db")
    catalog.create_table("db.t", _schema())
    with pytest.raises(ValueError):
        catalog.drop_table("db.t$branch_dev")
    with pytest.raises(ValueError):
        catalog.rename_table("db.t$branch_dev", "db.u")
    assert catalog.list_tables("db") == ["t"]
