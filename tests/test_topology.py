"""Multi-writer streaming ingest topology.

reference: flink/sink/FlinkSink.java topology (N writers keyed by
ChannelComputer + one committer), CommitterOperator exactly-once.
"""

import numpy as np
import pyarrow as pa
import pytest

from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.table.topology import StreamIngestTopology
from paimon_tpu.types import BigIntType, DoubleType


def pk_table(tmp_path, buckets=8):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": str(buckets), "write-only": "true"})
              .build())
    return FileStoreTable.create(str(tmp_path / "t"), schema)


def test_parallel_writers_checkpoint_commit(tmp_path):
    t = pk_table(tmp_path)
    topo = StreamIngestTopology(t, num_writers=4)
    rng = np.random.default_rng(0)
    expected = {}
    ckpt = 0
    for _ in range(5):                    # 5 checkpoints
        for _ in range(10):               # 10 batches each
            ids = rng.integers(0, 3000, 200)
            vals = rng.random(200)
            topo.write(pa.table({"id": pa.array(ids, pa.int64()),
                                 "v": pa.array(vals, pa.float64())}))
            for i, v in zip(ids.tolist(), vals.tolist()):
                expected[i] = v
        ckpt += 1
        sid = topo.checkpoint(ckpt)
        assert sid is not None
    topo.close()
    out = {r["id"]: r["v"] for r in t.to_arrow().to_pylist()}
    assert out == pytest.approx(expected)
    assert t.latest_snapshot().id == 5


def test_replayed_checkpoint_is_noop(tmp_path):
    t = pk_table(tmp_path)
    topo = StreamIngestTopology(t, num_writers=2)
    topo.write_dicts([{"id": 1, "v": 1.0}])
    assert topo.checkpoint(7) is not None
    # replay after "recovery": same identifier must not double-commit
    topo.write_dicts([{"id": 1, "v": 1.0}])
    assert topo.checkpoint(7) is None
    assert t.latest_snapshot().id == 1
    topo.close()


def test_bucket_ownership_keeps_sequences_disjoint(tmp_path):
    """Same key always routes to the same worker, so versions order
    correctly even across many writers."""
    t = pk_table(tmp_path, buckets=16)
    topo = StreamIngestTopology(t, num_writers=8)
    for version in range(20):
        topo.write_dicts([{"id": i, "v": float(version)}
                          for i in range(50)])
    topo.checkpoint(1)
    topo.close()
    out = t.to_arrow().to_pylist()
    assert len(out) == 50
    assert all(r["v"] == 19.0 for r in out)


def test_append_unaware_round_robin(tmp_path):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .options({"bucket": "-1"})
              .build())
    t = FileStoreTable.create(str(tmp_path / "a"), schema)
    topo = StreamIngestTopology(t, num_writers=3)
    for b in range(9):
        topo.write_dicts([{"id": b * 10 + i} for i in range(10)])
    topo.checkpoint(1)
    topo.close()
    assert t.to_arrow().num_rows == 90


def test_dynamic_bucket_refuses_parallel(tmp_path):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "-1"})
              .build())
    t = FileStoreTable.create(str(tmp_path / "d"), schema)
    with pytest.raises(ValueError, match="dynamic-bucket"):
        StreamIngestTopology(t, num_writers=4)
