"""Merge-engine semantics: sequence groups, partial-update matrix, long
string keys in agg merges, extra aggregators.

reference oracle: mergetree/compact/PartialUpdateMergeFunction.java
(sequence groups), aggregate/FieldCollectAgg, FieldMergeMapAgg.
"""

import os

import pytest

from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, IntType, VarCharType


def _commit(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    wb.new_commit().commit(w.prepare_commit())
    w.close()


def _pu_table(tmp_warehouse, opts=None):
    options = {"bucket": "1", "merge-engine": "partial-update",
               "write-only": "true"}
    options.update(opts or {})
    schema = (Schema.builder()
              .column("k", BigIntType(False))
              .column("a", IntType())
              .column("b", IntType())
              .column("g1_seq", IntType())
              .column("c", IntType())
              .primary_key("k")
              .options(options)
              .build())
    return FileStoreTable.create(os.path.join(tmp_warehouse, "t"), schema)


def test_sequence_group_out_of_order_update_ignored(tmp_warehouse):
    """BASELINE config-3 shape: columns a,b update only when g1_seq
    advances; c follows the global order."""
    table = _pu_table(tmp_warehouse,
                      {"fields.g1_seq.sequence-group": "a,b"})
    _commit(table, [{"k": 1, "a": 10, "b": 10, "g1_seq": 5, "c": 1}])
    # late event: lower group sequence -> a,b must NOT regress; c updates
    _commit(table, [{"k": 1, "a": 99, "b": 99, "g1_seq": 3, "c": 2}])
    row = table.to_arrow().to_pylist()[0]
    assert (row["a"], row["b"], row["g1_seq"]) == (10, 10, 5)
    assert row["c"] == 2


def test_sequence_group_advance_overwrites(tmp_warehouse):
    table = _pu_table(tmp_warehouse,
                      {"fields.g1_seq.sequence-group": "a,b"})
    _commit(table, [{"k": 1, "a": 1, "b": 1, "g1_seq": 1, "c": 1}])
    _commit(table, [{"k": 1, "a": 2, "b": None, "g1_seq": 7, "c": None}])
    row = table.to_arrow().to_pylist()[0]
    # sequence advanced: group takes the new row's values, null included
    assert (row["a"], row["b"], row["g1_seq"]) == (2, None, 7)
    # c is plain partial-update: null does not overwrite
    assert row["c"] == 1


def test_sequence_group_null_sequence_never_updates(tmp_warehouse):
    table = _pu_table(tmp_warehouse,
                      {"fields.g1_seq.sequence-group": "a,b"})
    _commit(table, [{"k": 1, "a": 1, "b": 1, "g1_seq": 4, "c": 1}])
    _commit(table, [{"k": 1, "a": 9, "b": 9, "g1_seq": None, "c": 9}])
    row = table.to_arrow().to_pylist()[0]
    assert (row["a"], row["b"], row["g1_seq"]) == (1, 1, 4)
    assert row["c"] == 9


def test_sequence_group_tie_later_row_wins(tmp_warehouse):
    table = _pu_table(tmp_warehouse,
                      {"fields.g1_seq.sequence-group": "a,b"})
    _commit(table, [{"k": 1, "a": 1, "b": 1, "g1_seq": 5, "c": 1}])
    _commit(table, [{"k": 1, "a": 2, "b": 2, "g1_seq": 5, "c": 2}])
    row = table.to_arrow().to_pylist()[0]
    assert (row["a"], row["b"]) == (2, 2)


def test_two_sequence_groups_independent(tmp_warehouse):
    options = {"bucket": "1", "merge-engine": "partial-update",
               "write-only": "true",
               "fields.s1.sequence-group": "a",
               "fields.s2.sequence-group": "b"}
    schema = (Schema.builder()
              .column("k", BigIntType(False))
              .column("a", IntType()).column("s1", IntType())
              .column("b", IntType()).column("s2", IntType())
              .primary_key("k").options(options).build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "t2"), schema)
    _commit(table, [{"k": 1, "a": 1, "s1": 10, "b": 1, "s2": 1}])
    _commit(table, [{"k": 1, "a": 2, "s1": 5, "b": 2, "s2": 2}])
    row = table.to_arrow().to_pylist()[0]
    assert (row["a"], row["s1"]) == (1, 10)   # s1 regressed: no update
    assert (row["b"], row["s2"]) == (2, 2)    # s2 advanced: update


def test_agg_merge_long_string_keys(tmp_warehouse):
    """Lifted limitation: string PKs longer than the 16-byte lane prefix
    must still aggregate per full key (host repair path)."""
    schema = (Schema.builder()
              .column("k", VarCharType(nullable=False))
              .column("v", BigIntType())
              .primary_key("k")
              .options({"bucket": "1", "merge-engine": "aggregation",
                        "fields.v.aggregate-function": "sum",
                        "write-only": "true"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "t"), schema)
    base = "k" * 20                       # shared 16-byte prefix
    _commit(table, [{"k": base + "A", "v": 1},
                    {"k": base + "B", "v": 10}])
    _commit(table, [{"k": base + "A", "v": 2},
                    {"k": base + "B", "v": 20},
                    {"k": "short", "v": 100}])
    rows = {r["k"]: r["v"] for r in table.to_arrow().to_pylist()}
    assert rows == {base + "A": 3, base + "B": 30, "short": 100}


def test_partial_update_remove_record_on_delete(tmp_warehouse):
    from paimon_tpu.types import RowKind

    table = _pu_table(tmp_warehouse,
                      {"partial-update.remove-record-on-delete": "true"})
    _commit(table, [{"k": 1, "a": 1, "b": 1, "g1_seq": 1, "c": 1},
                    {"k": 2, "a": 2, "b": 2, "g1_seq": 2, "c": 2}])
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts([{"k": 1, "a": None, "b": None, "g1_seq": None,
                    "c": None}], row_kinds=[RowKind.DELETE])
    wb.new_commit().commit(w.prepare_commit())
    rows = table.to_arrow().to_pylist()
    assert [r["k"] for r in rows] == [2]


def test_collect_aggregator(tmp_warehouse):
    from paimon_tpu.types import ArrayType

    schema = (Schema.builder()
              .column("k", BigIntType(False))
              .column("tags", ArrayType(VarCharType()))
              .primary_key("k")
              .options({"bucket": "1", "merge-engine": "aggregation",
                        "fields.tags.aggregate-function": "collect",
                        "write-only": "true"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "t"), schema)
    _commit(table, [{"k": 1, "tags": ["x"]}])
    _commit(table, [{"k": 1, "tags": ["y"]}])
    row = table.to_arrow().to_pylist()[0]
    assert row["tags"] == ["x", "y"]
    table.compact(full=True)
    assert table.to_arrow().to_pylist()[0]["tags"] == ["x", "y"]


def test_collect_on_non_array_rejected(tmp_warehouse):
    schema = (Schema.builder()
              .column("k", BigIntType(False))
              .column("tags", VarCharType())
              .primary_key("k")
              .options({"bucket": "1", "merge-engine": "aggregation",
                        "fields.tags.aggregate-function": "collect",
                        "write-only": "true"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "t"), schema)
    _commit(table, [{"k": 1, "tags": "x"}])
    with pytest.raises(ValueError):
        table.to_arrow()


def test_sequence_group_date_field(tmp_warehouse):
    from paimon_tpu.types import DateType
    import datetime

    options = {"bucket": "1", "merge-engine": "partial-update",
               "write-only": "true", "fields.d.sequence-group": "a"}
    schema = (Schema.builder()
              .column("k", BigIntType(False))
              .column("a", IntType()).column("d", DateType())
              .primary_key("k").options(options).build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "td"), schema)
    _commit(table, [{"k": 1, "a": 1, "d": datetime.date(2026, 7, 28)}])
    _commit(table, [{"k": 1, "a": 2, "d": datetime.date(2026, 7, 20)}])
    row = table.to_arrow().to_pylist()[0]
    assert row["a"] == 1                       # stale date: no update


def test_sequence_group_member_with_agg_function_rejected(tmp_warehouse):
    table = _pu_table(tmp_warehouse,
                      {"fields.g1_seq.sequence-group": "a,b",
                       "fields.a.aggregate-function": "sum"})
    _commit(table, [{"k": 1, "a": 1, "b": 1, "g1_seq": 1, "c": 1}])
    with pytest.raises(NotImplementedError):
        table.to_arrow()


def test_sequence_field_out_of_order_events(tmp_warehouse):
    """sequence.field: late-arriving events with larger user sequence win
    regardless of commit order (reference UserDefinedSeqComparator)."""
    schema = (Schema.builder()
              .column("k", BigIntType(False))
              .column("v", IntType())
              .column("event_time", BigIntType())
              .primary_key("k")
              .options({"bucket": "1", "write-only": "true",
                        "sequence.field": "event_time"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "sf"),
                                  schema)
    _commit(table, [{"k": 1, "v": 10, "event_time": 100}])
    # later commit with an EARLIER event time: must NOT win
    _commit(table, [{"k": 1, "v": 99, "event_time": 50}])
    row = table.to_arrow().to_pylist()[0]
    assert (row["v"], row["event_time"]) == (10, 100)
    # compaction preserves the same resolution
    table.compact(full=True)
    row = table.to_arrow().to_pylist()[0]
    assert (row["v"], row["event_time"]) == (10, 100)
    # larger event time wins
    _commit(table, [{"k": 1, "v": 42, "event_time": 200}])
    assert table.to_arrow().to_pylist()[0]["v"] == 42


def test_sequence_field_null_always_loses(tmp_warehouse):
    schema = (Schema.builder()
              .column("k", BigIntType(False))
              .column("v", IntType())
              .column("ts", BigIntType())
              .primary_key("k")
              .options({"bucket": "1", "write-only": "true",
                        "sequence.field": "ts"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "sn"),
                                  schema)
    _commit(table, [{"k": 1, "v": 1, "ts": 5}])
    _commit(table, [{"k": 1, "v": 2, "ts": None}])
    assert table.to_arrow().to_pylist()[0]["v"] == 1


def test_sequence_field_with_partial_update(tmp_warehouse):
    schema = (Schema.builder()
              .column("k", BigIntType(False))
              .column("a", IntType())
              .column("ts", BigIntType())
              .primary_key("k")
              .options({"bucket": "1", "write-only": "true",
                        "merge-engine": "partial-update",
                        "sequence.field": "ts"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "sp"),
                                  schema)
    _commit(table, [{"k": 1, "a": 1, "ts": 10}])
    _commit(table, [{"k": 1, "a": 2, "ts": 5}])   # stale event
    row = table.to_arrow().to_pylist()[0]
    assert (row["a"], row["ts"]) == (1, 10)


def test_sequence_field_first_row_rejected(tmp_warehouse):
    schema = (Schema.builder()
              .column("k", BigIntType(False)).column("ts", BigIntType())
              .primary_key("k")
              .options({"bucket": "1", "write-only": "true",
                        "merge-engine": "first-row",
                        "sequence.field": "ts"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "fr"),
                                  schema)
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    with pytest.raises(ValueError):
        w.write_dicts([{"k": 1, "ts": 1}])
        wb.new_commit().commit(w.prepare_commit())


def test_sequence_field_string_rejected(tmp_warehouse):
    schema = (Schema.builder()
              .column("k", BigIntType(False)).column("s", VarCharType())
              .primary_key("k")
              .options({"bucket": "1", "write-only": "true",
                        "sequence.field": "s"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "ss"),
                                  schema)
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    with pytest.raises(ValueError):
        w.write_dicts([{"k": 1, "s": "a"}])
        wb.new_commit().commit(w.prepare_commit())
