import json

import pytest

from paimon_tpu.fs import LocalFileIO
from paimon_tpu.schema import Schema, SchemaChange, SchemaManager, TableSchema
from paimon_tpu.types import (
    BigIntType, DoubleType, IntType, VarCharType,
)


@pytest.fixture
def manager(tmp_path):
    return SchemaManager(LocalFileIO(), str(tmp_path / "t"))


def sample_schema(**options):
    return (Schema.builder()
            .column("order_id", BigIntType(False))
            .column("dt", VarCharType(10, False))
            .column("amount", DoubleType())
            .partition_keys("dt")
            .primary_key("order_id", "dt")
            .options({"bucket": "2", **options})
            .build())


def test_create_and_read(manager):
    ts = manager.create_table(sample_schema())
    assert ts.id == 0
    latest = manager.latest()
    assert latest == ts
    assert latest.primary_keys == ["order_id", "dt"]
    assert latest.trimmed_primary_keys() == ["order_id"]
    assert latest.bucket_keys() == ["order_id"]
    # wire format has the spec'd keys
    d = json.loads(latest.to_json())
    assert d["version"] == 3
    assert d["fields"][0] == {"id": 0, "name": "order_id",
                              "type": "BIGINT NOT NULL"}


def test_create_twice_fails(manager):
    manager.create_table(sample_schema())
    with pytest.raises(RuntimeError):
        manager.create_table(sample_schema())
    # idempotent with flag
    assert manager.create_table(sample_schema(),
                                ignore_if_exists=True).id == 0


def test_alter_add_rename_drop(manager):
    manager.create_table(sample_schema())
    ts = manager.commit_changes(SchemaChange.add_column("note",
                                                        VarCharType(100)))
    assert ts.id == 1
    assert ts.field_names[-1] == "note"
    assert ts.highest_field_id == 3

    ts = manager.commit_changes(SchemaChange.rename_column("note", "memo"))
    assert "memo" in ts.field_names

    ts = manager.commit_changes(SchemaChange.drop_column("memo"))
    assert "memo" not in ts.field_names
    assert len(manager.list_all_ids()) == 4


def test_alter_validation(manager):
    manager.create_table(sample_schema())
    with pytest.raises(ValueError):
        manager.commit_changes(SchemaChange.drop_column("order_id"))
    with pytest.raises(ValueError):
        manager.commit_changes(SchemaChange.add_column("x", IntType(False)))
    with pytest.raises(ValueError):
        manager.commit_changes(SchemaChange.set_option("merge-engine",
                                                       "aggregation"))


def test_type_evolution(manager):
    manager.create_table(sample_schema())
    # widening is allowed implicitly
    ts = manager.commit_changes(
        SchemaChange.update_column_type("amount", DoubleType()))
    assert ts.id == 1
    # narrowing is allowed too — the reference admits any update whose
    # explicit cast rule resolves (SchemaManager.java:525); data casts
    # with Java truncation semantics at read time
    ts = manager.commit_changes(
        SchemaChange.update_column_type("amount", IntType()))
    assert ts.id == 2
    # pairs without a cast rule still refuse
    from paimon_tpu.types import DateType
    with pytest.raises(ValueError):
        manager.commit_changes(
            SchemaChange.update_column_type("amount", DateType()))


def test_key_value_row_type(manager):
    manager.create_table(sample_schema())
    kv = manager.latest().key_value_row_type()
    names = kv.field_names
    assert names[:3] == ["_KEY_order_id", "_SEQUENCE_NUMBER", "_VALUE_KIND"]
    assert names[3:] == ["order_id", "dt", "amount"]


def test_schema_version_compat():
    v1 = json.dumps({"version": 1, "id": 0,
                     "fields": [{"id": 0, "name": "a", "type": "INT"}],
                     "highestFieldId": 0, "partitionKeys": [],
                     "primaryKeys": [], "options": {}})
    ts = TableSchema.from_json(v1)
    assert ts.options["bucket"] == "1"
    assert ts.options["file.format"] == "orc"
