"""Persisted, incremental full-text index: BM25, segments, analyzers.

reference: paimon-full-text NativeFullTextGlobalIndexer +
paimon-eslib ESIndexGlobalIndexerFactory.java:32 / ESIndexOptions.java.
"""

import numpy as np
import pyarrow as pa

from paimon_tpu.index.fulltext import (Analyzer, FullTextIndex,
                                       PersistedFullTextIndex,
                                       full_text_search)
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, VarCharType


def docs_table(tmp_path):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("body", VarCharType.string_type())
              .options({"bucket": "-1", "row-tracking.enabled": "true"})
              .build())
    return FileStoreTable.create(str(tmp_path / "docs"), schema)


def write(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    wb.new_commit().commit(w.prepare_commit())
    w.close()


CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "a fast brown fox outpaces a slow hound",
    "lorem ipsum dolor sit amet",
    "the dog sleeps all day long",
    "quick thinking saves the day",
]


class TestAnalyzer:
    def test_lowercase_and_tokens(self):
        a = Analyzer()
        assert a.tokens("Hello, World! 42") == ["hello", "world", "42"]

    def test_stemming(self):
        a = Analyzer(stem=True)
        assert a.tokens("jumping jumped jumps") == ["jump", "jump",
                                                    "jump"]

    def test_stopwords(self):
        a = Analyzer(stopwords=["the", "a"])
        assert a.tokens("the quick a fox") == ["quick", "fox"]

    def test_cjk_bigrams(self):
        a = Analyzer()
        toks = a.tokens("日本語テキスト")
        assert all(len(t) == 2 for t in toks)
        assert "日本" in toks and "本語" in toks

    def test_mixed_cjk_latin(self):
        a = Analyzer()
        toks = a.tokens("jax高速化library")
        assert "jax" in toks and "library" in toks and "高速" in toks

    def test_roundtrip_config(self):
        a = Analyzer(stem=True, stopwords=["x"], min_token_len=2)
        b = Analyzer.from_json(a.to_json())
        assert b.stem and b.stopwords == frozenset(["x"])
        assert b.min_token_len == 2


class TestInMemoryBM25:
    def test_bm25_prefers_rarer_terms(self):
        idx = FullTextIndex(CORPUS)
        ids, scores = idx.search("fox", 10)
        assert set(ids.tolist()) == {0, 1}
        assert np.all(np.diff(scores) <= 0)

    def test_and_mode(self):
        idx = FullTextIndex(CORPUS)
        ids, _ = idx.search("quick AND fox", 10)
        assert ids.tolist() == [0]
        ids, _ = idx.search("+quick +day", 10)
        assert ids.tolist() == [4]

    def test_phrase_mode(self):
        idx = FullTextIndex(CORPUS)
        ids, _ = idx.search('"brown fox"', 10)
        assert set(ids.tolist()) == {0, 1}
        ids, _ = idx.search('"fox brown"', 10)
        assert ids.tolist() == []

    def test_or_still_ranks(self):
        idx = FullTextIndex(CORPUS)
        ids, scores = idx.search("quick dog", 10)
        # doc 0 has both terms: must rank first
        assert ids[0] == 0


class TestPersisted:
    def test_build_and_search(self, tmp_path):
        t = docs_table(tmp_path)
        write(t, [{"id": i, "body": b} for i, b in enumerate(CORPUS)])
        idx = PersistedFullTextIndex.open(t, "body")
        added = idx.refresh()
        assert added == len(CORPUS)
        ids, scores = idx.search("fox", 10)
        assert set(ids.tolist()) == {0, 1}

    def test_survives_restart(self, tmp_path):
        t = docs_table(tmp_path)
        write(t, [{"id": i, "body": b} for i, b in enumerate(CORPUS)])
        PersistedFullTextIndex.open(t, "body").refresh()
        # fresh object = fresh process: no rebuild required
        idx2 = PersistedFullTextIndex.open(t, "body")
        assert idx2.meta is not None
        assert idx2.refresh() == 0           # already current
        ids, _ = idx2.search("lorem", 5)
        assert ids.tolist() == [2]

    def test_incremental_refresh_new_segment(self, tmp_path):
        t = docs_table(tmp_path)
        write(t, [{"id": i, "body": b} for i, b in enumerate(CORPUS)])
        idx = PersistedFullTextIndex.open(t, "body")
        idx.refresh()
        assert len(idx.meta["segments"]) == 1
        write(t, [{"id": 100, "body": "an arctic fox in the snow"}])
        added = idx.refresh()
        assert added == 1
        assert len(idx.meta["segments"]) == 2
        ids, _ = idx.search("fox", 10)
        assert set(ids.tolist()) == {0, 1, 5}
        ids, _ = idx.search("arctic", 10)
        assert ids.tolist() == [5]

    def test_optimize_merges_segments(self, tmp_path):
        t = docs_table(tmp_path)
        write(t, [{"id": i, "body": b} for i, b in enumerate(CORPUS)])
        idx = PersistedFullTextIndex.open(t, "body")
        idx.refresh()
        write(t, [{"id": 100, "body": "an arctic fox in the snow"}])
        idx.refresh()
        before_ids, before_sc = idx.search("fox", 10)
        idx.optimize()
        assert len(idx.meta["segments"]) == 1
        after_ids, after_sc = idx.search("fox", 10)
        assert before_ids.tolist() == after_ids.tolist()
        np.testing.assert_allclose(before_sc, after_sc, rtol=1e-6)

    def test_phrase_across_persisted(self, tmp_path):
        t = docs_table(tmp_path)
        write(t, [{"id": i, "body": b} for i, b in enumerate(CORPUS)])
        idx = PersistedFullTextIndex.open(t, "body")
        idx.refresh()
        ids, _ = idx.search('"lazy dog"', 10)
        assert ids.tolist() == [0]

    def test_query_reads_only_matching_row_groups(self, tmp_path):
        """The postings read must prune row groups by term stats."""
        t = docs_table(tmp_path)
        rows = [{"id": i, "body": f"word{i:05d} common"}
                for i in range(5000)]
        write(t, rows)
        idx = PersistedFullTextIndex.open(t, "body")
        idx.refresh()
        seg = idx.meta["segments"][0]
        import io
        import pyarrow.parquet as pq
        pf = pq.ParquetFile(io.BytesIO(idx._read(seg["file"])))
        assert pf.num_row_groups > 1     # pruning is meaningful
        ids, _ = idx.search("word00007", 5)
        assert ids.tolist() == [7]

    def test_custom_analyzer_persisted(self, tmp_path):
        t = docs_table(tmp_path)
        write(t, [{"id": 0, "body": "Jumping foxes"},
                  {"id": 1, "body": "sleeping dogs"}])
        idx = PersistedFullTextIndex.open(
            t, "body", analyzer=Analyzer(stem=True))
        idx.refresh()
        # a new process re-reads the analyzer config from meta.json
        idx2 = PersistedFullTextIndex.open(t, "body")
        assert idx2.analyzer.stem
        ids, _ = idx2.search("jumps", 5)      # stems to 'jump'
        assert ids.tolist() == [0]


class TestTableHelper:
    def test_full_text_search_scores(self, tmp_path):
        t = docs_table(tmp_path)
        write(t, [{"id": i, "body": b} for i, b in enumerate(CORPUS)])
        out = full_text_search(t, "body", "brown fox", 3)
        assert "_score" in out.column_names
        assert set(out.column("id").to_pylist()) <= {0, 1}


class TestHybridUsesPersisted:
    def test_hybrid_text_route_reads_persisted_index(self, tmp_path):
        from paimon_tpu.vector.hybrid import hybrid_search
        t = docs_table(tmp_path)
        write(t, [{"id": i, "body": b} for i, b in enumerate(CORPUS)])
        idx = PersistedFullTextIndex.open(t, "body")
        idx.refresh()
        out = hybrid_search(t, [{"type": "text", "column": "body",
                                 "query": "fox", "limit": 5}], k=5)
        assert set(out.column("id").to_pylist()) == {0, 1}
        assert "_ROW_ID" not in out.column_names
        assert "_score" in out.column_names

    def test_hybrid_falls_back_without_index(self, tmp_path):
        from paimon_tpu.vector.hybrid import hybrid_search
        t = docs_table(tmp_path)
        write(t, [{"id": i, "body": b} for i, b in enumerate(CORPUS)])
        out = hybrid_search(t, [{"type": "text", "column": "body",
                                 "query": "fox", "limit": 5}], k=5)
        assert set(out.column("id").to_pylist()) == {0, 1}
