import io

import pytest

from paimon_tpu.format import avro


RECORD_SCHEMA = {
    "type": "record", "name": "R",
    "fields": [
        {"name": "i", "type": "int"},
        {"name": "l", "type": "long"},
        {"name": "s", "type": "string"},
        {"name": "b", "type": "bytes"},
        {"name": "d", "type": "double"},
        {"name": "opt", "type": ["null", "long"], "default": None},
        {"name": "arr", "type": {"type": "array", "items": "string"}},
        {"name": "nested", "type": {
            "type": "record", "name": "N",
            "fields": [{"name": "x", "type": "boolean"}]}},
    ],
}


def test_zigzag_longs():
    for n in [0, 1, -1, 63, -64, 64, 1 << 40, -(1 << 40), (1 << 62),
              -(1 << 62)]:
        buf = io.BytesIO()
        avro._write_long(buf, n)
        buf.seek(0)
        assert avro._read_long(buf) == n


def test_record_roundtrip():
    rec = {"i": -5, "l": 1 << 50, "s": "héllo", "b": b"\x00\xff",
           "d": 2.5, "opt": None, "arr": ["a", "b"], "nested": {"x": True}}
    buf = io.BytesIO()
    avro.encode_value(RECORD_SCHEMA, rec, buf)
    buf.seek(0)
    assert avro.decode_value(RECORD_SCHEMA, buf) == rec


def test_union_branches():
    rec = dict(i=0, l=0, s="", b=b"", d=0.0, opt=7, arr=[],
               nested={"x": False})
    buf = io.BytesIO()
    avro.encode_value(RECORD_SCHEMA, rec, buf)
    buf.seek(0)
    assert avro.decode_value(RECORD_SCHEMA, buf)["opt"] == 7


@pytest.mark.parametrize("codec", ["null", "deflate", "zstandard"])
def test_container_roundtrip(codec):
    records = [{"i": i, "l": i * 1000, "s": f"row-{i}", "b": bytes([i % 256]),
                "d": i / 3.0, "opt": i if i % 2 else None,
                "arr": [str(i)] * (i % 3), "nested": {"x": i % 2 == 0}}
               for i in range(500)]
    data = avro.write_container(RECORD_SCHEMA, records, codec=codec,
                                block_records=100)
    schema, out = avro.read_container(data)
    assert schema["name"] == "R"
    assert out == records


def test_container_empty():
    data = avro.write_container(RECORD_SCHEMA, [])
    _, out = avro.read_container(data)
    assert out == []


def test_magic_check():
    with pytest.raises(avro.AvroSchemaError):
        avro.read_container(b"nope" + b"\x00" * 100)
