"""Data-loader integration tests: torch IterableDataset sharding, jax
batch iterator, split-task plumbing shared by the ray/daft adapters."""

import numpy as np
import pyarrow as pa
import pytest

from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, VarCharType
from paimon_tpu import predicate as P


@pytest.fixture()
def table(tmp_path):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .column("name", VarCharType(VarCharType.MAX_LENGTH))
              .options({"bucket": "4", "bucket-key": "id"})
              .build())
    t = FileStoreTable.create(str(tmp_path / "t"), schema)
    n = 1000
    data = pa.table({
        "id": pa.array(np.arange(n), pa.int64()),
        "v": pa.array(np.arange(n) * 0.5, pa.float64()),
        "name": pa.array([f"row-{i}" for i in range(n)]),
    })
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write_arrow(data)
    wb.new_commit().commit(w.prepare_commit())
    w.close()
    return t


class TestTorch:
    def test_iterable_dataset_full_pass(self, table):
        from paimon_tpu.integrations.torch_data import \
            PaimonIterableDataset
        import torch

        ds = PaimonIterableDataset(table, batch_size=128)
        seen = []
        for batch in ds:
            assert isinstance(batch["id"], torch.Tensor)
            assert isinstance(batch["name"], list)
            seen.extend(batch["id"].tolist())
        assert sorted(seen) == list(range(1000))

    def test_dataloader_with_workers(self, table):
        from paimon_tpu.integrations.torch_data import to_torch_dataloader

        dl = to_torch_dataloader(table, projection=["id", "v"],
                                 batch_size=100, num_workers=2)
        seen = []
        for batch in dl:
            assert set(batch.keys()) == {"id", "v"}
            seen.extend(batch["id"].tolist())
        # two workers each read their own splits; union is one full pass
        assert sorted(seen) == list(range(1000))

    def test_rank_sharding_partitions_splits(self, table):
        from paimon_tpu.integrations.torch_data import \
            PaimonIterableDataset

        seen = []
        for rank in range(2):
            ds = PaimonIterableDataset(table, batch_size=100, rank=rank,
                                       world_size=2)
            seen.extend(b["id"].tolist() for b in ds)
        flat = sorted(x for chunk in seen for x in chunk)
        assert flat == list(range(1000))

    def test_predicate_pushdown(self, table):
        from paimon_tpu.integrations.torch_data import \
            PaimonIterableDataset

        ds = PaimonIterableDataset(table, projection=["id"],
                                   predicate=P.less_than("id", 10),
                                   batch_size=64)
        seen = sorted(x for b in ds for x in b["id"].tolist())
        assert seen == list(range(10))


class TestJax:
    def test_fixed_shape_batches(self, table):
        from paimon_tpu.integrations.jax_data import jax_batches

        shapes = set()
        total = 0
        for batch in jax_batches(table, 256, projection=["id", "v"]):
            shapes.add(batch["id"].shape)
            total += batch["id"].shape[0]
        assert shapes == {(256,)}
        assert total == 768          # 1000 rows -> 3 full batches

    def test_remainder_padding_with_mask(self, table):
        from paimon_tpu.integrations.jax_data import jax_batches

        ids = []
        for batch in jax_batches(table, 256, projection=["id"],
                                 drop_remainder=False):
            if "_mask" in batch:
                assert batch["id"].shape == (256,)
                ids.extend(np.asarray(batch["id"])[
                    np.asarray(batch["_mask"])].tolist())
            else:
                ids.extend(np.asarray(batch["id"]).tolist())
        assert sorted(ids) == list(range(1000))

    def test_non_numeric_rejected_without_projection_fallback(self, table):
        from paimon_tpu.integrations.jax_data import jax_batches

        with pytest.raises(ValueError):
            next(jax_batches(table, 10, projection=["name"]))


class TestSplitTasks:
    def test_split_tasks_cover_table(self, table):
        from paimon_tpu.integrations.ray_data import split_read_tasks

        tasks = split_read_tasks(table, projection=["id"])
        assert len(tasks) >= 2          # 4 buckets hold >=2 splits
        got = []
        for t in tasks:
            out = t["fn"]()
            assert out.column_names == ["id"]
            got.extend(out.column("id").to_pylist())
        assert sorted(got) == list(range(1000))
        assert sum(t["num_rows"] for t in tasks) == 1000

    def test_ray_daft_gated(self, table):
        from paimon_tpu.integrations import daft_data, ray_data

        with pytest.raises(ImportError, match="ray"):
            ray_data.to_ray_dataset(table)
        with pytest.raises(ImportError, match="daft"):
            daft_data.to_daft_dataframe(table)
