"""Decoupled changelog retention + branch-fallback reads + new system
tables.

reference: utils/ChangelogManager.java + Changelog.java (changelog
outlives snapshots), table/FallbackReadFileStoreTable.java
(scan.fallback-branch partition fallback),
table/system/SystemTableLoader.java (full loader set).
"""

import os

import pytest

from paimon_tpu import predicate as P
from paimon_tpu.maintenance import expire_changelogs
from paimon_tpu.schema import Schema
from paimon_tpu.snapshot.changelog_manager import ChangelogManager
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, IntType, RowKind


def cl_table(tmp_path, **opts):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true",
                        "changelog-producer": "input",
                        "changelog.num-retained.max": "50",
                        **opts})
              .build())
    return FileStoreTable.create(str(tmp_path / "t"), schema)


def commit(table, rows, kinds=None):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows, row_kinds=kinds)
    sid = wb.new_commit().commit(w.prepare_commit())
    w.close()
    return sid


class TestDecoupledChangelog:
    def test_changelog_survives_snapshot_expiry(self, tmp_path):
        t = cl_table(tmp_path)
        for i in range(6):
            commit(t, [{"id": i, "v": float(i)}])
        t.expire_snapshots(retain_max=2, retain_min=1)
        sm = t.snapshot_manager
        assert sm.earliest_snapshot_id() == 5
        cm = ChangelogManager(t.file_io, t.path)
        ids = cm._ids()
        assert ids and min(ids) == 1          # expired snapshots' logs
        # the preserved entry still points at readable changelog files
        scan = t.new_scan()
        for cid in ids:
            snap = cm.changelog(cid)
            plan = scan.plan_changelog(snap, streaming=True)
            rows = t.new_read_builder().new_read().to_arrow(plan)
            assert rows.num_rows == 1

    def test_stream_consumer_reads_past_expiry(self, tmp_path):
        t = cl_table(tmp_path)
        for i in range(5):
            commit(t, [{"id": i, "v": float(i)}])
        scan = t.copy({"scan.mode": "from-snapshot",
                       "scan.snapshot-id": "1"}) \
            .new_read_builder().new_stream_scan()
        t.expire_snapshots(retain_max=2, retain_min=1)
        read = t.new_read_builder().new_read()
        seen = []
        while True:
            plan = scan.plan()
            if plan is None:
                break
            rows = read.to_arrow(plan)
            seen.extend(rows.to_pylist())
        assert sorted(r["id"] for r in seen) == [0, 1, 2, 3, 4]

    def test_compact_snapshot_gap_does_not_strand_consumers(
            self, tmp_path):
        """Changelog-less snapshots (COMPACT commits) still leave a
        decoupled entry so consumers walking expired ids never hit a
        permanent FileNotFoundError gap."""
        schema = (Schema.builder()
                  .column("id", BigIntType(False))
                  .column("v", DoubleType())
                  .primary_key("id")
                  .options({"bucket": "1", "write-only": "true",
                            "changelog-producer": "input",
                            "changelog.num-retained.max": "50"})
                  .build())
        t = FileStoreTable.create(str(tmp_path / "t"), schema)
        commit(t, [{"id": 0, "v": 0.0}])
        commit(t, [{"id": 1, "v": 1.0}])
        t.compact(full=True)              # snapshot 3: COMPACT
        commit(t, [{"id": 2, "v": 2.0}])
        commit(t, [{"id": 3, "v": 3.0}])
        scan = t.copy({"scan.mode": "from-snapshot",
                       "scan.snapshot-id": "1"}) \
            .new_read_builder().new_stream_scan()
        t.expire_snapshots(retain_max=1, retain_min=1)
        read = t.new_read_builder().new_read()
        seen = []
        while True:
            plan = scan.plan()
            if plan is None:
                break
            seen.extend(read.to_arrow(plan).to_pylist())
        assert sorted(r["id"] for r in seen) == [0, 1, 2, 3]

    def test_expire_changelogs_trims(self, tmp_path):
        t = cl_table(tmp_path, **{"changelog.num-retained.max": "4"})
        for i in range(8):
            commit(t, [{"id": i, "v": float(i)}])
        t.expire_snapshots(retain_max=2, retain_min=1)
        cm = ChangelogManager(t.file_io, t.path)
        before = cm._ids()
        assert before
        res = expire_changelogs(t)
        after = cm._ids()
        assert len(after) < len(before)
        assert res.expired_snapshots
        # survivors still readable
        scan = t.new_scan()
        for cid in after:
            plan = scan.plan_changelog(cm.changelog(cid),
                                       streaming=True)
            assert t.new_read_builder().new_read() \
                .to_arrow(plan).num_rows == 1

    def test_expire_changelogs_respects_tags(self, tmp_path):
        """A tag pins its snapshot's changelog files even after the
        decoupled entry is trimmed (reference ExpireChangelogImpl
        takes the TagManager)."""
        t = cl_table(tmp_path, **{"changelog.num-retained.max": "1"})
        for i in range(4):
            commit(t, [{"id": i, "v": float(i)}])
        t.create_tag("pin", snapshot_id=2)
        t.expire_snapshots(retain_max=1, retain_min=1)
        expire_changelogs(t)
        # the tagged snapshot's changelog files must still be readable
        tagged = t.tag_manager.get_tag("pin")
        scan = t.new_scan()
        plan = scan.plan_changelog(tagged, streaming=True)
        rows = t.new_read_builder().new_read().to_arrow(plan)
        assert rows.num_rows == 1

    def test_without_option_changelog_dies_with_snapshot(self, tmp_path):
        schema = (Schema.builder()
                  .column("id", BigIntType(False))
                  .column("v", DoubleType())
                  .primary_key("id")
                  .options({"bucket": "1", "write-only": "true",
                            "changelog-producer": "input"})
                  .build())
        t4 = FileStoreTable.create(str(tmp_path / "plain"), schema)
        for i in range(5):
            commit(t4, [{"id": i, "v": 0.0}])
        t4.expire_snapshots(retain_max=2, retain_min=1)
        assert ChangelogManager(t4.file_io, t4.path)._ids() == []


class TestFallbackBranch:
    def test_partition_fallback_reads(self, tmp_path):
        schema = (Schema.builder()
                  .column("pt", IntType(False))
                  .column("id", BigIntType(False))
                  .column("v", DoubleType())
                  .partition_keys("pt")
                  .primary_key("pt", "id")
                  .options({"bucket": "1", "write-only": "true"})
                  .build())
        t = FileStoreTable.create(str(tmp_path / "t"), schema)
        # main branch: partitions 0 and 1
        commit(t, [{"pt": 0, "id": 1, "v": 0.1},
                   {"pt": 1, "id": 1, "v": 1.1}])
        t.create_tag("base")
        t.create_branch("backfill", "base")
        # backfill branch gets partition 2 (and its own pt=1 the main
        # branch must shadow)
        fb = FileStoreTable.load(t.path, dynamic_options={
            "branch": "backfill"})
        commit(fb, [{"pt": 2, "id": 1, "v": 2.2},
                    {"pt": 1, "id": 9, "v": 9.9}])

        plain = t.to_arrow().to_pylist()
        assert {r["pt"] for r in plain} == {0, 1}

        with_fb = t.copy({"scan.fallback-branch": "backfill"})
        rows = sorted(with_fb.to_arrow().to_pylist(),
                      key=lambda r: (r["pt"], r["id"]))
        # pt 2 came from the fallback; pt 1 stayed main-branch only
        assert {r["pt"] for r in rows} == {0, 1, 2}
        assert [r for r in rows if r["pt"] == 2][0]["v"] == 2.2
        assert all(r["id"] != 9 for r in rows if r["pt"] == 1)


class TestChainStreaming:
    def test_latest_full_stream_unions_fallback_then_delta_only(
            self, tmp_path):
        """Chain-table streaming (reference ChainTableFileStoreTable):
        the initial full result includes fallback-branch partitions;
        follow-up reads deltas of the primary branch only."""
        schema = (Schema.builder()
                  .column("pt", IntType(False))
                  .column("id", BigIntType(False))
                  .column("v", DoubleType())
                  .partition_keys("pt")
                  .primary_key("pt", "id")
                  .options({"bucket": "1", "write-only": "true"})
                  .build())
        t = FileStoreTable.create(str(tmp_path / "t"), schema)
        commit(t, [{"pt": 0, "id": 1, "v": 0.0}])
        t.create_tag("base")
        t.create_branch("hist", "base")
        hist = FileStoreTable.load(t.path,
                                   dynamic_options={"branch": "hist"})
        commit(hist, [{"pt": 9, "id": 1, "v": 9.0}])   # backfill part

        chained = t.copy({"scan.fallback-branch": "hist"})
        scan = chained.new_read_builder().new_stream_scan()
        read = chained.new_read_builder().new_read()
        first = read.to_arrow(scan.plan())
        assert {r["pt"] for r in first.to_pylist()} == {0, 9}

        # new delta on the primary branch streams through; fallback
        # partitions do NOT re-emit
        commit(t, [{"pt": 0, "id": 2, "v": 0.2}])
        nxt = read.to_arrow(scan.plan())
        assert [r["id"] for r in nxt.to_pylist()] == [2]

    def test_stream_filters_apply_to_fallback(self, tmp_path):
        schema = (Schema.builder()
                  .column("pt", IntType(False))
                  .column("id", BigIntType(False))
                  .column("v", DoubleType())
                  .partition_keys("pt")
                  .primary_key("pt", "id")
                  .options({"bucket": "1", "write-only": "true"})
                  .build())
        t = FileStoreTable.create(str(tmp_path / "t"), schema)
        commit(t, [{"pt": 0, "id": 1, "v": 0.0}])
        t.create_tag("base")
        t.create_branch("hist", "base")
        hist = FileStoreTable.load(t.path,
                                   dynamic_options={"branch": "hist"})
        commit(hist, [{"pt": 9, "id": 1, "v": 9.0},
                      {"pt": 5, "id": 1, "v": 5.0}])
        chained = t.copy({"scan.fallback-branch": "hist"})
        rb = chained.new_read_builder().with_partition_filter({"pt": 5})
        scan = rb.new_stream_scan()
        first = rb.new_read().to_arrow(scan.plan())
        assert {r["pt"] for r in first.to_pylist()} == {5}

    def test_empty_primary_branch_still_serves_fallback(self, tmp_path):
        schema = (Schema.builder()
                  .column("pt", IntType(False))
                  .column("id", BigIntType(False))
                  .column("v", DoubleType())
                  .partition_keys("pt")
                  .primary_key("pt", "id")
                  .options({"bucket": "1", "write-only": "true"})
                  .build())
        t = FileStoreTable.create(str(tmp_path / "t"), schema)
        t.create_branch("hist")
        hist = FileStoreTable.load(t.path,
                                   dynamic_options={"branch": "hist"})
        commit(hist, [{"pt": 1, "id": 1, "v": 1.0}])
        chained = t.copy({"scan.fallback-branch": "hist"})
        scan = chained.new_read_builder().new_stream_scan()
        plan = scan.plan()
        assert plan is not None
        rows = chained.new_read_builder().new_read() \
            .to_arrow(plan).to_pylist()
        assert rows and rows[0]["pt"] == 1


class TestNewSystemTables:
    def _table(self, tmp_path):
        schema = (Schema.builder()
                  .column("id", BigIntType(False))
                  .column("v", IntType())
                  .primary_key("id")
                  .options({"bucket": "1", "write-only": "true",
                            "merge-engine": "aggregation",
                            "fields.v.aggregate-function": "sum"})
                  .build())
        t = FileStoreTable.create(str(tmp_path / "t"), schema)
        commit(t, [{"id": 1, "v": 5}, {"id": 2, "v": 6}])
        commit(t, [{"id": 1, "v": 1}])
        return t

    def test_aggregation_fields(self, tmp_path):
        t = self._table(tmp_path)
        rows = t.system_table("aggregation_fields").to_pylist()
        by = {r["field_name"]: r for r in rows}
        assert by["v"]["function"] == "sum"
        assert by["id"]["function"] == "primary-key"

    def test_read_optimized(self, tmp_path):
        t = self._table(tmp_path)
        assert t.system_table("read_optimized").num_rows == 0  # all L0
        t.compact(full=True)
        ro = t.system_table("read_optimized")
        assert sorted(ro.column("id").to_pylist()) == [1, 2]

    def test_binlog_pairs_updates(self, tmp_path):
        schema = (Schema.builder()
                  .column("id", BigIntType(False))
                  .column("v", IntType())
                  .primary_key("id")
                  .options({"bucket": "1", "write-only": "true",
                            "changelog-producer": "input"})
                  .build())
        t = FileStoreTable.create(str(tmp_path / "t"), schema)
        commit(t, [{"id": 1, "v": 10}])
        rows = t.system_table("binlog").to_pylist()
        assert rows[0]["rowkind"] == "+I"
        assert rows[0]["v"] == [10]

    def test_file_key_ranges_and_table_indexes(self, tmp_path):
        t = self._table(tmp_path)
        kr = t.system_table("file_key_ranges").to_pylist()
        assert kr and kr[0]["min_key"] is not None
        # indexes table: empty but well-formed here
        ti = t.system_table("table_indexes")
        assert "index_type" in ti.column_names

    def test_statistics(self, tmp_path):
        t = self._table(tmp_path)
        t.analyze()
        st = t.system_table("statistics").to_pylist()
        assert st and st[0]["snapshot_id"] is not None

    def test_row_tracking_table(self, tmp_path):
        schema = (Schema.builder()
                  .column("id", BigIntType(False))
                  .options({"bucket": "-1",
                            "row-tracking.enabled": "true"})
                  .build())
        t = FileStoreTable.create(str(tmp_path / "rt"), schema)
        commit(t, [{"id": 1}, {"id": 2}])
        rows = t.system_table("row_tracking").to_pylist()
        assert rows[0]["first_row_id"] == 0
        assert rows[0]["next_row_id_after"] == 2
