"""The cpu lexsort fallback and the XLA kernel must agree bit-for-bit.

The fallback (ops/merge.py _host_sorted_winners) answers every
device_sorted_winners call on cpu backends, so the kernel's padding +
validity logic would otherwise be test-dead off-accelerator:
PAIMON_FORCE_DEVICE_SORT=1 pins the kernel path and these tests compare
the two against each other on random workloads.
"""

import os

import numpy as np
import pytest

from paimon_tpu.ops.merge import device_sorted_winners


def _both_paths(lanes, seq, keep, order_lanes=None):
    os.environ.pop("PAIMON_FORCE_DEVICE_SORT", None)
    host = device_sorted_winners(lanes, seq, keep, order_lanes)
    os.environ["PAIMON_FORCE_DEVICE_SORT"] = "1"
    try:
        dev = device_sorted_winners(lanes, seq, keep, order_lanes)
    finally:
        os.environ.pop("PAIMON_FORCE_DEVICE_SORT", None)
    return host, dev


def _winners(perm, winner, n):
    perm = np.asarray(perm)
    winner = np.asarray(winner)
    real = perm < n
    return perm[np.asarray(winner, bool) & real]


@pytest.mark.parametrize("keep", ["last", "first"])
@pytest.mark.parametrize("seed", [0, 7, 31])
def test_host_matches_device_kernel(keep, seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 5000))
    lanes = rng.integers(0, 8, (n, 2), dtype=np.uint64) \
        .astype(np.uint32)                 # few distincts: big segments
    seq = rng.permutation(n).astype(np.int64)
    (hp, hw, hprev), (dp, dw, dprev) = _both_paths(lanes, seq, keep)
    h = _winners(hp, hw, n)
    d = _winners(dp, dw, n)
    assert np.array_equal(np.sort(h), np.sort(d))
    # winner per segment must be identical, not just same count
    assert set(h.tolist()) == set(d.tolist())

    # prev_in_segment feeds changelog derivation: winner -> predecessor
    # maps must agree too
    def prev_map(perm, winner, prev):
        perm, winner, prev = (np.asarray(perm), np.asarray(winner, bool),
                              np.asarray(prev))
        pos = np.flatnonzero(winner & (perm < n))
        return {int(perm[i]): int(prev[i]) for i in pos}

    assert prev_map(hp, hw, hprev) == prev_map(dp, dw, dprev)


def test_order_lanes_agree():
    rng = np.random.default_rng(3)
    n = 777
    lanes = rng.integers(0, 5, (n, 1), dtype=np.uint64).astype(np.uint32)
    order = rng.integers(0, 3, (n, 1), dtype=np.uint64).astype(np.uint32)
    seq = np.arange(n, dtype=np.int64)
    (hp, hw, _), (dp, dw, _) = _both_paths(lanes, seq, "last", order)
    assert set(_winners(hp, hw, n).tolist()) == \
        set(_winners(dp, dw, n).tolist())


def test_device_path_padding_still_covered():
    """Direct kernel run (forced): padded outputs, validity respected."""
    os.environ["PAIMON_FORCE_DEVICE_SORT"] = "1"
    try:
        lanes = np.zeros((3, 1), dtype=np.uint32)   # all-equal keys
        seq = np.array([5, 9, 1], dtype=np.int64)
        perm, winner, prev = device_sorted_winners(lanes, seq, "last")
        assert len(perm) >= 1024                     # padded
        win = perm[np.asarray(winner, bool) & (perm < 3)]
        assert win.tolist() == [1]                   # max-seq row wins
    finally:
        os.environ.pop("PAIMON_FORCE_DEVICE_SORT", None)


@pytest.mark.parametrize("keep", ["last", "first"])
@pytest.mark.parametrize("seed", [1, 9, 42])
def test_winners_only_fast_path_matches_full_sort(keep, seed):
    """The packed-key argsort + segmented-argmax fast path must pick
    byte-identical winners to the full (key, seq) sort."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(50, 8000))
    lanes = rng.integers(0, 9, (n, 2), dtype=np.uint64) \
        .astype(np.uint32)                 # heavy duplication
    # non-unique sequences so arrival-order tie-breaks matter
    seq = rng.integers(0, 12, n).astype(np.int64)

    fast = device_sorted_winners(lanes, seq, keep, winners_only=True)
    full = device_sorted_winners(lanes, seq, keep, winners_only=False)
    w_fast = set(_winners(fast[0], fast[1], n).tolist())
    w_full = set(_winners(full[0], full[1], n).tolist())
    assert w_fast == w_full


@pytest.mark.parametrize("keep", ["last", "first"])
@pytest.mark.parametrize("seed", [2, 11, 77])
def test_bitmask_path_matches_host(keep, seed):
    """The N/8-byte bitmask device return + host winner radix must pick
    the SAME winners in the SAME key order as the host fast path."""
    rng = np.random.default_rng(seed)
    n = int(rng.integers(40, 9000))
    lanes = rng.integers(0, 50, (n, 2), dtype=np.uint64) \
        .astype(np.uint32)
    packed = (lanes[:, 0].astype(np.uint64) << np.uint64(32)) \
        | lanes[:, 1].astype(np.uint64)
    seq = rng.integers(0, 15, n).astype(np.int64)

    host = device_sorted_winners(lanes, seq, keep, winners_only=True,
                                 packed=packed)
    os.environ["PAIMON_FORCE_BITMASK_SORT"] = "1"
    try:
        bm = device_sorted_winners(lanes, seq, keep, winners_only=True,
                                   packed=packed)
    finally:
        os.environ.pop("PAIMON_FORCE_BITMASK_SORT", None)
    h_idx = np.asarray(host[0])[np.asarray(host[1], bool)
                                & (np.asarray(host[0]) < n)]
    b_idx = np.asarray(bm[0])[np.asarray(bm[1], bool)]
    # identical winners, identical (key-sorted) order
    assert np.array_equal(h_idx, b_idx)


def test_bitmask_path_with_order_lanes():
    rng = np.random.default_rng(5)
    n = 3000
    lanes = rng.integers(0, 20, (n, 2), dtype=np.uint64) \
        .astype(np.uint32)
    packed = (lanes[:, 0].astype(np.uint64) << np.uint64(32)) \
        | lanes[:, 1].astype(np.uint64)
    order = rng.integers(0, 4, (n, 1), dtype=np.uint64).astype(np.uint32)
    seq = np.arange(n, dtype=np.int64)
    host = device_sorted_winners(lanes, seq, "last", order_lanes=order,
                                 winners_only=True, packed=None)
    os.environ["PAIMON_FORCE_BITMASK_SORT"] = "1"
    try:
        bm = device_sorted_winners(lanes, seq, "last", order_lanes=order,
                                   winners_only=True, packed=packed)
    finally:
        os.environ.pop("PAIMON_FORCE_BITMASK_SORT", None)
    h_idx = np.asarray(host[0])[np.asarray(host[1], bool)
                                & (np.asarray(host[0]) < n)]
    b_idx = np.asarray(bm[0])[np.asarray(bm[1], bool)]
    assert set(h_idx.tolist()) == set(b_idx.tolist())
    # bitmask output is key-ordered
    assert np.all(np.diff(packed[b_idx].astype(np.int64)) >= 0)
