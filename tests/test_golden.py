"""Golden wire-format fixture: committed bytes that must stay readable
and re-serializable forever.

The fixture under tests/fixtures/golden_v1/ was written once by
tests/make_golden.py and committed; these tests assert that today's
code (a) still reads every plane of it and (b) re-serializes metadata
to the exact committed bytes, so snapshot JSON, manifest avro, DV and
Iceberg wire formats cannot silently drift (role of reference
paimon-core JavaPyE2ETest.java cross-impl compatibility, and of
iceberg/IcebergMetadata.java field layout).
"""

import json
import os
import shutil

import pytest

from paimon_tpu.table import FileStoreTable

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                       "golden_v1")


@pytest.fixture
def golden(tmp_path):
    """A writable copy so reads that touch hint files cannot mutate the
    committed fixture."""
    dst = tmp_path / "golden"
    shutil.copytree(FIXTURE, dst)
    with open(os.path.join(FIXTURE, "expected.json")) as f:
        expected = json.load(f)
    return str(dst), expected


def test_pk_table_reads_expected_rows(golden):
    root, expected = golden
    t = FileStoreTable.load(os.path.join(root, "golden_pk"))
    rows = sorted(t.to_arrow().to_pylist(),
                  key=lambda r: (r["pt"], r["id"]))
    assert rows == expected["pk_rows"]


def test_pk_tag_time_travel(golden):
    root, _ = golden
    t = FileStoreTable.load(os.path.join(root, "golden_pk"))
    tagged = t.copy({"scan.tag-name": "golden-tag"})
    rows = tagged.to_arrow().to_pylist()
    assert len(rows) > 0


def test_append_table_row_ids_and_dvs(golden):
    root, expected = golden
    t = FileStoreTable.load(os.path.join(root, "golden_append"))
    rows = sorted(t.to_arrow(with_row_ids=True).to_pylist(),
                  key=lambda r: r["id"])
    assert rows == expected["append_rows"]
    assert {r["id"] for r in rows}.isdisjoint({1, 6})   # DV'd out


def test_snapshot_json_bytes_stable(golden):
    root, _ = golden
    from paimon_tpu.snapshot.snapshot import Snapshot
    snap_dir = os.path.join(root, "golden_pk", "snapshot")
    checked = 0
    for name in sorted(os.listdir(snap_dir)):
        if not name.startswith("snapshot-"):
            continue
        with open(os.path.join(snap_dir, name), "rb") as f:
            raw = f.read()
        snap = Snapshot.from_json(raw.decode("utf-8"))
        assert snap.to_json().encode("utf-8") == raw, \
            f"snapshot serializer drifted for {name}"
        checked += 1
    assert checked >= 4


def test_snapshot_json_reference_keys(golden):
    root, _ = golden
    snap_dir = os.path.join(root, "golden_pk", "snapshot")
    latest = max(n for n in os.listdir(snap_dir)
                 if n.startswith("snapshot-"))
    with open(os.path.join(snap_dir, latest)) as f:
        d = json.load(f)
    # reference Snapshot.java JSON field names (paimon-api Snapshot)
    for key in ["version", "id", "schemaId", "baseManifestList",
                "deltaManifestList", "commitUser", "commitIdentifier",
                "commitKind", "timeMillis", "totalRecordCount",
                "deltaRecordCount"]:
        assert key in d, key


def test_manifest_avro_reencode_stable(golden):
    root, _ = golden
    from paimon_tpu.format.avro import read_container, write_container
    mdir = os.path.join(root, "golden_pk", "manifest")
    checked = 0
    for name in sorted(os.listdir(mdir)):
        with open(os.path.join(mdir, name), "rb") as f:
            raw = f.read()
        schema, records = read_container(raw)
        # decode -> encode -> decode must be lossless under the same
        # schema (byte equality is not required: codec frames and sync
        # markers may differ, the logical content may not)
        schema2, records2 = read_container(
            write_container(schema, records, codec="null"))
        assert records2 == records, name
        assert schema2 == schema, name
        checked += 1
    assert checked >= 10


def test_manifest_schema_fields_match_reference(golden):
    root, _ = golden
    from paimon_tpu.format.avro import read_container
    mdir = os.path.join(root, "golden_pk", "manifest")
    data_manifests = [n for n in os.listdir(mdir)
                      if n.startswith("manifest-")
                      and "list" not in n and "index" not in n]
    with open(os.path.join(mdir, sorted(data_manifests)[0]), "rb") as f:
        schema, _ = read_container(f.read())
    top = [x["name"] for x in schema["fields"]]
    # reference manifest/ManifestEntrySerializer avro layout
    for key in ["_VERSION", "_KIND", "_PARTITION", "_BUCKET",
                "_TOTAL_BUCKETS", "_FILE"]:
        assert key in top, (key, top)
    file_field = next(x for x in schema["fields"]
                      if x["name"] == "_FILE")
    ftype = file_field["type"]
    if isinstance(ftype, list):
        ftype = next(t for t in ftype if isinstance(t, dict))
    fnames = [x["name"] for x in ftype["fields"]]
    for key in ["_FILE_NAME", "_FILE_SIZE", "_ROW_COUNT", "_MIN_KEY",
                "_MAX_KEY", "_KEY_STATS", "_VALUE_STATS",
                "_MIN_SEQUENCE_NUMBER", "_MAX_SEQUENCE_NUMBER",
                "_SCHEMA_ID", "_LEVEL"]:
        assert key in fnames, (key, fnames)


def test_schema_json_reference_keys(golden):
    root, _ = golden
    with open(os.path.join(root, "golden_pk", "schema",
                           "schema-0")) as f:
        d = json.load(f)
    for key in ["version", "id", "fields", "highestFieldId",
                "partitionKeys", "primaryKeys", "options"]:
        assert key in d, key
    f0 = d["fields"][0]
    assert set(f0) >= {"id", "name", "type"}


def test_iceberg_metadata_reference_fields(golden):
    root, _ = golden
    meta_dir = os.path.join(root, "golden_pk", "metadata")
    with open(os.path.join(meta_dir, "version-hint.text")) as f:
        v = int(f.read().strip())
    with open(os.path.join(meta_dir, f"v{v}.metadata.json")) as f:
        d = json.load(f)
    # reference iceberg/metadata/IcebergMetadata.java serialized fields
    for key in ["format-version", "table-uuid", "location",
                "last-sequence-number", "last-updated-ms",
                "last-column-id", "current-schema-id", "schemas",
                "default-spec-id", "partition-specs",
                "last-partition-id", "current-snapshot-id",
                "snapshots"]:
        assert key in d, key
    assert d["format-version"] == 2
    snap = d["snapshots"][-1]
    for key in ["snapshot-id", "timestamp-ms", "manifest-list",
                "schema-id", "summary"]:
        assert key in snap, key
    # the manifest list it points to exists in the fixture and parses
    mlist = os.path.join(meta_dir,
                         os.path.basename(snap["manifest-list"]))
    from paimon_tpu.format.avro import read_container
    with open(mlist, "rb") as f:
        schema, records = read_container(f.read())
    assert records, "empty iceberg manifest list"
    fields = [x["name"] for x in schema["fields"]]
    for key in ["manifest_path", "manifest_length",
                "partition_spec_id", "added_snapshot_id"]:
        assert key in fields, (key, fields)


def test_fixture_is_pristine():
    """The committed fixture must never be regenerated in place: these
    digests were taken at freeze time; a rewrite (which would make every
    other golden test vacuous) fails loudly here."""
    import hashlib

    frozen = {
        ("golden_pk", "snapshot", "snapshot-1"):
            "2add7f501cf6665efa0dc0f52b85391f54c9637c"
            "0603fb71e60be557526e3fbb",
        ("golden_pk", "schema", "schema-0"):
            "559877f540eb83c09a0ec454e4daf98ce066d7bd"
            "26b1f3a16043bc5116ea9232",
    }
    for parts, digest in frozen.items():
        with open(os.path.join(FIXTURE, *parts), "rb") as f:
            assert hashlib.sha256(f.read()).hexdigest() == digest, parts
