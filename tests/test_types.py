import pyarrow as pa
import pytest

from paimon_tpu.types import (
    ArrayType, BigIntType, DataField, DecimalType, DoubleType, IntType,
    MapType, RowType, TimestampType, VarCharType, LocalZonedTimestampType,
    parse_data_type, row_type_to_arrow_schema, arrow_schema_to_row_type,
)


def test_atomic_roundtrip():
    for t in [IntType(), BigIntType(False), DoubleType(),
              VarCharType(10), DecimalType(10, 2), TimestampType(3),
              TimestampType(6, False), LocalZonedTimestampType(6)]:
        assert parse_data_type(t.to_json()) == t


def test_atomic_strings():
    assert str(IntType(False)) == "INT NOT NULL"
    assert str(VarCharType(10)) == "VARCHAR(10)"
    assert str(DecimalType(10, 2)) == "DECIMAL(10, 2)"
    assert parse_data_type("STRING") == VarCharType(VarCharType.MAX_LENGTH)
    assert parse_data_type("BYTES").root == "VARBINARY"
    assert (str(LocalZonedTimestampType(3, False))
            == "TIMESTAMP(3) WITH LOCAL TIME ZONE NOT NULL")


def test_row_roundtrip():
    row = RowType([
        DataField(0, "id", IntType(False)),
        DataField(1, "name", VarCharType(VarCharType.MAX_LENGTH)),
        DataField(2, "tags", ArrayType(VarCharType(VarCharType.MAX_LENGTH))),
        DataField(3, "attrs", MapType(VarCharType(5), BigIntType())),
        DataField(4, "nested", RowType([DataField(5, "x", DoubleType())])),
    ])
    j = row.to_json()
    assert parse_data_type(j) == row
    assert row.highest_field_id() == 5


def test_arrow_roundtrip():
    row = RowType.of("id", IntType(False), "name",
                     VarCharType(VarCharType.MAX_LENGTH),
                     "score", DoubleType())
    schema = row_type_to_arrow_schema(row)
    assert schema.field("id").type == pa.int32()
    assert not schema.field("id").nullable
    back = arrow_schema_to_row_type(schema)
    assert back.field_names == ["id", "name", "score"]


def test_project():
    row = RowType.of("a", IntType(), "b", BigIntType(), "c", DoubleType())
    p = row.project(["c", "a"])
    assert p.field_names == ["c", "a"]
    with pytest.raises(KeyError):
        row.project(["nope"])
