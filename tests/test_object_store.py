"""Object-store FileIO: commit CAS via conditional PUT, no rename.

reference: paimon-filesystems object-store FileIOs + their
SnapshotCommit behavior (no atomic rename; If-None-Match preconditions
are the only CAS).  A full table lifecycle runs against the emulated
bucket, so every plane (snapshots, manifests, data, DVs) works on
object semantics.
"""

import threading

import pytest

from paimon_tpu.fs.object_store import (
    LocalObjectStoreBackend, ObjectStoreFileIO,
)
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, RowKind


@pytest.fixture
def fio(tmp_path):
    return ObjectStoreFileIO(LocalObjectStoreBackend(
        str(tmp_path / "bucket")))


class TestPrimitives:
    def test_conditional_put_is_cas(self, fio):
        assert fio.try_to_write_atomic("objfs://a/b", b"one")
        assert not fio.try_to_write_atomic("objfs://a/b", b"two")
        assert fio.read_bytes("objfs://a/b") == b"one"

    def test_concurrent_cas_single_winner(self, fio):
        wins = []

        def racer(i):
            if fio.try_to_write_atomic("objfs://race", bytes([i])):
                wins.append(i)

        threads = [threading.Thread(target=racer, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(wins) == 1
        assert fio.read_bytes("objfs://race") == bytes(wins)

    def test_listing_synthesizes_directories(self, fio):
        fio.write_bytes("objfs://wh/t/snapshot/snapshot-1", b"x")
        fio.write_bytes("objfs://wh/t/bucket-0/data-1.parquet", b"y")
        names = {s.path.rsplit("/", 1)[-1]: s.is_dir
                 for s in fio.list_status("objfs://wh/t")}
        assert names == {"snapshot": True, "bucket-0": True}
        files = fio.list_status("objfs://wh/t/snapshot")
        assert [f.is_dir for f in files] == [False]

    def test_two_phase_stream(self, fio):
        s = fio.new_two_phase_stream("objfs://out/f")
        s.write(b"abc")
        c = s.close_for_commit()
        assert not fio.exists("objfs://out/f")
        c.commit()
        assert fio.read_bytes("objfs://out/f") == b"abc"
        # staging key cleaned up
        assert all(not st.path.endswith(".staging")
                   for st in fio.list_status("objfs://out"))

    def test_vectored_ranges(self, fio):
        fio.write_bytes("objfs://r/x", bytes(range(64)))
        assert fio.read_ranges("objfs://r/x", [(0, 4), (60, 4)]) == \
            [bytes(range(4)), bytes(range(60, 64))]


class TestTableOnObjectStore:
    def test_full_lifecycle(self, fio):
        schema = (Schema.builder()
                  .column("id", BigIntType(False))
                  .column("v", DoubleType())
                  .primary_key("id")
                  .options({"bucket": "2", "write-only": "true"})
                  .build())
        t = FileStoreTable.create("objfs://wh/db/t", schema,
                                  file_io=fio)

        def commit(rows, kinds=None):
            wb = t.new_batch_write_builder()
            w = wb.new_write()
            w.write_dicts(rows, row_kinds=kinds)
            sid = wb.new_commit().commit(w.prepare_commit())
            w.close()
            return sid

        commit([{"id": i, "v": float(i)} for i in range(50)])
        commit([{"id": 3, "v": 33.0}])
        commit([{"id": 5, "v": 5.0}], kinds=[RowKind.DELETE])
        rows = sorted(t.to_arrow().to_pylist(), key=lambda r: r["id"])
        assert len(rows) == 49
        assert rows[3]["v"] == 33.0
        assert all(r["id"] != 5 for r in rows)

        assert t.compact(full=True) is not None
        rows2 = sorted(t.to_arrow().to_pylist(), key=lambda r: r["id"])
        assert rows2 == rows

        t.create_tag("v1")
        t.expire_snapshots(retain_max=2, retain_min=1)
        assert sorted(r["id"] for r in
                      t.copy({"scan.tag-name": "v1"}).to_arrow()
                      .to_pylist())[:3] == [0, 1, 2]

        # reload from the bucket (fresh FileIO state)
        t2 = FileStoreTable.load("objfs://wh/db/t", file_io=fio)
        assert sorted(t2.to_arrow().to_pylist(),
                      key=lambda r: r["id"]) == rows


class TestContractEdges:
    def test_rename_contract(self, fio):
        assert not fio.rename("objfs://no/such", "objfs://x")
        fio.write_bytes("objfs://a", b"1")
        fio.write_bytes("objfs://b", b"2")
        assert not fio.rename("objfs://a", "objfs://b")  # dst exists
        assert fio.read_bytes("objfs://b") == b"2"
        # prefix rename moves every child
        fio.write_bytes("objfs://d/t/f1", b"x")
        fio.write_bytes("objfs://d/t/sub/f2", b"y")
        assert fio.rename("objfs://d/t", "objfs://d/u")
        assert fio.read_bytes("objfs://d/u/sub/f2") == b"y"
        assert not fio.exists("objfs://d/t/f1")

    def test_recursive_delete_object_and_prefix(self, fio):
        fio.write_bytes("objfs://k", b"obj")
        fio.write_bytes("objfs://k/child", b"c")
        assert fio.delete("objfs://k", recursive=True)
        assert not fio.exists("objfs://k")
        assert not fio.exists("objfs://k/child")

    def test_listings_never_show_staging(self, fio):
        import threading
        stop = threading.Event()

        def writer():
            i = 0
            while not stop.is_set():
                fio.write_bytes("objfs://c/obj", b"x" * 1000)
                i += 1

        t = threading.Thread(target=writer)
        t.start()
        try:
            for _ in range(200):
                for st in fio.list_status("objfs://c"):
                    assert "staging" not in st.path
                    assert st.path.endswith("obj"), st.path
        finally:
            stop.set()
            t.join()
