"""Object-store FileIO: commit CAS via conditional PUT, no rename.

reference: paimon-filesystems object-store FileIOs + their
SnapshotCommit behavior (no atomic rename; If-None-Match preconditions
are the only CAS).  A full table lifecycle runs against the emulated
bucket, so every plane (snapshots, manifests, data, DVs) works on
object semantics.
"""

import threading

import pytest

from paimon_tpu.fs.object_store import (
    LocalObjectStoreBackend, ObjectStoreFileIO,
)
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, RowKind


@pytest.fixture
def fio(tmp_path):
    return ObjectStoreFileIO(LocalObjectStoreBackend(
        str(tmp_path / "bucket")))


class TestPrimitives:
    def test_conditional_put_is_cas(self, fio):
        assert fio.try_to_write_atomic("objfs://a/b", b"one")
        assert not fio.try_to_write_atomic("objfs://a/b", b"two")
        assert fio.read_bytes("objfs://a/b") == b"one"

    def test_concurrent_cas_single_winner(self, fio):
        wins = []

        def racer(i):
            if fio.try_to_write_atomic("objfs://race", bytes([i])):
                wins.append(i)

        threads = [threading.Thread(target=racer, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(wins) == 1
        assert fio.read_bytes("objfs://race") == bytes(wins)

    def test_listing_synthesizes_directories(self, fio):
        fio.write_bytes("objfs://wh/t/snapshot/snapshot-1", b"x")
        fio.write_bytes("objfs://wh/t/bucket-0/data-1.parquet", b"y")
        names = {s.path.rsplit("/", 1)[-1]: s.is_dir
                 for s in fio.list_status("objfs://wh/t")}
        assert names == {"snapshot": True, "bucket-0": True}
        files = fio.list_status("objfs://wh/t/snapshot")
        assert [f.is_dir for f in files] == [False]

    def test_two_phase_stream(self, fio):
        s = fio.new_two_phase_stream("objfs://out/f")
        s.write(b"abc")
        c = s.close_for_commit()
        assert not fio.exists("objfs://out/f")
        c.commit()
        assert fio.read_bytes("objfs://out/f") == b"abc"
        # staging key cleaned up
        assert all(not st.path.endswith(".staging")
                   for st in fio.list_status("objfs://out"))

    def test_vectored_ranges(self, fio):
        fio.write_bytes("objfs://r/x", bytes(range(64)))
        assert fio.read_ranges("objfs://r/x", [(0, 4), (60, 4)]) == \
            [bytes(range(4)), bytes(range(60, 64))]


class TestTableOnObjectStore:
    def test_full_lifecycle(self, fio):
        schema = (Schema.builder()
                  .column("id", BigIntType(False))
                  .column("v", DoubleType())
                  .primary_key("id")
                  .options({"bucket": "2", "write-only": "true"})
                  .build())
        t = FileStoreTable.create("objfs://wh/db/t", schema,
                                  file_io=fio)

        def commit(rows, kinds=None):
            wb = t.new_batch_write_builder()
            w = wb.new_write()
            w.write_dicts(rows, row_kinds=kinds)
            sid = wb.new_commit().commit(w.prepare_commit())
            w.close()
            return sid

        commit([{"id": i, "v": float(i)} for i in range(50)])
        commit([{"id": 3, "v": 33.0}])
        commit([{"id": 5, "v": 5.0}], kinds=[RowKind.DELETE])
        rows = sorted(t.to_arrow().to_pylist(), key=lambda r: r["id"])
        assert len(rows) == 49
        assert rows[3]["v"] == 33.0
        assert all(r["id"] != 5 for r in rows)

        assert t.compact(full=True) is not None
        rows2 = sorted(t.to_arrow().to_pylist(), key=lambda r: r["id"])
        assert rows2 == rows

        t.create_tag("v1")
        t.expire_snapshots(retain_max=2, retain_min=1)
        assert sorted(r["id"] for r in
                      t.copy({"scan.tag-name": "v1"}).to_arrow()
                      .to_pylist())[:3] == [0, 1, 2]

        # reload from the bucket (fresh FileIO state)
        t2 = FileStoreTable.load("objfs://wh/db/t", file_io=fio)
        assert sorted(t2.to_arrow().to_pylist(),
                      key=lambda r: r["id"]) == rows


class TestContractEdges:
    def test_rename_contract(self, fio):
        assert not fio.rename("objfs://no/such", "objfs://x")
        fio.write_bytes("objfs://a", b"1")
        fio.write_bytes("objfs://b", b"2")
        assert not fio.rename("objfs://a", "objfs://b")  # dst exists
        assert fio.read_bytes("objfs://b") == b"2"
        # prefix rename moves every child
        fio.write_bytes("objfs://d/t/f1", b"x")
        fio.write_bytes("objfs://d/t/sub/f2", b"y")
        assert fio.rename("objfs://d/t", "objfs://d/u")
        assert fio.read_bytes("objfs://d/u/sub/f2") == b"y"
        assert not fio.exists("objfs://d/t/f1")

    def test_recursive_delete_object_and_prefix(self, fio):
        fio.write_bytes("objfs://k", b"obj")
        fio.write_bytes("objfs://k/child", b"c")
        assert fio.delete("objfs://k", recursive=True)
        assert not fio.exists("objfs://k")
        assert not fio.exists("objfs://k/child")

    def test_listings_never_show_staging(self, fio):
        import threading
        stop = threading.Event()

        def writer():
            i = 0
            while not stop.is_set():
                fio.write_bytes("objfs://c/obj", b"x" * 1000)
                i += 1

        t = threading.Thread(target=writer)
        t.start()
        try:
            for _ in range(200):
                for st in fio.list_status("objfs://c"):
                    assert "staging" not in st.path
                    assert st.path.endswith("obj"), st.path
        finally:
            stop.set()
            t.join()


class TestFaultInjection:
    """503 storms + ambiguous writes + eventually-consistent LIST
    (VERDICT r3 weak #8). reference: hadoop-aws-style retry layers
    under the object-store FileIOs."""

    def _flaky_fio(self, tmp_path, seed, fail_rate=0.15,
                   ambiguous_rate=0.1, list_lag=2):
        from paimon_tpu.fs.object_store import (
            FlakyObjectStoreBackend, RetryingObjectStoreBackend,
        )
        inner = LocalObjectStoreBackend(str(tmp_path / f"bkt{seed}"))
        flaky = FlakyObjectStoreBackend(
            inner, seed=seed, fail_rate=fail_rate,
            ambiguous_rate=ambiguous_rate, list_lag=list_lag)
        return ObjectStoreFileIO(
            RetryingObjectStoreBackend(flaky)), flaky

    def test_ambiguous_conditional_put_recovered(self, tmp_path):
        """503 AFTER the conditional PUT landed: a naive retry sees
        PreconditionFailed from its own write; the retry layer must
        read back, recognize its bytes, and report success."""
        from paimon_tpu.fs.object_store import (
            FlakyObjectStoreBackend, PreconditionFailed,
            RetryingObjectStoreBackend,
        )
        inner = LocalObjectStoreBackend(str(tmp_path / "b"))
        flaky = FlakyObjectStoreBackend(inner, seed=1,
                                        ambiguous_rate=1.0)
        retry = RetryingObjectStoreBackend(flaky)
        retry.put("snap/1", b"mine", if_none_match=True)   # recovered
        assert inner.get("snap/1") == b"mine"
        # a genuine loser (different bytes already there) still fails
        flaky.ambiguous_rate = 0.0
        with pytest.raises(PreconditionFailed):
            retry.put("snap/1", b"other", if_none_match=True)

    def test_503_storm_exhaustion_raises(self, tmp_path):
        from paimon_tpu.fs.object_store import (
            FlakyObjectStoreBackend, RetryingObjectStoreBackend,
            TransientStoreError,
        )
        inner = LocalObjectStoreBackend(str(tmp_path / "b"))
        flaky = FlakyObjectStoreBackend(inner, seed=2, fail_rate=1.0)
        retry = RetryingObjectStoreBackend(flaky, max_attempts=3)
        with pytest.raises(TransientStoreError):
            retry.get("nope")

    @pytest.mark.parametrize("seed", [11, 23, 47])
    def test_lifecycle_survives_storms(self, tmp_path, seed):
        """Full table lifecycle (writes, delete, compaction, reload)
        under injected 503s, ambiguous mutations, and lagging LIST:
        every commit lands exactly once, state stays correct."""
        fio, flaky = self._flaky_fio(tmp_path, seed)
        schema = (Schema.builder()
                  .column("id", BigIntType(False))
                  .column("v", DoubleType())
                  .primary_key("id")
                  .options({"bucket": "2", "write-only": "true"})
                  .build())
        t = FileStoreTable.create("objfs://wh/db/t", schema,
                                  file_io=fio)

        def commit(rows, kinds=None):
            wb = t.new_batch_write_builder()
            w = wb.new_write()
            w.write_dicts(rows, row_kinds=kinds)
            sid = wb.new_commit().commit(w.prepare_commit())
            w.close()
            return sid

        commit([{"id": i, "v": float(i)} for i in range(40)])
        commit([{"id": 7, "v": 77.0}])
        commit([{"id": 9, "v": 9.0}], kinds=[RowKind.DELETE])
        assert t.compact(full=True) is not None

        rows = sorted(t.to_arrow().to_pylist(), key=lambda r: r["id"])
        assert len(rows) == 39
        assert rows[7]["v"] == 77.0
        assert all(r["id"] != 9 for r in rows)
        # snapshot chain is gapless despite retried CAS
        sm = t.snapshot_manager
        latest = sm.latest_snapshot()
        for sid in range(1, latest.id + 1):
            assert sm.snapshot(sid) is not None
        # faults actually fired (the schedule exercised the machinery)
        assert flaky.stats["injected"] > 0
        # reload fresh from the bucket
        t2 = FileStoreTable.load("objfs://wh/db/t", file_io=fio)
        assert sorted(t2.to_arrow().to_pylist(),
                      key=lambda r: r["id"]) == rows

    def test_distinct_payload_racers_single_winner(self, tmp_path):
        """Two contenders with writer-unique payloads and full
        ambiguity injection: exactly one owns the key (the code-review
        regression for the constant-payload lock bug — lock tokens are
        now uuids, so read-back cannot misattribute ownership)."""
        from paimon_tpu.fs.object_store import (
            FlakyObjectStoreBackend, PreconditionFailed,
            RetryingObjectStoreBackend,
        )
        inner = LocalObjectStoreBackend(str(tmp_path / "b"))
        a = RetryingObjectStoreBackend(
            FlakyObjectStoreBackend(inner, seed=5, ambiguous_rate=1.0))
        b = RetryingObjectStoreBackend(
            FlakyObjectStoreBackend(inner, seed=6, ambiguous_rate=1.0))
        a.put("lock", b"token-A", if_none_match=True)   # A lands
        with pytest.raises(PreconditionFailed):
            b.put("lock", b"token-B", if_none_match=True)
        assert inner.get("lock") == b"token-A"
