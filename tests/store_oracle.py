"""Randomized store-level correctness oracle.

The reference's master correctness fixture drives the full store with
random KV workloads and checks every state against a replayed in-memory
model (paimon-core/src/test/java/org/apache/paimon/TestFileStore.java,
TestKeyValueGenerator.java).  This module is that harness for the TPU
store: a seeded generator produces random interleavings of

  - write batches (random sizes/keys/partitions, inserts/updates/deletes)
  - minor + full compactions
  - snapshot expiry
  - mid-stream schema evolution (add-column)

across all four merge engines and the changelog producers, while an
``OracleModel`` replays the exact merge semantics in plain Python dicts.
After every mutation the full merge-on-read scan must equal the model;
at the end every retained snapshot is time-travel read and checked
against the recorded per-snapshot model state, and (for changelog runs)
the drained changelog stream applied event-by-event must reproduce the
final state.
"""

from __future__ import annotations

import copy
import math
import random
from typing import Dict, List, Optional, Tuple

from paimon_tpu.schema import Schema, SchemaChange, SchemaManager
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import (
    BigIntType, DoubleType, IntType, RowKind, VarCharType,
)

VALUE_FIELDS = ["v1", "v2", "name"]


def make_random_engine_table(path: str, seed: int, engine: str, *,
                             buckets: int = 4, commits: int = 3,
                             rows_per_commit: int = 250,
                             key_space: int = 120,
                             deletes: bool = True,
                             sequence_group: bool = False,
                             extra_options: Optional[Dict] = None
                             ) -> FileStoreTable:
    """Randomized multi-bucket, multi-L0-run table for one merge engine.

    Written write-only, so every commit leaves an uncompacted overlapping
    L0 run per touched bucket — the input shape the mesh/single-chip
    compaction equivalence tests need.  Same (seed, engine, knobs) =>
    bit-identical table, so two calls produce interchangeable twins.

    `sequence_group`: partial-update only — members v2,name follow the
    largest v1 (reference PartialUpdateMergeFunction sequence groups).
    """
    rng = random.Random(seed)
    b = (Schema.builder()
         .column("pt", IntType(False))
         .column("id", BigIntType(False))
         .column("v1", IntType())
         .column("v2", DoubleType())
         .column("name", VarCharType.string_type()))
    opts = {"bucket": str(buckets), "write-only": "true",
            "merge-engine": engine}
    if engine == "aggregation":
        opts["fields.v1.aggregate-function"] = "sum"
        opts["fields.v2.aggregate-function"] = "max"
    if sequence_group:
        assert engine == "partial-update"
        opts["fields.v1.sequence-group"] = "v2,name"
    opts.update(extra_options or {})
    table = FileStoreTable.create(
        path, b.primary_key("pt", "id").options(opts).build())
    for _ in range(commits):
        rows, kinds = [], []
        for _ in range(rows_per_commit):
            rows.append({
                "pt": rng.randrange(3),
                "id": rng.randrange(key_space),
                "v1": rng.randrange(1000)
                if rng.random() > 0.1 else None,
                "v2": round(rng.uniform(0, 100), 6)
                if rng.random() > 0.1 else None,
                "name": rng.choice(["a", "b", "c", "longer-value",
                                    None]),
            })
            kinds.append(RowKind.DELETE
                         if deletes and engine == "deduplicate"
                         and rng.random() < 0.15 else RowKind.INSERT)
        wb = table.new_batch_write_builder()
        w = wb.new_write()
        w.write_dicts(rows, row_kinds=kinds)
        wb.new_commit().commit(w.prepare_commit())
        w.close()
    return table


class OracleModel:
    """In-memory replay of per-engine merge semantics.

    Keys are (pt, id); values are plain row dicts.  Mirrors the merge
    functions the store applies on read/compaction:
    DeduplicateMergeFunction, FirstRowMergeFunction,
    PartialUpdateMergeFunction (no sequence groups here — those have
    dedicated example tests), and the aggregation engine with
    v1 -> sum, v2 -> max, others -> last_non_null_value.
    """

    def __init__(self, engine: str):
        self.engine = engine
        self.state: Dict[Tuple, Dict] = {}
        self.fields: List[str] = ["v1", "v2", "name"]

    def add_field(self, name: str):
        self.fields.append(name)
        for row in self.state.values():
            row.setdefault(name, None)

    def apply(self, key: Tuple, values: Dict, kind: int):
        values = dict(values)
        for f in self.fields:
            values.setdefault(f, None)
        if self.engine == "deduplicate":
            if kind in (RowKind.INSERT, RowKind.UPDATE_AFTER):
                self.state[key] = values
            else:
                self.state.pop(key, None)
        elif self.engine == "first-row":
            self.state.setdefault(key, values)
        elif self.engine == "partial-update":
            cur = self.state.setdefault(
                key, {f: None for f in self.fields})
            for f, v in values.items():
                if v is not None:
                    cur[f] = v
        elif self.engine == "aggregation":
            cur = self.state.get(key)
            if cur is None:
                self.state[key] = values
                return
            if values["v1"] is not None:
                cur["v1"] = (cur["v1"] or 0) + values["v1"]
            if values["v2"] is not None:
                cur["v2"] = values["v2"] if cur["v2"] is None \
                    else max(cur["v2"], values["v2"])
            for f in self.fields:
                if f in ("v1", "v2"):
                    continue
                if values.get(f) is not None:
                    cur[f] = values[f]
        else:
            raise ValueError(self.engine)

    def rows(self) -> List[Dict]:
        out = []
        for (pt, kid), vals in self.state.items():
            row = {"pt": pt, "id": kid}
            row.update({f: vals.get(f) for f in self.fields})
            out.append(row)
        return sorted(out, key=lambda r: (r["pt"], r["id"]))


def _rows_equal(actual: List[Dict], expected: List[Dict]) -> Optional[str]:
    if len(actual) != len(expected):
        return f"row count {len(actual)} != {len(expected)}"
    for a, e in zip(actual, expected):
        if set(a) != set(e):
            return f"columns {sorted(a)} != {sorted(e)}"
        for f in e:
            av, ev = a[f], e[f]
            if isinstance(ev, float) and isinstance(av, float):
                if not (math.isclose(av, ev, rel_tol=1e-12, abs_tol=1e-12)):
                    return f"{f}: {av} != {ev} in {a} vs {e}"
            elif av != ev:
                return f"{f}: {av!r} != {ev!r} in {a} vs {e}"
    return None


class StoreOracle:
    """Seeded random workload driver + checker."""

    def __init__(self, path: str, seed: int, engine: str = "deduplicate",
                 changelog_producer: str = "none", bucket: str = "2",
                 partitioned: bool = True, key_space: int = 40,
                 allow_expire: bool = True, allow_schema_add: bool = True,
                 allow_rollback: bool = False):
        self.rng = random.Random(seed)
        self.engine = engine
        self.producer = changelog_producer
        self.partitioned = partitioned
        self.key_space = key_space
        # expiry drops old changelog with it; the changelog-replay check
        # needs the full stream, so expiry only runs without a producer
        self.allow_expire = allow_expire and changelog_producer == "none"
        self.allow_schema_add = allow_schema_add
        # rollback truncates changelog history, so the replay check
        # only composes with producer=none
        self.allow_rollback = allow_rollback and \
            changelog_producer == "none"
        self.model = OracleModel(engine)
        self.snapshots: Dict[int, List[Dict]] = {}   # sid -> expected rows
        self.expired: set = set()
        self.extra_added = False

        b = (Schema.builder()
             .column("pt", IntType(False))
             .column("id", BigIntType(False))
             .column("v1", IntType())
             .column("v2", DoubleType())
             .column("name", VarCharType.string_type()))
        if partitioned:
            b = b.partition_keys("pt")
        opts = {"bucket": bucket, "write-only": "true",
                "merge-engine": engine}
        if changelog_producer != "none":
            opts["changelog-producer"] = changelog_producer
        if engine == "aggregation":
            opts["fields.v1.aggregate-function"] = "sum"
            opts["fields.v2.aggregate-function"] = "max"
        self.table = FileStoreTable.create(
            path, b.primary_key("pt", "id").options(opts).build())

    # -- workload steps ------------------------------------------------------

    def _gen_row(self) -> Tuple[Tuple, Dict]:
        pt = self.rng.randrange(3) if self.partitioned else 0
        kid = self.rng.randrange(self.key_space)
        vals = {
            "v1": self.rng.randrange(1000)
            if self.rng.random() > 0.1 else None,
            "v2": round(self.rng.uniform(0, 100), 6)
            if self.rng.random() > 0.1 else None,
            "name": self.rng.choice(["a", "b", "c", "longer-value", None]),
        }
        if self.extra_added:
            vals["extra"] = self.rng.randrange(50) \
                if self.rng.random() > 0.3 else None
        return (pt, kid), vals

    def step_write(self):
        n = self.rng.randint(1, 40)
        rows, kinds = [], []
        for _ in range(n):
            key, vals = self._gen_row()
            if self.engine == "deduplicate" and self.rng.random() < 0.15:
                kind = RowKind.DELETE
            else:
                kind = RowKind.INSERT
            row = {"pt": key[0], "id": key[1]}
            row.update(vals)
            rows.append(row)
            kinds.append(kind)
            self.model.apply(key, vals, kind)
        wb = self.table.new_batch_write_builder()
        w = wb.new_write()
        w.write_dicts(rows, row_kinds=kinds)
        sid = wb.new_commit().commit(w.prepare_commit())
        w.close()
        if sid is not None:
            self.snapshots[sid] = copy.deepcopy(self.model.rows())
        return f"write({n})"

    def step_compact(self):
        full = self.rng.random() < 0.5
        sid = self.table.compact(full=full)
        if sid is not None:
            self.snapshots[sid] = copy.deepcopy(self.model.rows())
        return f"compact(full={full})"

    def step_expire(self):
        retain = self.rng.randint(3, 6)
        latest = self.table.latest_snapshot()
        self.table.expire_snapshots(retain_max=retain, retain_min=1)
        if latest is not None:
            for sid in list(self.snapshots):
                if sid <= latest.id - retain:
                    self.expired.add(sid)
        return f"expire(retain={retain})"

    def step_rollback(self):
        """Roll back to a random earlier retained snapshot; the model
        rewinds to its recorded state and later history is forgotten
        (reference RollbackHelper)."""
        live = sorted(s for s in self.snapshots if s not in self.expired)
        if len(live) < 2:
            return self.step_write()
        target = self.rng.choice(live[:-1])
        self.table.rollback_to(target)
        self.model.state = {
            (r["pt"], r["id"]): {f: r.get(f) for f in self.model.fields}
            for r in self.snapshots[target]}
        for sid in list(self.snapshots):
            if sid > target:
                del self.snapshots[sid]
        return f"rollback({target})"

    def step_schema_add(self):
        sm = SchemaManager(self.table.file_io, self.table.path)
        sm.commit_changes(SchemaChange.add_column("extra", IntType()))
        self.table = FileStoreTable.load(self.table.path,
                                         self.table.file_io)
        self.model.add_field("extra")
        self.extra_added = True
        return "add_column(extra)"

    # -- checks --------------------------------------------------------------

    def check_now(self, context: str):
        actual = sorted(self.table.to_arrow().to_pylist(),
                        key=lambda r: (r["pt"], r["id"]))
        diff = _rows_equal(actual, self.model.rows())
        assert diff is None, f"after {context}: {diff}"

    def check_time_travel(self, sample: int = 4):
        live = [s for s in self.snapshots if s not in self.expired]
        for sid in self.rng.sample(live, min(sample, len(live))):
            fs_scan = self.table.new_scan()
            snap = fs_scan.snapshot_manager.snapshot(sid)
            plan = fs_scan.plan(snapshot=snap)
            t = self.table.new_read_builder().new_read() \
                .to_arrow(plan.splits)
            actual = sorted(t.to_pylist(), key=lambda r: (r["pt"], r["id"]))
            expected = self.snapshots[sid]
            if self.extra_added and expected and \
                    "extra" not in expected[0]:
                # snapshot predates the add-column; read maps old files
                # through the current schema with nulls for the new field
                expected = [dict(r, extra=None) for r in expected]
            diff = _rows_equal(actual, expected)
            assert diff is None, f"time-travel snapshot {sid}: {diff}"

    def check_changelog_replay(self):
        """Drain the changelog stream from the beginning and apply it
        event-by-event; the result must equal the final model state.
        Valid for deduplicate (events are whole-row upserts/deletes)
        with producers input and lookup, which guarantee an event for
        every committed change.  full-compaction only reflects state
        as of full compactions (reference FullChangelog semantics): a
        key inserted and deleted entirely between two full compactions
        legitimately emits nothing, so a from-snapshot-full consumer's
        initial scan can see rows whose retraction never appears —
        replay equality does not hold by design."""
        if self.producer not in ("input", "lookup") or \
                self.engine != "deduplicate":
            return
        if self.producer == "lookup":
            # changelog is produced at compaction time; flush the tail
            sid = self.table.compact(full=True)
            if sid is not None:
                self.snapshots[sid] = copy.deepcopy(self.model.rows())
        scan = self.table.copy({"scan.mode": "from-snapshot-full",
                                "scan.snapshot-id": "1"}) \
            .new_read_builder().new_stream_scan()
        applied: Dict[Tuple, Dict] = {}
        read = self.table.new_read_builder().new_read()
        while True:
            plan = scan.plan()
            if plan is None:
                break
            t = read.to_arrow(plan)
            for row in t.to_pylist():
                kind = row.pop("_ROW_KIND", RowKind.INSERT)
                key = (row["pt"], row["id"])
                if kind in (RowKind.INSERT, RowKind.UPDATE_AFTER):
                    applied[key] = row
                elif kind == RowKind.DELETE:
                    applied.pop(key, None)
                # UPDATE_BEFORE: superseded by its UPDATE_AFTER
        actual = sorted(applied.values(), key=lambda r: (r["pt"], r["id"]))
        diff = _rows_equal(actual, self.model.rows())
        assert diff is None, f"changelog replay: {diff}"

    # -- driver --------------------------------------------------------------

    def run(self, steps: int = 20):
        schema_add_at = self.rng.randrange(steps) \
            if self.allow_schema_add else -1
        for i in range(steps):
            r = self.rng.random()
            if i == schema_add_at and not self.extra_added:
                ctx = self.step_schema_add()
            elif r < 0.70 or self.table.latest_snapshot() is None:
                ctx = self.step_write()
            elif r < 0.85:
                ctx = self.step_compact()
            elif r < 0.92 and self.allow_rollback:
                ctx = self.step_rollback()
            elif self.allow_expire:
                ctx = self.step_expire()
            else:
                ctx = self.step_compact()
            self.check_now(f"step {i}: {ctx}")
        self.check_time_travel()
        self.check_changelog_replay()


class ConcurrentOracle:
    """Randomized MULTI-WRITER oracle: N writer threads + a racing
    compactor, interleaved by the OS scheduler, checked after
    quiescence (reference ConflictDetection.java +
    FileStoreCommitImpl.java:756 retry loop; the reference's
    TestFileStore oracle is single-writer — concurrency there is
    covered by example tests, here by a seeded random harness).

    Modes, chosen by what the store's semantics actually guarantee
    under concurrency (sequence numbers are writer-local, restored
    from the latest snapshot, so overlapping-key dedup interleavings
    are NOT linearizable by commit order — same as the reference):

    - ``disjoint-dedup``: each writer owns a partition; exact model
      equality must hold regardless of interleaving.
    - ``overlap-agg``: all writers hit one shared key space with a
      commutative aggregation engine (sum/max); the final state is
      interleaving-independent, so exact equality must hold.
    - ``overlap-dedup``: shared key space, deduplicate; exact winners
      are timing-dependent, so the checks are corruption invariants:
      every surviving row must be bit-identical to SOME batch's write
      of that key (no torn/mixed rows), no key appears that was never
      written, and a final full compaction must not change the state.

    In every mode: all successful commits produced distinct contiguous
    snapshot ids, and any commit failure must be the typed
    CommitConflictError — anything else is a bug.
    """

    def __init__(self, path: str, seed: int, mode: str = "disjoint-dedup",
                 writers: int = 3, bucket: str = "2",
                 key_space: int = 30):
        assert mode in ("disjoint-dedup", "overlap-agg", "overlap-dedup")
        self.path = path
        self.seed = seed
        self.mode = mode
        self.writers = writers
        self.key_space = key_space
        engine = "aggregation" if mode == "overlap-agg" else "deduplicate"
        self.engine = engine
        opts = {"bucket": bucket, "write-only": "true",
                "merge-engine": engine}
        if engine == "aggregation":
            opts["fields.v1.aggregate-function"] = "sum"
            opts["fields.v2.aggregate-function"] = "max"
        b = (Schema.builder()
             .column("pt", IntType(False))
             .column("id", BigIntType(False))
             .column("v1", IntType())
             .column("v2", DoubleType())
             .column("name", VarCharType.string_type())
             .partition_keys("pt"))
        self.table = FileStoreTable.create(
            path, b.primary_key("pt", "id").options(opts).build())
        # (sid, writer_idx, batch) for every SUCCESSFUL write commit;
        # batch = [(key, vals, kind)]
        self.commits: List[Tuple[int, int, list]] = []
        self.conflicts: List[str] = []
        self.errors: List[BaseException] = []

    # -- writer / compactor bodies -------------------------------------------

    def _writer_body(self, idx: int, ops: int, barrier):
        rng = random.Random(self.seed * 1000 + idx)
        table = FileStoreTable.load(self.path)
        import threading
        barrier.wait()
        for _ in range(ops):
            n = rng.randint(1, 25)
            batch = []
            rows, kinds = [], []
            for _ in range(n):
                if self.mode == "disjoint-dedup":
                    pt = idx                        # owned partition
                else:
                    pt = rng.randrange(2)           # shared partitions
                kid = rng.randrange(self.key_space)
                vals = {
                    "v1": rng.randrange(1000)
                    if rng.random() > 0.1 else None,
                    "v2": round(rng.uniform(0, 100), 6)
                    if rng.random() > 0.1 else None,
                    # aggregation's name column uses last_non_null —
                    # order-dependent — so keep it None in agg mode
                    "name": None if self.engine == "aggregation"
                    else rng.choice(["a", "b", "c", None]),
                }
                kind = RowKind.DELETE \
                    if self.engine == "deduplicate" and \
                    rng.random() < 0.12 else RowKind.INSERT
                batch.append(((pt, kid), dict(vals), kind))
                row = {"pt": pt, "id": kid}
                row.update(vals)
                rows.append(row)
                kinds.append(kind)
            try:
                wb = table.new_batch_write_builder()
                w = wb.new_write()
                w.write_dicts(rows, row_kinds=kinds)
                sid = wb.new_commit().commit(w.prepare_commit())
                w.close()
            except Exception as e:      # noqa: BLE001
                from paimon_tpu.core.commit import CommitConflictError
                if isinstance(e, CommitConflictError):
                    self.conflicts.append(f"writer{idx}: {e}")
                    continue            # typed abort is acceptable
                self.errors.append(e)
                raise
            if sid is not None:
                self.commits.append((sid, idx, batch))
            if rng.random() < 0.2:
                self._compact_once(table, full=rng.random() < 0.5,
                                   who=f"writer{idx}")

    def _compact_once(self, table, full: bool, who: str):
        from paimon_tpu.core.commit import CommitConflictError
        try:
            table.compact(full=full)
        except CommitConflictError as e:
            self.conflicts.append(f"{who} compact: {e}")

    def _compactor_body(self, rounds: int, barrier):
        rng = random.Random(self.seed * 7777)
        table = FileStoreTable.load(self.path)
        barrier.wait()
        for _ in range(rounds):
            self._compact_once(table, full=rng.random() < 0.5,
                               who="compactor")

    # -- driver + checks -----------------------------------------------------

    def run(self, ops_per_writer: int = 6, compactor_rounds: int = 4):
        import threading
        barrier = threading.Barrier(self.writers + 1)
        threads = [threading.Thread(
            target=self._writer_body, args=(i, ops_per_writer, barrier))
            for i in range(self.writers)]
        threads.append(threading.Thread(
            target=self._compactor_body, args=(compactor_rounds, barrier)))
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        assert not any(t.is_alive() for t in threads), "deadlocked thread"
        assert not self.errors, f"non-conflict failures: {self.errors!r}"
        self.check_commit_chain()
        table = FileStoreTable.load(self.path)
        if self.mode in ("disjoint-dedup", "overlap-agg"):
            self.check_exact(table)
        else:
            self.check_invariants(table)
        # quiescent full compaction must preserve the merged state
        before = sorted(table.to_arrow().to_pylist(),
                        key=lambda r: (r["pt"], r["id"]))
        table.compact(full=True)
        after = sorted(FileStoreTable.load(self.path).to_arrow()
                       .to_pylist(), key=lambda r: (r["pt"], r["id"]))
        diff = _rows_equal(after, before)
        assert diff is None, f"full compaction changed state: {diff}"

    def check_commit_chain(self):
        sids = [sid for sid, _, _ in self.commits]
        assert len(sids) == len(set(sids)), "duplicate snapshot ids"
        sm = self.table.snapshot_manager
        latest = sm.latest_snapshot()
        assert latest is not None
        # every snapshot from 1..latest exists (CAS left no gaps)
        for sid in range(1, latest.id + 1):
            assert sm.snapshot(sid) is not None, f"gap at snapshot {sid}"

    def check_exact(self, table):
        model = OracleModel(self.engine)
        for sid, _, batch in sorted(self.commits):
            for key, vals, kind in batch:
                model.apply(key, vals, kind)
        actual = sorted(table.to_arrow().to_pylist(),
                        key=lambda r: (r["pt"], r["id"]))
        diff = _rows_equal(actual, model.rows())
        assert diff is None, \
            f"{self.mode} seed={self.seed}: {diff} " \
            f"({len(self.commits)} commits, {len(self.conflicts)} " \
            f"conflicts)"

    def check_invariants(self, table):
        written: Dict[Tuple, list] = {}
        deleted: set = set()
        for _, _, batch in self.commits:
            for key, vals, kind in batch:
                if kind == RowKind.DELETE:
                    deleted.add(key)
                else:
                    full = {"v1": vals.get("v1"), "v2": vals.get("v2"),
                            "name": vals.get("name")}
                    written.setdefault(key, []).append(full)
        for row in table.to_arrow().to_pylist():
            key = (row["pt"], row["id"])
            got = {"v1": row["v1"], "v2": row["v2"], "name": row["name"]}
            assert key in written, f"phantom key {key}"
            assert got in written[key], \
                f"torn row for {key}: {got} not among " \
                f"{len(written[key])} written versions"
