"""IVF-PQ: codebook training, ADC search, refine rerank, persistence.

reference: paimon-vector IVF-PQ factory (NativeVectorIndexLoader.java:28).
"""

import numpy as np
import pytest

from paimon_tpu.vector.ann import (BruteForceIndex, IVFPQIndex,
                                   PersistedVectorIndex)


def clustered(n, d, n_centers=64, seed=0, spread=0.15):
    """Clustered corpus — the realistic ANN workload (pure uniform
    noise is information-theoretically hostile to any quantizer)."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(n_centers, d)).astype(np.float32)
    assign = rng.integers(0, n_centers, n)
    return (centers[assign]
            + spread * rng.normal(size=(n, d)).astype(np.float32)) \
        .astype(np.float32), rng


def recall_at_k(idx_result, exact_result, k):
    hits = 0
    for got, want in zip(idx_result, exact_result):
        hits += len(set(got[:k].tolist()) & set(want[:k].tolist()))
    return hits / (len(idx_result) * k)


class TestIVFPQ:
    def test_recall_with_refine(self):
        v, rng = clustered(20_000, 64)
        queries = v[rng.choice(len(v), 32, replace=False)] \
            + 0.01 * rng.normal(size=(32, 64)).astype(np.float32)
        bf = BruteForceIndex(v, "l2")
        _, exact = bf.search(queries, 10)
        idx = IVFPQIndex(v, m=8, metric="l2", seed=1)
        _, got = idx.search(queries, 10, nprobe=16, refine=100)
        r = recall_at_k(got, exact, 10)
        assert r >= 0.9, f"recall@10 = {r}"

    def test_adc_alone_beats_random(self):
        v, rng = clustered(8_000, 32)
        queries = v[:8]
        bf = BruteForceIndex(v, "l2")
        _, exact = bf.search(queries, 10)
        idx = IVFPQIndex(v, m=8, metric="l2")
        _, got = idx.search(queries, 10, nprobe=8)
        assert recall_at_k(got, exact, 10) >= 0.5

    def test_memory_budget(self):
        """The compressed index must be far below raw f32 residency —
        the whole point of PQ (raw 64 f32 dims = 256 B/vec; PQ m=8
        codes = 8 B/vec)."""
        v, _ = clustered(20_000, 64)
        idx = IVFPQIndex(v, m=8, keep_vectors=False)
        raw_bytes = v.nbytes
        assert idx.memory_bytes() < raw_bytes / 8
        assert idx._vectors is None

    def test_cosine_metric(self):
        v, rng = clustered(5_000, 32)
        queries = v[:5]
        bf = BruteForceIndex(v, "cosine")
        _, exact = bf.search(queries, 5)
        idx = IVFPQIndex(v, m=8, metric="cosine")
        _, got = idx.search(queries, 5, nprobe=16, refine=50)
        assert recall_at_k(got, exact, 5) >= 0.9

    def test_search_contract_shapes(self):
        v, _ = clustered(2_000, 16)
        idx = IVFPQIndex(v, m=4)
        scores, ids = idx.search(v[0], 7)
        assert scores.shape == (1, 7) and ids.shape == (1, 7)
        assert np.all(np.diff(scores[0][ids[0] >= 0]) <= 1e-5)

    def test_dim_not_divisible_raises(self):
        v, _ = clustered(100, 30)
        with pytest.raises(ValueError, match="divisible"):
            IVFPQIndex(v, m=8)


class TestPersistedVectorIndex:
    def _table(self, tmp_path, n=2_000, d=32):
        import pyarrow as pa
        from paimon_tpu.schema import Schema
        from paimon_tpu.table import FileStoreTable
        from paimon_tpu.types import BigIntType, ArrayType, FloatType
        schema = (Schema.builder()
                  .column("id", BigIntType(False))
                  .column("emb", ArrayType(FloatType()))
                  .options({"bucket": "-1"})
                  .build())
        t = FileStoreTable.create(str(tmp_path / "vecs"), schema)
        v, _ = clustered(n, d)
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        w.write_arrow(pa.table({
            "id": pa.array(range(n), pa.int64()),
            "emb": pa.array(v.tolist(),
                            pa.list_(pa.float32()))}))
        wb.new_commit().commit(w.prepare_commit())
        w.close()
        return t, v

    def test_build_persist_load(self, tmp_path):
        t, v = self._table(tmp_path)
        p = PersistedVectorIndex(t, "emb")
        built = p.build(m=4)
        loaded = p.load()
        assert loaded is not None
        np.testing.assert_array_equal(built.codes, loaded.codes)
        np.testing.assert_allclose(built.centroids, loaded.centroids)
        # loaded index searches without raw vectors in memory
        scores, ids = loaded.search(v[:4], 5, nprobe=8)
        assert ids.shape == (4, 5)
        assert np.all(ids[:, 0] >= 0)

    def test_stale_after_new_commit(self, tmp_path):
        import pyarrow as pa
        t, v = self._table(tmp_path)
        p = PersistedVectorIndex(t, "emb")
        p.build(m=4)
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        w.write_arrow(pa.table({
            "id": pa.array([99999], pa.int64()),
            "emb": pa.array([v[0].tolist()], pa.list_(pa.float32()))}))
        wb.new_commit().commit(w.prepare_commit())
        w.close()
        assert p.load() is None              # stale -> rebuild
        assert len(p.load_or_build(m=4)) == len(v) + 1

    def test_refine_with_external_vectors(self, tmp_path):
        t, v = self._table(tmp_path)
        p = PersistedVectorIndex(t, "emb")
        p.build(m=4)
        loaded = p.load()
        bf = BruteForceIndex(v, "l2")
        _, exact = bf.search(v[:8], 5)
        _, got = loaded.search(v[:8], 5, nprobe=16, refine=64,
                               vectors=v)
        assert recall_at_k(got, exact, 5) >= 0.9
