"""CLI tests (reference pypaimon/cli/): drive `paimon_tpu.cli.main`
in-process with --warehouse pointing at a temp filesystem catalog."""

import json

import pytest

from paimon_tpu.cli import main


@pytest.fixture()
def wh(tmp_path):
    return str(tmp_path / "wh")


def run(capsys, *argv):
    rc = main(list(argv))
    out = capsys.readouterr()
    return rc, out.out, out.err


def _bootstrap(capsys, wh):
    assert run(capsys, "-w", wh, "db", "create", "d1")[0] == 0
    rc, out, err = run(
        capsys, "-w", wh, "table", "create", "d1.t",
        "--column", "id:BIGINT NOT NULL", "--column", "v:DOUBLE",
        "--primary-key", "id", "--option", "bucket=1")
    assert rc == 0, err
    rc, out, err = run(
        capsys, "-w", wh, "sql",
        "INSERT INTO d1.t VALUES (1, 1.5), (2, 2.5)")
    assert rc == 0, err


class TestCli:
    def test_db_lifecycle(self, capsys, wh):
        assert run(capsys, "-w", wh, "db", "create", "mydb")[0] == 0
        rc, out, _ = run(capsys, "-w", wh, "db", "list")
        assert "mydb" in out.splitlines()
        assert run(capsys, "-w", wh, "db", "drop", "mydb")[0] == 0
        rc, out, _ = run(capsys, "-w", wh, "db", "list")
        assert "mydb" not in out

    def test_table_create_read(self, capsys, wh):
        _bootstrap(capsys, wh)
        rc, out, _ = run(capsys, "-w", wh, "table", "list", "d1")
        assert out.splitlines() == ["t"]
        rc, out, _ = run(capsys, "-w", wh, "table", "get", "d1.t")
        info = json.loads(out)
        assert info["primary_keys"] == ["id"]
        assert info["options"]["bucket"] == "1"
        rc, out, _ = run(capsys, "-f", "json", "-w", wh,
                         "table", "read", "d1.t")
        rows = [json.loads(line) for line in out.splitlines()]
        assert rows == [{"id": 1, "v": 1.5}, {"id": 2, "v": 2.5}]

    def test_read_formats(self, capsys, wh):
        _bootstrap(capsys, wh)
        rc, out, _ = run(capsys, "-f", "csv", "-w", wh,
                         "table", "read", "d1.t", "--columns", "id")
        lines = [ln for ln in out.splitlines() if ln.strip()]
        assert lines[0].strip('"') == "id"
        assert [ln for ln in lines[1:]] == ["1", "2"]
        rc, out, _ = run(capsys, "-w", wh, "table", "read", "d1.t",
                         "--limit", "1")
        assert "1 row(s)" in out

    def test_sql_subcommand(self, capsys, wh):
        _bootstrap(capsys, wh)
        rc, out, _ = run(capsys, "-f", "json", "-w", wh, "sql",
                         "SELECT sum(v) AS s FROM d1.t", "-d", "d1")
        assert json.loads(out.splitlines()[0]) == {"s": 4.0}

    def test_compact_and_snapshot(self, capsys, wh):
        _bootstrap(capsys, wh)
        rc, out, _ = run(capsys, "-w", wh, "table", "compact", "d1.t",
                         "--full")
        assert "snapshot" in out
        rc, out, _ = run(capsys, "-w", wh, "table", "snapshot", "d1.t")
        snap = json.loads(out)
        assert snap["commitKind"] == "COMPACT"

    def test_tags_and_branches(self, capsys, wh):
        _bootstrap(capsys, wh)
        assert run(capsys, "-w", wh, "tag", "create", "d1.t", "v1")[0] == 0
        rc, out, _ = run(capsys, "-f", "json", "-w", wh,
                         "tag", "list", "d1.t")
        assert any(json.loads(l).get("tag_name") == "v1"
                   for l in out.splitlines())
        assert run(capsys, "-w", wh, "branch", "create", "d1.t", "b1",
                   "--tag", "v1")[0] == 0
        rc, out, _ = run(capsys, "-f", "json", "-w", wh,
                         "branch", "list", "d1.t")
        assert any(json.loads(l).get("branch_name") == "b1"
                   for l in out.splitlines())
        assert run(capsys, "-w", wh, "tag", "delete", "d1.t", "v1")[0] == 0

    def test_import_csv(self, capsys, wh, tmp_path):
        _bootstrap(capsys, wh)
        f = tmp_path / "data.csv"
        f.write_text("id,v\n10,10.5\n11,11.5\n")
        rc, out, _ = run(capsys, "-w", wh, "table", "import", "d1.t",
                         str(f))
        assert "2 rows imported" in out
        rc, out, _ = run(capsys, "-f", "json", "-w", wh, "sql",
                         "SELECT count(*) AS n FROM d1.t", "-d", "d1")
        assert json.loads(out.splitlines()[0]) == {"n": 4}

    def test_options_and_columns(self, capsys, wh):
        _bootstrap(capsys, wh)
        assert run(capsys, "-w", wh, "table", "set-option", "d1.t",
                   "snapshot.num-retained.max", "20")[0] == 0
        assert run(capsys, "-w", wh, "table", "add-column", "d1.t",
                   "note", "STRING")[0] == 0
        rc, out, _ = run(capsys, "-w", wh, "table", "get", "d1.t")
        info = json.loads(out)
        assert info["options"]["snapshot.num-retained.max"] == "20"
        assert info["fields"][-1]["name"] == "note"

    def test_error_paths(self, capsys, wh):
        rc, out, err = run(capsys, "-w", wh, "table", "get", "nope.t")
        assert rc == 1 and "error:" in err
        rc, out, err = run(capsys, "-w", wh, "table", "get", "badname")
        assert rc != 0
        assert main([]) == 0          # bare invocation prints help
