"""Compaction tests: strategy picks + end-to-end rewrite."""

import pyarrow as pa
import pytest

from paimon_tpu.compact import (
    CompactUnit, Levels, LevelSortedRun, SortedRun, UniversalCompaction,
)
from paimon_tpu.manifest import DataFileMeta, SimpleStats
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, RowKind, VarCharType


def fake_file(name, size, level=0, seq=0):
    return DataFileMeta(
        file_name=name, file_size=size, row_count=size,
        min_key=b"", max_key=b"", key_stats=SimpleStats.EMPTY,
        value_stats=SimpleStats.EMPTY, min_sequence_number=seq,
        max_sequence_number=seq, schema_id=0, level=level)


def run_of(level, *sizes, seq=0):
    return LevelSortedRun(level, SortedRun(
        [fake_file(f"f{level}-{i}-{seq}", s, level, seq + i)
         for i, s in enumerate(sizes)]))


class TestUniversalPick:
    def test_no_pick_below_trigger(self):
        u = UniversalCompaction(200, 1, 5)
        runs = [run_of(0, 10), run_of(0, 10)]
        assert u.pick(6, runs) is None

    def test_size_amp_full_compaction(self):
        u = UniversalCompaction(max_size_amp=100, size_ratio=1,
                                num_run_trigger=3)
        # candidate (all but last) = 300, earliest = 100 -> 300*100 >
        # 100*100 -> full compaction to max level
        runs = [run_of(0, 100, seq=1), run_of(0, 200, seq=2),
                run_of(5, 100)]
        unit = u.pick(6, runs)
        assert unit is not None
        assert unit.output_level == 5
        assert len(unit.files) == 3

    def test_size_ratio_merges_similar_runs(self):
        u = UniversalCompaction(max_size_amp=10**9, size_ratio=1,
                                num_run_trigger=3)
        runs = [run_of(0, 100, seq=3), run_of(0, 100, seq=2),
                run_of(0, 100, seq=1), run_of(5, 100000)]
        unit = u.pick(6, runs)
        assert unit is not None
        # the three similar L0 runs merge; big old run untouched
        assert len(unit.files) == 3
        assert unit.output_level == 4  # level of next run (5) - 1

    def test_file_num_trigger(self):
        u = UniversalCompaction(max_size_amp=10**9, size_ratio=0,
                                num_run_trigger=3)
        runs = [run_of(0, 1, seq=4), run_of(0, 100, seq=3),
                run_of(0, 10000, seq=2), run_of(0, 1000000, seq=1)]
        unit = u.pick(6, runs)
        assert unit is not None  # count trigger kicks in


def pk_table(tmp_path, **options):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", VarCharType.string_type())
              .primary_key("id")
              .options({"bucket": "1", **options})
              .build())
    return FileStoreTable.create(str(tmp_path / "t"), schema)


def write_rows(table, rows, kinds=None):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows, kinds)
    return wb.new_commit().commit(w.prepare_commit())


def test_full_compaction_e2e(tmp_path):
    table = pk_table(tmp_path)
    for i in range(4):
        write_rows(table, [{"id": k, "v": f"v{i}-{k}"}
                           for k in range(i * 5, i * 5 + 10)])
    files_before = table.new_read_builder().new_scan().plan()
    n_files_before = sum(len(s.data_files) for s in files_before.splits)
    assert n_files_before == 4

    sid = table.compact(full=True)
    assert sid is not None
    snap = table.latest_snapshot()
    assert snap.commit_kind == "COMPACT"

    plan = table.new_read_builder().new_scan().plan()
    files = [f for s in plan.splits for f in s.data_files]
    assert len(files) == 1
    assert files[0].level == table.options.num_levels - 1
    assert plan.splits[0].raw_convertible

    out = table.to_arrow().sort_by("id")
    assert out.num_rows == 25
    # latest writer wins for overlapping keys
    assert out.column("v").to_pylist()[5] == "v1-5"


def test_compaction_drops_deletes_at_max_level(tmp_path):
    table = pk_table(tmp_path)
    write_rows(table, [{"id": 1, "v": "a"}, {"id": 2, "v": "b"}])
    write_rows(table, [{"id": 1, "v": "x"}], kinds=[RowKind.DELETE])
    table.compact(full=True)
    plan = table.new_read_builder().new_scan().plan()
    files = [f for s in plan.splits for f in s.data_files]
    assert len(files) == 1
    assert files[0].delete_row_count == 0
    assert files[0].row_count == 1  # tombstone physically dropped
    assert table.to_arrow().column("id").to_pylist() == [2]


def test_compaction_noop_when_compacted(tmp_path):
    table = pk_table(tmp_path)
    write_rows(table, [{"id": 1, "v": "a"}])
    assert table.compact(full=True) is not None
    # second full compaction: nothing to do
    assert table.compact(full=True) is None


def test_auto_compaction_trigger(tmp_path):
    # write-only: runs accumulate so the MANUAL universal pick fires
    # (non write-only tables now auto-compact at commit)
    table = pk_table(tmp_path,
                     **{"num-sorted-run.compaction-trigger": "3",
                        "write-only": "true"})
    for i in range(5):
        write_rows(table, [{"id": k, "v": f"r{i}"} for k in range(5)])
    sid = table.compact()  # universal pick should fire (5 runs > 3)
    assert sid is not None
    plan = table.new_read_builder().new_scan().plan()
    files = [f for s in plan.splits for f in s.data_files]
    assert len(files) < 5
    out = table.to_arrow().sort_by("id")
    assert out.column("v").to_pylist() == ["r4"] * 5


def test_read_after_compaction_mixed_levels(tmp_path):
    table = pk_table(tmp_path)
    write_rows(table, [{"id": k, "v": "old"} for k in range(10)])
    table.compact(full=True)
    write_rows(table, [{"id": k, "v": "new"} for k in range(5)])
    out = table.to_arrow().sort_by("id")
    assert out.column("v").to_pylist() == ["new"] * 5 + ["old"] * 5


def test_file_format_per_level(tmp_warehouse):
    """'0:avro' puts hot L0 flushes in the row codec while compaction
    rewrites settle into parquet (reference file.format.per.level)."""
    from paimon_tpu.schema import Schema
    from paimon_tpu.table import FileStoreTable
    from paimon_tpu.types import BigIntType, DoubleType
    import os

    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true",
                        "file.format.per.level": "0:avro"})
              .build())
    t = FileStoreTable.create(os.path.join(tmp_warehouse, "t"), schema)
    for i in range(3):
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        w.write_dicts([{"id": i, "v": float(i)}])
        wb.new_commit().commit(w.prepare_commit())
        w.close()
    files = [f for s in t.new_read_builder().new_scan().plan().splits
             for f in s.data_files]
    assert all(f.file_name.endswith(".avro") for f in files)
    t.compact(full=True)
    files = [f for s in t.new_read_builder().new_scan().plan().splits
             for f in s.data_files]
    assert all(f.file_name.endswith(".parquet") for f in files)
    assert sorted(t.to_arrow().column("id").to_pylist()) == [0, 1, 2]


def test_file_format_per_level_validation():
    from paimon_tpu.options import CoreOptions, Options
    import pytest as _pytest
    with _pytest.raises(ValueError, match="file.format.per.level"):
        CoreOptions(Options({"file.format.per.level": "avro"})) \
            .file_format_per_level
    with _pytest.raises(ValueError, match="not an integer"):
        CoreOptions(Options({"file.format.per.level": "L0:avro"})) \
            .file_format_per_level
    assert CoreOptions(Options({"file.format.per.level": "0:AVRO"})) \
        .file_format_per_level == {0: "avro"}
