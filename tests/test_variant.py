"""Variant binary format, path access, shredding (reference
paimon-common data/variant/ + GenericVariantUtil tests)."""

import json

import pyarrow as pa
import pytest

from paimon_tpu.data.variant import (
    ShreddingPlan, Variant, column_from_objects, column_to_variants,
    shred_column, typed_path_column, unshred_column, variant_get,
)


SAMPLE = {
    "id": 12345678901,
    "name": "widget",
    "price": 9.99,
    "active": True,
    "tags": ["a", "b", None],
    "dims": {"w": 3, "h": 250, "note": "x" * 100},
    "nothing": None,
}


class TestCodec:
    def test_roundtrip_object(self):
        v = Variant.from_object(SAMPLE)
        assert v.to_object() == SAMPLE

    def test_roundtrip_json(self):
        v = Variant.from_json(json.dumps(SAMPLE))
        assert json.loads(v.to_json()) == SAMPLE

    @pytest.mark.parametrize("obj", [
        None, True, False, 0, -1, 127, -128, 32767, 2**31 - 1,
        -2**63, 2**63 - 1, 1.5, "", "short", "x" * 1000, b"\x00\xff",
        [], {}, [1, [2, [3]]], {"a": {"b": {"c": "deep"}}},
        [{"k": i} for i in range(300)],           # large array
    ])
    def test_roundtrip_values(self, obj):
        assert Variant.from_object(obj).to_object() == obj

    def test_large_object(self):
        obj = {f"key{i}": i for i in range(300)}
        assert Variant.from_object(obj).to_object() == obj

    def test_shared_key_dictionary(self):
        # repeated keys across nested objects encode once
        v1 = Variant.from_object([{"k": 1}, {"k": 2}, {"k": 3}])
        v2 = Variant.from_object([{"k": 1}])
        assert v1._dict_keys() == v2._dict_keys() == ["k"]

    def test_int_out_of_range(self):
        with pytest.raises(ValueError):
            Variant.from_object(2**63)


class TestPaths:
    def test_path_access(self):
        v = Variant.from_object(SAMPLE)
        assert v.get("$.name") == "widget"
        assert v.get("$.dims.w") == 3
        assert v.get("$['dims']['h']") == 250
        assert v.get("$.tags[1]") == "b"
        assert v.get("$.tags[9]") is None
        assert v.get("$.missing") is None
        assert v.get("$.dims.missing.deeper") is None
        assert variant_get(None, "$.x") is None

    def test_bad_paths(self):
        v = Variant.from_object({})
        with pytest.raises(ValueError):
            v.get("a.b")
        with pytest.raises(ValueError):
            v.get("$!!")


class TestShredding:
    def _col(self):
        rows = [
            {"a": 1, "b": "x", "extra": [1, 2]},
            {"a": 2, "b": "y"},
            {"a": "not-an-int", "b": "z"},     # type mismatch
            None,
            {"b": "w"},                        # missing path
        ]
        return column_from_objects(rows), rows

    def test_shred_and_typed_read(self):
        col, rows = self._col()
        plan = ShreddingPlan({"$.a": pa.int64(), "$.b": pa.string()})
        shredded = shred_column(col, plan)
        a = typed_path_column(shredded, plan, "$.a")
        b = typed_path_column(shredded, plan, "$.b")
        assert a.to_pylist() == [1, 2, None, None, None]
        assert b.to_pylist() == ["x", "y", "z", None, "w"]

    def test_residual_roundtrip(self):
        col, rows = self._col()
        plan = ShreddingPlan({"$.a": pa.int64()})
        shredded = shred_column(col, plan)
        back = unshred_column(shredded)
        vs = column_to_variants(back)
        assert vs[3] is None
        assert vs[0].to_object() == rows[0]
        assert vs[2].to_object() == rows[2]    # mismatch kept in full

    def test_arrow_column_roundtrip(self):
        col, rows = self._col()
        vs = column_to_variants(col)
        assert [None if v is None else v.to_object() for v in vs] == rows


class TestTableIntegration:
    def test_variant_column_through_table(self, tmp_path):
        """Variant columns persist through a real table write/read as
        struct<metadata,value> and decode back."""
        from paimon_tpu.schema import Schema
        from paimon_tpu.table import FileStoreTable
        from paimon_tpu.types import BigIntType, VariantType
        import pyarrow as _pa

        schema = (Schema.builder()
                  .column("id", BigIntType(False))
                  .column("payload", VariantType())
                  .options({"bucket": "-1"})
                  .build())
        t = FileStoreTable.create(str(tmp_path / "t"), schema)
        payloads = [SAMPLE, {"k": [1, 2, 3]}, None]
        data = _pa.table({
            "id": _pa.array([1, 2, 3], _pa.int64()),
            "payload": column_from_objects(payloads),
        })
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        w.write_arrow(data)
        wb.new_commit().commit(w.prepare_commit())
        out = t.to_arrow().sort_by("id")
        vs = column_to_variants(out.column("payload"))
        assert vs[0].to_object() == SAMPLE
        assert vs[1].get("$.k[2]") == 3
        assert vs[2] is None


class TestSpecConformance:
    def test_object_fields_sorted_by_key_name(self):
        # open-variant readers binary-search fields by name: encode
        # order must be lexicographic regardless of insertion order
        v = Variant.from_object({"b": 1, "a": 2, "c": 0})
        assert list(v.to_object().keys()) == ["a", "b", "c"]

    def test_shredding_is_lossless_only(self):
        # 9.99 must NOT truncate into an int64 typed column
        col = column_from_objects([{"price": 9.99}, {"price": 10}])
        plan = ShreddingPlan({"$.price": pa.int64()})
        sh = shred_column(col, plan)
        assert typed_path_column(sh, plan, "$.price").to_pylist() == \
            [None, 10]
        # residual still has the exact value
        vs = column_to_variants(unshred_column(sh))
        assert vs[0].get("$.price") == 9.99

    def test_bool_not_coerced_to_int(self):
        col = column_from_objects([{"x": True}])
        plan = ShreddingPlan({"$.x": pa.int64()})
        sh = shred_column(col, plan)
        assert typed_path_column(sh, plan, "$.x").to_pylist() == [None]
