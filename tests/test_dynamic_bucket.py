"""Dynamic bucket mode + system tables.

reference: index/HashBucketAssigner.java, PartitionIndex.java,
table/system/SystemTableLoader.java.
"""

import os

import numpy as np
import pytest

from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, VarCharType


def _make(tmp_warehouse, opts=None):
    options = {"write-only": "true",
               "dynamic-bucket.target-row-num": "100"}
    options.update(opts or {})
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options(options)            # no "bucket" -> dynamic (-1)
              .build())
    return FileStoreTable.create(os.path.join(tmp_warehouse, "t"), schema)


def _commit(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    sid = wb.new_commit().commit(w.prepare_commit())
    w.close()
    return sid


def test_dynamic_bucket_grows_with_data(tmp_warehouse):
    table = _make(tmp_warehouse)
    _commit(table, [{"id": i, "v": float(i)} for i in range(250)])
    splits = table.new_read_builder().new_scan().plan().splits
    buckets = {s.bucket for s in splits}
    assert len(buckets) == 3               # 250 keys / 100 per bucket
    assert table.to_arrow().num_rows == 250
    # hash index persisted
    snap = table.snapshot_manager.latest_snapshot()
    assert snap.index_manifest


def test_dynamic_bucket_stable_assignment_across_writers(tmp_warehouse):
    """An existing key must route to its original bucket from a fresh
    writer (index reloaded from disk) so upserts still merge."""
    table = _make(tmp_warehouse)
    _commit(table, [{"id": i, "v": 1.0} for i in range(150)])
    # fresh writer, upsert every key
    _commit(table, [{"id": i, "v": 2.0} for i in range(150)])
    out = table.to_arrow()
    assert out.num_rows == 150             # no duplicate keys
    assert set(out.column("v").to_pylist()) == {2.0}


def test_dynamic_bucket_upsert_and_compact(tmp_warehouse):
    table = _make(tmp_warehouse)
    _commit(table, [{"id": i, "v": float(i)} for i in range(120)])
    _commit(table, [{"id": 5, "v": 999.0}])
    assert table.compact(full=True) is not None
    rows = {r["id"]: r["v"] for r in table.to_arrow().to_pylist()}
    assert rows[5] == 999.0
    assert len(rows) == 120


def test_system_tables(tmp_warehouse):
    table = _make(tmp_warehouse, {"bucket": "1"})
    _commit(table, [{"id": 1, "v": 1.0}])
    _commit(table, [{"id": 2, "v": 2.0}])
    table.create_tag("t1", 1)

    snaps = table.system_table("snapshots")
    assert snaps.num_rows == 2
    assert snaps.column("commit_kind").to_pylist() == ["APPEND", "APPEND"]

    files = table.system_table("files")
    assert files.num_rows == 2
    assert all(p.endswith(".parquet")
               for p in files.column("file_name").to_pylist())

    tags = table.system_table("tags")
    assert tags.column("tag_name").to_pylist() == ["t1"]

    opts = table.system_table("options")
    assert "bucket" in opts.column("key").to_pylist()

    parts = table.system_table("partitions")
    assert parts.column("record_count").to_pylist() == [2]

    audit = table.system_table("audit_log")
    assert set(audit.column("rowkind").to_pylist()) == {"+I"}

    with pytest.raises(ValueError):
        table.system_table("nope")
