"""Behavior tests for round-4 wired options: spill tuning, tag
lifecycle, data-file layout, lookup cache, scan variants.

reference: paimon-api/.../CoreOptions.java families.
"""

import os

import numpy as np
import pyarrow as pa
import pytest

from paimon_tpu.options import CoreOptions
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, IntType, VarCharType


def pk_table(tmp_path, name="t", **opts):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", IntType())
              .primary_key("id")
              .options({"bucket": "1", **opts})
              .build())
    return FileStoreTable.create(str(tmp_path / name), schema)


def write_rows(table, ids, vs=None):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_arrow(pa.table({
        "id": pa.array(ids, pa.int64()),
        "v": pa.array(vs if vs is not None else [0] * len(ids),
                      pa.int32())}))
    sid = wb.new_commit().commit(w.prepare_commit())
    w.close()
    return sid


class TestSpillTuning:
    def _spilling_table(self, tmp_path, **extra):
        return pk_table(tmp_path, **{
            "write-buffer-spillable": "true",
            "write-only": "true",
            "sort-spill-buffer-size": "64 kb", **extra})

    def _write_wide(self, table, n=8000):
        wb = table.new_batch_write_builder()
        w = wb.new_write()
        rng = np.random.default_rng(0)
        for lo in range(0, n, 1000):
            ids = np.arange(lo, lo + 1000, dtype=np.int64)
            w.write_arrow(pa.table({
                "id": pa.array(ids),
                "v": pa.array(rng.integers(0, 99, 1000)
                              .astype(np.int32))}))
        wb.new_commit().commit(w.prepare_commit())
        w.close()

    def test_small_spill_buffer_still_correct(self, tmp_path):
        t = self._spilling_table(tmp_path)
        self._write_wide(t)
        out = t.to_arrow().sort_by("id")
        assert out.column("id").to_pylist() == list(range(8000))

    def test_max_file_handles_folds_runs(self, tmp_path):
        t = self._spilling_table(tmp_path,
                                 **{"local-sort.max-num-file-handles":
                                    "2"})
        self._write_wide(t)
        out = t.to_arrow().sort_by("id")
        assert out.column("id").to_pylist() == list(range(8000))

    def test_disk_budget_forces_flush(self, tmp_path):
        t = self._spilling_table(
            tmp_path, **{"write-buffer-spill.max-disk-size": "1 kb"})
        self._write_wide(t)
        out = t.to_arrow().sort_by("id")
        assert out.column("id").to_pylist() == list(range(8000))

    def test_spill_compression_none_roundtrips(self, tmp_path):
        t = self._spilling_table(tmp_path,
                                 **{"spill-compression": "none"})
        self._write_wide(t, 3000)
        assert t.to_arrow().num_rows == 3000

    def test_spill_zstd_level_applies(self, tmp_path):
        t = self._spilling_table(tmp_path,
                                 **{"spill-compression.zstd-level": "9"})
        self._write_wide(t, 3000)
        assert t.to_arrow().num_rows == 3000


class TestTagLifecycle:
    def _write_at(self, table, ts_ms):
        """Commit with a forced snapshot time (monkeypatched clock)."""
        import paimon_tpu.snapshot.snapshot as snap_mod
        wb = table.new_batch_write_builder()
        w = wb.new_write()
        w.write_arrow(pa.table({"id": pa.array([ts_ms], pa.int64()),
                                "v": pa.array([1], pa.int32())}))
        import unittest.mock as mock
        with mock.patch("time.time", return_value=ts_ms / 1000):
            wb.new_commit().commit(w.prepare_commit())
        w.close()

    DAY = 86_400_000

    def test_automatic_completion_backfills(self, tmp_path):
        t = pk_table(tmp_path, **{
            "tag.automatic-creation": "process-time",
            "tag.automatic-completion": "true"})
        self._write_at(t, 10 * self.DAY + 3600_000)
        names = sorted(t.tag_manager.tags())
        # every elapsed daily period is backfilled, not just the newest
        assert len(names) >= 5
        assert "1970-01-10" in names

    def test_num_retained_max_sweeps_oldest(self, tmp_path):
        t = pk_table(tmp_path, **{
            "tag.automatic-creation": "process-time",
            "tag.automatic-completion": "true",
            "tag.num-retained-max": "3"})
        self._write_at(t, 10 * self.DAY + 3600_000)
        auto = sorted(t.tag_manager.tags())
        assert len(auto) == 3
        assert auto[-1] == "1970-01-10"

    def test_success_file_written(self, tmp_path):
        t = pk_table(tmp_path, **{
            "tag.automatic-creation": "process-time",
            "tag.create-success-file": "true"})
        self._write_at(t, 3 * self.DAY + 3600_000)
        names = sorted(t.tag_manager.tags())
        assert names
        marker = f"{t.tag_manager.tag_dir}/{names[-1]}._SUCCESS"
        assert t.file_io.exists(marker)

    def test_period_formatter_without_dashes(self, tmp_path):
        t = pk_table(tmp_path, **{
            "tag.automatic-creation": "process-time",
            "tag.period-formatter": "without_dashes"})
        self._write_at(t, 3 * self.DAY + 3600_000)
        names = sorted(t.tag_manager.tags())
        assert names and names[-1] == "19700103"

    def test_time_retained_tags_expire(self, tmp_path):
        t = pk_table(tmp_path)
        write_rows(t, [1])
        snap = t.latest_snapshot()
        t.tag_manager.create_tag(snap, "short", time_retained_ms=1)
        t.tag_manager.create_tag(snap, "forever")
        import time
        time.sleep(0.01)
        removed = t.tag_manager.expire_tags()
        assert removed == ["short"]
        assert "forever" in t.tag_manager.tags()

    def test_default_time_retained_on_auto_tags(self, tmp_path):
        t = pk_table(tmp_path, **{
            "tag.automatic-creation": "process-time",
            "tag.default-time-retained": "1 ms",
            "tag.time-expire-enabled": "true"})
        self._write_at(t, 3 * self.DAY + 3600_000)
        # the auto tag carried a 1ms retention; the next commit's
        # expire sweep (time-expire-enabled) removes it
        import time
        time.sleep(0.01)
        self._write_at(t, 3 * self.DAY + 7200_000)
        assert "1970-01-03" not in t.tag_manager.tags()


class TestDataFileLayout:
    def test_data_file_prefix(self, tmp_path):
        t = pk_table(tmp_path, **{"data-file.prefix": "part-"})
        write_rows(t, [1, 2, 3])
        files = [f.file_name for s in
                 t.new_read_builder().new_scan().plan().splits
                 for f in s.data_files]
        assert files and all(f.startswith("part-") for f in files)

    def test_data_file_path_directory(self, tmp_path):
        t = pk_table(tmp_path, **{"data-file.path-directory": "data"})
        write_rows(t, [1, 2, 3])
        base = str(tmp_path / "t" / "data")
        assert os.path.isdir(base)
        assert any("bucket-" in d for d in os.listdir(base))
        assert t.to_arrow().num_rows == 3

    def test_target_file_row_num_rolls(self, tmp_path):
        t = pk_table(tmp_path, **{"target-file-row-num": "100",
                                  "write-only": "true"})
        write_rows(t, list(range(350)))
        files = [f for s in
                 t.new_read_builder().new_scan().plan().splits
                 for f in s.data_files]
        assert len(files) == 4           # 100+100+100+50
        assert t.to_arrow().num_rows == 350

    def test_file_block_size_makes_small_row_groups(self, tmp_path):
        import pyarrow.parquet as pq
        t = pk_table(tmp_path, **{"file.block-size": "4 kb"})
        write_rows(t, list(range(5000)),
                   vs=list(range(5000)))
        split = t.new_read_builder().new_scan().plan().splits[0]
        f = split.data_files[0]
        path = (f"{tmp_path}/t/bucket-1/{f.file_name}"
                if os.path.exists(f"{tmp_path}/t/bucket-1/{f.file_name}")
                else f"{tmp_path}/t/bucket-0/{f.file_name}")
        pf = pq.ParquetFile(path)
        assert pf.num_row_groups > 1

    def test_compression_per_level(self, tmp_path):
        import pyarrow.parquet as pq
        t = pk_table(tmp_path, **{"file.compression.per.level": "0:lz4"})
        write_rows(t, list(range(100)))
        split = t.new_read_builder().new_scan().plan().splits[0]
        f = split.data_files[0]
        assert f.level == 0
        for b in ("bucket-0", "bucket-1"):
            p = f"{tmp_path}/t/{b}/{f.file_name}"
            if os.path.exists(p):
                meta = pq.ParquetFile(p).metadata
                assert meta.row_group(0).column(0).compression \
                    .lower() == "lz4"
                return
        raise AssertionError("data file not found")

    def test_stats_mode_none_per_level(self, tmp_path):
        t = pk_table(tmp_path, **{"metadata.stats-mode.per.level":
                                  "0:none"})
        write_rows(t, [5, 6, 7], vs=[50, 60, 70])
        f = [f for s in t.new_read_builder().new_scan().plan().splits
             for f in s.data_files][0]
        # value stats nulled; reads still work
        from paimon_tpu.data.binary_row import BinaryRowCodec
        assert t.to_arrow().num_rows == 3

    def test_stats_keep_first_n(self, tmp_path):
        t = pk_table(tmp_path, **{"metadata.stats-keep-first-n-columns":
                                  "1"})
        write_rows(t, [5, 6], vs=[50, 60])
        assert t.to_arrow().num_rows == 2
