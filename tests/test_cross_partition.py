"""Cross-partition upsert (pk does not include the partition key).

reference: crosspartition/GlobalIndexAssigner.java semantics: a key
moving to a new partition retracts the old row first.
"""

import os

import pytest

from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, VarCharType


def _make(tmp_warehouse):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("dt", VarCharType(nullable=False))
              .column("v", DoubleType())
              .partition_keys("dt")
              .primary_key("id")                 # pk excludes dt
              .options({"dynamic-bucket.target-row-num": "100",
                        "write-only": "true"})
              .build())
    return FileStoreTable.create(os.path.join(tmp_warehouse, "t"), schema)


def _commit(table, rows, kinds=None):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows, row_kinds=kinds)
    wb.new_commit().commit(w.prepare_commit())
    w.close()


def test_partition_move_retracts_old_row(tmp_warehouse):
    table = _make(tmp_warehouse)
    _commit(table, [{"id": 1, "dt": "d1", "v": 1.0},
                    {"id": 2, "dt": "d1", "v": 2.0}])
    # key 1 moves to partition d2: d1's copy must disappear
    _commit(table, [{"id": 1, "dt": "d2", "v": 10.0}])
    rows = sorted(table.to_arrow().to_pylist(), key=lambda r: r["id"])
    assert rows == [{"id": 1, "dt": "d2", "v": 10.0},
                    {"id": 2, "dt": "d1", "v": 2.0}]


def test_partition_move_across_writers(tmp_warehouse):
    """A fresh writer bootstraps the index from the table."""
    table = _make(tmp_warehouse)
    _commit(table, [{"id": 7, "dt": "d1", "v": 1.0}])
    table2 = FileStoreTable.load(table.path)
    _commit(table2, [{"id": 7, "dt": "d3", "v": 3.0}])
    rows = table.to_arrow().to_pylist()
    assert rows == [{"id": 7, "dt": "d3", "v": 3.0}]


def test_same_partition_upsert_is_plain(tmp_warehouse):
    table = _make(tmp_warehouse)
    _commit(table, [{"id": 1, "dt": "d1", "v": 1.0}])
    _commit(table, [{"id": 1, "dt": "d1", "v": 2.0}])
    assert table.to_arrow().to_pylist() == \
        [{"id": 1, "dt": "d1", "v": 2.0}]


def test_delete_routes_to_current_partition(tmp_warehouse):
    from paimon_tpu.types import RowKind

    table = _make(tmp_warehouse)
    _commit(table, [{"id": 1, "dt": "d1", "v": 1.0}])
    # delete arrives tagged with a DIFFERENT partition value; it must
    # still remove the row where the key actually lives
    _commit(table, [{"id": 1, "dt": "d9", "v": 0.0}],
            kinds=[RowKind.DELETE])
    assert table.to_arrow().num_rows == 0


def test_within_batch_partition_move(tmp_warehouse):
    table = _make(tmp_warehouse)
    _commit(table, [{"id": 5, "dt": "d1", "v": 1.0},
                    {"id": 5, "dt": "d2", "v": 2.0}])   # same batch move
    rows = table.to_arrow().to_pylist()
    assert rows == [{"id": 5, "dt": "d2", "v": 2.0}]


def test_cdc_retract_then_insert_same_batch(tmp_warehouse):
    """CDC update shape in ONE batch: [-U old-partition, +U new-partition]
    must delete the persisted old row (retracts are never dropped)."""
    from paimon_tpu.types import RowKind

    table = _make(tmp_warehouse)
    _commit(table, [{"id": 1, "dt": "d1", "v": 1.0}])
    _commit(table, [{"id": 1, "dt": "d1", "v": 1.0},
                    {"id": 1, "dt": "d2", "v": 2.0}],
            kinds=[RowKind.UPDATE_BEFORE, RowKind.UPDATE_AFTER])
    rows = table.to_arrow().to_pylist()
    assert rows == [{"id": 1, "dt": "d2", "v": 2.0}]


def test_persistent_index_shared_across_writers(tmp_warehouse):
    """The bootstrapped index spills to an SST next to the table; a
    second writer at the same snapshot loads it instead of rescanning
    (reference GlobalIndexAssigner persists via RocksDB)."""
    table = _make(tmp_warehouse)
    _commit(table, [{"id": 1, "dt": "d1", "v": 1.0},
                    {"id": 2, "dt": "d1", "v": 2.0}])
    # first writer bootstraps and spills
    _commit(table, [{"id": 1, "dt": "d2", "v": 10.0}])
    idx_dir = os.path.join(table.path, "index", "cross-partition")
    assert any(f.endswith(".sst") for f in os.listdir(idx_dir))
    # second writer (fresh object) moves the key again using the index
    t2 = FileStoreTable.load(table.path)
    _commit(t2, [{"id": 1, "dt": "d3", "v": 100.0}])
    rows = sorted(t2.to_arrow().to_pylist(), key=lambda r: r["id"])
    assert [(r["dt"], r["id"]) for r in rows] == [("d3", 1), ("d1", 2)]
