"""Smoke test: the micro-benchmark harness runs and emits valid JSON
(reference paimon-micro-benchmarks is JUnit-driven; this suite is
driven the same way so CI catches API drift)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_micro_bench_smoke():
    env = dict(os.environ, MICRO_ROWS="20000", MICRO_RUNS="1",
               JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, "-m", "benchmarks.micro", "read_parquet",
         "merge", "bitmap"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(line) for line in proc.stdout.splitlines()]
    names = {d["benchmark"] for d in lines}
    assert {"table_read_parquet", "merge_dedup_10runs",
            "bitmap_index_build"} <= names
    assert all(d["value"] > 0 for d in lines)


def test_write_bench_smoke():
    """benchmarks/write_bench emits the serial + pipelined ingest lines
    and asserts row-identity itself (a diverged run exits nonzero)."""
    env = dict(os.environ, WRITE_ROWS="20000", WRITE_CHUNKS="4",
               MICRO_RUNS="1", JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, "-m", "benchmarks.write_bench", "ingest"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(line) for line in proc.stdout.splitlines()]
    by_name = {d["benchmark"]: d for d in lines}
    assert {"write_ingest_serial", "write_ingest_pipelined"} \
        <= set(by_name)
    assert by_name["write_ingest_pipelined"]["identical"] is True
    assert all(d["value"] > 0 for d in lines)

