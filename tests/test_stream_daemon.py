"""Streaming lakehouse daemon (service/stream_daemon.py): checkpointed
exactly-once ingest, supervised loop restarts, backpressure coupling,
graceful degradation, drain, changelog serving on the query service,
and the fault-injected soak (tier-1 smoke + `slow` full variant).
"""

import json
import os
import threading
import time

import pytest

from paimon_tpu.cdc.source import FileCdcSource, MemoryCdcSource
from paimon_tpu.core.read import ROW_KIND_COL
from paimon_tpu.metrics import (
    STREAM_CHECKPOINTS, STREAM_COMPACTIONS, STREAM_COMPACTIONS_PAUSED,
    global_registry,
)
from paimon_tpu.schema import Schema
from paimon_tpu.service.stream_daemon import (
    PROP_INGEST_TS, PROP_OFFSET, StreamDaemon, checkpoint_once,
    recover_checkpoint,
)
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType
from tests.soak_harness import run_soak

FAST = {
    "bucket": "2",
    "stream.checkpoint.interval": "60",
    "stream.compaction.interval": "120",
    "num-sorted-run.compaction-trigger": "3",
    "stream.serve.poll-interval": "15",
    "stream.ingest.poll-interval": "10",
    "stream.restart.backoff": "10",
    "stream.restart.backoff.cap": "60",
}


def _make(tmp_path, opts=None, name="t"):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", BigIntType())
              .primary_key("id")
              .options({**FAST, **(opts or {})})
              .build())
    return FileStoreTable.create(str(tmp_path / name), schema)


def _insert(i, key=None):
    return {"op": "c", "after": {"id": i if key is None else key,
                                 "v": i}}


def _wait(cond, timeout=15.0, step=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(step)
    return False


def _consume_state(daemon, state, timeout=0.05):
    while True:
        rows = daemon.poll_changelog(timeout=timeout)
        if not rows:
            return
        for r in rows:
            if r[ROW_KIND_COL] in (0, 2):
                state[r["id"]] = r["v"]
            elif r[ROW_KIND_COL] == 3:
                state.pop(r["id"], None)


# -- checkpoint / recovery ----------------------------------------------------

def test_recover_checkpoint_empty(tmp_path):
    table = _make(tmp_path)
    assert recover_checkpoint(table, "stream-daemon") == (-1, 0)


def test_checkpoint_once_commits_offset_atomically(tmp_path):
    table = _make(tmp_path)
    src = MemoryCdcSource([_insert(i) for i in range(5)])
    sid = checkpoint_once(table, src)
    assert sid is not None
    snap = FileStoreTable.load(table.path).latest_snapshot()
    assert snap.properties[PROP_OFFSET] == "4"
    assert int(snap.properties[PROP_INGEST_TS]) > 0
    assert recover_checkpoint(table, "stream-daemon") == (4, 1)
    # nothing new -> no checkpoint, offset unchanged
    assert checkpoint_once(table, src) is None
    src.append(_insert(5))
    assert checkpoint_once(table, src) is not None
    assert recover_checkpoint(table, "stream-daemon") == (5, 2)


def test_daemon_ingests_serves_and_drains(tmp_path):
    table = _make(tmp_path)
    src = MemoryCdcSource()
    daemon = StreamDaemon(table, src).start()
    expected = {}
    for i in range(120):
        expected[i % 11] = i
        src.append(_insert(i, key=i % 11))
    state = {}
    assert _wait(lambda: daemon.status()["offset_committed"] == 119)
    status = daemon.stop()               # drain
    _consume_state(daemon, state)
    assert status["offset_committed"] == 119
    assert not any(l["failed"] for l in status["loops"].values())
    assert state == expected             # changelog materializes exactly
    t2 = FileStoreTable.load(table.path)
    assert {r["id"]: r["v"] for r in t2.to_arrow().to_pylist()} \
        == expected
    assert t2.fsck().ok


def test_kill_restart_replays_exactly_once(tmp_path):
    """Kill without drain mid-stream; a second daemon must converge to
    exactly one copy of every event, with offsets strictly increasing
    and identifiers never reused."""
    table = _make(tmp_path)
    src = MemoryCdcSource()
    for i in range(60):
        src.append(_insert(i, key=i % 7))
    d1 = StreamDaemon(table, src).start()
    _wait(lambda: d1.status()["offset_committed"] >= 0)
    d1.kill()                            # no final checkpoint
    committed_at_kill = d1.status()["offset_committed"]
    for i in range(60, 90):
        src.append(_insert(i, key=i % 7))
    d2 = StreamDaemon(table, src).start()
    assert _wait(lambda: d2.status()["offset_committed"] == 89)
    d2.stop()
    final = FileStoreTable.load(table.path)
    assert {r["id"]: r["v"] for r in final.to_arrow().to_pylist()} \
        == {i % 7: i for i in range(90)}
    offs, idents = [], []
    for s in final.snapshot_manager.snapshots():
        if s.commit_user == "stream-daemon" and s.properties:
            offs.append(int(s.properties[PROP_OFFSET]))
            idents.append(s.commit_identifier)
    assert offs == sorted(set(offs)) and offs[-1] == 89
    assert idents == sorted(set(idents))
    assert committed_at_kill in offs
    assert final.fsck().ok


# -- backpressure / degradation ----------------------------------------------

def test_serve_buffer_is_bounded_backpressure(tmp_path):
    """An unconsumed changelog buffer must stall the serving loop at
    its bound, never grow (no unbounded queueing)."""
    cap = 64
    table = _make(tmp_path,
                  {"stream.serve.buffer.rows": str(cap)})
    src = MemoryCdcSource()
    daemon = StreamDaemon(table, src, compact=False).start()
    for i in range(1000):
        src.append(_insert(i, key=i))    # 1000 distinct keys
    _wait(lambda: daemon.status()["offset_committed"] == 999)
    # serving stalls at the cap: admission is chunked, so even a
    # single large batch cannot overshoot it
    time.sleep(0.5)
    assert daemon.status()["buffered_rows"] <= cap
    seen = {}
    deadline = time.monotonic() + 30.0
    while len(seen) < 1000 and time.monotonic() < deadline:
        _consume_state(daemon, seen, timeout=0.3)
    daemon.stop()
    _consume_state(daemon, seen)
    assert len(seen) == 1000             # everything arrived, in order


def test_compaction_pauses_under_ingest_pressure(tmp_path):
    """Graceful degradation: with the pause threshold forced on, the
    compaction loop skips rounds instead of competing with ingest."""
    g = global_registry().stream_metrics()
    paused0 = g.counter(STREAM_COMPACTIONS_PAUSED).count
    table = _make(tmp_path,
                  {"stream.compaction.pause-backlog": "-1"})
    src = MemoryCdcSource()
    daemon = StreamDaemon(table, src, serve=False).start()
    for i in range(100):
        src.append(_insert(i, key=i % 5))
    _wait(lambda: daemon.status()["offset_committed"] == 99)
    _wait(lambda: g.counter(STREAM_COMPACTIONS_PAUSED).count > paused0,
          timeout=5.0)
    daemon.stop()
    assert g.counter(STREAM_COMPACTIONS_PAUSED).count > paused0
    # no COMPACT snapshot was committed while paused
    from paimon_tpu.snapshot import CommitKind
    kinds = {s.commit_kind for s in
             FileStoreTable.load(table.path)
             .snapshot_manager.snapshots()}
    assert CommitKind.COMPACT not in kinds


def test_compaction_triggers_on_sorted_runs(tmp_path):
    g = global_registry().stream_metrics()
    c0 = g.counter(STREAM_COMPACTIONS).count
    table = _make(tmp_path)
    src = MemoryCdcSource()
    daemon = StreamDaemon(table, src, serve=False).start()
    # >= 4 checkpoints -> >= 4 level-0 files per bucket -> over trigger
    for batch in range(6):
        for i in range(20):
            src.append(_insert(batch * 20 + i, key=i))
        time.sleep(0.1)
    _wait(lambda: daemon.status()["offset_committed"] == 119)
    _wait(lambda: g.counter(STREAM_COMPACTIONS).count > c0,
          timeout=10.0)
    daemon.stop()
    assert g.counter(STREAM_COMPACTIONS).count > c0
    final = FileStoreTable.load(table.path)
    from paimon_tpu.snapshot import CommitKind
    assert any(s.commit_kind == CommitKind.COMPACT
               for s in final.snapshot_manager.snapshots())
    assert {r["id"]: r["v"] for r in final.to_arrow().to_pylist()} \
        == {i: 100 + i for i in range(20)}
    assert final.fsck().ok


def test_serving_stays_available_when_ingest_is_down(tmp_path):
    """Read availability: the serving loop keeps answering from
    committed snapshots while ingest crash-loops on a broken source."""

    class BrokenSource:
        def __init__(self, inner):
            self.inner = inner
            self.broken = False

        def poll(self, after, n):
            if self.broken:
                raise IOError("source connection lost")
            return self.inner.poll(after, n)

        def backlog(self, after):
            return 0 if self.broken else self.inner.backlog(after)

    inner = MemoryCdcSource()
    src = BrokenSource(inner)
    table = _make(tmp_path)
    daemon = StreamDaemon(table, src, compact=False).start()
    for i in range(30):
        inner.append(_insert(i, key=i % 5))
    _wait(lambda: daemon.status()["offset_committed"] == 29)
    src.broken = True                    # ingest starts crash-looping
    _wait(lambda: daemon.status()["loops"]["ingest"]["restarts"] > 0,
          timeout=10.0)
    state = {}
    _consume_state(daemon, state, timeout=1.0)
    assert state == {i % 5: i for i in range(30)}   # still served
    src.broken = False                   # ingest recovers by itself
    inner.append(_insert(30, key=0))
    assert _wait(lambda: daemon.status()["offset_committed"] == 30)
    daemon.stop()
    assert daemon.status()["loops"]["ingest"]["restarts"] >= 1


# -- sources ------------------------------------------------------------------

def test_file_cdc_source_tails_jsonl(tmp_path):
    path = str(tmp_path / "events.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps(_insert(0)) + "\n")
        f.write(json.dumps(_insert(1)) + "\n")
    src = FileCdcSource(path)
    assert [o for o, _ in src.poll(-1, 10)] == [0, 1]
    assert src.poll(1, 10) == []
    with open(path, "a") as f:
        f.write(json.dumps(_insert(2)) + "\n")
        f.write('{"op": "c", "after"')        # torn line: not yet an event
    assert [o for o, _ in src.poll(1, 10)] == [2]
    with open(path, "a") as f:
        f.write(': {"id": 9, "v": 9}}\n')     # completes the torn line
    polled = src.poll(2, 10)
    assert [o for o, _ in polled] == [3]
    assert polled[0][1]["after"]["id"] == 9
    # replay: same offsets return the same events
    assert src.poll(-1, 10)[0][1] == _insert(0)
    assert src.backlog(0) == 3
    # checkpointed eviction bounds memory; later offsets still replay
    src.commit_through(1)
    assert len(src._events) == 2
    assert [o for o, _ in src.poll(1, 10)] == [2, 3]
    assert src.poll(-1, 10)[0][0] == 2     # evicted range skipped
    assert src.latest_offset() == 3
    assert src.backlog(1) == 2


# -- query service ------------------------------------------------------------

def test_query_service_changelog_endpoint(tmp_path):
    from paimon_tpu.service.query_service import (
        KvQueryClient, KvQueryServer,
    )
    table = _make(tmp_path)
    src = MemoryCdcSource([_insert(i, key=i % 3) for i in range(10)])
    checkpoint_once(table, src)
    server = KvQueryServer(FileStoreTable.load(table.path)).start()
    try:
        client = KvQueryClient(address=server.address)
        out = client.changelog(consumer="c1")
        assert not out["caught_up"]
        state = {r["id"]: r["v"] for r in out["rows"]
                 if r[ROW_KIND_COL] in (0, 2)}
        assert state == {0: 9, 1: 7, 2: 8}
        # caught up until the next checkpoint commits
        assert client.changelog(consumer="c1")["caught_up"]
        src.append(_insert(10, key=0))
        checkpoint_once(table, src)
        out = client.changelog(consumer="c1")
        assert [r["id"] for r in out["rows"]] == [0]
        assert out["rows"][0]["v"] == 10
        # an independent consumer starts from its own full scan
        out2 = client.changelog(consumer="c2")
        assert {r["id"]: r["v"] for r in out2["rows"]} \
            == {0: 10, 1: 7, 2: 8}
        # bounded responses: a large snapshot streams out in chunks
        first = client.changelog(consumer="c3", max_rows=2)
        assert len(first["rows"]) == 2 and first["more"]
        rest = client.changelog(consumer="c3", max_rows=10)
        assert len(rest["rows"]) == 1 and not rest["more"]
    finally:
        server.stop()


def test_drain_failure_is_surfaced(tmp_path):
    """A final checkpoint that fails during drain must be visible:
    failed flag + last_error, offset_pending > offset_committed —
    never a silently 'clean' exit."""
    from paimon_tpu.table.table import FileStoreTable as FST
    from tests.failing_fileio import FailingFileIO

    base = _make(tmp_path, {"stream.checkpoint.interval": "60000"})
    fio = FailingFileIO(base.file_io, "drain-fail")
    table = FST(fio, base.path, base.schema_manager.latest())
    src = MemoryCdcSource([_insert(i, key=i % 3) for i in range(10)])
    daemon = StreamDaemon(table, src, compact=False,
                          serve=False).start()
    assert _wait(lambda: daemon.status()["offset_pending"] == 9)
    FailingFileIO.reset("drain-fail", 0)      # everything fails now
    try:
        status = daemon.stop(drain=True, timeout=10.0)
    finally:
        FailingFileIO.disarm("drain-fail")
    assert status["loops"]["ingest"]["failed"]
    assert status["loops"]["ingest"]["last_error"]
    assert status["offset_committed"] < status["offset_pending"]
    # recovery on a healed store converges
    d2 = StreamDaemon(
        table, src, compact=False, serve=False,
        dynamic_options={"stream.checkpoint.interval": "50"}).start()
    assert _wait(lambda: d2.status()["offset_committed"] == 9)
    d2.stop()
    assert {r["id"]: r["v"]
            for r in FST.load(base.path).to_arrow().to_pylist()} \
        == {i % 3: i for i in range(10)}


# -- CLI ----------------------------------------------------------------------

def test_cli_stream_verb(tmp_path, capsys):
    from paimon_tpu.cli import main
    wh = str(tmp_path / "wh")
    assert main(["-w", wh, "db", "create", "d1"]) == 0
    assert main(["-w", wh, "table", "create", "d1.t",
                 "--column", "id:BIGINT NOT NULL",
                 "--column", "v:BIGINT",
                 "--primary-key", "id", "--option", "bucket=1"]) == 0
    events = str(tmp_path / "events.jsonl")
    with open(events, "w") as f:
        for i in range(25):
            f.write(json.dumps(_insert(i, key=i % 4)) + "\n")
    capsys.readouterr()
    rc = main(["-w", wh, "table", "stream", "d1.t",
               "--source", events, "--duration", "1.5",
               "--option", "stream.checkpoint.interval=50",
               "--option", "stream.ingest.poll-interval=10"])
    out = capsys.readouterr().out
    assert rc == 0
    status = json.loads(out)
    assert status["offset_committed"] == 24
    rows = FileStoreTable.load(os.path.join(wh, "d1.db", "t")) \
        .to_arrow().to_pylist()
    assert {r["id"]: r["v"] for r in rows} == {i % 4: i
                                               for i in range(25)}


def test_sigterm_drains(tmp_path):
    """SIGTERM -> clean drain: final checkpoint committed, loops
    joined (the daemon's signal contract)."""
    import signal

    table = _make(tmp_path)
    src = MemoryCdcSource([_insert(i, key=i % 3) for i in range(12)])
    daemon = StreamDaemon(table, src, compact=False,
                          serve=False).start()
    daemon.install_signal_handlers()

    def fire():
        time.sleep(0.4)
        os.kill(os.getpid(), signal.SIGTERM)

    t = threading.Thread(target=fire, daemon=True)
    t.start()
    status = daemon.run_forever(duration_s=20.0)
    t.join()
    assert status["offset_committed"] == 11
    assert not any(l["alive"] for l in status["loops"].values())
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    signal.signal(signal.SIGINT, signal.default_int_handler)


# -- the soak -----------------------------------------------------------------

def test_soak_smoke(tmp_path):
    """Tier-1 smoke of the fault-injected soak: short deterministic
    schedule — 3 kill/restart cycles mid-checkpoint, 3 transient 503
    storms (bounded fail_times; small fail_after lands on two-phase
    uploads too) — asserting zero lost/duplicated CDC events, strictly
    increasing committed offsets, restart convergence, fsck-clean and
    measured end-to-end freshness."""
    report = run_soak(str(tmp_path), duration_s=5.0, seed=7)
    assert report["kill_restart_cycles"] == 3
    assert report["storms"] == 3
    assert report["daemon_incarnations"] == 4
    assert report["fsck_ok"]
    assert report["checkpoints"] >= 5
    assert report["freshness_samples"] > 0
    assert report["freshness_p95_ms"] < 60_000
    print("SOAK_SMOKE", json.dumps(report))


@pytest.mark.slow
def test_soak_full(tmp_path):
    """The full soak (>= 60 s wall clock): mesh compaction on (the
    retry/fallback ladder is live), 4 kill/restart cycles, 5 storms."""
    report = run_soak(str(tmp_path), duration_s=60.0, seed=11,
                      kills=4, storms=5, mesh=True)
    assert report["kill_restart_cycles"] == 4
    assert report["daemon_incarnations"] == 5
    assert report["fsck_ok"]
    assert report["compactions"] >= 1
    assert report["freshness_samples"] > 0
    print("SOAK_FULL", json.dumps(report))
