"""End-to-end table tests: write -> commit -> merge-on-read scan."""

import numpy as np
import pyarrow as pa
import pytest

from paimon_tpu import predicate as P
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import (
    BigIntType, DoubleType, IntType, RowKind, VarCharType,
)


def pk_schema(**options):
    return (Schema.builder()
            .column("id", BigIntType(False))
            .column("name", VarCharType.string_type())
            .column("score", DoubleType())
            .primary_key("id")
            .options({"bucket": "2", **options})
            .build())


def write_rows(table, rows, kinds=None):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows, kinds)
    msgs = w.prepare_commit()
    c = wb.new_commit()
    sid = c.commit(msgs)
    w.close()
    return sid


def read_sorted(table, **kw):
    t = table.to_arrow(**kw)
    return t.sort_by("id").to_pylist()


def test_create_write_read(tmp_path):
    table = FileStoreTable.create(str(tmp_path / "t"), pk_schema())
    sid = write_rows(table, [
        {"id": 1, "name": "a", "score": 1.0},
        {"id": 2, "name": "b", "score": 2.0},
        {"id": 3, "name": "c", "score": 3.0},
    ])
    assert sid == 1
    out = read_sorted(table)
    assert out == [
        {"id": 1, "name": "a", "score": 1.0},
        {"id": 2, "name": "b", "score": 2.0},
        {"id": 3, "name": "c", "score": 3.0},
    ]


def test_upsert_across_commits(tmp_path):
    table = FileStoreTable.create(str(tmp_path / "t"), pk_schema())
    write_rows(table, [{"id": 1, "name": "a", "score": 1.0},
                       {"id": 2, "name": "b", "score": 2.0}])
    write_rows(table, [{"id": 2, "name": "b2", "score": 20.0},
                       {"id": 3, "name": "c", "score": 3.0}])
    out = read_sorted(table)
    assert out == [
        {"id": 1, "name": "a", "score": 1.0},
        {"id": 2, "name": "b2", "score": 20.0},
        {"id": 3, "name": "c", "score": 3.0},
    ]
    assert table.latest_snapshot().id == 2


def test_delete_row(tmp_path):
    table = FileStoreTable.create(str(tmp_path / "t"), pk_schema())
    write_rows(table, [{"id": 1, "name": "a", "score": 1.0},
                       {"id": 2, "name": "b", "score": 2.0}])
    write_rows(table, [{"id": 1, "name": "a", "score": 1.0}],
               kinds=[RowKind.DELETE])
    out = read_sorted(table)
    assert [r["id"] for r in out] == [2]


def test_dedup_within_batch(tmp_path):
    table = FileStoreTable.create(str(tmp_path / "t"), pk_schema())
    write_rows(table, [
        {"id": 1, "name": "v1", "score": 1.0},
        {"id": 1, "name": "v2", "score": 2.0},
        {"id": 1, "name": "v3", "score": 3.0},
    ])
    out = read_sorted(table)
    assert out == [{"id": 1, "name": "v3", "score": 3.0}]


def test_projection_and_filter(tmp_path):
    table = FileStoreTable.create(str(tmp_path / "t"), pk_schema())
    write_rows(table, [{"id": i, "name": f"n{i}", "score": float(i)}
                       for i in range(10)])
    out = table.to_arrow(projection=["id", "score"],
                         predicate=P.greater_than("score", 6.5))
    assert out.column_names == ["id", "score"]
    assert sorted(out.column("id").to_pylist()) == [7, 8, 9]


def test_partitioned_table(tmp_path):
    schema = (Schema.builder()
              .column("dt", VarCharType(10, False))
              .column("id", BigIntType(False))
              .column("v", IntType())
              .partition_keys("dt")
              .primary_key("dt", "id")
              .options({"bucket": "2"})
              .build())
    table = FileStoreTable.create(str(tmp_path / "t"), schema)
    write_rows(table, [
        {"dt": "d1", "id": 1, "v": 1},
        {"dt": "d1", "id": 2, "v": 2},
        {"dt": "d2", "id": 1, "v": 10},
    ])
    # partition layout on disk
    assert (tmp_path / "t" / "dt=d1").exists()
    assert (tmp_path / "t" / "dt=d2").exists()
    rb = table.new_read_builder().with_partition_filter({"dt": "d2"})
    t = rb.new_read().to_arrow(rb.new_scan().plan().splits)
    assert t.num_rows == 1
    assert t.column("v").to_pylist() == [10]
    # full read
    assert table.to_arrow().num_rows == 3


def test_overwrite(tmp_path):
    table = FileStoreTable.create(str(tmp_path / "t"), pk_schema())
    write_rows(table, [{"id": 1, "name": "a", "score": 1.0}])
    wb = table.new_batch_write_builder().with_overwrite()
    w = wb.new_write()
    w.write_dicts([{"id": 9, "name": "z", "score": 9.0}])
    wb.new_commit().commit(w.prepare_commit())
    out = read_sorted(table)
    assert [r["id"] for r in out] == [9]
    assert table.latest_snapshot().commit_kind == "OVERWRITE"


def test_time_travel_snapshot(tmp_path):
    table = FileStoreTable.create(str(tmp_path / "t"), pk_schema())
    write_rows(table, [{"id": 1, "name": "a", "score": 1.0}])
    write_rows(table, [{"id": 1, "name": "b", "score": 2.0}])
    rb = table.new_read_builder()
    plan1 = rb.new_scan().plan(snapshot_id=1)
    out1 = rb.new_read().to_arrow(plan1.splits)
    assert out1.column("name").to_pylist() == ["a"]
    # via tag
    table.create_tag("v1", snapshot_id=1)
    t2 = table.copy({"scan.tag-name": "v1"})
    assert t2.to_arrow().column("name").to_pylist() == ["a"]


def test_multi_bucket_distribution(tmp_path):
    table = FileStoreTable.create(str(tmp_path / "t"),
                                  pk_schema(bucket="4"))
    write_rows(table, [{"id": i, "name": str(i), "score": float(i)}
                       for i in range(100)])
    plan = table.new_read_builder().new_scan().plan()
    buckets = {s.bucket for s in plan.splits}
    assert len(buckets) > 1  # keys spread over buckets
    out = read_sorted(table)
    assert [r["id"] for r in out] == list(range(100))


def test_sequence_number_restored_across_writers(tmp_path):
    table = FileStoreTable.create(str(tmp_path / "t"), pk_schema())
    write_rows(table, [{"id": 1, "name": "first", "score": 1.0}])
    # second writer must see seq > first writer's
    write_rows(table, [{"id": 1, "name": "second", "score": 2.0}])
    write_rows(table, [{"id": 1, "name": "third", "score": 3.0}])
    out = read_sorted(table)
    assert out[0]["name"] == "third"


def test_stats_pruning_by_key(tmp_path):
    table = FileStoreTable.create(str(tmp_path / "t"),
                                  pk_schema(bucket="1"))
    write_rows(table, [{"id": i, "name": str(i), "score": float(i)}
                       for i in range(0, 100)])
    write_rows(table, [{"id": i, "name": str(i), "score": float(i)}
                       for i in range(1000, 1100)])
    rb = table.new_read_builder().with_filter(P.equal("id", 1050))
    plan = rb.new_scan().plan()
    # only the second file group should survive key-stats pruning
    assert sum(len(s.data_files) for s in plan.splits) == 1
    out = rb.new_read().to_arrow(plan.splits)
    assert out.column("id").to_pylist() == [1050]
