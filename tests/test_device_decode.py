"""Device decode plane: raw Parquet pages -> vectorized device ops.

Three layers, per ISSUE 12:

1. Fuzz/oracle suite — random column chunks across encodings (RLE
   dictionary, PLAIN), codecs, null densities and row-group/page
   shapes, asserted BYTE-IDENTICAL to the pyarrow decode of the same
   file (format/rawpage.py + ops/decode.py).
2. End-to-end: `read.device-decode` tables scan/compact identically to
   the pyarrow path per merge engine, and unsupported files fall back
   (counted) instead of erroring.
3. Lowering proof — the fused decode+merge program compiles to a
   jaxpr/HLO with NO host callback or host transfer inside, the
   acceptance ROADMAP item 1 names while real TPUs are unavailable.
"""

import os
import tempfile

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from paimon_tpu.format.rawpage import (
    DeviceDecodeUnsupported, read_parquet_device,
)
from paimon_tpu.fs.fileio import LocalFileIO


@pytest.fixture
def fio():
    return LocalFileIO()


def _roundtrip(tmp_path, fio, table, name, **write_kw):
    path = str(tmp_path / f"{name}.parquet")
    pq.write_table(table, path, **write_kw)
    oracle = pq.ParquetFile(path).read()
    got = read_parquet_device(fio, path)
    assert got.equals(oracle), f"{name}: device decode != pyarrow"
    return got


# ---------------------------------------------------------------------------
# 1. fuzz/oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("codec", ["none", "zstd", "snappy"])
def test_plain_fixed_width_oracle(tmp_path, fio, seed, codec):
    """PLAIN INT32/INT64/FLOAT/DOUBLE pages decode byte-identical."""
    rng = np.random.default_rng(seed)
    n = 20_000
    t = pa.table({
        "i64": pa.array(rng.integers(-1 << 60, 1 << 60, n), pa.int64()),
        "f64": pa.array(rng.standard_normal(n), pa.float64()),
        "i32": pa.array(rng.integers(-1 << 30, 1 << 30, n).astype(
            np.int32), pa.int32()),
        "f32": pa.array(rng.random(n).astype(np.float32), pa.float32()),
    })
    _roundtrip(tmp_path, fio, t, f"plain_{codec}_{seed}",
               compression=codec, use_dictionary=False)


@pytest.mark.parametrize("seed,cards", [(0, 7), (1, 100), (2, 1000)])
def test_dictionary_oracle(tmp_path, fio, seed, cards):
    """RLE_DICTIONARY index streams + PLAIN dictionary pages."""
    rng = np.random.default_rng(seed)
    n = 30_000
    t = pa.table({
        "a": pa.array(rng.integers(0, cards, n), pa.int64()),
        "b": pa.array((rng.integers(0, cards, n) * 0.5), pa.float64()),
        "c": pa.array(rng.integers(0, cards, n).astype(np.int32),
                      pa.int32()),
    })
    _roundtrip(tmp_path, fio, t, f"dict_{cards}_{seed}",
               compression="zstd")


@pytest.mark.parametrize("density", [0.0, 0.01, 0.5, 0.97, 1.0])
def test_null_density_oracle(tmp_path, fio, density):
    """Definition-level RLE streams across null densities (incl. the
    all-null and no-null edges)."""
    rng = np.random.default_rng(17)
    n = 12_000
    mask = rng.random(n) < density       # True = null
    vals = rng.integers(0, 1 << 40, n)
    t = pa.table({
        "x": pa.array(vals, pa.int64(), mask=mask),
        "y": pa.array(rng.random(n), pa.float64(),
                      mask=rng.random(n) < density),
    })
    _roundtrip(tmp_path, fio, t, f"nulls_{density}",
               compression="zstd", use_dictionary=False)


@pytest.mark.parametrize("rg,page", [(977, 512), (5_000, 2048),
                                     (50_000, 1 << 20)])
def test_row_group_and_page_shapes(tmp_path, fio, rg, page):
    """Many row groups / tiny pages exercise the page walk + per-page
    RLE run parsing."""
    rng = np.random.default_rng(23)
    n = 25_000
    mask = rng.random(n) < 0.2
    t = pa.table({
        "k": pa.array(rng.integers(0, 1 << 50, n), pa.int64()),
        "d": pa.array(rng.integers(0, 30, n), pa.int64()),
        "nul": pa.array(rng.integers(0, 99, n), pa.int64(), mask=mask),
    })
    _roundtrip(tmp_path, fio, t, f"shapes_{rg}_{page}",
               compression="zstd", row_group_size=rg,
               data_page_size=page)


def test_temporal_and_narrow_ints(tmp_path, fio):
    """Logical types over the fixed-width physicals: timestamps, dates,
    int8/int16 (sign-extended INT32 storage)."""
    rng = np.random.default_rng(5)
    n = 8_000
    t = pa.table({
        "ts": pa.array(rng.integers(0, 1 << 44, n), pa.timestamp("us")),
        "d32": pa.array(rng.integers(0, 20_000, n).astype(np.int32),
                        pa.date32()),
        "i8": pa.array(rng.integers(-128, 128, n).astype(np.int8),
                       pa.int8()),
        "i16": pa.array(rng.integers(-1 << 15, 1 << 15, n).astype(
            np.int16), pa.int16()),
    })
    _roundtrip(tmp_path, fio, t, "temporal", compression="zstd",
               use_dictionary=False)


def test_projection_and_column_order(tmp_path, fio):
    rng = np.random.default_rng(7)
    n = 5_000
    t = pa.table({
        "a": pa.array(rng.integers(0, 10, n), pa.int64()),
        "b": pa.array(rng.random(n), pa.float64()),
        "c": pa.array(rng.integers(0, 9, n).astype(np.int32),
                      pa.int32()),
    })
    path = str(tmp_path / "proj.parquet")
    pq.write_table(t, path, compression="zstd")
    got = read_parquet_device(fio, path, projection=["c", "a"])
    assert got.equals(pq.ParquetFile(path).read(columns=["c", "a"]))


def test_unsupported_shapes_raise(tmp_path, fio):
    """Strings, v2 data pages and unknown codecs raise the typed
    fallback signal — never a wrong answer."""
    n = 1_000
    rng = np.random.default_rng(1)
    strings = pa.table({"s": pa.array(
        [f"v{i}" for i in range(n)], pa.string())})
    p = str(tmp_path / "str.parquet")
    pq.write_table(strings, p)
    with pytest.raises(DeviceDecodeUnsupported):
        read_parquet_device(fio, p)

    ints = pa.table({"x": pa.array(rng.integers(0, 1 << 40, n),
                                   pa.int64())})
    p2 = str(tmp_path / "v2.parquet")
    pq.write_table(ints, p2, data_page_version="2.0",
                   use_dictionary=False)
    with pytest.raises(DeviceDecodeUnsupported):
        read_parquet_device(fio, p2)

    p3 = str(tmp_path / "lz4.parquet")
    pq.write_table(ints, p3, compression="lz4")
    with pytest.raises(DeviceDecodeUnsupported):
        read_parquet_device(fio, p3)


def test_maybe_read_device_counts_fallback(tmp_path, fio):
    from paimon_tpu.format.rawpage import maybe_read_device
    from paimon_tpu.metrics import (
        SCAN_DEVICE_DECODE_FALLBACKS, global_registry,
    )
    t = pa.table({"s": pa.array(["a", "b"], pa.string())})
    p = str(tmp_path / "fb.parquet")
    pq.write_table(t, p)
    before = global_registry().group("scan").counter(
        SCAN_DEVICE_DECODE_FALLBACKS).count
    assert maybe_read_device(fio, p) is None
    after = global_registry().group("scan").counter(
        SCAN_DEVICE_DECODE_FALLBACKS).count
    assert after == before + 1


# ---------------------------------------------------------------------------
# 2. end-to-end table reads
# ---------------------------------------------------------------------------


def _numeric_engine_table(path, engine, seed=3, commits=3, rows=4_000):
    from paimon_tpu.schema import Schema
    from paimon_tpu.table import FileStoreTable
    from paimon_tpu.types import BigIntType, DoubleType, IntType
    rng = np.random.default_rng(seed)
    opts = {"bucket": "2", "write-only": "true", "merge-engine": engine,
            "parquet.enable.dictionary": "false"}
    if engine == "aggregation":
        opts.update({"fields.v1.aggregate-function": "sum",
                     "fields.v2.aggregate-function": "max"})
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v1", BigIntType())
              .column("v2", DoubleType())
              .column("v3", IntType())
              .primary_key("id")
              .options(opts)
              .build())
    table = FileStoreTable.create(path, schema)
    wb = table.new_batch_write_builder()
    for _ in range(commits):
        with wb.new_write() as w:
            ids = rng.integers(0, rows, rows)
            w.write_arrow(pa.table({
                "id": pa.array(ids, pa.int64()),
                "v1": pa.array(rng.integers(0, 1 << 30, rows),
                               pa.int64()),
                "v2": pa.array(rng.random(rows), pa.float64()),
                "v3": pa.array(rng.integers(0, 50, rows).astype(
                    np.int32), pa.int32()),
            }))
            wb.new_commit().commit(w.prepare_commit())
    return table


@pytest.mark.parametrize("engine", ["deduplicate", "first-row",
                                    "aggregation", "partial-update"])
def test_scan_oracle_per_engine(tmp_path, engine):
    """Merge-on-read scans through the device decode plane are
    row-identical to the pyarrow path for every merge engine."""
    from paimon_tpu.metrics import (
        SCAN_DEVICE_DECODE_FILES, global_registry,
    )
    t = _numeric_engine_table(str(tmp_path / "t"), engine)
    oracle = t.to_arrow().sort_by("id")
    before = global_registry().group("scan").counter(
        SCAN_DEVICE_DECODE_FILES).count
    dev = t.copy({"read.device-decode": "true"}).to_arrow().sort_by("id")
    after = global_registry().group("scan").counter(
        SCAN_DEVICE_DECODE_FILES).count
    assert dev.equals(oracle)
    assert after > before, "device decode path never engaged"


def test_compact_oracle_device_decode(tmp_path):
    """Full compaction reading through the device decode plane produces
    a table identical to the host-decoded twin."""
    a = _numeric_engine_table(str(tmp_path / "a"), "deduplicate")
    b = _numeric_engine_table(str(tmp_path / "b"), "deduplicate")
    a.copy({"read.device-decode": "true"}).compact(full=True)
    b.compact(full=True)
    assert a.to_arrow().sort_by("id").equals(b.to_arrow().sort_by("id"))


def test_string_schema_falls_back_identically(tmp_path):
    """A schema with a string column (BYTE_ARRAY) silently takes the
    pyarrow path under read.device-decode — results identical."""
    from paimon_tpu.schema import Schema
    from paimon_tpu.table import FileStoreTable
    from paimon_tpu.types import BigIntType, VarCharType
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("s", VarCharType())
              .primary_key("id")
              .options({"bucket": "1"})
              .build())
    t = FileStoreTable.create(str(tmp_path / "t"), schema)
    wb = t.new_batch_write_builder()
    with wb.new_write() as w:
        w.write_arrow(pa.table({
            "id": pa.array(np.arange(500), pa.int64()),
            "s": pa.array([f"row-{i}" for i in range(500)]),
        }))
        wb.new_commit().commit(w.prepare_commit())
    oracle = t.to_arrow().sort_by("id")
    dev = t.copy({"read.device-decode": "true"}).to_arrow().sort_by("id")
    assert dev.equals(oracle)


# ---------------------------------------------------------------------------
# 3. lowering proof (ROADMAP item 1 acceptance)
# ---------------------------------------------------------------------------


_HOST_MARKERS = ("pure_callback", "io_callback", "python_callback",
                 "outside_compilation", "infeed", "outfeed",
                 "SendToHost", "RecvFromHost", "host_callback")


def test_fused_decode_merge_lowering_has_no_host_transfers():
    """The fused raw-bytes -> decode -> normalized-key -> merge program
    must stay on-device end to end: its jaxpr holds no callback
    primitive and its compiled HLO no host-transfer custom call."""
    import jax
    import jax.numpy as jnp

    from paimon_tpu.ops.decode import fused_decode_merge

    n = 2048
    key_bytes = jnp.zeros(8 * n, jnp.uint8)
    seq_bytes = jnp.zeros(8 * n, jnp.uint8)
    invalid = jnp.zeros(n, jnp.uint32)

    jaxpr = jax.make_jaxpr(
        lambda k, s, i: fused_decode_merge(k, s, i))(
        key_bytes, seq_bytes, invalid)
    text = str(jaxpr)
    for marker in _HOST_MARKERS:
        assert marker not in text, f"jaxpr contains {marker}"

    lowered = jax.jit(
        lambda k, s, i: fused_decode_merge(k, s, i)).lower(
        key_bytes, seq_bytes, invalid)
    hlo = lowered.as_text()
    for marker in _HOST_MARKERS:
        assert marker not in hlo, f"HLO contains {marker}"


def test_fused_decode_merge_matches_numpy_reference():
    """The fused program's winners equal the host-side reference merge
    over the same raw bytes."""
    import jax.numpy as jnp

    from paimon_tpu.ops.decode import fused_decode_merge

    rng = np.random.default_rng(9)
    n = 2048
    keys = rng.integers(-1 << 40, 1 << 40, n).astype(np.int64)
    seq = np.arange(n, dtype=np.int64)
    perm, winner, packed = fused_decode_merge(
        jnp.asarray(keys.view(np.uint8)),
        jnp.asarray(seq.view(np.uint8)),
        jnp.zeros(n, jnp.uint32))
    perm = np.asarray(perm)
    winner = np.asarray(winner)
    # reference: stable sort by (key, seq); winner = last of key group
    order = np.lexsort((seq, keys))
    assert np.array_equal(perm, order)
    ks = keys[order]
    eq_next = np.concatenate([ks[1:] == ks[:-1], [False]])
    assert np.array_equal(winner, ~eq_next)
    # packed keys are the order-preserving normkey transform
    assert np.array_equal(
        np.asarray(packed),
        keys.view(np.uint64) ^ np.uint64(1 << 63))


def test_decode_primitives_unit():
    """unpack_bits / expand_rle_hybrid against tiny hand-computed
    streams (the parquet hybrid layout)."""
    import jax.numpy as jnp

    from paimon_tpu.format.rawpage import parse_rle_runs
    from paimon_tpu.ops.decode import expand_rle_hybrid, unpack_bits

    # bit-packed: header 0b11 = 1 group of 8 values, width 3
    # values 0..7 packed little-endian: 3 bytes
    vals = np.arange(8, dtype=np.uint8)
    packed = np.packbits(
        np.unpackbits(vals[:, None], axis=1, count=3,
                      bitorder="little"), bitorder="little").tobytes()
    buf = bytes([0b11]) + packed
    runs = parse_rle_runs(buf, 3, 8)
    is_p, val, cum, bits = runs
    assert is_p.tolist() == [1] and cum.tolist() == [8]
    words = np.frombuffer(buf + b"\0" * (32 - len(buf)), np.uint32)
    out = expand_rle_hybrid(
        jnp.asarray(words), jnp.asarray(is_p), jnp.asarray(val),
        jnp.asarray(cum), jnp.asarray(bits), 3, 8)
    assert np.asarray(out).tolist() == list(range(8))

    # RLE run: header 0b1010 = 5 repeats of value 4 (1 byte, width 3)
    buf2 = bytes([0b1010, 4])
    is_p, val, cum, bits = parse_rle_runs(buf2, 3, 5)
    assert is_p.tolist() == [0] and val.tolist() == [4] \
        and cum.tolist() == [5]

    # offsets: arbitrary bit positions
    words = jnp.asarray(np.frombuffer(
        np.uint64(0b110_101_100_011_010_001).tobytes() + b"\0" * 8,
        np.uint32))
    offs = jnp.asarray(np.arange(6, dtype=np.int32) * 3)
    got = np.asarray(unpack_bits(words, 3, offs))
    assert got.tolist() == [1, 2, 3, 4, 5, 6]


def test_iter_batches_device_streams_and_falls_back_midfile(tmp_path,
                                                            fio):
    """The streamed-compaction iterator decodes one row group at a
    time (bounded memory) and, when a page shape the footer cannot
    reveal appears (v2 data pages), silently reroutes the remaining
    row groups through pyarrow — rows identical either way."""
    from paimon_tpu.format.rawpage import iter_batches_device

    rng = np.random.default_rng(31)
    n = 24_000
    t = pa.table({
        "a": pa.array(rng.integers(0, 1 << 40, n), pa.int64()),
        "b": pa.array(rng.random(n), pa.float64()),
    })
    p1 = str(tmp_path / "v1.parquet")
    pq.write_table(t, p1, compression="zstd", use_dictionary=False,
                   row_group_size=5_000)
    got = pa.concat_tables(
        list(iter_batches_device(fio, p1, 2_000)))
    assert got.equals(pq.ParquetFile(p1).read())
    assert got.num_rows == n

    # v2 data pages: the footer pre-check passes, the first page does
    # not — the iterator must still deliver every row via pyarrow
    p2 = str(tmp_path / "v2.parquet")
    pq.write_table(t, p2, compression="zstd", use_dictionary=False,
                   row_group_size=5_000, data_page_version="2.0")
    from paimon_tpu.metrics import (
        SCAN_DEVICE_DECODE_FALLBACKS, global_registry,
    )
    before = global_registry().group("scan").counter(
        SCAN_DEVICE_DECODE_FALLBACKS).count
    got2 = pa.concat_tables(
        list(iter_batches_device(fio, p2, 2_000)))
    after = global_registry().group("scan").counter(
        SCAN_DEVICE_DECODE_FALLBACKS).count
    assert got2.equals(pq.ParquetFile(p2).read())
    assert after == before + 1
