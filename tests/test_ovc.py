"""Offset-value coded merge (ops/ovc.py + native tree-of-losers).

Oracle discipline: every OVC result is compared against the sort-based
paths it replaces (PAIMON_DISABLE_OVC twin runs, np.lexsort ground
truth), across engines, key shapes (packed u64 and multi-lane string
prefixes), tie densities, and contract violations (unsorted runs MUST
fall back, never mis-merge).
"""

import os

import numpy as np
import pyarrow as pa
import pytest

from paimon_tpu.ops.merge import PATH_COUNTS, merge_runs
from paimon_tpu.ops.normkey import NormalizedKeyEncoder
from paimon_tpu.ops.ovc import OVC_OFF_SENTINEL, run_ovc_offsets


@pytest.fixture
def no_ovc(monkeypatch):
    def off():
        monkeypatch.setenv("PAIMON_DISABLE_OVC", "1")

    def on():
        monkeypatch.delenv("PAIMON_DISABLE_OVC", raising=False)
    on()
    return off, on


def _int_runs(seed, k=8, per=4_000, space=3_000, kinds=True):
    rng = np.random.default_rng(seed)
    runs = []
    base = 0
    for _ in range(k):
        ids = np.sort(rng.integers(0, space, per))
        runs.append(pa.table({
            "_KEY_id": pa.array(ids, pa.int64()),
            "_SEQUENCE_NUMBER": pa.array(
                np.arange(base, base + per), pa.int64()),
            "_VALUE_KIND": pa.array(
                rng.integers(0, 4, per).astype(np.int8) if kinds
                else np.zeros(per, np.int8), pa.int8()),
            "v": pa.array(rng.random(per), pa.float64()),
        }))
        base += per
    return runs


def _str_runs(seed, k=6, per=3_000):
    rng = np.random.default_rng(seed)
    runs = []
    base = 0
    for _ in range(k):
        keys = sorted(f"key-{x:07d}" for x in rng.integers(0, per, per))
        runs.append(pa.table({
            "_KEY_s": pa.array(keys, pa.string()),
            "_SEQUENCE_NUMBER": pa.array(
                np.arange(base, base + per), pa.int64()),
            "_VALUE_KIND": pa.array(np.zeros(per, np.int8), pa.int8()),
        }))
        base += per
    return runs


_INT_ENC = NormalizedKeyEncoder([pa.int64()], nullable=[False])
_STR_ENC = NormalizedKeyEncoder([pa.string()], nullable=[False])


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_dedup_equals_sort_path(no_ovc, monkeypatch, seed):
    off, on = no_ovc
    runs = _int_runs(seed)
    before = PATH_COUNTS["ovc"]
    got = merge_runs(runs, ["_KEY_id"], key_encoder=_INT_ENC).take()
    assert PATH_COUNTS["ovc"] == before + 1
    off()
    ref = merge_runs(runs, ["_KEY_id"], key_encoder=_INT_ENC).take()
    assert got.equals(ref)


@pytest.mark.parametrize("engine", ["deduplicate", "first-row"])
def test_engines_and_prev(no_ovc, engine):
    off, on = no_ovc
    runs = _int_runs(11, kinds=(engine == "deduplicate"))
    got = merge_runs(runs, ["_KEY_id"], merge_engine=engine,
                     key_encoder=_INT_ENC, with_prev=True,
                     drop_deletes=False)
    off()
    ref = merge_runs(runs, ["_KEY_id"], merge_engine=engine,
                     key_encoder=_INT_ENC, with_prev=True,
                     drop_deletes=False)
    assert np.array_equal(got.indices, ref.indices)
    assert np.array_equal(got.prev_indices, ref.prev_indices)


@pytest.mark.parametrize("seed", [0, 1])
def test_multilane_string_keys(no_ovc, seed):
    """The lane-matrix OVC path (wide keys — where single-int compares
    replace an L-key lexsort)."""
    off, on = no_ovc
    runs = _str_runs(seed)
    before = PATH_COUNTS["ovc"]
    got = merge_runs(runs, ["_KEY_s"], key_encoder=_STR_ENC).take()
    assert PATH_COUNTS["ovc"] == before + 1
    off()
    ref = merge_runs(runs, ["_KEY_s"], key_encoder=_STR_ENC).take()
    assert got.equals(ref)


def test_heavy_duplicate_ties(no_ovc):
    """All-equal and two-key windows: the code-tie fallthrough path
    (equal codes -> lane compares -> seq/run order) dominates here."""
    off, on = no_ovc
    base = 0
    runs = []
    for r in range(5):
        n = 2_000
        ids = np.sort(np.repeat([7, 9], n // 2))
        runs.append(pa.table({
            "_KEY_id": pa.array(ids, pa.int64()),
            "_SEQUENCE_NUMBER": pa.array(
                np.arange(base, base + n), pa.int64()),
            "_VALUE_KIND": pa.array(np.zeros(n, np.int8), pa.int8()),
        }))
        base += n
    got = merge_runs(runs, ["_KEY_id"], key_encoder=_INT_ENC,
                     with_prev=True, drop_deletes=False)
    off()
    ref = merge_runs(runs, ["_KEY_id"], key_encoder=_INT_ENC,
                     with_prev=True, drop_deletes=False)
    assert np.array_equal(got.indices, ref.indices)


def test_unsorted_run_falls_back(no_ovc):
    """A caller violating the sorted-run contract silently takes the
    sort path — identical answer, no mis-merge."""
    off, on = no_ovc
    rng = np.random.default_rng(2)
    runs = [t.take(pa.array(rng.permutation(t.num_rows)))
            for t in _int_runs(5, k=3, per=800)]
    before_host = PATH_COUNTS["host"]
    got = merge_runs(runs, ["_KEY_id"], key_encoder=_INT_ENC).take()
    assert PATH_COUNTS["host"] > before_host     # fell back
    off()
    ref = merge_runs(runs, ["_KEY_id"], key_encoder=_INT_ENC).take()
    assert got.equals(ref)


def test_agg_path_equivalence(no_ovc):
    from paimon_tpu.ops.agg import merge_runs_agg
    from paimon_tpu.options import CoreOptions
    from paimon_tpu.schema import Schema
    from paimon_tpu.types import BigIntType, DoubleType

    schema_obj = (Schema.builder()
                  .column("id", BigIntType(False))
                  .column("v", DoubleType())
                  .primary_key("id")
                  .options({"bucket": "1", "merge-engine": "aggregation",
                            "fields.v.aggregate-function": "sum"})
                  .build())
    from paimon_tpu.schema.table_schema import TableSchema
    ts = TableSchema.from_schema(0, schema_obj)
    options = CoreOptions(schema_obj.options)
    runs = []
    base = 0
    rng = np.random.default_rng(3)
    for _ in range(6):
        n = 2_000
        ids = np.sort(rng.integers(0, 500, n))
        runs.append(pa.table({
            "_KEY_id": pa.array(ids, pa.int64()),
            "_SEQUENCE_NUMBER": pa.array(
                np.arange(base, base + n), pa.int64()),
            "_VALUE_KIND": pa.array(np.zeros(n, np.int8), pa.int8()),
            "id": pa.array(ids, pa.int64()),
            "v": pa.array(rng.random(n), pa.float64()),
        }))
        base += n
    got = merge_runs_agg(runs, ["_KEY_id"], ts, options,
                         key_encoder=_INT_ENC)
    os.environ["PAIMON_DISABLE_OVC"] = "1"
    try:
        ref = merge_runs_agg(runs, ["_KEY_id"], ts, options,
                             key_encoder=_INT_ENC)
    finally:
        del os.environ["PAIMON_DISABLE_OVC"]
    assert got.equals(ref)


# ---------------------------------------------------------------------------
# code-level semantics
# ---------------------------------------------------------------------------


def test_native_merge_matches_lexsort_ground_truth():
    from paimon_tpu import native
    if native.load() is None:
        pytest.skip("no native runtime")
    rng = np.random.default_rng(1)
    k, per = 7, 5_000
    keys = np.concatenate([
        np.sort(rng.integers(0, 8_000, per).astype(np.uint64))
        for _ in range(k)])
    seq = np.arange(k * per, dtype=np.int64)
    starts = np.arange(0, k * per + 1, per, dtype=np.int64)
    perm, code = native.ovc_merge_u64(keys, seq, starts)
    gt = np.lexsort((seq, keys))
    assert np.array_equal(perm, gt)
    ks = keys[perm]
    assert np.array_equal(code[1:] == 0, ks[1:] == ks[:-1])
    # first output is never coded "equal to predecessor"
    assert code[0] != 0


def test_run_codes_reference_semantics():
    """The C initial-code pass (the ONE implementation — the merge
    entries run it internally) against hand-computed codes."""
    from paimon_tpu import native
    if native.load() is None:
        pytest.skip("no native runtime")
    run_codes_u64 = native.ovc_codes_u64
    run_codes_lanes = native.ovc_codes_lanes
    keys = np.array([(2 << 32) | 5, (2 << 32) | 5, (2 << 32) | 9,
                     (3 << 32) | 1], dtype=np.uint64)
    seq = np.arange(4, dtype=np.int64)
    starts = np.array([0, 4], dtype=np.int64)
    codes = run_codes_u64(keys, seq, starts)
    assert codes is not None
    assert codes[0] == (np.uint64(2) << np.uint64(32)) | np.uint64(2)
    assert codes[1] == 0                          # equal to predecessor
    assert codes[2] == (np.uint64(1) << np.uint64(32)) | np.uint64(9)
    assert codes[3] == (np.uint64(2) << np.uint64(32)) | np.uint64(3)
    # violation: descending keys
    bad = run_codes_u64(keys[::-1].copy(), seq, starts)
    assert bad is None
    # violation: equal keys, descending seq
    bad2 = run_codes_u64(
        np.array([5, 5], np.uint64), np.array([3, 1], np.int64),
        np.array([0, 2], np.int64))
    assert bad2 is None

    lanes = np.array([[1, 1, 1], [1, 1, 1], [1, 2, 0], [2, 0, 0]],
                     dtype=np.uint32)
    codes = run_codes_lanes(lanes, np.arange(4, dtype=np.int64),
                            np.array([0, 4], np.int64))
    assert codes is not None
    assert codes[0] == (np.uint64(3) << np.uint64(32)) | np.uint64(1)
    assert codes[1] == 0
    assert codes[2] == (np.uint64(2) << np.uint64(32)) | np.uint64(2)
    assert codes[3] == (np.uint64(3) << np.uint64(32)) | np.uint64(2)


def test_run_ovc_offsets_semantics():
    lanes = np.array([[1, 1], [1, 1], [1, 2], [3, 0], [3, 0]],
                     dtype=np.uint32)
    starts = np.array([0, 3, 5], np.int64)
    off = run_ovc_offsets(lanes, starts)
    assert off[0] == OVC_OFF_SENTINEL              # run 0 start
    assert off[1] == 2                             # all lanes equal
    assert off[2] == 1                             # differs at lane 1
    assert off[3] == OVC_OFF_SENTINEL              # run 1 start
    assert off[4] == 2


def test_device_kernel_ovc_equivalence(monkeypatch):
    """Forced device sort with run_starts exercises the OVC-aware
    winner-select (Pallas interpret on cpu) — identical to the host
    path, including run-boundary equal keys that the sentinel must
    send through the lane-compare fallthrough."""
    runs = [
        pa.table({"_KEY_id": pa.array([1, 2, 7], pa.int64()),
                  "_SEQUENCE_NUMBER": pa.array([0, 1, 2], pa.int64()),
                  "_VALUE_KIND": pa.array([0, 0, 0], pa.int8())}),
        pa.table({"_KEY_id": pa.array([7, 8, 9], pa.int64()),
                  "_SEQUENCE_NUMBER": pa.array([3, 4, 5], pa.int64()),
                  "_VALUE_KIND": pa.array([0, 0, 0], pa.int8())}),
    ]
    monkeypatch.setenv("PAIMON_FORCE_DEVICE_SORT", "1")
    dev = merge_runs(runs, ["_KEY_id"], key_encoder=_INT_ENC,
                     with_prev=True, drop_deletes=False)
    monkeypatch.setenv("PAIMON_FORCE_HOST_SORT", "1")
    monkeypatch.delenv("PAIMON_FORCE_DEVICE_SORT")
    host = merge_runs(runs, ["_KEY_id"], key_encoder=_INT_ENC,
                      with_prev=True, drop_deletes=False)
    assert np.array_equal(dev.indices, host.indices)
    assert dev.indices.tolist()[-3:] == [3, 4, 5]  # 7 deduped to seq 3


def test_large_k_tree_path_matches_lexsort():
    """k > 64 takes the loser TREE (the scan path handles k <= 64):
    both must equal the lexsort ground truth."""
    from paimon_tpu import native
    if native.load() is None:
        pytest.skip("no native runtime")
    rng = np.random.default_rng(4)
    k, per = 100, 300
    keys = np.concatenate([
        np.sort(rng.integers(0, 2_000, per).astype(np.uint64))
        for _ in range(k)])
    seq = np.arange(k * per, dtype=np.int64)
    starts = np.arange(0, k * per + 1, per, dtype=np.int64)
    perm, code = native.ovc_merge_u64(keys, seq, starts)
    gt = np.lexsort((seq, keys))
    assert np.array_equal(perm, gt)
    ks = keys[perm]
    assert np.array_equal(code[1:] == 0, ks[1:] == ks[:-1])
    # lanes variant through the tree too
    lanes = np.stack([(keys >> 32).astype(np.uint32),
                      (keys & 0xFFFFFFFF).astype(np.uint32),
                      (keys % 7).astype(np.uint32)], axis=1)
    parts = []
    for j in range(k):
        sl = lanes[starts[j]:starts[j + 1]]
        order = np.lexsort((sl[:, 2], sl[:, 1], sl[:, 0]))
        parts.append(sl[order])
    lanes = np.ascontiguousarray(np.concatenate(parts))
    perm2, code2 = native.ovc_merge_lanes(lanes, seq, starts)
    gt2 = np.lexsort((seq, lanes[:, 2], lanes[:, 1], lanes[:, 0]))
    assert np.array_equal(perm2, gt2)


def test_window_rows_cap_bounds_windows_and_preserves_rows():
    """iter_merge_windows with a window cap yields BOUNDED windows
    whose concatenation equals the uncapped stream, with keys still
    never straddling windows."""
    from paimon_tpu.ops.merge_stream import iter_merge_windows

    rng = np.random.default_rng(6)
    k, per = 5, 20_000

    def run_iters():
        its = []
        base = 0
        for i in range(k):
            ids = np.sort(rng.integers(0, 30_000, per))
            t = pa.table({
                "_KEY_id": pa.array(ids, pa.int64()),
                "_SEQUENCE_NUMBER": pa.array(
                    np.arange(base + i * per, base + (i + 1) * per),
                    pa.int64()),
                "_VALUE_KIND": pa.array(np.zeros(per, np.int8),
                                        pa.int8())})
            its.append(iter([t]))
        return its

    rng = np.random.default_rng(6)
    capped = list(iter_merge_windows(run_iters(), ["_KEY_id"],
                                     _INT_ENC, window_rows=1_000))
    rng = np.random.default_rng(6)
    uncapped = list(iter_merge_windows(run_iters(), ["_KEY_id"],
                                       _INT_ENC))
    assert len(capped) > len(uncapped)
    sizes = [sum(it[0].num_rows for it in w) for w in capped]
    # ~k x window_rows bound (generous slack for duplicate groups)
    assert max(sizes) <= k * 1_000 + 1_000

    def flat_ids(windows):
        return np.concatenate([
            np.asarray(it[0].column("_KEY_id")) for w in windows
            for it in w])
    assert np.array_equal(np.sort(flat_ids(capped)),
                          np.sort(flat_ids(uncapped)))
    # key-window invariant: windows partition the keyspace in order
    prev_max = -1
    for w in capped:
        ids = np.concatenate([np.asarray(it[0].column("_KEY_id"))
                              for it in w])
        assert ids.min() > prev_max
        prev_max = ids.max()
