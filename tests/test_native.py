"""Native C radix sort / fused winner selection: parity vs numpy and
vs the python fast path, plus graceful degradation."""

import numpy as np
import pytest

from paimon_tpu import native
from paimon_tpu.ops import merge as M

pytestmark = pytest.mark.skipif(native.load() is None,
                                reason="no C compiler available")


def _keys(n, dupes=3, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, max(n // dupes, 1), n).astype(np.uint64) \
        << np.uint64(32)


class TestRadixSort:
    @pytest.mark.parametrize("n", [0, 1, 2, 1000, 100_000])
    def test_matches_numpy_stable(self, n):
        key = _keys(n)
        p_c = native.radix_argsort(key)
        p_np = np.argsort(key, kind="stable")
        assert np.array_equal(p_c.astype(np.int64), p_np)

    def test_random_low_bits(self):
        rng = np.random.default_rng(1)
        key = rng.integers(0, 1 << 63, 50_000).astype(np.uint64)
        assert np.array_equal(
            native.radix_argsort(key).astype(np.int64),
            np.argsort(key, kind="stable"))

    def test_all_equal_keys(self):
        key = np.full(5000, 42, np.uint64)
        p = native.radix_argsort(key)
        assert np.array_equal(p, np.arange(5000, dtype=np.int32))


class TestFusedWinners:
    @pytest.mark.parametrize("keep_last", [True, False])
    def test_matches_python_path(self, keep_last, monkeypatch):
        n = 30_000
        rng = np.random.default_rng(2)
        keys = rng.integers(0, n // 4, n).astype(np.uint32)
        lanes = np.stack([keys, np.zeros(n, np.uint32)], axis=1)
        seq = rng.integers(0, 1000, n).astype(np.int64)
        keep = "last" if keep_last else "first"
        perm_c, win_c, _ = M._host_sorted_winners_fast(lanes, seq, keep)
        # python reference: disable native for the comparison run
        monkeypatch.setattr(native, "merge_winners",
                            lambda *a, **k: None)
        perm_p, win_p, _ = M._host_sorted_winners_fast(lanes, seq, keep)
        assert np.array_equal(perm_c[win_c], perm_p[win_p])

    def test_winner_semantics(self):
        # key 7 appears with seqs [5, 9, 9]: keep=last -> the LATER
        # arrival of the tied max seq; keep=first -> min seq
        lanes = np.array([[7, 0], [7, 0], [7, 0], [3, 0]], np.uint32)
        seq = np.array([5, 9, 9, 1], np.int64)
        perm, win, _ = M._host_sorted_winners_fast(lanes, seq, "last")
        winners = set(perm[win].tolist())
        assert winners == {2, 3}
        perm, win, _ = M._host_sorted_winners_fast(lanes, seq, "first")
        assert set(perm[win].tolist()) == {0, 3}


class TestDegradation:
    def test_disabled_env_falls_back(self, monkeypatch):
        monkeypatch.setenv("PAIMON_DISABLE_NATIVE", "1")
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_tried", False)
        assert native.load() is None
        assert native.radix_argsort(np.zeros(4, np.uint64)) is None
        # merge plane still works end-to-end
        lanes = np.array([[1, 0], [1, 0]], np.uint32)
        perm, win, _ = M._host_sorted_winners_fast(
            lanes, np.array([0, 1], np.int64), "last")
        assert perm[win].tolist() == [1]
        monkeypatch.setattr(native, "_tried", False)   # restore probes
