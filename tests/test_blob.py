"""Blob column externalization to .blob sidecars.

reference: format/blob/BlobFileFormat.java + BlobDescriptor.
"""

import os

import pytest

from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, BlobType


def _commit(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    wb.new_commit().commit(w.prepare_commit())
    w.close()


@pytest.mark.parametrize("pk", [True, False])
def test_blob_roundtrip(tmp_warehouse, pk):
    b = (Schema.builder()
         .column("id", BigIntType(False))
         .column("payload", BlobType()))
    if pk:
        b = b.primary_key("id").options({"bucket": "1",
                                         "write-only": "true"})
    schema = b.build()
    table = FileStoreTable.create(
        os.path.join(tmp_warehouse, f"t{pk}"), schema)
    big = os.urandom(64 << 10)
    _commit(table, [{"id": 1, "payload": big},
                    {"id": 2, "payload": b"small"},
                    {"id": 3, "payload": None}])
    rows = {r["id"]: r["payload"]
            for r in table.to_arrow().to_pylist()}
    assert rows[1] == big
    assert rows[2] == b"small"
    assert rows[3] is None
    # blob bytes live in a .blob sidecar, not the data file
    snap = table.snapshot_manager.latest_snapshot()
    entries = table.new_scan().read_entries(snap)
    assert all(any(x.endswith(".blob") for x in e.file.extra_files)
               for e in entries)
    assert all(e.file.file_size < 64 << 10 for e in entries)


def test_blob_survives_compaction(tmp_warehouse):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("payload", BlobType())
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "c"),
                                  schema)
    _commit(table, [{"id": 1, "payload": b"abc" * 1000}])
    _commit(table, [{"id": 1, "payload": b"xyz" * 1000}])
    table.compact(full=True)
    assert table.to_arrow().to_pylist()[0]["payload"] == b"xyz" * 1000


def test_blob_survives_column_rename(tmp_warehouse):
    """File-schema-driven resolution: files written before a blob column
    rename still resolve."""
    from paimon_tpu.schema.schema_manager import SchemaChange

    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("payload", BlobType())
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "r"),
                                  schema)
    _commit(table, [{"id": 1, "payload": b"old-data"}])
    table.schema_manager.commit_changes(
        SchemaChange.rename_column("payload", "doc"))
    t2 = FileStoreTable.load(table.path)
    rows = t2.to_arrow().to_pylist()
    assert rows == [{"id": 1, "doc": b"old-data"}]
    assert t2.compact(full=True) is not None
    assert t2.to_arrow().to_pylist() == [{"id": 1, "doc": b"old-data"}]


def test_blob_projection_skips_sidecar(tmp_warehouse):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("payload", BlobType())
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "p"),
                                  schema)
    _commit(table, [{"id": 1, "payload": b"x" * 1000}])
    out = table.to_arrow(projection=["id"])
    assert out.column_names == ["id"]
    assert out.num_rows == 1


def test_delete_where_on_blob_table(tmp_warehouse):
    from paimon_tpu import predicate as P

    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("payload", BlobType())
              .build())                     # append table with DVs
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "dv"),
                                  schema)
    _commit(table, [{"id": i, "payload": bytes([i])} for i in range(5)])
    assert table.delete_where(P.equal("id", 2)) is not None
    rows = {r["id"]: r["payload"] for r in table.to_arrow().to_pylist()}
    assert sorted(rows) == [0, 1, 3, 4]
    assert rows[3] == bytes([3])
