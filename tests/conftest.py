"""Test config: force a deterministic 8-device CPU mesh so sharding tests
run without TPU hardware (the driver separately dry-runs multi-chip)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture
def tmp_warehouse(tmp_path):
    return str(tmp_path / "warehouse")
