"""Test config: force a deterministic 8-device CPU mesh so sharding tests
run without TPU hardware (the driver separately dry-runs multi-chip).

The environment boots an `axon` PJRT plugin (one real TPU behind a
single-client tunnel) and its register() forces jax_platforms="axon,cpu"
AFTER the env var is read -- so overriding the env is not enough; the jax
config must be set back to cpu before any backend initializes. Tests must
never touch the TPU tunnel (it wedges under concurrent clients); bench.py
is the only TPU user.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running (multichip dryruns); excluded from tier-1 "
        "via -m 'not slow'")


@pytest.fixture
def tmp_warehouse(tmp_path):
    return str(tmp_path / "warehouse")


@pytest.fixture(scope="session")
def lint_report():
    """ONE whole-program analysis pass (paimon_tpu/analysis/) shared
    by every tier-1 lint test — one parse per file per test session,
    replacing the seven independent full-tree AST walks the old
    tests/test_lint_swallow.py performed."""
    from paimon_tpu.analysis import default_report
    return default_report()
