"""Multi-host helpers on the virtual 8-device CPU mesh (the env's
stand-in for real multi-chip/host topology; conftest forces
xla_force_host_platform_device_count=8)."""

import numpy as np
import pyarrow as pa
import pytest

from paimon_tpu.parallel import multihost as MH


class TestBootstrap:
    def test_single_process_noop(self):
        idx, count = MH.initialize()
        assert (idx, count) == (0, 1)

    def test_env_defaults(self, monkeypatch):
        monkeypatch.setenv("NUM_PROCESSES", "1")
        monkeypatch.setenv("PROCESS_ID", "0")
        assert MH.initialize() == (0, 1)

    def test_peer_death_tolerance_unset(self, monkeypatch):
        monkeypatch.delenv("PAIMON_MULTIHOST_PEER_MISSED_HEARTBEATS",
                           raising=False)
        assert MH.peer_death_tolerance() == {}

    def test_peer_death_tolerance_explicit_and_env(self, monkeypatch):
        assert MH.peer_death_tolerance(360) == {
            "service_max_missing_heartbeats": 360,
            "client_max_missing_heartbeats": 360,
        }
        monkeypatch.setenv("PAIMON_MULTIHOST_PEER_MISSED_HEARTBEATS",
                           "25")
        assert MH.peer_death_tolerance() == {
            "service_max_missing_heartbeats": 25,
            "client_max_missing_heartbeats": 25,
        }
        # explicit argument wins over the env var
        assert MH.peer_death_tolerance(7)[
            "client_max_missing_heartbeats"] == 7


class TestGlobalMesh:
    def test_one_axis_inferred(self):
        import jax
        mesh = MH.global_mesh(("data",))
        assert mesh.devices.size == len(jax.devices())
        assert mesh.axis_names == ("data",)

    def test_two_axis(self):
        mesh = MH.global_mesh(("data", "model"), shape=(4, 2))
        assert mesh.devices.shape == (4, 2)

    def test_bad_shape_rejected(self):
        with pytest.raises(ValueError, match="!= device count"):
            MH.global_mesh(("data",), shape=(3,))
        with pytest.raises(ValueError, match="shape is required"):
            MH.global_mesh(("a", "b"))


class TestProcessLocalBatch:
    def test_batch_shards_across_mesh(self):
        import jax
        mesh = MH.global_mesh(("data",))
        n = len(jax.devices()) * 4
        batch = MH.process_local_batch(
            mesh, {"x": np.arange(n, dtype=np.int32),
                   "y": np.arange(n, dtype=np.float32) * 2})
        assert batch["x"].shape == (n,)
        assert batch["x"].sharding.mesh.shape["data"] == \
            len(jax.devices())
        # a sharded computation over it works
        assert int(jax.numpy.sum(batch["x"])) == n * (n - 1) // 2

    def test_feeds_jax_data_loader_sharding(self, tmp_path):
        # jax_batches with a NamedSharding scatters device_puts
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        from paimon_tpu.integrations.jax_data import jax_batches
        from paimon_tpu.schema import Schema
        from paimon_tpu.table import FileStoreTable
        from paimon_tpu.types import BigIntType

        schema = (Schema.builder().column("id", BigIntType(False))
                  .options({"bucket": "-1"}).build())
        t = FileStoreTable.create(str(tmp_path / "t"), schema)
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        w.write_arrow(pa.table({"id": pa.array(range(64), pa.int64())}))
        wb.new_commit().commit(w.prepare_commit())
        mesh = MH.global_mesh(("data",))
        sh = NamedSharding(mesh, PartitionSpec("data"))
        batches = list(jax_batches(t, 32, sharding=sh))
        assert len(batches) == 2
        assert batches[0]["id"].sharding == sh
        _ = jax.block_until_ready(batches[0]["id"])


class _FakeFile:
    def __init__(self, size):
        self.file_size = size


class _FakeSplit:
    def __init__(self, *sizes):
        self.data_files = [_FakeFile(s) for s in sizes]


class TestSplitAssignment:
    def test_partition_of_splits(self):
        # equal-weight splits degrade LPT to round-robin (ties break
        # on index), preserving the original ownership contract
        splits = list(range(10))
        owned = [MH.assign_splits(splits, p, 3) for p in range(3)]
        assert sorted(x for part in owned for x in part) == splits
        assert owned[0] == [0, 3, 6, 9]

    def test_default_single_process_owns_all(self):
        assert MH.assign_splits([1, 2, 3]) == [1, 2, 3]

    def test_byte_aware_lpt_balances_large_splits(self):
        # round-robin by index would give process 0 BOTH huge splits
        # (indices 0 and 2); byte-aware LPT spreads them
        splits = [_FakeSplit(1000), _FakeSplit(1), _FakeSplit(1000),
                  _FakeSplit(1)]
        owned = [MH.assign_splits(splits, p, 2) for p in range(2)]
        # disjoint cover
        ids = sorted(id(s) for part in owned for s in part)
        assert ids == sorted(id(s) for s in splits)
        loads = [sum(MH.split_weight(s) for s in part)
                 for part in owned]
        assert max(loads) <= 1001          # one big + one small each

    def test_lpt_deterministic_across_callers(self):
        import random
        sizes = [random.Random(7).randrange(1, 10_000)
                 for _ in range(50)]
        splits = [_FakeSplit(s) for s in sizes]
        for p in range(4):
            a = MH.assign_splits(splits, p, 4)
            b = MH.assign_splits(splits, p, 4)
            assert [id(s) for s in a] == [id(s) for s in b]
        # every process's plan agrees: union is a disjoint cover
        all_owned = [s for p in range(4)
                     for s in MH.assign_splits(splits, p, 4)]
        assert sorted(id(s) for s in all_owned) == \
            sorted(id(s) for s in splits)

    def test_split_weight_floor(self):
        assert MH.split_weight(object()) == 1
        assert MH.split_weight(_FakeSplit()) == 1
        assert MH.split_weight(_FakeSplit(0, 0)) == 1

    def test_commit_user(self):
        assert MH.distributed_write_commit_user("w") == "w-p0"


class TestInitializeConfigWarning:
    def test_gloo_config_failure_warns_not_silent(self, monkeypatch):
        """A jax build where the Gloo opt-in flag is missing must warn
        through the obs plane (+ multihost config_warnings counter),
        not silently proceed into broken CPU collectives."""
        import warnings

        import jax

        from paimon_tpu.metrics import (
            MULTIHOST_CONFIG_WARNINGS, global_registry,
        )

        def boom(key, value):
            raise ValueError(f"no such config {key}")

        inits = []
        monkeypatch.setattr(jax.config, "update", boom)
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda **kw: inits.append(kw))
        counter = global_registry().multihost_metrics().counter(
            MULTIHOST_CONFIG_WARNINGS)
        before = counter.count
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            idx, count = MH.initialize("127.0.0.1:1", 2, 0)
        assert len(inits) == 1              # runtime still brought up
        msgs = [str(w.message) for w in caught]
        assert any("Gloo" in m and "cross-process" in m for m in msgs), \
            msgs
        assert counter.count == before + 1
