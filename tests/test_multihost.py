"""Multi-host helpers on the virtual 8-device CPU mesh (the env's
stand-in for real multi-chip/host topology; conftest forces
xla_force_host_platform_device_count=8)."""

import numpy as np
import pyarrow as pa
import pytest

from paimon_tpu.parallel import multihost as MH


class TestBootstrap:
    def test_single_process_noop(self):
        idx, count = MH.initialize()
        assert (idx, count) == (0, 1)

    def test_env_defaults(self, monkeypatch):
        monkeypatch.setenv("NUM_PROCESSES", "1")
        monkeypatch.setenv("PROCESS_ID", "0")
        assert MH.initialize() == (0, 1)


class TestGlobalMesh:
    def test_one_axis_inferred(self):
        import jax
        mesh = MH.global_mesh(("data",))
        assert mesh.devices.size == len(jax.devices())
        assert mesh.axis_names == ("data",)

    def test_two_axis(self):
        mesh = MH.global_mesh(("data", "model"), shape=(4, 2))
        assert mesh.devices.shape == (4, 2)

    def test_bad_shape_rejected(self):
        with pytest.raises(ValueError, match="!= device count"):
            MH.global_mesh(("data",), shape=(3,))
        with pytest.raises(ValueError, match="shape is required"):
            MH.global_mesh(("a", "b"))


class TestProcessLocalBatch:
    def test_batch_shards_across_mesh(self):
        import jax
        mesh = MH.global_mesh(("data",))
        n = len(jax.devices()) * 4
        batch = MH.process_local_batch(
            mesh, {"x": np.arange(n, dtype=np.int32),
                   "y": np.arange(n, dtype=np.float32) * 2})
        assert batch["x"].shape == (n,)
        assert batch["x"].sharding.mesh.shape["data"] == \
            len(jax.devices())
        # a sharded computation over it works
        assert int(jax.numpy.sum(batch["x"])) == n * (n - 1) // 2

    def test_feeds_jax_data_loader_sharding(self, tmp_path):
        # jax_batches with a NamedSharding scatters device_puts
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        from paimon_tpu.integrations.jax_data import jax_batches
        from paimon_tpu.schema import Schema
        from paimon_tpu.table import FileStoreTable
        from paimon_tpu.types import BigIntType

        schema = (Schema.builder().column("id", BigIntType(False))
                  .options({"bucket": "-1"}).build())
        t = FileStoreTable.create(str(tmp_path / "t"), schema)
        wb = t.new_batch_write_builder()
        w = wb.new_write()
        w.write_arrow(pa.table({"id": pa.array(range(64), pa.int64())}))
        wb.new_commit().commit(w.prepare_commit())
        mesh = MH.global_mesh(("data",))
        sh = NamedSharding(mesh, PartitionSpec("data"))
        batches = list(jax_batches(t, 32, sharding=sh))
        assert len(batches) == 2
        assert batches[0]["id"].sharding == sh
        _ = jax.block_until_ready(batches[0]["id"])


class TestSplitAssignment:
    def test_partition_of_splits(self):
        splits = list(range(10))
        owned = [MH.assign_splits(splits, p, 3) for p in range(3)]
        assert sorted(x for part in owned for x in part) == splits
        assert owned[0] == [0, 3, 6, 9]

    def test_default_single_process_owns_all(self):
        assert MH.assign_splits([1, 2, 3]) == [1, 2, 3]

    def test_commit_user(self):
        assert MH.distributed_write_commit_user("w") == "w-p0"
