"""Postpone bucket mode (bucket=-2): staging + rescale.

reference: postpone/PostponeBucketFileStoreWrite.java, BucketMode
POSTPONE_MODE.
"""

import os

import pytest

from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType


def _make(tmp_warehouse):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "-2", "write-only": "true",
                        "dynamic-bucket.target-row-num": "100"})
              .build())
    return FileStoreTable.create(os.path.join(tmp_warehouse, "t"), schema)


def _commit(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    wb.new_commit().commit(w.prepare_commit())
    w.close()


def test_postpone_staging_invisible_until_rescale(tmp_warehouse):
    table = _make(tmp_warehouse)
    _commit(table, [{"id": i, "v": float(i)} for i in range(250)])
    # staged data lands under bucket-postpone and is NOT readable
    assert os.path.isdir(os.path.join(table.path, "bucket-postpone"))
    assert table.to_arrow().num_rows == 0

    sid = table.rescale_postpone()
    assert sid is not None
    out = table.to_arrow()
    assert out.num_rows == 250
    # rescale honored upserts staged before it
    buckets = {s.bucket for s in
               table.new_read_builder().new_scan().plan().splits}
    assert -2 not in buckets
    assert len(buckets) >= 2       # spread by dynamic target-row-num

    # idempotent: nothing left to rescale
    assert table.rescale_postpone() is None


def test_postpone_upserts_resolve_after_rescale(tmp_warehouse):
    table = _make(tmp_warehouse)
    _commit(table, [{"id": 1, "v": 1.0}])
    _commit(table, [{"id": 1, "v": 2.0}])      # staged upsert
    table.rescale_postpone()
    assert table.to_arrow().to_pylist() == [{"id": 1, "v": 2.0}]


def test_compact_skips_postpone_staging(tmp_warehouse):
    """Regular compaction must not rewrite bucket-postpone data (it would
    drop DELETE tombstones before rescale)."""
    from paimon_tpu.types import RowKind

    table = _make(tmp_warehouse)
    _commit(table, [{"id": 1, "v": 1.0}])
    table.rescale_postpone()
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts([{"id": 1, "v": 0.0}], row_kinds=[RowKind.DELETE])
    wb.new_commit().commit(w.prepare_commit())     # staged tombstone
    assert table.compact(full=True) is None or True  # must not crash
    table.rescale_postpone()
    assert table.to_arrow().num_rows == 0          # tombstone survived
