"""Mosaic format: round-trip, projection, row-group pruning, stats.

reference tests: paimon-mosaic/src/test/java/org/apache/paimon/format/
mosaic/MosaicReaderWriterTest.java, MosaicWriterMetadataTest.java.
"""

import pyarrow as pa
import pytest

from paimon_tpu import predicate as P
from paimon_tpu.format.format import get_format
from paimon_tpu.format.mosaic import (
    MosaicReader, MosaicWriter, extract_footer_stats, read_footer,
)
from paimon_tpu.fs import LocalFileIO
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, VarCharType


@pytest.fixture
def fio():
    return LocalFileIO()


def sample_table(n=100):
    return pa.table({
        "id": pa.array(range(n), pa.int64()),
        "name": pa.array([f"row-{i}" if i % 7 else None
                          for i in range(n)], pa.string()),
        "score": pa.array([i * 0.5 for i in range(n)], pa.float64()),
        "payload": pa.array([bytes([i % 256]) * (i % 50)
                             for i in range(n)], pa.large_binary()),
    })


def test_round_trip_all_columns(fio, tmp_path):
    t = sample_table()
    path = str(tmp_path / "f.mosaic")
    size = MosaicWriter().write(fio, path, t)
    assert size > 0
    out = MosaicReader().read(fio, path)
    assert out.equals(t)


def test_round_trip_empty(fio, tmp_path):
    t = sample_table(0)
    path = str(tmp_path / "f.mosaic")
    MosaicWriter().write(fio, path, t)
    out = MosaicReader().read(fio, path)
    assert out.num_rows == 0
    assert out.schema.names == t.schema.names


def test_projection_reads_subset(fio, tmp_path):
    t = sample_table()
    path = str(tmp_path / "f.mosaic")
    MosaicWriter().write(fio, path, t)
    out = MosaicReader().read(fio, path, projection=["score", "id"])
    assert out.column_names == ["score", "id"]
    assert out.column("id").to_pylist() == list(range(100))


def test_multiple_row_groups_and_pruning(fio, tmp_path):
    t = sample_table(1000)
    path = str(tmp_path / "f.mosaic")
    MosaicWriter(row_group_rows=100).write(fio, path, t)
    footer = read_footer(fio.read_bytes(path))
    assert len(footer["row_groups"]) == 10

    # predicate touching only the last row group prunes the other nine
    groups = list(MosaicReader().read_batches(
        fio, path, predicate=P.greater_or_equal("id", 950)))
    assert len(groups) == 1
    out = MosaicReader().read(fio, path,
                              predicate=P.greater_or_equal("id", 950))
    assert out.num_rows == 100          # pruning is row-group granular


def test_num_buckets_grouping(fio, tmp_path):
    t = sample_table(10)
    path = str(tmp_path / "f.mosaic")
    MosaicWriter(num_buckets=2).write(fio, path, t)
    footer = read_footer(fio.read_bytes(path))
    assert len(footer["column_buckets"]) == 2
    out = MosaicReader().read(fio, path)
    assert out.select(t.column_names).equals(t)


def test_footer_stats_extractor(fio, tmp_path):
    t = sample_table(50)
    path = str(tmp_path / "f.mosaic")
    MosaicWriter(stats_columns=["id", "name"]).write(fio, path, t)
    mins, maxs, nulls, cols = extract_footer_stats(fio, path)
    s = dict(zip(cols, zip(mins, maxs, nulls)))
    assert s["id"] == (0, 49, 0)
    assert s["name"][2] == len([i for i in range(50) if i % 7 == 0])


def test_writer_metadata_recorded(fio, tmp_path):
    path = str(tmp_path / "f.mosaic")
    MosaicWriter().write(fio, path, sample_table(5))
    footer = read_footer(fio.read_bytes(path))
    assert footer["writer"]["created_by"] == "paimon-tpu-mosaic"
    assert footer["version"] == 1


def test_registered_in_format_spi(fio, tmp_path):
    fmt = get_format("mosaic")
    assert fmt.extension == "mosaic"
    path = str(tmp_path / "f.mosaic")
    fmt.create_writer("zstd").write(fio, path, sample_table(8))
    out = fmt.create_reader().read(fio, path)
    assert out.num_rows == 8


def test_table_with_mosaic_file_format(tmp_path):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("name", VarCharType.string_type())
              .column("score", DoubleType())
              .options({"bucket": "-1", "file.format": "mosaic"})
              .build())
    table = FileStoreTable.create(str(tmp_path / "t"), schema)
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts([{"id": i, "name": f"n{i}", "score": float(i)}
                   for i in range(20)])
    wb.new_commit().commit(w.prepare_commit())
    w.close()
    out = table.to_arrow()
    assert out.num_rows == 20
    assert sorted(out.column("id").to_pylist()) == list(range(20))


def test_pk_table_with_mosaic_format(tmp_path):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("score", DoubleType())
              .primary_key("id")
              .options({"bucket": "1", "file.format": "mosaic"})
              .build())
    table = FileStoreTable.create(str(tmp_path / "t"), schema)
    for batch in ([{"id": 1, "score": 1.0}, {"id": 2, "score": 2.0}],
                  [{"id": 2, "score": 20.0}]):
        wb = table.new_batch_write_builder()
        w = wb.new_write()
        w.write_dicts(batch)
        wb.new_commit().commit(w.prepare_commit())
        w.close()
    out = table.to_arrow().sort_by("id").to_pylist()
    assert out == [{"id": 1, "score": 1.0}, {"id": 2, "score": 20.0}]
