"""Auto-compaction at commit (write-only=false) + record-level expire."""

import os
import time

import pytest

from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType


def _commit(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    sid = wb.new_commit().commit(w.prepare_commit())
    w.close()
    return sid


def test_auto_compaction_bounds_sorted_runs(tmp_warehouse):
    """Default (non write-only) tables compact inline when the run count
    crosses num-sorted-run.compaction-trigger."""
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "1",
                        "num-sorted-run.compaction-trigger": "3"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "t"),
                                  schema)
    for i in range(8):
        _commit(table, [{"id": i % 3, "v": float(i)}])
    splits = table.new_read_builder().new_scan().plan().splits
    n_runs = sum(len(s.data_files) for s in splits)
    assert n_runs <= 4              # unbounded would be 8
    rows = {r["id"]: r["v"] for r in table.to_arrow().to_pylist()}
    assert rows == {0: 6.0, 1: 7.0, 2: 5.0}
    # COMPACT snapshots were committed along the way
    kinds = {s.commit_kind
             for s in table.snapshot_manager.snapshots()}
    assert "COMPACT" in kinds


def test_write_only_never_auto_compacts(tmp_warehouse):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true",
                        "num-sorted-run.compaction-trigger": "2"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "w"),
                                  schema)
    for i in range(5):
        _commit(table, [{"id": 1, "v": float(i)}])
    splits = table.new_read_builder().new_scan().plan().splits
    assert sum(len(s.data_files) for s in splits) == 5


def test_record_level_expire(tmp_warehouse):
    now = int(time.time())
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("created", BigIntType())      # epoch millis
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true",
                        "record-level.expire-time": "1 h",
                        "record-level.time-field": "created"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "e"),
                                  schema)
    _commit(table, [
        {"id": 1, "created": (now - 7200) * 1000},   # 2h old: expired
        {"id": 2, "created": now * 1000},            # fresh
        {"id": 3, "created": None},                  # null: kept
    ])
    table.compact(full=True)
    ids = sorted(table.to_arrow().column("id").to_pylist())
    assert ids == [2, 3]


def test_record_level_expire_with_projection(tmp_warehouse):
    """Projection must not resurrect expired rows."""
    now = int(time.time())
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("created", BigIntType())
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true",
                        "record-level.expire-time": "1 h",
                        "record-level.time-field": "created"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "ep"),
                                  schema)
    _commit(table, [{"id": 1, "created": (now - 7200) * 1000},
                    {"id": 2, "created": now * 1000}])
    out = table.to_arrow(projection=["id"])
    assert out.column("id").to_pylist() == [2]
