"""End-to-end sharded compaction + all_to_all bucket rescale on the
virtual 8-device CPU mesh.

reference: mergetree/compact/MergeTreeCompactTask.java (per-bucket
compaction tasks), table/sink/ChannelComputer.java (rescale routing).
"""

import numpy as np
import pyarrow as pa
import pytest

from paimon_tpu.core.bucket import _bucket_from_hash
from paimon_tpu.parallel import (
    bucket_mesh, compact_table_sharded, rescale_dispatch_sharded,
)
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, VarCharType


def pk_table(tmp_path, buckets=8):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("name", VarCharType.string_type())
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": str(buckets), "write-only": "true"})
              .build())
    return FileStoreTable.create(str(tmp_path / "t"), schema)


def write(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    wb.new_commit().commit(w.prepare_commit())
    w.close()


def test_sharded_compact_end_to_end(tmp_path):
    t = pk_table(tmp_path, buckets=8)
    rng = np.random.default_rng(3)
    for _ in range(3):   # 3 overlapping L0 runs per bucket
        ids = rng.integers(0, 500, 600)
        write(t, [{"id": int(i), "name": f"n{i}", "v": float(i)}
                  for i in ids])
    before = t.to_arrow().sort_by("id").to_pylist()
    files_before = sum(len(s.data_files) for s in
                       t.new_read_builder().new_scan().plan().splits)

    mesh = bucket_mesh(8)
    stats = compact_table_sharded(t, mesh)
    assert stats.snapshot_id is not None
    assert stats.buckets == 8
    assert stats.output_rows == len(before)

    snap = t.latest_snapshot()
    assert snap.id == stats.snapshot_id
    assert snap.commit_kind == "COMPACT"
    after = t.to_arrow().sort_by("id").to_pylist()
    assert after == before
    plan = t.new_read_builder().new_scan().plan()
    files_after = sum(len(s.data_files) for s in plan.splits)
    assert files_after <= 8 < files_before
    # every bucket now holds exactly one max-level run
    for s in plan.splits:
        assert len(s.data_files) == 1
        assert s.data_files[0].level == t.options.num_levels - 1


def test_sharded_compact_drops_deletes(tmp_path):
    from paimon_tpu.types import RowKind
    t = pk_table(tmp_path, buckets=8)
    write(t, [{"id": i, "name": "a", "v": float(i)} for i in range(40)])
    wb = t.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts([{"id": i, "name": "a", "v": float(i)}
                   for i in range(0, 40, 2)],
                  row_kinds=[RowKind.DELETE] * 20)
    wb.new_commit().commit(w.prepare_commit())
    w.close()

    stats = compact_table_sharded(t, bucket_mesh(8))
    out = t.to_arrow().sort_by("id")
    assert out.column("id").to_pylist() == list(range(1, 40, 2))
    assert stats.output_rows == 20


def test_rescale_dispatch_matches_reference_formula():
    rng = np.random.default_rng(11)
    # 5003 rows: NOT divisible by 8 devices, so padding rows exist and
    # must not race genuine slot-(0,0) rows in the scatter
    hashes = rng.integers(0, 1 << 32, 5003, dtype=np.uint64) \
        .astype(np.uint32)
    for new_b in (3, 8, 17):
        routing = rescale_dispatch_sharded(hashes, new_b, bucket_mesh(8))
        expected = _bucket_from_hash(hashes, new_b)
        seen = 0
        for b, gids in routing.items():
            assert (expected[gids] == b).all()
            seen += len(gids)
        assert seen == len(hashes)


def test_rescale_table_buckets_roundtrip(tmp_path):
    t = pk_table(tmp_path, buckets=2)
    rng = np.random.default_rng(5)
    for _ in range(2):
        ids = rng.integers(0, 300, 400)
        write(t, [{"id": int(i), "name": f"n{i}", "v": float(i)}
                  for i in ids])
    before = t.to_arrow().sort_by("id").to_pylist()

    sid = t.rescale_buckets(8, mesh=bucket_mesh(8))
    assert sid is not None

    t2 = FileStoreTable.load(t.path)
    assert t2.options.bucket == 8
    after = t2.to_arrow().sort_by("id").to_pylist()
    assert after == before
    plan = t2.new_read_builder().new_scan().plan()
    assert {s.bucket for s in plan.splits} <= set(range(8))
    assert len(plan.splits) > 2

    # the rescaled table keeps working: upsert + read
    write(t2, [{"id": 7, "name": "updated", "v": -1.0}])
    row = [r for r in t2.to_arrow().to_pylist() if r["id"] == 7]
    assert row and row[0]["name"] == "updated"


def test_rescale_rejects_wrong_table_kinds(tmp_path):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .options({"bucket": "-1"})
              .build())
    t = FileStoreTable.create(str(tmp_path / "a"), schema)
    with pytest.raises(ValueError):
        t.rescale_buckets(4)
