"""Shared deterministic CDC stream for the multi-host soak
(tests/test_multihost_maintenance.py).

Every host of the mesh — and the auditing parent — must see the
IDENTICAL global event stream (the SPMD shape of the distributed
stream daemon), so the generator is a pure function of the offset:
event n upserts key `n % keys` with value n, except that a crc32-
derived slice of offsets are DELETES of the key (tombstones must
survive takeover and serve-catch-up too).  No RNG state, no clock:
two processes and the parent replay byte-identical histories.
"""

from __future__ import annotations

import zlib
from typing import Dict, List, Optional

DEFAULT_KEYS = 41

SOAK_TABLE_OPTIONS = {
    "bucket": "4",
    "stream.checkpoint.interval": "60",
    "stream.compaction.interval": "120",
    "stream.ingest.poll-interval": "10",
    "stream.serve.poll-interval": "15",
    "num-sorted-run.compaction-trigger": "3",
    "multihost.lease.interval": "200",
    "multihost.lease.timeout": "1500",
    # keep every snapshot: the offset audit walks all of them and the
    # serve takeover must never lose a delta to expiry
    "snapshot.num-retained.min": "100000",
    "snapshot.num-retained.max": "100000",
}


def _is_delete(n: int) -> bool:
    return zlib.crc32(f"soak-{n}".encode()) % 12 == 0


def gen_event(n: int, keys: int = DEFAULT_KEYS) -> Dict:
    """The n-th event of the global stream (pure function of n)."""
    key = n % keys
    if _is_delete(n):
        return {"op": "d", "before": {"id": key, "v": n}}
    return {"op": "c", "after": {"id": key, "v": n}}


def gen_events(n0: int, n1: int, keys: int = DEFAULT_KEYS
               ) -> List[Dict]:
    return [gen_event(n, keys) for n in range(n0, n1)]


def expected_state(total: int, keys: int = DEFAULT_KEYS
                   ) -> Dict[int, int]:
    """{key: value} after replaying events 0..total-1."""
    state: Dict[int, int] = {}
    for n in range(total):
        key = n % keys
        if _is_delete(n):
            state.pop(key, None)
        else:
            state[key] = n
    return state


def materialize(streams: List[List[dict]],
                kind_col: str = "_ROW_KIND") -> Dict[int, int]:
    """Apply consumed changelog rows stream-by-stream (each stream in
    its consumption order).  For the host-kill soak the dead host's
    stream is applied FIRST: every row it delivered predates the
    takeover, and the survivor re-serves the unserved suffix per
    adopted bucket before continuing — suffix replays are idempotent
    here exactly like daemon restarts are for single-host serving."""
    out: Dict[int, int] = {}
    for rows in streams:
        for r in rows:
            kind = r[kind_col]
            if kind in (0, 2):                       # +I / +U
                out[r["id"]] = r["v"]
            elif kind == 3:                          # -D
                out.pop(r["id"], None)
    return out
