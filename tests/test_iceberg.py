"""Iceberg v2 metadata dual-write (structural conformance).

reference: iceberg/IcebergCommitCallback + metadata/manifest classes.
"""

import json
import os

import pytest

from paimon_tpu.format.avro import read_container
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, VarCharType


def _commit(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    wb.new_commit().commit(w.prepare_commit())
    w.close()


def test_iceberg_metadata_export(tmp_warehouse):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("dt", VarCharType(nullable=False))
              .column("v", DoubleType())
              .partition_keys("dt")
              .primary_key("id", "dt")
              .options({"bucket": "1", "write-only": "true"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "t"),
                                  schema)
    _commit(table, [{"id": 1, "dt": "d1", "v": 1.0},
                    {"id": 2, "dt": "d2", "v": 2.0}])
    meta_path = table.sync_iceberg()
    assert meta_path.endswith("v1.metadata.json")

    meta = json.loads(open(meta_path).read())
    assert meta["format-version"] == 2
    assert meta["current-snapshot-id"] == 1
    sch = meta["schemas"][0]
    assert [f["name"] for f in sch["fields"]] == ["id", "dt", "v"]
    assert sch["fields"][0]["required"] is True
    assert meta["partition-specs"][0]["fields"][0]["transform"] == \
        "identity"

    # manifest list -> manifest -> data files chain is readable avro
    list_path = meta["snapshots"][0]["manifest-list"]
    _, manifests = read_container(open(list_path, "rb").read())
    assert manifests[0]["added_files_count"] == 2
    _, entries = read_container(
        open(manifests[0]["manifest_path"], "rb").read())
    assert len(entries) == 2
    for e in entries:
        df = e["data_file"]
        assert os.path.exists(df["file_path"])
        assert df["file_format"] == "PARQUET"
        assert df["partition"]["dt"] in ("d1", "d2")
        assert df["record_count"] == 1

    # second sync bumps the version and the hint
    _commit(table, [{"id": 3, "dt": "d1", "v": 3.0}])
    meta2 = table.sync_iceberg()
    assert meta2.endswith("v2.metadata.json")
    hint = open(os.path.join(table.path, "metadata",
                             "version-hint.text")).read()
    assert hint == "2"
    meta2d = json.loads(open(meta2).read())
    assert meta2d["current-snapshot-id"] == 2
