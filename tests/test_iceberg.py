"""Iceberg v2 metadata dual-write (structural conformance).

reference: iceberg/IcebergCommitCallback + metadata/manifest classes.
"""

import json
import os

import pytest

from paimon_tpu.format.avro import read_container
from paimon_tpu.schema import Schema
from paimon_tpu.table import FileStoreTable
from paimon_tpu.types import BigIntType, DoubleType, VarCharType


def _commit(table, rows):
    wb = table.new_batch_write_builder()
    w = wb.new_write()
    w.write_dicts(rows)
    wb.new_commit().commit(w.prepare_commit())
    w.close()


def test_iceberg_metadata_export(tmp_warehouse):
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("dt", VarCharType(nullable=False))
              .column("v", DoubleType())
              .partition_keys("dt")
              .primary_key("id", "dt")
              .options({"bucket": "1", "write-only": "true"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "t"),
                                  schema)
    _commit(table, [{"id": 1, "dt": "d1", "v": 1.0},
                    {"id": 2, "dt": "d2", "v": 2.0}])
    # pk tables export the read-optimized view: only fully-compacted
    # top-level files are visible to Iceberg readers
    table.compact(full=True)
    meta_path = table.sync_iceberg()
    assert meta_path.endswith("v1.metadata.json")

    meta = json.loads(open(meta_path).read())
    assert meta["format-version"] == 2
    assert meta["current-snapshot-id"] == 2   # write + compact
    sch = meta["schemas"][0]
    assert [f["name"] for f in sch["fields"]] == ["id", "dt", "v"]
    assert sch["fields"][0]["required"] is True
    assert meta["partition-specs"][0]["fields"][0]["transform"] == \
        "identity"

    # manifest list -> manifest -> data files chain is readable avro
    list_path = meta["snapshots"][0]["manifest-list"]
    _, manifests = read_container(open(list_path, "rb").read())
    assert manifests[0]["added_files_count"] == 2
    _, entries = read_container(
        open(manifests[0]["manifest_path"], "rb").read())
    assert len(entries) == 2
    for e in entries:
        df = e["data_file"]
        assert os.path.exists(df["file_path"])
        assert df["file_format"] == "PARQUET"
        assert df["partition"]["dt"] in ("d1", "d2")
        assert df["record_count"] == 1

    # second sync bumps the version and the hint
    _commit(table, [{"id": 3, "dt": "d1", "v": 3.0}])
    meta2 = table.sync_iceberg()
    assert meta2.endswith("v2.metadata.json")
    hint = open(os.path.join(table.path, "metadata",
                             "version-hint.text")).read()
    assert hint == "2"
    meta2d = json.loads(open(meta2).read())
    assert meta2d["current-snapshot-id"] == 3


# ---------------------------------------------------------------------------
# independent reader round-trip (the external-consumer check)
# ---------------------------------------------------------------------------

def test_reader_roundtrip_append(tmp_warehouse):
    from paimon_tpu.iceberg.reader import IcebergTable
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .options({"bucket": "-1"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "a"),
                                  schema)
    _commit(table, [{"id": i, "v": i * 0.5} for i in range(100)])
    _commit(table, [{"id": i, "v": i * 0.5} for i in range(100, 150)])
    table.sync_iceberg()

    ice = IcebergTable.load(table.path)
    assert ice.column_names == ["id", "v"]
    files = ice.plan_files()
    assert len(files) == 2
    got = ice.to_arrow()
    expect = table.to_arrow()
    assert sorted(got.column("id").to_pylist()) == \
        sorted(expect.column("id").to_pylist())
    assert got.num_rows == 150


def test_reader_roundtrip_pk_read_optimized(tmp_warehouse):
    from paimon_tpu.iceberg.reader import IcebergTable
    schema = (Schema.builder()
              .column("id", BigIntType(False))
              .column("v", DoubleType())
              .primary_key("id")
              .options({"bucket": "1", "write-only": "true"})
              .build())
    table = FileStoreTable.create(os.path.join(tmp_warehouse, "p"),
                                  schema)
    _commit(table, [{"id": 1, "v": 1.0}, {"id": 2, "v": 2.0}])
    _commit(table, [{"id": 1, "v": 10.0}])            # upsert
    table.sync_iceberg()
    # nothing compacted yet: the read-optimized view is empty
    ice = IcebergTable.load(table.path)
    assert ice.plan_files() == []

    table.compact(full=True)
    table.sync_iceberg()
    ice = IcebergTable.load(table.path)
    got = ice.to_arrow().sort_by("id")
    assert got.to_pylist() == [{"id": 1, "v": 10.0},
                               {"id": 2, "v": 2.0}]
    # and the merged read agrees
    assert got.to_pylist() == \
        table.to_arrow().sort_by("id").to_pylist()


def test_reader_rejects_bad_metadata(tmp_path):
    from paimon_tpu.iceberg.reader import IcebergTable
    import pytest as _pytest
    with _pytest.raises(ValueError, match="missing"):
        IcebergTable({"format-version": 2}, None)
    meta = {k: None for k in (
        "format-version", "table-uuid", "location",
        "last-sequence-number", "last-updated-ms", "last-column-id",
        "current-schema-id", "schemas", "default-spec-id",
        "partition-specs", "current-snapshot-id", "snapshots")}
    meta.update({"format-version": 1})
    with _pytest.raises(ValueError, match="format-version 2"):
        IcebergTable(meta, None)
